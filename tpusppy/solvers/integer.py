"""Batched integer wheel: device-side rounding + bound-tightening kernels.

The reference framework certifies integer workloads because its Lagrangian
spoke inherits a persistent MIP solver
(``mpisppy/cylinders/lagrangian_bounder.py:19-56``) — every per-scenario
subproblem minimum is an INTEGER minimum, closing the 0.4-0.9%
per-scenario integrality gap an LP-relaxation bound cannot.  tpusppy's
device path solves LP relaxations, so until now integer families either
stalled above their gap target or paid a serial host-HiGHS tail
(:mod:`tpusppy.solvers.milp_bound`) that dwarfs the device wall.

This module is the device-first answer (doc/integer.md), three tiers:

1. **Batched inner-bound recovery on device** — a vmapped multi-candidate
   rounding sweep (:func:`candidate_ladder`: a threshold ladder over the
   consensus xbar plus SLAM-style per-node directional slams, the
   feasibility-pump/SLAM primitives of Fischetti-Glover-Lodi 2005 and
   Knueven et al. 2023 as pure tensor ops), each candidate fixed onto the
   nonant box and evaluated by ONE batched frozen solve on the megastep
   window's hot factors, feasibility-gated per candidate with the
   dtype-aware slack, device ``argmin`` over feasible candidates
   (:func:`sweep_candidates`) — every bound window produces the *best of
   C* integer-feasible incumbents instead of one clip-and-pray xhat.
2. **Batched outer-bound tightening** — vmapped reduced-cost fixing from
   the window's frozen duals (:func:`rc_fix_bounds`): integer slots
   provably at a bound under the W-augmented objective get fixed,
   shrinking the relaxation, and one more frozen solve + weak-duality
   assembly on the shrunk box yields a tightened per-scenario Lagrangian
   bound (:func:`integer_bound_pass` takes the per-scenario max with the
   plain bound, so tightening can only help).
3. **Gap-ranked host escalation** — :class:`EscalationBudget` +
   :func:`escalate_outer`: HiGHS seconds
   (:func:`~tpusppy.solvers.milp_bound.milp_lift`) are spent on the
   scenarios with the LARGEST remaining per-scenario LP-vs-MILP gap
   first (largest certified-gap closure per host-second), budget-elastic
   and valid at any completed subset.  :func:`escalate_inner` certifies
   the device sweep's best candidate by per-scenario host MIPs when the
   family carries second-stage integers (the device evaluation is then a
   relaxation and must not be offered as an incumbent).

Validity arguments (mirrored from ``milp_bound.py``'s docstring
contract, property-tested in tests/test_integer.py):

* Every inner candidate is integral on the integer nonant slots and
  evaluated with those slots FIXED; when the frozen evaluation is
  feasible on every scenario (and the family has no second-stage
  integers), its expected plain objective is a certified-to-tolerance
  incumbent — exactly the existing ``Xhat_Eval`` contract.
* Reduced-cost fixing: for any duals ``y``, any scenario-feasible ``x``
  with an integer slot ``j`` moved one unit off its bound has
  W-augmented objective ``>= d_s + |g_j|`` (the weak-duality box term
  shifts by exactly ``g_j`` per unit for a linear coordinate — quadratic
  coordinates are excluded from fixing).  When that exceeds a valid
  upper bound ``u_s`` on the scenario's integer minimum (the candidate
  evaluation's W-augmented value, feasible scenarios only, padded by
  ``rcfix_slack``), every integer-optimal solution has slot ``j`` AT the
  bound — fixing preserves some integer minimizer, so the shrunk
  problem's weak-duality bound still lower-bounds the ORIGINAL integer
  minimum.  The pass emits ``max(d_s, d_s^fixed)`` per scenario: never
  worse than the plain LP certificate.
"""

from __future__ import annotations

import time

import numpy as np

from ..obs import metrics as _metrics
from . import admm

# extra scalars the integer sweep appends to the in-wheel bound tail
# (after the base BOUND_PACK_LEN entries): [feasible candidate count,
# best candidate index, reduced-cost-fixed slot count, untightened outer]
INT_BOUND_EXTRA = 4

# default rounding-threshold ladder: nearest (0.5) plus two commit-biased
# entries — on UC-like families where under-commitment prices VOLL
# shedding, lower thresholds beat nearest-rounding by an order of
# magnitude (the xhatxbar spoke documents the same ladder effect)
DEFAULT_THRESHOLDS = (0.5, 0.35, 0.25)

# SLAM candidates appended after the threshold ladder (up = per-node max
# over scenarios then ceil, down = per-node min then floor — the
# mpisppy slam_heuristic directions as tensor ops)
N_SLAM = 2


def n_candidates(thresholds) -> int:
    """Sweep width C for a threshold ladder (ladder + the two slams)."""
    return len(tuple(thresholds)) + N_SLAM


def feas_slack(S: int, dt) -> float:
    """THE dtype-aware feasibility-mass slack (single-sourced with
    ``PHBase._consume_inwheel_bounds``): an all-feasible f32 sum over S
    non-representable probabilities lands ~S*eps below 1.0, so a bare
    1e-9 gate would reject every feasible candidate on the float32
    posture."""
    return max(1e-9, 4.0 * int(S) * float(np.finfo(np.dtype(dt)).eps))


# ---------------------------------------------------------------------------
# Device kernels (traced; callers are inside a jitted megastep program).
# No imports from parallel.sharded — the PHArrays/PHState arguments are
# duck-typed NamedTuples, keeping the solver layer dependency-clean.
# ---------------------------------------------------------------------------
def candidate_ladder(xbars, xk, int_mask, thresholds, onehot, nid_sk,
                     lb_k, ub_k, include_slams=True):
    """(C, S, K) candidate tensor: the rounding ladder + SLAM slams.

    ``include_slams=False`` drops the two SLAM candidates — REQUIRED on
    a per-bucket leg of a bucketed sweep: the slam reduction sees only
    the leg's own scenarios, so for a tree node spanning buckets the
    per-bucket extremes would assemble a NON-NONANTICIPATIVE global
    candidate (different first-stage values per bucket) whose expected
    objective must never be offered as an incumbent.  The ladder
    candidates are safe everywhere: xbars is already the GLOBAL
    per-node mean gathered per scenario, identical across buckets for
    shared nodes.

    ``xbars`` (S, K) is the consensus per-node mean gathered per
    scenario; ``xk`` (S, K) the current per-scenario nonants (the SLAM
    inputs); ``int_mask`` (K,) bool.  Ladder entry ``t``: integer slots
    round UP when their fractional part is at least ``t``
    (``floor(x + 1 - t)`` — the single-sourced
    ``xhatxbar_bounder.candidate_rule``); continuous slots keep xbars.
    SLAM-up slams every nonant to its per-node max over member scenarios
    (ceil on integer slots — commit anything any scenario wants
    committed), SLAM-down to the per-node min (floor — only what every
    scenario agrees on).  Every candidate is clipped to the nonant box
    (the load-bearing tolerance-noise clip of the candidate rule).
    """
    import jax.numpy as jnp

    mask = jnp.asarray(int_mask)[None, :]
    cands = [jnp.where(mask, jnp.floor(xbars + (1.0 - float(t))), xbars)
             for t in thresholds]
    if include_slams:
        # per-node extremes of the CURRENT iterates, gathered per
        # scenario (ghost scenarios have zero node membership and never
        # contribute)
        member = jnp.asarray(onehot) > 0                  # (S, K, N)
        big = jnp.asarray(np.inf, xk.dtype)
        mx_nk = jnp.max(jnp.where(member, xk[:, :, None], -big),
                        axis=0).T
        mn_nk = jnp.min(jnp.where(member, xk[:, :, None], big),
                        axis=0).T
        kidx = jnp.arange(xk.shape[1])[None, :]
        up = mx_nk[nid_sk, kidx]
        dn = mn_nk[nid_sk, kidx]
        cands.append(jnp.where(mask, jnp.ceil(up - 1e-9), up))
        cands.append(jnp.where(mask, jnp.floor(dn + 1e-9), dn))
    return jnp.clip(jnp.stack(cands), lb_k[None], ub_k[None])


def rc_fix_bounds(qL, q2_plain, lb, ub, g, d_cmp, u_s, u_ok, int_cols,
                  rcfix_slack):
    """Reduced-cost fixing masks + shrunk bounds (traced).

    ``g`` (S, n) are the weak-duality reduced costs ``qL + A'y`` (from
    :func:`~tpusppy.solvers.admm.dual_cut`, post dual-cone clipping);
    ``d_cmp`` (S,) the margin-subtracted per-scenario dual bound (the
    CONSERVATIVE side — a smaller d makes fixing harder, never unsafe);
    ``u_s`` (S,) the candidate's W-augmented per-scenario value, valid
    only where ``u_ok`` (S,) — the candidate evaluation was feasible for
    that scenario.  A LINEAR integer slot fixes at lb when moving one
    unit up provably exceeds the scenario's integer minimum:
    ``d_cmp + g_j > u_s + slack`` with ``g_j >= 0`` (symmetric at ub).
    Quadratic slots are excluded (the unit-shift bound argument is
    linear-coordinate only).  Returns ``(lbF, ubF, n_fixed)``.
    """
    import jax.numpy as jnp

    dt = g.dtype
    big = admm.BIG
    fin_lb = lb > -big / 2
    fin_ub = ub < big / 2
    room = (ub - lb) >= 0.5           # already-fixed slots are a no-op
    lin = q2_plain < 1e-14
    marg = (jnp.asarray(rcfix_slack, dt)
            * (1.0 + jnp.abs(u_s)))[:, None]
    gate = int_cols[None, :] & lin & room & u_ok[:, None]
    fix_lo = gate & fin_lb & (g >= 0) & (d_cmp[:, None] + g > u_s[:, None]
                                         + marg)
    fix_hi = gate & fin_ub & (g <= 0) & (d_cmp[:, None] - g > u_s[:, None]
                                         + marg)
    fix_hi = fix_hi & ~fix_lo         # g == 0: prefer the lower bound
    lbF = jnp.where(fix_hi, ub, lb)
    ubF = jnp.where(fix_lo, lb, ub)
    n_fixed = jnp.sum((fix_lo | fix_hi).astype(dt))
    return lbF, ubF, n_fixed


def sweep_partials(arr, st, idx, q_aug, q2_aug, frozen_fn, factors,
                   feas_tol, dt, int_mask, thresholds,
                   include_slams=True):
    """Per-candidate PARTIAL sums of the rounding sweep for ONE engine
    leg (traced): ``(inner_c (C,), feas_c (C,), sweeps_c (C,),
    u_cs (C, S), feasmask_cs (C, S))``.  ``inner_c``/``feas_c`` are
    probability-weighted partial sums over this leg's scenarios — for a
    bucketed family the caller SUMS them across buckets before the
    global argmin (probs/onehot are global-tree slices, so the sums
    compose exactly, the ``_bound_pass_terms`` composition argument).
    ``u_cs`` is the W-augmented per-scenario candidate value (const-free)
    — the reduced-cost fixing's per-scenario integer-minimum upper
    bound; ``feasmask_cs`` marks which scenarios' evaluation met the
    gate.  ``include_slams=False`` is the bucketed-leg posture (see
    :func:`candidate_ladder` — per-bucket slam extremes are not
    nonanticipative).
    """
    import jax.numpy as jnp

    W = st.W.astype(dt)
    q2_plain = arr.q2.astype(dt)
    lb_k = arr.lb.astype(dt)[:, idx]
    ub_k = arr.ub.astype(dt)[:, idx]
    cands = candidate_ladder(st.xbars.astype(dt), st.x.astype(dt)[:, idx],
                             int_mask, thresholds, arr.onehot, arr.nid_sk,
                             lb_k, ub_k, include_slams=include_slams)

    def eval_cand(cand):
        lb2 = arr.lb.at[:, idx].set(cand)
        ub2 = arr.ub.at[:, idx].set(cand)
        x0 = st.x.astype(dt).at[:, idx].set(cand)
        sol = frozen_fn(q_aug, q2_aug, arr.A, arr.cl, arr.cu, lb2, ub2,
                        x0, st.z, st.y, st.yx, factors)
        lin = jnp.einsum("sn,sn->s", arr.c.astype(dt), sol.x)
        quad = 0.5 * jnp.einsum("sn,sn->s", q2_plain, sol.x * sol.x)
        per_plain = lin + quad + arr.const
        feas_s = (sol.pri_res < jnp.asarray(feas_tol, dt)).astype(dt)
        # W-augmented per-scenario value — the reduced-cost fixing's
        # per-scenario integer-minimum upper bound u_s (const-free,
        # matching the dual bound's convention)
        u_s = lin + quad + jnp.einsum(
            "sk,sk->s", W, sol.x[:, idx].astype(dt))
        return (arr.probs @ per_plain, arr.probs @ feas_s,
                jnp.max(sol.iters).astype(dt), u_s, feas_s > 0)

    import jax

    return jax.vmap(eval_cand)(cands)


def rc_outer_partials(arr, st, idx, q_aug, q2_aug, frozen_fn, factors, dt,
                      int_cols, u_s, u_ok, rcfix_slack=1e-5,
                      want_perscen=False):
    """Reduced-cost-tightened Lagrangian outer bound for ONE engine leg
    (traced): ``(outer_tight, outer_base, n_fixed, sweepsF)`` —
    probability-weighted partial sums over this leg's scenarios (the
    bucketed kernel sums them).  ``u_s``/``u_ok`` come from the selected
    candidate's :func:`sweep_partials` row.  The tightened value is the
    per-scenario ``max`` of the plain weak-duality bound and the
    shrunk-box re-certification, so it can never be worse than the LP
    certificate.  ``want_perscen=True`` returns
    ``(final_s (S,), d_cmp (S,), n_fixed, sweepsF)`` — const-free
    per-scenario values, the property-test surface (every entry must
    lower-bound its scenario's integer minimum of the W-augmented
    objective)."""
    import jax.numpy as jnp

    W = st.W.astype(dt)
    qL = arr.c.astype(dt).at[:, idx].add(W)
    q2_plain = arr.q2.astype(dt)
    lb = arr.lb.astype(dt)
    ub = arr.ub.astype(dt)
    packed = admm.dual_objective_with_margin_traced(
        qL, q2_plain, arr.A, arr.cl, arr.cu, lb, ub,
        st.y.astype(dt), st.x.astype(dt))
    d_cmp = packed[0].astype(dt) - packed[1].astype(dt)   # const-free
    outer_base = arr.probs @ (d_cmp + arr.const)
    _, g = admm.dual_cut(qL, q2_plain, arr.A, arr.cl, arr.cu, lb, ub,
                         st.y.astype(dt), st.x.astype(dt),
                         jnp.zeros(arr.c.shape[1], dtype=bool))
    lbF, ubF, n_fixed = rc_fix_bounds(
        qL, q2_plain, lb, ub, g.astype(dt), d_cmp, u_s, u_ok,
        jnp.asarray(int_cols), rcfix_slack)
    solF = frozen_fn(q_aug, q2_aug, arr.A, arr.cl, arr.cu, lbF, ubF,
                     st.x, st.z, st.y, st.yx, factors)
    packedF = admm.dual_objective_with_margin_traced(
        qL, q2_plain, arr.A, arr.cl, arr.cu, lbF, ubF,
        solF.y.astype(dt), solF.x.astype(dt))
    dF = packedF[0].astype(dt) - packedF[1].astype(dt)
    # per-scenario max: the shrunk-box certificate can only help (when
    # nothing was fixed for a scenario, dF is just another valid bound)
    final_s = jnp.maximum(d_cmp, dF)
    if want_perscen:
        return (final_s, d_cmp, n_fixed,
                jnp.max(solF.iters).astype(dt))
    outer = arr.probs @ (final_s + arr.const)
    return (outer.astype(dt), outer_base.astype(dt), n_fixed,
            jnp.max(solF.iters).astype(dt))


def integer_bound_pass(arr, st, idx, q_aug, q2_aug, frozen_fn, factors,
                       feas_tol, settings_dt, int_mask, thresholds,
                       int_cols, rcfix_slack=1e-5, rcfix_enabled=True):
    """The INTEGER in-wheel bound pass (traced, homogeneous leg):
    best-of-C rounding sweep + reduced-cost-tightened Lagrangian outer
    bound, as fused device contractions on the megastep window's final
    state.

    ``arr``/``st`` are the megastep's PHArrays/PHState (duck-typed);
    ``q_aug``/``q2_aug`` the PH-augmented objective the window's factors
    were built for (fixed-candidate evaluation under the augmentation is
    minimizer-identical on the clamped columns — the
    ``_bound_pass_terms`` argument); ``int_mask`` (K,) the integer
    nonant slots, ``int_cols`` (n,) ALL integer columns (reduced-cost
    fixing applies beyond the nonant slots), ``thresholds`` the baked
    rounding ladder.  Returns the
    ``BOUND_PACK_LEN + INT_BOUND_EXTRA``-scalar tail (computed flag,
    tightened outer, best inner, its feasibility mass, sweep max,
    feasible-candidate count, best index, fixed-slot count, untightened
    outer).

    ``rcfix_enabled=False`` (a BAKED constant) skips the reduced-cost
    fixing + re-certification entirely and emits the plain weak-duality
    outer twice: fixing validity needs ``u_s`` to upper-bound the
    scenario's INTEGER minimum, and on families with SECOND-STAGE
    integer columns the candidate evaluation relaxes those columns —
    its value can sit BELOW the true integer minimum by the second
    stage's own integrality gap, which no slack absorbs.  Callers gate
    on the ``_inwheel_inner_ok`` condition (every integer column a
    nonant slot).
    """
    import jax.numpy as jnp

    dt = settings_dt
    S = arr.c.shape[0]
    inner_c, feas_c, sweeps_c, u_cs, feasmask_cs = sweep_partials(
        arr, st, idx, q_aug, q2_aug, frozen_fn, factors, feas_tol, dt,
        int_mask, thresholds)
    slack = jnp.asarray(feas_slack(S, dt), dt)
    ok_c = feas_c >= 1.0 - slack
    best_idx = jnp.argmin(jnp.where(ok_c, inner_c, jnp.asarray(np.inf, dt)))
    n_feas = jnp.sum(ok_c.astype(dt))
    if rcfix_enabled:
        outer, outer_base, n_fixed, sweepsF = rc_outer_partials(
            arr, st, idx, q_aug, q2_aug, frozen_fn, factors, dt,
            int_cols, u_cs[best_idx], feasmask_cs[best_idx], rcfix_slack)
        sweeps = jnp.maximum(jnp.max(sweeps_c), sweepsF)
    else:
        W = st.W.astype(dt)
        qL = arr.c.astype(dt).at[:, idx].add(W)
        packed = admm.dual_objective_with_margin_traced(
            qL, arr.q2.astype(dt), arr.A, arr.cl, arr.cu,
            arr.lb.astype(dt), arr.ub.astype(dt),
            st.y.astype(dt), st.x.astype(dt))
        outer = outer_base = (arr.probs @ (
            packed[0].astype(dt) - packed[1].astype(dt)
            + arr.const)).astype(dt)
        n_fixed = jnp.zeros((), dt)
        sweeps = jnp.max(sweeps_c)
    return jnp.stack([
        jnp.ones((), dt), outer, inner_c[best_idx].astype(dt),
        feas_c[best_idx].astype(dt), sweeps,
        n_feas.astype(dt), best_idx.astype(dt), n_fixed, outer_base])


# ---------------------------------------------------------------------------
# Host side: candidate twins, the escalation budget controller, and the
# gap-ranked MILP escalation tier.
# ---------------------------------------------------------------------------
def int_mask_rows(opt) -> np.ndarray:
    """(S, K) per-scenario integer mask of the nonant slots — bucketed
    batches may key buckets on the integer pattern, so the mask can
    differ by row."""
    from ..ir import BucketedBatch

    b = opt.batch
    nidg = opt.tree.nonant_indices
    if isinstance(b, BucketedBatch):
        out = np.zeros((b.num_scenarios, len(nidg)), dtype=bool)
        for idx, sub in b.buckets:
            out[np.asarray(idx)] = np.asarray(
                sub.is_int, bool)[sub.tree.nonant_indices]
        return out
    return np.broadcast_to(np.asarray(b.is_int, bool)[nidg],
                           (b.num_scenarios, len(nidg))).copy()


def host_candidates(opt, thresholds=DEFAULT_THRESHOLDS):
    """(C, S, K) host twin of :func:`candidate_ladder` built from the opt
    object's host mirrors (xbars + current nonants) — 1e-9 parity with
    the device ladder is pinned by tests.  The rounding rule is the
    single-sourced ``xhatxbar_bounder.candidate_rule`` semantics
    (``floor(x + 1 - t)`` + the load-bearing box clip) applied with the
    per-row integer mask; the slams reuse ``xhatbase.slam_cache``."""
    from ..extensions.xhatbase import slam_cache

    if getattr(opt, "_host_state_stale", False):
        opt._sync_host_state()
    b = opt.batch
    nid = opt.tree.nonant_indices
    ints = int_mask_rows(opt)
    xbars = np.asarray(opt.xbars, dtype=float)
    lo = np.asarray(b.lb)[:, nid]
    hi = np.asarray(b.ub)[:, nid]
    cands = [np.clip(np.where(ints, np.floor(xbars + (1.0 - float(t))),
                              xbars), lo, hi)
             for t in thresholds]
    xk = opt.nonants_of(opt.local_x)
    for how, snap in (("max", lambda c: np.ceil(c - 1e-9)),
                      ("min", lambda c: np.floor(c + 1e-9))):
        cand = slam_cache(opt, xk, how=how)
        cand = np.where(ints, snap(cand), cand)
        cands.append(np.clip(cand, lo, hi))
    return np.stack(cands)


class EscalationBudget:
    """Shared wall-clock budget for the host escalation tier.

    One controller per wheel: every escalation call *takes* a grant,
    runs, and *spends* what it actually used, so the total host-HiGHS
    tail is bounded by ``budget_s`` no matter how many windows escalate.
    ``clock`` is injectable (deterministic fake-clock tests pin the
    gap-ranked ordering and partial-budget elasticity without wall
    time).
    """

    def __init__(self, budget_s: float, clock=time.monotonic):
        self.budget_s = float(budget_s)
        self.clock = clock
        self.spent_s = 0.0

    @property
    def remaining(self) -> float:
        return max(0.0, self.budget_s - self.spent_s)

    def take(self, want_s: float | None = None) -> float:
        """Grant up to ``want_s`` seconds (the whole remainder when
        None).  0.0 means exhausted — the caller must leave every
        untouched scenario on its existing certificate."""
        rem = self.remaining
        return rem if want_s is None else min(float(want_s), rem)

    def timed(self):
        """Context manager charging the enclosed wall time."""
        return _BudgetTimer(self)


class _BudgetTimer:
    def __init__(self, budget: EscalationBudget):
        self.b = budget

    def __enter__(self):
        self.t0 = self.b.clock()
        return self

    def __exit__(self, *exc):
        dt = max(0.0, self.b.clock() - self.t0)
        self.b.spent_s += dt
        _metrics.inc("integer.escalation_secs", dt)
        return False


def gap_ranked_order(probs, lp_perscen, upper_perscen) -> np.ndarray:
    """Scenario visit order for the escalation tier: DESCENDING estimated
    probability-weighted per-scenario LP-vs-MILP gap ``p_s * (u_s -
    d_s)`` (clamped at 0; non-finite estimates sort last) — the largest
    certified-gap closure per host-second comes first, replacing
    ``milp_lift``'s default probability ordering."""
    p = np.asarray(probs, dtype=float)
    gap = p * np.clip(np.asarray(upper_perscen, dtype=float)
                      - np.asarray(lp_perscen, dtype=float), 0.0, None)
    gap = np.where(np.isfinite(gap), gap, -np.inf)
    return np.argsort(-gap, kind="stable")


def _waug_q(opt):
    """The W-augmented (W on, prox OFF) per-scenario objective — the
    Lagrangian subproblem objective every escalation bound certifies."""
    b = opt.batch
    q = np.array(b.c, copy=True)
    q[:, opt.tree.nonant_indices] += np.asarray(opt.W, dtype=float)
    return q


def candidate_upper_perscen(opt, cand) -> tuple[np.ndarray, np.ndarray]:
    """(u_s, ok_s): per-scenario W-augmented value of one fixed candidate
    via a single batched frozen-style device evaluation (the ranking
    input of :func:`gap_ranked_order`) — ``ok_s`` marks scenarios whose
    evaluation met the feasibility gate.  Falls back to (+inf, False)
    rows when no frozen state exists."""
    import jax.numpy as jnp

    from . import hostsync, shared_admm

    b = opt.batch
    S = b.num_scenarios
    if opt._factors is None or opt._warm is None:
        return (np.full(S, np.inf), np.zeros(S, dtype=bool))
    nid = np.asarray(opt.tree.nonant_indices)
    lb = np.array(b.lb, copy=True)
    ub = np.array(b.ub, copy=True)
    lb[:, nid] = cand
    ub[:, nid] = cand
    q, q2 = opt._augmented_q()
    st = opt.admm_settings
    dt = st.jdtype()
    A_d, cl_d, cu_d = opt._device_consts(dt)
    x, z, y, yx = opt._warm
    x0 = jnp.asarray(x, dt).at[:, nid].set(jnp.asarray(cand, dt))
    warm = (x0, jnp.asarray(z, dt), jnp.asarray(y, dt), jnp.asarray(yx, dt))
    args = (jnp.asarray(q, dt), jnp.asarray(q2, dt), A_d, cl_d, cu_d,
            jnp.asarray(lb, dt), jnp.asarray(ub, dt))
    solve = (shared_admm.solve_shared_frozen
             if getattr(b, "A_shared", None) is not None
             else admm.solve_batch_frozen)
    sol = solve(*args, factors=opt._factors, settings=st, warm=warm)
    xs, pri = (np.asarray(a) for a in hostsync.fetch((sol.x, sol.pri_res)))
    qL = _waug_q(opt)
    u = (np.einsum("sn,sn->s", qL, xs)
         + 0.5 * np.einsum("sn,sn->s", np.asarray(b.q2), xs * xs))
    ok = pri < opt._inwheel_feas_tol()
    return u, ok


def escalate_outer(opt, budget: EscalationBudget, *, want_s=None,
                   time_limit=10.0, mip_rel_gap=1e-4,
                   upper_perscen=None, want_x=False):
    """ONE gap-ranked host escalation round: lift per-scenario LP
    certificates to MILP dual bounds, largest estimated gap first, inside
    the shared budget.  Returns the lifted expected outer bound (always
    ``>=`` the LP bound — :func:`milp_bound.milp_lift` takes the
    per-scenario max), or None when the budget is exhausted or the
    family is continuous.  ``want_x=True`` returns ``(bound, X)`` with
    the (S, n) per-scenario MILP minimizers (NaN rows where not lifted)
    — the Lagrangian-heuristic incumbent seeds.

    ``upper_perscen``: per-scenario integer-minimum upper estimates for
    the ranking (from :func:`candidate_upper_perscen`); when absent the
    ranking falls back to probability order (still valid, just not
    gap-optimal).
    """
    b = opt.batch
    if not bool(np.asarray(b.is_int).any()):
        return (None, None) if want_x else None
    grant = budget.take(want_s)
    if grant <= 0.05:
        return (None, None) if want_x else None
    from .milp_bound import milp_lift

    q = _waug_q(opt)
    base = np.asarray(opt.Edualbound_perscen(q=q, q2=b.q2), dtype=float)
    order = None
    if upper_perscen is not None:
        order = gap_ranked_order(opt.probs, base, upper_perscen)
    _metrics.inc("integer.escalations")
    with budget.timed():
        out = milp_lift(
            b, q, base, budget_s=grant, order=order,
            time_limit=min(float(time_limit), grant),
            mip_rel_gap=mip_rel_gap, want_x=want_x)
    lifted, n = out[0], out[1]
    _metrics.inc("integer.escalation_lifts", int(n))
    bound = float(np.asarray(opt.probs, dtype=float) @ lifted)
    return (bound, out[2]) if want_x else bound


def restricted_ef_incumbent(opt, X, budget: EscalationBudget, *,
                            want_s=None, time_limit=20.0,
                            mip_rel_gap=1e-4) -> float | None:
    """Restricted-EF dive seeded by the MILP lift's minimizers: integer
    nonant slots where EVERY scenario minimizer agrees are FIXED at the
    agreed value, the rest stay free, and the (much smaller) restricted
    EF MIP is solved time-limited.  ANY feasible solution of the
    restricted EF is EF-feasible, so its objective is a certified
    incumbent — usually far tighter than rounding a relaxation
    consensus, because the agreement pattern of integer subproblem
    minima under a near-converged W is most of the optimal first stage
    (the cross-scenario consensus-dive idea, host-tier).  Returns the
    incumbent value or None (budget exhausted / no solution in time /
    a solver error — declines, never kills the wheel)."""
    import dataclasses

    from ..ef import solve_ef

    b = opt.batch
    grant = budget.take(want_s)
    if grant <= 0.05:
        return None
    X = np.asarray(X, dtype=float)
    if np.isnan(X[:, 0]).any():
        return None
    nid = np.asarray(opt.tree.nonant_indices)
    ints = np.asarray(b.is_int, bool)[nid]
    xk = np.round(X[:, nid])
    agree = ints[None, :] & (xk == xk[:1]).all(axis=0)[None, :]
    lb = np.array(b.lb, copy=True)
    ub = np.array(b.ub, copy=True)
    lb[:, nid] = np.where(agree, xk, lb[:, nid])
    ub[:, nid] = np.where(agree, xk, ub[:, nid])
    _metrics.inc("integer.escalations")
    with budget.timed():
        try:
            obj, _ = solve_ef(
                dataclasses.replace(b, lb=lb, ub=ub), solver="highs",
                mip=True, time_limit=min(float(time_limit), grant),
                mip_rel_gap=mip_rel_gap)
        except Exception:
            return None
    return float(obj) if np.isfinite(obj) else None


def escalate_inner(opt, budget: EscalationBudget, cand, *,
                   want_s=None, time_limit=10.0) -> float | None:
    """Certify ONE candidate by per-scenario host MIPs — the escalation
    tier's inner-bound leg for families with SECOND-STAGE integers
    (sizes): the device sweep's frozen evaluation relaxes those columns,
    so its value is not an incumbent; fixing the nonants at the
    candidate and solving each scenario's MIP exactly is.  Returns the
    certified expected objective, or None (budget exhausted, any
    scenario infeasible/timed out, or a solver error — a failed
    escalation declines, never kills the wheel)."""
    from . import scipy_backend

    b = opt.batch
    grant = budget.take(want_s)
    if grant <= 0.05:
        return None
    nid = opt.tree.nonant_indices
    lb = np.array(b.lb, copy=True)
    ub = np.array(b.ub, copy=True)
    lb[:, nid] = cand
    ub[:, nid] = cand
    is_int = np.asarray(b.is_int, bool)
    probs = np.asarray(opt.probs, dtype=float)
    deadline = budget.clock() + grant
    objs = np.full(b.num_scenarios, np.inf)
    _metrics.inc("integer.escalations")
    with budget.timed():
        try:
            for s in range(b.num_scenarios):
                rem = deadline - budget.clock()
                if rem <= 0.05:
                    return None
                q2s = np.asarray(b.q2[s])
                if q2s.any():
                    return None      # host MIP tier is LP-objective only
                r = scipy_backend.solve_lp(
                    b.c[s], b.A[s], b.cl[s], b.cu[s], lb[s], ub[s],
                    is_int=is_int, const=float(b.const[s]),
                    time_limit=min(float(time_limit), rem))
                # ANY integer-feasible incumbent certifies (its objective
                # upper-bounds the scenario minimum) — a time-limited
                # solve with an incumbent still counts
                if not r.feasible or not np.isfinite(r.obj):
                    return None
                objs[s] = r.obj
        except Exception:
            return None
    return float(probs @ objs)
