"""Block/Woodbury factorization of the shared-A KKT system.

The shared x-update system K = diag(d) + A' R A separates for
block-structured families (UC above all: generator-local ramp/min-up/
segment rows + a few hundred wide balance/reserve rows) into

    K = B + A_w' R_w A_w,     B block-diagonal over variable components.

Instead of the dense (n, n) explicit inverse (O(n^3) to build, O(S n^2)
to apply, n^2 floats of HBM — 4.1 GB at reference horizon 48), this
factors each variable block independently (batched per size bucket) and
applies the wide-row coupling through the Woodbury identity

    K^-1 = B^-1 - B^-1 A_w' C^-1 A_w B^-1,
    C    = R_w^-1 + A_w B^-1 A_w'            (r x r, SPD).

Apply cost per x-update drops from O(S n^2) to O(S (sum_b bs^2 + 2 n r))
— ~6x fewer flops at WECC-240 horizon-24 shape (n=16008, r=1098), and
the factors hold O(sum_b bs^2 + n r + r^2) floats instead of n^2.

The structure (variable components, bucketed padding, wide-row set) is
detected host-side once per family by
:func:`tpusppy.solvers.sparse.detect_structure`; this module runs on
device inside the jitted factor/solve programs.

Reference analogue: Gurobi's internal sparse LU/ordering on each
subproblem (spopt.py:85-223); parapint's Schur-complement decomposition
(opt/sc.py:59-106) is the same algebra applied at the scenario level —
here it is applied INSIDE the per-scenario KKT, batched over scenarios.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .sparse import KKTStructure, SparseA


class StructureArrays(NamedTuple):
    """Device-resident static index arrays of a :class:`KKTStructure`.

    ``bvars[k]`` is (nb, bs) int32 (dummy slot = n), ``brows[k]`` is
    (nb, mb) int32 (dummy slot = m); ``wide_rows`` is (r,) int32.
    Tuples keep per-bucket shapes static under jit.
    """

    bvars: tuple
    brows: tuple
    wide_rows: jax.Array

    @classmethod
    def from_structure(cls, st: KKTStructure):
        return cls(
            bvars=tuple(jnp.asarray(bv) for bv, _ in st.buckets),
            brows=tuple(jnp.asarray(br) for _, br in st.buckets),
            wide_rows=jnp.asarray(st.wide_rows, jnp.int32),
        )


class BlockWoodbury(NamedTuple):
    """Factored K^-1 operator (the structured stand-in for the dense
    ``Kinv`` array inside :class:`~tpusppy.solvers.shared_admm.SharedFactors`)."""

    binv: tuple        # per bucket (nb, bs, bs) explicit block inverses
    bvars: tuple       # per bucket (nb, bs) variable ids (dummy = n)
    Aw: jax.Array      # (r, n) dense scaled wide rows
    Cinv: jax.Array    # (r, r) inverse Woodbury cap


def _bapply(binv: tuple, bvars: tuple, b, prec=None):
    """B^-1 b for b (..., n): gather per bucket, batched block matmul,
    scatter back.  Blocks partition the variables, so scatters never
    collide (the dummy slot n collides only with itself).

    ``prec``: optional matmul precision mode for the block matmuls
    (solvers/precision.py); None keeps the legacy ambient-precision op."""
    n = b.shape[-1]
    b_pad = jnp.concatenate(
        [b, jnp.zeros(b.shape[:-1] + (1,), b.dtype)], axis=-1)
    out = jnp.zeros_like(b_pad)
    for inv_k, bv_k in zip(binv, bvars):
        g = b_pad[..., bv_k]                        # (..., nb, bs)
        if prec is None:
            r = jnp.einsum("...kb,kbt->...kt", g, inv_k)
        else:
            from . import precision
            r = precision.contract("...kb,kbt->...kt", g, inv_k, prec)
        out = out.at[..., bv_k.reshape(-1)].set(
            r.reshape(r.shape[:-2] + (-1,)))
    return out[..., :n]


def factor_structured(A: SparseA, struct: StructureArrays, dvec, rho_a,
                      sigma) -> BlockWoodbury:
    """Factor K = diag(dvec) + sigma I + A' diag(rho_a) A given the
    block/Woodbury split.  ``A`` must already be Ruiz-SCALED.

    Runs inside the jitted refresh program.  The dense (m+1, n+1)
    scatter of A is transient (alive only during block extraction) and
    its buffer is reused by XLA once the (nb, mb, bs) block tensors are
    built.
    """
    m, n = A.shape
    dt = A.dtype
    A_pad = jnp.zeros((m + 1, n + 1), dt).at[A.rows, A.cols].add(A.vals)
    d_pad = jnp.concatenate([dvec + sigma, jnp.ones((1,), dt)])
    rho_pad = jnp.concatenate([rho_a, jnp.zeros((1,), dt)])

    from .admm import _explicit_inverse

    binv = []
    for bv_k, br_k in zip(struct.bvars, struct.brows):
        Ablk = A_pad[br_k[:, :, None], bv_k[:, None, :]]   # (nb, mb, bs)
        Bb = jnp.einsum("kms,kmt,km->kst", Ablk, Ablk, rho_pad[br_k])
        diag = d_pad[bv_k]                                  # (nb, bs)
        Bb = Bb + jax.vmap(jnp.diag)(diag)
        binv.append(_explicit_inverse(Bb))
    binv = tuple(binv)

    Aw = A_pad[struct.wide_rows, :n]                        # (r, n)
    rho_w = rho_a[struct.wide_rows]
    T = _bapply(binv, struct.bvars, Aw)                     # (r, n)
    C = Aw @ T.T
    C = 0.5 * (C + C.T) + jnp.diag(1.0 / rho_w)
    Cinv = _explicit_inverse(C[None])[0]
    return BlockWoodbury(binv=binv, bvars=struct.bvars, Aw=Aw, Cinv=Cinv)


def zero_factors(struct: StructureArrays, n: int, dt) -> BlockWoodbury:
    """Shape-matching all-zeros BlockWoodbury — the lax.scan carry
    initializer for the adaptive restart loop (the first restart
    overwrites it; a real factorization at carry init would double the
    factor cost for nothing)."""
    binv = tuple(jnp.zeros(bv.shape + (bv.shape[1],), dt)
                 for bv in struct.bvars)
    r = struct.wide_rows.shape[0]
    return BlockWoodbury(binv=binv, bvars=struct.bvars,
                         Aw=jnp.zeros((r, n), dt),
                         Cinv=jnp.zeros((r, r), dt))


def kinv_apply(bw: BlockWoodbury, b, prec=None):
    """K^-1 b for b (..., n) via the Woodbury identity.

    ``prec`` lowers the matmul precision of the apply (the mixed-precision
    sweep fast path — the defect correction against the exact system lives
    in the caller, :func:`tpusppy.solvers.shared_admm._solve_shared_K`)."""
    t = _bapply(bw.binv, bw.bvars, b, prec)
    if prec is None:
        u = t @ bw.Aw.T
        v = u @ bw.Cinv
        w = v @ bw.Aw
    else:
        from . import precision
        u = precision.contract("...n,rn->...r", t, bw.Aw, prec)
        v = precision.contract("...r,rq->...q", u, bw.Cinv, prec)
        w = precision.contract("...r,rn->...n", v, bw.Aw, prec)
    return t - _bapply(bw.binv, bw.bvars, w, prec)


def apply_kinv_like(Kinv, b, prec=None):
    """Uniform K^-1 application: dense (n, n) array or BlockWoodbury."""
    if isinstance(Kinv, BlockWoodbury):
        return kinv_apply(Kinv, b, prec)
    if prec is None:
        return b @ Kinv
    from . import precision
    return precision.contract("...n,nk->...k", b, Kinv, prec)
