"""Mixed-precision contraction helpers for the sweep engines.

TPU MXU throughput is precision-tiered: a float32 matmul at jax precision
"highest" runs as SIX bf16 passes (f32 emulation), "high" as THREE
(bf16x3), "default" as ONE (plain bf16 inputs, f32 accumulation) — so
lowering the matmul precision of the sweep-dominated frozen inner loop
buys up to 6x MXU rate on the same arrays.  This module is the single
place that maps a *mode string* onto an actual contraction:

- on TPU, :func:`contract` passes the corresponding
  ``jax.lax.Precision`` through to the native einsum — the hardware does
  the pass splitting;
- on every other backend (the CPU test/fallback posture above all), the
  pass structure is EMULATED: operands are rounded to bf16 ("default")
  or split into a 2-term bf16 expansion with the three cross products
  kept ("high" = bf16x3), accumulating in f32.  CPU tests therefore
  exercise *genuine* low-precision numerics — the refinement guard and
  the parity gates are real tests, not no-ops.

The solver engines use these helpers only for the LOW-precision sweep
phase (``ADMMSettings.sweep_precision``); defect/residual bookkeeping is
always pinned to "highest" so the OSQP termination test measures true
f32 residuals regardless of the sweep mode (classic mixed-precision
iterative refinement: defect at full precision, correction at low).
See doc/precision.md for the full scheme.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..obs import metrics as _metrics

#: Recognized matmul precision modes, fastest first.  Mirrors
#: jax.default_matmul_precision's vocabulary (and
#: flops.PRECISION_PASSES's keys).
MODES = ("default", "high", "highest")

_JAX_PRECISION = {
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}


def canon(mode: str | None) -> str:
    """Validate a mode string; ``None`` means "highest" (full f32)."""
    if mode is None:
        return "highest"
    if mode not in MODES:
        raise ValueError(
            f"matmul precision mode must be one of {MODES}; got {mode!r}")
    return mode


def is_low(mode: str | None) -> bool:
    """True when ``mode`` actually lowers precision below full f32."""
    return mode is not None and canon(mode) != "highest"


def _bf16_round(x):
    """Round to bf16 and back — the MXU input rounding, kept in the
    original float dtype so downstream arithmetic is unchanged."""
    return x.astype(jnp.bfloat16).astype(x.dtype)


def contract(spec: str, a, b, mode: str | None = None, platform=None):
    """``jnp.einsum(spec, a, b)`` at the given precision mode.

    "highest" (or None) is an exact full-precision einsum (explicitly
    pinned, so callers inside a lowered ``default_matmul_precision``
    context still get true f32 defects).  Lower modes use native MXU
    precision flags on TPU and the emulation described in the module
    docstring elsewhere.  f64 operands are emulated THROUGH f32 (the
    modes describe MXU behavior; an f64 caller opting into bf16 sweeps
    gets bf16-grade sweeps, as it asked).
    """
    mode = canon(mode)
    if mode == "highest":
        return jnp.einsum(spec, a, b, precision=jax.lax.Precision.HIGHEST)
    # TRACE-time counter (this function runs while building the program,
    # not per device execution): how many lowered contractions each
    # compiled solver embeds — the observable that a "default"/"high"
    # sweep program really was built lowered
    _metrics.inc(f"precision.lowered_contractions.{mode}")
    platform = platform or jax.default_backend()
    if platform == "tpu":
        return jnp.einsum(spec, a, b, precision=_JAX_PRECISION[mode])
    # Emulation: reproduce the TPU pass structure in f32 arithmetic.
    dt = jnp.result_type(a, b)
    a32, b32 = a.astype(jnp.float32), b.astype(jnp.float32)
    a1, b1 = _bf16_round(a32), _bf16_round(b32)
    hi = jax.lax.Precision.HIGHEST
    if mode == "default":
        out = jnp.einsum(spec, a1, b1, precision=hi)
    else:  # "high" = bf16x3: 2-term splits, drop the low-low product
        a2, b2 = _bf16_round(a32 - a1), _bf16_round(b32 - b1)
        out = (jnp.einsum(spec, a1, b1, precision=hi)
               + jnp.einsum(spec, a1, b2, precision=hi)
               + jnp.einsum(spec, a2, b1, precision=hi))
    return out.astype(dt)
