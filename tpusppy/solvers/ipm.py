"""Batched Schur-complement primal-dual interior point (continuous SPs).

The TPU-native replacement for the reference's parapint delegation
(``mpisppy/opt/sc.py:59-106``: MPI block-structured IP with MA27 factoring
each scenario's KKT block and a dense Schur system on the coupling).  Here
the same block-arrowhead structure maps onto the batch dimension:

- each IP iteration condenses every scenario's KKT system to
  ``H_s = diag(Dx_s) + A_s' diag(Dz_s) A_s`` — ONE batched (S, n, n)
  factorization on the MXU (the analogue of parapint's per-rank MA27 calls);
- the coupling (nonanticipativity) unknowns form the dense Schur system
  ``C Δw = b`` with ``C = Σ_s p_s Π_s T_s^{-1} Π_s'``, ``T_s`` the
  K x K coupling block of ``H_s^{-1}`` — a single small dense solve
  (multistage trees scatter per-scenario blocks into (node, slot) pairs).

Formulation per scenario (slack form; E selects the nonant columns):

    min c'x + 0.5 x' diag(q2) x
    s.t. A x = z,  cl <= z <= cu,  lb <= x <= ub,  E'x = w_sel(s)

with log barriers on every FINITE bound; w are the per-(node, slot)
consensus variables, and stationarity in w is the probability-weighted sum
of the coupling multipliers.  Plain path-following (fraction-to-boundary,
sigma-damped mu) — continuous problems only, like the reference.

Zero-width boxes (equality rows, clamped columns) are widened by ``EQ_EPS``
so the barrier stays defined; the induced constraint error is O(EQ_EPS).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .admm import BIG, _clean_bounds, _explicit_inverse

EQ_EPS = 1e-7


@dataclasses.dataclass(frozen=True)
class IPMSettings:
    tol: float = 1e-7          # residual + mu tolerance (equilibrated units)
    max_iter: int = 100
    sigma: float = 0.2         # centering parameter
    tau: float = 0.995         # fraction-to-boundary
    dtype: str = "float64"
    # active-set crossover from the final interior iterate (host, merged
    # EF): identifies the active set, solves its KKT equalities exactly,
    # and keeps the result only when it is feasible and improving —
    # IPM-endgame ~1e-6 accuracy becomes ~1e-9 (reference capability:
    # sc.py:59-106's solver reaches solver-exactness)
    crossover: bool = True


class IPMResult(NamedTuple):
    x: np.ndarray          # (S, n)
    w: np.ndarray          # (N, K) consensus values (nan at invalid pairs)
    obj: float             # probability-weighted objective (no const)
    mu: float
    res: float
    iters: int
    converged: bool
    crossover: bool = False   # exact-simplex cleanup verified the result


def _prep(batch, dt):
    # The condensed-KKT algebra below uses per-scenario row scalings and
    # (S, n, n) factorizations, so a shared-A batch is DENSIFIED here to
    # (S, m, n).  That silently defeats the shared-A memory savings at
    # scale, so refuse loudly rather than OOM: SchurComplement is for
    # small-to-medium batches; large shared-A families belong on the
    # shared-ADMM PH/Lagrangian path (solvers/shared_admm.py).
    if batch.A_shared is not None:
        S = batch.num_scenarios
        m, n = batch.A_shared.shape
        gib = S * m * n * np.dtype(dt).itemsize / 2**30
        if gib > 2.0:
            raise ValueError(
                f"solve_sc would densify this shared-A batch to "
                f"(S={S}, m={m}, n={n}) = {gib:.1f} GiB; use the "
                f"shared-A ADMM path (SPOpt/PH) for families this large")
    A = jnp.asarray(np.asarray(batch.A), dt)
    c = jnp.asarray(batch.c, dt)
    q2 = jnp.asarray(batch.q2, dt)
    cl, cu = _clean_bounds(jnp.asarray(batch.cl, dt), jnp.asarray(batch.cu, dt))
    lb, ub = _clean_bounds(jnp.asarray(batch.lb, dt), jnp.asarray(batch.ub, dt))
    # row/box classification on UNSCALED widths (scaling would reclassify
    # narrow range rows as equalities whenever Ruiz shrinks their rows)
    eq_unscaled = cu - cl < EQ_EPS
    eqx_unscaled = ub - lb < EQ_EPS

    # Ruiz equilibration of the WHOLE stacked system with a SHARED column
    # scaling D (n,) — per-scenario D would break the nonant consensus
    # (x_s[k] = w would couple differently-scaled coordinates); rows scale
    # per scenario.  Equilibration tames cond(H) by ~||A||^2, which the
    # late-barrier Newton systems need.
    D = jnp.ones((A.shape[2],), dt)
    E = jnp.ones(A.shape[:2], dt)
    for _ in range(8):
        As = A * E[:, :, None] * D[None, None, :]
        col = jnp.max(jnp.abs(As), axis=(0, 1))
        row = jnp.max(jnp.abs(As), axis=2)
        col = jnp.where(col < 1e-12, 1.0, col)
        row = jnp.where(row < 1e-12, 1.0, row)
        D = D / jnp.sqrt(col)
        E = E / jnp.sqrt(row)
    big = jnp.asarray(BIG, dt)
    # finiteness decided BEFORE scaling; infinite sides stay pinned at +-BIG
    fzL, fzU = cl > -BIG / 2, cu < BIG / 2
    fxL, fxU = lb > -BIG / 2, ub < BIG / 2
    A = A * E[:, :, None] * D[None, None, :]
    c = c * D[None, :]
    q2 = q2 * (D * D)[None, :]
    cl = jnp.where(fzL, cl * E, -big)
    cu = jnp.where(fzU, cu * E, big)
    lb = jnp.where(fxL, lb / D[None, :], -big)
    ub = jnp.where(fxU, ub / D[None, :], big)

    # Equality ROWS (cl == cu) carry no barrier at all: they are handled as
    # true equalities with a fixed dual regularization (Dz = 1/delta in the
    # condensed system — the same elimination algebra, mu-INDEPENDENT
    # conditioning).  A widened-box barrier instead pinches from both sides
    # and drives cond(H) -> inf as mu -> 0 (observed late divergence).
    eq = eq_unscaled
    fzL = fzL & ~eq
    fzU = fzU & ~eq
    # zero-width x boxes (clamped columns) are rare in SC usage; widen them
    lb = jnp.where(eqx_unscaled, lb - EQ_EPS, lb)
    ub = jnp.where(eqx_unscaled, ub + EQ_EPS, ub)
    return A, c, q2, cl, cu, lb, ub, D, (fxL, fxU, fzL, fzU, eq)


class _Consts(NamedTuple):
    """Problem constants for the jitted IP step (module-level jit: one
    compile per problem SHAPE, not per solve_sc call; the arrays are traced
    arguments, never baked-in XLA constants)."""

    A: jax.Array
    c: jax.Array
    q2: jax.Array
    cl: jax.Array
    cu: jax.Array
    lb: jax.Array
    ub: jax.Array
    fxL: jax.Array
    fxU: jax.Array
    fzL: jax.Array
    fzU: jax.Array
    eq: jax.Array
    probs: jax.Array
    idx: jax.Array        # (K,) nonant columns
    flat_idx: jax.Array   # (S, K) -> w slot
    valid: jax.Array      # (NK,) live (node, slot) pairs


def _gaps(con, x, z):
    """Positive barrier gaps (floored: cancellation at O(1e-7) widened-box
    widths can make the raw difference negative and poison the barrier)."""
    dt = x.dtype
    one = jnp.asarray(1.0, dt)
    floor = jnp.asarray(1e-12, dt)
    gxL = jnp.where(con.fxL, jnp.maximum(x - con.lb, floor), one)
    gxU = jnp.where(con.fxU, jnp.maximum(con.ub - x, floor), one)
    gzL = jnp.where(con.fzL, jnp.maximum(z - con.cl, floor), one)
    gzU = jnp.where(con.fzU, jnp.maximum(con.cu - z, floor), one)
    return gxL, gxU, gzL, gzU


def _mu_of(con, x, z, piL, piU, sL, sU):
    gxL, gxU, gzL, gzU = _gaps(con, x, z)
    num = (jnp.sum(piL * gxL * con.fxL) + jnp.sum(piU * gxU * con.fxU)
           + jnp.sum(sL * gzL * con.fzL) + jnp.sum(sU * gzU * con.fzU))
    den = (jnp.sum(con.fxL) + jnp.sum(con.fxU)
           + jnp.sum(con.fzL) + jnp.sum(con.fzU))
    return num / jnp.maximum(den, 1.0)


@functools.partial(jax.jit, static_argnames=("st",))
def _ipm_step(con: _Consts, x, z, y, piL, piU, sL, sU, nu, w, mu,
              st: IPMSettings):
    """One primal-dual step.  The returned ``res`` is the KKT residual of
    the INPUT iterate (that is what this step linearized); callers must
    attribute it to the pre-step state."""
    dt = x.dtype
    A, c, q2 = con.A, con.c, con.q2
    cl, cu, lb, ub = con.cl, con.cu, con.lb, con.ub
    fxL, fxU, fzL, fzU, eq = con.fxL, con.fxU, con.fzL, con.fzU, con.eq
    probs, idx, flat_idx, valid = con.probs, con.idx, con.flat_idx, con.valid
    S, m, n = A.shape
    K = idx.shape[0]
    NK = valid.shape[0]

    gxL, gxU, gzL, gzU = _gaps(con, x, z)
    w_sel = w[flat_idx]                          # (S, K)

    # residuals of the KKT system
    Enu = jnp.zeros((S, n), dt).at[:, idx].add(nu)
    r1 = -(q2 * x + c + jnp.einsum("smn,sm->sn", A, y)
           - piL + piU + Enu)                                 # stat_x
    r2 = jnp.where(eq, 0.0, -(-y - sL + sU))                  # stat_z
    r3 = -(jnp.einsum("smn,sn->sm", A, x) - z)                # prim_e
    r4 = -(x[:, idx] - w_sel)                                 # prim_c
    r5 = -(jnp.zeros((NK,), dt).at[flat_idx].add(
        probs[:, None] * nu))                                 # stat_w

    # condensed diagonal terms (masked at infinite bounds)
    Dx = q2 + jnp.where(fxL, piL / gxL, 0.0) + jnp.where(
        fxU, piU / gxU, 0.0)
    Dz = jnp.where(fzL, sL / gzL, 0.0) + jnp.where(
        fzU, sU / gzU, 0.0)
    # equality rows: regularized equality with a mu-HOMOTOPY stiffness.
    # A fixed 1/delta = 1e8 makes the cold Newton step equality-dominated
    # (|dx| ~ 1e8 * violation, clamped to ~1e-3 steps forever); tying
    # delta to mu keeps equalities soft while far from the central path
    # and machine-stiff at convergence.
    stiff = 1.0 / jnp.clip(1e-3 * mu, 1e-7, 1e2)
    Dz = jnp.where(eq, stiff, jnp.maximum(Dz, 1e-8))

    H = jnp.einsum("smn,sm,smk->snk", A, Dz, A)
    H = H + jax.vmap(jnp.diag)(Dx + jnp.asarray(1e-11, dt))
    Hinv = _explicit_inverse(H)
    # Newton refinement of the inverses (X <- X(2I - MX)) squares the
    # inverse residual: the regularized-equality rows put ~1e8 blocks in
    # H, and near convergence the barrier terms push cond(H) (and the
    # coupling block T it induces) past what one Cholesky inverse holds;
    # unrefined T was the observed failure (Schur system went garbage)
    eyeN = jnp.eye(n, dtype=dt)[None]
    for _ in range(2):
        Hinv = Hinv + jnp.einsum(
            "snk,skj->snj", Hinv, eyeN - jnp.einsum(
                "snk,skj->snj", H, Hinv))

    T = Hinv[:, idx[:, None], idx[None, :]]      # (S, K, K)
    T = T + jnp.eye(K, dtype=dt)[None] * 1e-13
    Tinv = _explicit_inverse(T)
    eyeK = jnp.eye(K, dtype=dt)[None]
    for _ in range(2):
        Tinv = Tinv + jnp.einsum(
            "skj,sjl->skl", Tinv, eyeK - jnp.einsum(
                "skj,sjl->skl", T, Tinv))

    # dense Schur matrix over (node, slot) consensus pairs — rhs-independent,
    # shared by the predictor and corrector solves
    Cm = jnp.zeros((NK, NK), dt).at[
        flat_idx[:, :, None], flat_idx[:, None, :]].add(
        probs[:, None, None] * Tinv)
    Cm = Cm + jnp.diag(jnp.where(valid, 1e-12, 1.0))
    r_e = r3
    r_c = r4

    def kkt_solve(cxL, cxU, czL, czU):
        """Direction for given centering vectors, reusing the factored
        H/T/Schur operators (the predictor-corrector pays ONE factorization
        for two solves)."""
        rhs_x = r1 + cxL - cxU
        r_z = jnp.where(eq, 0.0, r2 + czL - czU)
        rt = rhs_x + jnp.einsum("smn,sm->sn", A, Dz * r_e + r_z)
        Hr = jnp.einsum("snk,sk->sn", Hinv, rt)
        g = Hr[:, idx]
        b = jnp.zeros((NK,), dt).at[flat_idx].add(
            probs[:, None] * jnp.einsum("skj,sj->sk", Tinv, g - r_c)) - r5
        dw = jnp.linalg.solve(Cm, b)
        dnu = jnp.einsum("skj,sj->sk", Tinv, g - dw[flat_idx] - r_c)
        Ednu = jnp.zeros((S, n), dt).at[:, idx].add(dnu)
        dx = Hr - jnp.einsum("snk,sk->sn", Hinv, Ednu)
        dy = Dz * (jnp.einsum("smn,sn->sm", A, dx) - r_e) - r_z
        # equality slacks stay pinned at b: their dz would otherwise be
        # dy/stiffness, which drifts z off the equality at soft stiffness
        dz = jnp.where(eq, 0.0, (r_z + dy) / Dz)
        dpiL = jnp.where(fxL, cxL - piL * dx / gxL, 0.0)
        dpiU = jnp.where(fxU, cxU + piU * dx / gxU, 0.0)
        dsL = jnp.where(fzL, czL - sL * dz / gzL, 0.0)
        dsU = jnp.where(fzU, czU + sU * dz / gzU, 0.0)
        return dx, dz, dw, dy, dnu, dpiL, dpiU, dsL, dsU

    def max_step(v, dv, finite):
        r = jnp.where(finite & (dv < 0), -v / jnp.where(
            dv < 0, dv, -1.0), jnp.inf)
        return jnp.min(r)

    def steps(dx, dz, dpiL, dpiU, dsL, dsU, tau):
        ap = jnp.minimum(
            jnp.minimum(max_step(gxL, dx, fxL), max_step(gxU, -dx, fxU)),
            jnp.minimum(max_step(gzL, dz, fzL), max_step(gzU, -dz, fzU)))
        ad = jnp.minimum(
            jnp.minimum(max_step(piL, dpiL, fxL),
                        max_step(piU, dpiU, fxU)),
            jnp.minimum(max_step(sL, dsL, fzL), max_step(sU, dsU, fzU)))
        return jnp.minimum(tau * ap, 1.0), jnp.minimum(tau * ad, 1.0)

    # --- Mehrotra predictor: pure Newton (sigma = 0) ---------------------
    # The affine centering vectors are the mu=0 case of
    # c = (mu - dual*gap)/gap, i.e. simply -dual on every finite side
    # (the same vector feeds the rhs AND the dual-update formulas).
    aff = kkt_solve(jnp.where(fxL, -piL, 0.0), jnp.where(fxU, -piU, 0.0),
                    jnp.where(fzL, -sL, 0.0), jnp.where(fzU, -sU, 0.0))
    (dx_a, dz_a, _, _, _, dpiL_a, dpiU_a, dsL_a, dsU_a) = aff
    ap_a, ad_a = steps(dx_a, dz_a, dpiL_a, dpiU_a, dsL_a, dsU_a, 1.0)
    mu_aff = _mu_of(con, x + ap_a * dx_a, z + ap_a * dz_a,
                    piL + ad_a * dpiL_a, piU + ad_a * dpiU_a,
                    sL + ad_a * dsL_a, sU + ad_a * dsU_a)
    sigma = jnp.clip((mu_aff / jnp.maximum(mu, 1e-300)) ** 3, 1e-4, 0.99)
    smu = sigma * mu

    # --- corrector: centering + second-order complementarity terms ------
    cxL = jnp.where(fxL, (smu - piL * gxL - dpiL_a * dx_a) / gxL, 0.0)
    cxU = jnp.where(fxU, (smu - piU * gxU + dpiU_a * dx_a) / gxU, 0.0)
    czL = jnp.where(fzL, (smu - sL * gzL - dsL_a * dz_a) / gzL, 0.0)
    czU = jnp.where(fzU, (smu - sU * gzU + dsU_a * dz_a) / gzU, 0.0)
    dx, dz, dw, dy, dnu, dpiL, dpiU, dsL, dsU = kkt_solve(
        cxL, cxU, czL, czU)
    ap, ad = steps(dx, dz, dpiL, dpiU, dsL, dsU, st.tau)

    tiny = jnp.asarray(1e-16, dt)

    def advance(ap, ad):
        x2 = x + ap * dx
        z2 = z + ap * dz
        w2 = w + ap * dw
        y2 = y + ad * dy
        nu2 = nu + ad * dnu
        # duals stay strictly positive (fraction-to-boundary guarantees it
        # analytically; the floor guards rounding at tiny magnitudes)
        piL2 = jnp.where(fxL, jnp.maximum(piL + ad * dpiL, tiny), 0.0)
        piU2 = jnp.where(fxU, jnp.maximum(piU + ad * dpiU, tiny), 0.0)
        sL2 = jnp.where(fzL, jnp.maximum(sL + ad * dsL, tiny), 0.0)
        sU2 = jnp.where(fzU, jnp.maximum(sU + ad * dsU, tiny), 0.0)
        # Mehrotra: the carried mu is the MEASURED complementarity of the
        # new iterate (the adaptive sigma already did the centering damping)
        mu2 = jnp.maximum(
            _mu_of(con, x2, z2, piL2, piU2, sL2, sU2), tiny)
        return x2, z2, w2, y2, nu2, piL2, piU2, sL2, sU2, mu2

    out = advance(ap, ad)
    # safeguard: a step that INFLATES complementarity 10x (dual blow-up in
    # the soft-equality phase) is retaken short
    bad = out[-1] > 10.0 * mu
    ap = jnp.where(bad, 0.2 * ap, ap)
    ad = jnp.where(bad, 0.2 * ad, ad)
    x2, z2, w2, y2, nu2, piL2, piU2, sL2, sU2, mu2 = advance(ap, ad)

    res = jnp.maximum(
        jnp.maximum(jnp.max(jnp.abs(r1)), jnp.max(jnp.abs(r2))),
        jnp.maximum(
            jnp.maximum(jnp.max(jnp.abs(r3)), jnp.max(jnp.abs(r4))),
            jnp.max(jnp.abs(r5))))
    return x2, z2, y2, piL2, piU2, sL2, sU2, nu2, w2, mu2, res, ap, ad


def _crossover_ef(batch, xs, q2_any, masks=None):
    """Crossover from the interior iterate: restricted exact-simplex cleanup
    on the MERGED extensive form.

    The commercial-IPM recipe: variables the interior point confidently
    puts at a bound (dual multiplier dominating its gap, or primal gap
    below a tight threshold) are FIXED there, and the restricted LP — all
    rows kept, so feasibility is structural — is solved exactly (HiGHS
    simplex).  A correct restriction leaves the optimum reachable and the
    solve is fast (most columns eliminated); a wrong one shows up as a
    worse-than-interior objective and the next, looser restriction is
    tried.  Continuous families only (the SC algorithm's scope, reference
    sc.py:18-21); QPs keep the interior solution.

    Returns the (S, n) split solution or None (caller keeps the interior
    iterate).
    """
    if q2_any:
        return None
    # size guard: build_ef materializes a dense (S*m, K + S*(n-K)) matrix —
    # S times the batch's own footprint; the cleanup is validation-scale
    # machinery, not a large-deployment path
    S_, m_, n_ = batch.num_scenarios, batch.num_rows, batch.num_vars
    K_ = batch.tree.nonant_indices.shape[0]
    ef_bytes = 8 * (S_ * m_) * (K_ + S_ * (n_ - K_))
    if ef_bytes > 512 * 1024 ** 2:
        return None
    from ..ef import build_ef
    from . import scipy_backend

    ef = build_ef(batch)
    nv = ef.c.shape[0]
    cnt = np.zeros(nv)
    acc = np.zeros(nv)
    np.add.at(cnt, ef.col_of.ravel(), 1.0)
    np.add.at(acc, ef.col_of.ravel(), np.asarray(xs, float).ravel())
    x0 = acc / np.maximum(cnt, 1.0)
    lb, ub = ef.lb, ef.ub
    obj0 = float(ef.c @ x0)

    dual_lb = np.zeros(nv, bool)
    dual_ub = np.zeros(nv, bool)
    if masks is not None:
        v_lb, v_ub = masks
        np.logical_or.at(dual_lb, ef.col_of.ravel(), v_lb.ravel())
        np.logical_or.at(dual_ub, ef.col_of.ravel(), v_ub.ravel())
    tight_lb = np.isfinite(lb) & (x0 - lb < 1e-5 * (1 + np.abs(x0)))
    tight_ub = np.isfinite(ub) & (ub - x0 < 1e-5 * (1 + np.abs(x0)))
    # rung order: restricted solves are cheap warm paths, but ONLY the
    # unrestricted rung is guaranteed optimal — when it is affordable
    # (nv <= 4096) it runs LAST and its result WINS over any restricted
    # rung, so an accepted point from this function is the true EF optimum
    # whenever that rung exists; callers gate the restricted-only case on
    # interior-point quality (see _solve_sc)
    fix_sets = [
        ((dual_lb | tight_lb) & np.isfinite(lb),
         (dual_ub | tight_ub) & np.isfinite(ub)),
        (tight_lb, tight_ub & ~tight_lb),
    ]
    exact_rung = nv <= 4096
    if exact_rung:
        fix_sets.append((np.zeros(nv, bool), np.zeros(nv, bool)))
    best = None
    best_obj = obj0 + 1e-9 * max(1.0, abs(obj0))
    for k, (fl, fu) in enumerate(fix_sets):
        is_exact = exact_rung and k == len(fix_sets) - 1
        if best is not None and not is_exact:
            continue              # restricted rungs: first accepted wins
        fu = fu & ~fl
        lb_r = np.where(fu, ub, lb)
        ub_r = np.where(fl, lb, ub)
        res = scipy_backend.solve_lp(ef.c, ef.A, ef.cl, ef.cu, lb_r, ub_r)
        # require a PROVEN optimum of the restricted problem (HiGHS status
        # 0): an iteration-limited incumbent must not be installed as exact
        if not res.feasible or res.status != "0":
            continue
        if res.obj <= best_obj:
            best = res.x
            best_obj = res.obj
    return None if best is None else ef.split_solution(best)


def solve_sc(batch, settings: IPMSettings = IPMSettings()) -> IPMResult:
    """Solve the continuous SP by Schur-complement interior point."""
    st = settings
    dt = jnp.dtype(st.dtype)
    if dt == jnp.dtype(jnp.float64) and not jax.config.jax_enable_x64:
        # scoped: never flip the process-global x64 flag from library code
        with jax.enable_x64(True):
            return _solve_sc(batch, st, jnp.dtype(jnp.float64))
    return _solve_sc(batch, st, dt)


def _solve_sc(batch, st, dt):
    A, c, q2, cl, cu, lb, ub, D, masks = _prep(batch, dt)
    S, m, n = A.shape
    tree = batch.tree
    idx = jnp.asarray(tree.nonant_indices)
    K = int(idx.shape[0])
    N = tree.num_nodes
    nid = jnp.asarray(tree.nid_sk())              # (S, K) node ids
    probs = jnp.asarray(batch.probs, dt)
    NK = N * K
    flat_idx = nid * K + jnp.arange(K)[None, :]   # (S, K) -> w slot
    fxL, fxU, fzL, fzU, eq = masks
    one = jnp.asarray(1.0, dt)

    # strictly interior start: midpoint of doubly-finite boxes, a unit
    # inside single-sided ones, 0 when free
    def interior(v, lo, hi, finL, finU):
        mid = jnp.where(finL & finU, 0.5 * (lo + hi), 0.0)
        v = jnp.where(finL & finU, mid, v)
        v = jnp.where(finL & ~finU, jnp.maximum(v, lo + 1.0), v)
        v = jnp.where(~finL & finU, jnp.minimum(v, hi - 1.0), v)
        return v

    x = interior(jnp.zeros((S, n), dt), lb, ub, fxL, fxU)
    z = interior(jnp.einsum("smn,sn->sm", A, x), cl, cu, fzL, fzU)
    z = jnp.where(eq, cl, z)          # equality rows: z pinned to b
    y = jnp.zeros((S, m), dt)
    piL = jnp.where(fxL, one, 0.0)
    piU = jnp.where(fxU, one, 0.0)
    sL = jnp.where(fzL, one, 0.0)
    sU = jnp.where(fzU, one, 0.0)
    nu = jnp.zeros((S, K), dt)
    # w starts at the prob-weighted nonant average
    w0 = jnp.zeros((NK,), dt).at[flat_idx].add(
        probs[:, None] * x[:, idx])
    wden = jnp.zeros((NK,), dt).at[flat_idx].add(
        jnp.broadcast_to(probs[:, None], flat_idx.shape))
    valid = wden > 1e-300
    w = jnp.where(valid, w0 / jnp.maximum(wden, 1e-300), 0.0)

    con = _Consts(A=A, c=c, q2=q2, cl=cl, cu=cu, lb=lb, ub=ub,
                  fxL=fxL, fxU=fxU, fzL=fzL, fzU=fzU, eq=eq, probs=probs,
                  idx=idx, flat_idx=flat_idx, valid=valid)

    import os

    debug = bool(os.environ.get("TPUSPPY_IPM_DEBUG"))
    with jax.default_matmul_precision("highest"):
        mu = _mu_of(con, x, z, piL, piU, sL, sU)
        res = np.inf
        it = 0
        # equilibrated system => absolute tolerances
        best = None
        best_merit = np.inf
        stale = 0
        mu0 = float(mu)
        for it in range(1, st.max_iter + 1):
            # _ipm_step's res describes the PRE-step iterate: pair
            # snapshots and the convergence test with prev, not the
            # (unevaluated) post-step state
            prev = (x, w, float(mu))
            x, z, y, piL, piU, sL, sU, nu, w, mu, res, ap, ad = _ipm_step(
                con, x, z, y, piL, piU, sL, sU, nu, w, mu, st)
            if debug:
                print(f"ipm it={it} res={float(res):.3e} "
                      f"mu={prev[2]:.3e} ap={float(ap):.4f} "
                      f"ad={float(ad):.4f}", flush=True)
            merit = float(res) + prev[2]
            # the mu-homotopy makes early residuals meaningless (soft
            # equalities): snapshots and endgame guards engage only once
            # the path parameter has dropped well below its start
            endgame = prev[2] < 1e-3 * max(mu0, 1.0)
            if np.isfinite(merit) and endgame and merit < best_merit:
                best_merit = merit
                best = (prev[0], prev[1], prev[2], float(res))
                stale = 0
            elif endgame:
                stale += 1
            if not np.isfinite(merit):
                break          # diverged: the best iterate is the answer
            if best is not None and merit > 1e3 * max(best_merit, 1e-300):
                break
            if stale >= 4:
                break          # endgame stagnation (barrier conditioning)
            if float(res) < st.tol and prev[2] < st.tol:
                best = (prev[0], prev[1], prev[2], float(res))
                break
    if best is not None:
        x, w, mu_f, res_f = best
    else:
        mu_f, res_f = float(mu), float(res)

    # unscale (the loop ran on the Ruiz-equilibrated system)
    D_np = np.asarray(D)
    xs = np.asarray(x) * D_np[None, :]
    converged = bool(res_f < st.tol and mu_f < st.tol)
    crossed = False
    if st.crossover:
        q2_any = bool(np.any(np.asarray(batch.q2) != 0.0))
        # dual-ratio activity masks from the final interior multipliers
        # (equilibrated units: active iff multiplier dominates its gap)
        hL, hU, _, _ = [np.asarray(v) for v in _gaps(con, x, z)]
        piL_n, piU_n = np.asarray(piL), np.asarray(piU)
        fxL_n, fxU_n = np.asarray(fxL), np.asarray(fxU)
        masks = (fxL_n & (piL_n > hL), fxU_n & (piU_n > hU))
        # a STALLED interior point (endgame stagnation far from tol) may
        # sit above the optimum: restricted rungs could then certify a
        # suboptimal vertex.  Small EFs always finish with the
        # unrestricted exact rung, so any accepted point IS optimal;
        # bigger EFs only cross over from a converged interior point.
        interior_ok = bool(res_f < 100 * st.tol)
        # "small" must mean the EXACT unrestricted rung exists (EF column
        # count <= 4096), not just a small row count.  Count columns the
        # way build_ef does: one merged column per distinct (node, nonant
        # slot) pair — a two-stage shortcut (K + S*(n-K)) undercounts
        # multistage EFs, which allocate per-node columns.
        K_c = batch.tree.nonant_indices.shape[0]
        nid_sk = batch.tree.nid_sk()                     # (S, K) node ids
        merged_cols = np.unique(
            nid_sk.astype(np.int64) * max(K_c, 1)
            + np.arange(K_c, dtype=np.int64)[None, :]).size
        nv_est = merged_cols + batch.num_scenarios * (batch.num_vars - K_c)
        small_ef = nv_est <= 4096
        x_cross = None
        if interior_ok or small_ef:
            x_cross = _crossover_ef(batch, xs, q2_any, masks=masks)
        if x_cross is not None:
            xs = x_cross
            res_f = 0.0          # feasibility verified to crisp tolerance
            mu_f = 0.0
            converged = True
            crossed = True
            # the consensus values are exact on the merged columns
            w_src = xs[:, np.asarray(idx)]
            w_np0 = np.zeros((N, K))
            cnt0 = np.zeros((N, K))
            nid_np = np.asarray(batch.tree.nid_sk())
            for s in range(xs.shape[0]):
                w_np0[nid_np[s], np.arange(K)] = w_src[s]
                cnt0[nid_np[s], np.arange(K)] = 1.0
            w = None
    obj = float(np.asarray(batch.probs) @ (
        np.einsum("sn,sn->s", np.asarray(batch.c, float), xs)
        + 0.5 * np.einsum("sn,sn->s", np.asarray(batch.q2, float),
                          xs * xs)))
    if w is not None:
        w_np = np.asarray(w).reshape(N, K) * D_np[np.asarray(idx)][None, :]
        w_np = np.where(np.asarray(valid).reshape(N, K), w_np, np.nan)
    else:
        w_np = np.where(cnt0 > 0, w_np0, np.nan)
    return IPMResult(
        x=xs, w=w_np, obj=obj, mu=float(mu_f), res=float(res_f), iters=it,
        converged=converged, crossover=crossed,
    )
