"""Partial MILP lift for Lagrangian outer bounds.

The reference's Lagrangian spoke inherits the hub's MIP solver, so its
per-scenario subproblem minima are INTEGER minima
(mpisppy/cylinders/lagrangian_bounder.py:19-56 with a persistent MIP solver
behind it) — its dual bound closes the integrality gap that a pure
LP-relaxation bound cannot (measured on the 30x24 UC family: 0.4-0.9 %
per-scenario, which alone forbids a 1 % certified gap from LP bounds).

tpusppy's device path solves LP relaxations (batched ADMM), so the spoke's
baseline certificate is the per-scenario LP dual objective
(:meth:`tpusppy.spopt.SPOpt.Edualbound_perscen`).  This module lifts it:

    For ANY subset M of scenarios,
        bound = sum_{s in M} p_s * milp_dual_bound_s
              + sum_{s not in M} p_s * lp_dual_s
    is a certified lower bound on the EF optimum — each term independently
    lower-bounds its scenario's integer minimum of the W-augmented
    objective, and the probability-weighted W sums to zero per node.

So the lift is budget-elastic: spend ``budget_s`` host-seconds solving
scenario MILPs (HiGHS); whatever fraction completes tightens the bound,
the rest keep their LP certificate.  Even a time-limited MILP contributes:
HiGHS's best-bound (``SolveResult.dual_bound``) is certified at any stop.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

import numpy as np

from . import scipy_backend


def milp_lift(batch, q, base_perscen, *, budget_s=30.0, mip_rel_gap=1e-4,
              time_limit=30.0, workers=None, order=None, want_x=False):
    """Lift per-scenario LP dual bounds to MILP dual bounds, budget-bound.

    ``q``: (S, n) per-scenario objective (c + W on nonant columns — the
    caller's W-augmented objective, prox off).  ``base_perscen``: (S,)
    certified LP dual bounds including ``batch.const``.  Returns
    ``(lifted (S,), n_lifted)`` — or ``(lifted, n_lifted, X)`` with
    ``want_x`` where ``X`` is the (S, n) MILP minimizers (NaN rows for
    unlifted scenarios; :func:`milp_dual_ascent` consumes them as
    subgradients).  Every entry keeps the LP certificate whenever that is
    the tighter bound — both certify the scenario's integer minimum.

    ``order``: scenario visit order (default: descending probability, so a
    truncated budget lifts the heaviest terms first).  ``workers`` threads
    solve concurrently (HiGHS releases the GIL); on single-core hosts this
    degrades gracefully to serial.
    """
    S = batch.num_scenarios
    lifted = np.array(base_perscen, dtype=float, copy=True)
    X = np.full((S, batch.num_vars), np.nan) if want_x else None
    if not bool(np.asarray(batch.is_int).any()):
        # continuous family: LP bound is already exact
        return (lifted, 0, X) if want_x else (lifted, 0)
    probs = np.asarray(batch.tree.scen_prob, dtype=float)
    if order is None:
        order = np.argsort(-probs, kind="stable")
    q = np.asarray(q, dtype=float)
    const = np.broadcast_to(np.asarray(batch.const), (S,))
    deadline = time.monotonic() + float(budget_s)
    workers = workers or min(8, os.cpu_count() or 1)
    # shared-A families: one csr conversion for the whole lift round
    import scipy.sparse as _sp

    A_sh = getattr(batch, "A_shared", None)
    A_csr = _sp.csr_matrix(np.asarray(A_sh)) if A_sh is not None else None

    def solve(s):
        rem = deadline - time.monotonic()
        if rem <= 0.05:
            return s, None
        res = scipy_backend.solve_lp(
            q[s], A_csr if A_csr is not None else batch.A[s],
            batch.cl[s], batch.cu[s],
            batch.lb[s], batch.ub[s], is_int=batch.is_int,
            mip_rel_gap=mip_rel_gap,
            time_limit=min(float(time_limit), rem))
        return s, res

    n_lifted = 0
    order = list(order)
    with ThreadPoolExecutor(max_workers=workers) as ex:
        pending = set()
        while order or pending:
            while order and len(pending) < workers:
                if time.monotonic() >= deadline:
                    order = []
                    break
                pending.add(ex.submit(solve, order.pop(0)))
            if not pending:
                break
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for fut in done:
                s, res = fut.result()
                db = None if res is None else res.dual_bound
                if db is not None and np.isfinite(db):
                    # RESULT-PLUMBING CONTRACT (regression-tested): a
                    # time-limited best-bound that is LOOSER than the
                    # scenario's existing LP certificate is never
                    # installed — both certify the same integer minimum,
                    # so the per-scenario max is the certificate
                    cand = db + float(const[s])
                    if cand > lifted[s]:
                        lifted[s] = cand
                    if X is not None and res.feasible \
                            and res.status == "0":
                        # only gap-closed solves install X: the rows are
                        # documented as MILP MINIMIZERS (milp_dual_ascent
                        # consumes them as subgradients), and a
                        # time-limited incumbent is merely feasible
                        X[s] = res.x
                    n_lifted += 1
    return (lifted, n_lifted, X) if want_x else (lifted, n_lifted)


def milp_dual_ascent(batch, W, base_fn, *, steps=8, budget_s=120.0,
                     step0=None, mip_rel_gap=1e-3, time_limit=30.0,
                     workers=None):
    """Projected subgradient ascent on the INTEGER Lagrangian dual.

    The Lagrangian dual value L(W) = sum_s p_s min{(c_s + W_s).x : x in
    X_s^int} is concave in W with subgradient (x_s* - xbar*) per scenario;
    ascent steps tighten the certified bound past what the hub's PH weights
    reach (PH's W targets the LP-relaxation dual; the integer dual optimum
    sits above it by part of the integrality gap).  Reference analogue: the
    Lagranger spoke takes its own steps on W rather than mirroring the hub
    (mpisppy/cylinders/lagranger_bounder.py).

    ``base_fn(W) -> (q (S, n), base_perscen (S,))`` supplies the
    W-augmented objective and the LP fallback certificates for partial
    lifts.  Every iterate's value is a VALID bound (any W with
    probability-weighted zero mean certifies); the best is kept.  Returns
    ``(best_bound, best_W)``.
    """
    nid = np.asarray(batch.tree.nonant_indices)
    probs = np.asarray(batch.tree.scen_prob, dtype=float)
    W = np.array(W, dtype=float, copy=True)
    deadline = time.monotonic() + float(budget_s)
    best = -np.inf
    best_W = W.copy()
    step = step0
    for _ in range(int(steps)):
        rem = deadline - time.monotonic()
        if rem <= 1.0:
            break
        q, base = base_fn(W)
        lifted, n, X = milp_lift(
            batch, q, base, budget_s=rem, mip_rel_gap=mip_rel_gap,
            time_limit=time_limit, workers=workers, want_x=True)
        val = float(probs @ lifted)
        if val > best:
            best, best_W = val, W.copy()
        ok = ~np.isnan(X[:, 0])
        if not ok.all():
            break                 # partial lift: subgradient incomplete
        xs = X[:, nid]
        g = xs - (probs @ xs)[None, :]
        gn = np.sqrt(float((probs[:, None] * g * g).sum()))
        if gn < 1e-12:
            break                 # consensus among integer minimizers
        if step is None:
            # scale the first step to move the dual by ~0.1% of |best|
            step = 1e-3 * max(abs(best), 1.0) / gn
        W = best_W + step * g
        W = W - (probs @ W)[None, :]    # probability-weighted zero mean
        step *= 0.7
    return best, best_W
