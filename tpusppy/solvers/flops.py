"""FLOP model for the batched ADMM engines + MFU accounting.

Single source for the arithmetic-cost model that previously lived inline in
:mod:`tpusppy.solvers.segmented` (dispatch sizing) and is now also consumed
by the fused-step autotuner (:mod:`tpusppy.tune`) and the benchmark's MFU
reporting (``bench.py``/``bench_uc.py``).

The model counts the dominant matmul work only (multiply-add = 2 flops):

- one ADMM **sweep** per scenario is one (n, n) x-update apply plus an A and
  an A' matvec: ``(n^2 + 2nm) * 2`` flops, scaled by ``sparse_factor`` for
  the gather/segment-sum SparseA engine (measured 2-4x cheaper than the
  dense accounting at reference-UC shapes);
- one **factorization** is the K assembly plus the blocked inversion:
  ``(m n^2 + 3 n^3) * 2`` flops, times ``factor_batch`` (S for the dense
  per-scenario engine, 1 for the shared-A engine).

MFU is *model* flops over *nominal* peak — an accounting convention, not a
hardware counter: elementwise work, residual bookkeeping and host/dispatch
gaps all land in the denominator, so the number is conservative.  The peak
is precision-adjusted: ``matmul_precision="highest"`` on TPU runs bf16x6
passes (6 MXU passes per f32 multiply-add), so the achievable ceiling is
the bf16 peak divided by the pass count.  Report ``peak_note`` alongside
``mfu_pct`` so the assumption is auditable.
"""

from __future__ import annotations

# bf16 MXU peak per chip, matched by substring against device_kind (first
# hit wins; order matters for e.g. "v5" vs "v5p").  Sources: public TPU
# spec sheets.  Unknown kinds fall back to the env override or None.
_TPU_PEAKS_BF16 = (
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v5", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# MXU passes per multiply-add at each jax matmul precision on TPU:
# "highest" = bf16x6 f32 emulation, "high" = bf16x3, "default" = plain bf16
PRECISION_PASSES = {"highest": 6, "high": 3, "default": 1}

# Conservative wall-clock speedup of a SWEEP at lowered matmul precision,
# used by dispatch sizing (segmented.dispatch_segments).  Deliberately the
# FLOOR over execution regimes, far below the theoretical pass ratios
# (6x/2x): the per-scenario dense Pallas kernel runs its contractions in
# exact f32 VPU math regardless of mode ("high" gains nothing there;
# "default" gains only the bf16-storage bandwidth saving), while the XLA
# MXU regimes gain the pass ratio.  Underestimating the speedup is
# watchdog-safe (dispatches sized smaller than they could be);
# overestimating would let a fused program outlive the worker's ~60 s
# execution kill.  Revisit with measured sweep times per mode.
SWEEP_SPEEDUP = {"highest": 1.0, "high": 1.0, "default": 1.25}


def sweep_speedup(mode) -> float:
    """Dispatch-model throughput factor for a sweep at precision ``mode``
    (None = "highest" = 1.0)."""
    return SWEEP_SPEEDUP.get(mode or "highest", 1.0)

# Nominal CPU peak used when nothing better is known (one modern core's
# order-of-magnitude f64 FMA throughput).  CPU MFU numbers exist so the
# smoke bench exercises the full reporting path, not as a claim about the
# host — the artifact carries peak_note for honesty.
CPU_NOMINAL_PEAK = 5e10


def sweep_flops(S, n, m, sparse_factor=1.0):
    """Model flops of ONE ADMM sweep over an S-scenario batch."""
    return S * (n * float(n) + 2.0 * n * m) * 2.0 * sparse_factor


def factor_flops(n, m, factor_batch=1, sparse_factor=1.0):
    """Model flops of one batch (re)factorization."""
    return factor_batch * (m * float(n) * n + 3.0 * float(n) ** 3) \
        * 2.0 * sparse_factor


def speculation_flops(S, n, m, seg_f, overlap=1, sparse_factor=1.0):
    """Worst-case model flops a PIPELINED frozen continuation may burn on
    DISCARDED speculative segments per solve (``overlap`` segments of
    ``seg_f`` sweeps each — see ``segmented.continue_frozen``).

    This is the billing term for the overlapped dispatch pipeline: the
    continuation charges its sweep budget at dispatch time, so the waste
    is bounded by exactly this amount and the total dispatched work never
    exceeds the serial worst case.  The tune stage
    (``tpusppy.tune.autotune_pipeline``) weighs it against the measured
    stop-stats RPC latency to decide whether speculation pays for a
    shape.
    """
    return max(0, int(overlap)) * max(0, int(seg_f)) \
        * sweep_flops(S, n, m, sparse_factor)


def megastep_flops(S, n, m, n_iters, sweeps, sparse_factor=1.0):
    """Model flops of ONE wheel megastep dispatch: ``n_iters`` frozen PH
    iterations (sweep work only — the refresh rides its own dispatch at
    the cadence boundary) of ``sweeps`` ADMM sweeps each.

    This is the mega-dispatch billing unit: a megastep is N iterations of
    work in one device program, so its dispatch accounting — watchdog
    sizing (``segmented.megastep_cap``), FLOP billing
    (``segmented.bill_megastep``) and the bench MFU denominator — must
    scale with N, and a watchdog- or budget-capped megastep bills only
    the iterations actually dispatched (callers pass the executed count,
    never the requested one).
    """
    return max(0, int(n_iters)) * sweep_flops(S, n, m, sparse_factor) \
        * max(float(sweeps), 1.0)


def bound_pass_flops(S, n, m, sweeps, sparse_factor=1.0, n_evals=1):
    """Model flops of ONE in-wheel bound pass (doc/pipeline.md "In-wheel
    certification"): ``n_evals`` frozen evaluations at the measured
    ``sweeps`` (1 for the legacy xhat-at-xbar pass; the batched integer
    sweep runs its C rounding candidates + 1 reduced-cost re-solve,
    doc/integer.md) plus one sweep-equivalent for the Lagrangian
    dual-objective assembly (an A'y matvec pair and per-coordinate
    closed-form minima — the same matvec volume as a single sweep)."""
    return sweep_flops(S, n, m, sparse_factor) \
        * (max(1, int(n_evals)) * max(float(sweeps), 1.0) + 1.0)


def tenant_shares(rows):
    """Live-row-fraction attribution weights for a SHARED dispatch
    (doc/serving.md "Continuous batching"): one fused tenant-batched
    megastep serves K tenants at once, and the shared wall/FLOP cost is
    split ``share_t = rows_t / sum(rows)`` where ``rows_t`` is the
    tenant's live row count weighted by the iterations it actually ran
    (``S_t * max(1, executed_t)``; 0 for ghost slots).  Returns one
    float per entry, summing to 1.0 over live tenants (all zeros ->
    all-zero shares)."""
    rows = [max(0.0, float(r)) for r in rows]
    total = sum(rows)
    if total <= 0.0:
        return [0.0] * len(rows)
    return [r / total for r in rows]


def ph_iteration_flops(S, n, m, sweeps, refresh_every=16, restarts=1,
                       factor_batch=1, sparse_factor=1.0):
    """Model flops of one PH iteration, refresh cost amortized over the
    cadence.

    ``sweeps`` is the MEASURED (or configured) ADMM sweep count per
    subproblem solve — use ``PHStepOut.iters`` from the actual run, not
    ``max_iter``, or the MFU is inflated by sweeps that never ran.  A
    refresh iteration runs ``restarts`` adaptation rounds (each a sweep
    budget + a factorization); 1 in ``refresh_every`` iterations is a
    refresh.
    """
    sw = sweep_flops(S, n, m, sparse_factor) * max(float(sweeps), 1.0)
    fa = factor_flops(n, m, factor_batch, sparse_factor)
    f = 1.0 / max(1, refresh_every)
    rst = max(1, restarts)
    return (1.0 - f) * sw + f * rst * (sw + fa)


def device_peak_flops(device=None, matmul_precision="highest"):
    """(peak_flops_per_device, note) for MFU accounting.

    ``TPUSPPY_PEAK_FLOPS`` (flops/s per device, already precision-adjusted)
    overrides everything — the escape hatch for unknown hardware.  Returns
    (None, reason) when no peak is known.
    """
    import os

    env = os.environ.get("TPUSPPY_PEAK_FLOPS")
    if env:
        return float(env), "TPUSPPY_PEAK_FLOPS override"
    if device is None:
        import jax
        device = jax.devices()[0]
    platform = getattr(device, "platform", "cpu")
    if platform == "cpu":
        return CPU_NOMINAL_PEAK, "cpu nominal (order-of-magnitude)"
    kind = (getattr(device, "device_kind", "") or "").lower()
    passes = PRECISION_PASSES.get(matmul_precision, 1)
    for key, bf16 in _TPU_PEAKS_BF16:
        if key in kind:
            return bf16 / passes, (
                f"{key} {bf16/1e12:.0f}T bf16 / {passes} "
                f"({matmul_precision})")
    return None, f"unknown device_kind {kind!r}"


def mfu_pct(iters_per_sec, flops_per_iter, n_devices=1, device=None,
            matmul_precision="highest"):
    """(mfu_pct, note): model-flop utilization of the whole mesh.

    None when the peak is unknown (note says why).  ``flops_per_iter`` is
    the TOTAL model flops of one PH iteration (all scenarios), so the
    denominator scales with ``n_devices``.
    """
    peak, note = device_peak_flops(device, matmul_precision)
    if peak is None or iters_per_sec is None:
        return None, note
    achieved = iters_per_sec * flops_per_iter
    return 100.0 * achieved / (peak * max(1, n_devices)), note
