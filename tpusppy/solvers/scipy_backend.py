"""HiGHS (scipy) validation backend.

The reference delegates every subproblem/EF solve to an external commercial
solver through Pyomo's SolverFactory (spopt.py:839-903).  tpusppy's primary
solver is the TPU-native batched ADMM (:mod:`tpusppy.solvers.admm`); this module
is the analogue of the external-solver path — a CPU LP/MILP solve via
scipy's vendored HiGHS — used for golden-value tests and as a fallback backend.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.optimize as sopt
import scipy.sparse as sp


@dataclasses.dataclass
class SolveResult:
    x: np.ndarray
    obj: float
    duals: np.ndarray | None
    status: str
    feasible: bool
    # MIP solves: HiGHS's best dual (lower) bound — certified even when the
    # solve stops on a gap/time limit; None for LP/IPM paths
    dual_bound: float | None = None


def solve_lp(c, A, cl, cu, lb, ub, is_int=None, q2=None, const=0.0,
             mip_rel_gap=None, time_limit=None) -> SolveResult:
    """Solve one canonical-form problem with HiGHS.

    Quadratic objectives are not supported by scipy's HiGHS wrapper; callers
    with q2 != 0 must use the ADMM backend (this mirrors the reference, where
    solver capability gates algorithm choice, e.g. sc.py:18-21).
    """
    if q2 is not None and np.any(q2 != 0):
        raise NotImplementedError("HiGHS backend is LP/MILP only; use admm for QP")
    m, n = A.shape
    if not sp.issparse(A):
        A = sp.csr_matrix(np.asarray(A))
    constraints = sopt.LinearConstraint(A, cl, cu) if m else ()
    integrality = None
    if is_int is not None and np.any(is_int):
        integrality = np.where(is_int, 1, 0)
    options = {}
    if mip_rel_gap is not None:
        options["mip_rel_gap"] = mip_rel_gap
    if time_limit is not None:
        options["time_limit"] = time_limit
    res = sopt.milp(
        c=c,
        constraints=constraints,
        integrality=integrality,
        bounds=sopt.Bounds(lb, ub),
        options=options,
    )
    # milp status: 0 optimal, 1 iteration/time limit (may carry an incumbent),
    # 2 infeasible, 3 unbounded, 4 other
    feasible = res.x is not None and res.status in (0, 1)
    x = res.x if res.x is not None else np.zeros(n)
    obj = float(c @ x + const) if res.x is not None else np.inf
    db = getattr(res, "mip_dual_bound", None)
    if db is None and res.status == 0:
        db = obj                 # LP optimal: the solve itself is the bound
    elif db is not None:
        db = float(db + const)
    # scipy.milp does not expose duals; LP duals come from linprog when needed.
    return SolveResult(x=x, obj=obj, duals=None, status=str(res.status),
                       feasible=feasible, dual_bound=db)


def solve_lp_with_duals(c, A, cl, cu, lb, ub, const=0.0,
                        time_limit=None) -> SolveResult:
    """Continuous LP with row duals via linprog (for Benders/Lagrangian
    checks and the straggler rescue).  ``A`` goes through scipy.sparse:
    UC-scale matrices are ~0.3% dense, and linprog's dense input path
    both copies and scans the full (m, n) array per call.
    ``time_limit``: HiGHS wall-clock cap in seconds (budgeted callers —
    e.g. donor-dual rounds — must not hang on one degenerate LP)."""
    # linprog wants A_ub x <= b_ub and A_eq x = b_eq; split rows.
    if not sp.issparse(A):
        A = sp.csr_matrix(np.asarray(A))
    eq = np.isfinite(cl) & np.isfinite(cu) & (cl == cu)
    ub_rows = np.isfinite(cu) & ~eq
    lb_rows = np.isfinite(cl) & ~eq
    A_ub = (sp.vstack([A[ub_rows], -A[lb_rows]], format="csr")
            if (ub_rows.any() or lb_rows.any()) else None)
    b_ub = np.concatenate([cu[ub_rows], -cl[lb_rows]]) if A_ub is not None else None
    A_eq = A[eq] if eq.any() else None
    b_eq = cl[eq] if eq.any() else None
    options = {"time_limit": float(time_limit)} if time_limit else None
    res = sopt.linprog(c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq,
                       bounds=np.stack([lb, ub], axis=1), method="highs",
                       options=options)
    duals = None
    if res.status == 0:
        duals = np.zeros(A.shape[0])
        if A_eq is not None:
            duals[np.flatnonzero(eq)] = res.eqlin.marginals
        k = 0
        for rows, sign in ((ub_rows, 1.0), (lb_rows, -1.0)):
            cnt = int(rows.sum())
            if cnt:
                duals[np.flatnonzero(rows)] += sign * res.ineqlin.marginals[k:k + cnt]
                k += cnt
    x = res.x if res.x is not None else np.zeros(A.shape[1])
    return SolveResult(x=x, obj=float(res.fun + const) if res.status == 0 else np.inf,
                       duals=duals, status=str(res.status), feasible=res.status == 0)


def solve_qp_with_duals(c, q2, A, cl, cu, lb, ub, const=0.0,
                        tol=1e-9, max_iter=60) -> SolveResult:
    """Host-exact diagonal-Hessian QP with row duals: the QP sibling of
    :func:`solve_lp_with_duals` for the straggler rescue (scipy's HiGHS
    wrapper is LP/MILP only, so this is a self-contained dense Mehrotra
    predictor-corrector IPM in numpy).

        min c.x + 0.5 x'diag(q2)x   s.t. cl <= Ax <= cu, lb <= x <= ub

    Returns x and row duals y in the framework's convention (y > 0 active
    at cu, y < 0 at cl — the convention :func:`tpusppy.solvers.admm.
    dual_objective` certifies bounds with).  Equality rows (cl == cu; UC
    logic/balance rows) are handled through an explicit augmented KKT
    block, NOT a large penalty — penalized equalities push the condensed
    Hessian's conditioning past f64 (observed res ~ 1e4 on 30x24 UC).
    Thin wrapper over the batched :func:`solve_qp_batch_with_duals`.
    Reference analogue: subproblem solves are always solver-exact
    (mpisppy/spopt.py:85-223).
    """
    c = np.asarray(c, float)
    q2 = np.asarray(q2, float)
    x, y, feasible, res, mu = _qp_ipm_batch(
        c[None], q2[None], np.asarray(A, float),
        np.asarray(cl, float)[None], np.asarray(cu, float)[None],
        np.asarray(lb, float)[None], np.asarray(ub, float)[None],
        tol, max_iter)
    obj = float(c @ x[0] + 0.5 * (q2 @ (x[0] * x[0])) + const)
    return SolveResult(x=x[0], obj=obj if feasible[0] else np.inf,
                       duals=y[0],
                       status=f"ipm_res={res[0]:.2e}_mu={mu[0]:.2e}",
                       feasible=bool(feasible[0]))


def solve_qp_batch_with_duals(c, q2, A, cl, cu, lb, ub, tol=1e-9,
                              max_iter=60):
    """Batched sibling of :func:`solve_qp_with_duals`: k scenarios at once.

    Same dense Mehrotra predictor-corrector, vectorized over a leading
    scenario axis — the per-iteration factorization becomes one
    LAPACK-batched (k, n+me, n+me) solve and the ``H = A' Dz A`` build one
    einsum, so rescuing dozens of stragglers costs one IPM run instead of
    k serial ones (the straggler rescue's QP path is the caller:
    ``spopt._rescue_stragglers``).

    ``A`` may be (m, n) — shared across scenarios, the shared-A family
    case, keeping the rescue at zero extra constraint memory — or
    (k, m, n).  Returns ``(x (k, n), y (k, m), feasible (k,) bool)``.
    Scenarios are grouped by equality-row pattern (the augmented KKT block
    must be structurally shared inside one batched solve); family slices
    share the pattern, so the common case is a single group.
    """
    c = np.atleast_2d(np.asarray(c, float))
    q2 = np.atleast_2d(np.asarray(q2, float))
    k, n = c.shape
    A = np.asarray(A, float)
    shared = A.ndim == 2
    m = A.shape[-2]
    cl = np.broadcast_to(np.asarray(cl, float), (k, m))
    cu = np.broadcast_to(np.asarray(cu, float), (k, m))
    lb = np.broadcast_to(np.asarray(lb, float), (k, n))
    ub = np.broadcast_to(np.asarray(ub, float), (k, n))
    eq = (np.where(np.isfinite(cu), cu, 1e18)
          - np.where(np.isfinite(cl), cl, -1e18)) < 1e-9
    x = np.zeros((k, n))
    y = np.zeros((k, m))
    feasible = np.zeros(k, bool)
    groups = {}
    for s in range(k):
        groups.setdefault(eq[s].tobytes(), []).append(s)
    for idx in groups.values():
        idx = np.asarray(idx)
        Ag = A if shared else A[idx]
        xg, yg, fg, _, _ = _qp_ipm_batch(
            c[idx], q2[idx], Ag, cl[idx], cu[idx], lb[idx], ub[idx],
            tol, max_iter)
        x[idx], y[idx], feasible[idx] = xg, yg, fg
    return x, y, feasible


def _qp_ipm_batch(c, q2, A, cl, cu, lb, ub, tol, max_iter):
    """Core batched Mehrotra IPM; every scenario in the batch must share
    one equality-row pattern (callers group).  Equality rows enter an
    augmented quasi-definite KKT system

        [ A_in' Dz A_in + diag(q2 + Dx)   A_eq' ] [dx   ]   [rhs_x]
        [ A_eq                            -dI   ] [dy_eq] = [rp_eq]

    solved LAPACK-batched; inequality-row duals stay condensed through Dz.
    Returns (x, y, feasible, res, mu), all with the leading k axis.
    """
    k, n = c.shape
    shared = A.ndim == 2
    m = A.shape[-2]

    # Ruiz equilibration + cost normalization: the raw UC family (|c| ~ 1e4,
    # |A| rows ~ 1e3) collapses Mehrotra step lengths to ~1e-7 from the
    # first iteration without it.  Same posture as the ADMM solver's
    # scaling; duals unscale as y = k_c E y_hat, box duals fold into the
    # returned stationarity identity automatically.
    finL_c = np.isfinite(cl) & (cl > -1e17)
    finU_c = np.isfinite(cu) & (cu < 1e17)
    finL_b = np.isfinite(lb) & (lb > -1e17)
    finU_b = np.isfinite(ub) & (ub < 1e17)
    Aref = np.abs(A) if shared else np.abs(A).mean(axis=0)
    D = np.ones(n)
    E = np.ones(m)
    for _ in range(10):
        Am = Aref * E[:, None] * D[None, :]
        rm = Am.max(axis=1)
        cm = Am.max(axis=0)
        # all-zero rows/columns (preallocated cut slots, ir.with_extra) must
        # keep unit scale — dividing by sqrt(eps) diverges 1e6x per sweep
        E /= np.where(rm > 0, np.sqrt(np.maximum(rm, 1e-12)), 1.0)
        D /= np.where(cm > 0, np.sqrt(np.maximum(cm, 1e-12)), 1.0)
    A = A * (E[:, None] * D[None, :])
    c = c * D
    q2 = q2 * D * D
    kc = np.maximum(1.0, np.abs(c).max(axis=1, initial=0.0))[:, None]
    c = c / kc
    q2 = q2 / kc
    cl = np.where(finL_c, cl * E, -np.inf)
    cu = np.where(finU_c, cu * E, np.inf)
    lb = np.where(finL_b, lb / D, -np.inf)
    ub = np.where(finU_b, ub / D, np.inf)

    def Ax(v):      # (k, n) -> (k, m)
        return v @ A.T if shared else np.einsum("kmn,kn->km", A, v)

    def ATy(v):     # (k, m) -> (k, n)
        return v @ A if shared else np.einsum("kmn,km->kn", A, v)

    big = 1e18
    cl = np.where(np.isfinite(cl), cl, -big)
    cu = np.where(np.isfinite(cu), cu, big)
    lb = np.where(np.isfinite(lb), lb, -big)
    ub = np.where(np.isfinite(ub), ub, big)
    eq1 = (cu[0] - cl[0]) < 1e-9           # shared pattern (callers group)
    eq = eq1[None, :]
    idx_eq = np.flatnonzero(eq1)
    me = idx_eq.size
    A_eq = (A[idx_eq] if shared else A[:, idx_eq, :])   # (me, n) / (k, me, n)
    fzL = (cl > -big / 2) & ~eq
    fzU = (cu < big / 2) & ~eq
    fxL = lb > -big / 2
    fxU = ub < big / 2

    scale = np.maximum(1.0, np.maximum(np.abs(c).max(axis=1, initial=0.0),
                                       np.abs(q2).max(axis=1, initial=0.0)))

    def interior(v, lo, hi, finL, finU):
        mid = np.where(finL & finU, 0.5 * (lo + hi), v)
        v = np.where(finL & finU, mid, v)
        v = np.where(finL & ~finU, np.maximum(v, lo + 1.0), v)
        v = np.where(~finL & finU, np.minimum(v, hi - 1.0), v)
        return v

    x = interior(np.zeros((k, n)), lb, ub, fxL, fxU)
    z = interior(Ax(x), cl, cu, fzL, fzU)
    z = np.where(eq, cl, z)
    y = np.zeros((k, m))
    sL = np.where(fzL, 1.0, 0.0)
    sU = np.where(fzU, 1.0, 0.0)
    piL = np.where(fxL, 1.0, 0.0)
    piU = np.where(fxU, 1.0, 0.0)
    delta = 1e-10 * max(1.0, float(np.abs(A_eq).max(initial=0.0)))

    def gaps():
        gL = np.where(fzL, np.maximum(z - cl, 1e-14), 1.0)
        gU = np.where(fzU, np.maximum(cu - z, 1e-14), 1.0)
        hL = np.where(fxL, np.maximum(x - lb, 1e-14), 1.0)
        hU = np.where(fxU, np.maximum(ub - x, 1e-14), 1.0)
        return gL, gU, hL, hU

    n_compl = np.maximum(
        fzL.sum(axis=1) + fzU.sum(axis=1) + fxL.sum(axis=1) + fxU.sum(axis=1),
        1)
    res = np.full(k, np.inf)
    mu = np.full(k, np.inf)
    eye = np.arange(n)
    M = None if me else np.empty(0)   # KKT block allocated once, first use
    for _ in range(max_iter):
        gL, gU, hL, hU = gaps()
        rd = -(c + q2 * x + ATy(y) - piL + piU)
        rp = -(Ax(x) - z)
        ry = -(y - sU + sL)
        mu = ((sL * np.where(fzL, gL, 0.0)).sum(axis=1)
              + (sU * np.where(fzU, gU, 0.0)).sum(axis=1)
              + (piL * np.where(fxL, hL, 0.0)).sum(axis=1)
              + (piU * np.where(fxU, hU, 0.0)).sum(axis=1)) / n_compl
        res = np.maximum(
            np.abs(rd).max(axis=1, initial=0.0) / scale,
            np.maximum(np.abs(rp).max(axis=1, initial=0.0),
                       np.abs(np.where(eq, 0.0, ry)).max(axis=1, initial=0.0)))
        done = (res < tol) & (mu < tol)
        if done.all():
            break

        Dz = np.where(eq, 0.0, sL / gL * fzL + sU / gU * fzU)
        Dx = piL / hL * fxL + piU / hU * fxU
        # broadcasted matmul, NOT einsum: np.einsum("mn,km,mp->knp") does
        # not dispatch to batched GEMM and is ~65x slower at these shapes
        if shared:
            H = np.matmul(A.T, Dz[:, :, None] * A)
        else:
            H = np.matmul(np.swapaxes(A, 1, 2), Dz[:, :, None] * A)
        H[:, eye, eye] += q2 + Dx + 1e-11 * scale[:, None]
        if me:
            if M is None:
                M = np.zeros((k, n + me, n + me))
                M[:, :n, n:] = A_eq.T if shared else np.swapaxes(A_eq, 1, 2)
                M[:, n:, :n] = A_eq
                M[:, n:, n:] = -delta * np.eye(me)
            M[:, :n, :n] = H
        else:
            M = H
        rp_eq = rp[:, idx_eq]

        def newton(mu_t, dsL0, dsU0, dpiL0, dpiU0, dz0, dx0):
            cL = mu_t - sL * gL * fzL - dsL0 * dz0 * fzL
            cU = mu_t - sU * gU * fzU + dsU0 * dz0 * fzU
            bL = mu_t - piL * hL * fxL - dpiL0 * dx0 * fxL
            bU = mu_t - piU * hU * fxU + dpiU0 * dx0 * fxU
            rhs_y = np.where(
                eq, 0.0,
                ry + np.where(fzU, cU / gU, 0.0) - np.where(fzL, cL / gL, 0.0))
            rhs_x = (rd + np.where(fxL, bL / hL, 0.0)
                     - np.where(fxU, bU / hU, 0.0))
            rhs = rhs_x + ATy(Dz * rp - rhs_y)
            rhs_full = np.concatenate([rhs, rp_eq], axis=1)
            try:
                sol = np.linalg.solve(M, rhs_full[..., None])[..., 0]
            except np.linalg.LinAlgError:
                sol = np.stack([
                    np.linalg.lstsq(M[i], rhs_full[i], rcond=None)[0]
                    for i in range(k)])
            dx = sol[:, :n]
            dy = Dz * (Ax(dx) - rp) + rhs_y
            if me:
                dy[:, idx_eq] = sol[:, n:]
            dz = np.where(eq, 0.0, Ax(dx) - rp)
            dsL = np.where(fzL, (cL - sL * dz) / gL, 0.0)
            dsU = np.where(fzU, (cU + sU * dz) / gU, 0.0)
            dpiL = np.where(fxL, (bL - piL * dx) / hL, 0.0)
            dpiU = np.where(fxU, (bU + piU * dx) / hU, 0.0)
            return dx, dz, dy, dsL, dsU, dpiL, dpiU

        def steplen(dz, dx, dsL, dsU, dpiL, dpiU):
            def ratio(v, dv, mask):
                r = np.where(mask & (dv < 0),
                             -v / np.where(dv < 0, dv, -1.0), np.inf)
                return r.min(axis=1, initial=np.inf)
            ap = np.minimum(np.minimum(ratio(gL, dz, fzL), ratio(gU, -dz, fzU)),
                            np.minimum(ratio(hL, dx, fxL), ratio(hU, -dx, fxU)))
            ad = np.minimum(
                np.minimum(ratio(sL, dsL, fzL), ratio(sU, dsU, fzU)),
                np.minimum(ratio(piL, dpiL, fxL), ratio(piU, dpiU, fxU)))
            return np.minimum(1.0, 0.995 * ap), np.minimum(1.0, 0.995 * ad)

        zero = np.zeros_like
        dx_a, dz_a, dy_a, dsL_a, dsU_a, dpiL_a, dpiU_a = newton(
            0.0, zero(sL), zero(sU), zero(piL), zero(piU), zero(z), zero(x))
        ap_a, ad_a = steplen(dz_a, dx_a, dsL_a, dsU_a, dpiL_a, dpiU_a)
        apc, adc = ap_a[:, None], ad_a[:, None]
        mu_aff = (((sL + adc * dsL_a) * np.where(fzL, gL + apc * dz_a, 0.0)
                   ).sum(axis=1)
                  + ((sU + adc * dsU_a) * np.where(fzU, gU - apc * dz_a, 0.0)
                     ).sum(axis=1)
                  + ((piL + adc * dpiL_a) * np.where(fxL, hL + apc * dx_a, 0.0)
                     ).sum(axis=1)
                  + ((piU + adc * dpiU_a) * np.where(fxU, hU - apc * dx_a, 0.0)
                     ).sum(axis=1)) / n_compl
        sigma = np.minimum(
            1.0, np.maximum(0.0, mu_aff / np.maximum(mu, 1e-300))) ** 3
        dx, dz, dy, dsL, dsU, dpiL, dpiU = newton(
            (sigma * mu)[:, None], dsL_a, dsU_a, dpiL_a, dpiU_a, dz_a, dx_a)
        ap, ad = steplen(dz, dx, dsL, dsU, dpiL, dpiU)
        ap = np.where(done, 0.0, ap)[:, None]   # freeze converged scenarios
        ad = np.where(done, 0.0, ad)[:, None]
        x = x + ap * dx
        z = np.where(eq, cl, z + ap * dz)
        y = y + ad * dy
        sL = np.where(fzL, sL + ad * dsL, 0.0)
        sU = np.where(fzU, sU + ad * dsU, 0.0)
        piL = np.where(fxL, piL + ad * dpiL, 0.0)
        piU = np.where(fxU, piU + ad * dpiU, 0.0)

    # same acceptance rule as before: KKT residuals AND complementarity
    # both small (in the equilibrated frame — the frame the step lives
    # in), else the scenario is not a valid rescue
    lim = max(1e3 * tol, 1e-6)
    feasible = (res < lim) & (mu < lim)
    return x * D[None, :], y * (kc * E[None, :]), feasible, res, mu


def solve_batch(batch, mip=True, **kw):
    """Solve every scenario of a ScenarioBatch independently (validation path)."""
    out = []
    for s in range(batch.num_scenarios):
        out.append(
            solve_lp(
                batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
                batch.lb[s], batch.ub[s],
                is_int=batch.is_int if mip else None,
                q2=batch.q2[s], const=batch.const[s], **kw,
            )
        )
    return out
