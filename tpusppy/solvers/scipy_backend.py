"""HiGHS (scipy) validation backend.

The reference delegates every subproblem/EF solve to an external commercial
solver through Pyomo's SolverFactory (spopt.py:839-903).  tpusppy's primary
solver is the TPU-native batched ADMM (:mod:`tpusppy.solvers.admm`); this module
is the analogue of the external-solver path — a CPU LP/MILP solve via
scipy's vendored HiGHS — used for golden-value tests and as a fallback backend.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.optimize as sopt
import scipy.sparse as sp


@dataclasses.dataclass
class SolveResult:
    x: np.ndarray
    obj: float
    duals: np.ndarray | None
    status: str
    feasible: bool


def solve_lp(c, A, cl, cu, lb, ub, is_int=None, q2=None, const=0.0,
             mip_rel_gap=None, time_limit=None) -> SolveResult:
    """Solve one canonical-form problem with HiGHS.

    Quadratic objectives are not supported by scipy's HiGHS wrapper; callers
    with q2 != 0 must use the ADMM backend (this mirrors the reference, where
    solver capability gates algorithm choice, e.g. sc.py:18-21).
    """
    if q2 is not None and np.any(q2 != 0):
        raise NotImplementedError("HiGHS backend is LP/MILP only; use admm for QP")
    m, n = A.shape
    constraints = sopt.LinearConstraint(sp.csr_matrix(A), cl, cu) if m else ()
    integrality = None
    if is_int is not None and np.any(is_int):
        integrality = np.where(is_int, 1, 0)
    options = {}
    if mip_rel_gap is not None:
        options["mip_rel_gap"] = mip_rel_gap
    if time_limit is not None:
        options["time_limit"] = time_limit
    res = sopt.milp(
        c=c,
        constraints=constraints,
        integrality=integrality,
        bounds=sopt.Bounds(lb, ub),
        options=options,
    )
    # milp status: 0 optimal, 1 iteration/time limit (may carry an incumbent),
    # 2 infeasible, 3 unbounded, 4 other
    feasible = res.x is not None and res.status in (0, 1)
    x = res.x if res.x is not None else np.zeros(n)
    obj = float(c @ x + const) if res.x is not None else np.inf
    # scipy.milp does not expose duals; LP duals come from linprog when needed.
    return SolveResult(x=x, obj=obj, duals=None, status=str(res.status),
                       feasible=feasible)


def solve_lp_with_duals(c, A, cl, cu, lb, ub, const=0.0) -> SolveResult:
    """Continuous LP with row duals via linprog (for Benders/Lagrangian checks)."""
    # linprog wants A_ub x <= b_ub and A_eq x = b_eq; split rows.
    eq = np.isfinite(cl) & np.isfinite(cu) & (cl == cu)
    ub_rows = np.isfinite(cu) & ~eq
    lb_rows = np.isfinite(cl) & ~eq
    A_ub = np.vstack([A[ub_rows], -A[lb_rows]]) if (ub_rows.any() or lb_rows.any()) else None
    b_ub = np.concatenate([cu[ub_rows], -cl[lb_rows]]) if A_ub is not None else None
    A_eq = A[eq] if eq.any() else None
    b_eq = cl[eq] if eq.any() else None
    res = sopt.linprog(c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq,
                       bounds=np.stack([lb, ub], axis=1), method="highs")
    duals = None
    if res.status == 0:
        duals = np.zeros(A.shape[0])
        if A_eq is not None:
            duals[np.flatnonzero(eq)] = res.eqlin.marginals
        k = 0
        for rows, sign in ((ub_rows, 1.0), (lb_rows, -1.0)):
            cnt = int(rows.sum())
            if cnt:
                duals[np.flatnonzero(rows)] += sign * res.ineqlin.marginals[k:k + cnt]
                k += cnt
    x = res.x if res.x is not None else np.zeros(A.shape[1])
    return SolveResult(x=x, obj=float(res.fun + const) if res.status == 0 else np.inf,
                       duals=duals, status=str(res.status), feasible=res.status == 0)


def solve_batch(batch, mip=True, **kw):
    """Solve every scenario of a ScenarioBatch independently (validation path)."""
    out = []
    for s in range(batch.num_scenarios):
        out.append(
            solve_lp(
                batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
                batch.lb[s], batch.ub[s],
                is_int=batch.is_int if mip else None,
                q2=batch.q2[s], const=batch.const[s], **kw,
            )
        )
    return out
