"""HiGHS (scipy) validation backend.

The reference delegates every subproblem/EF solve to an external commercial
solver through Pyomo's SolverFactory (spopt.py:839-903).  tpusppy's primary
solver is the TPU-native batched ADMM (:mod:`tpusppy.solvers.admm`); this module
is the analogue of the external-solver path — a CPU LP/MILP solve via
scipy's vendored HiGHS — used for golden-value tests and as a fallback backend.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.optimize as sopt
import scipy.sparse as sp


@dataclasses.dataclass
class SolveResult:
    x: np.ndarray
    obj: float
    duals: np.ndarray | None
    status: str
    feasible: bool


def solve_lp(c, A, cl, cu, lb, ub, is_int=None, q2=None, const=0.0,
             mip_rel_gap=None, time_limit=None) -> SolveResult:
    """Solve one canonical-form problem with HiGHS.

    Quadratic objectives are not supported by scipy's HiGHS wrapper; callers
    with q2 != 0 must use the ADMM backend (this mirrors the reference, where
    solver capability gates algorithm choice, e.g. sc.py:18-21).
    """
    if q2 is not None and np.any(q2 != 0):
        raise NotImplementedError("HiGHS backend is LP/MILP only; use admm for QP")
    m, n = A.shape
    constraints = sopt.LinearConstraint(sp.csr_matrix(A), cl, cu) if m else ()
    integrality = None
    if is_int is not None and np.any(is_int):
        integrality = np.where(is_int, 1, 0)
    options = {}
    if mip_rel_gap is not None:
        options["mip_rel_gap"] = mip_rel_gap
    if time_limit is not None:
        options["time_limit"] = time_limit
    res = sopt.milp(
        c=c,
        constraints=constraints,
        integrality=integrality,
        bounds=sopt.Bounds(lb, ub),
        options=options,
    )
    # milp status: 0 optimal, 1 iteration/time limit (may carry an incumbent),
    # 2 infeasible, 3 unbounded, 4 other
    feasible = res.x is not None and res.status in (0, 1)
    x = res.x if res.x is not None else np.zeros(n)
    obj = float(c @ x + const) if res.x is not None else np.inf
    # scipy.milp does not expose duals; LP duals come from linprog when needed.
    return SolveResult(x=x, obj=obj, duals=None, status=str(res.status),
                       feasible=feasible)


def solve_lp_with_duals(c, A, cl, cu, lb, ub, const=0.0) -> SolveResult:
    """Continuous LP with row duals via linprog (for Benders/Lagrangian checks)."""
    # linprog wants A_ub x <= b_ub and A_eq x = b_eq; split rows.
    eq = np.isfinite(cl) & np.isfinite(cu) & (cl == cu)
    ub_rows = np.isfinite(cu) & ~eq
    lb_rows = np.isfinite(cl) & ~eq
    A_ub = np.vstack([A[ub_rows], -A[lb_rows]]) if (ub_rows.any() or lb_rows.any()) else None
    b_ub = np.concatenate([cu[ub_rows], -cl[lb_rows]]) if A_ub is not None else None
    A_eq = A[eq] if eq.any() else None
    b_eq = cl[eq] if eq.any() else None
    res = sopt.linprog(c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq,
                       bounds=np.stack([lb, ub], axis=1), method="highs")
    duals = None
    if res.status == 0:
        duals = np.zeros(A.shape[0])
        if A_eq is not None:
            duals[np.flatnonzero(eq)] = res.eqlin.marginals
        k = 0
        for rows, sign in ((ub_rows, 1.0), (lb_rows, -1.0)):
            cnt = int(rows.sum())
            if cnt:
                duals[np.flatnonzero(rows)] += sign * res.ineqlin.marginals[k:k + cnt]
                k += cnt
    x = res.x if res.x is not None else np.zeros(A.shape[1])
    return SolveResult(x=x, obj=float(res.fun + const) if res.status == 0 else np.inf,
                       duals=duals, status=str(res.status), feasible=res.status == 0)


def solve_qp_with_duals(c, q2, A, cl, cu, lb, ub, const=0.0,
                        tol=1e-9, max_iter=60) -> SolveResult:
    """Host-exact diagonal-Hessian QP with row duals: the QP sibling of
    :func:`solve_lp_with_duals` for the straggler rescue (scipy's HiGHS
    wrapper is LP/MILP only, so this is a self-contained dense Mehrotra
    predictor-corrector IPM in numpy).

        min c.x + 0.5 x'diag(q2)x   s.t. cl <= Ax <= cu, lb <= x <= ub

    Returns x and row duals y in the framework's convention (y > 0 active
    at cu, y < 0 at cl — the convention :func:`tpusppy.solvers.admm.
    dual_objective` certifies bounds with).  Sizes here are one scenario
    (n, m in the hundreds-to-thousands): a dense (n, n) Cholesky per
    iteration is microseconds-to-milliseconds, and the rescue calls this
    for a handful of scenarios once per refresh.  Reference analogue:
    subproblem solves are always solver-exact (mpisppy/spopt.py:85-223).
    """
    c = np.asarray(c, float)
    q2 = np.asarray(q2, float)
    A = np.asarray(A, float)
    m, n = A.shape
    big = 1e18
    cl = np.where(np.isfinite(cl), np.asarray(cl, float), -big)
    cu = np.where(np.isfinite(cu), np.asarray(cu, float), big)
    lb = np.where(np.isfinite(lb), np.asarray(lb, float), -big)
    ub = np.where(np.isfinite(ub), np.asarray(ub, float), big)
    eq = cu - cl < 1e-9
    fzL = (cl > -big / 2) & ~eq
    fzU = (cu < big / 2) & ~eq
    fxL = lb > -big / 2
    fxU = ub < big / 2

    scale = max(1.0, np.abs(c).max(initial=0.0), np.abs(q2).max(initial=0.0))

    def interior(v, lo, hi, finL, finU):
        mid = np.where(finL & finU, 0.5 * (lo + hi), v)
        v = np.where(finL & finU, mid, v)
        v = np.where(finL & ~finU, np.maximum(v, lo + 1.0), v)
        v = np.where(~finL & finU, np.minimum(v, hi - 1.0), v)
        return v

    x = interior(np.zeros(n), lb, ub, fxL, fxU)
    z = interior(A @ x, cl, cu, fzL, fzU)
    z = np.where(eq, cl, z)
    y = np.zeros(m)
    sL = np.where(fzL, 1.0, 0.0)
    sU = np.where(fzU, 1.0, 0.0)
    piL = np.where(fxL, 1.0, 0.0)
    piU = np.where(fxU, 1.0, 0.0)
    delta_eq = 1e9              # fixed equality-row dual regularization

    def gaps():
        gL = np.where(fzL, np.maximum(z - cl, 1e-14), 1.0)
        gU = np.where(fzU, np.maximum(cu - z, 1e-14), 1.0)
        hL = np.where(fxL, np.maximum(x - lb, 1e-14), 1.0)
        hU = np.where(fxU, np.maximum(ub - x, 1e-14), 1.0)
        return gL, gU, hL, hU

    n_compl = int(fzL.sum() + fzU.sum() + fxL.sum() + fxU.sum())
    res = mu = np.inf
    for _ in range(max_iter):
        gL, gU, hL, hU = gaps()
        rd = -(c + q2 * x + A.T @ y - piL + piU)
        rp = -(A @ x - z)
        ry = -(y - sU + sL)
        mu = ((sL @ np.where(fzL, gL, 0.0) + sU @ np.where(fzU, gU, 0.0)
               + piL @ np.where(fxL, hL, 0.0)
               + piU @ np.where(fxU, hU, 0.0)) / max(n_compl, 1))
        res = max(np.abs(rd).max(initial=0.0) / scale,
                  np.abs(rp).max(initial=0.0),
                  np.abs(np.where(eq, 0.0, ry)).max(initial=0.0))
        if res < tol and mu < tol:
            break

        Dz = np.where(eq, delta_eq, sL / gL * fzL + sU / gU * fzU)
        Dx = piL / hL * fxL + piU / hU * fxU
        H = (A.T * Dz) @ A
        H[np.diag_indices(n)] += q2 + Dx + 1e-11 * scale

        def newton(mu_t, dsL0, dsU0, dpiL0, dpiU0, dz0, dx0):
            # complementarity rhs with optional Mehrotra second-order terms
            cL = mu_t - sL * gL * fzL - dsL0 * dz0 * fzL
            cU = mu_t - sU * gU * fzU + dsU0 * dz0 * fzU
            bL = mu_t - piL * hL * fxL - dpiL0 * dx0 * fxL
            bU = mu_t - piU * hU * fxU + dpiU0 * dx0 * fxU
            rhs_y = np.where(
                eq, 0.0,
                ry + np.where(fzU, cU / gU, 0.0) - np.where(fzL, cL / gL, 0.0))
            rhs_x = rd + np.where(fxL, bL / hL, 0.0) - np.where(fxU, bU / hU, 0.0)
            rhs = rhs_x + A.T @ (Dz * rp - rhs_y)
            try:
                L = np.linalg.cholesky(H)
                dx = np.linalg.solve(L.T, np.linalg.solve(L, rhs))
            except np.linalg.LinAlgError:
                dx = np.linalg.lstsq(H, rhs, rcond=None)[0]
            dy = Dz * (A @ dx - rp) + rhs_y
            dz = np.where(eq, 0.0, A @ dx - rp)
            dsL = np.where(fzL, (cL - sL * dz) / gL, 0.0)
            dsU = np.where(fzU, (cU + sU * dz) / gU, 0.0)
            dpiL = np.where(fxL, (bL - piL * dx) / hL, 0.0)
            dpiU = np.where(fxU, (bU + piU * dx) / hU, 0.0)
            return dx, dz, dy, dsL, dsU, dpiL, dpiU

        def steplen(dz, dx, dsL, dsU, dpiL, dpiU):
            def ratio(v, dv, mask):
                r = np.where(mask & (dv < 0), -v / np.where(dv < 0, dv, -1.0),
                             np.inf)
                return r.min(initial=np.inf)
            ap = min(ratio(gL, dz, fzL), ratio(gU, -dz, fzU),
                     ratio(hL, dx, fxL), ratio(hU, -dx, fxU))
            ad = min(ratio(sL, dsL, fzL), ratio(sU, dsU, fzU),
                     ratio(piL, dpiL, fxL), ratio(piU, dpiU, fxU))
            return min(1.0, 0.995 * ap), min(1.0, 0.995 * ad)

        dx_a, dz_a, dy_a, dsL_a, dsU_a, dpiL_a, dpiU_a = newton(
            0.0, 0.0 * sL, 0.0 * sU, 0.0 * piL, 0.0 * piU, 0.0 * z, 0.0 * x)
        ap_a, ad_a = steplen(dz_a, dx_a, dsL_a, dsU_a, dpiL_a, dpiU_a)
        mu_aff = (((sL + ad_a * dsL_a) @ np.where(fzL, gL + ap_a * dz_a, 0.0))
                  + ((sU + ad_a * dsU_a) @ np.where(fzU, gU - ap_a * dz_a, 0.0))
                  + ((piL + ad_a * dpiL_a) @ np.where(fxL, hL + ap_a * dx_a, 0.0))
                  + ((piU + ad_a * dpiU_a) @ np.where(fxU, hU - ap_a * dx_a, 0.0))
                  ) / max(n_compl, 1)
        sigma = min(1.0, max(0.0, (mu_aff / max(mu, 1e-300)))) ** 3
        dx, dz, dy, dsL, dsU, dpiL, dpiU = newton(
            sigma * mu, dsL_a, dsU_a, dpiL_a, dpiU_a, dz_a, dx_a)
        ap, ad = steplen(dz, dx, dsL, dsU, dpiL, dpiU)
        x = x + ap * dx
        z = np.where(eq, cl, z + ap * dz)
        y = y + ad * dy
        sL = np.where(fzL, sL + ad * dsL, 0.0)
        sU = np.where(fzU, sU + ad * dsU, 0.0)
        piL = np.where(fxL, piL + ad * dpiL, 0.0)
        piU = np.where(fxU, piU + ad * dpiU, 0.0)

    # optimal means KKT residuals AND complementarity both small — a
    # max_iter exit with small residuals but mu ~ 1e-3 is NOT a valid
    # rescue (x/y would be installed as exact while O(mu) off-optimal)
    feasible = bool(res < max(1e3 * tol, 1e-6)
                    and mu < max(1e3 * tol, 1e-6))
    obj = float(c @ x + 0.5 * (q2 @ (x * x)) + const)
    return SolveResult(x=x, obj=obj if feasible else np.inf,
                       duals=y, status=f"ipm_res={res:.2e}_mu={mu:.2e}",
                       feasible=feasible)


def solve_batch(batch, mip=True, **kw):
    """Solve every scenario of a ScenarioBatch independently (validation path)."""
    out = []
    for s in range(batch.num_scenarios):
        out.append(
            solve_lp(
                batch.c[s], batch.A[s], batch.cl[s], batch.cu[s],
                batch.lb[s], batch.ub[s],
                is_int=batch.is_int if mip else None,
                q2=batch.q2[s], const=batch.const[s], **kw,
            )
        )
    return out
