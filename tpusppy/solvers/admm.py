"""Batched OSQP-style ADMM QP/LP solver in JAX — the TPU-native subproblem engine.

This replaces the reference's external-MIP-solver hot loop (``solve_one`` /
``solve_loop``, spopt.py:85-307, and the persistent-solver objective refresh at
spopt.py:129-144): the entire local scenario batch is solved by ONE device
program — batched dense Cholesky factorizations ride the MXU, the ADMM sweep is a
``lax.while_loop``, and PH's per-iteration objective update is just new (q, rho)
tensors plus a warm start.

Canonical form per scenario (see :mod:`tpusppy.ir`):

    minimize    0.5 x' diag(q2) x + c' x
    subject to  cl <= A x <= cu,   lb <= x <= ub

Splitting (OSQP, Stellato et al.): introduce z_a = A x and z_x = x; the
variable-bound block is an implicit identity that never gets materialized — it
contributes only diagonal terms to the KKT system:

    (diag(q2) + sigma I + A' R_a A + R_x) x~ =
        sigma x - q + A'(R_a z_a - y_a) + (R_x z_x - y_x)

with per-row penalties R (equality rows boosted, free rows damped).  Ruiz
equilibration preconditions the batch; adaptive-rho restarts refactorize (cheap
for the dense sizes scenarios have).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import aot as _aot

BIG = 1e20  # stand-in for +inf inside kernels (keeps arithmetic finite)


@dataclasses.dataclass(frozen=True)
class ADMMSettings:
    sigma: float = 1e-6
    alpha: float = 1.6
    rho: float = 0.1
    rho_eq_scale: float = 1e3
    rho_min: float = 1e-6
    rho_max: float = 1e6
    max_iter: int = 1000          # inner iterations per rho setting
    restarts: int = 4             # rho-adaptation refactorizations
    check_every: int = 4          # sweeps per termination check (unrolled)
    solve_refine: int = 2         # refinement passes per x-update solve
    eps_abs: float = 1e-8
    eps_rel: float = 1e-8
    scaling_iters: int = 10
    polish: bool = True           # active-set KKT polish (OSQP-style)
    polish_passes: int = 4        # active-set correction passes
    polish_delta: float = 1e-8
    # Fused Pallas sweep kernel (scenario-on-lanes layout).  "auto"
    # (default) enables it in its MEASURED win regime on TPU — dense
    # batches whose block partition is fine-grained (n big enough that a
    # block is <=512 scenarios: 2.0x at S=1000 n=44, 6.5x at S=10000
    # n=44) or single-block (1.14x at S=1000 n=11) — and stays off where
    # it measured slower (many coarse blocks: 0.68x at S=10000 n=11).
    # True forces it wherever usable; False disables.
    use_pallas: bool | str = "auto"
    # Per-ROW rho adaptation between restarts: rows (and variable boxes) with
    # persistent primal violation get their penalty boosted.  Cures ADMM
    # stalls on strongly-coupled LPs (UC's ramp/genlim rows) that global rho
    # adaptation cannot fix — the global ratio is balanced while a handful of
    # rows are far from feasible.
    rho_row_adapt: bool = True
    rho_row_boost: float = 10.0
    rho_row_max: float = 1e6
    dtype: str = "float64"
    # Carry the exact K inside SharedFactors for dense refinement ("True",
    # fastest sweeps) or drop it and refine matrix-free through the shared A
    # ("False", ~1 GB less HBM per factors at reference UC shapes — the host
    # wheel path defaults this off via SPBase since several cylinders'
    # factors coexist on one chip).
    factors_keep_K: bool = True
    # Segmented continuations stop when one whole extra segment improves
    # the worst scaled residual by less than this fraction (plateau):
    # first-order batches on hard LP families park at a residual floor
    # regardless of budget, and further dispatches are pure waste.  0
    # disables (always run the full sweep budget).
    segment_plateau_rtol: float = 0.05
    # Matmul precision for the solve programs.  "highest" = full f32
    # (bf16x6 passes on TPU MXU — ~6x the flops of plain bf16); "high" =
    # bf16x3; "default" = bf16.  Lower precisions trade residual floor for
    # sweep throughput; certified-bound programs (dual_objective/dual_cut)
    # always run "highest" regardless.
    matmul_precision: str = "highest"
    # Mixed-precision FROZEN sweep engine (solvers/precision.py; see
    # doc/precision.md).  None (the default) leaves every path exactly as
    # before; "default" (bf16) or "high" (bf16x3) runs the frozen sweep
    # phase at lowered MXU precision — with the x-update defect and ALL
    # residual bookkeeping pinned to full f32, so the OSQP termination
    # test stays trustworthy — then, if not eps-converged, a bounded
    # full-precision refinement phase (``precision_refine_iters`` sweeps
    # on the SAME cached factors) restores the f32 residual floor.
    # Refresh/adaptive solves and certified-bound programs are never
    # lowered.  The autotuner (tpusppy.tune) picks this per shape: the
    # fastest mode whose warmup residuals certify.
    sweep_precision: str | None = None
    # f32 refinement sweep budget appended to a low-precision frozen sweep
    # phase that did not reach eps (skipped entirely when it did — the
    # f32-measured residuals already certify the iterate).
    precision_refine_iters: int = 64
    # Host-side fallback guard (spopt._solve_amortized): a low-precision
    # frozen solve whose worst residual exceeds ``precision_guard`` x the
    # last full-precision refresh floor (and is not converged) is re-run
    # at full precision on the same factors.  <= 0 disables.
    precision_guard: float = 10.0
    # In-loop plateau exit: leave the sweep while_loop when the batch-worst
    # eps-normalized residual improved by less than this fraction over each
    # of 2 consecutive windows of ``sweep_plateau_window`` sweeps.  Hard LP
    # families (reference-scale UC) park at a residual floor far above eps,
    # and every further sweep is waste — the segment-level host detector
    # (``segmented.continue_frozen``) catches the same condition only at
    # whole-dispatch granularity and burns 2 extra dispatches proving it.
    # 0 disables.  ``BatchSolution.done`` reports true eps-convergence, so
    # a plateau exit is never mistaken for convergence by callers.
    sweep_plateau_rtol: float = 0.0
    sweep_plateau_window: int = 32
    # Overlapped dispatch pipeline (doc/pipeline.md): segmented frozen
    # continuations speculatively launch segment k+1 from segment k's
    # device-resident iterate BEFORE fetching segment k's stop-stats, so
    # the per-segment host RPC overlaps device compute.  Results are
    # identical to the serial protocol (speculative segments are
    # discarded when the verdict says stop; waste is bounded at one
    # segment and billed against the sweep budget).  False forces the
    # legacy serial fetch-then-dispatch protocol everywhere (the
    # ``admm_pipeline`` config flag).  Host-dispatch-only: the traced
    # programs are unchanged.
    pipeline: bool = True
    # Device-resident wheel megakernel (doc/pipeline.md): the PH hub runs
    # N wheel iterations (frozen solve + xbar/W outer update) in ONE
    # donated lax.scan dispatch and fetches ONE packed measurement per
    # megastep instead of one per iteration.  0 = auto (the hub picks N
    # from the autotuner's banked verdict when one exists, else from the
    # refresh cadence clamped by the watchdog cap —
    # ``segmented.megastep_cap``); 1 forces the legacy per-iteration
    # dispatch everywhere (the ``admm_megastep`` config flag); k > 1
    # requests that N (still watchdog-clamped).  Host-dispatch-only for
    # the legacy toggle: the per-iteration traced programs are unchanged.
    megastep: int = 0

    def jdtype(self):
        return jnp.dtype(self.dtype)

    def sweep_mode(self) -> str:
        """Effective frozen-sweep matmul precision (for MFU/report use)."""
        return self.sweep_precision or self.matmul_precision


class BatchSolution(NamedTuple):
    x: jax.Array       # (S, n)
    z: jax.Array       # (S, m) constraint-row auxiliaries
    y: jax.Array       # (S, m) constraint-row duals
    yx: jax.Array      # (S, n) variable-bound duals
    pri_res: jax.Array  # (S,)
    dua_res: jax.Array  # (S,)
    iters: jax.Array   # (S,) total inner iterations used (same for all)
    done: jax.Array    # (S,) met the eps tolerances (False = budget spent or
    # plateau exit) — callers must use this, never an iters-vs-cap compare,
    # to decide convergence (the plateau exit leaves the loop early)
    raw: tuple         # pre-polish (x, z, y, yx) — the ONLY valid warm start
    # (polished states are exact-KKT candidates, not consistent ADMM
    # iterates; feeding them back as warm starts destabilizes later solves)


class _Scaling(NamedTuple):
    D: jax.Array       # (S, n) column scaling
    E: jax.Array       # (S, m) row scaling
    cost: jax.Array    # (S,) objective scaling


class Factors(NamedTuple):
    """Reusable solve state for the frozen-factor path.

    PH changes only the linear term between iterations (spopt.py:129-144 is
    the reference's persistent-solver analogue); the Ruiz scaling, the adapted
    rho vectors, and the KKT factorization all depend only on (A, q2, bounds)
    — so they can be computed once at a "refresh" solve and reused for many
    cheap sweep-only solves.  On TPU this removes the batched factorization
    (the dominant per-iteration cost) from the steady-state PH iteration.
    """

    D: jax.Array       # (S, n) Ruiz column scaling
    E: jax.Array       # (S, m) Ruiz row scaling
    cost: jax.Array    # (S,) objective scaling
    rho_a: jax.Array   # (S, m) row penalties actually used last
    rho_x: jax.Array   # (S, n) variable-box penalties actually used last
    Kinv: jax.Array    # (S, n, n) explicit inverse of the x-update system
    K: jax.Array       # (S, n, n) exact K for iterative refinement


class _BoundMasks(NamedTuple):
    """Finiteness/equality classification of the UNSCALED bounds."""

    fin_cl: jax.Array  # (S, m) lower row bound finite
    fin_cu: jax.Array  # (S, m) upper row bound finite
    fin_lb: jax.Array  # (S, n) lower var bound finite
    fin_ub: jax.Array  # (S, n) upper var bound finite
    eq: jax.Array      # (S, m) equality row
    eqx: jax.Array     # (S, n) zero-width variable box (clamped column)


def _clean_bounds(lo, hi):
    lo = jnp.nan_to_num(lo, nan=-BIG, neginf=-BIG, posinf=BIG)
    hi = jnp.nan_to_num(hi, nan=BIG, neginf=-BIG, posinf=BIG)
    return jnp.maximum(lo, -BIG), jnp.minimum(hi, BIG)


def _ruiz(A, q2, iters):
    """Ruiz equilibration of [P A'; A 0] restricted to diagonal scalings.

    Returns (D, E) with the scaled matrix E A D having ~unit inf-norm rows/cols.
    Batched over the leading axis by construction (all ops are elementwise or
    row/col reductions).
    """
    S, m, n = A.shape
    D = jnp.ones((S, n), A.dtype)
    E = jnp.ones((S, m), A.dtype)

    def body(_, DE):
        D, E = DE
        As = A * E[:, :, None] * D[:, None, :]
        Ps = q2 * D * D
        col = jnp.maximum(jnp.max(jnp.abs(As), axis=1), jnp.abs(Ps))
        row = jnp.max(jnp.abs(As), axis=2)
        # empty rows/columns (e.g. cut slots not yet populated, objective-only
        # variables) must keep unit scaling: dividing by sqrt(eps) each sweep
        # compounds into astronomically wrong D/E otherwise
        col = jnp.where(col < 1e-12, 1.0, col)
        row = jnp.where(row < 1e-12, 1.0, row)
        D = D / jnp.sqrt(col)
        E = E / jnp.sqrt(row)
        return D, E

    D, E = jax.lax.fori_loop(0, iters, body, (D, E))
    return D, E


def _factor(q2, A, rho_a, rho_x, sigma, P=None):
    """Cholesky of K = P + diag(q2) + sigma I + A' diag(rho_a) A + diag(rho_x).

    ``P`` is an optional dense (S, n, n) quadratic term (FWPH's simplex QP and
    other column-space problems need one); the diagonal-only path stays the
    default.  Returns (L, K); K is kept for iterative refinement of the
    triangular solves — essential in float32, where cond(K) ~ 1/sigma *
    rho_eq_scale otherwise stalls ADMM around 1e-2 residuals.
    """
    n = A.shape[-1]
    K = jnp.einsum("smn,sm,smk->snk", A, rho_a, A)
    K = K + jnp.eye(n, dtype=A.dtype)[None] * sigma
    K = K + jax.vmap(jnp.diag)(q2 + rho_x)
    if P is not None:
        K = K + P
    # Explicit inverse via Cholesky: triangular substitution is SEQUENTIAL on
    # TPU (length-n dependency chain per solve), so the hot loop applies K^-1
    # as one MXU matmul per solve instead.  Iterative refinement against the
    # exact K (kept alongside) recovers the digits the explicit inverse
    # loses — cheaper than two triangular sweeps per inner iteration.
    return _explicit_inverse(K), K


# Matrices larger than 2 * this go through the recursive Schur inversion,
# avoiding XLA:TPU's TriangularSolve lowering at big n: one
# (16008, 16008) \ (16008, 2048) solve compiles to 9.2 GB of HLO temps
# (chunked substitution keeps ~n/128 O(n*rhs) accumulator copies live),
# which OOMed the headline UC refresh program at 62 GB demand on a 16 GB
# chip.  The recursion is pure MXU matmuls — measured at n=16008: 1.2 GB
# temps, 1.6 s steady-state (8x faster than the triangular path),
# comparable f32 accuracy (iterative refinement against the exact K in
# _chol_solve covers the rest).  Base cases — up to 2x the leaf size, i.e.
# n <= 4096 — still use Cholesky + triangular solves, where the lowering
# is cheap.
_EXPLICIT_INV_LEAF_N = 2048


def _explicit_inverse(K):
    """K^-1 of an SPD batch via recursive blocked Schur inversion.

    inv([[A, B], [B', C]]) = [[Ai + W Si W', -W Si], [-Si W', Si]] with
    Ai = inv(A), W = Ai B, Si = inv(C - B' Ai B); Schur complements of SPD
    are SPD, so the recursion is well posed.  Base cases (n <= 2 * leaf =
    4096) use Cholesky + triangular solves against I, where XLA's lowering
    is cheap.  Split points are multiples of the leaf size for tidy MXU
    tiling.
    """
    n = K.shape[-1]
    leaf = _EXPLICIT_INV_LEAF_N
    if n <= 2 * leaf:
        # XLA:TPU's blocked TriangularSolve lowering has a broken window
        # when the diagonal block IS the (sub-128) matrix: 64 < n < 128
        # allocates a fixed 18.95 MB of scoped VMEM (> the 16 MB limit)
        # in InvertDiagBlocksLowerTriangular regardless of batch size —
        # observed at n=88 for batches 139/190/1000 alike, while n=44
        # (unblocked path) and n>=128 (128-wide diag blocks) compile fine.
        # Embed K into a 128x128 identity-extended SPD and slice back.
        # TPU-only (trace-time check): other backends' lowerings are fine
        # and would just pay ~3x the flops for the padding.
        if 64 < n < 128 and jax.default_backend() == "tpu":
            pad = 128 - n
            eye_pad = jnp.eye(128, dtype=K.dtype)[n:, :]
            Kp = jnp.concatenate([
                jnp.concatenate(
                    [K, jnp.zeros(K.shape[:-1] + (pad,), K.dtype)], axis=-1),
                jnp.broadcast_to(eye_pad, K.shape[:-2] + (pad, 128)),
            ], axis=-2)
            return _explicit_inverse_oneshot(Kp)[..., :n, :n]
        return _explicit_inverse_oneshot(K)
    return _explicit_inverse_schur(K)


def _explicit_inverse_oneshot(K):
    """Cholesky + two triangular solves against I (small/medium n)."""
    n = K.shape[-1]
    L = jnp.linalg.cholesky(K)
    eye = jnp.broadcast_to(jnp.eye(n, dtype=K.dtype), K.shape)
    t = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return jax.scipy.linalg.solve_triangular(L, t, lower=True, trans=1)


def _explicit_inverse_schur(K):
    n = K.shape[-1]
    leaf = _EXPLICIT_INV_LEAF_N
    h = ((n // 2 + leaf - 1) // leaf) * leaf
    A = K[..., :h, :h]
    B = K[..., :h, h:]
    C = K[..., h:, h:]
    Ai = _explicit_inverse(A)
    AiB = Ai @ B
    Si = _explicit_inverse(C - jnp.swapaxes(B, -1, -2) @ AiB)
    TR = -(AiB @ Si)
    TL = Ai - TR @ jnp.swapaxes(AiB, -1, -2)
    top = jnp.concatenate([TL, TR], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(TR, -1, -2), Si], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def _chol_solve(LK, b, refine=2, prec=None):
    """K^-1 b via the explicit inverse + refinement against the exact K.

    ``prec``: None = legacy path (ambient matmul precision, unchanged
    programs).  A mode string runs the Kinv applies at that precision
    while the DEFECT ``b - K x`` stays pinned at full f32 — the classic
    mixed-precision iterative-refinement split (defect at high precision,
    correction at low)."""
    Kinv, K = LK
    if prec is None:
        x = jnp.einsum("snk,sk->sn", Kinv, b)
        for _ in range(refine):
            r = b - jnp.einsum("snk,sk->sn", K, x)
            x = x + jnp.einsum("snk,sk->sn", Kinv, r)
        return x
    from . import precision
    x = precision.contract("snk,sk->sn", Kinv, b, prec)
    for _ in range(refine):
        r = b - precision.contract("snk,sk->sn", K, x, "highest")
        x = x + precision.contract("snk,sk->sn", Kinv, r, prec)
    return x


class _IterState(NamedTuple):
    x: jax.Array
    z: jax.Array   # (S, m)
    zx: jax.Array  # (S, n)
    y: jax.Array
    yx: jax.Array
    pri: jax.Array
    dua: jax.Array
    prinorm: jax.Array
    duanorm: jax.Array
    k: jax.Array
    best: jax.Array   # scalar: best batch-worst eps-normalized residual
    stall: jax.Array  # scalar int32: consecutive non-improving windows


def _done_mask(pri, dua, prinorm, duanorm, st: ADMMSettings):
    """Per-scenario eps-convergence (the while_loop's own OSQP test)."""
    eps_pri = st.eps_abs + st.eps_rel * jnp.maximum(prinorm, 1.0)
    eps_dua = st.eps_abs + st.eps_rel * jnp.maximum(duanorm, 1.0)
    return (pri < eps_pri) & (dua < eps_dua)


def _plateau_update(s, pri, dua, prinorm, duanorm, st: ADMMSettings,
                    min_k=0):
    """(best, stall) update at a residual checkpoint; evaluated every
    ``sweep_plateau_window`` sweeps.

    The progress metric is the GEOMETRIC MEAN of per-scenario
    eps-normalized residual excesses (clipped to [1, 1e6]): converged
    scenarios contribute a neutral 1 (so scenarios crossing eps register
    as progress), a NaN/diverged scenario contributes the constant cap
    instead of poisoning the whole batch, and — unlike a batch-max — one
    parked scenario cannot stall the detector while the rest are still
    descending (stopping is all-or-nothing for the batched loop, so the
    exit must wait for COLLECTIVE stagnation; the host rescue ladder owns
    the per-scenario stragglers afterwards).

    ``min_k``: stall counting starts only at checkpoints past this sweep
    index — the shared engine's ADAPTIVE solve passes its in-loop gamma
    cadence so the exit cannot preempt the first adaptation opportunity
    (a batch that stalls precisely until gamma moves would otherwise be
    abandoned at 3 windows); its frozen solves, whose gamma is already
    adapted, pass 0 and keep the earliest exit."""
    eps_pri = st.eps_abs + st.eps_rel * jnp.maximum(prinorm, 1.0)
    eps_dua = st.eps_abs + st.eps_rel * jnp.maximum(duanorm, 1.0)
    excess = jnp.maximum(pri / eps_pri, dua / eps_dua)
    excess = jnp.clip(jnp.nan_to_num(excess, nan=1e6, posinf=1e6), 1.0, 1e6)
    gmean = jnp.exp(jnp.mean(jnp.log(excess)))
    ck = max(1, st.check_every)
    # ceil-divide: a window below (or not a multiple of) check_every must
    # round UP to the next checkpoint, not silently shrink the effective
    # window and fire the exit earlier than configured
    period = max(1, -(-st.sweep_plateau_window // ck))
    due = (((s.k // ck) + 1) % period == 0) & (s.k >= min_k)
    # near-eps grace: once the batch gmean sits within rtol of eps the
    # >=1 floor makes fractional improvement unmeasurable, so a batch 2
    # windows from crossing eps would be force-exited — treat that zone
    # as improving and let it finish (a batch PARKED there runs out its
    # budget instead, which is bounded and effectively converged anyway)
    improved = (gmean < (1.0 - st.sweep_plateau_rtol) * s.best) | (
        gmean <= 1.0 + st.sweep_plateau_rtol)
    stall = jnp.where(due, jnp.where(improved, 0, s.stall + 1), s.stall)
    best = jnp.where(due, jnp.minimum(s.best, gmean), s.best)
    return best, stall


def _admm_core(q, q2, A, cl, cu, lb, ub, state, LK, rho_a, rho_x,
               st: ADMMSettings, P=None, prec=None):
    """Inner ADMM sweep at fixed rho. Returns final state.

    ``prec``: None keeps the legacy (ambient-precision) program
    byte-for-byte; a mode string runs the SWEEP matvecs at that precision
    (solvers/precision.py) while residual bookkeeping and the
    checkpoint Ax re-anchor stay pinned at full f32 — so the while_loop's
    OSQP test measures true residuals whatever the sweep mode."""
    sigma, alpha = st.sigma, st.alpha

    if prec is None:
        lo = hi = lambda spec, a, b: jnp.einsum(spec, a, b)
    else:
        from . import precision
        lo = lambda spec, a, b: precision.contract(spec, a, b, prec)
        hi = lambda spec, a, b: precision.contract(spec, a, b, "highest")

    def Px(x):
        base = q2 * x
        if P is not None:
            base = base + hi("snk,sk->sn", P, x)
        return base

    def sweep(x, z, zx, y, yx, Ax):
        """One ADMM sweep WITHOUT residual bookkeeping.  Ax is carried
        incrementally (Ax_new = alpha*Axt + (1-alpha)*Ax), saving one matvec
        per sweep."""
        rhs = (
            sigma * x - q
            + lo("smn,sm->sn", A, rho_a * z - y)
            + (rho_x * zx - yx)
        )
        xt = _chol_solve(LK, rhs, refine=st.solve_refine, prec=prec)
        Axt = lo("smn,sn->sm", A, xt)
        x_new = alpha * xt + (1 - alpha) * x
        Ax_new = alpha * Axt + (1 - alpha) * Ax

        za_arg = alpha * Axt + (1 - alpha) * z + y / rho_a
        z_new = jnp.clip(za_arg, cl, cu)
        y_new = y + rho_a * (alpha * Axt + (1 - alpha) * z - z_new)

        zx_arg = alpha * xt + (1 - alpha) * zx + yx / rho_x
        zx_new = jnp.clip(zx_arg, lb, ub)
        yx_new = yx + rho_x * (alpha * xt + (1 - alpha) * zx - zx_new)
        return x_new, z_new, zx_new, y_new, yx_new, Ax_new

    def residuals(x, z, zx, y, yx, Ax):
        pri = jnp.maximum(
            jnp.max(jnp.abs(Ax - z), axis=1),
            jnp.max(jnp.abs(x - zx), axis=1),
        )
        Aty = hi("smn,sm->sn", A, y)
        Pxv = Px(x)
        dua = jnp.max(jnp.abs(Pxv + q + Aty + yx), axis=1)
        # OSQP-normalized residual scales, for tolerances and rho adaptation
        prinorm = jnp.maximum(
            jnp.max(jnp.abs(Ax), axis=1), jnp.max(jnp.abs(z), axis=1)
        )
        duanorm = jnp.maximum(
            jnp.maximum(
                jnp.max(jnp.abs(Pxv), axis=1),
                jnp.max(jnp.abs(Aty), axis=1),
            ),
            jnp.max(jnp.abs(q), axis=1),
        )
        return pri, dua, prinorm, duanorm

    def cont(carry):
        s, Ax = carry
        # OSQP termination: eps_abs + eps_rel * residual-scale norms
        done = _done_mask(s.pri, s.dua, s.prinorm, s.duanorm, st)
        go = (s.k < st.max_iter) & ~jnp.all(done)
        if st.sweep_plateau_rtol > 0:
            go = go & (s.stall < 2)
        return go

    # fused Pallas sweep block on TPU: all matrices stay in VMEM across the
    # check_every sweeps instead of re-streaming from HBM every sweep, in
    # scenario-on-lanes layout (matrices transposed ONCE per rho setting)
    from . import pallas_kernels

    S, m, n = A.shape
    if isinstance(st.use_pallas, str) and st.use_pallas != "auto":
        raise ValueError(
            f"use_pallas must be True, False, or 'auto'; got "
            f"{st.use_pallas!r} (strings other than 'auto' would silently "
            f"force the kernel on)")
    # dense-kernel precision: "default" stores the matrices in bf16 (halved
    # VMEM per scenario, bf16-rounded operands); "high" keeps f32 — the
    # kernel's VPU contractions run full f32 anyway, so bf16x3 has nothing
    # to save there (the kernel is then at least as accurate as the mode
    # asks; see pallas_kernels.fused_sweeps)
    kprec = "default" if prec == "default" else "highest"
    if st.use_pallas == "auto":
        bs = pallas_kernels.usable(S, m, n, P=P, precision=kprec)
        if bs is not None and bs < S and bs > 512:
            bs32 = (pallas_kernels.usable(S, m, n, P=P)
                    if kprec == "default" else bs)
            if (kprec == "default" and bs32 is not None
                    and not (bs32 < S and bs32 > 512)):
                # bf16 storage WIDENED an f32-ACCEPTED block into the
                # measured-loss band: clamp back to the band's top — the
                # mode's VMEM dividend must never turn the kernel OFF for
                # a shape the f32 path accepts.  Shapes the f32 heuristic
                # itself rejects stay rejected (the loss regime was
                # measured; bf16 storage doesn't re-litigate it).
                bs = 512
            else:
                bs = None      # measured-loss regime (many coarse blocks)
    elif st.use_pallas:
        bs = pallas_kernels.usable(S, m, n, P=P, precision=kprec)
    else:
        bs = None
    if bs is not None:
        Kinv, K = LK
        tT = lambda a: jnp.transpose(a, (1, 2, 0))
        AT, AtT = tT(A), jnp.transpose(A, (2, 1, 0))
        KinvT, KT = tT(Kinv), tT(K)
        if kprec == "default":
            # bf16 storage for the sweep matrices (halved VMEM -> bigger
            # blocks); K stays f32 — it is the refinement DEFECT operand,
            # which must be exact (matches the XLA path's pinned-f32 defect)
            AT, AtT, KinvT = (a.astype(jnp.bfloat16)
                              for a in (AT, AtT, KinvT))
        qT, clT, cuT, lbT, ubT = q.T, cl.T, cu.T, lb.T, ub.T
        rho_aT, rho_xT = rho_a.T, jnp.broadcast_to(rho_x, (S, n)).T

    def multi_step(carry):
        # unrolled sweeps between termination checks: each sweep is a handful
        # of tiny batched matvecs, so per-iteration overhead and residual
        # bookkeeping are amortized over check_every sweeps
        s, Ax = carry
        x, z, zx, y, yx = s.x, s.z, s.zx, s.y, s.yx
        if bs is not None:
            outs = pallas_kernels.fused_sweeps(
                qT, AT, AtT, KinvT, KT, clT, cuT, lbT, ubT, rho_aT, rho_xT,
                x.T, z.T, zx.T, y.T, yx.T, Ax.T,
                n_sweeps=max(1, st.check_every),
                n_refine=st.solve_refine, sigma=float(sigma),
                alpha=float(alpha), bs=bs, precision=kprec,
            )
            x, z, zx, y, yx, Ax = (o.T for o in outs)
        else:
            for _ in range(max(1, st.check_every)):
                x, z, zx, y, yx, Ax = sweep(x, z, zx, y, yx, Ax)
        # re-anchor the incrementally carried Ax: the relaxation combination
        # (alpha=1.6) amplifies carried floating error exponentially across
        # sweeps, so one true matvec per checkpoint resets the drift
        # (pinned f32 under a low sweep mode — the defect control)
        Ax = hi("smn,sn->sm", A, x)
        pri, dua, prinorm, duanorm = residuals(x, z, zx, y, yx, Ax)
        if st.sweep_plateau_rtol > 0:
            best, stall = _plateau_update(s, pri, dua, prinorm, duanorm, st)
        else:
            best, stall = s.best, s.stall
        return (_IterState(x, z, zx, y, yx, pri, dua, prinorm, duanorm,
                           s.k + max(1, st.check_every), best, stall), Ax)

    Ax0 = jnp.einsum("smn,sn->sm", A, state.x)
    state, _ = jax.lax.while_loop(cont, multi_step, (state, Ax0))
    return state


def _solve_scaled(q, q2, A, cl, cu, lb, ub, warm, masks, st: ADMMSettings,
                  P=None):
    """Adaptive-rho outer loop; everything already Ruiz-scaled.

    ``masks`` carries finiteness/equality classifications computed from the
    UNSCALED bounds (scaling can shrink +/-BIG below the BIG/2 test)."""
    S, m, n = A.shape
    dt = A.dtype
    eq = masks.eq
    loose = ~masks.fin_cl & ~masks.fin_cu

    def rho_vec(base):
        r = jnp.where(eq, base * st.rho_eq_scale, base)
        return jnp.where(loose, st.rho_min, r)

    def rho_x_vec(base):
        # clamped columns (lb == ub, the fix-nonants / Benders trick) get the
        # same equality boosting as equality rows: without it ADMM can stall
        # at ~1e-2 primal residuals on fix-and-evaluate solves
        return jnp.where(masks.eqx, base * st.rho_eq_scale,
                         jnp.broadcast_to(base, (S, n)))

    if warm is None:
        x0 = jnp.zeros((S, n), dt)
        z0 = jnp.clip(jnp.zeros((S, m), dt), cl, cu)
        zx0 = jnp.clip(x0, lb, ub)
        y0 = jnp.zeros((S, m), dt)
        yx0 = jnp.zeros((S, n), dt)
    else:
        x0, z0, y0, yx0 = warm
        zx0 = jnp.clip(x0, lb, ub)

    base0 = jnp.full((S,), st.rho, dt)
    inf = jnp.full((S,), jnp.inf, dt)
    one = jnp.ones((S,), dt)
    state0 = _IterState(x0, z0, zx0, y0, yx0, inf, inf, one, one,
                        jnp.zeros((), jnp.int32),
                        jnp.asarray(jnp.inf, dt), jnp.zeros((), jnp.int32))

    # Restart loop as a lax.scan with the factorization in the CARRY, so
    # the LAST rho vectors + factorization survive to become the reusable
    # :class:`Factors` of the frozen-factor path.  (A python-unrolled loop
    # multiplies the traced program by `restarts`; at restarts=8 the XLA:CPU
    # compiler has been observed to segfault on the resulting program.)
    def restart(carry, _):
        state, base, total, mult, multx = carry[:5]
        rho_a = rho_vec(base[:, None])
        rho_x = rho_x_vec(base[:, None])
        if st.rho_row_adapt:
            rho_a = jnp.minimum(rho_a * mult, st.rho_row_max)
            rho_x = jnp.minimum(rho_x * multx, st.rho_row_max)
        LK = _factor(q2, A, rho_a, rho_x, st.sigma, P)
        state = _admm_core(
            q, q2, A, cl, cu, lb, ub,
            state._replace(k=jnp.zeros((), jnp.int32),
                           best=jnp.asarray(jnp.inf, dt),
                           stall=jnp.zeros((), jnp.int32)),
            LK, rho_a, rho_x, st, P,
        )
        total = total + state.k
        # OSQP rho adaptation on NORMALIZED residuals (raw residual ratios
        # push rho the wrong way when primal/dual scales differ).  CONVERGED
        # scenarios keep their rho: their restarts do zero sweeps, so
        # adapting on the stale residual ratio would compound x10 per
        # remaining restart into a runaway rho that only ever reaches the
        # Factors (and wrecks the frozen path's dual convergence).
        done = _done_mask(state.pri, state.dua, state.prinorm,
                          state.duanorm, st)
        eps_pri = st.eps_abs + st.eps_rel * jnp.maximum(state.prinorm, 1.0)
        pri_rel = state.pri / jnp.maximum(state.prinorm, 1e-10)
        dua_rel = state.dua / jnp.maximum(state.duanorm, 1e-10)
        ratio = jnp.sqrt(
            jnp.maximum(pri_rel, 1e-12) / jnp.maximum(dua_rel, 1e-12)
        )
        new_base = jnp.clip(base * jnp.clip(ratio, 0.1, 10.0),
                            st.rho_min, st.rho_max)
        base = jnp.where(done, base, new_base)
        if st.rho_row_adapt:
            # Per-row boost for the DOMINANT violated rows of scenarios that
            # are genuinely stuck: global adaptation balances aggregate
            # residual ratios while a few strongly-coupled rows (UC
            # ramp/genlim) stay infeasible for thousands of sweeps.  The
            # double gate (scenario far from converged AND row near the max
            # violation) keeps ordinary mid-convergence rows un-boosted --
            # indiscriminate boosting wrecks dual convergence and poisons
            # the frozen-path factors.  Boost-only + bounded.
            stuck = (state.pri > 100.0 * eps_pri)[:, None]
            gate = jnp.maximum(0.3 * state.pri,
                               10.0 * eps_pri)[:, None]
            Ax = jnp.einsum("smn,sn->sm", A, state.x)
            viol = jnp.maximum(cl - Ax, Ax - cu)
            mult = jnp.where(stuck & (viol > gate),
                             mult * st.rho_row_boost, mult)
            violx = jnp.maximum(lb - state.x, state.x - ub)
            multx = jnp.where(stuck & (violx > gate),
                              multx * st.rho_row_boost, multx)
        return (state, base, total, mult, multx,
                rho_a, rho_x, LK[0], LK[1]), None

    zK = jnp.zeros((S, n, n), dt)
    carry0 = (state0, base0, jnp.zeros((), jnp.int32),
              jnp.ones((S, m), dt), jnp.ones((S, n), dt),
              jnp.zeros((S, m), dt), jnp.zeros((S, n), dt), zK, zK)
    (state, _, total, _, _, rho_a, rho_x, Kinv, K), _ = jax.lax.scan(
        restart, carry0, None, length=st.restarts)
    return state, total, rho_a, rho_x, (Kinv, K)


def _polish(state: _IterState, q, q2, A, cl, cu, lb, ub, masks,
            st: ADMMSettings, P=None):
    """OSQP-style polish: guess the active set from dual signs + slacks, solve
    the resulting equality-constrained KKT system exactly, and accept per
    scenario only where it improves the worst residual.

    The KKT system is built at FIXED shape (no per-scenario gather): inactive
    rows contribute the trivial equation nu_i = 0, inactive bounds mu_j = 0, so
    the whole batch is one vmapped dense solve — vertex-exact LP solutions from
    mediocre ADMM iterates, replacing thousands of extra sweeps.
    """
    S, m, n = A.shape
    dt = A.dtype
    # Per-side activity tolerances; an infinite side is never active.
    # Finiteness comes from the UNSCALED bounds via ``masks``.
    fin_cl, fin_cu = masks.fin_cl, masks.fin_cu
    tol_cl = 1e-6 * (1.0 + jnp.where(fin_cl, jnp.abs(cl), 0.0))
    tol_cu = 1e-6 * (1.0 + jnp.where(fin_cu, jnp.abs(cu), 0.0))
    ytol = 1e-6 * jnp.maximum(jnp.max(jnp.abs(state.y), axis=1, keepdims=True), 1.0)
    act_lo = ((state.y < -ytol) | (state.z < cl + tol_cl)) & fin_cl
    act_up = ((state.y > ytol) | (state.z > cu - tol_cu)) & fin_cu

    fin_lb, fin_ub = masks.fin_lb, masks.fin_ub
    tol_lb = 1e-6 * (1.0 + jnp.where(fin_lb, jnp.abs(lb), 0.0))
    tol_ub = 1e-6 * (1.0 + jnp.where(fin_ub, jnp.abs(ub), 0.0))
    yxtol = 1e-6 * jnp.maximum(jnp.max(jnp.abs(state.yx), axis=1, keepdims=True), 1.0)
    v_lo = ((state.yx < -yxtol) | (state.zx < lb + tol_lb)) & fin_lb
    v_up = ((state.yx > yxtol) | (state.zx > ub - tol_ub)) & fin_ub

    eq = masks.eq

    eye_n = jnp.eye(n, dtype=dt)[None]
    ftol = 1e-7
    # Reduced augmented-Lagrangian system instead of the full (n+m+n) KKT:
    # active rows and bounds become quadratic penalties with weight 1/delta,
    # so each solve is an n x n batched Cholesky (MXU-friendly) rather than
    # an LU of the 3x-larger saddle system.  A pure penalty would need
    # delta ~ 1e-8 for vertex accuracy — hopeless in float32 — so instead a
    # few multiplier (AL) iterations at a MODERATE delta reuse one
    # factorization and converge the constraint error geometrically:
    # nu_{k+1} = nu_k + (A x_k - b)/delta.
    # AL penalty parameter deliberately DECOUPLED from polish_delta: the
    # multiplier iterations exist so a moderate delta (f64-safe conditioning,
    # cond(K) ~ 1e7) still reaches vertex-exact primal feasibility; the
    # residual dual shift is delta*|x| and is absorbed at bound-active
    # coordinates by the recovery step below.
    delta = jnp.asarray(max(st.polish_delta, 1e-7), dt)
    AL_ITERS = 4

    def kkt_solve_full(act_lo, act_up, v_lo, v_up):
        """Row-replacement saddle LU at (n+m) — float32's accurate option.

        The reduced system's 1/delta conditioning exceeds what f32 Cholesky
        plus refinement can recover, so f32 needs a backward-stable LU of an
        O(1)-entry system.  Instead of the full (n+m+n) KKT, the variable
        -bound dual block is eliminated EXACTLY: for bound-active columns the
        stationarity row is replaced by ``x_j = vb_j`` and the bound dual is
        recovered afterwards from the stationarity residual (same recovery
        step the reduced path uses) — a 3x smaller batched LU, which is the
        dominant polish cost on TPU (batched LU is sequential per step).
        """
        row_act = act_lo | act_up
        row_b = jnp.where(act_up, cu, cl)
        var_act = v_lo | v_up
        var_b = jnp.where(v_up, ub, lb)
        N = n + m
        eye_m = jnp.eye(m, dtype=dt)[None]
        # f32 floor on the row regularizer: 1e-8 is below f32 eps, so a
        # degenerate (redundant) active row set would make the LU singular
        pd = jnp.asarray(max(st.polish_delta,
                             1e-6 if dt == jnp.float32 else 0.0), dt)
        Qblock = jax.vmap(jnp.diag)(q2) + pd * eye_n
        if P is not None:
            Qblock = Qblock + P
        va = var_act[:, :, None]
        ra = row_act[:, :, None]
        M = jnp.zeros((S, N, N), dt)
        rhs = jnp.zeros((S, N), dt)
        M = M.at[:, :n, :n].set(jnp.where(va, eye_n, Qblock))
        M = M.at[:, :n, n:].set(jnp.where(va, 0.0, jnp.swapaxes(A, 1, 2)))
        rhs = rhs.at[:, :n].set(jnp.where(var_act, var_b, -q))
        M = M.at[:, n:, :n].set(jnp.where(ra, A, 0.0))
        M = M.at[:, n:, n:].set(jnp.where(ra, -pd * eye_m, eye_m))
        rhs = rhs.at[:, n:].set(jnp.where(row_act, row_b, 0.0))
        sol = jnp.linalg.solve(M, rhs[..., None])[..., 0]
        xp, yp = sol[:, :n], sol[:, n:]
        # bound duals absorb the stationarity residual at active columns
        Pxp = (q2 * xp if P is None
               else q2 * xp + jnp.einsum("snk,sk->sn", P, xp))
        r_d = Pxp + q + jnp.einsum("smn,sm->sn", A, yp)
        yxp = jnp.where(var_act, -r_d, 0.0)
        return xp, yp, yxp

    def kkt_solve_reduced(act_lo, act_up, v_lo, v_up):
        row_act = act_lo | act_up
        row_b = jnp.where(act_up, cu, cl)
        var_act = v_lo | v_up
        var_b = jnp.where(v_up, ub, lb)
        w_row = row_act.astype(dt) / delta          # (S, m)
        w_var = var_act.astype(dt) / delta          # (S, n)
        K = jnp.einsum("smn,sm,smk->snk", A, w_row, A)
        K = K + delta * eye_n
        K = K + jax.vmap(jnp.diag)(q2 + w_var)
        if P is not None:
            K = K + P
        Kinv = _explicit_inverse(K)
        ra = row_act.astype(dt)
        va = var_act.astype(dt)
        nu = jnp.zeros_like(row_b)
        mu = jnp.zeros_like(var_b)
        xp = jnp.zeros_like(q)
        for _ in range(AL_ITERS):
            rhs = (-q + jnp.einsum("smn,sm->sn", A, w_row * row_b - ra * nu)
                   + (w_var * var_b - va * mu))
            xp = _chol_solve((Kinv, K), rhs, refine=1)
            Ax = jnp.einsum("smn,sn->sm", A, xp)
            nu = nu + w_row * (Ax - row_b)
            mu = mu + w_var * (xp - var_b)
        yp, yxp = ra * nu, va * mu
        # exact bound-dual recovery: at bound-active coordinates mu absorbs
        # the stationarity residual exactly — critical for consumers of
        # clamp duals (Benders cut gradients are -yx on clamped columns)
        Pxp = q2 * xp if P is None else q2 * xp + jnp.einsum(
            "snk,sk->sn", P, xp)
        r_d = Pxp + q + jnp.einsum("smn,sm->sn", A, yp) + yxp
        yxp = jnp.where(var_act, yxp - r_d, yxp)
        return xp, yp, yxp

    kkt_solve = (kkt_solve_full if dt == jnp.float32 else kkt_solve_reduced)

    def refine_add_only(xp, yp, yxp, sets):
        """ADD violated rows at the violated side, never drop.  Robust when
        the initial guess is near-correct: dropping actives by dual sign can
        oscillate (a dropped land/balance row lets the penalized solve blow
        x to -q/delta and the next pass re-adds it, forever)."""
        act_lo, act_up, v_lo, v_up = sets
        Ax = jnp.einsum("smn,sn->sm", A, xp)
        act_lo = act_lo | (Ax < cl - ftol) | eq
        act_up = act_up | (Ax > cu + ftol) | eq
        v_lo = (v_lo | (xp < lb - ftol)) & fin_lb
        v_up = (v_up | (xp > ub + ftol)) & fin_ub
        return act_lo, act_up, v_lo, v_up

    def refine_textbook(xp, yp, yxp, sets):
        """Textbook add-and-drop: also prune actives whose dual sign is
        wrong.  Recovers from BAD initial guesses (e.g. stalled clamped
        solves) where add-only is stuck with over-constrained sets."""
        act_lo, act_up, v_lo, v_up = sets
        Ax = jnp.einsum("smn,sn->sm", A, xp)
        act_lo = ((act_lo & ~(yp > ftol)) | (Ax < cl - ftol) | eq)
        act_up = ((act_up & ~(yp < -ftol)) | (Ax > cu + ftol) | eq)
        v_lo = ((v_lo & ~(yxp > ftol)) | (xp < lb - ftol)) & fin_lb
        v_up = ((v_up & ~(yxp < -ftol)) | (xp > ub + ftol)) & fin_ub
        return act_lo, act_up, v_lo, v_up

    # the initial solve on the guessed sets is shared by both disciplines
    sets0 = (act_lo | eq, act_up | eq, v_lo, v_up)
    first = kkt_solve(*sets0)

    def run_passes(refine):
        sets = sets0
        xp, yp, yxp = first
        for _ in range(st.polish_passes):
            sets = refine(xp, yp, yxp, sets)
            xp, yp, yxp = kkt_solve(*sets)
        Ax = jnp.einsum("smn,sn->sm", A, xp)
        zp = jnp.clip(Ax, cl, cu)
        zxp = jnp.clip(xp, lb, ub)
        pri = jnp.maximum(
            jnp.max(jnp.abs(Ax - zp), axis=1),
            jnp.max(jnp.abs(xp - zxp), axis=1),
        )
        Aty = jnp.einsum("smn,sm->sn", A, yp)
        Pxp = (q2 * xp if P is None
               else q2 * xp + jnp.einsum("snk,sk->sn", P, xp))
        dua = jnp.max(jnp.abs(Pxp + q + Aty + yxp), axis=1)
        return xp, zp, zxp, yp, yxp, pri, dua

    # run BOTH refinement disciplines; per scenario, keep whichever candidate
    # (or the original state) has the best worst-case residual
    cand = run_passes(refine_add_only)
    cand2 = run_passes(refine_textbook)
    worse2 = jnp.maximum(cand2[5], cand2[6]) >= jnp.maximum(cand[5], cand[6])
    cand = tuple(
        jnp.where(worse2[:, None] if a.ndim == 2 else worse2, a, b)
        for a, b in zip(cand, cand2)
    )
    xp, zp, zxp, yp, yxp, pri, dua = cand

    better = jnp.maximum(pri, dua) < jnp.maximum(state.pri, state.dua)
    pick = lambda a, b: jnp.where(better[:, None], a, b)
    return state._replace(
        x=pick(xp, state.x), z=pick(zp, state.z), zx=pick(zxp, state.zx),
        y=pick(yp, state.y), yx=pick(yxp, state.yx),
        pri=jnp.where(better, pri, state.pri),
        dua=jnp.where(better, dua, state.dua),
    )


@functools.partial(jax.jit, static_argnames=("settings",))
def solve_batch(c, q2, A, cl, cu, lb, ub, settings: ADMMSettings = ADMMSettings(),
                warm=None, P=None) -> BatchSolution:
    """Solve a batch of box-QP/LPs. All arrays (S, ...) as in ScenarioBatch.

    ``warm``: optional (x, z, y, yx) from a previous call — PH's persistent-solver
    analogue (spopt.py:129-144): between PH iterations only (q, rho-terms) change,
    so the previous primal/dual iterates are excellent starts.

    ``P``: optional dense (S, n, n) quadratic term added to diag(q2) — used by
    FWPH's simplex QPs; omit for the separable scenario subproblems.

    On TPU, float32 matmuls default to bf16 MXU accumulation, which stalls ADMM
    below ~1e-3 residuals; the solve traces at ``settings.matmul_precision``
    (default "highest": f32 full-precision passes on the MXU).  Lowering it
    trades residual floor for sweep throughput.
    """
    with jax.default_matmul_precision(settings.matmul_precision):
        return _solve_impl(c, q2, A, cl, cu, lb, ub, settings, warm, P)


# AOT executable cache (tpusppy/solvers/aot.py): the batch-solve entry
# points are what spopt's amortized solve loop dispatches every wheel
# iteration — persisting their executables is the wheel's warm start.
# Strict passthrough when TPUSPPY_AOT_CACHE is disarmed, and nested
# (in-trace) calls inline exactly like the plain jit.
solve_batch = _aot.cached_program(solve_batch, "admm.solve_batch",
                                  static_names=("settings",))


def _prep(c, q2, A, cl, cu, lb, ub, settings, P, want_masks=True):
    """Dtype casting, bound cleaning, finiteness masks — shared by the
    adaptive and frozen entry points.  ``want_masks=False`` skips the mask
    reductions for callers that never use them (polish-free frozen solves:
    inside a fused multi-iteration scan those reductions would otherwise
    run once per PH iteration for nothing)."""
    dt = settings.jdtype()
    c, q2, A = (jnp.asarray(v, dt) for v in (c, q2, A))
    if P is not None:
        P = jnp.asarray(P, dt)
    cl, cu = _clean_bounds(jnp.asarray(cl, dt), jnp.asarray(cu, dt))
    lb, ub = _clean_bounds(jnp.asarray(lb, dt), jnp.asarray(ub, dt))
    masks = None
    if want_masks:
        masks = _BoundMasks(
            fin_cl=cl > -BIG / 2, fin_cu=cu < BIG / 2,
            fin_lb=lb > -BIG / 2, fin_ub=ub < BIG / 2,
            eq=jnp.abs(cu - cl) < 1e-10,
            eqx=jnp.abs(ub - lb) < 1e-10,
        )
    return c, q2, A, cl, cu, lb, ub, masks, P


def _scale(c, q2, A, cl, cu, lb, ub, D, E, cost, P, warm, dt):
    As = A * E[:, :, None] * D[:, None, :]
    q2s = q2 * D * D * cost[:, None]
    qs = c * D * cost[:, None]
    Ps = None
    if P is not None:
        Ps = P * D[:, :, None] * D[:, None, :] * cost[:, None, None]
    cls, cus = cl * E, cu * E
    lbs, ubs = lb / D, ub / D
    if warm is not None:
        x0, z0, y0, yx0 = warm
        warm = (
            jnp.asarray(x0, dt) / D,
            jnp.asarray(z0, dt) * E,
            jnp.asarray(y0, dt) / E * cost[:, None],
            jnp.asarray(yx0, dt) * D * cost[:, None],
        )
    return qs, q2s, As, cls, cus, lbs, ubs, Ps, warm


def _solve_impl(c, q2, A, cl, cu, lb, ub, settings, warm, P=None,
                want_factors=False):
    dt = settings.jdtype()
    c, q2, A, cl, cu, lb, ub, masks, P = _prep(
        c, q2, A, cl, cu, lb, ub, settings, P)

    D, E = _ruiz(A, q2, settings.scaling_iters)
    cost = 1.0 / jnp.maximum(jnp.max(jnp.abs(c * D), axis=1), 1e-8)
    qs, q2s, As, cls, cus, lbs, ubs, Ps, warm = _scale(
        c, q2, A, cl, cu, lb, ub, D, E, cost, P, warm, dt)

    state, total, rho_a, rho_x, LK = _solve_scaled(
        qs, q2s, As, cls, cus, lbs, ubs, warm, masks, settings, Ps)

    def unscale(s):
        return (s.x * D, s.z / E, s.y * E / cost[:, None],
                s.yx / D / cost[:, None])

    raw = unscale(state)
    if settings.polish:
        state = _polish(state, qs, q2s, As, cls, cus, lbs, ubs, masks,
                        settings, Ps)
    x, z, y, yx = unscale(state)
    S = A.shape[0]
    sol = BatchSolution(
        x=x, z=z, y=y, yx=yx,
        pri_res=state.pri, dua_res=state.dua,
        iters=jnp.broadcast_to(total, (S,)),
        done=_done_mask(state.pri, state.dua, state.prinorm,
                        state.duanorm, settings),
        raw=raw,
    )
    if want_factors:
        return sol, Factors(D=D, E=E, cost=cost, rho_a=rho_a, rho_x=rho_x,
                            Kinv=LK[0], K=LK[1])
    return sol


def _frozen_sweep_phases(run_core, state0, settings, dt):
    """Two-phase frozen sweep shared by BOTH engines (dense per-scenario
    and shared-A — their ``_IterState``s both carry k/best/stall, which is
    all this touches).  ``run_core(state, st, prec)`` runs one engine core.

    Full precision: a single legacy-path core run.  Lowered
    (``settings.sweep_precision``): a bf16/bf16x3 sweep phase (f32-pinned
    residuals, so the while_loop's eps test is real), then — only when
    not every scenario reached eps — a bounded full-precision refinement
    phase on the SAME factors restores the f32 floor.  The reported
    residuals/done always come from f32 measurements; iteration counts
    accumulate across phases."""
    from . import precision as _precision
    if not _precision.is_low(settings.sweep_precision):
        return run_core(state0, settings, None)
    mode = _precision.canon(settings.sweep_precision)
    state = run_core(state0, settings, mode)
    if settings.precision_refine_iters > 0:
        k1 = state.k
        st_r = dataclasses.replace(
            settings, max_iter=int(settings.precision_refine_iters))
        state = run_core(
            state._replace(k=jnp.zeros((), jnp.int32),
                           best=jnp.asarray(jnp.inf, dt),
                           stall=jnp.zeros((), jnp.int32)),
            st_r, "highest")
        state = state._replace(k=state.k + k1)
    return state


def _solve_frozen_impl(c, q2, A, cl, cu, lb, ub, factors: Factors, warm,
                       settings, P=None, polish=False) -> BatchSolution:
    """Sweep-only solve reusing a previous refresh's :class:`Factors`.

    No Ruiz recomputation, no factorization, no rho adaptation — the
    steady-state PH iteration on TPU.  Valid while (A, q2, bounds) are
    unchanged since the refresh (only the linear term q may move); accuracy
    is still enforced by the residual-based while_loop, so a drifted active
    set costs extra sweeps, not correctness.

    ``polish=True`` additionally applies the active-set KKT polish to the
    final iterate (honoring ``settings.polish``): the segmented-dispatch
    refresh path ends its continuation with one short polishing dispatch so
    large shapes keep single-dispatch refresh accuracy.
    """
    dt = settings.jdtype()
    c, q2, A, cl, cu, lb, ub, masks, P = _prep(
        c, q2, A, cl, cu, lb, ub, settings, P,
        want_masks=polish and settings.polish)
    D, E, cost = factors.D, factors.E, factors.cost
    qs, q2s, As, cls, cus, lbs, ubs, Ps, warm = _scale(
        c, q2, A, cl, cu, lb, ub, D, E, cost, P, warm, dt)

    S, m, n = A.shape
    if warm is None:
        x0 = jnp.zeros((S, n), dt)
        z0 = jnp.clip(jnp.zeros((S, m), dt), cls, cus)
        y0 = jnp.zeros((S, m), dt)
        yx0 = jnp.zeros((S, n), dt)
    else:
        x0, z0, y0, yx0 = warm
    zx0 = jnp.clip(x0, lbs, ubs)
    inf = jnp.full((S,), jnp.inf, dt)
    one = jnp.ones((S,), dt)
    state0 = _IterState(x0, z0, zx0, y0, yx0, inf, inf, one, one,
                        jnp.zeros((), jnp.int32),
                        jnp.asarray(jnp.inf, dt), jnp.zeros((), jnp.int32))

    LK = (factors.Kinv, factors.K)

    def run_core(st0, st, prec):
        return _admm_core(qs, q2s, As, cls, cus, lbs, ubs, st0, LK,
                          factors.rho_a, factors.rho_x, st, Ps, prec=prec)

    state = _frozen_sweep_phases(run_core, state0, settings, dt)

    def unscale(s):
        return (s.x * D, s.z / E, s.y * E / cost[:, None],
                s.yx / D / cost[:, None])

    raw = unscale(state)
    if polish and settings.polish:
        state = _polish(state, qs, q2s, As, cls, cus, lbs, ubs, masks,
                        settings, Ps)
    x, z, y, yx = unscale(state)
    return BatchSolution(
        x=x, z=z, y=y, yx=yx,
        pri_res=state.pri, dua_res=state.dua,
        iters=jnp.broadcast_to(state.k, (S,)),
        done=_done_mask(state.pri, state.dua, state.prinorm,
                        state.duanorm, settings),
        raw=raw,
    )


@functools.partial(jax.jit, static_argnames=("settings", "polish"))
def solve_batch_frozen(c, q2, A, cl, cu, lb, ub, factors: Factors,
                       settings: ADMMSettings = ADMMSettings(),
                       warm=None, P=None, polish=False) -> BatchSolution:
    """Jitted frozen-factor solve; see :func:`_solve_frozen_impl`."""
    with jax.default_matmul_precision(settings.matmul_precision):
        return _solve_frozen_impl(c, q2, A, cl, cu, lb, ub, factors, warm,
                                  settings, P, polish=polish)


solve_batch_frozen = _aot.cached_program(
    solve_batch_frozen, "admm.solve_batch_frozen",
    static_names=("settings", "polish"))


@jax.jit
def stop_stats(sol: BatchSolution):
    """[max iters, max pri_res, max dua_res, all_done] as ONE device array.

    Segmented continuations (:mod:`.segmented`) need the iteration counter
    (stop-dispatch test), the worst residuals (plateau detector) and the
    convergence vote on the host between segments; fetched separately that
    is several serial host<->device round-trips per segment — over a
    remote TPU tunnel each is a full RPC.  This reduces them to one fetch.
    ``all_done`` lets the stop test catch a mixed-precision solve whose
    phase-1 sweep count hit the segment cap but whose f32 refinement phase
    then converged (iters alone would schedule a pointless extra
    dispatch)."""
    dt = sol.pri_res.dtype
    return jnp.stack([sol.iters.max().astype(dt),
                      sol.pri_res.max().astype(dt),
                      sol.dua_res.max().astype(dt),
                      jnp.all(sol.done).astype(dt)])


stop_stats = _aot.cached_program(stop_stats, "admm.stop_stats")


def precision_guard_trips(sol: BatchSolution, settings: ADMMSettings,
                          ref_worst=None, stats=None) -> bool:
    """Host-side residual guard for the mixed-precision frozen path.

    True when a low-precision frozen solve must be re-run at full
    precision: it is not eps-converged AND its worst residual exceeds
    ``precision_guard`` x the reference floor — the worst residual of the
    last FULL-precision refresh solve of the same family (``ref_worst``),
    floored at eps.  Plateau families (whose full-precision floor is far
    above eps) therefore never trip the guard on residuals full precision
    could not beat either; a genuinely precision-limited solve (parked
    orders of magnitude above the f32 floor, or non-finite) always does.

    ``stats``: optional precomputed ``(worst_residual, all_done)`` pair —
    callers that already hold a fetched measurement (the single-fetch
    amortized path, :func:`measure_unpack`) pass it so the guard costs
    ZERO additional device round-trips; without it the guard performs one
    :func:`stop_stats` fetch itself.
    """
    if not settings.sweep_precision or settings.sweep_precision == "highest":
        return False
    if settings.precision_guard <= 0:
        return False
    if stats is not None:
        worst, all_done = float(stats[0]), bool(stats[1])
    else:
        # ONE device fetch (stop_stats: iters/residual maxima/all_done) —
        # the guard sits in the amortized hot path, where separate fetches
        # are serial RPCs over a remote tunnel
        from . import hostsync
        st4 = hostsync.fetch(stop_stats(sol))
        worst, all_done = float(max(st4[1], st4[2])), bool(st4[3])
    if all_done:
        return False
    if not np.isfinite(worst):
        return True
    floor = max(settings.eps_abs, settings.eps_rel)
    bar = settings.precision_guard * max(float(ref_worst or 0.0), floor)
    return worst > bar


@jax.jit
def measure_pack(sol: BatchSolution):
    """Everything the host wheel iteration reads from one solve, as ONE
    flat device vector: ``[pri_res (S) | dua_res (S) | iters_max |
    all_done | x.ravel (S*n)]``.

    The amortized solve loop used to fetch ``x``, ``pri_res`` and
    ``dua_res`` separately (plus a ``stop_stats`` fetch when the
    mixed-precision guard is armed) — 3-4 serial RPCs per PH iteration
    over a remote tunnel.  Assembling the measurement device-side
    collapses them into a single fetch (:func:`measure_unpack` splits it
    back on the host); the warm-start state stays device-resident and is
    never fetched at all.
    """
    dt = sol.pri_res.dtype
    return jnp.concatenate([
        sol.pri_res.astype(dt),
        sol.dua_res.astype(dt),
        sol.iters.max().astype(dt)[None],
        jnp.all(sol.done).astype(dt)[None],
        sol.x.astype(dt).reshape(-1),
    ])


measure_pack = _aot.cached_program(measure_pack, "admm.measure_pack")


def measure_unpack(vec, S, n):
    """Split a fetched :func:`measure_pack` vector; returns a dict with
    ``pri`` (S,), ``dua`` (S,), ``iters`` (int), ``all_done`` (bool) and
    ``x`` (S, n)."""
    vec = np.asarray(vec)
    return {
        "pri": vec[:S],
        "dua": vec[S:2 * S],
        "iters": int(vec[2 * S]),
        "all_done": bool(vec[2 * S + 1]),
        "x": vec[2 * S + 2:].reshape(S, n),
    }


def _Aty(A, y):
    """A'y per scenario; A may be (S, m, n), a shared (m, n), or a
    :class:`~tpusppy.solvers.sparse.SparseA` (certified-bound programs
    then ride the exact sparse transpose matvec)."""
    from .sparse import SparseA
    if isinstance(A, SparseA):
        return A.rmatvec(y)
    return y @ A if A.ndim == 2 else jnp.einsum("smn,sm->sn", A, y)




def _highest_precision(fn):
    """Pin a jitted certified-bound program to full-f32 matmuls regardless
    of ambient or settings precision (the bound's validity is numerical)."""

    @functools.wraps(fn)
    def wrapped(*a, **k):
        with jax.default_matmul_precision("highest"):
            return fn(*a, **k)

    return wrapped


@_highest_precision
@jax.jit
def dual_objective(c, q2, A, cl, cu, lb, ub, y, x_hint, margin_scale=100.0):
    """(S,) LOWER bounds on each scenario optimum from row duals ``y``.

    Weak duality: for ANY y, ``g(y) = min_x L(x, y)`` bounds the optimum below
    — unlike the primal objective of an inexact solve, which the reference's
    Lagrangian spoke (lagrangian_bounder.py:19-56) gets exact from its MIP
    solver but an iterative solver only gets to tolerance.  Construction:

    - rows: contribute ``-y+·cu + y-·cl``; y is first CLIPPED to the dual
      cone of finite sides (clipping just picks a different valid y),
    - variables are NOT dualized: ``min_x [0.5 x'diag(q2)x + (c + A'y)'x]``
      is solved in closed form per coordinate over the variable box.

    For coordinates whose needed side is infinite (free variables with
    residual reduced cost), the box is capped at ``X = margin_scale *
    (1 + max|x_hint|)`` per scenario: the result is a certificate under the
    assumption that the true optimizer lies within X (use
    :func:`dual_objective_capped` to know which scenarios relied on it).
    Models with finite variable bounds get an unconditional certificate.

    Implemented as :func:`dual_cut` with nothing clamped.
    """
    base, _ = dual_cut(c, q2, A, cl, cu, lb, ub, y, x_hint,
                       jnp.zeros(c.shape[1], dtype=bool), margin_scale)
    return base


@_highest_precision
@jax.jit
def dual_objective_margin(c, q2, A, cl, cu, lb, ub, y, x_hint,
                          margin_scale=100.0, widen=10.0):
    """(S,) defensive margins for :func:`dual_objective`'s X-cap.

    ``dual_objective`` evaluates free coordinates over a synthetic box of
    half-width ``X = margin_scale*(1+max|x_hint|)``; its value is certified
    only under ``|x*| <= X``.  Subtracting this margin extends the validity
    box to ``widen*X``: for each coordinate whose needed side is infinite,
    the margin is the decrease of the coordinate minimum when the box grows
    from X to widen*X (exact for linear coordinates, an upper bound for
    quadratic ones).  Tight duals make every margin ~0, so the cost of the
    widened certificate vanishes exactly when the bound is good.
    """
    cl, cu = _clean_bounds(cl, cu)
    lb, ub = _clean_bounds(lb, ub)
    fin_lb, fin_ub = lb > -BIG / 2, ub < BIG / 2
    y = jnp.where(~(cu < BIG / 2) & (y > 0), 0.0, y)
    y = jnp.where(~(cl > -BIG / 2) & (y < 0), 0.0, y)
    g = c + _Aty(A, y)
    X = margin_scale * (1.0 + jnp.max(jnp.abs(x_hint), axis=1, keepdims=True))
    # linear coords: value at the capped side is g*(+-X); widening multiplies
    # the capped side by `widen`, decreasing the minimum by |g|*(widen-1)*X.
    # quadratic coords: the minimum over a LARGER box can only decrease, and
    # by at most the same linear envelope (q2 >= 0), so the bound applies too.
    need_hi = ~fin_ub & (g < 0)
    need_lo = ~fin_lb & (g > 0)
    # a quadratic coordinate only hits the cap when its unconstrained
    # minimizer |g|/q2 lies beyond X; interior minima are exact as-is
    engaged = (q2 <= 1e-14) | (jnp.abs(g) > q2 * X)
    per = jnp.where((need_hi | need_lo) & engaged,
                    jnp.abs(g) * (widen - 1.0) * X, 0.0)
    return jnp.sum(per, axis=1)


@jax.jit
def _dual_objective_with_margin_jit(c, q2, A, cl, cu, lb, ub, y, x_hint,
                                    margin_scale=100.0):
    base = dual_objective(c, q2, A, cl, cu, lb, ub, y, x_hint,
                          margin_scale)
    marg = dual_objective_margin(c, q2, A, cl, cu, lb, ub, y, x_hint,
                                 margin_scale)
    return jnp.stack([base, marg])


# _highest_precision OUTSIDE the executable cache so an AOT lower+compile
# still traces under the pinned full-precision matmul context
dual_objective_with_margin = _highest_precision(_aot.cached_program(
    _dual_objective_with_margin_jit, "admm.dual_objective_with_margin"))


def dual_objective_with_margin_traced(c, q2, A, cl, cu, lb, ub, y, x_hint,
                                      margin_scale=100.0):
    """TRACEABLE twin of :func:`dual_objective_with_margin` for callers
    fusing the certified-bound assembly into a larger device program (the
    in-wheel bound pass of ``parallel.sharded.make_wheel_megastep``).
    Same (2, S) stack of [dual_objective, margin], traced under the SAME
    ``_highest_precision`` matmul pin as the spoke-path wrapper — the
    bound's validity is numerical, so the fused assembly must not
    inherit a caller's lowered (bf16) matmul precision.  The
    tolerance-absorbing margin stays single-sourced here."""
    with jax.default_matmul_precision("highest"):
        return _dual_objective_with_margin_jit(c, q2, A, cl, cu, lb, ub, y,
                                               x_hint, margin_scale)
dual_objective_with_margin.__doc__ = \
    """(2, S): :func:`dual_objective` stacked with
    :func:`dual_objective_margin` in ONE device program.

    Bound spokes evaluate both every wheel iteration; as two separate
    jitted calls they cost two serial host RPCs per iteration over a
    remote tunnel — this packs them into a single dispatch + fetch (the
    single-fetch wheel-iteration discipline, doc/pipeline.md).
    """


@_highest_precision
@jax.jit
def dual_cut(c, q2, A, cl, cu, lb, ub, y, x_hint, clamp_mask,
             margin_scale=100.0):
    """Benders-cut data valid for ANY duals ``y`` (weak duality).

    For the value function of a problem whose ``clamp_mask`` columns are
    fixed at x̂ (lb = ub = x̂), the dual objective decomposes into terms
    independent of x̂ plus a term LINEAR in x̂:

        Q(x̂') >= base + g[clamp] . x̂'      for every x̂'

    with ``g = c + A'y`` and ``base`` the row term plus the non-clamped
    coordinate minima.  Unlike the raw clamp duals ``-yx`` (exact only for
    sign-FEASIBLE optimal duals — a polished dual at a degenerate optimum
    can satisfy stationarity with wrong-signed multipliers and yield an
    INVALID cut), this construction can only weaken, never invalidate.
    Returns ``(base (S,), g (S, n))``; callers slice g at the clamp columns.
    """
    dt = c.dtype
    cl, cu = _clean_bounds(cl, cu)
    lb, ub = _clean_bounds(lb, ub)
    fin_cl, fin_cu = cl > -BIG / 2, cu < BIG / 2
    fin_lb, fin_ub = lb > -BIG / 2, ub < BIG / 2

    y = jnp.where(~fin_cu & (y > 0), 0.0, y)
    y = jnp.where(~fin_cl & (y < 0), 0.0, y)
    yp = jnp.maximum(y, 0.0)
    ym = jnp.minimum(y, 0.0)
    row_term = jnp.sum(-yp * jnp.where(fin_cu, cu, 0.0)
                       - ym * jnp.where(fin_cl, cl, 0.0), axis=1)

    X = margin_scale * (1.0 + jnp.max(jnp.abs(x_hint), axis=1, keepdims=True))
    L = jnp.where(fin_lb, lb, -X)
    U = jnp.where(fin_ub, ub, X)
    g = c + _Aty(A, y)
    quad = q2 > 1e-14
    xq = jnp.clip(jnp.where(quad, -g / jnp.where(quad, q2, 1.0), 0.0), L, U)
    val_quad = 0.5 * q2 * xq * xq + g * xq
    val_lin = g * jnp.where(g >= 0, L, U)
    term = jnp.where(quad, val_quad, val_lin)
    base = row_term + jnp.sum(jnp.where(clamp_mask[None, :], 0.0, term),
                              axis=1)
    return base, g


@functools.partial(jax.jit, static_argnames=("settings",))
def solve_batch_factored(c, q2, A, cl, cu, lb, ub,
                         settings: ADMMSettings = ADMMSettings(),
                         warm=None, P=None):
    """Adaptive solve that ALSO returns the reusable :class:`Factors` for
    subsequent :func:`solve_batch_frozen` calls."""
    with jax.default_matmul_precision(settings.matmul_precision):
        return _solve_impl(c, q2, A, cl, cu, lb, ub, settings, warm, P,
                           want_factors=True)


solve_batch_factored = _aot.cached_program(
    solve_batch_factored, "admm.solve_batch_factored",
    static_names=("settings",))


class SingleSolution(NamedTuple):
    x: jax.Array
    y: jax.Array
    pri_res: jax.Array
    dua_res: jax.Array


def solve_single(c, q2, A, cl, cu, lb, ub, settings: ADMMSettings = ADMMSettings(),
                 **kw) -> SingleSolution:
    """Convenience wrapper: one problem as a batch of 1 (EF solves)."""
    sol = solve_batch(
        c[None], q2[None], A[None], cl[None], cu[None], lb[None], ub[None],
        settings=settings, **kw,
    )
    return SingleSolution(sol.x[0], sol.y[0], sol.pri_res[0], sol.dua_res[0])
