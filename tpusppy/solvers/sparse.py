"""Sparse shared constraint matrices + block/Woodbury KKT structure.

Reference-scale stochastic-programming families have EXTREMELY sparse
shared constraint matrices (the WECC-240 UC at horizon 24 is (12408,
16008) with 64k nonzeros — 0.03% dense), yet the shared-A ADMM engine
(:mod:`tpusppy.solvers.shared_admm`) streams the dense (m, n) matrix
through every sweep and applies a dense (n, n) explicit KKT inverse.
This module provides the two structure-exploiting pieces:

- :class:`SparseA` — a COO/CSR-ordered jit-compatible pytree with batched
  matvecs via gather + ``segment_sum``.  Measured on v5e at UC shapes
  (S=1000): 6.0 ms forward / 7.4 ms transpose in exact f32 versus ~42 ms
  for the dense matmul at matmul precision "highest" (the solver's
  setting) — and it removes the 795 MB (3.2 GB at horizon 48) dense A
  from the sweep path entirely.

- :func:`detect_structure` + :class:`BlockWoodbury` — the KKT system
  K = diag(d) + A' R A separates, for these families, into
  ``B + U R_w U'`` where B is BLOCK-DIAGONAL over variable components
  (generators: vars coupled only by their own ramp/min-up/segment rows)
  and U collects the few hundred WIDE rows (power balance, reserves)
  that couple everything.  The x-update solve then costs
  O(S*(sum_b bs^2 + 2 n r)) instead of O(S n^2) — ~6x fewer flops at UC
  shape, and no (n, n) dense inverse in HBM at all (the 4.1 GB Kinv at
  horizon 48 was the single-chip memory wall).

Reference analogue: none — the reference hands subproblems to Gurobi,
whose presolve/LU exploits sparsity internally (spopt.py:85-223).  This
is the TPU-native equivalent of that internal structure exploitation.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class EllA(NamedTuple):
    """Padded-ELL twin of a :class:`SparseA` — the Pallas-friendly layout.

    Row form (the forward matvec): ``rowcols``/``rowvals`` are (m, kr)
    with each row's nonzero column ids/values left-packed; padding slots
    carry column 0 with value 0 (inert in the multiply-accumulate).
    Column form (the transpose matvec): ``colrows``/``colvals`` are
    (n, kc) likewise.  kr/kc are the max per-row/per-column nonzero
    counts — the fused sparse sweep kernel
    (:func:`tpusppy.solvers.pallas_kernels.fused_sweeps_sparse`) loops
    them as static trace-time constants, so the build gate
    (:data:`ELL_MAX_K`) keeps them small."""

    rowcols: jax.Array   # (m, kr) int32
    rowvals: jax.Array   # (m, kr)
    colrows: jax.Array   # (n, kc) int32
    colvals: jax.Array   # (n, kc)


# per-row/per-column nonzero cap for building the ELL twin: the fused
# sparse kernel unrolls kr + kc multiply-accumulate steps per matvec, so
# wide rows (reference-UC power balance spans hundreds of columns) must
# decline — those families keep the gather/segment-sum XLA path
ELL_MAX_K = 64


def _build_ell(rows, cols, vals, m, n, max_k=ELL_MAX_K):
    """Host-side ELL construction from COO (None when a row or column
    exceeds ``max_k`` nonzeros).  Fully vectorized — the TPU opt-in
    shapes this feeds have 1e5+ nonzeros, where a per-nonzero Python
    loop would cost seconds per build."""
    row_counts = np.bincount(rows, minlength=m)
    col_counts = np.bincount(cols, minlength=n)
    kr = int(row_counts.max()) if rows.size else 1
    kc = int(col_counts.max()) if cols.size else 1
    if kr > max_k or kc > max_k:
        return None
    kr, kc = max(kr, 1), max(kc, 1)

    def pack(keys, others, vals_, counts, rows_out, k):
        """Left-pack (keys -> slots) via a stable sort: slot index =
        position within the key's sorted run."""
        order = np.argsort(keys, kind="stable")
        ks, os_, vs = keys[order], others[order], vals_[order]
        starts = np.zeros(counts.size + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        slot = np.arange(ks.size) - starts[ks]
        idx_out = np.zeros((rows_out, k), np.int32)
        val_out = np.zeros((rows_out, k))
        idx_out[ks, slot] = os_
        val_out[ks, slot] = vs
        return idx_out, val_out

    rowcols, rowvals = pack(np.asarray(rows), np.asarray(cols),
                            np.asarray(vals), row_counts, m, kr)
    colrows, colvals = pack(np.asarray(cols), np.asarray(rows),
                            np.asarray(vals), col_counts, n, kc)
    return rowcols, rowvals, colrows, colvals


@jax.tree_util.register_pytree_node_class
class SparseA:
    """Shared (m, n) sparse matrix, batched-matvec ready, jit-compatible.

    Arrays (pytree children): COO triplets sorted in CSR order plus a
    CSC-order permutation for the transpose matvec.  ``shape`` is static
    aux data (participates in the jit cache key, never traced).
    ``ell`` optionally carries the padded-ELL twin (:class:`EllA`) for
    the fused sparse Pallas sweep kernel.
    """

    def __init__(self, rows, cols, vals, perm_csc, shape, structure=None,
                 ell=None):
        self.rows = rows
        self.cols = cols
        self.vals = vals
        self.perm_csc = perm_csc
        self.shape = tuple(shape)
        # optional StructureArrays (tpusppy.solvers.structured_kkt): the
        # block/Woodbury split of this matrix's KKT system, attached at
        # build time so jitted factor programs can use it
        self.structure = structure
        self.ell = ell

    # -- pytree protocol --------------------------------------------------
    def tree_flatten(self):
        return ((self.rows, self.cols, self.vals, self.perm_csc,
                 self.structure, self.ell), self.shape)

    @classmethod
    def tree_unflatten(cls, shape, children):
        rows, cols, vals, perm_csc, structure, ell = children
        return cls(rows, cols, vals, perm_csc, shape, structure, ell)

    # -- construction -----------------------------------------------------
    @classmethod
    def from_dense(cls, A, dtype=None, structure: bool = False,
                   ell: bool | str = "auto", **detect_kw):
        """Build from a dense ndarray; ``structure=True`` additionally
        runs :func:`detect_structure` and attaches the device-side index
        arrays when a usable block/Woodbury split exists.

        ``ell``: build the padded-ELL twin for the fused sparse Pallas
        kernel.  "auto" (default) builds it only where the kernel could
        ever engage (``pallas_kernels.sparse_kernel_possible``: Pallas +
        TPU backend + the ``TPUSPPY_PALLAS_SPARSE=1`` opt-in): the twin
        costs two O(nnz) host passes plus a second device copy of the
        values, pure waste on paths that can never use it.  True forces
        the build (interpret-mode tests); False never builds."""
        A = np.asarray(A)
        m, n = A.shape
        rows, cols = np.nonzero(A)
        vals = A[rows, cols]
        order = np.lexsort((cols, rows))          # CSR order
        rows, cols, vals = rows[order], cols[order], vals[order]
        perm_csc = np.lexsort((rows, cols)).astype(np.int32)
        struct_arrays = None
        if structure:
            st = detect_structure(A, **detect_kw)
            if st is not None:
                from .structured_kkt import StructureArrays
                struct_arrays = StructureArrays.from_structure(st)
        vals_dev = (jnp.asarray(vals, dtype) if dtype is not None
                    # no explicit dtype when unspecified: jnp.asarray then
                    # applies the default f64->f32 demotion silently
                    # instead of warning on every upload in non-x64
                    # processes
                    else jnp.asarray(vals))
        if ell == "auto":
            from . import pallas_kernels

            ell = pallas_kernels.sparse_kernel_possible()
        ell_dev = None
        built = _build_ell(rows, cols, vals, m, n) if ell else None
        if built is not None:
            rc, rv, cr, cv = built
            ell_dev = EllA(jnp.asarray(rc), jnp.asarray(rv, vals_dev.dtype),
                           jnp.asarray(cr), jnp.asarray(cv, vals_dev.dtype))
        return cls(jnp.asarray(rows, jnp.int32),
                   jnp.asarray(cols, jnp.int32),
                   vals_dev,
                   jnp.asarray(perm_csc), (m, n), struct_arrays, ell_dev)

    @property
    def nnz(self):
        return self.vals.shape[0]

    @property
    def ndim(self):
        """2 — shared-matrix rank, so ``A.ndim == 2`` dispatch sites
        treat a SparseA exactly like a shared dense (m, n) matrix."""
        return 2

    @property
    def dtype(self):
        return self.vals.dtype

    def astype(self, dt):
        ell = None
        if self.ell is not None:
            ell = EllA(self.ell.rowcols, self.ell.rowvals.astype(dt),
                       self.ell.colrows, self.ell.colvals.astype(dt))
        return SparseA(self.rows, self.cols, self.vals.astype(dt),
                       self.perm_csc, self.shape, self.structure, ell)

    def scale(self, E, D):
        """diag(E) @ A @ diag(D) — the Ruiz application; zero-copy on the
        index arrays (the attached structure is sparsity-pattern-only and
        survives scaling; the ELL twin scales its padded values — inert
        zero slots stay zero)."""
        vals = self.vals * E[self.rows] * D[self.cols]
        ell = None
        if self.ell is not None:
            ell = EllA(self.ell.rowcols,
                       self.ell.rowvals * E[:, None] * D[self.ell.rowcols],
                       self.ell.colrows,
                       self.ell.colvals * E[self.ell.colrows] * D[:, None])
        return SparseA(self.rows, self.cols, vals, self.perm_csc,
                       self.shape, self.structure, ell)

    # -- matvecs ----------------------------------------------------------
    def matvec(self, x):
        """A x for x (S, n) -> (S, m).  Gather + sorted segment_sum."""
        g = x[:, self.cols] * self.vals[None, :]
        return jax.ops.segment_sum(
            g.T, self.rows, num_segments=self.shape[0],
            indices_are_sorted=True).T

    def rmatvec(self, y):
        """A' y for y (S, m) -> (S, n)."""
        rows = self.rows[self.perm_csc]
        cols = self.cols[self.perm_csc]
        vals = self.vals[self.perm_csc]
        g = y[:, rows] * vals[None, :]
        return jax.ops.segment_sum(
            g.T, cols, num_segments=self.shape[1],
            indices_are_sorted=True).T

    def row_absmax(self):
        """(m,) per-row max |a_ij| (Ruiz row norms); empty rows give 0
        (segment_max alone yields -inf there)."""
        out = jax.ops.segment_max(
            jnp.abs(self.vals), self.rows, num_segments=self.shape[0],
            indices_are_sorted=True)
        return jnp.maximum(out, 0.0)

    def col_absmax(self):
        """(n,) per-column max |a_ij|; empty columns give 0."""
        vals = jnp.abs(self.vals[self.perm_csc])
        out = jax.ops.segment_max(
            vals, self.cols[self.perm_csc], num_segments=self.shape[1],
            indices_are_sorted=True)
        return jnp.maximum(out, 0.0)

    def todense(self):
        """Dense (m, n) materialization (for factorization programs and
        consumers that need the full matrix; transient inside jit)."""
        return jnp.zeros(self.shape, self.vals.dtype).at[
            self.rows, self.cols].add(self.vals)


def should_sparsify(A_np) -> bool:
    """The shared enablement policy for uploading a shared A as SparseA
    (used by both parallel.sharded.shard_batch and spopt._device_A so the
    rate path and the wheel path always classify a family identically):
    large AND very sparse — small matrices ride the MXU better dense."""
    return A_np.size >= 4e6 and (A_np != 0).mean() < 0.01


def _as_numpy_coo(A):
    """(rows, cols, vals, m, n) from dense ndarray or SparseA."""
    if isinstance(A, SparseA):
        return (np.asarray(A.rows), np.asarray(A.cols),
                np.asarray(A.vals), A.shape[0], A.shape[1])
    A = np.asarray(A)
    rows, cols = np.nonzero(A)
    return rows, cols, A[rows, cols], A.shape[0], A.shape[1]


class KKTStructure(NamedTuple):
    """Host-side (static) description of the block/Woodbury split of
    K = diag + A' R A.  All members are numpy; shipped to the device by
    :func:`tpusppy.solvers.structured_kkt.factor_structured`.

    Variables are grouped into components connected by NARROW rows; wide
    rows form the low-rank coupling.  Components are padded into size
    buckets so each bucket factors as one batched (nb, bs, bs) program.
    """

    narrow_rows: np.ndarray   # (mn,) row ids whose support stays in-block
    wide_rows: np.ndarray     # (r,) row ids in the coupling term
    # per bucket: (block_vars (nb, bs) padded with n [dummy var],
    #             block_rows (nb, mb) padded with m [dummy row])
    buckets: tuple
    n: int
    m: int

    @property
    def r(self):
        return int(self.wide_rows.size)


def detect_structure(A, narrow_k: int = 8, max_block: int = 1024,
                     max_coupling: int = 4096,
                     min_blocks: int = 4) -> KKTStructure | None:
    """Find the block/Woodbury split, or None when the family has no
    usable structure (falls back to the dense explicit inverse).

    ``narrow_k``: rows with more nonzeros than this are coupling rows
    (their quadratic contribution is rank-1 each, handled via Woodbury).
    Union-find over narrow-row supports yields variable components; the
    split is usable when the largest component stays small (batched
    block factorization) and the coupling rank r is moderate (dense
    (r, r) cap solve).
    """
    rows, cols, vals, m, n = _as_numpy_coo(A)
    if rows.size == 0:
        return None
    counts = np.bincount(rows, minlength=m)
    wide_mask = counts > narrow_k
    wide_rows = np.flatnonzero(wide_mask)
    if wide_rows.size > max_coupling:
        return None
    narrow_sel = ~wide_mask[rows]
    nr, nc = rows[narrow_sel], cols[narrow_sel]

    # union-find over narrow-row supports
    parent = np.arange(n)

    def find(a):
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    # link all columns of a narrow row to its first column
    order = np.argsort(nr, kind="stable")
    nr_s, nc_s = nr[order], nc[order]
    starts = np.searchsorted(nr_s, np.unique(nr_s))
    bounds = np.append(starts, nr_s.size)
    for i in range(len(starts)):
        seg = nc_s[bounds[i]:bounds[i + 1]]
        r0 = find(seg[0])
        for c in seg[1:]:
            rc = find(c)
            if rc != r0:
                parent[rc] = r0
    roots = np.array([find(v) for v in range(n)])
    _, comp = np.unique(roots, return_inverse=True)
    n_comp = comp.max() + 1
    sizes = np.bincount(comp, minlength=n_comp)
    if sizes.max() > max_block or n_comp < min_blocks:
        return None

    # narrow-row -> component (all its columns share one, by construction)
    row_comp = np.full(m, -1)
    row_comp[nr] = comp[nc]
    narrow_rows = np.flatnonzero(row_comp >= 0)

    # bucket components by padded size (next power of two, min 8)
    pad = np.maximum(8, 2 ** np.ceil(np.log2(np.maximum(sizes, 1))).astype(int))
    buckets = []
    for bs in np.unique(pad):
        comp_ids = np.flatnonzero(pad == bs)
        nb = comp_ids.size
        bvars = np.full((nb, bs), n, np.int32)        # n = dummy var slot
        rows_per = []
        for j, cid in enumerate(comp_ids):
            vs = np.flatnonzero(comp == cid)
            bvars[j, :vs.size] = vs
            rows_per.append(np.flatnonzero(row_comp == cid))
        mb = max(1, max(r.size for r in rows_per))
        brows = np.full((nb, mb), m, np.int32)        # m = dummy row slot
        for j, rws in enumerate(rows_per):
            brows[j, :rws.size] = rws
        buckets.append((bvars, brows))
    return KKTStructure(narrow_rows=narrow_rows, wide_rows=wide_rows,
                        buckets=tuple(buckets), n=n, m=m)
