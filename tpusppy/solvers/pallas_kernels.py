"""Pallas TPU kernel: fused ADMM sweep block.

The ADMM inner loop is bandwidth-bound: every sweep re-reads the (S, n, n)
K-inverse/K pair and the (S, m, n) constraint matrix from HBM (three to five
matrix passes per sweep).  This kernel runs ``n_sweeps`` sweeps over a block
of scenarios with all matrices resident in VMEM, so HBM sees each matrix once
per kernel call instead of once per sweep — the hot-op fusion the build brief
calls for (SURVEY §7 step 2; the XLA einsum path remains the fallback for
CPU, dense-P, and shapes that exceed the VMEM budget).

All contractions are per-scenario matvecs with tiny n/m (tens), so the VPU
multiply-reduce form ``(M * v[:, None, :]).sum(-1)`` is used rather than MXU
dots (the 128-lane MXU tiles would be mostly padding at these sizes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

# VMEM budget for one scenario block's matrices (bytes).  v5e has ~16 MB of
# scoped VMEM per core, and the measured end-to-end footprint is ~5x the
# naive single-block byte count (Mosaic double-buffers inputs AND outputs
# for the grid pipeline, plus scratch): a block sized to 4.15 MB of
# operands compiled to a 20.7 MB scoped allocation (S=10000, n=11).  3 MB
# keeps the real footprint ~14-15 MB worst case while preserving bs=128 at
# the farmer bench shape (n=44), where the kernel measures 2.0x XLA.
_VMEM_BUDGET = 3 * 1024 * 1024


def sweep_block_size(S, m, n, itemsize=4, precision="highest") -> int:
    """Scenarios per grid step so A/Kinv/K (+vectors) fit in VMEM.

    ``precision="default"`` stores A/At/Kinv in bf16 (half the bytes —
    the mixed-precision sweep mode's VMEM dividend; K stays f32, it is
    the refinement-defect operand)."""
    if precision == "default":
        mat = (m * n + n * n) * 2 + n * n * itemsize
    else:
        mat = (m * n + 2 * n * n) * itemsize
    per_scen = mat + (6 * n + 6 * m) * itemsize
    bs = max(1, _VMEM_BUDGET // max(per_scen, 1))
    return int(min(S, bs))


def _sweeps_kernel(q_ref, A_ref, At_ref, Kinv_ref, K_ref, cl_ref, cu_ref,
                   lb_ref, ub_ref, rho_a_ref, rho_x_ref, x_ref, z_ref,
                   zx_ref, y_ref, yx_ref, Ax_ref, x_out, z_out, zx_out,
                   y_out, yx_out, Ax_out, *, n_sweeps, n_refine, sigma,
                   alpha, m, n, precision):
    """Scenario-on-lanes layout: every tensor is (..., Sb) with the scenario
    block on the 128-lane axis, so each matvec step is a full-width VPU
    multiply-accumulate.  Contractions loop over the LEADING (untiled) dim
    with static Python indices (m, n are small trace-time constants):

      A'(v):  out[j] += A[i, j, :] * v[i, :]   via A (m, n, Sb), loop i<m
      A x:    out[i] += At[j, i, :] * x[j, :]  via At (n, m, Sb), loop j<n
      K^-1 r: sym matrix, loop over rows.

    ``precision``: "default" takes A/At/Kinv in bf16 storage and rounds
    the vector operand of each sweep contraction to bf16 — matching the
    XLA mixed-precision sweep emulation (solvers/precision.py), with the
    refinement defect against the f32 K exact.  Every other mode runs the
    exact f32 path (the VPU has no MXU passes to economize, so "high"
    here is simply full f32 — at least as accurate as bf16x3 asks)."""
    dt = K_ref.dtype
    # matrices stay in their STORAGE dtype (bf16 under "default" — that is
    # the VMEM dividend); upcasts happen per leading-dim slice inside the
    # contraction, so no full f32 copy of A/At/Kinv is ever materialized
    A = A_ref[:]          # (m, n, Sb)
    At = At_ref[:]        # (n, m, Sb)
    Kinv = Kinv_ref[:]    # (n, n, Sb)
    K = K_ref[:]
    q = q_ref[:]          # (n, Sb)
    cl, cu, lb, ub = cl_ref[:], cu_ref[:], lb_ref[:], ub_ref[:]
    rho_a, rho_x = rho_a_ref[:], rho_x_ref[:]
    x, z, zx, y, yx, Ax = (x_ref[:], z_ref[:], zx_ref[:], y_ref[:],
                           yx_ref[:], Ax_ref[:])
    lowered = precision == "default"

    def rnd(v):
        """bf16 input rounding of the vector operand (lowered mode only)."""
        return v.astype(jnp.bfloat16).astype(dt) if lowered else v

    def contract(M, v, rows):
        """out[k, :] = sum_i M[i, k, :] * v[i, :] (loop over leading dim;
        per-slice upcast of bf16-stored matrices)."""
        acc = M[0].astype(dt) * v[0][None, :]
        for i in range(1, rows):
            acc = acc + M[i].astype(dt) * v[i][None, :]
        return acc

    def body(_, carry):
        x, z, zx, y, yx, Ax = carry
        rhs = (sigma * x - q + contract(A, rnd(rho_a * z - y), m)
               + (rho_x * zx - yx))
        xt = contract(Kinv, rnd(rhs), n)      # Kinv symmetric
        for _ in range(n_refine):
            r = rhs - contract(K, xt, n)      # defect: exact f32 K
            xt = xt + contract(Kinv, rnd(r), n)
        Axt = contract(At, rnd(xt), n)
        x_new = alpha * xt + (1 - alpha) * x
        Ax_new = alpha * Axt + (1 - alpha) * Ax

        za_arg = alpha * Axt + (1 - alpha) * z + y / rho_a
        z_new = jnp.clip(za_arg, cl, cu)
        y_new = y + rho_a * (alpha * Axt + (1 - alpha) * z - z_new)

        zx_arg = alpha * xt + (1 - alpha) * zx + yx / rho_x
        zx_new = jnp.clip(zx_arg, lb, ub)
        yx_new = yx + rho_x * (alpha * xt + (1 - alpha) * zx - zx_new)
        return x_new, z_new, zx_new, y_new, yx_new, Ax_new

    x, z, zx, y, yx, Ax = jax.lax.fori_loop(
        0, n_sweeps, body, (x, z, zx, y, yx, Ax))
    x_out[:] = x
    z_out[:] = z
    zx_out[:] = zx
    y_out[:] = y
    yx_out[:] = yx
    Ax_out[:] = Ax


@functools.partial(jax.jit,
                   static_argnames=("n_sweeps", "n_refine", "sigma", "alpha",
                                    "bs", "precision", "interpret"))
def fused_sweeps(q, A, At, Kinv, K, cl, cu, lb, ub, rho_a, rho_x,
                 x, z, zx, y, yx, Ax, n_sweeps, n_refine, sigma, alpha, bs,
                 precision="highest", interpret=False):
    """Run ``n_sweeps`` sweeps; ALL arrays in scenario-last layout
    (m,n,S)/(n,S) etc.  Returns transposed-state (x, z, zx, y, yx, Ax).

    ``precision="default"`` is the mixed-precision sweep mode: pass
    A/At/Kinv in bf16 (callers cast; K stays f32 for exact defects) —
    VMEM per scenario nearly halves, so blocks grow and fewer grid steps
    re-stream HBM.  "high"/"highest" run the exact f32 kernel (see
    ``_sweeps_kernel``).

    ``interpret=True`` runs the kernel through the Pallas interpreter —
    platform-independent, used by the CPU correctness tests
    (tests/test_pallas.py) to pin the kernel to the XLA sweep semantics."""
    m, n, S = A.shape
    grid = ((S + bs - 1) // bs,)

    def spec3(d0, d1):
        return pl.BlockSpec((d0, d1, bs), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)

    def spec2(d0):
        return pl.BlockSpec((d0, bs), lambda i: (0, i),
                            memory_space=pltpu.VMEM)

    kern = functools.partial(_sweeps_kernel, n_sweeps=n_sweeps,
                             n_refine=n_refine, sigma=sigma, alpha=alpha,
                             m=m, n=n, precision=precision)
    dt = K.dtype
    out_shape = [
        jax.ShapeDtypeStruct((n, S), dt),   # x
        jax.ShapeDtypeStruct((m, S), dt),   # z
        jax.ShapeDtypeStruct((n, S), dt),   # zx
        jax.ShapeDtypeStruct((m, S), dt),   # y
        jax.ShapeDtypeStruct((n, S), dt),   # yx
        jax.ShapeDtypeStruct((m, S), dt),   # Ax
    ]
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            spec2(n),            # q
            spec3(m, n),         # A
            spec3(n, m),         # At
            spec3(n, n),         # Kinv
            spec3(n, n),         # K
            spec2(m), spec2(m),  # cl cu
            spec2(n), spec2(n),  # lb ub
            spec2(m), spec2(n),  # rho_a rho_x
            spec2(n), spec2(m), spec2(n), spec2(m), spec2(n),  # x z zx y yx
            spec2(m),            # Ax
        ],
        out_specs=[spec2(n), spec2(m), spec2(n), spec2(m), spec2(n),
                   spec2(m)],
        out_shape=out_shape,
        interpret=interpret,
    )(q, A, At, Kinv, K, cl, cu, lb, ub, rho_a, rho_x, x, z, zx, y, yx, Ax)


def usable(S, m, n, platform=None, P=None, precision="highest") -> int | None:
    """Block size if the fused per-scenario kernel applies, else None.

    ``precision="default"`` widens the applicable range: bf16 matrix
    storage halves the per-scenario VMEM, so larger (m, n) still fit."""
    if not HAVE_PALLAS or P is not None:
        return None
    platform = platform or jax.default_backend()
    if platform != "tpu":
        return None
    budget = sweep_block_size(S, m, n, precision=precision)
    if budget >= S:
        return S          # one block covering the whole (lane) dimension
    # the lane-dim block must be a multiple of 128 (Mosaic tiling); the grid
    # uses ceiling division, so S need not divide evenly
    bs = (budget // 128) * 128
    return bs if bs >= 128 else None


# --------------------------------------------------------------------------
# Fused shared-A sweep kernel (the frozen shared-engine fast path)
# --------------------------------------------------------------------------
#
# The shared-A engine (solvers/shared_admm) keeps ONE (m, n) constraint
# matrix and ONE (n, n) KKT inverse for the whole scenario batch; its sweep
# contractions are genuine (Sb, k) @ (k, j) MXU matmuls — exactly where
# lowered matmul precision pays (1/3/6 bf16 passes per f32 multiply-add).
# This kernel runs a whole ``check_every`` sweep block per call with the
# shared matrices VMEM-resident (constant index_map: Mosaic keeps revisited
# blocks in place) and the scenario block on the SUBLANE axis, and applies
# the precision mode with explicit bf16 operand splits — identical
# semantics under Mosaic and the interpreter, so the CPU parity tests pin
# it to the XLA mixed-precision sweep (solvers/precision.py emulation).


def _prep_mat(M, mode):
    """(M1, M2) bf16 expansion of a matrix for ``mode`` ("highest": the
    matrix itself, no split).  Splits go THROUGH f32 — exactly the
    rounding chain of precision.contract's emulation (and a no-op on the
    f32 arrays real TPU runs carry), so interpret-mode parity with the
    XLA mixed-precision path is exact up to summation order."""
    if mode == "highest":
        return (M, None)
    Mf = M.astype(jnp.float32)
    M1 = Mf.astype(jnp.bfloat16)
    if mode == "default":
        return (M1, None)
    return (M1, (Mf - M1.astype(jnp.float32)).astype(jnp.bfloat16))


def _pdot(u, Msplit, mode, dt, transpose=False):
    """u @ M (or u @ M.T) at ``mode``; u is rounded/split per call, M is
    pre-split by :func:`_prep_mat`."""
    dn = (((1,), (1 if transpose else 0,)), ((), ()))
    d = functools.partial(jax.lax.dot_general, dimension_numbers=dn,
                          preferred_element_type=dt)
    M1, M2 = Msplit
    if mode == "highest":
        return d(u, M1, precision=jax.lax.Precision.HIGHEST)
    uf = u.astype(jnp.float32)
    u1 = uf.astype(jnp.bfloat16)
    if mode == "default":
        return d(u1, M1)
    u2 = (uf - u1.astype(jnp.float32)).astype(jnp.bfloat16)
    return d(u1, M1) + d(u1, M2) + d(u2, M1)


def _shared_sweeps_kernel(q_ref, A_ref, Kinv_ref, K_ref, cl_ref, cu_ref,
                          lb_ref, ub_ref, rho_a_ref, rho_x_ref, dq2_ref,
                          has_ref, gamma_ref, x_ref, z_ref, zx_ref, y_ref,
                          yx_ref, Ax_ref, x_out, z_out, zx_out, y_out,
                          yx_out, Ax_out, *, n_sweeps, n_refine, n_extra,
                          sigma, alpha, precision):
    """One ``n_sweeps`` block of the shared-A frozen sweep (the exact
    semantics of ``shared_admm._core``'s block(): per-scenario gamma
    scaling, dq2 refinement against the exact f32 K with the lax.cond
    extra passes reproduced as a global-``has`` select)."""
    dt = K_ref.dtype
    A = _prep_mat(A_ref[:], precision)          # (m, n)
    Kinv = _prep_mat(Kinv_ref[:], precision)    # (n, n)
    K = K_ref[:]                                # exact, defect operand
    q = q_ref[:]                                # (Sb, n)
    cl, cu, lb, ub = cl_ref[:], cu_ref[:], lb_ref[:], ub_ref[:]
    g = gamma_ref[:]                            # (Sb, 1)
    has = has_ref[0, 0]                         # global any(dq2 != 0)
    dq2 = dq2_ref[:]                            # (Sb, n)
    sigma_s = g * sigma
    rho_a_s = g * rho_a_ref[:]                  # (Sb, m)
    rho_x_s = g * rho_x_ref[:]                  # (Sb, n)
    x, z, zx, y, yx, Ax = (x_ref[:], z_ref[:], zx_ref[:], y_ref[:],
                           yx_ref[:], Ax_ref[:])

    def kdefect(rhs, xt):
        # exact per-scenario system defect at full f32 (the refinement's
        # accuracy anchor — never lowered)
        Kx = jax.lax.dot_general(
            xt, K, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST, preferred_element_type=dt)
        return rhs - (g * Kx + dq2 * xt)

    def body(_, carry):
        x, z, zx, y, yx, Ax = carry
        rhs = (sigma_s * x - q + _pdot(rho_a_s * z - y, A, precision, dt)
               + (rho_x_s * zx - yx))
        xt = _pdot(rhs / g, Kinv, precision, dt)
        for _ in range(n_refine):
            xt = xt + _pdot(kdefect(rhs, xt) / g, Kinv, precision, dt)
        for _ in range(n_extra):
            xt2 = xt + _pdot(kdefect(rhs, xt) / g, Kinv, precision, dt)
            xt = jnp.where(has > 0, xt2, xt)
        Axt = _pdot(xt, A, precision, dt, transpose=True)
        x_new = alpha * xt + (1 - alpha) * x
        Ax_new = alpha * Axt + (1 - alpha) * Ax

        za_arg = alpha * Axt + (1 - alpha) * z + y / rho_a_s
        z_new = jnp.clip(za_arg, cl, cu)
        y_new = y + rho_a_s * (alpha * Axt + (1 - alpha) * z - z_new)

        zx_arg = alpha * xt + (1 - alpha) * zx + yx / rho_x_s
        zx_new = jnp.clip(zx_arg, lb, ub)
        yx_new = yx + rho_x_s * (alpha * xt + (1 - alpha) * zx - zx_new)
        return x_new, z_new, zx_new, y_new, yx_new, Ax_new

    x, z, zx, y, yx, Ax = jax.lax.fori_loop(
        0, n_sweeps, body, (x, z, zx, y, yx, Ax))
    x_out[:] = x
    z_out[:] = z
    zx_out[:] = zx
    y_out[:] = y
    yx_out[:] = yx
    Ax_out[:] = Ax


@functools.partial(jax.jit,
                   static_argnames=("n_sweeps", "n_refine", "n_extra",
                                    "sigma", "alpha", "bs", "precision",
                                    "interpret"))
def fused_sweeps_shared(q, A, Kinv, K, cl, cu, lb, ub, rho_a, rho_x, dq2,
                        has_dq2, gamma, x, z, zx, y, yx, Ax, n_sweeps,
                        n_refine, n_extra, sigma, alpha, bs,
                        precision="highest", interpret=False):
    """``n_sweeps`` shared-A frozen sweeps per call, scenario-blocked on
    the sublane axis.  Shapes: A/Kinv/K shared ((m,n)/(n,n)/(n,n)); rho_a
    (1, m), rho_x (1, n); per-scenario state/bounds (S, m)/(S, n); gamma
    (S, 1); dq2 (S, n); has_dq2 (1, 1) — the traced global
    ``any(dq2 != 0)`` flag that reproduces the XLA path's lax.cond.
    Returns (x, z, zx, y, yx, Ax)."""
    S, n = q.shape
    m = cl.shape[1]
    grid = ((S + bs - 1) // bs,)

    def shared2(d0, d1):
        return pl.BlockSpec((d0, d1), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)

    def scen(d1):
        return pl.BlockSpec((bs, d1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    kern = functools.partial(_shared_sweeps_kernel, n_sweeps=n_sweeps,
                             n_refine=n_refine, n_extra=n_extra,
                             sigma=sigma, alpha=alpha, precision=precision)
    dt = K.dtype
    out_shape = [
        jax.ShapeDtypeStruct((S, n), dt),   # x
        jax.ShapeDtypeStruct((S, m), dt),   # z
        jax.ShapeDtypeStruct((S, n), dt),   # zx
        jax.ShapeDtypeStruct((S, m), dt),   # y
        jax.ShapeDtypeStruct((S, n), dt),   # yx
        jax.ShapeDtypeStruct((S, m), dt),   # Ax
    ]
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            scen(n),             # q
            shared2(m, n),       # A
            shared2(n, n),       # Kinv
            shared2(n, n),       # K
            scen(m), scen(m),    # cl cu
            scen(n), scen(n),    # lb ub
            shared2(1, m),       # rho_a
            shared2(1, n),       # rho_x
            scen(n),             # dq2
            shared2(1, 1),       # has_dq2
            scen(1),             # gamma
            scen(n), scen(m), scen(n), scen(m), scen(n),  # x z zx y yx
            scen(m),             # Ax
        ],
        out_specs=[scen(n), scen(m), scen(n), scen(m), scen(n), scen(m)],
        out_shape=out_shape,
        interpret=interpret,
    )(q, A, Kinv, K, cl, cu, lb, ub, rho_a, rho_x, dq2, has_dq2, gamma,
      x, z, zx, y, yx, Ax)


# --------------------------------------------------------------------------
# Fused SPARSE/structured-KKT shared-A sweep kernel
# --------------------------------------------------------------------------
#
# Extends the fused-sweep coverage to the SparseA engines (gather/
# segment-sum matvecs, dense-Kinv or block/Woodbury x-update) so those
# paths can participate in the fused body (megastep scans included).  The
# constraint matvecs run in padded-ELL form (:class:`~tpusppy.solvers.
# sparse.EllA`): kr/kc static multiply-accumulate steps per matvec, each a
# full-width gather of the scenario block — matching the XLA engine's
# "sparse matvecs are exact VPU work" contract (only the Kinv applies run
# at the lowered precision mode; the refinement defect is matrix-free
# through the ELL arrays at full precision, exactly the
# ``shared_admm._solve_shared_K`` split).  The structured-KKT engine
# participates through a DENSIFIED (n, n) K^-1 operand: at kernel-eligible
# sizes (the shared matrices must fit VMEM) the BlockWoodbury memory
# saving is irrelevant, so the caller materializes ``kinv_apply(bw, I)``
# once per refresh and the kernel stays one code path.


def _ell_mv(cols, vals, x, k):
    """A x in ELL row form: out[:, i] = sum_j vals[i, j] * x[:, cols[i, j]]
    (k static; padded slots are col 0 / val 0 — inert)."""
    acc = jnp.take(x, cols[:, 0], axis=1) * vals[:, 0][None, :]
    for j in range(1, k):
        acc = acc + jnp.take(x, cols[:, j], axis=1) * vals[:, j][None, :]
    return acc


def _sparse_sweeps_kernel(q_ref, rc_ref, rv_ref, cr_ref, cv_ref, Kinv_ref,
                          diagK_ref, cl_ref, cu_ref, lb_ref, ub_ref,
                          rho_a_ref, rho_x_ref, dq2_ref, has_ref,
                          gamma_ref, x_ref, z_ref, zx_ref, y_ref, yx_ref,
                          Ax_ref, x_out, z_out, zx_out, y_out, yx_out,
                          Ax_out, *, n_sweeps, n_refine, n_extra, sigma,
                          alpha, precision):
    """One ``n_sweeps`` block of the sparse shared-A frozen sweep — the
    exact semantics of ``shared_admm._core``'s block() on a SparseA:
    per-scenario gamma scaling, EXACT ELL matvecs, lowered Kinv applies,
    matrix-free dq2 refinement defect with the lax.cond extra passes
    reproduced as a global-``has`` select."""
    dt = Kinv_ref.dtype
    rc, rv = rc_ref[:], rv_ref[:]           # (m, kr)
    cr, cv = cr_ref[:], cv_ref[:]           # (n, kc)
    kr, kc = rc.shape[1], cr.shape[1]
    Kinv = _prep_mat(Kinv_ref[:], precision)
    diagK = diagK_ref[:]                    # (1, n)
    q = q_ref[:]
    cl, cu, lb, ub = cl_ref[:], cu_ref[:], lb_ref[:], ub_ref[:]
    g = gamma_ref[:]                        # (Sb, 1)
    has = has_ref[0, 0]
    dq2 = dq2_ref[:]
    rho_a = rho_a_ref[:]                    # (1, m) shared, unscaled
    rho_a_s = g * rho_a
    rho_x_s = g * rho_x_ref[:]
    sigma_s = g * sigma
    x, z, zx, y, yx, Ax = (x_ref[:], z_ref[:], zx_ref[:], y_ref[:],
                           yx_ref[:], Ax_ref[:])

    def mv(v):                              # A v: (Sb, n) -> (Sb, m)
        return _ell_mv(rc, rv, v, kr)

    def rmv(v):                             # A' v: (Sb, m) -> (Sb, n)
        return _ell_mv(cr, cv, v, kc)

    def kdefect(rhs, xt):
        # exact per-scenario system defect, matrix-free through the ELL
        # arrays at full precision (the refinement's accuracy anchor)
        Kx = xt * diagK + rmv(mv(xt) * rho_a)
        return rhs - (g * Kx + dq2 * xt)

    def body(_, carry):
        x, z, zx, y, yx, Ax = carry
        rhs = (sigma_s * x - q + rmv(rho_a_s * z - y)
               + (rho_x_s * zx - yx))
        xt = _pdot(rhs / g, Kinv, precision, dt)
        for _ in range(n_refine):
            xt = xt + _pdot(kdefect(rhs, xt) / g, Kinv, precision, dt)
        for _ in range(n_extra):
            xt2 = xt + _pdot(kdefect(rhs, xt) / g, Kinv, precision, dt)
            xt = jnp.where(has > 0, xt2, xt)
        Axt = mv(xt)
        x_new = alpha * xt + (1 - alpha) * x
        Ax_new = alpha * Axt + (1 - alpha) * Ax

        za_arg = alpha * Axt + (1 - alpha) * z + y / rho_a_s
        z_new = jnp.clip(za_arg, cl, cu)
        y_new = y + rho_a_s * (alpha * Axt + (1 - alpha) * z - z_new)

        zx_arg = alpha * xt + (1 - alpha) * zx + yx / rho_x_s
        zx_new = jnp.clip(zx_arg, lb, ub)
        yx_new = yx + rho_x_s * (alpha * xt + (1 - alpha) * zx - zx_new)
        return x_new, z_new, zx_new, y_new, yx_new, Ax_new

    x, z, zx, y, yx, Ax = jax.lax.fori_loop(
        0, n_sweeps, body, (x, z, zx, y, yx, Ax))
    x_out[:] = x
    z_out[:] = z
    zx_out[:] = zx
    y_out[:] = y
    yx_out[:] = yx
    Ax_out[:] = Ax


@functools.partial(jax.jit,
                   static_argnames=("n_sweeps", "n_refine", "n_extra",
                                    "sigma", "alpha", "bs", "precision",
                                    "interpret"))
def fused_sweeps_sparse(q, rowcols, rowvals, colrows, colvals, Kinv, diagK,
                        cl, cu, lb, ub, rho_a, rho_x, dq2, has_dq2, gamma,
                        x, z, zx, y, yx, Ax, n_sweeps, n_refine, n_extra,
                        sigma, alpha, bs, precision="highest",
                        interpret=False):
    """``n_sweeps`` sparse shared-A frozen sweeps per call, scenario-
    blocked on the sublane axis.  Shapes: ELL arrays (m, kr)/(n, kc)
    shared; ``Kinv`` (n, n) — the dense shared inverse, or the densified
    BlockWoodbury apply for the structured-KKT engine; ``diagK`` (1, n) =
    q2ref + rho_x + sigma (the matrix-free defect diagonal); ``rho_a``
    (1, m) UNSCALED shared row penalties; everything else as
    :func:`fused_sweeps_shared`.  Returns (x, z, zx, y, yx, Ax)."""
    S, n = q.shape
    m = cl.shape[1]
    kr = rowcols.shape[1]
    kc = colrows.shape[1]
    grid = ((S + bs - 1) // bs,)

    def shared2(d0, d1):
        return pl.BlockSpec((d0, d1), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)

    def scen(d1):
        return pl.BlockSpec((bs, d1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    kern = functools.partial(_sparse_sweeps_kernel, n_sweeps=n_sweeps,
                             n_refine=n_refine, n_extra=n_extra,
                             sigma=sigma, alpha=alpha, precision=precision)
    dt = Kinv.dtype
    out_shape = [
        jax.ShapeDtypeStruct((S, n), dt),   # x
        jax.ShapeDtypeStruct((S, m), dt),   # z
        jax.ShapeDtypeStruct((S, n), dt),   # zx
        jax.ShapeDtypeStruct((S, m), dt),   # y
        jax.ShapeDtypeStruct((S, n), dt),   # yx
        jax.ShapeDtypeStruct((S, m), dt),   # Ax
    ]
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            scen(n),                  # q
            shared2(m, kr), shared2(m, kr),   # rowcols rowvals
            shared2(n, kc), shared2(n, kc),   # colrows colvals
            shared2(n, n),            # Kinv
            shared2(1, n),            # diagK
            scen(m), scen(m),         # cl cu
            scen(n), scen(n),         # lb ub
            shared2(1, m),            # rho_a
            shared2(1, n),            # rho_x
            scen(n),                  # dq2
            shared2(1, 1),            # has_dq2
            scen(1),                  # gamma
            scen(n), scen(m), scen(n), scen(m), scen(n),  # x z zx y yx
            scen(m),                  # Ax
        ],
        out_specs=[scen(n), scen(m), scen(n), scen(m), scen(n), scen(m)],
        out_shape=out_shape,
        interpret=interpret,
    )(q, rowcols, rowvals, colrows, colvals, Kinv, diagK, cl, cu, lb, ub,
      rho_a, rho_x, dq2, has_dq2, gamma, x, z, zx, y, yx, Ax)


def sparse_kernel_possible(platform=None) -> bool:
    """Could :func:`fused_sweeps_sparse` EVER engage in this process:
    Pallas importable + TPU backend + the experimental
    ``TPUSPPY_PALLAS_SPARSE=1`` opt-in.  The ONE engagement gate —
    ``SparseA.from_dense``'s ``ell="auto"`` asks it before paying for the
    ELL twin build, and :func:`usable_sparse` layers the per-shape VMEM
    budget on top."""
    import os

    if not HAVE_PALLAS:
        return False
    platform = platform or jax.default_backend()
    return (platform == "tpu"
            and os.environ.get("TPUSPPY_PALLAS_SPARSE") == "1")


def usable_sparse(S, m, n, kr, kc, platform=None, itemsize=4) -> int | None:
    """Scenario block size if the fused sparse kernel applies, else None.

    EXPERIMENTAL on real TPU: the ELL matvec's lane-axis gathers
    (``jnp.take`` inside the kernel) are not validated against every
    Mosaic version, so the kernel additionally requires the
    ``TPUSPPY_PALLAS_SPARSE=1`` opt-in there; interpret-mode tests pin
    the semantics platform-independently.  The shared operands (densified
    Kinv + ELL arrays) must fit VMEM alongside one scenario block."""
    if not sparse_kernel_possible(platform):
        return None
    from .sparse import ELL_MAX_K
    if max(kr, kc) > ELL_MAX_K:
        return None
    mat = n * n * itemsize + (m * kr + n * kc) * (itemsize + 4) \
        + n * itemsize
    if mat > _VMEM_BUDGET // 2:
        return None
    per_scen = (8 * n + 6 * m + 2) * itemsize
    bs = (_VMEM_BUDGET - mat) // max(per_scen, 1)
    if bs >= S:
        return int(S)
    bs = (bs // 8) * 8
    return int(bs) if bs >= 8 else None


def usable_shared(S, m, n, platform=None, itemsize=4) -> int | None:
    """Scenario block size if the fused shared-A kernel applies, else None.

    The shared matrices (A + Kinv + K) must fit VMEM alongside one
    scenario block's state; the block rides the SUBLANE axis (multiples
    of 8 for f32).  Reference-scale UC (n=16008) exceeds the matrix
    budget by orders of magnitude and correctly declines — the kernel is
    the small/medium-n shared-family fast path."""
    if not HAVE_PALLAS:
        return None
    platform = platform or jax.default_backend()
    if platform != "tpu":
        return None
    mat = (m * n + 2 * n * n) * itemsize
    if mat > _VMEM_BUDGET // 2:
        return None
    per_scen = (6 * n + 6 * m + 2) * itemsize
    bs = (_VMEM_BUDGET - mat) // max(per_scen, 1)
    if bs >= S:
        return int(S)
    bs = (bs // 8) * 8
    return int(bs) if bs >= 8 else None
