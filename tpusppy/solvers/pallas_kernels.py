"""Pallas TPU kernel: fused ADMM sweep block.

The ADMM inner loop is bandwidth-bound: every sweep re-reads the (S, n, n)
K-inverse/K pair and the (S, m, n) constraint matrix from HBM (three to five
matrix passes per sweep).  This kernel runs ``n_sweeps`` sweeps over a block
of scenarios with all matrices resident in VMEM, so HBM sees each matrix once
per kernel call instead of once per sweep — the hot-op fusion the build brief
calls for (SURVEY §7 step 2; the XLA einsum path remains the fallback for
CPU, dense-P, and shapes that exceed the VMEM budget).

All contractions are per-scenario matvecs with tiny n/m (tens), so the VPU
multiply-reduce form ``(M * v[:, None, :]).sum(-1)`` is used rather than MXU
dots (the 128-lane MXU tiles would be mostly padding at these sizes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

# VMEM budget for one scenario block's matrices (bytes).  v5e has ~16 MB of
# scoped VMEM per core, and the measured end-to-end footprint is ~5x the
# naive single-block byte count (Mosaic double-buffers inputs AND outputs
# for the grid pipeline, plus scratch): a block sized to 4.15 MB of
# operands compiled to a 20.7 MB scoped allocation (S=10000, n=11).  3 MB
# keeps the real footprint ~14-15 MB worst case while preserving bs=128 at
# the farmer bench shape (n=44), where the kernel measures 2.0x XLA.
_VMEM_BUDGET = 3 * 1024 * 1024


def sweep_block_size(S, m, n, itemsize=4) -> int:
    """Scenarios per grid step so A/Kinv/K (+vectors) fit in VMEM."""
    per_scen = (m * n + 2 * n * n + 6 * n + 6 * m) * itemsize
    bs = max(1, _VMEM_BUDGET // max(per_scen, 1))
    return int(min(S, bs))


def _sweeps_kernel(q_ref, A_ref, At_ref, Kinv_ref, K_ref, cl_ref, cu_ref,
                   lb_ref, ub_ref, rho_a_ref, rho_x_ref, x_ref, z_ref,
                   zx_ref, y_ref, yx_ref, Ax_ref, x_out, z_out, zx_out,
                   y_out, yx_out, Ax_out, *, n_sweeps, n_refine, sigma,
                   alpha, m, n):
    """Scenario-on-lanes layout: every tensor is (..., Sb) with the scenario
    block on the 128-lane axis, so each matvec step is a full-width VPU
    multiply-accumulate.  Contractions loop over the LEADING (untiled) dim
    with static Python indices (m, n are small trace-time constants):

      A'(v):  out[j] += A[i, j, :] * v[i, :]   via A (m, n, Sb), loop i<m
      A x:    out[i] += At[j, i, :] * x[j, :]  via At (n, m, Sb), loop j<n
      K^-1 r: sym matrix, loop over rows.
    """
    A = A_ref[:]          # (m, n, Sb)
    At = At_ref[:]        # (n, m, Sb)
    Kinv = Kinv_ref[:]    # (n, n, Sb)
    K = K_ref[:]
    q = q_ref[:]          # (n, Sb)
    cl, cu, lb, ub = cl_ref[:], cu_ref[:], lb_ref[:], ub_ref[:]
    rho_a, rho_x = rho_a_ref[:], rho_x_ref[:]
    x, z, zx, y, yx, Ax = (x_ref[:], z_ref[:], zx_ref[:], y_ref[:],
                           yx_ref[:], Ax_ref[:])

    def contract(M, v, rows):
        """out[k, :] = sum_i M[i, k, :] * v[i, :] (loop over leading dim)."""
        acc = M[0] * v[0][None, :]
        for i in range(1, rows):
            acc = acc + M[i] * v[i][None, :]
        return acc

    def body(_, carry):
        x, z, zx, y, yx, Ax = carry
        rhs = (sigma * x - q + contract(A, rho_a * z - y, m)
               + (rho_x * zx - yx))
        xt = contract(Kinv, rhs, n)           # Kinv symmetric
        for _ in range(n_refine):
            r = rhs - contract(K, xt, n)
            xt = xt + contract(Kinv, r, n)
        Axt = contract(At, xt, n)
        x_new = alpha * xt + (1 - alpha) * x
        Ax_new = alpha * Axt + (1 - alpha) * Ax

        za_arg = alpha * Axt + (1 - alpha) * z + y / rho_a
        z_new = jnp.clip(za_arg, cl, cu)
        y_new = y + rho_a * (alpha * Axt + (1 - alpha) * z - z_new)

        zx_arg = alpha * xt + (1 - alpha) * zx + yx / rho_x
        zx_new = jnp.clip(zx_arg, lb, ub)
        yx_new = yx + rho_x * (alpha * xt + (1 - alpha) * zx - zx_new)
        return x_new, z_new, zx_new, y_new, yx_new, Ax_new

    x, z, zx, y, yx, Ax = jax.lax.fori_loop(
        0, n_sweeps, body, (x, z, zx, y, yx, Ax))
    x_out[:] = x
    z_out[:] = z
    zx_out[:] = zx
    y_out[:] = y
    yx_out[:] = yx
    Ax_out[:] = Ax


@functools.partial(jax.jit,
                   static_argnames=("n_sweeps", "n_refine", "sigma", "alpha",
                                    "bs", "interpret"))
def fused_sweeps(q, A, At, Kinv, K, cl, cu, lb, ub, rho_a, rho_x,
                 x, z, zx, y, yx, Ax, n_sweeps, n_refine, sigma, alpha, bs,
                 interpret=False):
    """Run ``n_sweeps`` sweeps; ALL arrays in scenario-last layout
    (m,n,S)/(n,S) etc.  Returns transposed-state (x, z, zx, y, yx, Ax).

    ``interpret=True`` runs the kernel through the Pallas interpreter —
    platform-independent, used by the CPU correctness tests
    (tests/test_pallas.py) to pin the kernel to the XLA sweep semantics."""
    m, n, S = A.shape
    grid = ((S + bs - 1) // bs,)

    def spec3(d0, d1):
        return pl.BlockSpec((d0, d1, bs), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)

    def spec2(d0):
        return pl.BlockSpec((d0, bs), lambda i: (0, i),
                            memory_space=pltpu.VMEM)

    kern = functools.partial(_sweeps_kernel, n_sweeps=n_sweeps,
                             n_refine=n_refine, sigma=sigma, alpha=alpha,
                             m=m, n=n)
    dt = A.dtype
    out_shape = [
        jax.ShapeDtypeStruct((n, S), dt),   # x
        jax.ShapeDtypeStruct((m, S), dt),   # z
        jax.ShapeDtypeStruct((n, S), dt),   # zx
        jax.ShapeDtypeStruct((m, S), dt),   # y
        jax.ShapeDtypeStruct((n, S), dt),   # yx
        jax.ShapeDtypeStruct((m, S), dt),   # Ax
    ]
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            spec2(n),            # q
            spec3(m, n),         # A
            spec3(n, m),         # At
            spec3(n, n),         # Kinv
            spec3(n, n),         # K
            spec2(m), spec2(m),  # cl cu
            spec2(n), spec2(n),  # lb ub
            spec2(m), spec2(n),  # rho_a rho_x
            spec2(n), spec2(m), spec2(n), spec2(m), spec2(n),  # x z zx y yx
            spec2(m),            # Ax
        ],
        out_specs=[spec2(n), spec2(m), spec2(n), spec2(m), spec2(n),
                   spec2(m)],
        out_shape=out_shape,
        interpret=interpret,
    )(q, A, At, Kinv, K, cl, cu, lb, ub, rho_a, rho_x, x, z, zx, y, yx, Ax)


def usable(S, m, n, platform=None, P=None) -> int | None:
    """Block size if the fused kernel applies, else None."""
    if not HAVE_PALLAS or P is not None:
        return None
    platform = platform or jax.default_backend()
    if platform != "tpu":
        return None
    budget = sweep_block_size(S, m, n)
    if budget >= S:
        return S          # one block covering the whole (lane) dimension
    # the lane-dim block must be a multiple of 128 (Mosaic tiling); the grid
    # uses ceiling division, so S need not divide evenly
    bs = (budget // 128) * 128
    return bs if bs >= 128 else None
