"""Watchdog-safe segmented solve wrappers.

The remote TPU worker kills any single program execution around ~60 s
(measured: a synthetic 110 s matmul loop dies at 62 s with "TPU worker
process crashed or restarted"), so solves whose sweep loops would run
longer must be split into bounded segments re-entered from the host.  The
frozen-factor protocol makes continuation free: factors are computed once,
segments warm-start from the previous raw iterate.

Two consumers share this module: the scenario-sharded jitted PH step
(:mod:`tpusppy.parallel.sharded`) and the host solve loop
(:meth:`tpusppy.spopt.SPOpt._solve_amortized` — the path every cylinder in
a wheel runs).  Shapes that fit one dispatch pass through unchanged.

Reference context: the reference's per-rank Gurobi solves
(``mpisppy/spopt.py:85-223``) have no analogue of this constraint — the
solver runs on the host.  On TPU the solve IS a device program, so dispatch
length becomes a correctness concern, not a tuning knob.
"""

from __future__ import annotations

import collections
import dataclasses

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from . import flops as flops_model
from . import hostsync

# Per-dispatch budget: must stay well under the remote worker's ~60 s
# execution kill, but long enough that the solver's IN-LOOP plateau exit
# (earliest at 3 x sweep_plateau_window = 96 sweeps) can fire inside one
# dispatch — at 18 s the reference-UC S=1000 segments capped at 52 sweeps
# and the in-loop exit could never trigger, wasting 2 whole continuation
# dispatches proving the plateau at host granularity.  30 s x the model's
# built-in overestimate (~1.5x vs measured sweep times) lands actual
# dispatches around 20-30 s: 2x margin under the watchdog.
_DISPATCH_TARGET_SECS = 30.0
# effective sweep throughput on the model's (n^2 + 2nm) flop accounting
# under matmul precision "highest" (bf16x6): measured 6.9-7.7e12 flop/s at
# reference-UC shapes on v5e (48.8 ms/sweep at S=256, n=16008, m=12408,
# solve_refine=2); 6e12 keeps ~15% conservatism
_DISPATCH_EFF_FLOPS = 6e12
# the 6.9-7.7e12 evidence is all SHARED-A shapes; the per-scenario dense
# path (batched small factorizations, factor_batch=S) has no measured
# sweep times at watchdog-relevant scale, so it keeps the pre-raise
# conservative constant — dispatch_segments clamps to this when
# factor_batch > 1
_DISPATCH_EFF_FLOPS_DENSE = 4e12


def _frozen_refine_iters(st):
    """Worst-case full-precision refinement sweeps a LOWERED frozen solve
    appends inside one dispatch (0 for full-precision settings)."""
    if st.sweep_precision in (None, "highest"):
        return 0
    return max(0, int(st.precision_refine_iters))


def _frozen_iter_secs(st, t_sweep):
    """Worst-case seconds of ONE frozen iteration: the full ``max_iter``
    sweep budget at the (possibly lowered) sweep precision, plus the
    in-dispatch f32 refinement phase a lowered mode appends.  The ONE
    expression the fused-iteration budget and the megastep watchdog cap
    must share — they are two views of the same worker-kill worst case."""
    return (st.max_iter * t_sweep
            / flops_model.sweep_speedup(st.sweep_precision)
            + _frozen_refine_iters(st) * t_sweep)


def seg_settings(settings, seg_iter):
    """Per-dispatch settings for one segment: the sweep cap, plus — for
    lowered sweep modes — the in-dispatch f32 refinement budget clamped
    to the same cap, so one dispatch can never embed a refinement phase
    larger than the watchdog-sized segment itself (dispatch_segments
    bills exactly this worst case)."""
    kw = {"max_iter": seg_iter}
    if _frozen_refine_iters(settings) > seg_iter:
        kw["precision_refine_iters"] = seg_iter
    return dataclasses.replace(settings, **kw)


def _dense_clamped_eff(eff_flops, factor_batch):
    """Default throughput, dense-clamped.  An EXPLICIT eff_flops stays
    authoritative (callers/tests monkeypatch the module constants to force
    dispatch regimes); only the defaults get the per-scenario-dense clamp."""
    if eff_flops is not None:
        return eff_flops
    if factor_batch > 1:
        return min(_DISPATCH_EFF_FLOPS, _DISPATCH_EFF_FLOPS_DENSE)
    return _DISPATCH_EFF_FLOPS


def dispatch_segments(S, n, m, st, factor_batch=1,
                      eff_flops=None, target_secs=None,
                      sparse_factor=1.0):
    """(seg_refresh, seg_frozen): per-dispatch sweep caps for these shapes.

    ``S`` is the PER-DEVICE scenario count (mesh callers divide by the mesh
    size); ``factor_batch`` is how many factorizations one adaptive solve
    performs per restart (the scenario count for dense per-scenario A, 1
    for the shared-A engine).  Returns (max_iter, max_iter) — i.e. "don't
    segment" — when the whole solve fits one dispatch under the worker
    watchdog.

    Floors: rho adaptation on fewer than ~32 sweeps of residual evidence
    misadapts (restart ratios are meaningless at cold residuals), and a
    frozen segment must exceed one check interval or a converged batch
    (which always burns its first ``check_every`` sweeps) is
    indistinguishable from an unconverged one.

    Pipelined continuations (:func:`continue_frozen` with speculation)
    need NO extra headroom here: a speculative segment is its own device
    program under exactly these caps — the worker watchdog is
    per-EXECUTION, and queued programs each get their own budget — and
    its sweeps are billed against the continuation budget at dispatch
    time, so the total dispatched work (the waste included, modeled by
    :func:`..flops.speculation_flops`) never exceeds the serial worst
    case of ``refresh_budget``/``max_iter`` sweeps.
    """
    eff = _dense_clamped_eff(eff_flops, factor_batch)
    target = _DISPATCH_TARGET_SECS if target_secs is None else target_secs
    ce = max(1, st.check_every)
    # ``sparse_factor``: scale applied by SparseA callers — sweeps there
    # replace the dense n^2/nm matmuls with gather/segment-sum matvecs and
    # the block/Woodbury x-update (measured 2-4x cheaper than the dense
    # accounting at reference-UC shapes; 0.25 keeps dispatches inside the
    # watchdog with the same 2x margin).  Flop accounting lives in
    # solvers/flops.py (shared with the autotuner + MFU reporting).
    t_sweep = flops_model.sweep_flops(S, n, m, sparse_factor) / eff
    # frozen sweeps run at the (possibly lowered) sweep precision —
    # conservatively faster (flops.SWEEP_SPEEDUP), so frozen dispatches may
    # carry more sweeps; refresh solves always run full precision.  A
    # lowered frozen dispatch also carries an in-dispatch f32 refinement
    # phase, which :func:`seg_settings` clamps to the SEGMENT cap — so the
    # worst case per lowered frozen sweep is one lowered sweep plus one
    # full-precision refinement sweep, billed jointly here (a flat
    # subtraction of the unclamped refine budget can go negative at
    # reference-UC sweep costs, which would break the watchdog bound the
    # sizing exists for).
    t_sweep_f = t_sweep / flops_model.sweep_speedup(st.sweep_precision)
    if _frozen_refine_iters(st) > 0:
        t_sweep_f = t_sweep_f + t_sweep
    t_factor = flops_model.factor_flops(n, m, factor_batch,
                                        sparse_factor) / eff
    rst = max(1, st.restarts)

    def _cap(budget_secs, floor, ts):
        raw = budget_secs / max(ts, 1e-12)
        return int(max(min(floor, st.max_iter),
                       min(st.max_iter, ce * int(raw / ce))))

    seg_r = _cap(target / rst - t_factor, 32, t_sweep)
    seg_f = _cap(target, 2 * ce, t_sweep_f)
    return seg_r, seg_f


def fused_iteration_budget(S, n, m, st, refresh_every, factor_batch=1,
                           eff_flops=None, target_secs=None,
                           sparse_factor=1.0):
    """Max PH iterations fusable into ONE device program (multiple of
    ``refresh_every``; 0 = don't fuse — the shape needs segmentation).

    Worst-case accounting on the :func:`dispatch_segments` flop model: every
    frozen iteration burns its full ``max_iter`` sweep budget (the
    while_loop usually exits earlier — this is the safety bound, not the
    expectation), every refresh runs ``restarts`` adaptation rounds plus the
    factorizations.  One block = 1 refresh + (refresh_every-1) frozen
    iterations; as many whole blocks as fit ``target_secs``.
    """
    eff = _dense_clamped_eff(eff_flops, factor_batch)
    target = _DISPATCH_TARGET_SECS if target_secs is None else target_secs
    t_sweep = flops_model.sweep_flops(S, n, m, sparse_factor) / eff
    t_factor = flops_model.factor_flops(n, m, factor_batch,
                                        sparse_factor) / eff
    rst = max(1, st.restarts)
    t_frozen_iter = _frozen_iter_secs(st, t_sweep)
    # the adaptive solve factorizes once PER RESTART (admm._solve_scaled's
    # restart scan calls _factor each round), matching dispatch_segments'
    # per-restart budget accounting
    t_refresh_iter = rst * (st.max_iter * t_sweep + t_factor)
    t_block = t_refresh_iter + (refresh_every - 1) * t_frozen_iter
    return int(target / max(t_block, 1e-12)) * refresh_every


def megastep_cap(S, n, m, st, eff_flops=None, target_secs=None,
                 factor_batch=1, sparse_factor=1.0, bound_pass=False):
    """Max wheel iterations ONE megastep dispatch may carry for these
    shapes under the worker watchdog (0 or 1 = don't megastep: the shape
    is in the segmentation regime, or barely fits one iteration).

    A megastep is N iterations of work inside a single device program, so
    the per-dispatch kill budget must scale with N: the cap is sized on
    the same worst-case flop model as :func:`dispatch_segments` — every
    frozen iteration billed at its full ``max_iter`` sweep budget, plus
    the in-dispatch f32 refinement phase a lowered sweep mode appends —
    against the same ``target_secs`` watchdog budget.  The in-scan
    early-exit mask never shrinks the worst case (a masked iteration does
    no sweeps, but the cap must hold when nothing converges).

    ``bound_pass`` (in-wheel certification, doc/pipeline.md): the
    dispatch may end with the fused bound pass — worst-cased at one extra
    frozen iteration PER EVALUATION (the xhat frozen evaluation's full
    sweep budget; the dual-objective contraction is a rounding error next
    to it) — so that many frozen-iteration budgets are reserved out of
    the watchdog window.  ``True`` reserves 1 (the legacy single-
    candidate pass); an int reserves that many (the batched integer
    sweep reserves its C candidate evaluations + 1 reduced-cost
    re-solve, doc/integer.md).
    """
    eff = _dense_clamped_eff(eff_flops, factor_batch)
    target = _DISPATCH_TARGET_SECS if target_secs is None else target_secs
    t_sweep = flops_model.sweep_flops(S, n, m, sparse_factor) / eff
    t_iter = _frozen_iter_secs(st, t_sweep)
    if bound_pass:
        target = max(target - int(bound_pass) * t_iter, 0.0)
    return int(target / max(t_iter, 1e-12))


def megastep_cap_multi(shapes, st, eff_flops=None, target_secs=None,
                       bound_pass=False):
    """Watchdog cap for a BUCKETED megastep: one scan step runs EVERY
    bucket's frozen sweep back to back inside the same program, so the
    per-iteration worst case is the SUM over buckets of the homogeneous
    :func:`megastep_cap` accounting.  ``shapes`` is
    ``[(S_b, n_b, m_b[, factor_batch_b[, sparse_factor_b]]), ...]``.
    ``bound_pass`` reserves cross-bucket frozen-iteration budgets for
    the fused bound pass — ``True`` = 1, an int = that many evaluations
    (the batched integer sweep; see :func:`megastep_cap`)."""
    target = _DISPATCH_TARGET_SECS if target_secs is None else target_secs
    total = 0.0
    for shp in shapes:
        S, n, m = shp[0], shp[1], shp[2]
        fb = shp[3] if len(shp) > 3 else 1
        sf = shp[4] if len(shp) > 4 else 1.0
        eff = _dense_clamped_eff(eff_flops, fb)
        t_sweep = flops_model.sweep_flops(S, n, m, sf) / eff
        total += _frozen_iter_secs(st, t_sweep)
    if bound_pass:
        target = max(target - int(bound_pass) * total, 0.0)
    return int(target / max(total, 1e-12))


def bill_megastep(S, n, m, n_iters, sweeps, sparse_factor=1.0,
                  rejected_sweeps=None, count_dispatch=True):
    """Bill one EXECUTED megastep into the metrics registry.

    ``n_iters`` is the number of wheel iterations the dispatch ACCEPTED
    (the packed measurement's stop counter — iterations the early-exit
    mask skipped did no sweeps and are NOT billed; a watchdog- or
    window-capped megastep likewise bills only what was dispatched);
    ``sweeps`` is the mean measured ADMM sweep count per iteration.
    ``rejected_sweeps``: the sweep count of an iterate the in-scan
    acceptance test DISCARDED (refresh_hit) — real dispatched work whose
    result was dropped, billed into ``dispatch.flops`` and counted under
    ``megastep.rejected_iterations`` but never into
    ``dispatch.mega_iterations`` (it is not a fused PH iteration).

    ``count_dispatch=False``: bill the FLOPS only — the bucketed
    megakernel calls this once per bucket (each bucket's own shapes) but
    the window is ONE dispatch of ``n_iters`` fused PH iterations, so
    only the first bucket's call counts toward the dispatch counters."""
    if count_dispatch:
        _metrics.inc("dispatch.megasteps")
        _metrics.inc("dispatch.mega_iterations", int(n_iters))
    fl = flops_model.megastep_flops(S, n, m, n_iters, sweeps, sparse_factor)
    if rejected_sweeps is not None:
        if count_dispatch:
            _metrics.inc("megastep.rejected_iterations")
        fl += flops_model.megastep_flops(S, n, m, 1, rejected_sweeps,
                                         sparse_factor)
    if fl:
        _metrics.inc("dispatch.flops", fl)
    if _trace.enabled():
        _trace.instant("dispatch", "megastep", S=S, n=n, m=m,
                       iters=int(n_iters), sweeps=float(sweeps))
    return fl


def bill_bound_pass(S, n, m, sweeps, sparse_factor=1.0,
                    count_pass=True, n_evals=1):
    """Bill one EXECUTED in-wheel bound pass (doc/pipeline.md "In-wheel
    certification"): the xhat-at-xbar frozen evaluation's measured
    ``sweeps`` plus the Lagrangian dual-objective contraction, at this
    shape, into ``dispatch.flops`` — dispatched work inside the megastep
    window that is certification, not PH iterations, so it never inflates
    ``dispatch.mega_iterations``.  ``count_pass=False``: FLOPS only (the
    bucketed kernel bills per bucket but the window ran ONE pass).
    ``n_evals``: frozen evaluations in the pass (the batched integer
    sweep runs C candidates + 1 reduced-cost re-solve, doc/integer.md)."""
    if count_pass:
        _metrics.inc("megastep.bound_passes")
    fl = flops_model.bound_pass_flops(S, n, m, sweeps, sparse_factor,
                                      n_evals=n_evals)
    if fl:
        _metrics.inc("dispatch.flops", fl)
    if _trace.enabled():
        _trace.instant("dispatch", "bound_pass", S=S, n=n, m=m,
                       sweeps=float(sweeps))
    return fl


# measured 2-4x cheaper sweeps on the SparseA/block-Woodbury path vs the
# dense flop accounting at reference-UC shapes; 0.25 keeps worst-case
# dispatches inside the worker watchdog with the same 2x margin (see
# dispatch_segments) — single source, reused by parallel.sharded
SPARSE_DISPATCH_FACTOR = 0.25


def _sparse_factor(args):
    """SPARSE_DISPATCH_FACTOR for SparseA solves, else 1."""
    from .sparse import SparseA
    return SPARSE_DISPATCH_FACTOR if isinstance(args[2], SparseA) else 1.0


def _shapes(args, shared):
    q, q2, A = args[0], args[1], args[2]
    S, n = np.shape(q)
    # A.shape works for numpy/jax arrays AND SparseA (np.shape would try
    # to materialize the latter)
    m = A.shape[0] if shared else A.shape[1]
    return S, n, m


def _seg_flops(args, shared, seg_f):
    """Model flops of ONE frozen segment — the speculation billing unit
    (``flops.sweep_flops`` x the segment's sweep cap)."""
    S, n, m = _shapes(args, shared)
    return flops_model.sweep_flops(S, n, m, _sparse_factor(args)) * seg_f


def _segmenting_events(S, n, m, seg_r, seg_f):
    """Observability of a watchdog-driven segmentation decision: the
    per-dispatch sweep caps this shape was sized to (the worker kills
    ~60s+ executions — these caps ARE the watchdog posture)."""
    _metrics.inc("dispatch.segmented_solves")
    if _trace.enabled():
        _trace.instant("dispatch", "watchdog_caps", S=S, n=n, m=m,
                       seg_refresh=seg_r, seg_frozen=seg_f)


def refresh_budget(settings, seg_r):
    """Sweep budget left for frozen continuations after a segmented
    adaptive dispatch (which ran ``restarts`` rounds of ``seg_r``)."""
    rst = max(1, settings.restarts)
    return rst * settings.max_iter - rst * seg_r


# ---------------------------------------------------------------------------
# Pipelined continuation policy.  Per-shape verdicts measured by
# tpusppy.tune.autotune_pipeline land here: tiny shapes whose segment is
# cheaper than a stop-stats RPC gain nothing from speculation (the fetch
# dominates wall time either way) and are disabled.  Unmeasured shapes
# default to speculating — the waste is bounded at ``overlap`` segments
# per solve and billed against the sweep budget (see continue_frozen).
# ---------------------------------------------------------------------------
_PIPELINE_POLICY: dict = {}


def _policy_key(S, n, m):
    return (int(S), int(n), int(m))


def set_pipeline_policy(S, n, m, enabled: bool):
    """Record a measured per-shape speculation verdict (tune stage)."""
    _PIPELINE_POLICY[_policy_key(S, n, m)] = bool(enabled)


def pipeline_enabled(settings, S, n, m) -> bool:
    """Whether the segmented continuation for these shapes may speculate:
    the ``pipeline`` setting (the ``admm_pipeline`` config flag) is the
    hard off-switch; under it, a measured per-shape verdict wins, and
    unmeasured shapes speculate."""
    if not getattr(settings, "pipeline", True):
        return False
    return _PIPELINE_POLICY.get(_policy_key(S, n, m), True)


def continue_frozen(run_segment, sol, seg_f, budget, all_done=None,
                    plateau_rtol=None, pipeline=False, overlap=1,
                    check_incoming=False, seg_flops=None):
    """Generic frozen-continuation loop shared by the host solve path and
    the jitted sharded PH step: re-dispatch ``run_segment(warm)`` until
    converged, plateaued, or the sweep budget is spent.

    ``all_done(sol)`` decides whether to STOP DISPATCHING; the default
    reads the iteration counter — the while_loop leaves before its cap
    when every scenario met eps OR the in-loop plateau exit fired
    (``sweep_plateau_rtol``), and in both cases further dispatches are
    pointless.  It is a stop signal, NOT a convergence signal: use
    ``BatchSolution.done`` for convergence.  Multi-controller callers
    MUST pass a deterministic ``all_done`` (e.g. ``lambda sol: False``)
    and ``plateau_rtol=None``: both defaults
    fetch scenario-sharded data, which is impossible for non-addressable
    shards — and even a local-shard check would let processes disagree on
    the loop count and deadlock the collective dispatches.

    ``plateau_rtol``: stop when a whole extra segment improved the worst
    scaled residual by less than this fraction — further sweeps are futile
    (first-order UC batches park around 5e-2 at ANY budget; the host
    path's rescue-tolerance ladder already embraces exactly this).

    With the default ``all_done`` (None), the per-segment host decision
    reads ONE fetched 4-vector (:func:`..admm.stop_stats`: iters + worst
    residuals) instead of three separate array fetches — per-segment host
    syncs are serial RPCs over the remote tunnel, and the segmented UC
    path pays them every dispatch.  A caller-provided ``all_done`` keeps
    the legacy separate-fetch protocol (and NEVER speculates — the same
    restriction as the deterministic multi-controller schedules).

    ``pipeline=True`` (single-controller, default ``all_done`` only)
    overlaps the host decision with device compute: segment k+1 is
    dispatched from segment k's device-resident raw iterate BEFORE
    segment k's stop-stats are fetched, so the fetch RPC resolves while
    k+1 runs.  The stop-stats program for each segment is dispatched
    immediately after the segment itself (ahead of its successor), so
    its value is ready the moment the segment finishes and the host read
    never waits on speculative work.  If the verdict says "stop", the
    in-flight speculative segments are DISCARDED — pure-functional state
    makes this safe, and the result is identical to the serial protocol
    on the same stop decisions (the parity tests pin this).  Waste is
    bounded at ``overlap`` segments per continuation and BILLED: the
    sweep budget is charged at dispatch time, so the total dispatched
    work never exceeds the serial worst case (budget exhaustion) and no
    single dispatch grows — every speculative segment is its own device
    program under the same ``dispatch_segments`` watchdog cap.

    ``seg_flops`` (optional): model flops of ONE segment, used to bill
    dispatched/speculated/discarded work into the metrics registry
    (``dispatch.flops``, ``speculation.flops``,
    ``speculation.discarded_flops`` — doc/observability.md); segment
    counts are billed regardless.

    ``check_incoming=True`` additionally evaluates the INCOMING
    solution's stats first and returns it untouched when it already says
    stop (the first-frozen-dispatch test previously inlined in
    :func:`solve_frozen_segmented`).  The pipelined protocol reads this
    verdict BEFORE its first speculative dispatch: the stats value is
    already complete so the fetch costs exactly what serial pays, and
    the steady-state hot case — a warm frozen solve converged in its
    first dispatch, every PH iteration — then wastes nothing; later
    segments' verdicts are the ones worth overlapping.
    """
    from . import admm as _admm

    def _worst(s):
        return max(float(hostsync.fetch(s.pri_res).max()),
                   float(hostsync.fetch(s.dua_res).max()))

    if all_done is None:
        def _stats_launch(s):
            """Dispatch the (tiny) stop-stats program for a real pytree
            BatchSolution; scripted stand-ins (tests) carry their stats as
            plain attributes and need no device program."""
            if isinstance(s, _admm.BatchSolution):
                return _admm.stop_stats(s)
            return None

        def _stats_read(s, dev, overlapped=False):
            """(stop_dispatching, worst_residual) — ONE host fetch.  The
            eps vote catches solves whose iteration counter includes a
            refinement phase (mixed precision) on top of a capped sweep
            phase."""
            if dev is not None:
                st = hostsync.fetch(dev, overlapped=overlapped)
                stop = int(st[0]) < seg_f or bool(st[3])
                return stop, max(float(st[1]), float(st[2]))
            stop = int(hostsync.fetch(
                s.iters, overlapped=overlapped).max()) < seg_f
            return stop, _worst(s)
    else:
        pipeline = False      # legacy protocol: deterministic schedules
        # (multi-controller) and custom stop functions must not speculate

        def _stats_launch(s):
            return None

        def _stats_read(s, dev, overlapped=False):
            return all_done(s), _worst(s) if plateau_rtol else None

    if pipeline and overlap >= 1:
        return _continue_frozen_pipelined(
            run_segment, sol, seg_f, budget, _stats_launch, _stats_read,
            plateau_rtol, check_incoming, overlap, seg_flops)

    # ---- serial protocol --------------------------------------------------
    if check_incoming:
        done, worst = _stats_read(sol, _stats_launch(sol))
        if done:
            return sol
        best = worst if plateau_rtol else None
    else:
        # best is seeded from the INCOMING iterate so an already-parked
        # batch exits quickly
        best = _worst(sol) if plateau_rtol else None
    # two consecutive non-improving segments are required so a transient
    # residual uptick (ADMM is not monotone segment-to-segment) cannot
    # abort a budget that was still making progress
    stall = 0
    while budget > 0:
        # payload attach is guarded so the disabled path builds no dict
        # (the module contract: hot sites stay allocation-free when off)
        with _trace.span("dispatch", "segment") as _sp:
            if _trace.enabled():
                _sp.add(seg_f=seg_f)
            sol = run_segment(sol.raw)
        _metrics.inc("dispatch.segments")
        if seg_flops:
            _metrics.inc("dispatch.flops", seg_flops)
        budget -= seg_f
        done, worst = _stats_read(sol, _stats_launch(sol))
        if done:
            break
        if plateau_rtol:
            if worst > (1.0 - plateau_rtol) * best:
                stall += 1
                if stall >= 2:
                    break
            else:
                stall = 0
            best = min(best, worst)
    return sol


def _continue_frozen_pipelined(run_segment, sol, seg_f, budget,
                               stats_launch, stats_read, plateau_rtol,
                               check_incoming, overlap, seg_flops=None):
    """Speculative variant of the continuation loop (see
    :func:`continue_frozen`).  Dispatch order per segment is
    segment → its stop-stats program → successor segment, so each stats
    vector is computed before any speculative work and the host fetch of
    segment k's verdict overlaps segment k+1's execution."""
    pend = collections.deque()    # (candidate, stats_device) to validate

    def _fill(newest, newest_read=False):
        """Dispatch speculative segments from the newest iterate until the
        pipeline is ``overlap`` deep or the budget is spent.  The budget
        is charged at DISPATCH time: a discarded segment is still paid
        for, so the total dispatched work can never exceed the serial
        worst case.

        Speculation billing: a dispatch is speculative iff its SOURCE
        iterate's stop verdict is unread at dispatch time — entries on
        ``pend`` always are, and ``newest`` is unless the caller just
        read it (``newest_read``; only the check-incoming seed).  At the
        production ``overlap=1`` every steady-state dispatch launches
        from the just-popped candidate BEFORE its verdict fetch — that
        is the overlap, and it is speculative."""
        nonlocal budget
        while len(pend) < overlap and budget > 0:
            speculative = bool(pend) or not newest_read
            src = pend[-1][0] if pend else newest
            with _trace.span("dispatch", "segment") as _sp:
                if _trace.enabled():
                    _sp.add(seg_f=seg_f, speculative=speculative)
                cand = run_segment(src.raw)
            _metrics.inc("dispatch.segments")
            if seg_flops:
                _metrics.inc("dispatch.flops", seg_flops)
            if speculative:
                _metrics.inc("speculation.segments")
                if seg_flops:
                    _metrics.inc("speculation.flops", seg_flops)
            budget -= seg_f
            pend.append((cand, stats_launch(cand)))

    def _discard():
        """Bill the in-flight speculative segments a stop verdict just
        invalidated (the work was dispatched and paid for — the billing
        contract — but its results are dropped)."""
        if not pend:
            return
        _metrics.inc("speculation.discarded_segments", len(pend))
        if seg_flops:
            _metrics.inc("speculation.discarded_flops",
                         len(pend) * seg_flops)
        if _trace.enabled():
            _trace.instant("dispatch", "speculation_discard",
                           segments=len(pend))

    # the incoming iterate's stats are launched BEFORE any speculative
    # dispatch (the stats program must not queue behind one)
    seed_dev = (stats_launch(sol)
                if (check_incoming or plateau_rtol) else None)
    if check_incoming:
        # read the incoming verdict FIRST: its device value is already
        # complete, so this costs exactly the serial protocol's fetch —
        # and the steady-state hot case (a warm frozen solve converged in
        # its first dispatch, every PH iteration) then dispatches NOTHING
        # instead of burning a discarded segment per solve.  Speculation
        # starts only once the continuation is confirmed live.
        done, worst = stats_read(sol, seed_dev)
        if done:
            return sol
        best = worst if plateau_rtol else None
        _fill(sol, newest_read=True)   # seed verdict just read: confirmed
    else:
        # the first dispatch from the incoming iterate is MANDATORY work
        # the serial protocol performs identically (it has no incoming
        # verdict to read either) — billing it as speculation would
        # overstate the pipeline's waste vs serial
        _fill(sol, newest_read=True)
        best = (stats_read(sol, seed_dev, overlapped=bool(pend))[1]
                if plateau_rtol else None)
    stall = 0
    cur = sol
    while pend:
        cand, sdev = pend.popleft()
        _fill(cand)
        cur = cand
        if not pend:
            # budget exhausted and nothing speculative in flight: the
            # verdict cannot change what is returned — skip the fetch
            break
        done, worst = stats_read(cand, sdev, overlapped=True)
        if done:
            _discard()            # in-flight speculation discarded
            break
        if plateau_rtol:
            if worst > (1.0 - plateau_rtol) * best:
                stall += 1
                if stall >= 2:
                    _discard()
                    break
            else:
                stall = 0
            best = min(best, worst)
    return cur


def _continue_frozen(frozen_fn, args, factors, sol, st_f, seg_f, budget,
                     pipeline=False, check_incoming=False, seg_flops=None,
                     **kw):
    """Host-path adapter for :func:`continue_frozen`."""
    return continue_frozen(
        lambda warm: frozen_fn(*args, factors, settings=st_f, warm=warm,
                               **kw),
        sol, seg_f, budget,
        plateau_rtol=st_f.segment_plateau_rtol, pipeline=pipeline,
        check_incoming=check_incoming, seg_flops=seg_flops)


def solve_factored_segmented(frozen_fn, factored_fn, args, settings,
                             warm=None, shared=False, want_converged=True):
    """Adaptive solve + factors, segmented when the shapes demand it.

    Equivalent to ``factored_fn(*args, settings=settings, warm=warm)`` for
    shapes that fit one dispatch.  Returns (sol, factors, converged);
    ``want_converged=False`` skips the final ``sol.done`` fetch (one host
    RPC) and returns ``converged=None`` — for callers that read the
    convergence vote from their own packed measurement fetch
    (``admm.measure_pack``).

    SINGLE-CONTROLLER ONLY: the ``converged`` flag (and the continuation's
    defaults) fetch scenario-sharded device data, which raises on a
    multi-controller mesh with non-addressable shards — and even local-shard
    votes could disagree across processes and deadlock the collectives.
    Multi-controller callers drive the jitted sharded step with a
    deterministic schedule instead (see :func:`continue_frozen`).
    """
    S, n, m = _shapes(args, shared)
    seg_r, seg_f = dispatch_segments(S, n, m, settings,
                                     factor_batch=1 if shared else S,
                                     sparse_factor=_sparse_factor(args))
    def _conv(s):
        return (bool(hostsync.fetch(s.done).all()) if want_converged
                else None)

    if seg_r >= settings.max_iter and seg_f >= settings.max_iter:
        with _trace.span("dispatch", "adaptive_solve"):
            sol, factors = factored_fn(*args, settings=settings, warm=warm)
        return sol, factors, _conv(sol)
    _segmenting_events(S, n, m, seg_r, seg_f)
    st_r = dataclasses.replace(settings, max_iter=seg_r)
    st_f = seg_settings(settings, seg_f)
    with _trace.span("dispatch", "adaptive_segment") as _sp:
        if _trace.enabled():
            _sp.add(S=S, seg_r=seg_r)
        sol, factors = factored_fn(*args, settings=st_r, warm=warm)
    sol = _continue_frozen(frozen_fn, args, factors, sol, st_f, seg_f,
                           refresh_budget(settings, seg_r),
                           pipeline=pipeline_enabled(settings, S, n, m),
                           seg_flops=_seg_flops(args, shared, seg_f))
    if not shared and settings.polish and settings.polish_passes:
        # dense-path parity with the one-dispatch adaptive solve, which
        # polishes its final iterate; frozen continuations don't
        ce = max(1, settings.check_every)
        st_p = dataclasses.replace(settings, max_iter=2 * ce)
        sol = frozen_fn(*args, factors, settings=st_p, warm=sol.raw,
                        polish=True)
    # convergence from the RETURNED sol (post-polish), so the flag and
    # sol.done can never disagree
    return sol, factors, _conv(sol)


def solve_frozen_segmented(frozen_fn, args, factors, settings, warm=None,
                           want_converged=True):
    """Frozen solve, segmented when the shapes demand it.

    Returns (sol, converged) — callers must use ``converged`` (computed
    from ``BatchSolution.done``, the solver's own eps test) instead of any
    iters-vs-cap compare: iters reflects only the LAST segment's counter,
    and the in-loop plateau exit (``sweep_plateau_rtol``) leaves the sweep
    loop early without convergence.  ``want_converged=False`` skips that
    final done fetch (converged=None) for callers reading the vote from
    their own packed measurement fetch.

    SINGLE-CONTROLLER ONLY — same contract as
    :func:`solve_factored_segmented`: the convergence fetch and the
    data-dependent continuation need addressable shards.
    """
    shared = getattr(args[2], "ndim", None) == 2
    S, n, m = _shapes(args, shared)
    seg_r, seg_f = dispatch_segments(S, n, m, settings,
                                     factor_batch=1 if shared else S,
                                     sparse_factor=_sparse_factor(args))
    def _conv(s):
        return (bool(hostsync.fetch(s.done).all()) if want_converged
                else None)

    if seg_f >= settings.max_iter:
        with _trace.span("dispatch", "frozen_solve"):
            sol = frozen_fn(*args, factors, settings=settings, warm=warm)
        return sol, _conv(sol)
    _segmenting_events(S, n, m, seg_r, seg_f)
    st_f = seg_settings(settings, seg_f)
    with _trace.span("dispatch", "frozen_segment") as _sp:
        if _trace.enabled():
            _sp.add(S=S, seg_f=seg_f)
        sol = frozen_fn(*args, factors, settings=st_f, warm=warm)
    # check_incoming replaces the separate first-dispatch iters fetch the
    # serial protocol used to inline here (single-fetch stop_stats; the
    # pipelined policy overlaps every LATER segment's verdict)
    sol = _continue_frozen(frozen_fn, args, factors, sol, st_f, seg_f,
                           settings.max_iter - seg_f,
                           pipeline=pipeline_enabled(settings, S, n, m),
                           check_incoming=True,
                           seg_flops=_seg_flops(args, shared, seg_f))
    return sol, _conv(sol)
