"""Host-sync accounting for dispatch-decision fetches.

Every device→host fetch on a dispatch decision path (segmented
continuations, the amortized solve loop, bench measurement windows) goes
through :func:`fetch` so the sync traffic is *observable*: trackers opened
with :func:`track` count the fetches and the host wall-time spent blocked
in them, and ``bench.py`` reports the totals per segment as
``host_sync_count`` / ``dispatch_overhead_pct`` next to ``mfu_pct``.

Why it matters: on the remote-tunnel TPU posture every host fetch is a
serial RPC, and a fetch that gates the next dispatch leaves the device
idle for the whole round-trip.  The pipelined continuation
(:func:`tpusppy.solvers.segmented.continue_frozen`) marks fetches that
resolve while further device work is already queued as ``overlapped`` —
the host still blocks, but the device does not, so only NON-overlapped
fetch time counts as dispatch overhead.

:func:`fetch` is an EXPLICIT transfer (``jax.device_get``), which is the
transfer-guard contract: decision paths run clean under
``jax.transfer_guard_device_to_host("disallow")`` (which blocks only
implicit transfers such as ``np.asarray`` on a device array), so any
unplanned fetch added later fails loudly in the guard tests instead of
silently re-serializing the pipeline.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace

_local = threading.local()

# process-wide absorption into the metrics registry (tpusppy.obs.metrics):
# every fetch feeds these counters so bench/report numbers come from ONE
# source; the thread-local trackers below remain the scoped per-window
# view (and the parity test pins that single-threaded windows agree)
_CTR_COUNT = _metrics.counter("host_sync.count")
_CTR_OVERLAPPED = _metrics.counter("host_sync.overlapped")
_CTR_BLOCKED = _metrics.counter("host_sync.blocked_secs")
_CTR_FETCH = _metrics.counter("host_sync.fetch_secs")


def _stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def reset():
    """Drop the calling thread's tracker stack.

    Test-isolation hook (an autouse fixture calls it): a tracker left
    open by a failed/interrupted test — or pushed by library code that
    never unwound — must not keep counting fetches into a later test's
    ``host_sync_count`` assertion."""
    _local.stack = []


class SyncTracker:
    """Counts decision-path fetches and the host time spent blocked in
    them.  ``blocked_secs`` accumulates only NON-overlapped fetches (the
    ones that can leave the device idle); ``fetch_secs`` accumulates all.
    """

    def __init__(self):
        self.count = 0
        self.overlapped = 0
        self.blocked_secs = 0.0
        self.fetch_secs = 0.0

    def add(self, secs: float, overlapped: bool):
        self.count += 1
        self.fetch_secs += secs
        if overlapped:
            self.overlapped += 1
        else:
            self.blocked_secs += secs

    def overhead_pct(self, wall_secs: float) -> float:
        """Dispatch overhead: blocked-fetch time over a measured wall
        window (clipped to [0, 100] — clock skew must not produce >100)."""
        if wall_secs <= 0:
            return 0.0
        return float(min(100.0, 100.0 * self.blocked_secs / wall_secs))


@contextlib.contextmanager
def track():
    """Open a tracker for the current thread; nests (inner fetches land in
    every open tracker of this thread — cylinder threads never share)."""
    t = SyncTracker()
    _stack().append(t)
    try:
        yield t
    finally:
        _stack().remove(t)


def fetch(x, overlapped: bool = False):
    """Device→host fetch of an array or pytree, counted by the open
    trackers.  Explicit (``jax.device_get``) so decision paths satisfy the
    transfer-guard contract; numpy/scalar inputs pass through unchanged
    (scripted test stand-ins take this path)."""
    t0 = time.perf_counter()
    try:
        import jax
        out = jax.device_get(x)
    except ImportError:                  # pure-host callers (unit tests)
        out = np.asarray(x)
    dt = time.perf_counter() - t0
    for tr in _stack():
        tr.add(dt, overlapped)
    _CTR_COUNT.inc(1)
    _CTR_FETCH.inc(dt)
    if overlapped:
        _CTR_OVERLAPPED.inc(1)
    else:
        _CTR_BLOCKED.inc(dt)
    if _trace.enabled():
        # retroactive span: the fetch wall-time on the "host-sync" track
        _trace.record_span("host-sync", "fetch", t0, dt,
                           {"overlapped": overlapped})
    return out
