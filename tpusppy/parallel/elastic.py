"""Elastic mesh recovery: distributed wheels survive controller loss.

The paper's hub-and-spoke architecture tolerates dead SPOKES (asynchronous
bounds, PAPER.md §1; the in-process supervisor of
:mod:`tpusppy.resilience.supervisor` reproduces that).  A dead CONTROLLER
of the multi-controller wheel was a different story: Gloo collectives
block (or error unpredictably) on a dead peer, and the jax coordination
service goes further — its error-polling thread ``LOG(FATAL)``s surviving
processes once a peer death propagates, and ``jax.distributed.shutdown``
with a dead peer aborts on the shutdown barrier (both measured on this
toolchain).  In-process "re-initialize on the smaller mesh" is therefore
impossible; the recovery shape that works is the one elastic training
systems use: DETECT fast, AGREE on the survivor set, and RESTART the
surviving processes onto a fresh, smaller mesh, restoring state from the
shard-written checkpoints (doc/scaling.md) whose row-range reads are
layout-agnostic by construction.

Three pieces:

- :class:`Watchdog` — bounded-timeout execution of every mesh collective
  (PH steps, consensus fetches, write-id vote allgathers).  A dead or
  wedged controller turns an infinite hang into a typed
  :class:`ControllerLost` within ``TPUSPPY_MESH_TIMEOUT`` seconds; fast
  Gloo connection errors (the common CPU observation: a SIGKILLed peer
  refuses connections) convert to the same type.
- :class:`MeshLiveness` — a side-channel liveness protocol over the TCP
  window runtime (:mod:`tpusppy.runtime.tcp_window_service`): every
  controller serves a tiny heartbeat box set and beats into every peer's
  boxes, so each controller has a LOCAL view of who is alive that does
  not depend on any collective (or on controller 0 — there is no
  distinguished server).
- :func:`elastic_wheel_hub` — the driver: runs
  :func:`~tpusppy.parallel.dist_wheel.distributed_wheel_hub` under the
  watchdog; on :class:`ControllerLost` the survivors agree on the
  survivor set through the liveness channel (:func:`agree_survivors`),
  check the quorum (losing a MAJORITY of the original controllers raises
  :class:`MeshMajorityLost` — loudly, not a hang), and **re-exec**
  themselves (``os.execve`` of the same argv) with the next mesh epoch's
  topology in the environment.  The re-exec'd processes re-run
  ``initialize_backend`` on the smaller mesh (fresh coordinator port per
  epoch), re-derive placement from the partition rules with ghost
  padding absorbing the new uneven S split, restore wheel state from the
  latest COMPLETE sharded checkpoint set via per-process row-range
  reads, re-seed bounds through the resume seam, and continue with
  total-iteration semantics intact.

What is NOT survivable (typed errors, never hangs): loss of a majority
of the original controllers (:class:`MeshMajorityLost`), and loss of all
copies of a shard row — which with shard-per-process checkpoints on a
shared filesystem only happens when the filesystem lost the dead
controller's shard files (the resume then falls back to the previous
complete set, or cold-starts loudly).

See doc/resilience.md ("Elastic recovery") and scripts/chaos_smoke.py
(the real-SIGKILL acceptance).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import random
import socket
import sys
import threading
import time

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.log import get_logger
from ..resilience import faults as _faults

_log = get_logger("elastic")

_CTR_LOST = _metrics.counter("mesh.controller_lost")
_CTR_TIMEOUTS = _metrics.counter("mesh.collective_timeouts")
_CTR_ERRORS = _metrics.counter("mesh.collective_errors")
_CTR_REMESH = _metrics.counter("mesh.remesh")
_CTR_BEATS = _metrics.counter("mesh.heartbeats")
_CTR_BEAT_FAILS = _metrics.counter("mesh.heartbeat_fails")
_GAUGE_LIVE = _metrics.gauge("mesh.live_controllers")

#: env knobs (read at call time so tests and the chaos smoke can set them
#: per process): detection deadline + the epoch/survivor topology the
#: re-exec hands to the next incarnation
ENV_TIMEOUT = "TPUSPPY_MESH_TIMEOUT"
ENV_EPOCH = "TPUSPPY_ELASTIC_EPOCH"
ENV_SURVIVORS = "TPUSPPY_ELASTIC_SURVIVORS"
ENV_LOST_TOTAL = "TPUSPPY_ELASTIC_LOST_TOTAL"
ENV_REMESH_TOTAL = "TPUSPPY_ELASTIC_REMESH_TOTAL"
ENV_DETECT_SECS = "TPUSPPY_ELASTIC_DETECT_SECS"

# Conservative default: far above any healthy steady-state iteration or
# contention stall (the same reasoning that widened the jax coordination
# heartbeat window to 300s), so plain dist wheels never flake on a slow
# box — arming still turns an INFINITE hang into a bounded typed error.
# Elastic deployments that want fast recovery set a tight value
# explicitly (the chaos smoke runs at 20s).
DEFAULT_MESH_TIMEOUT = 300.0


def mesh_timeout() -> float:
    """The detection deadline in seconds (``TPUSPPY_MESH_TIMEOUT``;
    0 disables the watchdog — legacy block-forever collectives)."""
    return float(os.environ.get(ENV_TIMEOUT, DEFAULT_MESH_TIMEOUT) or 0.0)


class ControllerLost(RuntimeError):
    """A mesh peer is dead or unreachable: a guarded collective timed out
    or failed with a dead-peer error.  Carries ``what`` (the operation)
    and ``elapsed`` (seconds until detection)."""

    def __init__(self, what: str, elapsed: float, cause: str = "timeout"):
        self.what = str(what)
        self.elapsed = float(elapsed)
        self.cause = str(cause)
        super().__init__(
            f"controller lost: mesh collective {what!r} {cause} after "
            f"{elapsed:.1f}s (TPUSPPY_MESH_TIMEOUT={mesh_timeout():g})")


class MeshMajorityLost(ControllerLost):
    """The NON-recoverable case: fewer than a strict majority of the
    ORIGINAL controllers survive, so no quorum can agree on a survivor
    set (split-brain hazard) — fail loudly instead of re-meshing."""

    def __init__(self, survivors, n_original):
        self.survivors = sorted(int(s) for s in survivors)
        self.n_original = int(n_original)
        RuntimeError.__init__(
            self,
            f"mesh majority lost: only {len(self.survivors)} of "
            f"{self.n_original} original controllers survive "
            f"({self.survivors}) — below quorum, refusing to re-mesh")


# dead-peer signatures this toolchain's Gloo/coordination stack surfaces
# when a SIGKILLed peer's sockets vanish (measured; a plain hang is the
# other presentation, covered by the timeout)
_DEAD_PEER_MARKS = (
    "Connection refused", "Connection reset", "Broken pipe",
    "Socket closed", "UNAVAILABLE", "DEADLINE_EXCEEDED", "Gloo",
    "connection lost", "Transport endpoint",
)


def _is_dead_peer_error(exc: BaseException) -> bool:
    msg = repr(exc)
    return any(m in msg for m in _DEAD_PEER_MARKS)


class Watchdog:
    """Bounded-timeout execution of mesh collectives.

    Guarded calls run serialized on ONE dedicated worker thread (order
    preserved); the caller waits with a deadline.  On timeout the worker
    is abandoned mid-call (the process is about to re-mesh via exec — a
    wedged Gloo op cannot be cancelled anyway) and :class:`ControllerLost`
    raises on the calling thread.  Exceptions matching dead-peer
    signatures convert to :class:`ControllerLost` too; everything else
    propagates untouched.  ``timeout=0`` disables the thread hop entirely
    (deterministic passthrough — the legacy path).

    The FIRST guarded call gets ``first_grace`` × the timeout: it folds
    in XLA compiles and the Gloo rendezvous window, which are not
    liveness signals.  Steady state is LOAD-ADAPTIVE (the same policy as
    the spoke supervisor's staleness grace): the effective deadline is
    ``max(timeout, adaptive_grace × observed call latency)`` (latency =
    max of the EWMA and the latest completed call), so a wheel whose
    healthy steps legitimately approach or exceed the configured timeout
    — a big-S consensus fetch, a contention stall — widens its own
    window instead of tripping a spurious loss, while a genuine hang
    (unbounded) still fires within a small multiple of the run's own
    demonstrated cadence.
    """

    def __init__(self, timeout: float | None = None,
                 first_grace: float = 10.0, adaptive_grace: float = 8.0):
        self.timeout = mesh_timeout() if timeout is None else float(timeout)
        self.first_grace = float(first_grace)
        self.adaptive_grace = float(adaptive_grace)
        self._first = True
        self._lat_ewma = 0.0
        self._lat_last = 0.0
        self._q: queue.Queue | None = None
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    @classmethod
    def from_options(cls, options) -> "Watchdog":
        t = (options or {}).get("mesh_timeout")
        return cls(timeout=None if t is None else float(t))

    @property
    def armed(self) -> bool:
        return self.timeout > 0

    def _submit(self, fn):
        # DAEMON worker, not a ThreadPoolExecutor: concurrent.futures
        # joins its (non-daemon) workers at interpreter exit, so an
        # abandoned wedged collective would hang the process at shutdown
        # — the exact hang this class exists to remove (the typed
        # majority-loss failure must EXIT, not park in atexit)
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._q = queue.Queue()
                self._thread = threading.Thread(
                    target=self._worker_loop, name="mesh-watchdog",
                    daemon=True)
                self._thread.start()
            box: queue.Queue = queue.Queue(maxsize=1)
            self._q.put((fn, box))
            return box

    def _worker_loop(self):
        q = self._q
        while True:
            item = q.get()
            if item is None:
                return
            fn, box = item
            try:
                box.put((True, fn()))
            except BaseException as e:      # delivered to the caller
                box.put((False, e))

    def deadline(self) -> float:
        """The budget the NEXT guarded call gets."""
        if self._first:
            return self.timeout * self.first_grace
        return max(self.timeout,
                   self.adaptive_grace * max(self._lat_ewma,
                                             self._lat_last))

    def call(self, fn, what: str):
        _faults.on_collective(what)
        if not self.armed:
            return fn()
        budget = self.deadline()
        t0 = time.monotonic()
        box = self._submit(fn)
        try:
            ok, out = box.get(timeout=budget)
        except queue.Empty:
            _CTR_TIMEOUTS.inc(1)
            self._lost(what, time.monotonic() - t0, "timed out")
        if not ok:
            if isinstance(out, ControllerLost):
                raise out
            if _is_dead_peer_error(out):
                _CTR_ERRORS.inc(1)
                self._lost(what, time.monotonic() - t0,
                           f"failed ({type(out).__name__})")
            raise out
        if not self._first:
            # the FIRST (grace) call is compile + rendezvous, not a
            # cadence sample: learning it would inflate the adaptive
            # deadline ~grace-fold for the whole run and stall detection
            dt = time.monotonic() - t0
            self._lat_last = dt
            self._lat_ewma = (dt if self._lat_ewma == 0.0
                              else 0.8 * self._lat_ewma + 0.2 * dt)
        self._first = False
        return out

    def _lost(self, what, elapsed, cause):
        _CTR_LOST.inc(1)
        if _trace.enabled():
            _trace.instant("hub", "controller_lost", what=what,
                           elapsed=elapsed, cause=cause)
        _log.warning("mesh collective %r %s after %.1fs — controller "
                     "presumed lost", what, cause, elapsed)
        raise ControllerLost(what, elapsed, cause)

    def wrap(self, fn, what: str):
        """A guarded version of ``fn`` (for injecting into callers that
        take a collective function, e.g. the write-id vote's
        allgather)."""
        def guarded(*args, **kwargs):
            return self.call(lambda: fn(*args, **kwargs), what)
        return guarded

    def close(self):
        with self._lock:
            q, self._q, self._thread = self._q, None, None
        if q is not None:
            q.put(None)         # idle worker exits; a wedged one is
            # abandoned — daemonized, it cannot block process exit


# ---------------------------------------------------------------------------
# Liveness side-channel
# ---------------------------------------------------------------------------
# payload: [epoch, beat counter, view bits lo, view bits hi, phase] —
# the survivor-set bitmask rides as TWO <2^27 words so every value is
# exact in float64 (one word would silently round past 53 ranks and the
# exact-compare agreement could never converge); _MAX_RANKS guards the
# representable range at construction
_HB_LEN = 5
_BITS_WORD = 27
_MAX_RANKS = 2 * _BITS_WORD
_PHASE_RUNNING = 0.0
_PHASE_PROPOSING = 1.0


def _bits(ranks) -> int:
    return sum(1 << int(r) for r in ranks)


def _bits_words(bits: int):
    return (float(bits & ((1 << _BITS_WORD) - 1)),
            float(bits >> _BITS_WORD))


def free_port_block(n: int, tries: int = 64) -> int:
    """Base of ``n`` CONSECUTIVE currently-free TCP ports.

    The liveness servers bind ``base + original_rank`` and the per-epoch
    jax coordinators ``base + epoch`` — single ``bind(0)`` reservations
    only vouch for the base, and an unreserved offset colliding with a
    busy port would kill a controller for reasons unrelated to recovery.
    Probes a random high-range base until the whole block binds (the
    usual TOCTOU caveat applies; the block is outside the kernel's
    ephemeral range to keep collisions rare)."""
    for _ in range(tries):
        base = random.randint(20000, 29000)
        socks = []
        try:
            for k in range(n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + k))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"no free block of {n} consecutive ports found")


class MeshLiveness:
    """All-to-all controller heartbeats over the TCP window runtime.

    Every controller SERVES one tiny box set (``n_original`` boxes of 4
    doubles) on ``port_base + its ORIGINAL rank`` and beats
    ``[epoch, counter, view_bits, phase]`` into box ``rank`` on every
    peer's server (plus its own, locally).  Liveness of peer ``r`` is
    judged from the LOCAL server alone: box ``r``'s write-id advanced
    within ``stale_after`` seconds.  No collective, no distinguished
    process — the channel survives any subset of deaths, which is the
    property the post-loss survivor agreement needs.

    Ports are stable across mesh epochs (original ranks never change);
    all sockets are close-on-exec, so a re-exec'd survivor re-serves its
    port immediately.  The shared ``secret`` gates the handshake exactly
    as the wheel fabric's does.
    """

    def __init__(self, rank: int, members, n_original: int,
                 port_base: int, hosts=None, secret: int = 0,
                 epoch: int = 0, stale_after: float | None = None,
                 interval: float | None = None):
        from ..runtime.tcp_window_service import TcpEndpoint

        self.rank = int(rank)
        self.members = sorted(int(m) for m in members)
        self.n_original = int(n_original)
        if self.n_original > _MAX_RANKS:
            raise ValueError(
                f"MeshLiveness supports up to {_MAX_RANKS} original "
                f"controllers (the agreement bitmask rides two exact "
                f"f64 words), got {self.n_original}")
        self.port_base = int(port_base)
        self.hosts = list(hosts) if hosts else \
            ["127.0.0.1"] * self.n_original
        self.secret = int(secret)
        self.epoch = int(epoch)
        self.stale_after = float(stale_after if stale_after is not None
                                 else max(mesh_timeout(), 1.0))
        self.interval = float(interval if interval is not None
                              else min(1.0, max(0.05,
                                                self.stale_after / 8.0)))
        self._ep_cls = TcpEndpoint
        self._srv = TcpEndpoint(lengths=[_HB_LEN] * self.n_original,
                                port=self.port_base + self.rank,
                                bind="0.0.0.0" if any(
                                    h not in ("127.0.0.1", "localhost")
                                    for h in self.hosts) else "127.0.0.1",
                                secret=self.secret)
        self._peers: dict = {}          # rank -> TcpEndpoint | None
        self._last_dial: dict = {}      # rank -> monotonic of last attempt
        self._counter = 0
        self._view_bits = _bits(self.members)
        self._phase = _PHASE_RUNNING
        self._state_lock = threading.Lock()
        # last observed (write_id, change time) per LOCAL box; seeding
        # with start time gives every peer one stale window to say hello
        now = time.monotonic()
        self._seen = {r: (0, now) for r in self.members if r != self.rank}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- beating -----------------------------------------------------------
    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._beat_loop,
                                            name="mesh-liveness",
                                            daemon=True)
            self._thread.start()
        return self

    def _payload(self):
        import numpy as np

        with self._state_lock:
            self._counter += 1
            lo, hi = _bits_words(self._view_bits)
            return np.asarray([float(self.epoch), float(self._counter),
                               lo, hi, float(self._phase)],
                              dtype=np.float64)

    def beat(self):
        """One heartbeat round: put the payload into our own box locally
        and on every peer's server (dead peers are skipped with a dial
        cooldown so one corpse cannot stall beats to the living)."""
        import ctypes

        payload = self._payload()
        lib = self._srv._lib
        ptr = payload.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        lib.tws_put(self._srv._handle, self.rank, ptr, _HB_LEN)
        _CTR_BEATS.inc(1)
        for r in self.members:
            if r == self.rank:
                continue
            ep = self._dial(r)
            if ep is None:
                continue
            try:
                _faults.on_tcp_io(f"liveness->r{r}")
                rc = lib.tws_put(ep._handle, self.rank, ptr, _HB_LEN)
                if rc < -1:
                    raise RuntimeError(f"liveness put rc={rc}")
            except Exception:
                _CTR_BEAT_FAILS.inc(1)
                try:
                    ep.close()
                except Exception:
                    pass
                self._peers[r] = None
        # refresh the local view each beat (write_id progression)
        self._observe()

    def _dial(self, r: int):
        """Client endpoint to peer ``r``'s liveness server, (re)dialed
        with a SHORT connect timeout and a cooldown — a down peer must
        never stall the beat loop for the healthy ones."""
        ep = self._peers.get(r)
        if ep is not None:
            return ep
        now = time.monotonic()
        if now - self._last_dial.get(r, -1e9) < max(self.interval * 2, 0.5):
            return None
        self._last_dial[r] = now
        try:
            ep = self._ep_cls(
                connect=(self.hosts[r], self.port_base + r),
                connect_timeout=min(2.0, self.stale_after / 2),
                secret=self.secret, op_timeout=min(2.0, self.stale_after))
        except Exception:
            _CTR_BEAT_FAILS.inc(1)
            return None
        self._peers[r] = ep
        return ep

    def _beat_loop(self):
        while not self._stop.is_set():
            try:
                self.beat()
            except Exception as e:      # the channel must never crash a run
                _log.warning("liveness beat failed: %r", e)
            self._stop.wait(self.interval)

    # ---- observing ---------------------------------------------------------
    def _observe(self):
        now = time.monotonic()
        for r in list(self._seen):
            try:
                wid = int(self._srv._lib.tws_write_id(self._srv._handle, r))
            except Exception:
                continue
            last_wid, _t = self._seen[r]
            if wid != last_wid:
                self._seen[r] = (wid, now)
        _GAUGE_LIVE.set(float(len(self._alive_from_seen())))

    def _alive_from_seen(self):
        now = time.monotonic()
        return sorted([self.rank] + [
            r for r, (_wid, t) in self._seen.items()
            if now - t <= self.stale_after])

    def alive_ranks(self):
        """Sorted ORIGINAL ranks currently considered alive (self always;
        peers whose local box advanced within ``stale_after``)."""
        try:                    # cheap local reads; any thread may call
            self._observe()
        except Exception:
            pass
        return self._alive_from_seen()

    def peer_states(self) -> dict:
        """{rank: (epoch, counter, view_bits, phase)} from the LOCAL
        boxes (self included) — the agreement protocol's read side
        (``view_bits`` reassembled from the two exact payload words)."""
        import ctypes

        import numpy as np

        out = {}
        buf = np.empty(_HB_LEN, dtype=np.float64)
        ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
        for r in self.members:
            rc = self._srv._lib.tws_get(self._srv._handle, r, ptr, _HB_LEN)
            if int(rc) <= 0:
                continue        # never written (or killed): no state yet
            bits = int(buf[2]) | (int(buf[3]) << _BITS_WORD)
            out[r] = (float(buf[0]), float(buf[1]), bits, float(buf[4]))
        return out

    def set_state(self, view_bits: int | None = None,
                  phase: float | None = None):
        with self._state_lock:
            if view_bits is not None:
                self._view_bits = int(view_bits)
            if phase is not None:
                self._phase = float(phase)

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval + 1.0)
            self._thread = None
        for ep in self._peers.values():
            if ep is not None:
                try:
                    ep.close()
                except Exception:
                    pass
        self._peers = {}
        try:
            self._srv.close()
        except Exception:
            pass


def agree_survivors(liveness: MeshLiveness, deadline_secs: float | None = None):
    """Post-loss survivor agreement: publish my live view through the
    heartbeat payload, wait until every member of that view publishes
    the SAME view (same epoch, PROPOSING phase) — then the set is the
    agreed survivor roster.  Deterministic: all survivors see the same
    dead peers (heartbeats stopped for everyone), so the fixed point is
    the true survivor set; skew while views converge just loops.

    Raises :class:`MeshMajorityLost` the moment my own view drops to a
    non-strict-majority of the ORIGINAL controllers (no quorum can ever
    form), and :class:`ControllerLost` if agreement does not converge
    within the deadline (default 6× the stale window) — a fabric so
    broken that the survivors cannot even see each other.
    """
    deadline = time.monotonic() + (
        float(deadline_secs) if deadline_secs is not None
        else 6.0 * liveness.stale_after)
    n0 = liveness.n_original
    while True:
        view = liveness.alive_ranks()
        if 2 * len(view) <= n0:
            raise MeshMajorityLost(view, n0)
        bits = _bits(view)
        liveness.set_state(view_bits=bits, phase=_PHASE_PROPOSING)
        liveness.beat()                  # publish NOW, don't wait a tick
        states = liveness.peer_states()
        agreed = True
        for r in view:
            if r == liveness.rank:
                continue
            st = states.get(r)
            # a peer counts as agreeing when it published PROPOSING with
            # the same roster at our epoch — or when it ALREADY MOVED ON:
            # an agreed peer execs immediately, and its epoch+1 heartbeats
            # (whose view IS the agreed roster) can overwrite the
            # lingering PROPOSING payload before a slower survivor reads
            # it; without this acceptance the slow side loops until its
            # deadline while the fast side waits at the next epoch's
            # rendezvous (race observed in the chaos smoke)
            same_roster = st is not None and int(st[2]) == bits
            proposing_now = (same_roster
                             and st[0] == float(liveness.epoch)
                             and st[3] == _PHASE_PROPOSING)
            already_next_epoch = (same_roster
                                  and st[0] == float(liveness.epoch) + 1.0)
            if not (proposing_now or already_next_epoch):
                agreed = False
                break
        if agreed:
            _log.warning("survivor agreement: %s of %d original "
                         "controllers (epoch %d)", view, n0,
                         liveness.epoch)
            return view
        if time.monotonic() > deadline:
            raise ControllerLost("survivor_agreement",
                                 6.0 * liveness.stale_after,
                                 "did not converge")
        time.sleep(liveness.interval / 2)


# ---------------------------------------------------------------------------
# Topology spec + re-exec re-meshing
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class ElasticSpec:
    """Everything a controller needs to (re)join an elastic mesh.

    ``rank`` is the process's ORIGINAL rank (stable across epochs — it
    names its liveness port and its identity in survivor sets);
    ``n_original`` the epoch-0 controller count (the quorum base);
    ``coord_port_base + epoch`` the jax coordinator port of each epoch
    (a fresh port per epoch: the previous coordinator socket died with
    the exec'd process image, and ports linger in TIME_WAIT);
    ``liveness_port_base + rank`` each controller's heartbeat server.
    ``survivors`` is None at epoch 0 (all ranks), else the agreed roster.
    """

    rank: int
    n_original: int
    checkpoint_dir: str
    coord_port_base: int
    liveness_port_base: int
    hosts: list | None = None
    secret: int = 0
    epoch: int = 0
    survivors: list | None = None
    mesh_timeout_secs: float | None = None

    def with_env(self) -> "ElasticSpec":
        """Fold in the re-exec environment overrides (epoch + survivor
        roster) — the first thing a (possibly re-exec'd) worker does."""
        epoch = int(os.environ.get(ENV_EPOCH, self.epoch))
        surv = os.environ.get(ENV_SURVIVORS)
        survivors = ([int(x) for x in surv.split(",") if x != ""]
                     if surv else self.survivors)
        return dataclasses.replace(self, epoch=epoch, survivors=survivors)

    @property
    def members(self) -> list:
        return (sorted(int(s) for s in self.survivors)
                if self.survivors else list(range(self.n_original)))

    @property
    def process_id(self) -> int:
        return self.members.index(self.rank)

    @property
    def coordinator(self) -> str:
        host = (self.hosts or ["127.0.0.1"] * self.n_original)[
            self.members[0]]
        return f"{host}:{self.coord_port_base + self.epoch}"

    @property
    def timeout(self) -> float:
        return (float(self.mesh_timeout_secs)
                if self.mesh_timeout_secs is not None else mesh_timeout())


def _reseed_counters_from_env():
    """The registry dies with the exec'd image: previous epochs' loss/
    re-mesh counts ride the environment so the FINAL process's registry
    still shows the whole recovery (the acceptance contract)."""
    lost = int(os.environ.get(ENV_LOST_TOTAL, "0") or 0)
    remesh = int(os.environ.get(ENV_REMESH_TOTAL, "0") or 0)
    if lost > int(_CTR_LOST.get()):
        _CTR_LOST.inc(lost - int(_CTR_LOST.get()))
    if remesh > int(_CTR_REMESH.get()):
        _CTR_REMESH.inc(remesh - int(_CTR_REMESH.get()))


def _await_peers_next_epoch(liveness: MeshLiveness, survivors,
                            next_epoch: int, deadline_secs: float):
    """Exec-ordering barrier for the CURRENT epoch's coordinator.

    The jax coordination service lives inside the epoch's rank-min
    controller; exec'ing that process closes the service socket, and any
    fellow survivor still running the old epoch is LOG(FATAL)'d the
    instant its error-poller notices (measured: the chaos smoke's
    controller_2 post-mortem shows PollForError "Socket closed" →
    termination whenever controller 0 exec'd first).  So the coordinator
    holds its exec until every other survivor's liveness payload shows
    ``epoch >= next_epoch`` — its re-exec'd incarnation is beating and
    no longer owns an epoch-``e`` coordination client.  Bounded: past
    the deadline (a peer died instead of re-meshing) the exec proceeds
    and the next epoch's bounded ``RegisterTask`` window reports the
    missing peer."""
    deadline = time.monotonic() + float(deadline_secs)
    rest = [int(r) for r in survivors if int(r) != liveness.rank]
    while rest and time.monotonic() < deadline:
        states = liveness.peer_states()
        if all(states.get(r) is not None
               and states[r][0] >= float(next_epoch) for r in rest):
            return True
        time.sleep(liveness.interval / 2)
    if rest:
        _log.warning(
            "coordinator exec barrier: peers %s never reached epoch %d "
            "within %.0fs — exec'ing anyway (the next epoch's register "
            "window bounds the damage)", rest, next_epoch, deadline_secs)
    return False


def remesh_exec(spec: ElasticSpec, survivors, detect_secs: float):
    """Replace this process with the next mesh epoch's incarnation:
    same executable, same argv, environment carrying the new epoch and
    survivor roster.  Never returns.  ``os.execve`` keeps the PID and
    the inherited stdio pipes (a parent harness keeps reading the same
    stream); every runtime socket is close-on-exec, so the liveness and
    fabric ports rebind cleanly in the new image."""
    _CTR_REMESH.inc(1)
    env = dict(os.environ)
    env[ENV_EPOCH] = str(spec.epoch + 1)
    env[ENV_SURVIVORS] = ",".join(str(s) for s in sorted(survivors))
    env[ENV_LOST_TOTAL] = str(int(_CTR_LOST.get()))
    env[ENV_REMESH_TOTAL] = str(int(_CTR_REMESH.get()))
    env[ENV_DETECT_SECS] = f"{detect_secs:.3f}"
    _log.warning("re-meshing: exec epoch %d with survivors %s "
                 "(detected in %.1fs)", spec.epoch + 1, sorted(survivors),
                 detect_secs)
    sys.stdout.flush()
    sys.stderr.flush()
    argv = [sys.executable] + sys.argv
    os.execve(sys.executable, argv, env)


def elastic_wheel_hub(spec: ElasticSpec, all_scenario_names,
                      scenario_creator, scenario_creator_kwargs=None,
                      options=None, fabric_factory=None, spoke_roles=None,
                      is_minimizing: bool = True):
    """Run one controller of an ELASTIC distributed wheel.

    Call from every controller process (a script whose argv can be
    re-exec'd verbatim).  Epoch 0 runs the full mesh; on
    :class:`ControllerLost` the survivors agree on the roster and
    re-exec into epoch ``e+1``, where this function (reached again
    through the re-run script) initializes the smaller mesh and resumes
    from ``spec.checkpoint_dir``'s latest complete sharded set.  Returns
    the :class:`~tpusppy.parallel.dist_wheel.DistWheelResult` of the
    epoch that completes; raises :class:`MeshMajorityLost` (typed, loud)
    when no quorum survives.

    ``fabric_factory(spec)`` builds this epoch's spoke fabric view (or
    None for the spokeless posture).  Serve the boxes OFF-controller (or
    accept that spokes ride their reconnect path while the serving
    controller re-execs).
    """
    from .dist_wheel import distributed_wheel_hub
    from .distributed import initialize_backend

    spec = spec.with_env()
    _reseed_counters_from_env()
    options = dict(options or {})
    options.setdefault("mesh_timeout", spec.timeout)
    options.setdefault("checkpoint_dir", spec.checkpoint_dir)
    options.setdefault("checkpoint_sharded", True)
    if spec.epoch > 0:
        # elastic restore: latest complete sharded set, per-process
        # row-range reads on the NEW (smaller) mesh; bounds re-seed and
        # PHIterLimit keeps meaning TOTAL iterations
        options["resume"] = spec.checkpoint_dir
        options["elastic_epoch"] = spec.epoch
    liveness = MeshLiveness(
        rank=spec.rank, members=spec.members, n_original=spec.n_original,
        port_base=spec.liveness_port_base, hosts=spec.hosts,
        secret=spec.secret, epoch=spec.epoch,
        stale_after=max(spec.timeout, 1.0)).start()
    t_start = time.monotonic()
    try:
        # epoch > 0: a tighter register window — the survivors exec
        # within seconds of each other (the coordinator last, see
        # _await_peers_next_epoch), so a peer that fails to appear is
        # dead and the failure should be bounded, not a 300s wait
        initialize_backend(
            spec.coordinator, len(spec.members), spec.process_id,
            initialization_timeout=120 if spec.epoch > 0 else 300)
        fabric = fabric_factory(spec) if fabric_factory else None
        return distributed_wheel_hub(
            all_scenario_names, scenario_creator,
            scenario_creator_kwargs=scenario_creator_kwargs,
            options=options, fabric=fabric, spoke_roles=spoke_roles,
            is_minimizing=is_minimizing)
    except ControllerLost as e:
        if isinstance(e, MeshMajorityLost):
            _die_typed(e)
        detect = getattr(e, "elapsed", time.monotonic() - t_start)
        _log.warning("epoch %d: %s", spec.epoch, e)
        try:
            survivors = agree_survivors(liveness)
        except ControllerLost as e2:     # majority lost / no convergence
            _die_typed(e2)
        if spec.rank == spec.members[0]:
            # THIS process hosts the epoch's coordination service: its
            # exec must come LAST or it kills the other survivors
            _await_peers_next_epoch(liveness, survivors, spec.epoch + 1,
                                    4.0 * liveness.stale_after)
        # never jax.distributed.shutdown() here: with a dead peer the
        # shutdown barrier LOG(FATAL)s the process (measured) — the exec
        # replaces the image, which is the only clean teardown there is
        remesh_exec(spec, survivors, detect)
        raise AssertionError("unreachable: execve returned")  # pragma: no cover
    finally:
        liveness.close()


#: process exit code of a NON-RECOVERABLE elastic failure (majority
#: loss, survivor agreement not converging): the typed error is printed,
#: then the process exits WITHOUT running C++ destructors — with a dead
#: peer, the jax coordination client's destructor aborts the process
#: through its shutdown barrier (LOG(FATAL), rc=-6, measured on this
#: toolchain), which would bury the typed diagnosis under a crash
ELASTIC_FATAL_EXIT = 13


def _die_typed(exc: ControllerLost):
    """Fail LOUDLY with the typed error, not a hang and not an abort:
    print the diagnosis, flush, and exit with :data:`ELASTIC_FATAL_EXIT`
    before interpreter teardown can reach the coordination client's
    aborting destructor.  Raises instead when no distributed backend is
    initialized (nothing to abort — normal exception semantics)."""
    import jax

    if jax._src.distributed.global_state.client is None:
        raise exc
    _log.warning("NON-RECOVERABLE elastic failure: %s", exc)
    print(f"ELASTIC-FATAL {type(exc).__name__}: {exc}",
          file=sys.stderr, flush=True)
    sys.stdout.flush()
    os._exit(ELASTIC_FATAL_EXIT)
