"""Scenario-sharded PH over a `jax.sharding.Mesh` — the multi-chip path.

This is the TPU-native replacement for the reference's rank-level scenario
parallelism (P1/P2 in SURVEY §2.12): scenarios are block-partitioned over MPI
ranks there (``spbase.py:184-216``, ``sputils.py:774-840``) with per-tree-node
``Allreduce`` reductions (``phbase.py:27-107``, ``spbase.py:333-375``).  Here
the whole scenario batch is sharded over a named mesh axis (``"scen"``); each
device solves its local shard of subproblems inside ONE jitted program, and the
per-node weighted averages are a one-hot contraction whose scenario-axis
reduction XLA lowers to a psum over ICI.  No explicit communicator management:
the mesh + sharding annotations replace ``comm.Split``.

The functional core (:func:`make_ph_step`) is also the single-chip fast path:
the same compiled step runs on one device with a trivial mesh.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..solvers import admm, shared_admm
from ..solvers import aot as aot_cache
from ..solvers import segmented as segmented_solvers
from ..solvers.admm import ADMMSettings
from ..solvers.sparse import SparseA


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: ``jax.shard_map`` + ``check_vma``
    (>= 0.6) when present, else ``jax.experimental.shard_map`` with the
    old ``check_rep`` spelling (0.4.x, the pinned toolchain here)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)

# ---------------------------------------------------------------------------
# Dispatch segmentation: the remote TPU worker kills any single program
# execution around ~60 s, so reference-scale UC (S=1000, n=16008) can never
# run one monolithic PH step — see tpusppy/solvers/segmented.py for the
# shared mechanism.  The constants live here too so tests can monkeypatch
# this module's copies; _dispatch_segments forwards them explicitly.
# ---------------------------------------------------------------------------
# None = defer to segmented's defaults (including its per-scenario-dense
# throughput clamp); tests monkeypatch these with explicit numbers to force
# dispatch regimes — explicit values are authoritative, never clamped
_DISPATCH_TARGET_SECS = None
_DISPATCH_EFF_FLOPS = None


def _dispatch_segments(S, n, m, st: ADMMSettings, factor_batch=1,
                       sparse_factor=1.0):
    return segmented_solvers.dispatch_segments(
        S, n, m, st, factor_batch=factor_batch,
        eff_flops=_DISPATCH_EFF_FLOPS, target_secs=_DISPATCH_TARGET_SECS,
        sparse_factor=sparse_factor)


def _dispatch_model_params(arr, mesh):
    """(S_dev, n, m, factor_batch, sparse_factor) for the dispatch flop
    model — single source for _segments_for and fused_iteration_cap."""
    S, n = arr.c.shape
    m = arr.cl.shape[1]
    ndev = 1 if mesh is None else len(mesh.devices.flat)
    S_dev = -(-S // ndev)          # per-device shard does the sweeping
    dense = arr.A.ndim == 3
    sf = (segmented_solvers.SPARSE_DISPATCH_FACTOR
          if isinstance(arr.A, SparseA) else 1.0)
    return S_dev, n, m, (S_dev if dense else 1), sf


class PHArrays(NamedTuple):
    """Device-resident, scenario-sharded problem data + tree indexing.

    Leading axis S is sharded over the mesh ``scen`` axis; everything else is
    replicated.  ``onehot`` is (S, K, N) node membership (nid one-hot), the
    matmul form of per-node sub-communicators.

    For a shared-A batch (``ScenarioBatch.A_shared``), ``A`` is the single
    (m, n) matrix REPLICATED across the mesh — scenario data stays sharded,
    and the shared-A solver's matmuls against the replicated matrix shard
    naturally on the scenario axis under jit auto-partitioning.
    """

    c: jax.Array        # (S, n)
    q2: jax.Array       # (S, n)
    A: jax.Array        # (S, m, n) — or (m, n) replicated when shared
    cl: jax.Array       # (S, m)
    cu: jax.Array       # (S, m)
    lb: jax.Array       # (S, n)
    ub: jax.Array       # (S, n)
    const: jax.Array    # (S,)
    probs: jax.Array    # (S,)
    onehot: jax.Array   # (S, K, N)
    nid_sk: jax.Array   # (S, K) node id per nonant slot


class PHState(NamedTuple):
    """Per-iteration PH carry (all scenario-sharded)."""

    W: jax.Array        # (S, K)
    xbars: jax.Array    # (S, K)
    rho: jax.Array      # (S, K)
    x: jax.Array        # (S, n) last solution
    z: jax.Array        # (S, m) ADMM aux
    y: jax.Array        # (S, m) ADMM dual
    yx: jax.Array       # (S, n) bound dual


class PHStepOut(NamedTuple):
    conv: jax.Array       # scalar: prob-weighted L1 deviation from xbar
    eobj: jax.Array       # scalar: expected objective at current x
    pri_res: jax.Array    # (S,)
    dua_res: jax.Array    # (S,)
    iters: jax.Array      # scalar: ADMM sweeps the subproblem solve used
    # (batch max; feeds the FLOP-model MFU accounting — solvers/flops.py)


# ---------------------------------------------------------------------------
# Rule-driven placement (ROADMAP item 1; the match_partition_rules /
# shard-and-gather pattern of SNIPPETS [3] under the pjit/GSPMD mesh
# semantics of [1]).  One declarative table maps EVERY PHArrays / PHState
# leaf — and therefore every megastep scan carry, which is a PHState — to
# its PartitionSpec by leaf-path regex, instead of per-field ad-hoc
# device_put calls scattered through shard_batch/init_state.  Adding a
# field to either NamedTuple without a matching rule is a loud error, not
# a silently-replicated (S, ...) array: at S=10^4-10^5 one unsharded
# per-scenario leaf is the difference between O(S/ndev) and O(S) HBM.
# ---------------------------------------------------------------------------
def ph_partition_rules(axis: str = "scen", row_axis: str | None = None,
                       shared: bool = False, tenant: bool = False) -> list:
    """[(leaf-path regex, PartitionSpec)] for one mesh posture.

    ``shared``: the batch carries one (m, n) ``A_shared`` — A is replicated
    (or row-sharded over ``row_axis`` on a 2-D mesh, with the (S, m)
    row-state leaves sharded on both axes); dense per-scenario batches
    shard A's leading scenario axis like every other leaf.  First match
    wins, so the specific rows precede the catch-all scenario rule.

    ``tenant``: the TENANT-BATCHED posture (continuous batching,
    doc/serving.md): leaves carry a leading tenant axis — (T, S, ...)
    instead of (S, ...) — and sharding is SCENARIO-WITHIN-TENANT: the
    tenant axis is never partitioned (each slot's scenario rows must stay
    whole so per-tenant masked reductions never cross a device boundary
    mid-slot), the scenario axis shards exactly as in the solo posture.
    Every scenario-leading spec gains a leading ``None``; engine-shaped
    leaves (a replicated shared A) are tenant-stacked but otherwise
    unchanged.
    """
    scen = P(axis)
    if shared:
        A_spec = P(row_axis, None) if row_axis else P()
        row = P(axis, row_axis) if row_axis else scen
    else:
        A_spec, row = scen, scen
    rules = [
        # constraint matrix: the one leaf whose layout depends on the
        # engine (dense stack / replicated shared / SparseA sub-leaves)
        (r"(^|/)A(/|$)", A_spec),
        # (S, m) row-state: constraint bounds + ADMM row iterates
        (r"(^|/)(cl|cu|z|y)$", row),
        # every remaining per-scenario leaf: (S, n), (S, K), (S, K, N), (S,)
        (r"(^|/)(c|q2|lb|ub|const|probs|onehot|nid_sk)$", scen),
        (r"(^|/)(W|xbars|rho|x|yx)$", scen),
    ]
    if tenant:
        # scenario-within-tenant: prepend an UNSHARDED tenant dim to every
        # spec that leads with the scenario axis (the engine-dependent A
        # spec keeps its own layout — a tenant-stacked replicated A simply
        # gains an unsharded leading dim through the same transform)
        rules = [(r, P(None, *s)) for r, s in rules]
    return rules


def _leaf_path(path) -> str:
    parts = []
    for p in path:
        name = getattr(p, "name", None)
        if name is None:
            name = getattr(p, "key", getattr(p, "idx", ""))
        parts.append(str(name))
    return "/".join(parts)


def match_partition_rules(rules, tree):
    """Pytree of PartitionSpec matching each leaf of ``tree`` against
    ``rules`` by its slash-joined path (the SNIPPETS [3] idiom).  Scalars
    never partition; a leaf no rule matches raises — an unplaced leaf is
    a placement-table bug, not a default."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    leaves, treedef = tree_flatten_with_path(tree)

    def pick(path, leaf):
        if np.ndim(leaf) == 0 or np.size(leaf) == 1:
            return P()
        name = _leaf_path(path)
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"no partition rule matches leaf {name!r}")

    return tree_unflatten(treedef, [pick(p, l) for p, l in leaves])


def ph_shardings(mesh: Mesh, tree, axis: str = "scen",
                 row_axis: str | None = None, shared: bool = False,
                 tenant: bool = False):
    """Pytree of :class:`NamedSharding` for ``tree`` (a PHArrays, a
    PHState, or any sub-pytree of their leaves) under the placement
    table.  THE single source of wheel-state placement: shard_batch,
    init_state and the shard-read checkpoint restore all derive their
    shardings here, so they cannot drift.  ``tenant`` selects the
    scenario-within-tenant posture for (T, S, ...)-stacked trees."""
    specs = match_partition_rules(
        ph_partition_rules(axis, row_axis, shared, tenant), tree)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def num_ghosts(S: int, mesh: Mesh, axis: str = "scen") -> int:
    """Ghost scenarios appended so S fills the mesh axis evenly (0 when S
    already divides).  Ghosts are zero-probability copies of scenario 0
    with ZERO node membership: inert in every psum-lowered reduction
    (xbar/xsqbar numerators AND denominators, conv, eobj), so an uneven
    S=7 on a 4-device mesh is exact, not approximately padded."""
    return (-int(S)) % int(mesh.shape[axis])


def _node_xbar(onehot, probs, xk):
    """Per-node weighted mean of nonants; per-scenario gather back.

    The contraction over the scenario axis is the Allreduce analogue
    (phbase.py:75-87): under a sharded-in jit, XLA emits one psum per einsum.
    """
    p = probs[:, None]
    num = jnp.einsum("skn,sk->nk", onehot, p * xk)
    sqnum = jnp.einsum("skn,sk->nk", onehot, p * xk * xk)
    den = jnp.einsum("skn,sk->nk", onehot, jnp.broadcast_to(p, xk.shape))
    den = jnp.maximum(den, 1e-300)
    return num / den, sqnum / den


def _gather_per_scenario(xbar_nk, nid_sk):
    K = nid_sk.shape[1]
    kidx = jnp.arange(K)[None, :]
    return xbar_nk[nid_sk, kidx]


def _solver_fns_for(st: ADMMSettings, mesh, axis):
    """(shared_refresh, shared_frozen, dense_refresh, dense_frozen) for one
    settings variant; dense fns are shard_mapped when on a mesh."""
    # the fused shared-A Pallas kernel cannot ride jit auto-partitioning
    # (a pallas_call is opaque to the partitioner): permit it only when the
    # shared engine's program spans a single device
    shared_pallas_ok = mesh is None or len(mesh.devices.flat) == 1

    def shared_refresh(q, q2, A, cl, cu, lb, ub, x, z, y, yx):
        with jax.default_matmul_precision(st.matmul_precision):
            return shared_admm._solve_shared_impl(
                q, q2, A, cl, cu, lb, ub, st, (x, z, y, yx),
                want_factors=True)

    def shared_frozen(q, q2, A, cl, cu, lb, ub, x, z, y, yx, factors):
        with jax.default_matmul_precision(st.matmul_precision):
            return shared_admm._solve_shared_frozen_impl(
                q, q2, A, cl, cu, lb, ub, factors, (x, z, y, yx), st,
                allow_pallas=shared_pallas_ok)

    def local_refresh(q, q2, A, cl, cu, lb, ub, x, z, y, yx):
        with jax.default_matmul_precision(st.matmul_precision):
            return admm._solve_impl(
                q, q2, A, cl, cu, lb, ub, st, (x, z, y, yx),
                want_factors=True)

    def local_frozen(q, q2, A, cl, cu, lb, ub, x, z, y, yx, factors):
        with jax.default_matmul_precision(st.matmul_precision):
            return admm._solve_frozen_impl(
                q, q2, A, cl, cu, lb, ub, factors, (x, z, y, yx), st)

    if mesh is not None:
        sp = jax.sharding.PartitionSpec(axis)
        sol_spec = admm.BatchSolution(*([sp] * 8), raw=(sp, sp, sp, sp))
        fac_spec = admm.Factors(*([sp] * 7))
        refresh_solve = _shard_map(
            local_refresh, mesh, in_specs=(sp,) * 11,
            out_specs=(sol_spec, fac_spec),
        )
        frozen_solve = _shard_map(
            local_frozen, mesh,
            in_specs=(sp,) * 11 + (fac_spec,),
            out_specs=sol_spec,
        )
    else:
        refresh_solve, frozen_solve = local_refresh, local_frozen
    return shared_refresh, shared_frozen, refresh_solve, frozen_solve


def _ph_objective(arr, state, prox_on, idx, settings):
    dt = settings.jdtype()
    W, xbars, rho = (state.W.astype(dt), state.xbars.astype(dt),
                     state.rho.astype(dt))
    prox_on = jnp.asarray(prox_on, dt)
    q = arr.c.astype(dt).at[:, idx].add(W - prox_on * rho * xbars)
    q2 = arr.q2.astype(dt).at[:, idx].add(prox_on * rho)
    return q, q2, W, rho


def _ph_finish(arr, state, sol, W, rho, idx):
    xk = sol.x[:, idx]
    xbar_nk, _ = _node_xbar(arr.onehot, arr.probs, xk)
    new_xbars = _gather_per_scenario(xbar_nk, arr.nid_sk)
    new_W = W + rho * (xk - new_xbars)
    dev = jnp.abs(xk - new_xbars).mean(axis=1)
    conv = arr.probs @ dev
    lin = jnp.einsum("sn,sn->s", arr.c, sol.x)
    quad = 0.5 * jnp.einsum("sn,sn->s", arr.q2, sol.x * sol.x)
    eobj = arr.probs @ (lin + quad + arr.const)
    new_state = PHState(
        W=new_W, xbars=new_xbars, rho=rho,
        x=sol.x, z=sol.z, y=sol.y, yx=sol.yx,
    )
    return new_state, PHStepOut(conv, eobj, sol.pri_res, sol.dua_res,
                                jnp.max(sol.iters))


def make_ph_step(nonant_idx: np.ndarray, settings: ADMMSettings,
                 mesh: Mesh | None = None, axis: str = "scen"):
    """Back-compat single-step API: the adaptive (refresh) step of
    :func:`make_ph_step_pair`, with the factors dropped.  One compiled
    program per (shapes, settings); PH iterations re-enter it with new state
    only — the persistent-solver analogue (spopt.py:129-144)."""
    refresh, _ = make_ph_step_pair(nonant_idx, settings, mesh, axis)

    def step(state: PHState, arr: PHArrays, prox_on):
        new_state, out, _ = refresh(state, arr, prox_on)
        return new_state, out

    return step


def make_ph_step_pair(nonant_idx: np.ndarray, settings: ADMMSettings,
                      mesh: Mesh | None = None, axis: str = "scen"):
    """(refresh_step, frozen_step) — the factorization-amortized PH iteration.

    ``refresh_step(state, arr, prox_on) -> (state, out, factors)`` runs the
    full adaptive solve (Ruiz + rho adaptation + factorizations + optional
    polish) and returns the final :class:`~tpusppy.solvers.admm.Factors`.
    ``frozen_step(state, arr, prox_on, factors) -> (state, out)`` reuses them:
    no factorization in the program at all, so the steady-state PH iteration
    is pure batched matvec sweeps (the MXU path).  PH leaves (A, q2, bounds)
    unchanged between iterations — only q moves — so factors stay valid; the
    residual-driven while_loop still guards accuracy, and a periodic refresh
    re-adapts rho (see :func:`run_ph`'s ``refresh_every``).

    The engine is picked PER TRACE from ``arr.A.ndim`` (jit specializes on
    shapes, so the branch is free): 3-D A runs the dense per-scenario solver
    (shard_mapped over the mesh), 2-D A the shared-A solver — invoked
    WITHOUT shard_map, under jit auto-partitioning: its cross-scenario
    reductions (shared-rho adaptation, the all-done termination vote) lower
    to psums over the mesh, so every device sees the SAME shared factors and
    per-device factor divergence is structurally impossible.
    """
    idx = jnp.asarray(nonant_idx)
    # executable-cache identity of the single-dispatch step programs:
    # everything baked into the trace that the call signature can't show
    _aot_extra = (settings, axis, aot_cache.mesh_fingerprint(mesh),
                  aot_cache.array_digest(nonant_idx))

    def _solver_fns(st: ADMMSettings):
        return _solver_fns_for(st, mesh, axis)

    shared_refresh, shared_frozen, refresh_solve, frozen_solve = \
        _solver_fns(settings)

    def _objective(arr, state, prox_on):
        return _ph_objective(arr, state, prox_on, idx, settings)

    def _finish(arr, state, sol, W, rho):
        return _ph_finish(arr, state, sol, W, rho, idx)

    @jax.jit
    def refresh_step_1(state: PHState, arr: PHArrays, prox_on):
        q, q2, W, rho = _objective(arr, state, prox_on)
        solve = shared_refresh if arr.A.ndim == 2 else refresh_solve
        sol, factors = solve(
            q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
            state.x, state.z, state.y, state.yx,
        )
        new_state, out = _finish(arr, state, sol, W, rho)
        return new_state, out, factors

    @jax.jit
    def frozen_step_1(state: PHState, arr: PHArrays, prox_on, factors):
        q, q2, W, rho = _objective(arr, state, prox_on)
        solve = shared_frozen if arr.A.ndim == 2 else frozen_solve
        sol = solve(
            q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
            state.x, state.z, state.y, state.yx, factors,
        )
        new_state, out = _finish(arr, state, sol, W, rho)
        return new_state, out

    # AOT executable cache (tpusppy/solvers/aot.py): the single-dispatch
    # step programs are exactly the iter0/refresh cold-start cost — a
    # repeated or resumed run deserializes them instead of recompiling.
    # Strict passthrough when TPUSPPY_AOT_CACHE is disarmed.
    refresh_step_1 = aot_cache.cached_program(
        refresh_step_1, "ph_refresh", key_extra=_aot_extra)
    frozen_step_1 = aot_cache.cached_program(
        frozen_step_1, "ph_frozen", key_extra=_aot_extra)

    # ---- segmented dispatch (shapes too big for one program execution) ----

    @jax.jit
    def _prep_jit(state: PHState, arr: PHArrays, prox_on):
        return _objective(arr, state, prox_on)

    @jax.jit
    def _finish_jit(state: PHState, arr: PHArrays, sol, W, rho):
        return _finish(arr, state, sol, W, rho)

    seg_cache: dict = {}

    def _seg_programs(seg_r, seg_f):
        key = (seg_r, seg_f)
        if key not in seg_cache:
            st_r = dataclasses.replace(settings, max_iter=seg_r)
            st_f = segmented_solvers.seg_settings(settings, seg_f)
            sr, _, lr, _ = _solver_fns(st_r)
            _, sf, _, lf = _solver_fns(st_f)

            @jax.jit
            def refresh_solve_seg(q, q2, arr: PHArrays, warm):
                solve = sr if arr.A.ndim == 2 else lr
                x, z, y, yx = warm
                return solve(q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
                             x, z, y, yx)

            @jax.jit
            def frozen_solve_seg(q, q2, arr: PHArrays, warm, factors):
                solve = sf if arr.A.ndim == 2 else lf
                x, z, y, yx = warm
                return solve(q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
                             x, z, y, yx, factors)

            # short polishing finale for the dense path (single-dispatch
            # refresh polishes; frozen continuations don't — this restores
            # parity from the converged iterate without re-factorizing)
            ce = max(1, settings.check_every)
            st_p = dataclasses.replace(settings, max_iter=2 * ce)

            def local_polish(q, q2, A, cl, cu, lb, ub, x, z, y, yx,
                             factors):
                with jax.default_matmul_precision(st_p.matmul_precision):
                    return admm._solve_frozen_impl(
                        q, q2, A, cl, cu, lb, ub, factors, (x, z, y, yx),
                        st_p, polish=True)

            if mesh is not None:
                sp = jax.sharding.PartitionSpec(axis)
                sol_spec = admm.BatchSolution(
                    *([sp] * 8), raw=(sp, sp, sp, sp))
                fac_spec = admm.Factors(*([sp] * 7))
                local_polish = _shard_map(
                    local_polish, mesh,
                    in_specs=(sp,) * 11 + (fac_spec,),
                    out_specs=sol_spec,
                )

            @jax.jit
            def polish_solve_seg(q, q2, arr: PHArrays, warm, factors):
                x, z, y, yx = warm
                return local_polish(q, q2, arr.A, arr.cl, arr.cu, arr.lb,
                                    arr.ub, x, z, y, yx, factors)

            seg_cache[key] = (refresh_solve_seg, frozen_solve_seg,
                              polish_solve_seg)
        return seg_cache[key]

    def _segments_for(arr):
        S_dev, n, m, factor_batch, sf = _dispatch_model_params(arr, mesh)
        return _dispatch_segments(S_dev, n, m, settings,
                                  factor_batch=factor_batch,
                                  sparse_factor=sf)

    def _seg_flops_for(arr, seg_f):
        """Per-segment model flops (speculation/dispatch billing unit)."""
        from ..solvers import flops as flops_model
        S_dev, n, m, _, sf = _dispatch_model_params(arr, mesh)
        return flops_model.sweep_flops(S_dev, n, m, sf) * seg_f

    # A mesh spanning several processes cannot make data-dependent host
    # decisions: sol.iters' shards are non-addressable (fetch raises), and
    # even local-shard votes could disagree across processes — different
    # dispatch counts would deadlock the collectives.  Run the full budget
    # deterministically there (and NEVER speculate — continue_frozen
    # disables the pipeline for caller-provided all_done); single-process
    # meshes early-exit normally through the single-fetch stop-stats path,
    # which also unlocks the speculative overlapped continuation.
    multiproc = mesh is not None and len(
        {d.process_index for d in mesh.devices.flat}) > 1

    # plateau stop is data-dependent => multi-process meshes must not use it
    plateau = None if multiproc else settings.segment_plateau_rtol

    def _continue_kw(arr):
        """continue_frozen keywords for this mesh posture."""
        if multiproc:
            return {"all_done": lambda sol: False, "plateau_rtol": None}
        S_dev, n, m, _, _ = _dispatch_model_params(arr, mesh)
        return {"plateau_rtol": plateau,
                "pipeline": segmented_solvers.pipeline_enabled(
                    settings, S_dev, n, m)}

    def refresh_step(state: PHState, arr: PHArrays, prox_on):
        seg_r, seg_f = _segments_for(arr)
        if seg_r >= settings.max_iter and seg_f >= settings.max_iter:
            return refresh_step_1(state, arr, prox_on)
        rsolve, fsolve, psolve = _seg_programs(seg_r, seg_f)
        q, q2, W, rho = _prep_jit(state, arr, prox_on)
        warm = (state.x, state.z, state.y, state.yx)
        sol, factors = rsolve(q, q2, arr, warm)
        sol = segmented_solvers.continue_frozen(
            lambda w: fsolve(q, q2, arr, w, factors), sol, seg_f,
            segmented_solvers.refresh_budget(settings, seg_r),
            seg_flops=_seg_flops_for(arr, seg_f), **_continue_kw(arr))
        if arr.A.ndim == 3 and settings.polish and settings.polish_passes:
            sol = psolve(q, q2, arr, sol.raw, factors)
        new_state, out = _finish_jit(state, arr, sol, W, rho)
        return new_state, out, factors

    def frozen_step(state: PHState, arr: PHArrays, prox_on, factors):
        seg_r, seg_f = _segments_for(arr)
        if seg_r >= settings.max_iter and seg_f >= settings.max_iter:
            return frozen_step_1(state, arr, prox_on, factors)
        _, fsolve, _ = _seg_programs(seg_r, seg_f)
        q, q2, W, rho = _prep_jit(state, arr, prox_on)
        warm = (state.x, state.z, state.y, state.yx)
        sol = fsolve(q, q2, arr, warm, factors)
        if multiproc:
            # deterministic schedule: the first dispatch cannot be checked
            # (non-addressable shards), so the continuation always runs
            # the full budget
            sol = segmented_solvers.continue_frozen(
                lambda w: fsolve(q, q2, arr, w, factors), sol, seg_f,
                settings.max_iter - seg_f, all_done=lambda s: False,
                plateau_rtol=None,
                seg_flops=_seg_flops_for(arr, seg_f))
        else:
            # check_incoming folds the first-dispatch verdict into the
            # (possibly pipelined) continuation's single-fetch protocol
            sol = segmented_solvers.continue_frozen(
                lambda w: fsolve(q, q2, arr, w, factors), sol, seg_f,
                settings.max_iter - seg_f, check_incoming=True,
                seg_flops=_seg_flops_for(arr, seg_f), **_continue_kw(arr))
        new_state, out = _finish_jit(state, arr, sol, W, rho)
        return new_state, out

    return refresh_step, frozen_step


def fused_iteration_cap(arr: PHArrays, settings: ADMMSettings,
                        mesh: Mesh | None = None,
                        refresh_every: int = 16) -> int:
    """Max PH iterations safely fusable into ONE device program for these
    shapes (a multiple of ``refresh_every``; 0 = do not fuse).

    Sized with the same flop model as :func:`dispatch_segments` against the
    remote worker's ~60 s execution kill; shapes that need segmentation get
    0 and must use the step pair.
    """
    S_dev, n, m, factor_batch, sf = _dispatch_model_params(arr, mesh)
    return segmented_solvers.fused_iteration_budget(
        S_dev, n, m, settings, refresh_every,
        factor_batch=factor_batch,
        eff_flops=_DISPATCH_EFF_FLOPS, target_secs=_DISPATCH_TARGET_SECS,
        sparse_factor=sf)


def make_ph_fused_step(nonant_idx: np.ndarray, settings: ADMMSettings,
                       mesh: Mesh | None = None, axis: str = "scen",
                       chunk: int = 16, refresh_every: int | None = None,
                       donate: bool = True, collect: str = "last"):
    """ONE jitted program running ``chunk`` PH iterations — the latency-proof
    headline path.

    The step pair (:func:`make_ph_step_pair`) pays one device dispatch per PH
    iteration; over a remote tunnel each dispatch is a serial RPC, and for
    small programs (farmer: S=1000, n=44) the RPC dominates — the measured
    rate collapses ~25x when the tunnel is slow.  This factory fuses the
    whole refresh cadence into one program: an adaptive refresh (Ruiz + rho
    adaptation + factorization) at iteration 0 and every ``refresh_every``
    after it, frozen factor-reusing sweeps in between, all inside nested
    ``lax.scan`` — so ``chunk`` PH iterations cost ONE dispatch.  Identical
    trajectory to driving the step pair from the host with the same cadence
    (tests assert this).

    This replaces the reference's per-iteration solve round-trip
    (``mpisppy/spopt.py:226-307``: one ``solve()`` per rank per iteration,
    every iteration a fresh host<->solver exchange) with a single compiled
    multi-iteration program — the XLA-native amortization.

    ``refresh_every`` defaults to ``chunk`` (one refresh at the top).
    ``chunk`` need NOT be a multiple of ``refresh_every``: a trailing
    partial block (refresh + the leftover frozen iterations) preserves the
    host cadence — refreshes land exactly at iteration indices that are
    multiples of ``refresh_every`` within the chunk.  Callers must size
    ``chunk`` within :func:`fused_iteration_cap` (or a measured cap from
    :mod:`tpusppy.tune`) — a fused program past the worker watchdog is
    killed mid-flight, which the host cannot recover.

    ``donate=True`` (default) donates the incoming :class:`PHState` buffers
    to the program (``jax.jit`` ``donate_argnums``): the state is updated
    in place on device instead of round-tripping fresh allocations per
    chunk.  The caller's input state is CONSUMED — rebind it
    (``state, out = fused(state, arr, p)``); reading the old reference
    afterwards raises.  Pass ``donate=False`` for call sites that must
    re-enter the same state object (A/B comparisons).

    ``collect="last"`` returns the LAST iteration's :class:`PHStepOut`;
    ``collect="trace"`` returns the full per-iteration trace (leaves gain a
    leading ``chunk`` axis), carried device-side so a measurement window of
    many chunks needs ONE host fetch at the end instead of per-iteration
    conv/eobj syncs.

    Returns ``fused(state, arr, prox_on) -> (state, out)``.
    """
    if refresh_every is None:
        refresh_every = chunk
    if chunk < 1 or refresh_every < 1:
        raise ValueError(
            f"chunk ({chunk}) and refresh_every ({refresh_every}) must be "
            f">= 1")
    if collect not in ("last", "trace"):
        raise ValueError(f"collect must be 'last' or 'trace': {collect!r}")
    n_full, rem = divmod(chunk, refresh_every)
    idx = jnp.asarray(nonant_idx)
    shared_refresh, shared_frozen, refresh_solve, frozen_solve = \
        _solver_fns_for(settings, mesh, axis)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def fused(state: PHState, arr: PHArrays, prox_on):
        def block_outs(state, length):
            """One refresh + (length-1) frozen iterations; outs stacked
            along a leading ``length`` axis (the device-side trace)."""
            q, q2, W, rho = _ph_objective(arr, state, prox_on, idx, settings)
            rsolve = shared_refresh if arr.A.ndim == 2 else refresh_solve
            sol, factors = rsolve(
                q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
                state.x, state.z, state.y, state.yx)
            state, out0 = _ph_finish(arr, state, sol, W, rho, idx)

            def frozen_iter(st, _):
                q, q2, W, rho = _ph_objective(arr, st, prox_on, idx,
                                              settings)
                fsolve = shared_frozen if arr.A.ndim == 2 else frozen_solve
                sol = fsolve(q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
                             st.x, st.z, st.y, st.yx, factors)
                return _ph_finish(arr, st, sol, W, rho, idx)

            if length > 1:
                state, outs = jax.lax.scan(
                    frozen_iter, state, None, length=length - 1)
                outs = jax.tree.map(
                    lambda a0, a: jnp.concatenate([a0[None], a]), out0, outs)
            else:
                outs = jax.tree.map(lambda a: a[None], out0)
            return state, outs

        traces = []
        if n_full:
            state, outs = jax.lax.scan(
                lambda s, _: block_outs(s, refresh_every), state, None,
                length=n_full)
            # (n_full, refresh_every, ...) -> (n_full * refresh_every, ...)
            traces.append(jax.tree.map(
                lambda a: a.reshape((-1,) + a.shape[2:]), outs))
        if rem:
            state, outs = block_outs(state, rem)
            traces.append(outs)
        trace = (traces[0] if len(traces) == 1 else jax.tree.map(
            lambda *xs: jnp.concatenate(xs), *traces))
        if collect == "trace":
            return state, trace
        return state, jax.tree.map(lambda a: a[-1], trace)

    # AOT executable cache: the fused multi-iteration program is the
    # dominant bench/wheel cold-start cost (one compile per (chunk,
    # refresh_every) cadence) — repeated and ladder-sibling runs
    # deserialize it in milliseconds instead (tpusppy/solvers/aot.py;
    # passthrough when disarmed)
    return aot_cache.cached_program(
        fused, "ph_fused",
        key_extra=(settings, chunk, refresh_every, bool(donate), collect,
                   axis, aot_cache.mesh_fingerprint(mesh),
                   aot_cache.array_digest(nonant_idx)))


# scalars the in-wheel bound pass appends to the packed measurement
# (lean-pack compatible by construction): [computed flag, Lagrangian outer
# bound, xhat-at-xbar expected objective, feasible probability mass of the
# frozen evaluation, its sweep count (billing)]
BOUND_PACK_LEN = 5


def bound_pack_len(bounds: bool = False, int_sweep: bool = False) -> int:
    """Length of the in-wheel bound tail: :data:`BOUND_PACK_LEN` scalars,
    plus the :data:`~tpusppy.solvers.integer.INT_BOUND_EXTRA` integer
    extras (feasible-candidate count, best candidate index, reduced-cost
    fixed slots, untightened outer) when the batched integer sweep is
    armed (doc/integer.md)."""
    if not bounds:
        return 0
    if int_sweep:
        from ..solvers import integer as integer_solvers

        return BOUND_PACK_LEN + integer_solvers.INT_BOUND_EXTRA
    return BOUND_PACK_LEN


def megastep_measure_len(n_iters: int, S: int, n: int, K: int,
                         pack: str = "full", bounds: bool = False,
                         int_sweep: bool = False) -> int:
    """Length of the packed megastep measurement vector.

    ``pack="lean"`` is the O(1)-host-traffic wheel posture (ROADMAP item
    1): the fetch carries the per-iteration stats plus per-scenario
    residual/done diagnostics ONLY — the (S, n) iterate and the (S, K)
    W/xbars stay device-resident in the returned :class:`PHState`, to be
    fetched explicitly (and billed) at checkpoint/termination boundaries
    instead of every window.

    ``bounds=True`` (in-wheel certification, doc/pipeline.md) appends
    :func:`bound_pack_len` scalars — outer/inner bound evidence computed
    on the window's final device state — compatible with BOTH packs (the
    bound pass emits scalars only); ``int_sweep=True`` is the batched
    integer variant (doc/integer.md) with its longer tail."""
    base = 6 * n_iters + 2 + 3 * S
    if pack != "lean":
        base += S * n + 2 * S * K
    return base + bound_pack_len(bounds, int_sweep)


def unpack_bound_tail(out: dict, vec, int_sweep: bool = False) -> dict:
    """Install the in-wheel bound scalars (the trailing
    :func:`bound_pack_len` entries of a ``bounds=True`` measurement) into
    an unpacked measurement dict.  ``bound_computed`` False means the
    window's traced ``bound_live`` flag was off (cadence skip) — the
    other entries are inert zeros then.  ``int_sweep`` additionally
    parses the integer extras (``int_feas_cands``/``int_best_idx``/
    ``int_rcfix_slots``/``bound_outer_base``)."""
    tail_len = bound_pack_len(True, int_sweep)
    tail = np.asarray(vec)[-tail_len:]
    out["bound_computed"] = bool(tail[0])
    out["bound_outer"] = float(tail[1])
    out["bound_inner_obj"] = float(tail[2])
    out["bound_inner_feas"] = float(tail[3])
    out["bound_sweeps"] = float(tail[4])
    if int_sweep:
        out["int_feas_cands"] = int(tail[5])
        out["int_best_idx"] = int(tail[6])
        out["int_rcfix_slots"] = int(tail[7])
        out["bound_outer_base"] = float(tail[8])
    return out


def megastep_unpack(vec, n_iters: int, S: int, n: int, K: int,
                    pack: str = "full", bounds: bool = False,
                    int_sweep: bool = False) -> dict:
    """Split a fetched :func:`make_wheel_megastep` measurement.

    Returns per-iteration arrays (length ``n_iters``; entries past
    ``executed`` are inert zeros — the early-exit mask froze those steps):
    ``conv``, ``eobj``, ``pri_max``, ``dua_max``, ``iters``, ``all_done``;
    the ``executed`` iteration count; the ``refresh_hit`` flag (an
    iterate failed the in-scan acceptance test — its update was masked
    out, exactly as the serial protocol discards a rejected frozen
    solve, and the host must refresh; index ``executed`` of the per-
    iteration arrays then holds the REJECTED iterate's stats so its
    dispatched sweeps can be billed); and the FINAL executed iterate's
    ``pri``/``dua``/``done`` (S,), ``x`` (S, n), ``W``/``xbars`` (S, K) —
    everything the host wheel reads between termination checks, from ONE
    fetch.  With ``pack="lean"`` the x/W/xbars blocks are absent (device-
    resident state; see :func:`megastep_measure_len`) and those keys are
    not in the dict.  ``bounds=True`` additionally parses the in-wheel
    bound tail (:func:`unpack_bound_tail`)."""
    vec = np.asarray(vec)
    N = n_iters
    per = vec[:6 * N].reshape(6, N)
    off = 6 * N
    executed = int(vec[off])
    refresh_hit = bool(vec[off + 1])
    off += 2
    out = {
        "conv": per[0], "eobj": per[1], "pri_max": per[2],
        "dua_max": per[3], "iters": per[4], "all_done": per[5] != 0.0,
        "executed": executed, "refresh_hit": refresh_hit,
        "pri": vec[off:off + S], "dua": vec[off + S:off + 2 * S],
        "done": vec[off + 2 * S:off + 3 * S] != 0.0,
    }
    off += 3 * S
    if bounds:
        out = unpack_bound_tail(out, vec, int_sweep=int_sweep)
    if pack == "lean":
        return out
    out["x"] = vec[off:off + S * n].reshape(S, n)
    off += S * n
    out["W"] = vec[off:off + S * K].reshape(S, K)
    off += S * K
    out["xbars"] = vec[off:off + S * K].reshape(S, K)
    return out


def _bound_pass_terms(arr, st, idx, settings, frozen_fn, factors,
                      feas_tol, int_mask, xhat_threshold):
    """One engine leg of the IN-WHEEL bound pass (doc/pipeline.md
    "In-wheel certification"): probability-weighted partial sums of the
    two certification bounds, computed as fused device contractions on the
    window's final device-resident :class:`PHState` — so a megastep window
    can certify without any spoke device program.

    * OUTER — the Lagrangian dual bound (W on, prox off): the subproblem
      objective ``c + W`` on the nonant columns, evaluated through the
      single-sourced :func:`~tpusppy.solvers.admm.
      dual_objective_with_margin_traced` weak-duality assembly with the
      state's row duals ``y`` (ANY y certifies; the carried duals of a
      near-converged wheel are tight) — the
      ``cylinders.lagrangian_bounder`` semantics without the spoke's own
      batched solve.
    * INNER — xhat-at-xbar: the candidate is the window's consensus
      ``xbars`` (integer nonant slots rounded at ``xhat_threshold``, the
      ``cylinders.xhatxbar_bounder.xbar_candidate`` rule), clamped onto
      the nonant columns and evaluated by ONE batched frozen solve.  The
      clamped problem is solved under the PH-AUGMENTED (q, q2) — on the
      clamped box the augmentation differs from the plain objective only
      on fixed coordinates (a constant), so the minimizer is identical
      AND the window's cached factors match exactly; the reported
      objective is the PLAIN one.  Feasibility is the ``Xhat_Eval`` gate:
      the per-scenario primal residual against ``feas_tol``, emitted as a
      probability mass so the host applies the all-scenarios rule.

    Returns ``(outer, inner_obj, feas_mass, sweeps)`` scalars; the
    bucketed kernel sums the per-bucket contributions (probs are
    global-tree slices there, so the sums compose exactly)."""
    dt = settings.jdtype()
    W = st.W.astype(dt)
    qL = arr.c.astype(dt).at[:, idx].add(W)
    packed = admm.dual_objective_with_margin_traced(
        qL, arr.q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
        st.y.astype(dt), st.x.astype(dt))
    outer = arr.probs @ (packed[0].astype(dt) - packed[1].astype(dt)
                         + arr.const)
    cand = st.xbars.astype(dt)
    if int_mask is not None and int_mask.any():
        cand = jnp.where(jnp.asarray(int_mask)[None, :],
                         jnp.floor(cand + (1.0 - xhat_threshold)), cand)
    # the `xbar_candidate` bounds clip: consensus means carry ADMM
    # tolerance noise (u = -4e-8), and a clamped column eps outside its
    # box poisons every coupled row (p <= pmax*u < 0 vs p >= 0) — the
    # frozen evaluation would read a 1e-8 rounding artifact as batchwide
    # infeasibility
    cand = jnp.clip(cand, arr.lb.astype(dt)[:, idx],
                    arr.ub.astype(dt)[:, idx])
    lb2 = arr.lb.at[:, idx].set(cand)
    ub2 = arr.ub.at[:, idx].set(cand)
    q, q2, _, _ = _ph_objective(arr, st, 1.0, idx, settings)
    x0 = st.x.astype(dt).at[:, idx].set(cand)
    sol = frozen_fn(q, q2, arr.A, arr.cl, arr.cu, lb2, ub2,
                    x0, st.z, st.y, st.yx, factors)
    lin = jnp.einsum("sn,sn->s", arr.c.astype(dt), sol.x)
    quad = 0.5 * jnp.einsum("sn,sn->s", arr.q2.astype(dt),
                            sol.x * sol.x)
    inner_obj = arr.probs @ (lin + quad + arr.const)
    feas = arr.probs @ (sol.pri_res < jnp.asarray(feas_tol, dt)).astype(dt)
    return (outer.astype(dt), inner_obj.astype(dt), feas.astype(dt),
            jnp.max(sol.iters).astype(dt))


def make_wheel_megastep(nonant_idx: np.ndarray, settings: ADMMSettings,
                        mesh: Mesh | None = None, axis: str = "scen",
                        n_iters: int = 8, donate: bool = True,
                        pack: str = "full", bounds: bool = False,
                        int_nonants: np.ndarray | None = None,
                        xhat_threshold: float = 0.5,
                        int_rounding: tuple | None = None,
                        int_cols: np.ndarray | None = None,
                        rcfix_slack: float = 1e-5,
                        int_rcfix: bool = True):
    """ONE jitted program running up to ``n_iters`` FROZEN wheel iterations
    — the device-resident wheel megakernel (ROADMAP item 4).

    Each scan step is a full PH wheel iteration: augmented objective from
    the carried (W, xbars, rho), the frozen factor-reusing subproblem
    sweep (dense, shared-A, or SparseA/structured — picked per trace from
    ``arr.A``), and the PH outer update (``Compute_Xbar``/``Update_W``/
    convergence, :mod:`tpusppy.phbase` ported to the pure device form
    ``_ph_finish`` — under a mesh its scenario-axis contractions lower to
    psum trees, so N iterations cost ZERO per-iteration host traffic).
    The program returns the new device state plus ONE packed measurement
    vector (:func:`megastep_unpack`): per-iteration stats, the executed
    count, and the final iterate — the host fetches once per megastep
    instead of once per iteration.

    In-scan early exit: the scan always runs ``n_iters`` steps, but once
    the PH convergence test fires (``conv < convthresh``, evaluated after
    each iteration exactly like the serial loop's break) — or the step
    index reaches the traced ``n_live`` budget — the remaining steps take
    the dead ``lax.cond`` branch: no sweeps, state passes through
    untouched.  The packed measurement records the true stopping
    iteration, so results are identical to the serial per-iteration
    protocol that broke at the same iteration, and a single compiled
    program serves any executed count <= ``n_iters``.

    In-scan ACCEPTANCE (the serial frozen protocol's per-iteration test,
    ``spopt._solve_amortized``): an iterate that is neither eps-converged
    nor within the traced ``accept_tol`` residual ladder is DISCARDED —
    its state update is masked out and the window stops with
    ``refresh_hit`` set, exactly as the serial path throws away a
    rejected frozen solve and re-solves adaptively.  The host then runs
    that iteration through the legacy refresh path, so trajectories stay
    identical to serial even when factor aging degrades the frozen
    residuals mid-window.  Pass ``accept_tol=inf`` to disable (raw
    N-iteration fusion).

    Callers must size ``n_iters`` within
    :func:`tpusppy.solvers.segmented.megastep_cap` (a megastep is N
    iterations of work against the worker watchdog's per-execution kill)
    and bill executed iterations via
    :func:`~tpusppy.solvers.segmented.bill_megastep`.  SINGLE-CONTROLLER
    fetch contract: the packed measurement is fetched by the host, which
    needs addressable shards (same restriction as the segmented
    stop-stats protocol).

    ``donate=True`` donates the incoming :class:`PHState` (the caller
    rebinds); pass False for A/B comparisons re-entering one state.

    ``pack="lean"`` drops the final iterate's x/W/xbars from the packed
    measurement (:func:`megastep_measure_len`): those leaves live on in
    the RETURNED device state, making the per-window host traffic O(S)
    diagnostics instead of O(S·n) state — the big-S wheel fetches full
    state only at checkpoint/termination boundaries
    (:meth:`tpusppy.phbase.PHBase._sync_host_state`).

    ``bounds=True`` makes the megastep SELF-CERTIFYING (in-wheel
    certification, doc/pipeline.md): after the scan, an optional bound
    pass (:func:`_bound_pass_terms` — the Lagrangian outer bound and the
    xhat-at-xbar inner bound as fused contractions on the final device
    state) appends :data:`BOUND_PACK_LEN` scalars to the packed
    measurement (lean-pack compatible).  The pass is gated by the TRACED
    ``bound_live`` flag — a cadence skip takes a dead ``lax.cond`` branch
    at zero cost inside the SAME compiled program, so the bound cadence
    never multiplies compiles or AOT cache entries.  ``int_nonants`` is
    the (K,) integer mask of nonant slots (candidate rounding at
    ``xhat_threshold``); both are baked constants and ride the AOT key.

    ``int_rounding`` (a tuple of rounding thresholds) arms the BATCHED
    INTEGER sweep (doc/integer.md) for ``bounds=True`` families with
    integer nonants: the bound pass becomes the vmapped best-of-C
    rounding ladder + SLAM slams with device argmin over feasible
    candidates, plus reduced-cost fixing from the frozen duals and a
    tightened Lagrangian outer bound
    (:func:`tpusppy.solvers.integer.integer_bound_pass`); the bound tail
    grows by :data:`~tpusppy.solvers.integer.INT_BOUND_EXTRA` scalars.
    ``int_cols`` is the (n,) mask of ALL integer columns (the
    reduced-cost fixing scope; defaults to integer nonant slots only).
    ``int_rcfix=False`` disables the reduced-cost fixing +
    re-certification (MANDATORY for families with second-stage integer
    columns: the candidate evaluation relaxes them, so its value is not
    a valid integer-minimum upper bound for the fixing argument — see
    :func:`tpusppy.solvers.integer.integer_bound_pass`).  Families
    WITHOUT integer nonants ignore all the integer knobs and compile
    the byte-identical legacy bound pass (the warm-serving zero-miss
    contract — pinned by test).

    Returns ``mega(state, arr, prox_on, factors, convthresh, n_live,
    accept_tol) -> (state, packed)`` — with ``bounds=True`` the signature
    gains trailing ``(bound_live, feas_tol)`` arguments.
    """
    if n_iters < 1:
        raise ValueError(f"n_iters ({n_iters}) must be >= 1")
    if pack not in ("full", "lean"):
        raise ValueError(f"pack must be 'full' or 'lean': {pack!r}")
    idx = jnp.asarray(nonant_idx)
    int_mask = (None if int_nonants is None
                else np.asarray(int_nonants, dtype=bool))
    # the integer sweep exists in the program ONLY when the family has
    # integer nonants AND a rounding ladder was requested — a bounds=True
    # megastep without integer slots stays byte-identical to the legacy
    # program whatever the integer knobs say (warm serving stays
    # zero-miss; pinned by test)
    int_sweep = bool(bounds and int_mask is not None and int_mask.any()
                     and int_rounding)
    int_thresholds = tuple(float(t) for t in (int_rounding or ()))
    from ..solvers import integer as integer_solvers
    tail_len = bound_pack_len(True, int_sweep)
    if int_sweep:
        int_cols_mask = (np.asarray(int_cols, dtype=bool)
                         if int_cols is not None else None)
        int_mask_arr = jnp.asarray(int_mask)
    _, shared_frozen, _, frozen_solve = _solver_fns_for(settings, mesh, axis)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def mega(state: PHState, arr: PHArrays, prox_on, factors, convthresh,
             n_live, accept_tol, bound_live=False, feas_tol=1e-3):
        dt = settings.jdtype()
        S = arr.c.shape[0]
        n_live_t = jnp.asarray(n_live, jnp.int32)
        thresh = jnp.asarray(convthresh, dt)
        tol = jnp.asarray(accept_tol, dt)

        def body(carry, k):
            st, pri, dua, done_s, executed, stopped, refresh = carry
            live = (~stopped) & (k < n_live_t)

            def live_fn(op):
                st, pri, dua, done_s, executed, stopped, refresh = op
                q, q2, W, rho = _ph_objective(arr, st, prox_on, idx,
                                              settings)
                fsolve = (shared_frozen if arr.A.ndim == 2
                          else frozen_solve)
                sol = fsolve(q, q2, arr.A, arr.cl, arr.cu, arr.lb,
                             arr.ub, st.x, st.z, st.y, st.yx, factors)
                # the serial acceptance test (NaN/inf residuals — e.g. a
                # divergence-frozen scenario — fail it too, so a rejected
                # iterate can never poison the carried state)
                ok = jnp.all(sol.done) | jnp.all(
                    (sol.pri_res <= tol) & (sol.dua_res <= tol))
                new_st, out = _ph_finish(arr, st, sol, W, rho, idx)
                stats = jnp.stack([
                    out.conv.astype(dt), out.eobj.astype(dt),
                    jnp.max(sol.pri_res).astype(dt),
                    jnp.max(sol.dua_res).astype(dt),
                    jnp.max(sol.iters).astype(dt),
                    jnp.all(sol.done).astype(dt)])
                # rejected iterate: mask the whole STATE update (the
                # serial protocol discards the failed frozen solve and
                # re-solves adaptively — the host's refresh does that).
                # Its stats row stays recorded at index ``executed`` so
                # the host can BILL the dispatched-but-discarded sweeps.
                sel = lambda a, b: jnp.where(ok, a, b)
                new_st = jax.tree.map(sel, new_st, st)
                # the serial loop breaks AFTER the iteration whose conv
                # crossed the threshold: this iteration's state is kept,
                # later ones are masked
                return ((new_st, sel(sol.pri_res, pri),
                         sel(sol.dua_res, dua), sel(sol.done, done_s),
                         executed + ok.astype(jnp.int32),
                         stopped | (ok & (out.conv < thresh)) | ~ok,
                         refresh | ~ok),
                        stats)

            def dead_fn(op):
                return op, jnp.zeros((6,), dt)

            return jax.lax.cond(
                live, live_fn, dead_fn,
                (st, pri, dua, done_s, executed, stopped, refresh))

        inf = jnp.full((S,), jnp.inf, dt)
        carry0 = (state, inf, inf, jnp.zeros((S,), bool),
                  jnp.zeros((), jnp.int32), jnp.zeros((), bool),
                  jnp.zeros((), bool))
        (st, pri, dua, done_s, executed, _, refresh), stats = jax.lax.scan(
            body, carry0, jnp.arange(n_iters, dtype=jnp.int32))
        parts = [
            stats.T.reshape(-1),          # [conv|eobj|pri|dua|iters|done]xN
            executed.astype(dt)[None], refresh.astype(dt)[None],
            pri.astype(dt), dua.astype(dt), done_s.astype(dt),
        ]
        if pack == "full":
            parts += [st.x.astype(dt).reshape(-1),
                      st.W.astype(dt).reshape(-1),
                      st.xbars.astype(dt).reshape(-1)]
        if bounds:
            fsolve = shared_frozen if arr.A.ndim == 2 else frozen_solve

            if int_sweep:
                # fixing scope: all integer columns when the caller
                # supplied them, else the integer nonant slots only
                if int_cols_mask is not None:
                    cols = jnp.asarray(int_cols_mask)
                else:
                    cols = jnp.zeros(arr.c.shape[1], bool).at[idx].set(
                        int_mask_arr)

                def bounds_on(stf):
                    # PH-augmented objective, prox ON — the factors match
                    # exactly (the _bound_pass_terms argument)
                    q, q2, _, _ = _ph_objective(arr, stf, 1.0, idx,
                                                settings)
                    return integer_solvers.integer_bound_pass(
                        arr, stf, idx, q, q2, fsolve, factors, feas_tol,
                        dt, int_mask_arr, int_thresholds, cols,
                        rcfix_slack, rcfix_enabled=bool(int_rcfix))
            else:
                def bounds_on(stf):
                    outer, inner, feas, sweeps = _bound_pass_terms(
                        arr, stf, idx, settings, fsolve, factors,
                        feas_tol, int_mask, xhat_threshold)
                    return jnp.stack(
                        [jnp.ones((), dt), outer, inner, feas, sweeps])

            parts.append(jax.lax.cond(
                jnp.asarray(bound_live, bool),
                bounds_on, lambda _: jnp.zeros((tail_len,), dt), st))
        return st, jnp.concatenate(parts)

    # AOT executable cache: one megakernel compile per width N — resumed
    # and repeated wheels load the serialized executable instead
    # (tpusppy/solvers/aot.py; passthrough when disarmed).  The bound-pass
    # variant (and its baked rounding constants) rides the key so warm
    # serving of a self-certifying wheel stays zero-miss.
    return aot_cache.cached_program(
        mega, "wheel_megastep",
        key_extra=(settings, n_iters, bool(donate), axis, pack,
                   # the rounding constants exist only in the bounds=True
                   # program — keying them while bounds are off would
                   # recompile a byte-identical megastep over an inert
                   # knob (a warm-serving aot.misses hit).  The integer-
                   # sweep constants (ladder + fixing scope) likewise
                   # ride the key ONLY when the sweep is compiled in: a
                   # no-integer-slots family keys identically whatever
                   # the integer knobs say.
                   (float(xhat_threshold),
                    None if int_mask is None
                    else aot_cache.array_digest(int_mask),
                    (int_thresholds, float(rcfix_slack),
                     bool(int_rcfix),
                     None if int_cols is None
                     else aot_cache.array_digest(
                         np.asarray(int_cols, dtype=bool)))
                    if int_sweep else None)
                   if bounds else None,
                   aot_cache.mesh_fingerprint(mesh),
                   aot_cache.array_digest(nonant_idx)))


def bucketed_megastep_measure_len(n_iters: int, shapes, K: int,
                                  bounds: bool = False,
                                  int_sweep: bool = False) -> int:
    """Length of the bucketed packed measurement (``shapes`` =
    ``[(S_b, n_b), ...]`` per bucket, concatenated in bucket order).
    ``bounds`` appends the :func:`bound_pack_len` in-wheel bound tail
    (``int_sweep`` = the longer batched-integer variant)."""
    S = sum(s for s, _ in shapes)
    return (6 * n_iters + 2 + 3 * S
            + sum(s * n for s, n in shapes) + 2 * S * K
            + bound_pack_len(bounds, int_sweep))


def bucketed_megastep_unpack(vec, n_iters: int, shapes, K: int,
                             bounds: bool = False,
                             int_sweep: bool = False) -> dict:
    """Split a fetched :func:`make_bucketed_wheel_megastep` measurement.

    Global per-iteration stats exactly as :func:`megastep_unpack`; the
    per-scenario blocks come back PER BUCKET (``shapes`` order): ``pri``/
    ``dua``/``done`` are lists of (S_b,) arrays, ``x`` a list of
    (S_b, n_b), ``W``/``xbars`` lists of (S_b, K) — the host scatters
    them through each bucket's scenario-index array.  ``bounds`` parses
    the trailing in-wheel bound tail (:func:`unpack_bound_tail`)."""
    vec = np.asarray(vec)
    N = n_iters
    per = vec[:6 * N].reshape(6, N)
    off = 6 * N
    out = {
        "conv": per[0], "eobj": per[1], "pri_max": per[2],
        "dua_max": per[3], "iters": per[4], "all_done": per[5] != 0.0,
        "executed": int(vec[off]), "refresh_hit": bool(vec[off + 1]),
    }
    off += 2
    if bounds:
        out = unpack_bound_tail(out, vec, int_sweep=int_sweep)
    pri, dua, done = [], [], []
    for S_b, _ in shapes:
        pri.append(vec[off:off + S_b])
        dua.append(vec[off + S_b:off + 2 * S_b])
        done.append(vec[off + 2 * S_b:off + 3 * S_b] != 0.0)
        off += 3 * S_b
    out.update(pri=pri, dua=dua, done=done)
    xs = []
    for S_b, n_b in shapes:
        xs.append(vec[off:off + S_b * n_b].reshape(S_b, n_b))
        off += S_b * n_b
    Ws, xbs = [], []
    for S_b, _ in shapes:
        Ws.append(vec[off:off + S_b * K].reshape(S_b, K))
        off += S_b * K
    for S_b, _ in shapes:
        xbs.append(vec[off:off + S_b * K].reshape(S_b, K))
        off += S_b * K
    out.update(x=xs, W=Ws, xbars=xbs)
    return out


def _bucketed_finish(arrs, states, sols, Ws, rhos, idx, dt):
    """The cross-bucket PH outer update as pure device contractions: each
    bucket contributes its node-membership partial sums (its ``onehot``/
    ``probs`` are GLOBAL-tree slices), the per-node averages form once
    globally, and each bucket gathers its scenarios' rows back — under a
    mesh every cross-bucket sum is the same psum tree the homogeneous
    :func:`_node_xbar` lowers to.  Returns (new_states, conv, eobj).

    The bucketed kernel packs FULL measurements only: the lean
    (device-resident, O(1)-host) posture is homogeneous-only today —
    ``_megastep_solve_bucketed`` says so loudly when ``ph_device_state``
    is set on a bucketed family."""
    num = den = None
    xks = []
    for arr, sol in zip(arrs, sols):
        xk = sol.x[:, idx]
        xks.append(xk)
        p = arr.probs[:, None]
        nm = jnp.einsum("skn,sk->nk", arr.onehot, p * xk)
        dn = jnp.einsum("skn,sk->nk", arr.onehot,
                        jnp.broadcast_to(p, xk.shape))
        num = nm if num is None else num + nm
        den = dn if den is None else den + dn
    xbar_nk = num / jnp.maximum(den, 1e-300)
    new_states = []
    conv = jnp.zeros((), dt)
    eobj = jnp.zeros((), dt)
    for arr, st, sol, W, rho, xk in zip(arrs, states, sols, Ws, rhos, xks):
        new_xbars = _gather_per_scenario(xbar_nk, arr.nid_sk)
        new_W = W + rho * (xk - new_xbars)
        dev = jnp.abs(xk - new_xbars).mean(axis=1)
        conv = conv + (arr.probs @ dev).astype(dt)
        lin = jnp.einsum("sn,sn->s", arr.c, sol.x)
        quad = 0.5 * jnp.einsum("sn,sn->s", arr.q2, sol.x * sol.x)
        eobj = eobj + (arr.probs @ (lin + quad + arr.const)).astype(dt)
        new_states.append(PHState(
            W=new_W, xbars=new_xbars, rho=rho,
            x=sol.x, z=sol.z, y=sol.y, yx=sol.yx))
    return tuple(new_states), conv, eobj


def make_bucketed_wheel_megastep(nonant_idx: np.ndarray,
                                 settings: ADMMSettings,
                                 n_iters: int = 8, donate: bool = True,
                                 axis: str = "scen", bounds: bool = False,
                                 int_nonants=None,
                                 xhat_threshold: float = 0.5,
                                 int_rounding: tuple | None = None,
                                 int_cols=None,
                                 rcfix_slack: float = 1e-5,
                                 int_rcfix: bool = True):
    """ONE jitted program running up to ``n_iters`` frozen wheel
    iterations over a BUCKETED (ragged) family — the shape-bucketed twin
    of :func:`make_wheel_megastep`.

    Each scan step runs EVERY bucket's frozen factor-reusing sweep on its
    own compact shapes (one ragged bucket no longer pads the others), then
    the PH outer update couples them: per-node sums accumulate across
    buckets (each bucket's ``onehot``/``probs`` slice the GLOBAL tree),
    the node averages form once, and every bucket gathers its own rows
    back — the scattered host path's Compute_Xbar/Update_W, device-side.
    The early-exit / acceptance masks are GLOBAL (the serial protocol
    evaluates convergence and acceptance on the whole family), and one
    packed measurement (:func:`bucketed_megastep_unpack`) serves the
    window.

    ``nonant_idx`` is the GLOBAL nonant column index array — valid in
    every bucket's column space, exactly as the host path applies its
    globally-assembled augmented objective bucket-sliced.  Callers size
    ``n_iters`` within :func:`~tpusppy.solvers.segmented.megastep_cap_multi`
    (one scan step is the SUM of all buckets' sweeps against the worker
    watchdog).

    ``bounds=True`` appends the in-wheel bound tail exactly like the
    homogeneous kernel: each bucket contributes its probability-weighted
    partial sums (:func:`_bound_pass_terms` — probs/onehot are
    GLOBAL-tree slices, so cross-bucket accumulation is exact), and the
    feasibility mass is global like the acceptance mask.
    ``int_nonants`` is per-bucket (a tuple of (K,) masks — bucketing can
    key on the integer pattern, so slots may differ across buckets).

    Returns ``mega(states, arrs, prox_on, factors, convthresh, n_live,
    accept_tol) -> (states, packed)`` over tuples of per-bucket
    :class:`PHState` / :class:`PHArrays` / factors — with ``bounds=True``
    the signature gains trailing ``(bound_live, feas_tol)``.
    """
    if n_iters < 1:
        raise ValueError(f"n_iters ({n_iters}) must be >= 1")
    idx = jnp.asarray(nonant_idx)
    int_masks = (None if int_nonants is None else
                 tuple(None if m is None else np.asarray(m, dtype=bool)
                       for m in int_nonants))
    # the batched integer sweep arms when ANY bucket has integer nonants
    # and a ladder was requested; candidates are evaluated per bucket and
    # the best-of-C selection is GLOBAL (summed partial objectives) —
    # no-integer families compile the byte-identical legacy pass
    int_sweep = bool(
        bounds and int_rounding and int_masks is not None
        and any(m is not None and m.any() for m in int_masks))
    int_thresholds = tuple(float(t) for t in (int_rounding or ()))
    int_cols_masks = (None if int_cols is None else
                      tuple(None if m is None else np.asarray(m, bool)
                            for m in int_cols))
    from ..solvers import integer as integer_solvers
    tail_len = bound_pack_len(True, int_sweep)
    shared_refresh, shared_frozen, _, frozen_solve = _solver_fns_for(
        settings, None, axis)
    del shared_refresh

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def mega(states, arrs, prox_on, factors, convthresh, n_live,
             accept_tol, bound_live=False, feas_tol=1e-3):
        dt = settings.jdtype()
        n_live_t = jnp.asarray(n_live, jnp.int32)
        thresh = jnp.asarray(convthresh, dt)
        tol = jnp.asarray(accept_tol, dt)

        def body(carry, k):
            sts, pris, duas, dones, executed, stopped, refresh = carry
            live = (~stopped) & (k < n_live_t)

            def live_fn(op):
                sts, pris, duas, dones, executed, stopped, refresh = op
                sols = []
                for bi, (arr, st) in enumerate(zip(arrs, sts)):
                    q, q2, _, _ = _ph_objective(arr, st, prox_on, idx,
                                                settings)
                    fsolve = (shared_frozen if arr.A.ndim == 2
                              else frozen_solve)
                    sols.append(fsolve(
                        q, q2, arr.A, arr.cl, arr.cu, arr.lb, arr.ub,
                        st.x, st.z, st.y, st.yx, factors[bi]))
                Ws = [st.W.astype(dt) for st in sts]
                rhos = [st.rho.astype(dt) for st in sts]
                # GLOBAL acceptance: the serial protocol accepts/rejects
                # the whole family's iterate, never a single bucket's
                all_done = jnp.array(True)
                lad = jnp.array(True)
                for sol in sols:
                    all_done = all_done & jnp.all(sol.done)
                    lad = lad & jnp.all(
                        (sol.pri_res <= tol) & (sol.dua_res <= tol))
                ok = all_done | lad
                new_sts, conv, eobj = _bucketed_finish(
                    arrs, sts, sols, Ws, rhos, idx, dt)
                stats = jnp.stack([
                    conv, eobj,
                    jnp.max(jnp.stack(
                        [jnp.max(s.pri_res) for s in sols])).astype(dt),
                    jnp.max(jnp.stack(
                        [jnp.max(s.dua_res) for s in sols])).astype(dt),
                    jnp.max(jnp.stack(
                        [jnp.max(s.iters) for s in sols])).astype(dt),
                    all_done.astype(dt)])
                sel = lambda a, b: jnp.where(ok, a, b)
                new_sts = jax.tree.map(sel, new_sts, sts)
                new_pris = tuple(sel(s.pri_res, p)
                                 for s, p in zip(sols, pris))
                new_duas = tuple(sel(s.dua_res, d)
                                 for s, d in zip(sols, duas))
                new_dones = tuple(sel(s.done, d)
                                  for s, d in zip(sols, dones))
                return ((new_sts, new_pris, new_duas, new_dones,
                         executed + ok.astype(jnp.int32),
                         stopped | (ok & (conv < thresh)) | ~ok,
                         refresh | ~ok),
                        stats)

            def dead_fn(op):
                return op, jnp.zeros((6,), dt)

            return jax.lax.cond(
                live, live_fn, dead_fn,
                (sts, pris, duas, dones, executed, stopped, refresh))

        infs = tuple(jnp.full((arr.c.shape[0],), jnp.inf, dt)
                     for arr in arrs)
        falses = tuple(jnp.zeros((arr.c.shape[0],), bool) for arr in arrs)
        carry0 = (states, infs, infs, falses,
                  jnp.zeros((), jnp.int32), jnp.zeros((), bool),
                  jnp.zeros((), bool))
        (sts, pris, duas, dones, executed, _, refresh), stats = \
            jax.lax.scan(body, carry0,
                         jnp.arange(n_iters, dtype=jnp.int32))
        parts = [stats.T.reshape(-1),
                 executed.astype(dt)[None], refresh.astype(dt)[None]]
        for p, d, dn in zip(pris, duas, dones):
            parts += [p.astype(dt), d.astype(dt), dn.astype(dt)]
        parts += [st.x.astype(dt).reshape(-1) for st in sts]
        parts += [st.W.astype(dt).reshape(-1) for st in sts]
        parts += [st.xbars.astype(dt).reshape(-1) for st in sts]
        if bounds:
            if int_sweep:
                def bounds_on(stsf):
                    # per-bucket partial sums of the candidate sweep —
                    # probs/onehot are GLOBAL-tree slices, so summing
                    # composes exactly and the argmin is global.  SLAM
                    # candidates are DROPPED on the bucketed posture: a
                    # per-bucket slam extreme is not nonanticipative
                    # across buckets (candidate_ladder docstring); the
                    # ladder candidates derive from the GLOBAL xbars and
                    # are identical across buckets for shared nodes.
                    S_tot = sum(arr.c.shape[0] for arr in arrs)
                    per = []
                    for bi, (arr, stf) in enumerate(zip(arrs, stsf)):
                        fsolve = (shared_frozen if arr.A.ndim == 2
                                  else frozen_solve)
                        q, q2, _, _ = _ph_objective(arr, stf, 1.0, idx,
                                                    settings)
                        mb = (int_masks[bi] if int_masks is not None and
                              int_masks[bi] is not None
                              else np.zeros(arr.nid_sk.shape[1], bool))
                        per.append((integer_solvers.sweep_partials(
                            arr, stf, idx, q, q2, fsolve, factors[bi],
                            feas_tol, dt, jnp.asarray(mb),
                            int_thresholds, include_slams=False),
                            q, q2, fsolve, mb))
                    inner_c = sum(p[0][0] for p in per)
                    feas_c = sum(p[0][1] for p in per)
                    sweeps_c = functools.reduce(
                        jnp.maximum, (p[0][2] for p in per))
                    slack = jnp.asarray(
                        integer_solvers.feas_slack(S_tot, dt), dt)
                    ok_c = feas_c >= 1.0 - slack
                    best = jnp.argmin(jnp.where(
                        ok_c, inner_c, jnp.asarray(np.inf, dt)))
                    n_feas = jnp.sum(ok_c.astype(dt))
                    outer = base = nfix = jnp.zeros((), dt)
                    sweeps = jnp.max(sweeps_c)
                    for bi, (arr, stf) in enumerate(zip(arrs, stsf)):
                        (res, q, q2, fsolve, mb) = per[bi]
                        _, _, _, u_cs, fm_cs = res
                        if int_rcfix:
                            if int_cols_masks is not None and \
                                    int_cols_masks[bi] is not None:
                                cols = jnp.asarray(int_cols_masks[bi])
                            else:
                                cols = jnp.zeros(
                                    arr.c.shape[1], bool).at[idx].set(
                                    jnp.asarray(mb))
                            ob, obb, nf, swF = \
                                integer_solvers.rc_outer_partials(
                                    arr, stf, idx, q, q2, fsolve,
                                    factors[bi], dt, cols, u_cs[best],
                                    fm_cs[best], rcfix_slack)
                            sweeps = jnp.maximum(sweeps, swF)
                        else:
                            # second-stage integers somewhere in the
                            # family: plain weak duality only (the
                            # fixing argument has no valid u_s)
                            W = stf.W.astype(dt)
                            qL = arr.c.astype(dt).at[:, idx].add(W)
                            packed = \
                                admm.dual_objective_with_margin_traced(
                                    qL, arr.q2.astype(dt), arr.A,
                                    arr.cl, arr.cu, arr.lb.astype(dt),
                                    arr.ub.astype(dt),
                                    stf.y.astype(dt), stf.x.astype(dt))
                            ob = obb = (arr.probs @ (
                                packed[0].astype(dt)
                                - packed[1].astype(dt)
                                + arr.const)).astype(dt)
                            nf = jnp.zeros((), dt)
                        outer = outer + ob
                        base = base + obb
                        nfix = nfix + nf
                    return jnp.stack([
                        jnp.ones((), dt), outer, inner_c[best],
                        feas_c[best], sweeps, n_feas, best.astype(dt),
                        nfix, base])
            else:
                def bounds_on(stsf):
                    outer = inner = feas = jnp.zeros((), dt)
                    sweeps = jnp.zeros((), dt)
                    for bi, (arr, stf) in enumerate(zip(arrs, stsf)):
                        fsolve = (shared_frozen if arr.A.ndim == 2
                                  else frozen_solve)
                        ob, ib, fm, sw = _bound_pass_terms(
                            arr, stf, idx, settings, fsolve, factors[bi],
                            feas_tol,
                            None if int_masks is None else int_masks[bi],
                            xhat_threshold)
                        outer = outer + ob
                        inner = inner + ib
                        feas = feas + fm
                        sweeps = jnp.maximum(sweeps, sw)
                    return jnp.stack(
                        [jnp.ones((), dt), outer, inner, feas, sweeps])

            parts.append(jax.lax.cond(
                jnp.asarray(bound_live, bool),
                bounds_on, lambda _: jnp.zeros((tail_len,), dt),
                sts))
        return sts, jnp.concatenate(parts)

    # AOT executable cache: keyed on the bucket count via the call
    # signature (per-bucket shapes ride the avals); cadence and constants
    # — including the bound-pass variant — ride key_extra like the
    # homogeneous megakernel
    return aot_cache.cached_program(
        mega, "bucketed_megastep",
        key_extra=(settings, n_iters, bool(donate), axis,
                   # bounds-only constants keyed only when the bound-pass
                   # variant is compiled (see the homogeneous kernel);
                   # the integer-sweep ladder/scope likewise only when
                   # the sweep is compiled in
                   (float(xhat_threshold),
                    None if int_masks is None else tuple(
                        None if m is None else aot_cache.array_digest(m)
                        for m in int_masks),
                    (int_thresholds, float(rcfix_slack),
                     bool(int_rcfix),
                     None if int_cols_masks is None else tuple(
                         None if m is None else aot_cache.array_digest(m)
                         for m in int_cols_masks))
                    if int_sweep else None)
                   if bounds else None,
                   aot_cache.array_digest(nonant_idx)))


def tenant_megastep_measure_len(n_iters: int, S: int, n_tenants: int,
                                bounds: bool = False) -> int:
    """Length of the packed TENANT-BATCHED measurement
    (:func:`make_tenant_megastep`): per-tenant per-iteration stat blocks
    (``6 * n_iters`` each, tenant-major), per-tenant ``executed``/
    ``refresh`` scalars, the per-tenant final-iterate ``pri``/``dua``/
    ``done`` diagnostics, and — with ``bounds=True`` — ONE
    :data:`BOUND_PACK_LEN` bound pack PER TENANT (per-tenant masked
    certification; the tenant kernel never compiles the integer sweep —
    integer-sweep families are gated to solo time-slicing).

    The pack is LEAN by construction (the big-S wheel posture): x/W/xbars
    stay in the returned per-slot device states, fetched explicitly at
    join/evict/termination boundaries."""
    return n_tenants * (6 * n_iters + 2 + 3 * S) \
        + (n_tenants * BOUND_PACK_LEN if bounds else 0)


def tenant_megastep_unpack(vec, n_iters: int, S: int, n_tenants: int,
                           bounds: bool = False) -> dict:
    """Split a fetched :func:`make_tenant_megastep` measurement into
    PER-TENANT lists (index = slot): ``conv``/``eobj``/``pri_max``/
    ``dua_max``/``iters``/``all_done`` are lists of length-``n_iters``
    arrays, ``executed``/``refresh_hit`` lists of scalars, ``pri``/
    ``dua``/``done`` lists of (S,) arrays; ``bounds=True`` adds
    ``bound_computed``/``bound_outer``/``bound_inner_obj``/
    ``bound_inner_feas``/``bound_sweeps`` lists (each tenant's own
    in-wheel bound pack).  Ghost/dead slots come back as inert zeros
    (``executed == 0``)."""
    vec = np.asarray(vec)
    N, T = n_iters, n_tenants
    out = {k: [] for k in ("conv", "eobj", "pri_max", "dua_max", "iters",
                           "all_done", "executed", "refresh_hit",
                           "pri", "dua", "done")}
    off = 0
    for _t in range(T):
        per = vec[off:off + 6 * N].reshape(6, N)
        off += 6 * N
        out["conv"].append(per[0])
        out["eobj"].append(per[1])
        out["pri_max"].append(per[2])
        out["dua_max"].append(per[3])
        out["iters"].append(per[4])
        out["all_done"].append(per[5] != 0.0)
        out["executed"].append(int(vec[off]))
        out["refresh_hit"].append(bool(vec[off + 1]))
        off += 2
        out["pri"].append(vec[off:off + S])
        out["dua"].append(vec[off + S:off + 2 * S])
        out["done"].append(vec[off + 2 * S:off + 3 * S] != 0.0)
        off += 3 * S
    if bounds:
        for k in ("bound_computed", "bound_outer", "bound_inner_obj",
                  "bound_inner_feas", "bound_sweeps"):
            out[k] = []
        for _t in range(T):
            tail = vec[off:off + BOUND_PACK_LEN]
            off += BOUND_PACK_LEN
            out["bound_computed"].append(bool(tail[0]))
            out["bound_outer"].append(float(tail[1]))
            out["bound_inner_obj"].append(float(tail[2]))
            out["bound_inner_feas"].append(float(tail[3]))
            out["bound_sweeps"].append(float(tail[4]))
    return out


def make_tenant_megastep(nonant_idx: np.ndarray, settings: ADMMSettings,
                         n_iters: int = 8, donate: bool = True,
                         axis: str = "scen", bounds: bool = False,
                         int_nonants: np.ndarray | None = None,
                         xhat_threshold: float = 0.5):
    """ONE jitted program running up to ``n_iters`` frozen wheel
    iterations for K ISOMORPHIC TENANTS AT ONCE — the continuous-batching
    megakernel (ROADMAP item 2, doc/serving.md "Continuous batching").

    Where the bucketed kernel (:func:`make_bucketed_wheel_megastep`)
    couples its slots through a shared scenario tree, the tenant kernel
    keeps every slot a FULLY INDEPENDENT wheel: per-slot
    :class:`PHState`/:class:`PHArrays`/factors tuples (all the same
    shape family, so ONE compile serves any tenant mix of that family —
    the AOT key is effectively (family, K) via the tuple avals), and
    every reduction — xbar/W onehot contractions, the early-exit/
    acceptance masks, the in-wheel bound pack — is PER-TENANT masked:
    slot ``t``'s block solve, ``_ph_finish`` outer update, acceptance
    test ``ok_t``, convergence stop and bound pass read ONLY slot ``t``'s
    arrays.  A tenant's trajectory inside a K-batch is therefore the
    EXACT solo-megastep computation on its own state (the 1e-9 batched-
    vs-solo parity contract, pinned by tests/test_batching.py); the
    throughput win is K wheels sharing one dispatch + one host fetch per
    window instead of K park/resume/sync cycles.

    Per-slot liveness: ``live_mask[t]`` False is a GHOST SLOT — the
    slot's rows ride the program inert (dead ``lax.cond`` branch, zero
    stats, state passthrough), exactly like ghost scenarios pad an
    uneven mesh.  A finished/evicted tenant's slot goes ghost until the
    scheduler backfills it at a window boundary (join = write fresh
    state/arrays into the slot; evict = bank the slot's W/xbars/rho
    through the checkpoint seam).  ``convthresh``/``n_live``/
    ``bound_live`` are (K,) per-tenant — one tenant stopping (or
    skipping its bound cadence) never perturbs a sibling's masks.

    ``bounds=True`` appends ONE :data:`BOUND_PACK_LEN` pack PER TENANT
    (each slot's own :func:`_bound_pass_terms` under its own traced
    ``bound_live[t]`` flag) — per-tenant in-wheel certification under
    the batched source char ('B', service/batching.py).  The tenant
    kernel does NOT compile the batched integer sweep: integer-sweep
    families are gated to solo time-slicing by the scheduler (the
    sweep's global argmin semantics have no per-tenant masked form).

    Returns ``mega(states, arrs, prox_on, factors, convthresh, n_live,
    accept_tol, live_mask) -> (states, packed)`` over K-tuples of
    per-slot :class:`PHState` / :class:`PHArrays` / factors, with
    (K,)-shaped ``convthresh``/``n_live``/``live_mask``; ``bounds=True``
    adds trailing ``(bound_live, feas_tol)`` with (K,) ``bound_live``.
    Unpack with :func:`tenant_megastep_unpack`.
    """
    if n_iters < 1:
        raise ValueError(f"n_iters ({n_iters}) must be >= 1")
    idx = jnp.asarray(nonant_idx)
    int_mask = (None if int_nonants is None
                else np.asarray(int_nonants, dtype=bool))
    _, shared_frozen, _, frozen_solve = _solver_fns_for(
        settings, None, axis)

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def mega(states, arrs, prox_on, factors, convthresh, n_live,
             accept_tol, live_mask, bound_live=None, feas_tol=1e-3):
        dt = settings.jdtype()
        T = len(states)
        n_live_t = jnp.asarray(n_live, jnp.int32)
        thresh = jnp.asarray(convthresh, dt)
        tol = jnp.asarray(accept_tol, dt)
        live_m = jnp.asarray(live_mask, bool)

        def body(carry, k):
            sts, pris, duas, dones, exs, stps, rfs = carry
            new_sts, new_pris, new_duas, new_dones = [], [], [], []
            new_exs, new_stps, new_rfs, stats_rows = [], [], [], []
            for t in range(T):
                arr = arrs[t]
                fsolve = (shared_frozen if arr.A.ndim == 2
                          else frozen_solve)

                # the solo megastep's live_fn, verbatim, on slot t only —
                # per-tenant masked isolation is BY CONSTRUCTION: no
                # cross-slot array ever enters this closure
                def live_fn(op, arr=arr, fsolve=fsolve, t=t,
                            fac=factors[t]):
                    st, pri, dua, done_s, ex, stp, rf = op
                    q, q2, W, rho = _ph_objective(arr, st, prox_on, idx,
                                                  settings)
                    sol = fsolve(q, q2, arr.A, arr.cl, arr.cu, arr.lb,
                                 arr.ub, st.x, st.z, st.y, st.yx, fac)
                    ok = jnp.all(sol.done) | jnp.all(
                        (sol.pri_res <= tol) & (sol.dua_res <= tol))
                    new_st, out = _ph_finish(arr, st, sol, W, rho, idx)
                    stats = jnp.stack([
                        out.conv.astype(dt), out.eobj.astype(dt),
                        jnp.max(sol.pri_res).astype(dt),
                        jnp.max(sol.dua_res).astype(dt),
                        jnp.max(sol.iters).astype(dt),
                        jnp.all(sol.done).astype(dt)])
                    sel = lambda a, b: jnp.where(ok, a, b)
                    new_st = jax.tree.map(sel, new_st, st)
                    return ((new_st, sel(sol.pri_res, pri),
                             sel(sol.dua_res, dua), sel(sol.done, done_s),
                             ex + ok.astype(jnp.int32),
                             stp | (ok & (out.conv < thresh[t])) | ~ok,
                             rf | ~ok),
                            stats)

                def dead_fn(op):
                    return op, jnp.zeros((6,), dt)

                live_t = live_m[t] & (~stps[t]) & (k < n_live_t[t])
                (st2, pri2, dua2, done2, ex2, stp2, rf2), stats_t = \
                    jax.lax.cond(
                        live_t, live_fn, dead_fn,
                        (sts[t], pris[t], duas[t], dones[t], exs[t],
                         stps[t], rfs[t]))
                new_sts.append(st2)
                new_pris.append(pri2)
                new_duas.append(dua2)
                new_dones.append(done2)
                new_exs.append(ex2)
                new_stps.append(stp2)
                new_rfs.append(rf2)
                stats_rows.append(stats_t)
            return ((tuple(new_sts), tuple(new_pris), tuple(new_duas),
                     tuple(new_dones), tuple(new_exs), tuple(new_stps),
                     tuple(new_rfs)), jnp.stack(stats_rows))

        infs = tuple(jnp.full((arr.c.shape[0],), jnp.inf, dt)
                     for arr in arrs)
        falses = tuple(jnp.zeros((arr.c.shape[0],), bool) for arr in arrs)
        zeros_i = tuple(jnp.zeros((), jnp.int32) for _ in arrs)
        zeros_b = tuple(jnp.zeros((), bool) for _ in arrs)
        carry0 = (states, infs, infs, falses, zeros_i, zeros_b, zeros_b)
        (sts, pris, duas, dones, exs, _, rfs), stats = jax.lax.scan(
            body, carry0, jnp.arange(n_iters, dtype=jnp.int32))
        # stats is (n_iters, T, 6); pack tenant-major so each tenant's
        # block reads exactly like a solo measurement prefix
        parts = []
        for t in range(T):
            parts += [stats[:, t, :].T.reshape(-1),
                      exs[t].astype(dt)[None], rfs[t].astype(dt)[None],
                      pris[t].astype(dt), duas[t].astype(dt),
                      dones[t].astype(dt)]
        if bounds:
            bl = jnp.asarray(
                jnp.zeros((T,), bool) if bound_live is None else
                bound_live, bool)
            for t in range(T):
                arr = arrs[t]
                fsolve = (shared_frozen if arr.A.ndim == 2
                          else frozen_solve)

                def bounds_on(stf, arr=arr, fsolve=fsolve, t=t,
                              fac=factors[t]):
                    outer, inner, feas, sweeps = _bound_pass_terms(
                        arr, stf, idx, settings, fsolve, fac,
                        feas_tol, int_mask, xhat_threshold)
                    return jnp.stack(
                        [jnp.ones((), dt), outer, inner, feas, sweeps])

                parts.append(jax.lax.cond(
                    bl[t] & live_m[t], bounds_on,
                    lambda _: jnp.zeros((BOUND_PACK_LEN,), dt), sts[t]))
        return sts, jnp.concatenate(parts)

    # AOT key: the slot count K rides the call signature (tuple avals),
    # so the cache key is effectively (family, K) — one compile serves
    # any tenant mix of the family at that K
    return aot_cache.cached_program(
        mega, "tenant_megastep",
        key_extra=(settings, n_iters, bool(donate), axis,
                   (float(xhat_threshold),
                    None if int_mask is None
                    else aot_cache.array_digest(int_mask))
                   if bounds else None,
                   aot_cache.array_digest(nonant_idx)))


def collect_traces(fused, state, arr, prox_on, n_chunks: int):
    """Drive ``n_chunks`` fused dispatches, DOUBLE-BUFFERING each chunk's
    trace D2H against the next chunk's device compute.

    The serial pattern (fetch chunk k's trace, then dispatch chunk k+1)
    leaves the device idle for a full host round-trip per chunk — over a
    remote tunnel, a serial RPC each.  Here chunk k+1 is dispatched
    FIRST; chunk k's trace (complete by then — the device executes in
    dispatch order) starts its host copy asynchronously and the blocking
    read happens while k+1 runs, so the fetch RPC overlaps compute.  The
    fetches ride :func:`~tpusppy.solvers.hostsync.fetch` (explicit
    transfers, counted by open sync trackers).

    Requires a ``fused`` from :func:`make_ph_fused_step` with
    ``collect="trace"``.  Returns ``(state, trace)`` with the per-chunk
    traces concatenated on the host along the iteration axis.
    """
    from ..solvers import hostsync

    def _start_copy(tr):
        # start the D2H DMA now; the later blocking read only waits on
        # the copy, not on a cold fetch issued after the next dispatch
        jax.tree.map(lambda a: a.copy_to_host_async()
                     if hasattr(a, "copy_to_host_async") else None, tr)
        return tr

    # fetch takes the WHOLE trace pytree in one call: one counted sync
    # per chunk, matching the one round-trip it actually is
    traces = []
    prev = None
    for _ in range(max(1, int(n_chunks))):
        state, trace = fused(state, arr, prox_on)
        if prev is not None:
            traces.append(hostsync.fetch(prev, overlapped=True))
        prev = _start_copy(trace)
    traces.append(hostsync.fetch(prev))
    out = (traces[0] if len(traces) == 1 else jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *traces))
    return state, out


def dispatch_window(mesh: Mesh) -> int:
    """How many step dispatches may be in flight before blocking.

    XLA's CPU in-process collectives have a hard 40s rendezvous timeout, and
    dozens of queued multi-device runs on an oversubscribed host starve a
    given run's all-reduce past it (observed as "Expected 8 threads to join
    ... only 7 arrived" aborts).  A small window keeps device/host overlap
    without unbounded queueing; single-device meshes have no rendezvous and
    can pipeline deep.
    """
    return 4 if len(mesh.devices.flat) > 1 else 64


def make_mesh(n_devices: int | None = None, axis: str = "scen") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_mesh_2d(n_scen: int, n_row: int, scen_axis: str = "scen",
                 row_axis: str = "row") -> Mesh:
    """2-D mesh for the shared-A engine: scenarios x constraint ROWS.

    The row axis is the tensor-parallel analogue (SURVEY §5 "constraint-axis
    available for intra-problem sharding"): the shared (m, n) A and all
    (S, m) row-state shard over it, so huge-m families scale past one
    chip's HBM/FLOPs.  Under jit auto-partitioning the m-contractions
    (A'y, A'diag(rho)A) lower to psum over the row axis — no manual
    collectives.  Dense (per-scenario A) batches use the 1-D mesh.
    """
    devs = jax.devices()[: n_scen * n_row]
    if len(devs) < n_scen * n_row:
        raise ValueError(
            f"need {n_scen * n_row} devices, have {len(devs)}")
    return Mesh(np.asarray(devs).reshape(n_scen, n_row),
                (scen_axis, row_axis))


def shard_batch(batch, mesh: Mesh, axis: str = "scen",
                sparse: bool | str = "auto") -> PHArrays:
    """Place a :class:`~tpusppy.ir.ScenarioBatch` on the mesh, scenario-sharded.

    Pads S up to a multiple of the mesh axis size with zero-probability copies
    of scenario 0 — inert in every reduction (the batched analogue of uneven
    scenario-to-rank maps, sputils.py:807-812).  On a 2-D mesh
    (:func:`make_mesh_2d`) with a shared-A batch, the row dimension
    additionally shards over the "row" axis (m padded to a multiple of it).

    ``sparse``: upload a shared A as a :class:`~tpusppy.solvers.sparse.SparseA`
    (gather/segment-sum matvecs + block/Woodbury structured KKT when the
    family has the structure) instead of the dense (m, n) matrix.  "auto"
    enables it for large very-sparse families (reference-scale UC: 0.03%
    dense) on a 1-D mesh; dense stays the default elsewhere (small
    matrices ride the MXU better dense, and the 2-D row-sharded mesh
    needs the dense layout).
    """
    S = batch.num_scenarios
    pad = num_ghosts(S, mesh, axis)
    K = batch.tree.num_nonants
    N = batch.tree.num_nodes
    nid_sk = batch.tree.nid_sk()
    probs = batch.probs

    def padded(a):
        if pad == 0:
            return a
        return np.concatenate([a, np.repeat(a[:1], pad, axis=0)], axis=0)

    probs_p = np.concatenate([probs, np.zeros(pad)]) if pad else probs
    nid_p = padded(nid_sk)
    onehot = batch.tree.onehot_sk_n()
    if pad:
        # ghost scenarios get zero membership so they never perturb reductions
        onehot = np.concatenate([onehot, np.zeros((pad, K, N))], axis=0)

    A_shared = getattr(batch, "A_shared", None)
    # any second mesh axis (beyond the scenario axis) is the row axis —
    # make_mesh_2d's row_axis name passes through automatically
    extra = [ax for ax in mesh.axis_names if ax != axis]
    row_axis = (extra[0] if (extra and A_shared is not None) else None)

    def pad_rows(a, row_dim):
        """Pad dim ``row_dim`` to a multiple of the row-axis size (inert
        padded rows are neutralized by the caller: zero A rows with
        -inf/inf bounds)."""
        if row_axis is None:
            return a
        rsh = mesh.shape[row_axis]
        rpad = (-a.shape[row_dim]) % rsh
        if rpad == 0:
            return a
        widths = [(0, 0)] * a.ndim
        widths[row_dim] = (0, rpad)
        return np.pad(a, widths)

    if A_shared is not None:
        An = np.asarray(A_shared)
        from ..solvers.sparse import should_sparsify
        use_sparse = (sparse is True) or (
            sparse == "auto" and row_axis is None and should_sparsify(An))
        if sparse is True and row_axis is not None:
            raise ValueError(
                "sparse=True is incompatible with a 2-D row-sharded mesh: "
                "the row axis needs the dense (m, n) layout — use the 1-D "
                "mesh for the SparseA engine or sparse='auto'")
        if row_axis is not None:
            A_host = pad_rows(An, 0)
        elif use_sparse:
            A_host = SparseA.from_dense(An, structure=True)
        else:
            A_host = An
        cl_p = pad_rows(padded(batch.cl), 1)
        cu_p = pad_rows(padded(batch.cu), 1)
        m0 = batch.cl.shape[1]
        if cl_p.shape[1] != m0:
            # inert padded rows: -inf <= (zero row) x <= +inf
            cl_p[:, m0:] = -np.inf
            cu_p[:, m0:] = np.inf
    else:
        A_host = padded(batch.A)
        cl_p = padded(batch.cl)
        cu_p = padded(batch.cu)
    host = PHArrays(
        c=padded(batch.c), q2=padded(batch.q2), A=A_host,
        cl=cl_p, cu=cu_p,
        lb=padded(batch.lb), ub=padded(batch.ub),
        const=padded(batch.const), probs=probs_p,
        onehot=onehot, nid_sk=nid_p)
    # rule-driven placement: ONE declarative table maps every leaf to its
    # NamedSharding (ph_partition_rules); an unmatched leaf fails loudly
    shardings = ph_shardings(mesh, host, axis, row_axis,
                             shared=A_shared is not None)
    return jax.tree.map(
        lambda a, s: jax.device_put(jnp.asarray(a), s), host, shardings)


def init_state(arr: PHArrays, default_rho: float, settings: ADMMSettings) -> PHState:
    dt = settings.jdtype()
    S, n = arr.c.shape
    m = arr.cl.shape[1]
    K = arr.nid_sk.shape[1]
    shardS = lambda shape: jnp.zeros(shape, dt)
    state = PHState(
        W=shardS((S, K)),
        xbars=shardS((S, K)),
        rho=jnp.full((S, K), default_rho, dt),
        x=shardS((S, n)),
        z=shardS((S, m)),
        y=shardS((S, m)),
        yx=shardS((S, n)),
    )
    return jax.tree.map(jax.device_put, state, state_shardings(arr, state))


def state_shardings(arr: PHArrays, state: PHState | None = None):
    """The placement-rule shardings for a :class:`PHState` matching
    ``arr``'s mesh posture — the data shardings and the state shardings
    come from ONE table, so the first step never reshards.  Used by
    :func:`init_state` and the shard-read checkpoint restore.  Falls back
    to fully-addressable single-device placement when ``arr`` carries no
    mesh (plain jnp arrays, e.g. the host megastep path)."""
    sh = getattr(arr.c, "sharding", None)
    mesh = getattr(sh, "mesh", None)
    if state is None:
        K = arr.nid_sk.shape[1]
        S, n = arr.c.shape
        m = arr.cl.shape[1]
        z = np.zeros(())
        state = PHState(*(np.broadcast_to(z, s) for s in (
            (S, K), (S, K), (S, K), (S, n), (S, m), (S, m), (S, n))))
    if mesh is None or getattr(mesh, "empty", False):
        return jax.tree.map(lambda a: sh, state) if sh is not None else None
    axis = mesh.axis_names[0]
    extra = [ax for ax in mesh.axis_names if ax != axis]
    shared = getattr(arr.A, "ndim", 2) != 3
    row_axis = extra[0] if (extra and shared) else None
    # the row-state leaves (z, y) only shard over row_axis when cl does
    # (2-D shared-A posture) — exactly what the rules table encodes
    return ph_shardings(mesh, state, axis, row_axis, shared=shared)


def run_ph(batch, mesh: Mesh, iters: int, default_rho: float = 1.0,
           settings: ADMMSettings | None = None, axis: str = "scen",
           refresh_every: int = 32, fused: bool | str = "auto",
           chunk: int | None = None, precision: str | None = None):
    """Sharded PH driver: Iter0 (plain objective via rho=W=0 warmup step
    semantics) + ``iters`` PH iterations.  Returns (state, last PHStepOut).

    Iterations run on the factorization-amortized path: a full adaptive
    refresh at the first PH iteration and every ``refresh_every`` after it,
    sweep-only frozen steps in between (``refresh_every=1`` disables the
    frozen path).  Used by ``__graft_entry__.dryrun_multichip`` and
    ``bench.py``; the class API (:class:`tpusppy.opt.ph.PH`) remains the
    feature-complete host path.

    ``fused="auto"`` (default) packs the iterations into fused
    multi-iteration programs (:func:`make_ph_fused_step`, buffer-donated,
    same cadence hence bit-identical trajectory) whenever the shape fits
    the fused dispatch cap; segmentation-regime shapes fall back to the
    per-iteration step pair.  ``fused=False`` forces the pair path;
    ``chunk`` overrides the fused chunk size (else the cap, rounded down
    to a refresh multiple).  conv/eobj stay device-side across chunks —
    the host syncs only once per dispatch window.

    ``precision``: frozen-sweep matmul precision ("default"/"high"/
    "highest", see doc/precision.md) — shorthand for
    ``settings.sweep_precision`` so drivers can thread an autotuned mode
    without rebuilding settings.
    """
    settings = settings or ADMMSettings()
    if precision is not None:
        settings = dataclasses.replace(settings, sweep_precision=precision)
    arr = shard_batch(batch, mesh, axis)
    refresh, frozen = make_ph_step_pair(
        batch.tree.nonant_indices, settings, mesh, axis)
    state = init_state(arr, default_rho, settings)
    window = dispatch_window(mesh)
    # Iter0: W=0, prox off, cf. phbase.py:758-872
    state, out, _ = refresh(state, arr, 0.0)

    refresh_every = max(refresh_every, 1)
    cap = fused_iteration_cap(arr, settings, mesh, refresh_every)
    use_fused = iters > 0 and (
        fused is True or (fused == "auto" and cap >= refresh_every))
    if use_fused:
        if chunk is None:
            chunk = max(refresh_every,
                        (cap or iters) // refresh_every * refresh_every)
        chunk = min(chunk, iters)
        fused_cache: dict[int, object] = {}

        def fused_for(c):
            if c not in fused_cache:
                fused_cache[c] = make_ph_fused_step(
                    batch.tree.nonant_indices, settings, mesh, axis,
                    chunk=c, refresh_every=min(refresh_every, c))
            return fused_cache[c]

        done = 0
        n_call = 0
        while done < iters:
            c = min(chunk, iters - done)
            state, out = fused_for(c)(state, arr, 1.0)
            done += c
            n_call += 1
            if n_call % window == 0:
                jax.block_until_ready(out.conv)
        return state, out

    factors = None
    for i in range(iters):
        if factors is None or i % refresh_every == 0:
            state, out, factors = refresh(state, arr, 1.0)
        else:
            state, out = frozen(state, arr, 1.0, factors)
        if (i + 1) % window == 0:
            jax.block_until_ready(out.conv)
    return state, out
