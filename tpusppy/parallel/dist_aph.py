"""Distributed APH: cross-host listener reductions under asynchronous solves.

The reference's APH runs a LISTENER THREAD doing background MPI Allreduces
concurrently with worker solves (``mpisppy/opt/aph.py:198-330`` +
``utils/listener_util/listener_util.py:277-327``): workers publish local
contributions, the listener reduces them across ranks while the workers are
already solving the next dispatch, and workers tolerate one-reduction-stale
averages.  tpusppy's single-controller APH collapses that to host einsums
(:mod:`tpusppy.opt.aph`); this module is the MULTI-HOST form, where the
reduction genuinely crosses a network and overlapping it with solves pays.

Architecture (no ``jax.distributed`` needed — matching the reference, the
coupling between hosts is ONLY the reduction):

- each process owns a scenario shard and runs the ordinary batched APH on
  it (its own devices, its own dispatch fraction);
- node averages decompose into per-node partial sums, so each process
  publishes ``(num_x, num_xsq, num_y, den, phi)`` partials weighted by its
  TRUE global probabilities;
- :class:`APHPartialSync`'s listener thread sums partials over processes
  through the C++ TCP window service (the DCN path) and broadcasts the
  global sums back — process 0 serves the boxes, everyone else connects;
- workers read the latest global reduction with a bounded freshness wait
  and continue on stale averages when the network is behind — APH's
  tolerated staleness, verbatim.

Two-stage trees only: every process's local tree must contain the same node
set (a scenario shard of a deep multistage tree can miss interior nodes);
multistage stays on the single-controller path.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..obs.log import get_logger
from ..opt.aph import APH


class APHPartialSync:
    """Async cross-process partial-sum reducer over the TCP window fabric.

    Box layout per non-root process p: ``to_hub[p]`` carries p's latest
    partial ``[payload (L), serial]``; ``to_spoke[p]`` carries the reduced
    global ``[summed payload (L), min_serial]``.  Process 0 keeps its own
    partial in memory and its listener thread re-reduces whenever any
    contribution moved; other processes' listeners poll their global box.
    Staleness is explicit: ``latest()`` returns the reduction's min serial
    so callers can decide freshness (aph.py:198-330 semantics).
    """

    def __init__(self, nproc: int, process_id: int, length: int,
                 port: int = 0, host: str = "127.0.0.1",
                 secret: int | None = None, sleep_secs: float = 0.005):
        from ..runtime.tcp_window_service import TcpWindowFabric

        self.nproc = int(nproc)
        self.pid = int(process_id)
        self.L = int(length)
        self.sleep_secs = float(sleep_secs)
        boxlen = self.L + 1
        if self.pid == 0:
            self.fabric = TcpWindowFabric(
                spoke_lengths=[(boxlen, boxlen)] * (self.nproc - 1),
                port=port, secret=secret)
            self.port = self.fabric.port
        else:
            self.fabric = TcpWindowFabric(connect=(host, port),
                                          secret=secret)
            self.port = port
        self._lock = threading.Lock()
        self._own = None              # this process's latest [payload, serial]
        self._own_version = 0
        self._global = None           # latest reduced [payload, min_serial]
        self.listener_error = None    # first listener exception (diagnostic)
        self._quit = False
        self._listener = threading.Thread(
            target=self._listener_loop, name="APHPartialSync", daemon=True)
        self._listener.start()

    # ---- worker side -------------------------------------------------------
    def publish(self, payload: np.ndarray, serial: int):
        vec = np.concatenate([np.asarray(payload, float).ravel(),
                              [float(serial)]])
        if vec.shape != (self.L + 1,):
            raise ValueError(f"partial length {vec.shape} != {self.L + 1}")
        if self.pid == 0:
            with self._lock:
                self._own = vec
                self._own_version += 1
        else:
            self.fabric.to_hub[self.pid].put(vec)

    def latest(self):
        """(global payload copy, min_serial) or None if no reduction yet."""
        with self._lock:
            if self._global is None:
                return None
            return self._global[:-1].copy(), int(self._global[-1])

    # ---- listener side -----------------------------------------------------
    def _listener_loop(self):
        last_ids = {}
        last_version = -1
        while not self._quit:
            try:
                if self.pid == 0:
                    moved = False
                    parts = []
                    with self._lock:
                        if self._own is not None:
                            parts.append(self._own)
                        if self._own_version != last_version:
                            last_version = self._own_version
                            moved = True
                    for p in range(1, self.nproc):
                        data, wid = self.fabric.to_hub[p].get()
                        if wid > 0:
                            parts.append(data)
                            if wid != last_ids.get(p):
                                last_ids[p] = wid
                                moved = True
                    if moved and len(parts) == self.nproc:
                        tot = np.sum([q[:-1] for q in parts], axis=0)
                        serial = min(float(q[-1]) for q in parts)
                        red = np.concatenate([tot, [serial]])
                        with self._lock:
                            self._global = red
                        for p in range(1, self.nproc):
                            self.fabric.to_spoke[p].put(red)
                        _metrics.inc("dist_aph.listener_reductions")
                        if _trace.enabled():
                            _trace.instant("listener", "reduce",
                                           min_serial=serial,
                                           parts=len(parts))
                else:
                    data, wid = self.fabric.to_spoke[self.pid].get()
                    if wid > 0:
                        with self._lock:
                            self._global = data
                        if wid != last_ids.get("global"):
                            # count NEW reductions only (the poll re-reads
                            # the same box every few ms)
                            last_ids["global"] = wid
                            _metrics.inc("dist_aph.listener_pulls")
            except Exception as e:
                # a torn-down fabric mid-poll must not spin a traceback
                # storm — but a LIVE run degrading to stale/local-only
                # reductions must be loud: record + print the first error
                # (workers surface staleness via _stale_dist_reductions)
                if self._quit:
                    return
                if self.listener_error is None:
                    self.listener_error = repr(e)
                    _metrics.inc("dist_aph.listener_errors")
                    # rank-attributable logger, not a bare print: several
                    # wheel processes interleave on one terminal
                    get_logger(f"dist_aph[p{self.pid}].listener").error(
                        "listener error (reductions may go stale): %r", e)
            time.sleep(self.sleep_secs)

    def close(self):
        self._quit = True
        self._listener.join(timeout=10)
        self.fabric.close()


class DistributedAPH(APH):
    """APH over a scenario SHARD whose reductions are global.

    Construct per process with its LOCAL scenario names (probabilities
    renormalized so the local tree validates); ``prob_share`` is the shard's
    true global probability mass, so published partials carry the global
    weighting.  Everything else — fractional dispatch, compact sub-batch
    solves, theta/z/W updates — is the inherited batched APH, now driven by
    globally-reduced averages.  Reference: one APH rank group of
    ``mpisppy/opt/aph.py:46-982`` with listener reductions.
    """

    def __init__(self, options, local_scenario_names, scenario_creator,
                 *, sync: APHPartialSync, prob_share: float = 1.0,
                 **kwargs):
        super().__init__(options, local_scenario_names, scenario_creator,
                         **kwargs)
        self.sync = sync
        self.prob_share = float(prob_share)
        self._stale_dist_reductions = 0
        K = self.nonant_length
        N = self._onehot.shape[2]
        expect = 4 * N * K + 1
        if sync.L != expect:
            raise ValueError(
                f"sync length {sync.L} != 4*N*K+1 = {expect} "
                f"(N={N} nodes, K={K} nonants)")

    def partial_length(self):
        K = self.nonant_length
        N = self._onehot.shape[2]
        return 4 * N * K + 1

    def Compute_Averages(self):
        """Publish global-prob-weighted partial sums; derive the averages
        from the listener's cross-process reduction (aph.py:332-453 math,
        decomposed into per-node sums so it distributes)."""
        xk = self.nonants_of(self.local_x)
        K = self.nonant_length
        N = self._onehot.shape[2]
        pt = (self.prob_share * self.probs)[:, None]
        num_x = np.einsum("skn,sk->nk", self._onehot, pt * xk)
        num_xsq = np.einsum("skn,sk->nk", self._onehot, pt * xk * xk)
        num_y = np.einsum("skn,sk->nk", self._onehot, pt * self.y)
        den = np.einsum("skn,sk->nk", self._onehot,
                        np.broadcast_to(pt, xk.shape))
        local_phis = (self.prob_share * self.probs) * np.einsum(
            "sk,sk->s", self.z - xk, self.W - self.y)
        payload = np.concatenate([
            num_x.ravel(), num_xsq.ravel(), num_y.ravel(), den.ravel(),
            [float(local_phis.sum())]])
        self.sync.publish(payload, self._iter)

        g = self._wait_reduction()
        if g is None:
            # no global reduction yet (first publishes in flight): proceed
            # on own partials — transient, and only possible at startup
            g = payload
        NK = N * K
        g_num_x = g[:NK].reshape(N, K)
        g_num_xsq = g[NK:2 * NK].reshape(N, K)
        g_num_y = g[2 * NK:3 * NK].reshape(N, K)
        g_den = np.maximum(g[3 * NK:4 * NK].reshape(N, K), 1e-300)
        g_phi = float(g[4 * NK])

        xbar_nk = g_num_x / g_den
        xsqbar_nk = g_num_xsq / g_den
        ybar_nk = g_num_y / g_den
        kidx = np.arange(K)[None, :]
        xbars = xbar_nk[self.nid_sk, kidx]
        pusq = float(np.sum(g_num_xsq - g_num_x * g_num_x / g_den))
        pvsq = float(np.sum(g_num_y * g_num_y / g_den))
        tau = pusq + pvsq / self.APHgamma
        self.xbars = xbars
        self.xsqbars = xsqbar_nk[self.nid_sk, kidx]
        self.ybars = ybar_nk[self.nid_sk, kidx]
        self.uk = xk - xbars
        self.global_pusqnorm = pusq
        self.global_pvsqnorm = pvsq
        self.tau_summand = tau
        self.global_tau = tau
        self.global_phi = g_phi
        # dispatch priorities stay LOCAL (each process dispatches within
        # its own shard, like each reference rank solves its own list)
        self.phis = local_phis

    def _wait_reduction(self):
        """Latest global sums, waiting briefly for this iteration's serial;
        returns the stale reduction (counted) when the network is behind."""
        wait = self.options.get("APH_listener_wait_secs")
        if wait is None:
            wait = float(self.options.get("async_sleep_secs", 0.01)) * 100
        deadline = time.time() + float(wait)
        while True:
            got = self.sync.latest()
            if got is not None and got[1] >= self._iter:
                return got[0]
            if time.time() >= deadline:
                break
            time.sleep(0.0005)
        got = self.sync.latest()
        if got is None:
            return None
        if got[1] < self._iter:
            self._stale_dist_reductions += 1
            _metrics.inc("dist_aph.stale_reductions")
            if _trace.enabled():
                _trace.instant("listener", "stale_reduction",
                               serial=got[1], iter=self._iter)
        return got[0]
