"""Multi-controller (multi-host) scenario parallelism within one cylinder.

The reference's core scaling axis: ONE cylinder spans hundreds of MPI ranks,
each rank owning a contiguous slice of scenarios, with per-tree-node
``Allreduce`` reductions (``mpisppy/utils/sputils.py:774-840`` scenario->rank
maps, ``spbase.py:184-216`` rank assignment, 4000 ranks in paperruns).

The TPU-native equivalent is multi-controller JAX: each host process builds
ONLY its own scenario shard (so no host materializes the global batch — the
same memory scaling as rank-local scenario lists), assembles global
scenario-sharded ``jax.Array``s via ``make_array_from_process_local_data``
over a mesh spanning every process's devices, and runs the SAME jitted PH
step as the single-controller path (:mod:`tpusppy.parallel.sharded`) — the
scenario-axis contractions inside it lower to psums that ride ICI within a
host and DCN across hosts.  No communicator management, no send/recv: the
mesh is the communicator.

Launch (per host)::

    jax.distributed.initialize(coordinator, num_processes, process_id)
    ...
    result = distributed_ph(all_names, creator, kwargs, options)

See ``doc/multihost.md`` ("Scaling one cylinder across hosts") for the
two-host recipe, and ``tests/test_distributed.py`` for the 2-process CPU
harness (the same wire format the driver's multi-chip dryrun validates).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class DistPHResult(NamedTuple):
    conv: float
    eobj: float
    xbars: np.ndarray        # (K,) root-stage consensus (replicated)
    iters: int


def initialize_backend(coordinator_address, num_processes, process_id,
                       **kwargs):
    """``jax.distributed.initialize`` with the CPU collectives backend
    enabled first, and WIDENED coordination-service heartbeat windows.

    Current jaxlib defaults ``jax_cpu_collectives_implementation`` to
    "none", so a multi-controller CPU job initializes fine and then every
    cross-process computation dies with "Multiprocess computations aren't
    implemented on the CPU backend" — selecting the Gloo implementation
    BEFORE backend initialization is required.  TPU/GPU jobs ignore the
    setting entirely, so every worker can use this wrapper unconditionally
    (and should: it is the single place the requirement is encoded).

    Heartbeats: the jax coordination client ``LOG(FATAL)``s the whole
    process on heartbeat-window misses, and under full-suite CPU
    contention the default window (10s × 10 misses) is starvable — the
    PR-5 dist checkpoint-resume leg was slow-marked over exactly that.
    Controller-death DETECTION is now owned by the elastic watchdog
    (``TPUSPPY_MESH_TIMEOUT``), so the coordination heartbeat can be
    generous: ``TPUSPPY_DIST_HB_INTERVAL_SECS`` (default 10) ×
    ``TPUSPPY_DIST_HB_MAX_MISSING`` (default 30 → a 300s window), passed
    through the private ``State.initialize`` when this jax exposes the
    knobs (public API falls back silently on drift — the deps-canary
    covers it).
    """
    import jax

    # explicit presence check, no exception swallowing: a jaxlib whose
    # knob EXISTS but rejects "gloo" (renamed value, dropped backend —
    # exactly the drift the nightly deps-canary watches) must fail HERE,
    # loudly, not three collectives later with the cryptic "Multiprocess
    # computations aren't implemented on the CPU backend"
    if "jax_cpu_collectives_implementation" in jax.config.values:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    else:
        # renamed/removed knob (upstream drift): keep the loud-failure
        # contract — a CPU multi-process job without a collectives
        # backend only fails at its first cross-process computation
        import warnings

        warnings.warn(
            "jax.config has no jax_cpu_collectives_implementation knob "
            "(upstream rename/removal?): CPU multi-process collectives "
            "may be unavailable — expect 'Multiprocess computations "
            "aren't implemented on the CPU backend' if so",
            RuntimeWarning, stacklevel=2)
    import os

    interval = int(os.environ.get("TPUSPPY_DIST_HB_INTERVAL_SECS", "10"))
    missing = int(os.environ.get("TPUSPPY_DIST_HB_MAX_MISSING", "30"))
    hb_kwargs = {
        "service_heartbeat_interval_seconds": interval,
        "service_max_missing_heartbeats": missing,
        "client_heartbeat_interval_seconds": interval,
        "client_max_missing_heartbeats": missing,
    }
    try:
        import inspect

        from jax._src import distributed as _jd
        from jax._src import xla_bridge as _xb

        sig = inspect.signature(_jd.global_state.initialize)
        if all(k in sig.parameters for k in hb_kwargs):
            # the public jax.distributed.initialize guards against
            # already-initialized backends — the private State does not,
            # and skipping the check would let the Gloo knob above be a
            # silent no-op on the already-built backend (first collective
            # hangs); replicate the guard before taking the private path
            if _xb.backends_are_initialized():
                raise RuntimeError(
                    "initialize_backend must be called before any JAX "
                    "computations (the backend is already initialized)")
            # jax.distributed.initialize delegates to this very State
            # object — only the heartbeat kwargs are private surface;
            # ALL four must exist (a partial rename would TypeError)
            _jd.global_state.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                **hb_kwargs, **kwargs)
            return
    except (ImportError, AttributeError):
        pass    # private surface moved (upstream drift): default
        #         heartbeat windows via the public API below
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)


def scen_to_process(num_scenarios: int, num_processes: int,
                    process_id: int | None = None):
    """Contiguous block scenario->process map (sputils.py:774-812 analogue:
    uneven counts spread the remainder over the leading processes).

    Returns the (start, stop) slice for ``process_id``, or the full list of
    slices when ``process_id`` is None.
    """
    base, rem = divmod(num_scenarios, num_processes)
    slices = []
    lo = 0
    for p in range(num_processes):
        hi = lo + base + (1 if p < rem else 0)
        slices.append((lo, hi))
        lo = hi
    if process_id is None:
        return slices
    return slices[process_id]


def process_rows(mesh, S_global, axis: str = "scen"):
    """Padded-global scenario rows owned by THIS process under the mesh's
    device layout, and the padded total Sp.

    THE scenario->process map: ownership follows the mesh (a 1-D
    scenario-sharded array places each padded-global row on exactly one
    device), so partitioning any other way would strand real scenarios on
    inert fill rows.  Rows >= S_global are padding.  Reference analogue:
    the scen->rank maps of sputils.py:774-840, except here the mesh IS the
    map.
    """
    import jax

    nsh = mesh.shape[axis]
    pad = (-S_global) % nsh
    Sp = S_global + pad
    per_dev = Sp // nsh
    dev_order = list(mesh.devices.ravel())
    rows = []
    for i, d in enumerate(dev_order):
        if d.process_index == jax.process_index():
            rows.extend(range(i * per_dev, (i + 1) * per_dev))
    return np.asarray(sorted(rows)), Sp


def _shared_A_unanimous(A_shared) -> bool:
    """Cross-process vote on the shared-A engine: True only when EVERY
    process detected a shared A (pass None otherwise) and all of them
    are the same matrix (sha1 over the f64 bytes, exchanged as two
    exact <2^53 float words).  COLLECTIVE — every process of the job
    must call it exactly once (at setup), whatever its local verdict:
    a subset-joined allgather would deadlock the mesh."""
    import jax

    if jax.process_count() == 1:
        return A_shared is not None
    import hashlib

    from jax.experimental import multihost_utils

    if A_shared is None:
        mine = np.asarray([0.0, 0.0, 0.0])
    else:
        h = hashlib.sha1(np.ascontiguousarray(
            np.asarray(A_shared, np.float64)).tobytes()).hexdigest()
        mine = np.asarray([1.0, float(int(h[:12], 16)),
                           float(int(h[12:24], 16))])
    votes = np.asarray(
        multihost_utils.process_allgather(mine)).reshape(-1, 3)
    return bool((votes[:, 0] == 1.0).all()
                and (votes[:, 1] == votes[0, 1]).all()
                and (votes[:, 2] == votes[0, 2]).all())


def _global_scen_arrays(batch_local, S_global, owned_rows, mesh, axis,
                        settings, probs_local=None):
    """Assemble globally-sharded PHArrays from a process-LOCAL batch.

    ``owned_rows``: the padded-global row ids this process's devices hold
    (:func:`process_rows`); the local batch's scenarios correspond to its
    entries that are < S_global, in order.  Pad rows (>= S_global) are
    filled with inert zero-probability copies of the local row 0.  Every
    real row is owned by exactly one process, so probabilities and node
    memberships stay globally consistent.  Every process must call this
    collectively with the same global shapes.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .sharded import PHArrays

    rows = np.asarray(owned_rows)
    nsh = mesh.shape[axis]
    pad = (-S_global) % nsh
    Sp = S_global + pad

    b = batch_local
    dt = np.dtype(settings.dtype)
    shard = NamedSharding(mesh, P(axis))
    owned = rows < S_global
    local_index = {r: j for j, r in enumerate(rows[owned])}

    def mk(get_row, dtype, extra_shape=(), inert=None):
        """Stack local rows: real rows map through the local batch, pad
        rows take ``inert`` (default: a copy of local row 0)."""
        fill = get_row(0) if inert is None else inert
        local = np.stack([
            get_row(local_index[r]) if ok else fill
            for r, ok in zip(rows, owned)]).astype(dtype)
        return jax.make_array_from_process_local_data(
            shard, local, (Sp,) + extra_shape)

    n = b.num_vars
    m = b.num_rows
    nid_sk = b.tree.nid_sk()
    onehot = b.tree.onehot_sk_n()
    K = nid_sk.shape[1]
    N = onehot.shape[2]
    if probs_local is None:
        probs_local = np.asarray(b.tree.scen_prob, dtype=float)
    probs_local = np.asarray(probs_local, dtype=float)
    const_local = np.broadcast_to(np.asarray(b.const),
                                  (int(owned.sum()),))

    A_shared = getattr(b, "A_shared", None)
    if not _shared_A_unanimous(A_shared):
        # a process whose local slice is a SINGLE scenario (uneven S —
        # exactly the shape an elastic re-mesh produces) detects a
        # "shared" A trivially and would compile the 2-D shared-A
        # engine while its peers compile the 3-D per-scenario one: the
        # two programs post different collectives and Gloo ABORTS the
        # whole job with a size mismatch (measured: 3 controllers, S=7,
        # "op.preamble.length <= op.nbytes. 16 vs 8").  The engine
        # choice is therefore VOTED across processes (the vote itself
        # is collective — every process joins whatever its local
        # verdict): shared only when all hold the same shared A;
        # otherwise the per-scenario branch (b.A is the broadcast view).
        A_shared = None
    if A_shared is not None:
        from ..solvers.sparse import SparseA, should_sparsify

        An = np.asarray(A_shared)
        if should_sparsify(An):
            # every process builds the identical SparseA (+ structure)
            # deterministically from the identical A, so the jitted
            # step's pytree structure is globally consistent; the
            # in-loop plateau exit stays multi-process-safe because its
            # stall decision is computed INSIDE the program via
            # collectives (unlike the host-side segment detectors,
            # which multi-process meshes already disable)
            A_arr = SparseA.from_dense(An, jnp.dtype(dt), structure=True)
        else:
            A_arr = jnp.asarray(An, dt)                 # replicated
    else:
        A_arr = mk(lambda i: np.asarray(b.A[i]), dt, (m, n))

    return PHArrays(
        c=mk(lambda i: np.asarray(b.c[i]), dt, (n,)),
        q2=mk(lambda i: np.asarray(b.q2[i]), dt, (n,)),
        A=A_arr,
        cl=mk(lambda i: np.asarray(b.cl[i]), dt, (m,)),
        cu=mk(lambda i: np.asarray(b.cu[i]), dt, (m,)),
        lb=mk(lambda i: np.asarray(b.lb[i]), dt, (n,)),
        ub=mk(lambda i: np.asarray(b.ub[i]), dt, (n,)),
        const=mk(lambda i: const_local[i], dt),
        probs=mk(lambda i: probs_local[i], dt, inert=np.float64(0.0)),
        onehot=mk(lambda i: onehot[i], dt, (K, N),
                  inert=np.zeros((K, N))),
        nid_sk=mk(lambda i: nid_sk[i], np.int32, (K,)),
    )


def _init_state_dist(arr, default_rho, settings):
    """Distributed-safe :func:`tpusppy.parallel.sharded.init_state`: zeros
    are produced INSIDE a jit with explicit output shardings —
    ``device_put`` of host arrays cannot target non-addressable devices in
    a multi-controller job."""
    import jax
    import jax.numpy as jnp

    from .sharded import PHState

    dt = settings.jdtype()
    S, n = arr.c.shape
    m = arr.cl.shape[1]
    K = arr.nid_sk.shape[1]
    like = PHState(
        W=arr.nid_sk.sharding, xbars=arr.nid_sk.sharding,
        rho=arr.nid_sk.sharding, x=arr.c.sharding, z=arr.cl.sharding,
        y=arr.cl.sharding, yx=arr.c.sharding)

    def init():
        z = lambda shape: jnp.zeros(shape, dt)
        return PHState(
            W=z((S, K)), xbars=z((S, K)),
            rho=jnp.full((S, K), default_rho, dt),
            x=z((S, n)), z=z((S, m)), y=z((S, m)), yx=z((S, n)))

    return jax.jit(init, out_shardings=like)()


class DistPHSetup(NamedTuple):
    """Everything a multi-controller PH loop needs (``_setup_distributed``)."""

    arr: object          # sharded.PHArrays, globally sharded
    state: object        # sharded.PHState
    refresh: object
    frozen: object
    batch_local: object  # this process's ScenarioBatch slice
    settings: object
    mesh: object
    S: int               # global (unpadded) scenario count


def _setup_distributed(all_scenario_names, scenario_creator,
                       scenario_creator_kwargs=None, options=None,
                       mesh=None, axis: str = "scen") -> DistPHSetup:
    """Collective setup for one multi-controller cylinder: local scenario
    slice -> globally-sharded arrays + compiled step pair + initial state.
    Shared by :func:`distributed_ph` and the distributed wheel hub
    (:mod:`tpusppy.parallel.dist_wheel`)."""
    from ..ir import ScenarioBatch
    from ..solvers.admm import ADMMSettings
    from . import sharded

    options = dict(options or {})
    kwargs = dict(scenario_creator_kwargs or {})
    S = len(all_scenario_names)
    if mesh is None:
        mesh = sharded.make_mesh(axis=axis)
    rows, _ = process_rows(mesh, S, axis)
    local_ids = [int(r) for r in rows if r < S]
    local_names = [all_scenario_names[i] for i in local_ids]
    problems = [scenario_creator(nm, **kwargs) for nm in local_names]
    # the local slice's probabilities sum to its GLOBAL share, not 1 —
    # renormalize for the local tree build (which validates sum == 1) and
    # carry the true global probabilities into the sharded arrays
    import dataclasses as _dc

    raw = [p.prob for p in problems]
    if all(pr is None for pr in raw):
        true_probs = np.full(len(problems), 1.0 / S)
    else:
        true_probs = np.asarray([float(pr) for pr in raw])
        share = float(true_probs.sum())
        problems = [_dc.replace(p, prob=float(pr) / share)
                    for p, pr in zip(problems, true_probs)]
    batch_local = ScenarioBatch.from_problems(problems)

    so = dict(options.get("solver_options", {}))
    so.setdefault("dtype", "float64")
    settings = ADMMSettings(**so)

    arr = _global_scen_arrays(batch_local, S, rows, mesh, axis, settings,
                              probs_local=true_probs)
    refresh, frozen = sharded.make_ph_step_pair(
        batch_local.tree.nonant_indices, settings, mesh, axis)
    state = _init_state_dist(
        arr, float(options.get("defaultPHrho", 1.0)), settings)
    return DistPHSetup(arr, state, refresh, frozen, batch_local, settings,
                       mesh, S)


def distributed_ph(all_scenario_names, scenario_creator,
                   scenario_creator_kwargs=None, options=None,
                   mesh=None, axis: str = "scen"):
    """Run scenario-sharded PH with scenarios partitioned across PROCESSES.

    Call collectively from every process of an initialized
    ``jax.distributed`` job.  Each process instantiates only its own
    scenario slice (:func:`scen_to_process`), so the global family never
    materializes on one host — the reference's rank-local scenario lists
    (spbase.py:184-216).  Returns a :class:`DistPHResult` (identical on
    every process; the consensus xbar is fully reduced).
    """
    import jax

    from .elastic import Watchdog

    options = dict(options or {})
    setup = _setup_distributed(all_scenario_names, scenario_creator,
                               scenario_creator_kwargs, options, mesh, axis)
    arr, state, refresh, frozen = (setup.arr, setup.state, setup.refresh,
                                   setup.frozen)

    iters = int(options.get("PHIterLimit", 10))
    refresh_every = max(1, int(options.get("solver_refresh_every", 16)))
    convthresh = float(options.get("convthresh", -1.0))
    # bounded-timeout mesh barriers (doc/resilience.md): a dead peer
    # raises ControllerLost within options["mesh_timeout"] /
    # TPUSPPY_MESH_TIMEOUT instead of wedging every process forever
    wd = Watchdog.from_options(options)
    state, out, factors = wd.call(
        lambda: refresh(state, arr, 0.0), "iter0")   # plain objective
    conv = eobj = np.inf
    it = 0

    def _step(it):
        nonlocal state, out, factors, conv, eobj
        if (it - 1) % refresh_every == 0:
            state, out, factors = refresh(state, arr, 1.0)
        else:
            state, out = frozen(state, arr, 1.0, factors)
        conv = float(np.asarray(out.conv))
        eobj = float(np.asarray(out.eobj))

    for it in range(1, iters + 1):
        wd.call(lambda: _step(it), f"ph_iter[{it}]")
        if 0 <= convthresh and conv < convthresh:
            break
    wd.close()

    # consensus nonants: replicated per-node xbar, gathered host-side from
    # the addressable shard (identical across processes post-psum)
    xb = np.asarray(
        jax.device_get(state.xbars.addressable_shards[0].data))[0]
    return DistPHResult(conv=conv, eobj=eobj, xbars=np.asarray(xb),
                        iters=it)
