"""Multi-controller hub cylinder inside a wheel + the write-id acceptance vote.

The reference's headline topology puts EVERY cylinder on many MPI ranks:
``mpisppy/spin_the_wheel.py:219-237`` requires ``n_proc % (n_spokes+1) == 0``
and splits COMM_WORLD so each cylinder is its own multi-rank communicator.
Because one-sided RMA reads on different ranks can race a writer mid-Put,
acceptance is a VOTE: a spoke's ranks all read their local window copy and
agree on the write-id before acting (``cylinders/spoke.py:99-118``), and the
hub's ranks do the same for spoke payloads (``cylinders/hub.py:424-436``).

Here the multi-rank cylinder is a multi-controller JAX job: the hub's PH
state is scenario-sharded over a mesh spanning every controller process
(:mod:`tpusppy.parallel.distributed`), and the wheel fabric is the C++ TCP
window service (:mod:`tpusppy.runtime.tcp_window_service`) — controller 0
serves the boxes, the other controllers connect as clients, spokes attach
from anywhere.  Each controller reads the spoke mailboxes over its own
connection, so reads genuinely race spoke Puts — the same hazard the
reference votes away, solved the same way: :func:`read_voted` re-reads until
every controller snapshotted the SAME write-id.

Determinism contract: after a voted read, every controller holds identical
payloads, so bound updates and the termination decision are bit-identical
across controllers — no controller can leave the PH collective early (which
would deadlock the psums).  :func:`distributed_wheel_hub` asserts this by
voting on the termination decision itself.
"""

from __future__ import annotations

import os
import time
from math import inf
from typing import NamedTuple

import numpy as np

from ..obs import metrics as _metrics
from ..obs import telemetry as _telemetry
from ..obs import trace as _trace
from ..obs.log import get_logger
from ..resilience import faults as _faults
from .distributed import _setup_distributed
from .elastic import ControllerLost, Watchdog

_log = get_logger("dist_wheel")

_CTR_ELASTIC_RESTORES = _metrics.counter("checkpoint.elastic_restores")
#: Device->host doubles each controller fetched for consensus assembly —
#: the shard-local routing contract (ROADMAP item 1): O(S/n_proc) per
#: controller per iteration, never the full replicated (S, K) state.
_CTR_CONSENSUS_DOUBLES = _metrics.counter(
    "dist_wheel.consensus_local_doubles")


def default_allgather():
    """Scalar allgather over the processes of the current jax.distributed
    job (the vote's communication primitive).  Write-ids are < 2^53 so the
    float64 path is exact."""
    from jax.experimental import multihost_utils

    def allgather(v):
        out = multihost_utils.process_allgather(
            np.asarray([float(v)], np.float64))
        return [float(x) for x in np.asarray(out).ravel()]

    return allgather


def read_voted(mailbox, allgather, max_tries: int = 10000,
               sleep_s: float = 0.002):
    """All-controllers-agree mailbox read.

    Every controller snapshots ``(payload, write_id)`` from its own view of
    the mailbox, then the controllers exchange write-ids; if any pair
    disagrees (a writer raced between their reads), ALL re-read and vote
    again.  Mirrors ``mpisppy/cylinders/spoke.py:99-118`` (spoke ranks) and
    ``hub.py:424-436`` (hub ranks).  The kill sentinel (-1) is terminal and
    immediately visible on every connection, so a mixed [-1, n] vote
    converges to agreement on -1 within one re-read.

    Returns ``(payload, write_id, retries)``; raises after ``max_tries``
    disagreeing rounds (a vote that cannot converge means a broken fabric,
    not a slow writer).
    """
    retries = 0
    _metrics.inc("dist_wheel.voted_reads")
    for _ in range(max_tries):
        data, wid = mailbox.get()
        ids = allgather(wid)
        if all(i == ids[0] for i in ids):
            return data, int(wid), retries
        retries += 1
        # a disagreeing round = controllers re-read after racing a writer
        # mid-Put — the exact hazard the vote exists for, so it is the
        # covered-path observable (DistWheelResult.vote_retries totals it)
        _metrics.inc("dist_wheel.vote_retries")
        if _trace.enabled():
            _trace.instant("hub", "vote_retry",
                           box=getattr(mailbox, "name", "?"),
                           ids=list(ids))
        time.sleep(sleep_s)
    raise RuntimeError(
        f"write-id vote failed to converge after {max_tries} rounds "
        f"(mailbox {getattr(mailbox, 'name', '?')})")


class DistWheelResult(NamedTuple):
    BestInnerBound: float
    BestOuterBound: float
    rel_gap: float
    conv: float
    eobj: float
    iters: int
    vote_retries: int    # total disagreeing vote rounds (the covered path)
    # per-iteration (it, conv, eobj) triples, recorded only under
    # options["record_trajectory"] — the elastic re-shard parity tests
    # compare post-resume trajectories against an uninterrupted golden
    trajectory: tuple = ()


def distributed_wheel_hub(all_scenario_names, scenario_creator,
                          scenario_creator_kwargs=None, options=None,
                          fabric=None, spoke_roles=None, mesh=None,
                          axis: str = "scen", allgather=None,
                          is_minimizing: bool = True):
    """Run the HUB cylinder of a wheel across every process of a
    jax.distributed job, spokes attached over ``fabric``.

    Call collectively from all controller processes.  ``fabric`` is each
    process's own view of the TCP window fabric (controller 0: the serving
    ``TcpWindowFabric(spoke_lengths=...)``; others: a client
    ``TcpWindowFabric(connect=...)``).  ``spoke_roles[i]`` (for strata rank
    i+1) is ``{"bound": "outer"|"inner", "wants": "W"|"nonants"}`` — the
    role vocabulary of the spoke type lattice (cylinders/spoke.py).
    ``fabric=None`` with empty ``spoke_roles`` runs the spokeless hub
    cylinder alone — the tier-1 smoke posture exercising the 2-process PH
    collective + voted-termination path on a deterministic schedule
    (exactly where the historical deadlock classes lived) without any
    window-service dependency.

    Controller 0 is the single WRITER (payloads are replicated consensus
    state, identical on every controller); ALL controllers read spoke
    mailboxes and accept via :func:`read_voted`.  Payload layouts match
    :class:`tpusppy.cylinders.hub.PHHub`: ``[W.ravel()|xk.ravel(), OB, IB]``.

    Fault tolerance (doc/resilience.md "Elastic recovery"): every mesh
    collective — PH steps, consensus fetches, vote allgathers — runs
    under a :class:`~tpusppy.parallel.elastic.Watchdog`, so a dead or
    wedged peer raises a typed ``ControllerLost`` within
    ``options["mesh_timeout"]`` (default ``TPUSPPY_MESH_TIMEOUT``; 0
    disables) instead of hanging forever.  Drive this function through
    :func:`tpusppy.parallel.elastic.elastic_wheel_hub` to turn that
    detection into survivor agreement + re-mesh + sharded-checkpoint
    resume (``options["elastic_epoch"]`` marks the restore as elastic
    for the ``checkpoint.elastic_restores`` counter);
    ``options["record_trajectory"]`` banks per-iteration (it, conv,
    eobj) on the result for parity tests.

    Reference: one multi-rank hub cylinder of ``spin_the_wheel.py:219-237``
    with the acceptance votes of ``hub.py:424-436``.
    """
    import jax

    options = dict(options or {})
    spoke_roles = list(spoke_roles or [])
    if allgather is None:
        allgather = default_allgather()
    # collective watchdog (tpusppy.parallel.elastic, doc/resilience.md):
    # every mesh barrier, voted-read allgather and consensus fetch runs
    # under a bounded deadline, so a dead or wedged controller raises a
    # typed ControllerLost within TPUSPPY_MESH_TIMEOUT instead of
    # hanging the surviving mesh forever.  options["mesh_timeout"]=0
    # restores the legacy block-forever collectives.
    wd = Watchdog.from_options(options)
    allgather = wd.wrap(allgather, "vote_allgather")
    writer = jax.process_index() == 0
    my_rank = jax.process_index()
    # clock-sync stamp per controller ring: scripts/trace_merge.py reads
    # it to place each process's perf_counter-relative events on one
    # absolute wall timeline (multi-controller meshes included)
    _telemetry.record_clock_sync(f"controller{my_rank}", rank=my_rank,
                                 nproc=jax.process_count())

    setup = _setup_distributed(all_scenario_names, scenario_creator,
                               scenario_creator_kwargs, options, mesh, axis)
    arr, state = setup.arr, setup.state
    refresh, frozen = setup.refresh, setup.frozen
    S = setup.S
    nonant_idx = setup.batch_local.tree.nonant_indices

    # ---- shard-local consensus fetch (ROADMAP item 1 remaining) ----------
    # Each controller pulls ONLY its own scenario-row slice off the device
    # (O(S/n_proc) doubles per fetch, billed to
    # ``dist_wheel.consensus_local_doubles``); the full consensus the spoke
    # payloads need is then assembled by ONE host-level all-gather per
    # fetch.  The old path resharded the whole state to replicated and
    # materialized the full (S, K) array on EVERY controller — O(S) D2H
    # apiece — as two/three back-to-back single-collective jitted programs.
    # That shape was also the root cause of the two-controller wheel abort
    # ("op.preamble.length <= op.nbytes. 44 vs 12"): separately jitted
    # single-collective programs are lowered with the same collective
    # channel id, so a controller still draining the W gather could
    # receive its peer's already-dispatched x-gather payload on the same
    # Gloo slot — the 44-double x rows landing in a 12-double W buffer
    # aborts the whole job.  One fused gather per fetch removes the
    # same-channel adjacency entirely (post-mortem in
    # tests/test_distributed_wheel.py::test_two_controller_hub_wheel_certifies).
    nproc = jax.process_count()
    nonant_idx_np = np.asarray(nonant_idx)

    def _local_block(arr2d):
        """(lo, rows) — this controller's contiguous row block of one
        (Sp, ·) scenario-sharded array, fetched shard by shard (the only
        D2H this loop ever does on consensus state) and billed."""
        seen = {}
        for sh in arr2d.addressable_shards:
            seen.setdefault(sh.index[0].start or 0, sh)
        starts = sorted(seen)
        lo = starts[0]
        block = np.concatenate(
            [np.asarray(seen[s].data) for s in starts], axis=0)
        _CTR_CONSENSUS_DOUBLES.inc(block.size)
        return int(lo), block

    iters = int(options.get("PHIterLimit", 10))
    refresh_every = max(1, int(options.get("solver_refresh_every", 16)))
    rel_gap_target = float(options.get("rel_gap", -1.0))
    BestInner = inf if is_minimizing else -inf
    BestOuter = -inf if is_minimizing else inf

    def better_inner(new, old):
        return new < old if is_minimizing else new > old

    def better_outer(new, old):
        return new > old if is_minimizing else new < old

    # ---- resilience: resume + async checkpointing (doc/resilience.md) ----
    # Every controller loads the SAME checkpoint (shared filesystem, the
    # same contract the fabric's launch recipe already assumes for
    # secrets) so the restored consensus state is bit-identical; only
    # controller 0 ever writes snapshots.
    from ..resilience import checkpoint as _ckpt

    it_base = 0

    def _merge_resume_scalars(iteration, best_inner, best_outer,
                              tune_state):
        """The one scalar-restore path both resume forms share: bounds
        merge monotonically, the iteration base continues the TOTAL
        count, banked tune verdicts skip warmup probes."""
        nonlocal BestInner, BestOuter, it_base
        if np.isfinite(best_inner) and better_inner(best_inner, BestInner):
            BestInner = float(best_inner)
        if np.isfinite(best_outer) and better_outer(best_outer, BestOuter):
            BestOuter = float(best_outer)
        it_base = int(iteration)
        if tune_state:
            from .. import tune as _tune

            _tune.import_state(tune_state)

    resume_src = options.get("resume")
    ck0 = ck0_reader = None
    if resume_src:
        p0 = resume_src if not os.path.isdir(str(resume_src)) \
            else _ckpt.latest(str(resume_src))
        if p0 and _ckpt._SHARD_RE.match(os.path.basename(p0)):
            # SHARDED resume: scalars come from shard 0's meta; the W
            # restore reads only this process's row shards, via
            # make_array_from_callback — the full (S, K) state never
            # materializes on one host
            ck0_reader = _ckpt.ShardedCheckpointReader(p0)
            md = ck0_reader.meta
            sh = md.get("meta", {}).get("shard", {})
            # K from the shard META (stored alongside rows/S): answering
            # the shape check must not decompress shard 0's whole array
            # block on every process at 10^5-scenario scale
            K_ck = ck0_reader.K if ck0_reader.K is not None \
                else ck0_reader.read_rows("W", 0, 1).shape[1]
            if int(sh.get("S", -1)) != S or K_ck != state.W.shape[1]:
                raise RuntimeError(
                    f"sharded checkpoint ({sh.get('S')} scenarios, "
                    f"K={K_ck}) does not match this wheel ({S} "
                    f"scenarios, K={state.W.shape[1]}) — resuming a "
                    f"different family?")
            _merge_resume_scalars(
                ck0_reader.iteration, md.get("best_inner", inf),
                md.get("best_outer", -inf), md.get("tune_state"))
        elif p0:
            ck0 = _ckpt.load(p0)
    if ck0 is not None:
        # exact-S match (snapshots carry exactly S rows): the PADDED
        # state row count would silently accept a different scenario
        # count and certify against a foreign run's bounds
        if (ck0.W is None or ck0.W.shape[1] != state.W.shape[1]
                or ck0.W.shape[0] != S):
            raise RuntimeError(
                f"checkpoint W {getattr(ck0.W, 'shape', None)} does not "
                f"match this wheel ({S} scenarios, K="
                f"{state.W.shape[1]}) — resuming a different family?")
        _merge_resume_scalars(ck0.iteration, ck0.best_inner,
                              ck0.best_outer, ck0.tune_state)
    if (ck0 is not None or ck0_reader is not None) \
            and int(options.get("elastic_epoch", 0) or 0) > 0:
        # an ELASTIC restore: this controller is a re-meshed survivor
        # rebuilding the wheel on a smaller mesh from the shard set the
        # previous epoch banked (the acceptance-visible signal)
        _CTR_ELASTIC_RESTORES.inc(1)
        _log.warning(
            "elastic restore (mesh epoch %d): resuming iteration %d on "
            "the re-meshed survivor set", int(options["elastic_epoch"]),
            it_base)

    def _restore_W(state):
        """Re-seat the checkpointed W AND xbars AFTER Iter0 (the phbase
        seam): Iter0 must run with W=0 — its prox-off eobj is only the
        valid wait-and-see trivial bound at W=0 (the solve minimizes
        (c+W)x while eobj prices plain c), and the wholesale replacement
        also discards Iter0's W-update so the loop continues from exactly
        the snapshot's duals.  xbars matters as much as W: it is the
        PROX CENTER of the next iterk solve (sharded._ph_objective), so
        a W-only restore would aim the first resumed iteration at Iter0's
        consensus instead of the snapshot's — the elastic re-shard parity
        tests pin the trajectory against an uninterrupted golden.  Old
        W-only checkpoints still restore (bounds + duals, legacy
        semantics)."""

        def _dev(field, like):
            if ck0_reader is not None:
                # shard-read restore: each process's callback reads ONLY
                # the shard files overlapping its addressable rows
                # (ghost/pad rows past S come back zero) — state's own
                # dtype, as below
                return _ckpt.restore_sharded_array(
                    ck0_reader, field, like.sharding,
                    like.shape, dtype=like.dtype)
            # state's own dtype, not the npz's (always f64): an f32
            # wheel must not have a mixed-dtype carry swapped into its
            # compiled state pytree
            src = getattr(ck0, field)
            full = np.zeros(like.shape, dtype=like.dtype)
            full[:src.shape[0]] = src
            return jax.make_array_from_callback(
                full.shape, like.sharding, lambda idx: full[idx])

        if ck0_reader is not None:
            fields = ck0_reader.meta.get("arrays", ["W"])
        else:
            fields = [f for f in ("W", "xbars") if getattr(ck0, f, None)
                      is not None]
        rep = {f: _dev(f, getattr(state, f))
               for f in ("W", "xbars") if f in fields}
        if ck0_reader is not None:
            # the reader stays alive in this closure for the run: free
            # its cached row blocks now that the restore consumed them
            ck0_reader.drop_cache()
        return state._replace(**rep)

    def _local_rows(Wd):
        """Contiguous global row range this process's addressable shards
        cover (the scenario axis is the leading dim; device order on the
        1-D mesh makes per-process rows contiguous)."""
        los, his = [], []
        for s in Wd.addressable_shards:
            r = s.index[0]
            los.append(0 if r.start is None else r.start)
            his.append(Wd.shape[0] if r.stop is None else r.stop)
        return min(los), max(his)

    ckpt_mgr = None
    ckpt_sharded = bool(options.get("checkpoint_sharded"))
    shard_rows = None
    if options.get("checkpoint_dir") and (writer or ckpt_sharded):
        shard = None
        every_secs = options.get("checkpoint_every_secs", 60.0)
        every_iters = options.get("checkpoint_every_iters")
        if ckpt_sharded:
            lo, hi = _local_rows(state.W)
            # clip to the REAL scenario count: ghost/pad rows (uneven S
            # over the mesh) never checkpoint
            shard_rows = (min(lo, S), min(hi, S))
            shard = (jax.process_index(), jax.process_count(),
                     shard_rows, S)
            if every_iters is None:
                # a WALL-CLOCK cadence is per-process: controllers can
                # disagree on which iterations are due (and each
                # writer thread coalesces independently), so per-shard
                # managers could persist DISJOINT iteration sets and the
                # keep-window prune would eventually leave no COMPLETE
                # set at all — a resume would silently cold-start.  A
                # deterministic iteration cadence keeps every process's
                # shard files aligned by construction.
                every_iters = max(1, refresh_every)
                every_secs = None
                _log.warning(
                    "checkpoint_sharded without checkpoint_every_iters: "
                    "forcing the deterministic iteration cadence "
                    "(every %d iterations) — wall-clock cadences "
                    "desynchronize per-process shard sets", every_iters)
        ckpt_mgr = _ckpt.CheckpointManager(
            options["checkpoint_dir"],
            every_secs=every_secs, every_iters=every_iters,
            keep=options.get("checkpoint_keep", 3), tag="dist_wheel",
            fresh_start=ck0 is None and ck0_reader is None, shard=shard)

    def gap():
        ag = (BestInner - BestOuter) if is_minimizing \
            else (BestOuter - BestInner)
        if np.isfinite(ag) and np.isfinite(BestOuter):
            return ag / (abs(BestOuter) or 1.0)
        return inf

    last_ids = {i + 1: 0 for i in range(len(spoke_roles))}
    total_retries = 0

    def pull_bounds():
        """Voted read of every spoke bound; freshness by write-id, exactly
        the hub-side acceptance of hub.py:424-436."""
        nonlocal BestInner, BestOuter, total_retries
        for i, role in enumerate(spoke_roles):
            idx = i + 1
            data, wid, retries = read_voted(fabric.to_hub[idx], allgather)
            total_retries += retries
            if wid > last_ids[idx] or wid < 0:
                last_ids[idx] = wid
                b = float(data[0])
                if np.isfinite(b):
                    if role["bound"] == "outer" and better_outer(b, BestOuter):
                        if _trace.enabled():
                            _trace.instant("hub", "outer_bound_update",
                                           old=BestOuter, new=b, spoke=idx)
                            _trace.counter("hub", "best_outer", b)
                        BestOuter = b
                    elif (role["bound"] == "inner"
                          and better_inner(b, BestInner)):
                        if _trace.enabled():
                            _trace.instant("hub", "inner_bound_update",
                                           old=BestInner, new=b, spoke=idx)
                            _trace.counter("hub", "best_inner", b)
                        BestInner = b

    # checkpointing wheels also fetch xbars: it is the PROX CENTER of
    # the next iterk solve, so snapshots must carry it for an exact
    # trajectory continuation (elastic re-shard parity).  Both
    # conditions derive from the SHARED options dict (+ the iteration
    # counter, identical by lockstep), so every controller runs the same
    # collective program — a per-role condition would deadlock the mesh.
    # With a deterministic iteration cadence the extra (S, K) all-gather
    # happens only on iterations that can actually capture; a wall-clock
    # cadence is per-process-unpredictable, so there it rides every
    # iteration.
    _ck_armed = bool(options.get("checkpoint_dir"))
    _ck_every_iters = options.get("checkpoint_every_iters")
    if _ck_armed and ckpt_sharded and _ck_every_iters is None:
        _ck_every_iters = max(1, refresh_every)    # mirrors the manager

    def want_xbars(it) -> bool:
        if not _ck_armed:
            return False
        if not _ck_every_iters:
            return True        # wall-clock cadence: any iteration may be due
        return (it - it_base) % max(1, int(_ck_every_iters)) == 0

    def _fetch_consensus_raw(include_xbars=False):
        # the assembly all-gather is a COLLECTIVE (every controller must
        # join it, even though only controller 0 writes the result into
        # the spoke boxes — an early non-writer return here deadlocks the
        # mesh), and it is ONE fused gather: W rows, nonant-sliced x rows
        # and (when a capture may be due) xbars rows ride a single host
        # vector, so there is exactly one collective program per fetch
        # and no same-channel adjacent-program hazard
        lo, W_loc = _local_block(state.W)
        _, x_loc = _local_block(state.x)
        xk_loc = x_loc[:, nonant_idx_np]
        blocks = [W_loc, xk_loc]
        if include_xbars:
            blocks.append(_local_block(state.xbars)[1])
        rows_pp = W_loc.shape[0]
        widths = [b.shape[1] for b in blocks]
        if nproc == 1:
            full = [np.asarray(b, np.float64) for b in blocks]
        else:
            from jax.experimental import multihost_utils

            vec = np.concatenate(
                [np.asarray([float(lo)])]
                + [np.asarray(b, np.float64).ravel() for b in blocks])
            allv = np.asarray(multihost_utils.process_allgather(vec))
            Sp = rows_pp * nproc
            full = [np.zeros((Sp, w)) for w in widths]
            for p in range(nproc):
                v, off, lo_p = allv[p], 1, int(allv[p][0])
                for fi, w in enumerate(widths):
                    sz = rows_pp * w
                    full[fi][lo_p:lo_p + rows_pp] = \
                        v[off:off + sz].reshape(rows_pp, w)
                    off += sz
        base = (full[0][:S].ravel(), full[1][:S].ravel())
        return base + ((full[2][:S],) if include_xbars else ())

    fetch_consensus = wd.wrap(_fetch_consensus_raw, "consensus_fetch")

    def push_state(cached=None):
        W, xk = (fetch_consensus() if cached is None else cached)[:2]
        if not writer:
            return
        for i, role in enumerate(spoke_roles):
            payload = W if role.get("wants", "W") == "W" else xk
            fabric.to_spoke[i + 1].put(
                np.concatenate([payload, [BestOuter, BestInner]]))

    def robust_collective(fn, tries=8, backoff=3.0):
        """Re-attempt a collective step whose Gloo context init timed out.

        The first cross-process execution races a fixed ~30s rendezvous
        window; controllers can reach it further apart than that (cold
        local compiles, loaded hosts).  Re-execution is safe — inputs are
        immutable jax arrays — and both controllers retry symmetrically
        until their attempts overlap inside the window.
        """
        last = None
        for i in range(tries):
            try:
                return fn()
            except Exception as e:     # jaxlib surfaces DEADLINE_EXCEEDED
                msg = repr(e)
                if "Gloo" not in msg and "DEADLINE" not in msg:
                    raise
                last = e
                time.sleep(backoff)
        raise last

    # Iter0: plain objective (W=0, prox off) — its eobj is the wait-and-see
    # bound, the hub's trivial outer bound (phbase.py:758-872 semantics)
    def _iter0():
        st, o, f = refresh(state, arr, 0.0)
        return st, o, f, float(np.asarray(o.eobj))

    state, out, factors, trivial = wd.call(
        lambda: robust_collective(_iter0), "iter0")
    if better_outer(trivial, BestOuter):
        BestOuter = trivial
    if ck0 is not None or ck0_reader is not None:
        state = _restore_W(state)

    conv = eobj = inf
    it = it_base
    record_traj = bool(options.get("record_trajectory"))
    trajectory = []

    def voted_stop():
        # the termination DECISION is itself voted: identical voted
        # inputs make it deterministic, and the assert turns any
        # nondeterminism bug into a loud failure instead of a psum
        # deadlock two iterations later
        stop = rel_gap_target >= 0 and gap() <= rel_gap_target
        votes = allgather(1.0 if stop else 0.0)
        assert all(v == votes[0] for v in votes), \
            "controllers disagreed on termination — determinism bug"
        if votes[0] and _trace.enabled():
            _trace.instant("hub", "terminate", reason="rel_gap",
                           rel_gap=gap(), best_outer=BestOuter,
                           best_inner=BestInner, iter=it)
        return bool(votes[0])

    last_consensus = [None]

    def _snap(it, consensus):
        from .. import tune as _tune

        W_host = consensus[0]
        K = W_host.size // max(1, S)
        W_full = np.asarray(W_host).reshape(S, K)
        # xbars rides the snapshot when the consensus carried it (every
        # checkpointing wheel): the prox center of the next solve —
        # without it a resume re-aims the first iteration at Iter0's
        # consensus and trajectory parity with the uninterrupted run dies
        xb_full = (np.asarray(consensus[2])[:S] if len(consensus) > 2
                   else None)
        # sharded capture slices ONLY this process's rows from the
        # already-fetched consensus (zero extra fetches, zero
        # collectives); the non-sharded writer takes all S rows — one
        # unconditional slice serves both
        lo, hi = shard_rows if shard_rows is not None else (0, S)
        return _ckpt.WheelCheckpoint(
            iteration=it, W=W_full[lo:hi].copy(),
            xbars=None if xb_full is None else xb_full[lo:hi].copy(),
            best_inner=BestInner, best_outer=BestOuter,
            tune_state=_tune.export_state(),
            meta={"S": S, "K": K, "kind": "dist_wheel"})

    def maybe_checkpoint(it, consensus):
        """Bank a snapshot from the ALREADY-fetched consensus (push_state
        needed the same host arrays this very iteration), so
        checkpointing adds zero fetches — and, critically, zero
        COLLECTIVES — to the wheel's decision path (only controller 0
        owns a manager; a collective here would desynchronize it from
        the other controllers)."""
        last_consensus[0] = consensus
        if ckpt_mgr is None:
            return
        try:
            ckpt_mgr.maybe_capture(it, lambda: _snap(it, consensus))
        except Exception as e:
            # capture costs resumability, never the run (hub.py policy) —
            # and on THIS topology an exception here would also strand
            # the other controllers mid-collective
            _metrics.inc("checkpoint.capture_errors")
            _log.warning("checkpoint capture failed (run continues): %r", e)

    def _step(it):
        """One PH iteration: the sharded collective program + its result
        materialization — THE blocking point a dead peer wedges, so the
        whole thing runs under the watchdog's deadline."""
        nonlocal state, out, factors, conv, eobj
        if (it - it_base - 1) % refresh_every == 0:
            state, out, factors = refresh(state, arr, 1.0)
        else:
            state, out = frozen(state, arr, 1.0, factors)
        conv = float(np.asarray(out.conv))
        eobj = float(np.asarray(out.eobj))

    lost_mid_wheel = False
    try:
        for it in range(it_base + 1, iters + 1):
            # deterministic controller-death injection (faults.py): a
            # real SIGKILL of THIS process at an exact iteration — one
            # module-flag check when disarmed
            if _faults.active():
                _faults.on_controller_iter(my_rank, it)
            with _trace.span("hub", "wheel_iter"):
                wd.call(lambda: _step(it), f"wheel_iter[{it}]")
                consensus = fetch_consensus(want_xbars(it))
                push_state(consensus)
                pull_bounds()
                maybe_checkpoint(it, consensus)
            if record_traj:
                trajectory.append((it, conv, eobj))
            if voted_stop():
                break
        else:
            # PRE-KILL harvest (PHHub._linger semantics): the hub's sharded
            # iterations are much faster than the spokes' solve rounds, so
            # at loop end the spokes are still digesting early Ws.  Keep
            # the final consensus posted and the bound boxes polled until
            # the gap certifies or the budget runs out — FIXED poll count,
            # like every other loop here (wall-clock-bounded loops could
            # desynchronize the controllers' collective calls).  Pointless
            # without a gap target; the state is frozen, so the consensus
            # is fetched ONCE and only the bound tail refreshes per poll.
            if rel_gap_target >= 0:
                cached = fetch_consensus()
                polls = max(1, int(float(options.get(
                    "harvest_secs",
                    options.get("linger_secs", 10.0))) / 0.5))
                for _ in range(polls):
                    push_state(cached)
                    pull_bounds()
                    if voted_stop():
                        break
                    time.sleep(0.5)
    except Exception as e:
        lost_mid_wheel = isinstance(e, ControllerLost)
        raise
    finally:
        # a ControllerLost exit must NOT kill the spokes: the surviving
        # controllers re-mesh and resume this very wheel (elastic.py),
        # and the spokes — attached to the fabric, not the mesh — keep
        # solving right through the outage
        if writer and fabric is not None and not lost_mid_wheel:
            fabric.send_terminate()

    # harvest late spoke bounds posted between our last pull and the kill
    # (their boxes stay writable after the hub->spoke kill, and finalize
    # passes may tighten bounds — hub_finalize semantics, hub.py:438-450).
    # FIXED poll count: a wall-clock-bounded loop could run different
    # iteration counts on different controllers and deadlock the vote's
    # collectives — the same reason the segmented dispatch runs a
    # deterministic schedule multi-process.
    polls = max(1, int(float(options.get("linger_secs", 10.0)) / 0.25))
    for _ in range(polls):
        pull_bounds()
        time.sleep(0.25)

    if ckpt_mgr is not None:
        # terminal snapshot with the HARVESTED bounds, from the loop's
        # last fetched consensus — never a fresh collective fetch (the
        # other controllers are no longer in lockstep with this code)
        try:
            if last_consensus[0] is not None:
                ckpt_mgr.capture(it, lambda: _snap(it, last_consensus[0]))
        except Exception as e:   # never lose the certified result over it
            _metrics.inc("checkpoint.capture_errors")
            _log.warning("final checkpoint capture failed: %r", e)
        ckpt_mgr.close()

    wd.close()
    return DistWheelResult(BestInner, BestOuter, gap(), conv, eobj, it,
                           total_retries, tuple(trajectory))
