"""Warmup autotuner for the fused PH dispatch cadence.

The fused multi-iteration program (:func:`tpusppy.parallel.sharded.
make_ph_fused_step`) has two knobs: ``refresh_every`` (how many PH
iterations reuse one factorization — the math/amortization trade) and
``chunk`` (how many PH iterations one device dispatch carries — the
latency/watchdog trade).  The benchmark used to hard-code ``chunk=64``/
``refresh_every=16``; shapes whose sweeps are 16x costlier (farmer
crops_mult=4 vs 1) then run chunks far below what the worker watchdog
allows and pay dispatch round-trips they don't have to, while the static
worst-case cap (:func:`~tpusppy.parallel.sharded.fused_iteration_cap`,
every frozen iteration billed at its full ``max_iter`` sweep budget) is
~5-10x more conservative than measured reality.

:func:`autotune_fused` replaces both with measurement at warmup: for each
``refresh_every`` candidate it times a one-block probe dispatch, converts
the MEASURED seconds/iteration into a watchdog-safe chunk (``margin`` x
the dispatch target budget), confirms the rate at that chunk, and picks
the fastest cadence.  Probes are real PH iterations (the state advances —
warmup work is not wasted) and each probe is itself sized inside the
static worst-case cap, so a mistuned model can never push a probe past
the watchdog.

Grew out of ``scripts/profile_sweep_parts.py`` (whose jit/fetch timing
helper lives here now as :func:`time_jitted`); results feed ``bench.py``
and any driver that wants a per-shape cadence instead of a global
default.

Verdicts PERSIST: every fresh pick is banked in a JSON-able store keyed
by the same shape+settings+mesh key plus the jax version, saved
atomically to ``TPUSPPY_TUNE_CACHE`` when that knob names a file and
carried inside wheel checkpoints (:mod:`tpusppy.resilience.checkpoint`),
so repeated bench/wheel runs — and resumed ones — skip the warmup
probes entirely (:func:`export_state` / :func:`import_state` /
:func:`save_cache` / :func:`load_cache`).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any

import numpy as np

from .obs import metrics as _metrics
from .obs import trace as _trace
from .parallel import sharded
from .solvers import aot as _aot
from .solvers import segmented as segmented_solvers


def _probe_event(kind: str, entry: dict):
    """One autotune probe verdict onto the "tune" track + counters —
    the autotuner's decisions (cadence picks, precision certifications,
    pipeline enables) are exactly the knobs a perf regression hunt needs
    on the timeline."""
    _metrics.inc(f"tune.{kind}_probes")
    if _trace.enabled():
        _trace.instant("tune", kind, **entry)


@dataclasses.dataclass
class TuneResult:
    chunk: int                 # picked dispatch size (PH iters per dispatch)
    refresh_every: int         # picked factorization cadence
    iters_per_sec: float       # measured at the picked (chunk, refresh)
    secs_per_iter: float
    sweeps_per_iter: float     # mean measured ADMM sweeps per PH iteration
    table: list                # per-candidate measurement dicts
    state: Any                 # PH state advanced by the probe iterations
    out: Any                   # last probe's PHStepOut
    # picked frozen-sweep matmul precision: the fastest mode whose probe
    # residuals certified against the full-precision reference ("highest"
    # when no lower mode certified or none were probed)
    precision: str = "highest"


_cache: dict = {}


# ---------------------------------------------------------------------------
# Persistent verdict store (disk + checkpoint interchange).
#
# Repeated bench/wheel runs used to re-pay the warmup probes (cadence,
# precision, pipeline) on every process start.  Verdicts are banked here
# keyed by ``repr`` of the SAME shape+settings+mesh key the in-memory
# cache uses, partitioned by jax version (a jaxlib bump can change every
# measured rate), and persisted to ``TPUSPPY_TUNE_CACHE`` (a JSON file)
# with the engine-wide atomic write-tmp-then-rename discipline.  The
# resilience checkpoint engine snapshots/reseeds the same store
# (:func:`export_state` / :func:`import_state`), so a resumed wheel
# skips its warmup probes too.  Multiple processes banking concurrently
# are last-writer-wins per save — acceptable for a cache whose entries
# are independently recomputable.
# ---------------------------------------------------------------------------
# Schema v2 (the megakernel PR): a "megastep" verdict kind joined the
# store, and the fused/pipeline KEYS changed — ``ADMMSettings`` grew the
# ``megastep`` field, which rides every settings repr in a key — so a v1
# file's verdicts could otherwise never be distinguished from current
# ones.  ``import_state`` drops foreign-version state wholesale (tolerant
# load: an old cache file is just a cold cache, never a crash and never a
# stale cadence/pipeline verdict served to a megakernel-enabled run).
_PERSIST_VERSION = 2
# "aot" (the executable-cache PR): per-fused-key list of AOT executable
# cache keys compiled/loaded while that verdict was measured — a disk hit
# on the fused verdict then PRE-WARMS those executables in a background
# thread before iter0 (tpusppy/solvers/aot.py).  Absent in older v2
# files, tolerated (just no prewarm) — no schema bump needed: fused/
# pipeline/megastep keys are unchanged.
# "bound_cadence" (the in-wheel certification PR): per-shape verdict for
# how often a self-certifying megastep window runs its fused bound pass
# (doc/pipeline.md "In-wheel certification").  Absent in older v2 files,
# tolerated — existing kinds' keys are unchanged, no schema bump.
# "integer" (the batched integer wheel PR, doc/integer.md): per-shape
# verdict for the rounding-sweep width K (how many ladder thresholds the
# integer bound pass evaluates) and its window cadence, picked from the
# measured marginal pass cost.  Absent in older files, tolerated.
# "batched" (continuous batching, doc/serving.md): per-family verdict
# for the tenant-batched megastep's slot count K, picked so the fused
# window's measured per-slot marginal cost keeps the whole dispatch
# under the watchdog budget.  Absent in older files, tolerated.
_PERSIST_KINDS = ("fused", "pipeline", "megastep", "aot", "bound_cadence",
                  "integer", "batched")
_persist: dict = {k: {} for k in _PERSIST_KINDS}
_persist_lock = threading.Lock()
_disk_loaded_from: str | None = None


def _jax_version() -> str:
    try:
        import jax

        return str(jax.__version__)
    except ImportError:             # key-building unit tests without jax
        return "none"


_cache_path_override: str | None = None


def set_cache_path(path: str | None):
    """Programmatic override of the TPUSPPY_TUNE_CACHE knob (what
    ``Config.tune_cache`` routes through — scoped to this process's tune
    module instead of leaking an env var into every child)."""
    global _cache_path_override
    _cache_path_override = str(path) if path else None


def cache_path() -> str | None:
    """The armed persistent-cache path (programmatic override first, then
    TPUSPPY_TUNE_CACHE; empty/unset disables persistence — tests stay
    hermetic by default)."""
    return (_cache_path_override
            or os.environ.get("TPUSPPY_TUNE_CACHE") or None)


def export_state() -> dict:
    """JSON-able snapshot of every banked verdict (fused + pipeline) —
    what wheel checkpoints carry so a resume skips warmup probes."""
    with _persist_lock:
        out = {"version": _PERSIST_VERSION, "jax": _jax_version()}
        out.update({k: dict(_persist[k]) for k in _PERSIST_KINDS})
        return out


def import_state(state: dict):
    """Merge a snapshot produced by :func:`export_state` (same-jax-version
    entries only; foreign measurements must not masquerade as local).

    Foreign SCHEMA versions are dropped wholesale (tolerant load): a
    pre-megakernel (v1) store's fused/pipeline verdicts were keyed
    without the ``ADMMSettings.megastep`` field and must never be served
    to a megakernel-enabled run — an old file is just a cold cache."""
    if not state or state.get("jax") not in (None, _jax_version()):
        return
    if state.get("version") != _PERSIST_VERSION:
        _metrics.inc("tune.disk_version_skips")
        return
    with _persist_lock:
        for kind in _PERSIST_KINDS:
            _persist[kind].update(state.get(kind) or {})


def save_cache(path: str | None = None) -> str | None:
    """Atomically write the banked verdicts to ``path`` (default: the
    TPUSPPY_TUNE_CACHE knob).  No-op (None) when no path is armed."""
    path = path or cache_path()
    if not path:
        return None
    from .resilience.checkpoint import atomic_write_json

    return atomic_write_json(path, export_state())


def load_cache(path: str | None = None) -> int:
    """Load a verdict file into the in-process store; returns the number
    of entries now banked.  Files from another jax version are ignored
    (their measurements are not this toolchain's)."""
    path = path or cache_path()
    if not path or not os.path.exists(path):
        return 0
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, json.JSONDecodeError):
        return 0                 # a torn/foreign file is just a cold cache
    import_state(state)
    with _persist_lock:
        return sum(len(_persist[k]) for k in _PERSIST_KINDS)


def _maybe_load_disk():
    """Lazy one-shot load of the armed cache file (re-armed paths reload)."""
    global _disk_loaded_from
    path = cache_path()
    if path and path != _disk_loaded_from:
        _disk_loaded_from = path
        n = load_cache(path)
        if n:
            _metrics.inc("tune.disk_entries_loaded", n)


def _persist_get(kind: str, key_str: str):
    _maybe_load_disk()
    with _persist_lock:
        return _persist[kind].get(key_str)


def _persist_put(kind: str, key_str: str, entry: dict):
    with _persist_lock:
        _persist[kind][key_str] = entry
    if cache_path():
        try:
            save_cache()
        except OSError as e:     # a read-only cache dir must not kill tuning
            _metrics.inc("tune.disk_save_errors")
            from .obs.log import get_logger

            get_logger("tune").warning(
                "persistent cache save failed: %r", e)


def reset_persist():
    """Drop banked verdicts (test isolation)."""
    global _disk_loaded_from, _cache_path_override
    with _persist_lock:
        for kind in _PERSIST_KINDS:
            _persist[kind].clear()
    _mega_cache.clear()
    _bound_cadence_cache.clear()
    _integer_cache.clear()
    _disk_loaded_from = None
    _cache_path_override = None


def prewarm_aot(background: bool = False) -> int:
    """Pre-warm the AOT executable cache from every banked "aot" verdict
    (plus anything else in the cache dir): call before iter0/the first
    program build.  SYNCHRONOUS by default — on this toolchain the
    executable loader is only reliable in a clean XLA state (a big
    compile first can leave deserialization refusing entries with
    "Symbols not found"), so front-loading the deserializes beats
    overlapping them.  ``background=True`` restores the overlapped
    daemon-thread load (what a tune-cache disk hit uses mid-flow, where
    the fused-program load is the first XLA work anyway).  Returns the
    number of banked keys (0 = nothing armed/banked)."""
    _maybe_load_disk()
    with _persist_lock:
        keys = [k for entry in _persist["aot"].values()
                for k in (entry.get("keys") or [])]
    if not _aot.enabled():
        return 0
    # banked keys load first, then the directory sweep picks up programs
    # no tune verdict recorded (already-loaded keys are skipped).  The
    # banked list is capped like the sweep: a many-rung ladder cache can
    # bank far more shapes than this process will ever call, and every
    # load costs pre-iter0 wall + resident memory.
    want = list(dict.fromkeys(keys))[:_aot.PREWARM_MAX_FILES] or None
    if background:
        def _load():
            if want:
                _aot.prewarm(want)
            _aot.prewarm(None)

        threading.Thread(target=_load, name="aot-prewarm",
                         daemon=True).start()
    else:
        if want:
            _aot.prewarm(want)
        _aot.prewarm(None)
    return len(keys)


def _fetch(x):
    """Host fetch as the timing fence (block_until_ready returns early on
    the axon TPU plugin — see bench.py's timing note)."""
    return np.asarray(x)


def time_jitted(fn, *args, reps=20):
    """Milliseconds per call of an already-jitted ``fn`` (fetch-fenced);
    the sweep-part profiler's timing core (scripts/profile_sweep_parts)."""
    import jax
    import jax.numpy as jnp

    out = fn(*args)
    first = out[0] if isinstance(out, tuple) else out
    _fetch(jnp.sum(first) if isinstance(first, jax.Array) else first)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    first = out[0] if isinstance(out, tuple) else out
    _fetch(jnp.sum(first) if isinstance(first, jax.Array) else first)
    return (time.time() - t0) / reps * 1e3


def _tune_key(arr, settings, mesh, axis, prox_on, refresh_candidates,
              max_chunk, target_secs, margin, precision_candidates,
              certify_factor):
    # the shape+settings+mesh prefix is THE shared key builder
    # (aot.family_parts): the executable cache keys embed the same tuple,
    # so tune-cache keys and AOT-cache keys cannot silently drift
    return _aot.family_parts(arr, settings, mesh, axis) + (
        float(prox_on), tuple(refresh_candidates), max_chunk, target_secs,
        margin, tuple(precision_candidates or ()), certify_factor)


def autotune_fused(nonant_idx, settings, arr, state, mesh=None,
                   axis: str = "scen", prox_on=1.0,
                   refresh_candidates=(8, 16, 32), max_chunk: int = 256,
                   target_secs: float | None = None, margin: float = 0.5,
                   budget_s: float = 120.0, cache: bool = True,
                   precision_candidates=None, certify_factor: float = 1.5):
    """Measure-and-pick (chunk, refresh_every[, sweep precision]) for
    these shapes.

    Returns a :class:`TuneResult` (with the probe-advanced ``state``), or
    ``None`` when no candidate fits even a one-block probe under the
    static worst-case cap (segmentation regime — use the step pair).

    ``target_secs``: per-dispatch wall budget (defaults to the segmented
    dispatch target, itself 2x under the worker watchdog); the picked
    chunk keeps a measured dispatch at ``margin * target_secs``.
    ``budget_s`` bounds total tuning wall-clock — candidates that don't
    fit the remaining budget fall back to their probe measurement.

    ``precision_candidates`` (e.g. ``("default", "high")``): after the
    cadence pick, probe each lowered frozen-sweep precision mode at the
    picked cadence and CERTIFY it — its probe's final worst residual must
    stay within ``certify_factor`` x the full-precision reference probe's
    (floored at eps).  The fastest certified mode wins
    (:attr:`TuneResult.precision`); state advances only along certified
    iterates (uncertified probes run donate-free from a kept state and
    are discarded).  None/empty skips the stage entirely.  Cost note: the
    stage compiles one fresh donate-free program per probed mode PLUS a
    full-precision reference (the budget gates model run time, not
    compiles — the persistent XLA cache amortizes those across runs);
    shapes with minutes-long compiles should pin the mode instead.

    The cache (keyed on shapes + settings + mesh width + the tuning
    parameters, budget included) makes repeat calls free but returns the
    CALLER's state untouched — probe iterations only advance the state on
    a cache miss.
    """
    if target_secs is None:
        # honor the same override slot the static cap and probes obey
        # (sharded._DISPATCH_TARGET_SECS, None = the segmented default): a
        # stricter worker watchdog must also shrink the MEASURED chunk
        target_secs = (sharded._DISPATCH_TARGET_SECS
                       if sharded._DISPATCH_TARGET_SECS is not None
                       else segmented_solvers._DISPATCH_TARGET_SECS)
    key = _tune_key(arr, settings, mesh, axis, prox_on, refresh_candidates,
                    max_chunk, target_secs, margin, precision_candidates,
                    certify_factor)
    if cache and key in _cache:
        hit = _cache[key]
        return dataclasses.replace(hit, state=state, out=None)
    if cache:
        # persistent verdicts (TPUSPPY_TUNE_CACHE / resumed checkpoints):
        # a banked same-key pick skips the whole warmup probe ladder
        dk = _persist_get("fused", repr(key))
        if dk is not None:
            _metrics.inc("tune.disk_hits")
            # pre-warm THIS verdict's banked executables, synchronously:
            # a background load here would race the caller's imminent
            # plain-jit compiles, which is exactly the deserialize-vs-
            # compile crash aot._xla_work_lock documents (the lock only
            # covers aot's own work).  The list is a handful of keys and
            # each load is ~ms against the compile it replaces.
            ak = _persist_get("aot", repr(key))
            if ak and ak.get("keys"):
                _aot.prewarm(ak["keys"][:_aot.PREWARM_MAX_FILES])
            res = TuneResult(
                chunk=int(dk["chunk"]), refresh_every=int(dk["refresh_every"]),
                iters_per_sec=float(dk["iters_per_sec"]),
                secs_per_iter=float(dk["secs_per_iter"]),
                sweeps_per_iter=float(dk["sweeps_per_iter"]),
                table=list(dk.get("table", [])) + [{"from": "disk_cache"}],
                state=state, out=None,
                precision=str(dk.get("precision", "highest")))
            _cache[key] = dataclasses.replace(res, state=None, out=None)
            return res

    t_start = time.time()
    aot_mark = _aot.session_mark()
    table = []
    best = None
    out = None
    for r in refresh_candidates:
        r = int(r)
        if r > max_chunk:
            # max_chunk is the caller's per-dispatch bound; even the
            # one-block probe of this candidate would exceed it
            table.append({"refresh_every": r, "skipped": "max_chunk"})
            _probe_event("cadence", table[-1])
            continue
        cap = sharded.fused_iteration_cap(arr, settings, mesh, r)
        if cap < r:
            table.append({"refresh_every": r, "skipped": "static cap"})
            _probe_event("cadence", table[-1])
            continue
        fused_probe = sharded.make_ph_fused_step(
            nonant_idx, settings, mesh, axis, chunk=r, refresh_every=r,
            collect="trace")
        state, trace = fused_probe(state, arr, prox_on)   # compile + run
        iters_tr = _fetch(trace.iters)
        t0 = time.time()
        state, trace = fused_probe(state, arr, prox_on)
        iters_tr = _fetch(trace.iters)
        dt = time.time() - t0
        out = trace
        spi = dt / r
        sweeps = float(iters_tr.mean())
        # measured watchdog-safe chunk: margin * target over the measured
        # per-iteration cost, whole refresh blocks only
        c = int(margin * target_secs / max(spi, 1e-9)) // r * r
        c = max(r, min(c, max_chunk))
        entry = {"refresh_every": r, "probe_chunk": r,
                 "probe_secs_per_iter": round(spi, 6),
                 "sweeps_per_iter": round(sweeps, 1), "chunk": c}
        rate = 1.0 / spi
        remaining = budget_s - (time.time() - t_start)
        if c > r and c * spi * 2.5 < remaining:
            # confirm at the picked chunk (compile + one timed dispatch):
            # the dispatch amortization is the whole point, so rank on it
            fused_c = sharded.make_ph_fused_step(
                nonant_idx, settings, mesh, axis, chunk=c, refresh_every=r,
                collect="trace")
            state, trace = fused_c(state, arr, prox_on)
            _fetch(trace.conv)
            t0 = time.time()
            state, trace = fused_c(state, arr, prox_on)
            iters_tr = _fetch(trace.iters)
            dt = time.time() - t0
            out = trace
            rate = c / dt
            sweeps = float(iters_tr.mean())
            entry["confirmed_iters_per_sec"] = round(rate, 4)
            entry["sweeps_per_iter"] = round(sweeps, 1)
        entry["iters_per_sec"] = round(rate, 4)
        table.append(entry)
        _probe_event("cadence", entry)
        if best is None or rate > best[0]:
            best = (rate, c, r, sweeps)
        if time.time() - t_start > budget_s:
            break
    if best is None:
        return None
    rate, c, r, sweeps = best

    # ---- precision stage: fastest mode whose residuals certify ----------
    precision = settings.sweep_precision or "highest"
    # each probe costs ~2 dispatches (compile + timed) of c iterations at
    # the measured rate; skip the whole stage — reference probe included —
    # when the cadence stage already spent the budget.  The skip is
    # RECORDED: the returned precision is then just the caller's setting,
    # not a certified pick (bench treats a pin the same way)
    est_probe = 2.5 * c / max(rate, 1e-9)
    stage_fits = budget_s - (time.time() - t_start) > 2 * est_probe
    if precision_candidates and not stage_fits:
        table.append({"precision_stage": "skipped", "reason": "budget"})
    if precision_candidates and stage_fits:
        eps_floor = max(settings.eps_abs, settings.eps_rel)

        def _probe_mode(st_m):
            """(rate, worst_final_residual, sweeps, state, trace) of one
            timed dispatch at the picked cadence; donate=False so every
            probe starts from the same kept ``state``."""
            fused_m = sharded.make_ph_fused_step(
                nonant_idx, st_m, mesh, axis, chunk=c, refresh_every=r,
                collect="trace", donate=False)
            fused_m(state, arr, prox_on)           # compile
            t0 = time.time()
            st_out, tr = fused_m(state, arr, prox_on)
            pri = _fetch(tr.pri_res)
            dt = time.time() - t0
            dua = _fetch(tr.dua_res)
            worst = float(max(pri[-1].max(), dua[-1].max()))
            return (c / dt, worst, float(_fetch(tr.iters).mean()),
                    st_out, tr)

        # the certification reference is ALWAYS full precision, whatever
        # mode the caller's settings carry (the documented contract —
        # certifying a lowered mode against another lowered floor would
        # be vacuous)
        st_ref = dataclasses.replace(settings, sweep_precision=None)
        ref_rate, ref_worst, ref_sweeps, ref_state, ref_tr = _probe_mode(
            st_ref)
        bar = certify_factor * max(ref_worst, eps_floor)
        table.append({"precision": "highest", "iters_per_sec":
                      round(ref_rate, 4), "worst_residual": ref_worst,
                      "reference": True})
        # a caller whose settings ALREADY carry a lowered mode gets that
        # mode certified like any candidate (the cadence stage measured
        # with it, so it must earn its place or be replaced)
        caller_mode = settings.sweep_precision or "highest"
        cands = [m for m in precision_candidates if m != "highest"]
        if caller_mode != "highest" and caller_mode not in cands:
            cands.insert(0, caller_mode)
        # reference pick keeps the cadence stage's donated measurements
        # (rate/sweeps/state/out stay untouched unless a lowered mode
        # wins); candidates race the reference under IDENTICAL probe
        # conditions (donate=False), so the comparison is apples-to-apples
        precision = "highest"
        pick = None
        best_rate = ref_rate
        for mode in cands:
            remaining = budget_s - (time.time() - t_start)
            if est_probe > remaining:
                table.append({"precision": mode, "skipped": "budget"})
                continue
            st_m = dataclasses.replace(settings, sweep_precision=mode)
            rate_m, worst_m, sweeps_m, st_out, tr_m = _probe_mode(st_m)
            ok = np.isfinite(worst_m) and worst_m <= bar
            table.append({"precision": mode,
                          "iters_per_sec": round(rate_m, 4),
                          "worst_residual": worst_m, "certified": bool(ok)})
            _probe_event("precision", table[-1])
            _metrics.inc("tune.precision_certified" if ok
                         else "tune.precision_rejected")
            if ok and rate_m > best_rate:
                best_rate = rate_m
                pick = (rate_m, mode, sweeps_m, st_out, tr_m)
        if pick is not None:
            rate, precision, sweeps, state, out = pick
        elif caller_mode != "highest":
            # no lowered mode certified, but the cadence stage measured at
            # the caller's (now-rejected) mode — report the full-precision
            # probe's figures so the returned rate matches the returned
            # precision
            rate, sweeps, state, out = (ref_rate, ref_sweeps, ref_state,
                                        ref_tr)

    last = None if out is None else sharded.PHStepOut(
        *(a[-1] for a in out))
    res = TuneResult(chunk=c, refresh_every=r, iters_per_sec=rate,
                     secs_per_iter=1.0 / rate, sweeps_per_iter=sweeps,
                     table=table, state=state, out=last,
                     precision=precision)
    if cache:
        _cache[key] = dataclasses.replace(res, state=None, out=None)
        _persist_put("fused", repr(key), {
            "chunk": int(c), "refresh_every": int(r),
            "iters_per_sec": float(rate), "secs_per_iter": float(1.0 / rate),
            "sweeps_per_iter": float(sweeps), "precision": str(precision),
            "table": _json_safe(table)})
        # bank the AOT executable-cache keys the probe programs resolved
        # under (the "aot" persist kind): a future run's disk hit on this
        # verdict prewarms exactly those executables before iter0
        aot_keys = _aot.session_keys_since(aot_mark)
        if aot_keys:
            _persist_put("aot", repr(key), {"keys": aot_keys})
    return res


def _json_safe(obj):
    """Probe tables carry numpy scalars; the persistent store is JSON."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (np.floating, np.integer)):
        return obj.item()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj if obj == obj else None     # NaN -> null (strict JSON)
    return repr(obj)


@dataclasses.dataclass
class PipelineTune:
    enabled: bool              # speculation pays for this shape
    seg_secs: float            # measured wall of one frozen re-dispatch
    fetch_secs: float          # measured stop-stats RPC round-trip
    waste_flops: float         # model flops of one discarded segment
    sol: Any                   # the probe segment's solution (real work —
    # callers may keep it as their next warm state)


_pipe_cache: dict = {}


def autotune_pipeline(run_segment, sol, shape, seg_f, pay_factor=1.0,
                      reps=3, cache=True, sparse_factor=1.0):
    """Measure whether the speculative frozen continuation pays for a
    shape, and record the verdict in the segmented dispatch policy.

    The pipelined continuation (``segmented.continue_frozen``) hides one
    stop-stats fetch RPC behind each segment's device compute, at a
    worst-case cost of one discarded segment per solve.  Two measurements
    decide whether that trade wins:

    - ``fetch_secs``: the stop-stats round-trip on an ALREADY-computed
      solution — pure host<->device latency, the thing speculation hides;
    - ``seg_secs``: one frozen re-dispatch (``run_segment(sol.raw)``)
      end to end — the speculative unit of work, and the worst-case waste.

    Speculation pays when a segment costs at least ``pay_factor`` x the
    RPC: the latency hidden per segment then rivals or exceeds the
    bounded waste.  Tiny shapes whose segment is CHEAPER than the RPC
    (farmer-sized batches on a remote tunnel) gain nothing — the fetch
    dominates wall time with or without overlap — and are disabled via
    :func:`tpusppy.solvers.segmented.set_pipeline_policy`, which
    ``solve_frozen_segmented`` / ``solve_factored_segmented`` and the
    sharded step pair consult per shape.

    ``shape`` is (S, n, m) in the DISPATCH-model convention of
    :func:`segmented.dispatch_segments`: the PER-DEVICE scenario count on
    a mesh (what one segment actually sweeps — and the key the sharded
    step pair queries), the global S on the single-device host path.
    The probe segments (a compile-absorbing warmup plus the timed
    dispatch) are REAL work — the returned ``sol`` advanced by two
    segments; keep it as the next warm state.  Cached per (shape, seg_f,
    pay_factor); repeat calls are free, re-record the verdict, and do
    not re-advance the solution.  This is an opt-in measurement utility for drivers and
    benches on the remote-tunnel posture — nothing calls it implicitly;
    unmeasured shapes default to speculating (waste bounded + billed).
    """
    from .solvers import admm, hostsync
    from .solvers import flops as flops_model
    from .solvers import segmented

    S, n, m = (int(v) for v in shape)
    key = (S, n, m, int(seg_f), float(pay_factor))
    if cache and key in _pipe_cache:
        hit = _pipe_cache[key]
        # re-apply the verdict: the policy dict in `segmented` is a
        # separate store and may have been cleared/reset since it was
        # recorded — a cached verdict that is not re-recorded would
        # silently fall back to the default
        segmented.set_pipeline_policy(S, n, m, hit.enabled)
        return dataclasses.replace(hit, sol=sol)
    if cache:
        dk = _persist_get("pipeline", repr(key))
        if dk is not None:
            _metrics.inc("tune.disk_hits")
            hit = PipelineTune(
                enabled=bool(dk["enabled"]), seg_secs=float(dk["seg_secs"]),
                fetch_secs=float(dk["fetch_secs"]),
                waste_flops=float(dk["waste_flops"]), sol=None)
            _pipe_cache[key] = hit
            segmented.set_pipeline_policy(S, n, m, hit.enabled)
            return dataclasses.replace(hit, sol=sol)

    # fetch latency: dispatch + host read of a FRESH stop-stats program
    # per rep — re-fetching one array would time jax's cached host value
    # (ArrayImpl memoizes its numpy value after the first transfer), not
    # the RPC.  The stats compute is a handful of reductions, negligible
    # against the round-trip this exists to measure; the first (warmup)
    # call absorbs the compile.
    hostsync.fetch(admm.stop_stats(sol))
    t0 = time.time()
    for _ in range(max(1, reps)):
        hostsync.fetch(admm.stop_stats(sol))
    fetch_secs = (time.time() - t0) / max(1, reps)

    # frozen re-dispatch cost: a compile-absorbing WARMUP segment first
    # (the frozen program is a different executable from whatever
    # produced ``sol``, and 0.1-10 s of one-time XLA compile inside the
    # timed window would bias every verdict toward "enabled" — the same
    # reason autotune_fused warms its probes), then one timed dispatch,
    # fetch-fenced end to end (includes its own stats fetch — exactly
    # what a serial continuation step costs).  Both segments are real
    # work: the returned sol advanced by two.
    probe = run_segment(sol.raw)
    hostsync.fetch(admm.stop_stats(probe))
    t0 = time.time()
    probe = run_segment(probe.raw)
    hostsync.fetch(admm.stop_stats(probe))
    seg_secs = time.time() - t0

    # the verdict weighs the segment's COMPUTE cost (what a discarded
    # speculative segment wastes) against the RPC it hides: seg_secs
    # includes its own fence fetch, so comparing it raw would be >=
    # fetch_secs by construction and the tiny-shape disable could never
    # fire at the default pay_factor
    compute_secs = max(0.0, seg_secs - fetch_secs)
    enabled = compute_secs >= pay_factor * fetch_secs
    segmented.set_pipeline_policy(S, n, m, enabled)
    _probe_event("pipeline", {"S": S, "n": n, "m": m, "enabled": enabled,
                              "seg_secs": seg_secs,
                              "fetch_secs": fetch_secs})
    res = PipelineTune(
        enabled=enabled, seg_secs=seg_secs, fetch_secs=fetch_secs,
        waste_flops=flops_model.speculation_flops(
            S, n, m, seg_f, sparse_factor=sparse_factor),
        sol=probe)
    if cache:
        _pipe_cache[key] = dataclasses.replace(res, sol=None)
        _persist_put("pipeline", repr(key), {
            "enabled": bool(enabled), "seg_secs": float(seg_secs),
            "fetch_secs": float(fetch_secs),
            "waste_flops": float(res.waste_flops)})
    return res


# ---------------------------------------------------------------------------
# Megastep stage: pick the wheel-megakernel width N per shape from MEASURED
# dispatch overhead (ROADMAP item 4's "use obs dispatch-overhead data to
# pick N").  Verdicts persist under the "megastep" kind, keyed like the
# cadence/precision/pipeline verdicts, and the PH hub's auto path
# (PHBase._megastep_request) consults them via :func:`megastep_verdict`.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class MegastepTune:
    n: int                    # picked megastep width (iterations/dispatch)
    per_iter_secs: float      # marginal device cost per fused iteration
    overhead_secs: float      # dispatch + packed-fetch overhead per window
    overhead_pct_at_n: float  # modeled dispatch_overhead_pct at the pick


_mega_cache: dict = {}


def _mega_key(shape, settings=None):
    """Megastep verdict key: the :func:`tpusppy.solvers.aot.
    shape_family_parts` family identity per shape — ``shape`` is one
    (S, n, m) triple or, for a bucketed family, a tuple of per-bucket
    triples.  S (per bucket) and the settings ride the key, so the
    ladder's shared ``TPUSPPY_TUNE_CACHE`` can never serve an S=1000
    verdict to an S=10000 run (the family_parts drift guard in
    tests/test_tune.py pins the structure against aot's)."""
    if shape and isinstance(shape[0], (tuple, list, np.ndarray)):
        return tuple(_aot.shape_family_parts(s, n, m, settings)
                     for s, n, m in shape)
    S, n, m = shape
    return _aot.shape_family_parts(S, n, m, settings)


def _mega_disk_lookup(key):
    """Rehydrate a banked megastep verdict from the persistent store into
    ``_mega_cache`` (None when the store holds none for ``key``)."""
    dk = _persist_get("megastep", repr(key))
    if dk is None:
        return None
    _metrics.inc("tune.disk_hits")
    res = MegastepTune(
        n=int(dk["n"]), per_iter_secs=float(dk["per_iter_secs"]),
        overhead_secs=float(dk["overhead_secs"]),
        overhead_pct_at_n=float(dk["overhead_pct_at_n"]))
    _mega_cache[key] = res
    return res


def megastep_verdict(S, n=None, m=None, settings=None) -> int | None:
    """Banked autotuned megastep width for a shape (None = no verdict —
    the hub then falls back to the refresh-window default).  ``S`` may be
    the full shape key — one (S, n, m) triple or a tuple of per-bucket
    triples — with ``n``/``m`` omitted."""
    shape = (S, n, m) if n is not None else S
    key = _mega_key(shape, settings)
    hit = _mega_cache.get(key) or _mega_disk_lookup(key)
    return hit.n if hit is not None else None


def autotune_megastep(run_window, shape, n_cap, target_pct: float = 1.0,
                      n_probe: int | None = None, cache: bool = True,
                      settings=None):
    """Measure the per-window dispatch+fetch overhead of the wheel
    megakernel and pick the smallest N that amortizes it below
    ``target_pct`` percent of the window wall (the farmer-m1
    ``dispatch_overhead_pct < 1%`` target), clamped to ``n_cap`` (the
    watchdog cap — :func:`segmented.megastep_cap` — and/or the refresh
    window).

    ``run_window(n)`` executes ONE megastep window of up to ``n`` wheel
    iterations end to end (dispatch + packed measurement fetch) and
    returns the executed iteration count.  Probe windows are REAL wheel
    iterations — callers apply each window's measurement normally, so
    warmup work is never wasted (the autotune_fused posture).  Three
    windows run: a compile-absorbing n=1 warmup, a timed n=1 window (the
    overhead + one iteration), and a timed ``n_probe`` window (the
    marginal per-iteration cost).  The verdict is banked under the
    "megastep" persist kind, so repeated runs (and resumed wheels) skip
    the probes.
    """
    key = _mega_key(shape, settings)
    if cache:
        hit = _mega_cache.get(key) or _mega_disk_lookup(key)
        if hit is not None:
            return hit

    n_cap = max(1, int(n_cap))
    if n_probe is None:
        n_probe = max(2, min(n_cap, 8))
    n_probe = max(2, min(int(n_probe), max(2, n_cap)))
    run_window(1)                       # compile-absorbing warmup window
    t0 = time.time()
    run_window(1)
    t1 = time.time() - t0               # overhead + 1 iteration
    t0 = time.time()
    ex = int(run_window(n_probe))
    tN = time.time() - t0               # overhead + ex iterations
    if ex <= 1:
        # degenerate probe (the window converged, or its first iterate
        # failed the in-scan acceptance test): (tN - t1) measures noise,
        # and a verdict derived from it would permanently steer this
        # shape via the persistent store — return the conservative
        # "don't megastep" answer WITHOUT banking, so the next run
        # re-probes under normal conditions
        _probe_event("megastep", {"shape": repr(shape),
                                  "skipped": "degenerate probe",
                                  "executed": ex})
        return MegastepTune(n=1, per_iter_secs=max(tN, 1e-9),
                            overhead_secs=max(t1, 0.0),
                            overhead_pct_at_n=100.0)
    per_iter = max((tN - t1) / max(ex - 1, 1), 1e-9)
    overhead = max(t1 - per_iter, 0.0)
    f = max(target_pct, 1e-3) / 100.0
    # overhead_pct(N) = o / (o + N*per_iter) <= f  =>  N >= o(1-f)/(f*p)
    n_pick = int(np.ceil(overhead * (1.0 - f) / (f * per_iter)))
    n_pick = max(1, min(n_pick, n_cap))
    pct = 100.0 * overhead / (overhead + n_pick * per_iter)
    res = MegastepTune(n=n_pick, per_iter_secs=per_iter,
                       overhead_secs=overhead, overhead_pct_at_n=pct)
    _probe_event("megastep", {"shape": repr(shape), "pick": n_pick,
                              "per_iter_secs": per_iter,
                              "overhead_secs": overhead,
                              "overhead_pct_at_n": pct})
    if cache:
        _mega_cache[key] = res
        _persist_put("megastep", repr(key), {
            "n": int(n_pick), "per_iter_secs": float(per_iter),
            "overhead_secs": float(overhead),
            "overhead_pct_at_n": float(pct)})
    return res


# ---------------------------------------------------------------------------
# Bound-cadence stage (in-wheel certification, doc/pipeline.md): pick how
# often a self-certifying megastep window runs its fused bound pass from
# the MEASURED marginal bound-pass cost vs the window wall.  Fresh bounds
# every window close the certified gap soonest; when the pass costs a
# meaningful fraction of the window (the xhat frozen evaluation is about
# one extra frozen iteration), spacing it every k windows trades bound
# staleness (at most k-1 windows of gap-closing lag) for wheel
# throughput.  Verdicts persist under the "bound_cadence" kind on the
# same shape+settings key family as the megastep stage.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class BoundCadenceTune:
    every: int                # bound pass every k-th megastep window
    bound_secs: float         # marginal cost of one fused bound pass
    window_secs: float        # wall of one bound-less megastep window
    overhead_pct_at_pick: float


_bound_cadence_cache: dict = {}


def _bound_cadence_disk_lookup(key):
    dk = _persist_get("bound_cadence", repr(key))
    if dk is None:
        return None
    _metrics.inc("tune.disk_hits")
    res = BoundCadenceTune(
        every=int(dk["every"]), bound_secs=float(dk["bound_secs"]),
        window_secs=float(dk["window_secs"]),
        overhead_pct_at_pick=float(dk["overhead_pct_at_pick"]))
    _bound_cadence_cache[key] = res
    return res


def bound_cadence_verdict(shape, settings=None) -> int | None:
    """Banked bound-pass cadence for a shape (None = no verdict — the
    hub then runs the pass every window).  ``shape`` is one (S, n, m)
    triple or the bucketed tuple-of-triples, like
    :func:`megastep_verdict`."""
    key = _mega_key(shape, settings)
    hit = _bound_cadence_cache.get(key) or _bound_cadence_disk_lookup(key)
    return hit.every if hit is not None else None


def autotune_bound_cadence(run_window, shape, settings=None,
                           target_pct: float = 10.0, every_cap: int = 8,
                           cache: bool = True):
    """Measure the marginal cost of the in-wheel bound pass and pick the
    smallest cadence k keeping it under ``target_pct`` percent of the
    wheel wall (bound_secs / (k*window_secs + bound_secs) <= f).

    ``run_window(bound_live)`` executes ONE real megastep window end to
    end (dispatch + packed fetch, measurement applied normally — warmup
    work is never wasted, the autotune_megastep posture) and returns the
    executed iteration count.  Three windows run: a compile-absorbing
    bound-pass warmup, a timed bound-pass window, a timed plain window.
    k=1 (every window) wins whenever the pass is cheap — the common case,
    since the frozen evaluation re-enters the window's still-hot factors.
    Degenerate probes (a converged or rejected window) return the
    conservative every-window answer WITHOUT banking.
    """
    key = _mega_key(shape, settings)
    if cache:
        hit = (_bound_cadence_cache.get(key)
               or _bound_cadence_disk_lookup(key))
        if hit is not None:
            return hit
    run_window(True)                    # compile-absorbing warmup
    t0 = time.time()
    ex_b = int(run_window(True))
    t_bound = time.time() - t0
    t0 = time.time()
    ex_p = int(run_window(False))
    t_plain = time.time() - t0
    if ex_b < 1 or ex_p < 1:
        _probe_event("bound_cadence", {"shape": repr(shape),
                                       "skipped": "degenerate probe",
                                       "executed": (ex_b, ex_p)})
        return BoundCadenceTune(every=1, bound_secs=max(t_bound, 0.0),
                                window_secs=max(t_plain, 1e-9),
                                overhead_pct_at_pick=100.0)
    # normalize to per-iteration so unequal executed counts don't skew
    # the marginal-cost estimate: t_bound = ex_b*c + B with c =
    # t_plain/ex_p, so B = ex_b * (t_bound/ex_b - t_plain/ex_p) — the
    # multiplier is the BOUND window's executed count (the pass ran once
    # in THAT window), not the plain window's
    bound_secs = max(t_bound / ex_b - t_plain / ex_p, 0.0) * ex_b
    window_secs = max(t_plain, 1e-9)
    f = max(target_pct, 1e-3) / 100.0
    k = int(np.ceil(bound_secs * (1.0 - f) / (f * window_secs))) \
        if bound_secs > 0 else 1
    k = max(1, min(k, max(1, int(every_cap))))
    pct = 100.0 * bound_secs / (bound_secs + k * window_secs)
    res = BoundCadenceTune(every=k, bound_secs=bound_secs,
                           window_secs=window_secs,
                           overhead_pct_at_pick=pct)
    _probe_event("bound_cadence", {"shape": repr(shape), "pick": k,
                                   "bound_secs": bound_secs,
                                   "window_secs": window_secs,
                                   "overhead_pct_at_pick": pct})
    if cache:
        _bound_cadence_cache[key] = res
        _persist_put("bound_cadence", repr(key), {
            "every": int(k), "bound_secs": float(bound_secs),
            "window_secs": float(window_secs),
            "overhead_pct_at_pick": float(pct)})
    return res


# ---------------------------------------------------------------------------
# Integer stage (batched integer wheel, doc/integer.md): pick the rounding
# sweep width K (how many ladder thresholds the integer bound pass
# evaluates on device — the SLAM slams always ride) and the pass cadence
# from the MEASURED marginal sweep cost vs the plain window wall.  A wide
# ladder finds integer-feasible incumbents sooner (best-of-C); each extra
# candidate costs one more vmapped frozen evaluation per pass.  Verdicts
# persist under the "integer" kind on the same shape+settings key family
# as the megastep/bound-cadence stages.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class IntegerTune:
    k: int                    # picked ladder width (thresholds evaluated)
    every: int                # integer pass every k-th megastep window
    sweep_secs: float         # marginal cost of one full integer pass
    window_secs: float        # wall of one bound-less megastep window


_integer_cache: dict = {}


def _integer_disk_lookup(key):
    dk = _persist_get("integer", repr(key))
    if dk is None:
        return None
    _metrics.inc("tune.disk_hits")
    res = IntegerTune(
        k=int(dk["k"]), every=int(dk["every"]),
        sweep_secs=float(dk["sweep_secs"]),
        window_secs=float(dk["window_secs"]))
    _integer_cache[key] = res
    return res


def integer_verdict(shape, settings=None) -> IntegerTune | None:
    """Banked integer-sweep verdict for a shape (None = no verdict — the
    hub then runs the default ladder every bound window).  ``shape`` is
    one (S, n, m) triple or the bucketed tuple-of-triples, like
    :func:`megastep_verdict`."""
    key = _mega_key(shape, settings)
    return _integer_cache.get(key) or _integer_disk_lookup(key)


def autotune_integer(run_window, shape, settings=None, k_full: int = 3,
                     target_pct: float = 15.0, every_cap: int = 8,
                     cache: bool = True):
    """Measure the marginal cost of the batched integer bound pass and
    pick (K, cadence) keeping it under ``target_pct`` percent of the
    wheel wall.

    ``run_window(int_live)`` executes ONE real megastep window end to end
    (dispatch + packed fetch, measurement applied normally — warmup work
    is never wasted, the autotune_megastep posture) with the integer
    bound pass on (True) or off (False), returning the executed
    iteration count.  Three windows run: a compile-absorbing integer
    warmup, a timed integer window, a timed plain window.  The sweep
    cost scales ~linearly in the candidate count (C = K + 2 slams), so
    K shrinks first (never below 1 — the nearest-rounding candidate
    always rides) and the cadence stretches only when K=1 still misses
    the target.  Degenerate probes (a converged or rejected window)
    return the conservative full-ladder answer WITHOUT banking.
    """
    key = _mega_key(shape, settings)
    if cache:
        hit = _integer_cache.get(key) or _integer_disk_lookup(key)
        if hit is not None:
            return hit
    k_full = max(1, int(k_full))
    run_window(True)                    # compile-absorbing warmup
    t0 = time.time()
    ex_i = int(run_window(True))
    t_int = time.time() - t0
    t0 = time.time()
    ex_p = int(run_window(False))
    t_plain = time.time() - t0
    if ex_i < 1 or ex_p < 1:
        _probe_event("integer", {"shape": repr(shape),
                                 "skipped": "degenerate probe",
                                 "executed": (ex_i, ex_p)})
        return IntegerTune(k=k_full, every=1,
                           sweep_secs=max(t_int, 0.0),
                           window_secs=max(t_plain, 1e-9))
    # per-iteration normalization (the bound_cadence estimator): the
    # pass ran once in the integer window
    sweep_secs = max(t_int / ex_i - t_plain / ex_p, 0.0) * ex_i
    window_secs = max(t_plain, 1e-9)
    f = max(target_pct, 1e-3) / 100.0
    # cost model: sweep_secs covers C_full = k_full + 2 evaluations + the
    # reduced-cost re-solve; per-evaluation cost is ~sweep/(C_full + 1)
    per_eval = sweep_secs / max(k_full + 3, 1)
    k = k_full
    every = 1
    while k > 1 and (k + 3) * per_eval > f * window_secs:
        k -= 1
    if (k + 3) * per_eval > f * window_secs:
        cost = (k + 3) * per_eval
        every = int(np.ceil(cost * (1.0 - f) / (f * window_secs)))
        every = max(1, min(every, max(1, int(every_cap))))
    res = IntegerTune(k=k, every=every, sweep_secs=sweep_secs,
                      window_secs=window_secs)
    _probe_event("integer", {"shape": repr(shape), "k": k, "every": every,
                             "sweep_secs": sweep_secs,
                             "window_secs": window_secs})
    if cache:
        _integer_cache[key] = res
        _persist_put("integer", repr(key), {
            "k": int(k), "every": int(every),
            "sweep_secs": float(sweep_secs),
            "window_secs": float(window_secs)})
    return res


# ---------------------------------------------------------------------------
# Batched stage (continuous batching, doc/serving.md "Continuous
# batching"): pick the tenant-batched megastep's slot count K from the
# MEASURED per-window cost.  One fused window runs every live slot's
# frozen sweep back to back, so window wall grows ~linearly in K; the
# verdict is the largest K whose modeled window wall stays inside
# ``target_frac`` of the dispatch watchdog budget — the same budget the
# static cap (segmented.megastep_cap_multi at K copies of the shape)
# guards a priori, but measured, so a fast family batches wider than the
# worst-case flop model would dare.  Verdicts persist under the
# "batched" kind on the same shape+settings key family.
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class BatchedTune:
    k: int                    # picked slot count
    per_slot_secs: float      # marginal window cost per live slot
    base_secs: float          # window wall at one live slot
    window_secs_at_k: float   # modeled window wall at the pick


_batched_cache: dict = {}


def _batched_disk_lookup(key):
    dk = _persist_get("batched", repr(key))
    if dk is None:
        return None
    _metrics.inc("tune.disk_hits")
    res = BatchedTune(
        k=int(dk["k"]), per_slot_secs=float(dk["per_slot_secs"]),
        base_secs=float(dk["base_secs"]),
        window_secs_at_k=float(dk["window_secs_at_k"]))
    _batched_cache[key] = res
    return res


def batched_verdict(S, n=None, m=None, settings=None) -> int | None:
    """Banked autotuned slot count for a family shape (None = no verdict
    — the server then runs its configured ``batch_slots``).  ``S`` may
    be the full shape key, like :func:`megastep_verdict`."""
    shape = (S, n, m) if n is not None else S
    key = _mega_key(shape, settings)
    hit = _batched_cache.get(key) or _batched_disk_lookup(key)
    return hit.k if hit is not None else None


def autotune_batched(run_window, shape, k_cap, target_frac: float = 0.5,
                     k_probe: int | None = None, cache: bool = True,
                     settings=None, target_secs: float | None = None):
    """Measure the fused tenant window's per-slot marginal cost and pick
    the max K whose modeled window wall ``base + (K-1) * per_slot`` stays
    under ``target_frac`` of the dispatch watchdog budget, clamped to
    ``k_cap``.

    ``run_window(k)`` executes ONE fused window with ``k`` live slots
    end to end (dispatch + packed fetch) and returns the executed
    iteration count of its busiest slot.  Probe windows are REAL wheel
    work (the autotune_megastep posture — callers apply each window's
    measurements normally).  Three windows run: a compile-absorbing
    k=1 warmup, a timed k=1, and a timed ``k_probe``; a degenerate probe
    (nothing executed) returns the conservative K=1 WITHOUT banking.
    """
    from .solvers.segmented import _DISPATCH_TARGET_SECS

    key = _mega_key(shape, settings)
    if cache:
        hit = _batched_cache.get(key) or _batched_disk_lookup(key)
        if hit is not None:
            return hit

    k_cap = max(1, int(k_cap))
    if k_probe is None:
        k_probe = max(2, min(k_cap, 4))
    k_probe = max(2, min(int(k_probe), max(2, k_cap)))
    budget = (target_secs if target_secs is not None
              else max(target_frac, 1e-3) * _DISPATCH_TARGET_SECS)
    run_window(1)                       # compile-absorbing warmup window
    t0 = time.time()
    ex1 = int(run_window(1))
    t1 = time.time() - t0               # one-slot window wall
    t0 = time.time()
    exK = int(run_window(k_probe))
    tK = time.time() - t0               # k_probe-slot window wall
    if ex1 <= 0 or exK <= 0:
        _probe_event("batched", {"shape": repr(shape),
                                 "skipped": "degenerate probe",
                                 "executed": (ex1, exK)})
        return BatchedTune(k=1, per_slot_secs=max(tK, 1e-9),
                           base_secs=max(t1, 1e-9),
                           window_secs_at_k=max(t1, 1e-9))
    per_slot = max((tK - t1) / max(k_probe - 1, 1), 1e-9)
    base = max(t1, 1e-9)
    k = int((budget - base) // per_slot) + 1 if budget > base else 1
    k = max(1, min(k, k_cap))
    at_k = base + (k - 1) * per_slot
    res = BatchedTune(k=k, per_slot_secs=per_slot, base_secs=base,
                      window_secs_at_k=at_k)
    _probe_event("batched", {"shape": repr(shape), "pick": k,
                             "per_slot_secs": per_slot,
                             "base_secs": base,
                             "window_secs_at_k": at_k})
    if cache:
        _batched_cache[key] = res
        _persist_put("batched", repr(key), {
            "k": int(k), "per_slot_secs": float(per_slot),
            "base_secs": float(base),
            "window_secs_at_k": float(at_k)})
    return res
