"""Warmup autotuner for the fused PH dispatch cadence.

The fused multi-iteration program (:func:`tpusppy.parallel.sharded.
make_ph_fused_step`) has two knobs: ``refresh_every`` (how many PH
iterations reuse one factorization — the math/amortization trade) and
``chunk`` (how many PH iterations one device dispatch carries — the
latency/watchdog trade).  The benchmark used to hard-code ``chunk=64``/
``refresh_every=16``; shapes whose sweeps are 16x costlier (farmer
crops_mult=4 vs 1) then run chunks far below what the worker watchdog
allows and pay dispatch round-trips they don't have to, while the static
worst-case cap (:func:`~tpusppy.parallel.sharded.fused_iteration_cap`,
every frozen iteration billed at its full ``max_iter`` sweep budget) is
~5-10x more conservative than measured reality.

:func:`autotune_fused` replaces both with measurement at warmup: for each
``refresh_every`` candidate it times a one-block probe dispatch, converts
the MEASURED seconds/iteration into a watchdog-safe chunk (``margin`` x
the dispatch target budget), confirms the rate at that chunk, and picks
the fastest cadence.  Probes are real PH iterations (the state advances —
warmup work is not wasted) and each probe is itself sized inside the
static worst-case cap, so a mistuned model can never push a probe past
the watchdog.

Grew out of ``scripts/profile_sweep_parts.py`` (whose jit/fetch timing
helper lives here now as :func:`time_jitted`); results feed ``bench.py``
and any driver that wants a per-shape cadence instead of a global
default.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from .parallel import sharded
from .solvers import segmented as segmented_solvers


@dataclasses.dataclass
class TuneResult:
    chunk: int                 # picked dispatch size (PH iters per dispatch)
    refresh_every: int         # picked factorization cadence
    iters_per_sec: float       # measured at the picked (chunk, refresh)
    secs_per_iter: float
    sweeps_per_iter: float     # mean measured ADMM sweeps per PH iteration
    table: list                # per-candidate measurement dicts
    state: Any                 # PH state advanced by the probe iterations
    out: Any                   # last probe's PHStepOut


_cache: dict = {}


def _fetch(x):
    """Host fetch as the timing fence (block_until_ready returns early on
    the axon TPU plugin — see bench.py's timing note)."""
    return np.asarray(x)


def time_jitted(fn, *args, reps=20):
    """Milliseconds per call of an already-jitted ``fn`` (fetch-fenced);
    the sweep-part profiler's timing core (scripts/profile_sweep_parts)."""
    import jax
    import jax.numpy as jnp

    out = fn(*args)
    first = out[0] if isinstance(out, tuple) else out
    _fetch(jnp.sum(first) if isinstance(first, jax.Array) else first)
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    first = out[0] if isinstance(out, tuple) else out
    _fetch(jnp.sum(first) if isinstance(first, jax.Array) else first)
    return (time.time() - t0) / reps * 1e3


def _tune_key(arr, settings, mesh, axis, prox_on, refresh_candidates,
              max_chunk, target_secs, margin):
    ndev = 1 if mesh is None else len(mesh.devices.flat)
    return (arr.c.shape, arr.cl.shape, arr.A.ndim if hasattr(arr.A, "ndim")
            else "sparse", settings, ndev, axis, float(prox_on),
            tuple(refresh_candidates), max_chunk, target_secs, margin)


def autotune_fused(nonant_idx, settings, arr, state, mesh=None,
                   axis: str = "scen", prox_on=1.0,
                   refresh_candidates=(8, 16, 32), max_chunk: int = 256,
                   target_secs: float | None = None, margin: float = 0.5,
                   budget_s: float = 120.0, cache: bool = True):
    """Measure-and-pick (chunk, refresh_every) for these shapes.

    Returns a :class:`TuneResult` (with the probe-advanced ``state``), or
    ``None`` when no candidate fits even a one-block probe under the
    static worst-case cap (segmentation regime — use the step pair).

    ``target_secs``: per-dispatch wall budget (defaults to the segmented
    dispatch target, itself 2x under the worker watchdog); the picked
    chunk keeps a measured dispatch at ``margin * target_secs``.
    ``budget_s`` bounds total tuning wall-clock — candidates that don't
    fit the remaining budget fall back to their probe measurement.

    The cache (keyed on shapes + settings + mesh width + the tuning
    parameters, budget included) makes repeat calls free but returns the
    CALLER's state untouched — probe iterations only advance the state on
    a cache miss.
    """
    if target_secs is None:
        # honor the same override slot the static cap and probes obey
        # (sharded._DISPATCH_TARGET_SECS, None = the segmented default): a
        # stricter worker watchdog must also shrink the MEASURED chunk
        target_secs = (sharded._DISPATCH_TARGET_SECS
                       if sharded._DISPATCH_TARGET_SECS is not None
                       else segmented_solvers._DISPATCH_TARGET_SECS)
    key = _tune_key(arr, settings, mesh, axis, prox_on, refresh_candidates,
                    max_chunk, target_secs, margin)
    if cache and key in _cache:
        hit = _cache[key]
        return dataclasses.replace(hit, state=state, out=None)

    t_start = time.time()
    table = []
    best = None
    out = None
    for r in refresh_candidates:
        r = int(r)
        if r > max_chunk:
            # max_chunk is the caller's per-dispatch bound; even the
            # one-block probe of this candidate would exceed it
            table.append({"refresh_every": r, "skipped": "max_chunk"})
            continue
        cap = sharded.fused_iteration_cap(arr, settings, mesh, r)
        if cap < r:
            table.append({"refresh_every": r, "skipped": "static cap"})
            continue
        fused_probe = sharded.make_ph_fused_step(
            nonant_idx, settings, mesh, axis, chunk=r, refresh_every=r,
            collect="trace")
        state, trace = fused_probe(state, arr, prox_on)   # compile + run
        iters_tr = _fetch(trace.iters)
        t0 = time.time()
        state, trace = fused_probe(state, arr, prox_on)
        iters_tr = _fetch(trace.iters)
        dt = time.time() - t0
        out = trace
        spi = dt / r
        sweeps = float(iters_tr.mean())
        # measured watchdog-safe chunk: margin * target over the measured
        # per-iteration cost, whole refresh blocks only
        c = int(margin * target_secs / max(spi, 1e-9)) // r * r
        c = max(r, min(c, max_chunk))
        entry = {"refresh_every": r, "probe_chunk": r,
                 "probe_secs_per_iter": round(spi, 6),
                 "sweeps_per_iter": round(sweeps, 1), "chunk": c}
        rate = 1.0 / spi
        remaining = budget_s - (time.time() - t_start)
        if c > r and c * spi * 2.5 < remaining:
            # confirm at the picked chunk (compile + one timed dispatch):
            # the dispatch amortization is the whole point, so rank on it
            fused_c = sharded.make_ph_fused_step(
                nonant_idx, settings, mesh, axis, chunk=c, refresh_every=r,
                collect="trace")
            state, trace = fused_c(state, arr, prox_on)
            _fetch(trace.conv)
            t0 = time.time()
            state, trace = fused_c(state, arr, prox_on)
            iters_tr = _fetch(trace.iters)
            dt = time.time() - t0
            out = trace
            rate = c / dt
            sweeps = float(iters_tr.mean())
            entry["confirmed_iters_per_sec"] = round(rate, 4)
            entry["sweeps_per_iter"] = round(sweeps, 1)
        entry["iters_per_sec"] = round(rate, 4)
        table.append(entry)
        if best is None or rate > best[0]:
            best = (rate, c, r, sweeps)
        if time.time() - t_start > budget_s:
            break
    if best is None:
        return None
    rate, c, r, sweeps = best
    last = None if out is None else sharded.PHStepOut(
        *(a[-1] for a in out))
    res = TuneResult(chunk=c, refresh_every=r, iters_per_sec=rate,
                     secs_per_iter=1.0 / rate, sweeps_per_iter=sweeps,
                     table=table, state=state, out=last)
    if cache:
        _cache[key] = dataclasses.replace(res, state=None, out=None)
    return res
