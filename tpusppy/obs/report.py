"""Post-run "flight recorder" summary of a trace ring + the metrics registry.

The artifact that turns a run into evidence (the S=1000 campaign burned
three instrumented reruns discovering an invalid trivial bound and a
starved Lagrangian — both visible in this summary's bound-vs-wall
arrays): :func:`build_report` reduces the event ring to

- ``gap_vs_wall``: ``[t_rel_secs, value]`` samples of every ``rel_gap``
  counter event (the hub emits one per gap computation; the LAST entry is
  the run's final certified gap);
- ``bounds_vs_wall``: the same for ``best_outer`` / ``best_inner`` /
  ``abs_gap`` series;
- ``tracks``: per-track per-name span totals (count / total_secs) — where
  the wall went, cylinder by cylinder;
- ``instants``: per-track per-name instant counts (speculation discards,
  guard trips, terminations, ...);
- ``counters``: the full metrics-registry dump — histogram entries carry
  ``p50``/``p95``/``p99`` next to count/total/min/max (bounded-reservoir
  quantiles, :class:`tpusppy.obs.metrics.Histogram`), which is where
  serving SLO latency percentiles (``service.*``) land in per-run
  reports;
- ``dropped_events``: ring-overflow count (0 means the timeline is
  complete).

``bench.py --trace`` attaches this JSON per segment next to the Perfetto
file; :func:`tpusppy.obs.trace.flush` writes it as ``<path>.report.json``.
"""

from __future__ import annotations

#: Counter-event names collected into *-vs-wall arrays.
SERIES = ("rel_gap", "abs_gap", "best_outer", "best_inner")


def build_report(events, registry=None, counters=None,
                 dropped=None) -> dict:
    """Reduce ring events (+ the metrics registry) to the summary dict.

    ``counters`` (optional dict) overrides the registry dump — pass a
    :meth:`metrics.Window.deltas` snapshot so a per-segment report
    carries that segment's traffic, not the process lifetime's.
    ``dropped`` (optional int) pins the ring-overflow count to the
    moment ``events`` was snapshotted — the live ring may have been
    reset (or still be recording) by the time the report is built.
    """
    from . import metrics as _metrics
    from . import trace as _trace

    registry = registry if registry is not None else _metrics.REGISTRY
    t0 = min((ev.t for ev in events), default=0.0)

    series: dict = {name: [] for name in SERIES}
    tenants: dict = {}
    tracks: dict = {}
    instants: dict = {}
    for ev in events:
        if ev.kind == "span":
            per = tracks.setdefault(ev.track, {})
            agg = per.setdefault(ev.name, {"count": 0, "total_secs": 0.0})
            agg["count"] += 1
            agg["total_secs"] += ev.dur or 0.0
        elif ev.kind == "counter":
            if ev.name in series:
                payload = ev.payload or {}
                rid = payload.get("request_id")
                if rid is None:
                    # process-global series (the hub's compute_gaps
                    # counters — one solve at a time)
                    series[ev.name].append([ev.t - t0,
                                            payload.get("value")])
                else:
                    # request-scoped sample (telemetry.tenant_counter):
                    # batched-runner bounds (source 'B') and the
                    # server's per-window progress land here — without
                    # this bucket a batched run's gap_vs_wall was EMPTY
                    row = tenants.setdefault(
                        str(rid), {"trace_id": payload.get("trace_id"),
                                   **{n: [] for n in SERIES}})
                    row[ev.name].append([ev.t - t0,
                                         payload.get("value")])
        else:
            per = instants.setdefault(ev.track, {})
            per[ev.name] = per.get(ev.name, 0) + 1
    for per in tracks.values():
        for agg in per.values():
            agg["total_secs"] = round(agg["total_secs"], 6)
    return {
        "n_events": len(events),
        "dropped_events": (dropped if dropped is not None
                           else _trace.dropped()),
        "gap_vs_wall": series["rel_gap"],
        "bounds_vs_wall": {
            "best_outer": series["best_outer"],
            "best_inner": series["best_inner"],
            "abs_gap": series["abs_gap"],
        },
        # per-tenant gap/bound series keyed by request_id: {rid:
        # {"trace_id", "rel_gap": [[t, v], ...], "abs_gap": ...,
        #  "best_outer": ..., "best_inner": ...}} — each tenant's LAST
        # rel_gap entry is its final certified gap, exactly like the
        # global array for a solo run
        "tenants": tenants,
        "tracks": tracks,
        "instants": instants,
        "counters": counters if counters is not None else registry.dump(),
    }
