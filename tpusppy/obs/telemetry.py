"""Request-scoped telemetry plane: trace propagation, scrape, streaming.

The flight recorder (:mod:`.trace`) is process-local and post-hoc; a
multi-tenant serving stack (:mod:`tpusppy.service`) needs the LIVE
plane, in the shape the industry settled on:

- **Request-scoped distributed tracing** (Dapper idiom): a ``trace_id``
  minted once at :meth:`~tpusppy.service.net.SolveClient.submit`, carried
  in the wire payload, persisted in the request journal (so a recovered
  request keeps its trace across a SIGKILL) and threaded through
  admission, batch slot join/evict/bank/rejoin and every per-window
  bound event.  Each request renders as one contiguous logical track
  (``req:<request_id>``); every event's payload carries
  ``trace_id``/``request_id`` so :mod:`scripts.trace_merge` can stitch
  per-process rings into one multi-process timeline.
- **Clock alignment**: per-process rings are ``perf_counter``-relative.
  :func:`record_clock_sync` stamps a ``(wall, perf)`` pair into the ring
  (one instant on the ``clock`` track); the TCP hello/status exchange
  additionally records an NTP-style :func:`handshake_offset` between the
  client's and server's wall clocks, so ``scripts/trace_merge.py`` can
  place every file on one absolute timeline — including multi-controller
  ``dist_wheel`` meshes.
- **Prometheus text exposition** (:func:`prometheus_text`): the
  always-on metrics registry plus per-tenant gauges rendered in the
  text exposition format, served zero-dependency by
  :class:`ScrapeServer` (stdlib ``http.server``) on the TCP frontend.
- **Progress streaming** (:class:`ProgressBus`): bounded per-request
  event queues the scheduler feeds per window (gap point, bound updates
  with source char, join/evict/deadline verdicts) and the frontend
  drains into ``SolveClient.watch`` long-poll batches.

Everything here preserves the obs contract: the trace-ring paths gate on
:func:`trace.enabled` first (the <5µs disabled-span pin in
tests/test_obs.py holds with a request context in place), the bus and
the scrape surface are always-on but touched only at window boundaries.
"""

from __future__ import annotations

import collections
import contextlib
import json
import math
import os
import threading
import time
import uuid

from . import metrics as _metrics
from . import trace as _trace

__all__ = [
    "mint_trace_id", "req_track", "request_scope", "current_context",
    "tenant_instant", "tenant_counter", "tenant_span",
    "clock_stamp", "record_clock_sync", "handshake_offset",
    "record_clock_handshake", "ProgressBus", "prometheus_text",
    "tenant_gauge_lines", "ScrapeServer", "json_safe",
]


# ---------------------------------------------------------------------------
# Request context
# ---------------------------------------------------------------------------
def mint_trace_id() -> str:
    """A fresh trace id — minted ONCE per request at the outermost edge
    (the client's submit; the server mints only when a request arrives
    without one, e.g. in-process submits)."""
    return f"tr-{uuid.uuid4().hex[:16]}"


def req_track(request_id) -> str:
    """The logical trace track one request's events render on — one
    contiguous row per request in the merged timeline."""
    return f"req:{request_id}"


_tls = threading.local()


def push_context(trace_id, request_id):
    stack = getattr(_tls, "req_stack", None)
    if stack is None:
        stack = _tls.req_stack = []
    stack.append((str(trace_id or ""), str(request_id or "")))


def pop_context():
    stack = getattr(_tls, "req_stack", None)
    if stack:
        stack.pop()


def current_context():
    """(trace_id, request_id) of the innermost active request scope on
    this thread, or None.  Kept to a bare TLS list read so the disabled
    trace fast path stays under its 5µs/span pin with scopes active."""
    stack = getattr(_tls, "req_stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def request_scope(trace_id, request_id):
    """Bind the calling thread to one request: :func:`tenant_instant` /
    :func:`tenant_counter` / :func:`tenant_span` called with
    ``request_id=None`` inside the scope resolve to this request."""
    push_context(trace_id, request_id)
    try:
        yield
    finally:
        pop_context()


def _resolve(request_id, trace_id):
    if request_id is None:
        ctx = current_context()
        if ctx is None:
            return None, None
        return ctx[1], ctx[0]
    return str(request_id), str(trace_id or "")


def tenant_instant(request_id, trace_id, name, **payload):
    """Point event on the request's own track, tagged with its trace id
    (the merge key).  No-op (nothing allocated) while tracing is off."""
    if not _trace.enabled():
        return
    rid, tid = _resolve(request_id, trace_id)
    if rid is None:
        return
    _trace.instant(req_track(rid), name,
                   request_id=rid, trace_id=tid, **payload)


def tenant_counter(request_id, trace_id, name, value, **payload):
    """Numeric series sample on the request's track.  The payload
    carries ``request_id`` so :func:`report.build_report` buckets the
    sample into that tenant's gap/bound series (the batched runner's
    source-'B' bounds land here — the hub-only collection missed them).
    """
    if not _trace.enabled():
        return
    rid, tid = _resolve(request_id, trace_id)
    if rid is None:
        return
    _trace.counter(req_track(rid), name, value,
                   request_id=rid, trace_id=tid, **payload)


def tenant_span(request_id, trace_id, name, **payload):
    """Span on the request's track (context-manager).  Disabled: the
    shared no-op singleton, same as :func:`trace.span`."""
    if not _trace.enabled():
        return _trace._NULL
    rid, tid = _resolve(request_id, trace_id)
    if rid is None:
        return _trace._NULL
    return _trace.span(req_track(rid), name,
                       request_id=rid, trace_id=tid, **payload)


# ---------------------------------------------------------------------------
# Clock alignment (trace_merge's input)
# ---------------------------------------------------------------------------
def clock_stamp() -> dict:
    """A ``(wall, perf)`` timestamp pair sampled back to back — the unit
    of clock alignment: ``wall - perf`` maps this process's
    perf_counter-relative ring onto the wall clock."""
    return {"wall": time.time(), "perf": time.perf_counter()}


def record_clock_sync(role: str, **extra):
    """Stamp this process's ring with a ``clock_sync`` instant (track
    ``clock``) carrying the pair :func:`clock_stamp` plus the process
    id.  ``scripts/trace_merge.py`` reads the FIRST such instant per
    file to place the file on the absolute wall timeline."""
    if not _trace.enabled():
        return
    st = clock_stamp()
    _trace.instant("clock", "clock_sync", role=str(role),
                   wall=st["wall"], perf=st["perf"], pid=os.getpid(),
                   **extra)


def handshake_offset(send_wall: float, recv_wall: float,
                     server_wall: float) -> float:
    """NTP-style wall-clock offset estimate from one request/response
    exchange: the server stamped ``server_wall`` somewhere inside the
    client's ``[send_wall, recv_wall]`` window, so
    ``server_wall - midpoint`` estimates (server - client) with error
    bounded by half the round trip."""
    return float(server_wall) - 0.5 * (float(send_wall)
                                       + float(recv_wall))


def record_clock_handshake(role: str, offset_s: float, rtt_s: float,
                           **extra):
    """Record the measured (server - local) wall offset in the local
    ring; ``trace_merge --align handshake`` applies it so client files
    from a DIFFERENT host still land on the server's timeline."""
    if not _trace.enabled():
        return
    _trace.instant("clock", "clock_handshake", role=str(role),
                   offset_s=float(offset_s), rtt_s=float(rtt_s),
                   pid=os.getpid(), **extra)


# ---------------------------------------------------------------------------
# Progress streaming
# ---------------------------------------------------------------------------
class ProgressBus:
    """Bounded per-request progress queues (always on — this is the
    streaming plane ``SolveClient.watch`` drains, independent of the
    trace ring).

    Each :meth:`emit` appends one event dict ``{"seq", "t", "kind",
    ...fields}`` to the request's bounded deque; :meth:`poll` returns
    the events past a consumer cursor (plus how many were lost to the
    bound — a slow watcher loses the OLDEST events, never blocks the
    scheduler).  :meth:`mark_done` latches the terminal state so a
    late-arriving watcher still observes completion."""

    def __init__(self, maxlen: int = 256):
        self.maxlen = int(maxlen)
        self._lock = threading.Lock()
        self._q: dict = {}        # rid -> {"dq", "next_seq", "done"}

    def _entry(self, rid: str):
        e = self._q.get(rid)
        if e is None:
            e = self._q[rid] = {
                "dq": collections.deque(maxlen=self.maxlen),
                "next_seq": 0, "done": False}
        return e

    def emit(self, rid, kind: str, **fields) -> int:
        """Append one event; returns its sequence number."""
        rid = str(rid)
        with self._lock:
            e = self._entry(rid)
            seq = e["next_seq"]
            e["next_seq"] = seq + 1
            ev = {"seq": seq, "t": time.time(), "kind": str(kind)}
            ev.update(fields)
            e["dq"].append(ev)
            return seq

    def mark_done(self, rid):
        with self._lock:
            self._entry(str(rid))["done"] = True

    def is_done(self, rid) -> bool:
        with self._lock:
            e = self._q.get(str(rid))
            return bool(e and e["done"])

    def poll(self, rid, cursor: int = 0):
        """``(events, next_cursor, lost, done)`` — every event with
        ``seq >= cursor`` still in the bound, the cursor to pass next
        time, how many the bound already evicted past the cursor, and
        the terminal latch."""
        rid = str(rid)
        cursor = int(cursor)
        with self._lock:
            e = self._q.get(rid)
            if e is None:
                return [], cursor, 0, False
            evs = [dict(ev) for ev in e["dq"] if ev["seq"] >= cursor]
            first_kept = e["dq"][0]["seq"] if e["dq"] else e["next_seq"]
            lost = max(0, first_kept - cursor)
            return evs, e["next_seq"], lost, e["done"]

    def drop(self, rid):
        """Release a retired request's queue (the server's
        ``retire_finished`` sweep calls this so bus memory tracks the
        retained-record window)."""
        with self._lock:
            self._q.pop(str(rid), None)

    def known(self, rid) -> bool:
        with self._lock:
            return str(rid) in self._q


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------
def _prom_name(name: str) -> str:
    out = []
    for ch in str(name):
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _prom_val(v) -> str:
    try:
        v = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def _prom_label(v) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n") \
                 .replace('"', r'\"')


def prometheus_text(registry=None, extra_lines=()) -> str:
    """Render the metrics registry in the Prometheus text exposition
    format (version 0.0.4): counters as ``tpusppy_<name>_total``,
    gauges as ``tpusppy_<name>``, histograms as summaries (quantile
    series + ``_sum``/``_count``).  ``extra_lines`` (pre-rendered
    strings, e.g. :func:`tenant_gauge_lines`) append verbatim."""
    registry = registry or _metrics.REGISTRY
    with registry._lock:
        items = sorted(registry._metrics.items())
    lines = []
    for name, m in items:
        base = "tpusppy_" + _prom_name(name)
        if isinstance(m, _metrics.Histogram):
            s = m.summary()
            lines.append(f"# TYPE {base} summary")
            for q in (0.50, 0.95, 0.99):
                qv = m.quantile(q)
                if qv is not None:
                    lines.append(f'{base}{{quantile="{q}"}} '
                                 f"{_prom_val(qv)}")
            lines.append(f"{base}_sum {_prom_val(s['total'])}")
            lines.append(f"{base}_count {_prom_val(s['count'])}")
        elif isinstance(m, _metrics.Gauge):
            v = m.get()
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {_prom_val(v if v is not None else 0)}")
        else:
            lines.append(f"# TYPE {base}_total counter")
            lines.append(f"{base}_total {_prom_val(m.get())}")
    lines.extend(extra_lines)
    return "\n".join(lines) + "\n"


#: (snapshot key, metric suffix) pairs rendered per live tenant.
_TENANT_GAUGES = (
    ("rel_gap", "tenant_rel_gap"),
    ("outer", "tenant_best_outer"),
    ("inner", "tenant_best_inner"),
    ("iters", "tenant_iters"),
    ("deadline_headroom_s", "tenant_deadline_headroom_seconds"),
    ("attributed_flops", "tenant_attributed_flops"),
    ("mfu_pct", "tenant_mfu_pct"),
)


def tenant_gauge_lines(snapshot: dict) -> list:
    """Per-tenant gauge lines from a server ``status_snapshot()``:
    live rel_gap / best bounds / deadline headroom / attributed FLOPs
    per request (labels ``request_id``, ``model``, ``qos``), plus the
    scheduler-level queue depth and batch slot occupancy."""
    lines = []
    sched = [("tpusppy_queue_depth", snapshot.get("queue_depth")),
             ("tpusppy_requests_live", snapshot.get("requests_live")),
             ("tpusppy_batch_slots", snapshot.get("batch_slots")),
             ("tpusppy_batch_slots_occupied",
              snapshot.get("batch_slots_occupied"))]
    for name, v in sched:
        if v is not None:
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_prom_val(v)}")
    per = snapshot.get("requests") or {}
    emitted = set()
    for rid, row in sorted(per.items()):
        labels = (f'request_id="{_prom_label(rid)}",'
                  f'model="{_prom_label(row.get("model", ""))}",'
                  f'qos="{_prom_label(row.get("qos", ""))}",'
                  f'status="{_prom_label(row.get("status", ""))}"')
        for key, suffix in _TENANT_GAUGES:
            v = row.get(key)
            if v is None:
                continue
            name = "tpusppy_" + suffix
            if name not in emitted:
                emitted.add(name)
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{{{labels}}} {_prom_val(v)}")
    return lines


# ---------------------------------------------------------------------------
# Zero-dependency scrape endpoint
# ---------------------------------------------------------------------------
def json_safe(v):
    """Strict-JSON scrub (non-finite floats -> repr strings) — the
    status surface carries records whose gaps are legitimately inf."""
    if isinstance(v, float):
        return v if math.isfinite(v) else repr(v)
    if isinstance(v, (bool, int, str)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [json_safe(x) for x in v]
    try:
        return json_safe(float(v))
    except (TypeError, ValueError):
        return repr(v)


class ScrapeServer:
    """Stdlib-HTTP scrape endpoint: ``GET /metrics`` serves
    :func:`prometheus_text` (+ per-tenant gauges when a ``status_fn``
    is wired), ``GET /status`` the structured JSON snapshot.  Runs a
    daemonized ``ThreadingHTTPServer`` — zero new dependencies, closed
    with the frontend that owns it."""

    def __init__(self, status_fn=None, registry=None, port: int = 0,
                 bind: str = "127.0.0.1"):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        scrape = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):      # no stderr chatter per scrape
                pass

            def _send(self, code, ctype, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path in ("/metrics", "/"):
                        extra = []
                        if scrape.status_fn is not None:
                            extra = tenant_gauge_lines(scrape.status_fn())
                        body = prometheus_text(
                            scrape.registry, extra_lines=extra).encode()
                        self._send(200, "text/plain; version=0.0.4",
                                   body)
                    elif path == "/status":
                        snap = (scrape.status_fn()
                                if scrape.status_fn is not None else {})
                        self._send(200, "application/json",
                                   json.dumps(json_safe(snap)).encode())
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except Exception as e:      # a scrape must never wedge
                    with contextlib.suppress(Exception):
                        self._send(500, "text/plain",
                                   f"scrape error: {e!r}\n".encode())

        self.status_fn = status_fn
        self.registry = registry
        self._httpd = ThreadingHTTPServer((bind, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="telemetry-scrape", daemon=True)
        self._thread.start()

    def close(self):
        with contextlib.suppress(Exception):
            self._httpd.shutdown()
            self._httpd.server_close()
        self._thread.join(timeout=5.0)
