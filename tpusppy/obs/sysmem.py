"""Host/device memory watermarks as obs gauges.

The scenario scale-out acceptance (doc/scaling.md) is phrased in memory:
the wheel must be O(1) in HOST memory with respect to S, and the device
high-water tells whether a rung actually fit the mesh.  Two gauges:

* ``mem.host_peak`` — peak RSS of this process in MB (``ru_maxrss``; a
  HIGH-WATER mark: it never decreases within a process, so per-segment
  deltas mean "this segment raised the peak by X", not "used X").
* ``mem.device_peak`` — max over local devices of the backend's
  ``peak_bytes_in_use`` in MB.  The XLA:CPU backend reports no memory
  stats; the gauge then reads 0.0 and callers label it unavailable —
  same CPU-caveat posture as the host-sync table in the README.

:func:`sample` refreshes both gauges and returns the values, so bench
segment lines (`peak_rss_mb`, `device_peak_mb`) and smoke-script budget
asserts read one source.
"""

from __future__ import annotations

import sys

from . import metrics as _metrics

_G_HOST = _metrics.gauge("mem.host_peak")
_G_DEV = _metrics.gauge("mem.device_peak")


def peak_rss_mb() -> float:
    """Peak resident set size of this process, in MB (0.0 when the
    platform offers no ``getrusage``)."""
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except (ImportError, OSError):
        return 0.0
    # ru_maxrss is KB on Linux, bytes on macOS
    scale = 1e-6 if sys.platform == "darwin" else 1e-3
    return float(peak) * scale


def device_peak_mb() -> float:
    """Max per-device peak bytes in use across local devices, in MB
    (0.0 when the backend reports no memory stats — XLA:CPU)."""
    try:
        import jax

        peaks = []
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                pass
            if stats:
                peaks.append(stats.get("peak_bytes_in_use",
                                       stats.get("bytes_in_use", 0)))
        return max(peaks) / 1e6 if peaks else 0.0
    except Exception:
        return 0.0


def sample() -> dict:
    """Refresh the ``mem.*`` gauges; returns
    ``{"peak_rss_mb": ..., "device_peak_mb": ...}`` (rounded to 0.1 MB)."""
    host = round(peak_rss_mb(), 1)
    dev = round(device_peak_mb(), 1)
    _G_HOST.set(host)
    _G_DEV.set(dev)
    return {"peak_rss_mb": host, "device_peak_mb": dev}
