"""tpusppy.obs: zero-dependency tracing + metrics + run reporting.

One subsystem for every number and event the stack emits about itself:

- :mod:`.trace` — a thread-safe bounded ring buffer of structured events
  (spans / instants / counters), OFF by default at near-zero cost, enabled
  via ``TPUSPPY_TRACE=<path>`` or :func:`trace.enable`;
- :mod:`.metrics` — the process-wide registry of counters / gauges /
  histograms that the host-sync trackers (:mod:`tpusppy.solvers.hostsync`)
  and the dispatch/speculation billing feed, and that every number
  ``bench.py`` reports is sourced from;
- :mod:`.perfetto` — export of the trace ring as Chrome/Perfetto
  trace-event JSON (open at https://ui.perfetto.dev);
- :mod:`.report` — the post-run "flight recorder" summary: gap-vs-wall and
  bound-vs-wall arrays, per-track span totals, counter dump;
- :mod:`.log` — ``get_logger(name)`` with the ``[track] message`` format
  and the ``TPUSPPY_LOG_LEVEL`` knob (:mod:`tpusppy.log` re-exports it);
- :mod:`.telemetry` — the LIVE serving plane: request-scoped trace
  propagation (``trace_id`` context, per-request tracks, clock-sync
  stamps for ``scripts/trace_merge.py``), Prometheus text exposition +
  the zero-dependency scrape endpoint, and the bounded per-request
  progress bus ``SolveClient.watch`` streams from.

Grew out of the PR-3 fragments (hostsync fetch counters, per-segment
``mfu_pct`` / ``dispatch_overhead_pct``); see doc/observability.md for the
event taxonomy and track naming.
"""

from . import log, metrics, perfetto, report, telemetry, trace  # noqa: F401

__all__ = ["log", "metrics", "perfetto", "report", "telemetry", "trace"]
