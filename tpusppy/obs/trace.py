"""Structured trace ring buffer: spans, instants, counters.

The flight-recorder core: a thread-safe bounded ring of
:class:`Event` tuples ``(t, tid, track, name, kind, dur, payload)``.
Recording is OFF by default and the disabled fast path is pinned by a
test: every public record function starts with one module-flag check and
returns a shared singleton (no event tuple, no payload dict is
constructed), so instrumentation can stay in hot paths permanently.

Tracks are logical timelines (one per cylinder / controller /
listener-thread — see doc/observability.md for the naming scheme).  Most
instrumentation passes ``track=None`` which resolves to the calling
thread's track (:func:`set_thread_track` — the wheel spinner names its
cylinder threads); fixed subsystem timelines ("host-sync", "dispatch",
"mailbox", …) pass their track explicitly.  The OS thread ident is
recorded per event so the Perfetto exporter can keep concurrent spans on
one logical track from interleaving their begin/end pairs.

Enablement: ``TPUSPPY_TRACE=<path>`` in the environment turns tracing on
at import and registers an atexit flush of ``<path>`` (Perfetto JSON)
plus ``<path>.report.json`` (the :mod:`.report` summary); programmatic
:func:`enable`/:func:`disable` and :func:`flush` do the same on demand.
``Config.tracing`` (see :meth:`tpusppy.utils.config.Config.tracing_args`)
routes here through :func:`maybe_enable_from_config`.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import NamedTuple

#: Default ring capacity (events).  At the wheel's event rates (~10-100
#: events/iteration) this keeps minutes of history; the ring drops the
#: OLDEST events on overflow (``dropped`` counts them).
DEFAULT_CAPACITY = 131072

_perf = time.perf_counter


class Event(NamedTuple):
    t: float            # perf_counter timestamp (seconds)
    tid: int            # OS thread ident at record time
    track: str          # logical timeline name
    name: str           # event name
    kind: str           # "span" | "instant" | "counter"
    dur: float | None   # span duration (seconds); None otherwise
    payload: dict | None


class TraceBuffer:
    """Thread-safe bounded ring of events (newest kept on overflow)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._dq: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dropped = 0

    def add(self, ev: Event):
        with self._lock:
            if len(self._dq) == self.capacity:
                self.dropped += 1
            self._dq.append(ev)

    def snapshot(self) -> list:
        """Copy of the current events, oldest first."""
        with self._lock:
            return list(self._dq)

    def clear(self):
        with self._lock:
            self._dq.clear()
            self.dropped = 0

    def __len__(self):
        with self._lock:
            return len(self._dq)


# ---------------------------------------------------------------------------
# Module state.  `_enabled` is THE fast-path flag: every record function
# checks it first and allocates nothing when False.
# ---------------------------------------------------------------------------
_enabled = False
_buffer = TraceBuffer()
_flush_path: str | None = None
_atexit_registered = False
_tls = threading.local()
# recording generation: bumped by disable()/reset() so a span OPENED in
# an earlier generation (a lingering daemon cylinder thread crossing a
# test fixture's disable+reset+re-enable) drops its event instead of
# leaking it into the next owner's ring
_gen = 0


def enabled() -> bool:
    return _enabled


def set_thread_track(name: str | None):
    """Set (or clear) the calling thread's default track — events recorded
    with ``track=None`` land here.  The wheel spinner names its cylinder
    threads this way ("hub", "spoke1:LagrangianOuterBound", ...)."""
    _tls.track = name


def thread_track() -> str:
    return getattr(_tls, "track", None) or "main"


def enable(path: str | None = None, capacity: int | None = None):
    """Turn recording on.  ``path`` (optional) arms :func:`flush` and an
    atexit flush; ``capacity`` resizes (and clears) the ring."""
    global _enabled, _flush_path, _buffer, _atexit_registered
    if capacity is not None and capacity != _buffer.capacity:
        _buffer = TraceBuffer(capacity)
    if path:
        _flush_path = str(path)
        if not _atexit_registered:
            import atexit

            atexit.register(_flush_atexit)
            _atexit_registered = True
    _enabled = True


def disable():
    global _enabled, _gen
    _enabled = False
    _gen += 1


def reset(capacity: int | None = None):
    """Clear the ring (recording flag unchanged) — test isolation hook.
    ``capacity`` also restores the ring size (an ``enable(capacity=...)``
    from one owner must not shrink every later owner's ring)."""
    global _gen, _buffer
    _gen += 1
    if capacity is not None and capacity != _buffer.capacity:
        _buffer = TraceBuffer(capacity)
    else:
        _buffer.clear()


def events() -> list:
    """Snapshot of the recorded events, oldest first."""
    return _buffer.snapshot()


def dropped() -> int:
    return _buffer.dropped


# ---------------------------------------------------------------------------
# Recording.  Spans via context manager; `_NULL` is the shared disabled
# singleton (identity-checkable by the overhead test).
# ---------------------------------------------------------------------------
class _NullSpan:
    """Shared no-op span: returned whenever tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **kw):   # payload attach is a no-op too
        pass


_NULL = _NullSpan()


class _Span:
    __slots__ = ("track", "name", "payload", "t0", "gen")

    def __init__(self, track, name, payload):
        self.track = track
        self.name = name
        self.payload = payload

    def __enter__(self):
        self.gen = _gen
        self.t0 = _perf()
        return self

    def add(self, **kw):
        """Attach payload discovered mid-span (recorded at exit)."""
        if self.payload is None:
            self.payload = {}
        self.payload.update(kw)

    def __exit__(self, *exc):
        if not _enabled or self.gen != _gen:
            # tracing was disabled or reset while this span was open —
            # e.g. a lingering daemon spoke thread the wheel spinner
            # deliberately survives, crossing a test fixture's
            # disable+reset(+re-enable).  Dropping the event keeps
            # foreign spans out of the next owner's ring.
            return False
        t1 = _perf()
        _buffer.add(Event(self.t0, threading.get_ident(),
                          self.track or thread_track(), self.name, "span",
                          t1 - self.t0, self.payload))
        return False


def span(track: str | None, name: str, **payload):
    """Context manager recording a duration event on ``track`` (None =
    the calling thread's track).  Disabled: returns the shared no-op
    singleton — nothing is allocated beyond the kwargs dict, so hot paths
    with payloads should guard on :func:`enabled` first."""
    if not _enabled:
        return _NULL
    return _Span(track, name, payload or None)


def record_span(track: str | None, name: str, t0: float, dur: float,
                payload: dict | None = None):
    """Record an ALREADY-timed span (callers that measured their own
    ``perf_counter`` window, e.g. the host-sync fetch wrapper)."""
    if not _enabled:
        return
    _buffer.add(Event(t0, threading.get_ident(),
                      track or thread_track(), name, "span", dur, payload))


def instant(track: str | None, name: str, **payload):
    """Point event (a marker on the timeline)."""
    if not _enabled:
        return
    _buffer.add(Event(_perf(), threading.get_ident(),
                      track or thread_track(), name, "instant", None,
                      payload or None))


def counter(track: str | None, name: str, value, **payload):
    """Sampled numeric series (rendered as a counter track; the report
    collects named series like ``rel_gap`` into *-vs-wall arrays).
    Extra ``payload`` keys ride alongside ``value`` — the telemetry
    plane tags per-tenant samples with ``request_id``/``trace_id`` so
    the report can bucket series per request."""
    if not _enabled:
        return
    data = {"value": float(value)}
    if payload:
        data.update(payload)
    _buffer.add(Event(_perf(), threading.get_ident(),
                      track or thread_track(), name, "counter", None,
                      data))


# ---------------------------------------------------------------------------
# Flush / wiring
# ---------------------------------------------------------------------------
def flush(path: str | None = None) -> str | None:
    """Write the current ring as Perfetto JSON to ``path`` (default: the
    armed flush path) plus the report summary to ``<path>.report.json``.
    Returns the path written, or None when there is nowhere to write."""
    path = path or _flush_path
    if not path:
        return None
    import json

    from . import perfetto, report

    perfetto.export(events(), path=path)
    with open(path + ".report.json", "w") as f:
        json.dump(report.build_report(events()), f, indent=1)
    return path


def flush_if_enabled():
    """Flush when tracing is on and a path is armed (wheel/bench hook —
    safe to call unconditionally)."""
    if _enabled and _flush_path:
        flush()


def _flush_atexit():
    with contextlib.suppress(Exception):   # interpreter teardown
        flush_if_enabled()


def maybe_enable_from_config(cfg) -> bool:
    """Enable tracing when a Config carries a truthy ``tracing`` field
    (the path to flush to).  Returns whether tracing is now enabled."""
    path = None
    try:
        path = cfg.get("tracing")
    except Exception:
        path = getattr(cfg, "tracing", None)
    if path:
        enable(path=str(path))
    return _enabled


_env_path = os.environ.get("TPUSPPY_TRACE")
if _env_path:
    enable(path=_env_path)
