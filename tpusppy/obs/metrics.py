"""Process-wide metrics registry: counters, gauges, histograms.

The single source for every number the bench reports (the PR-3
``host_sync_count`` / ``dispatch_overhead_pct`` fragments grew into
this): :mod:`tpusppy.solvers.hostsync` feeds the ``host_sync.*``
counters on every decision-path fetch, the segmented dispatcher bills
``speculation.*``, the mailboxes count puts/skips, and so on — see
doc/observability.md for the key taxonomy.

Metrics are ALWAYS on (unlike the trace ring): each update is one lock +
an int/float add, cheap enough for every hot path that already crosses
the host.  Scoped measurements (bench segments, tests) read via
:func:`window`, which snapshots the registry and exposes per-key deltas —
the process-wide totals never need resetting mid-run.  Values are
monotone within a process; :func:`reset` exists for test isolation only.

Concurrency note: the registry is process-global, so a window opened
while OTHER threads also update the same keys sees their traffic too
(the thread-local trackers in ``hostsync`` remain the per-cylinder
view; the parity test pins that single-threaded windows agree exactly).
"""

from __future__ import annotations

import threading


class Counter:
    """Monotone float/int accumulator."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n=1.0):
        with self._lock:
            self.value += n

    def get(self) -> float:
        with self._lock:
            return self.value

    def reset(self):
        with self._lock:
            self.value = 0.0


class Gauge:
    """Last-value-wins sample."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def set(self, v):
        with self._lock:
            self.value = float(v)

    def get(self):
        with self._lock:
            return self.value

    def reset(self):
        with self._lock:
            self.value = None


class Histogram:
    """Streaming summary (count/total/min/max) plus QUANTILES from a
    bounded reservoir — serving SLOs need latency percentiles, not just
    sums (doc/serving.md).  The reservoir is classic Algorithm-R
    sampling (uniform over the stream) capped at :data:`RESERVOIR_CAP`
    samples, seeded deterministically so identical insert streams yield
    identical summaries."""

    RESERVOIR_CAP = 512

    __slots__ = ("_lock", "count", "total", "min", "max", "_samples",
                 "_rng")

    def __init__(self):
        import random

        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples: list = []
        self._rng = random.Random(0x5EED)

    def add(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._samples) < self.RESERVOIR_CAP:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.RESERVOIR_CAP:
                    self._samples[j] = v

    def quantile(self, q: float):
        """Reservoir quantile (nearest-rank on sorted samples); None when
        empty.  Exact while count <= RESERVOIR_CAP, sampled past it."""
        with self._lock:
            samples = sorted(self._samples)
        if not samples:
            return None
        q = min(max(float(q), 0.0), 1.0)
        idx = min(len(samples) - 1, int(round(q * (len(samples) - 1))))
        return samples[idx]

    def summary(self) -> dict:
        p50, p95, p99 = (self.quantile(q) for q in (0.50, 0.95, 0.99))
        with self._lock:
            return {"count": self.count, "total": self.total,
                    "min": self.min, "max": self.max,
                    "p50": p50, "p95": p95, "p99": p99}

    def reset(self):
        with self._lock:
            self.count = 0
            self.total = 0.0
            self.min = None
            self.max = None
            self._samples = []
            import random

            self._rng = random.Random(0x5EED)


class Registry:
    """Name -> metric store with get-or-create accessors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} is {type(m).__name__}, "
                    f"wanted {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def value(self, name: str, default=0.0):
        """Current scalar value of a counter/gauge (0.0 for unknown keys —
        a window over an idle subsystem reads as zero traffic)."""
        with self._lock:
            m = self._metrics.get(name)
        if m is None:
            return default
        if isinstance(m, Histogram):
            return m.summary()["total"]
        return m.get()

    def dump(self) -> dict:
        """{name: value-or-summary} snapshot of everything."""
        with self._lock:
            items = list(self._metrics.items())
        out = {}
        for name, m in sorted(items):
            out[name] = (m.summary() if isinstance(m, Histogram)
                         else m.get())
        return out

    def reset(self):
        """Zero every metric IN PLACE (test isolation; never call
        mid-run).  In place matters: instrumented modules bind their hot
        counters at import time (``hostsync._CTR_COUNT`` etc.) — dropping
        the objects would orphan those references and silently fork the
        registry."""
        with self._lock:
            for m in self._metrics.values():
                m.reset()


#: The process-wide registry every subsystem feeds.
REGISTRY = Registry()


# Module-level conveniences (the common call shape in instrumentation).
def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


def inc(name: str, n=1.0):
    REGISTRY.counter(name).inc(n)


def value(name: str, default=0.0):
    return REGISTRY.value(name, default)


def dump() -> dict:
    return REGISTRY.dump()


def reset():
    REGISTRY.reset()


class Window:
    """Delta view over the registry: snapshots counter/histogram totals
    at entry; ``delta(name)`` is the traffic since then.  Gauges read
    current (their delta is rarely meaningful)."""

    def __init__(self, registry: Registry | None = None):
        self.registry = registry or REGISTRY
        self._base: dict = {}

    def __enter__(self):
        # histograms snapshot as their running TOTAL (value() semantics)
        # so delta() is a real window delta for them too, not the
        # lifetime figure
        self._base = {
            k: (v["total"] if isinstance(v, dict) else v)
            for k, v in self.registry.dump().items()
        }
        return self

    def __exit__(self, *exc):
        return False

    def delta(self, name: str) -> float:
        base = self._base.get(name, 0.0)
        cur = self.registry.value(name, 0.0)
        if cur is None or isinstance(cur, dict):
            return 0.0
        return cur - (base or 0.0)

    def deltas(self) -> dict:
        """{name: windowed value} for every metric: counters and
        histograms as deltas since entry, gauges at their current value
        (a gauge delta is rarely meaningful).  The per-segment report
        uses this so one bench segment's counter dump never carries the
        previous segments' traffic."""
        with self.registry._lock:
            items = list(self.registry._metrics.items())
        out = {}
        for k, m in sorted(items):
            if isinstance(m, Gauge):
                out[k] = m.get()
            elif isinstance(m, Histogram):
                out[k] = m.summary()["total"] - (self._base.get(k) or 0.0)
            else:
                out[k] = m.get() - (self._base.get(k) or 0.0)
        return out


def window(registry: Registry | None = None) -> Window:
    """Context manager for scoped measurement (bench segments, tests)."""
    return Window(registry)
