"""Export the trace ring as Chrome/Perfetto trace-event JSON.

Open the output at https://ui.perfetto.dev (or chrome://tracing): every
logical track renders as its own named thread row — hub iterations,
spoke bound passes, mailbox puts/gets, segmented dispatches, speculation
discards and host-sync fetches land on one causally-ordered timeline.

Mapping (trace-event "JSON array format"):

- one fake process (pid 1) per export, one fake thread per (track, OS
  thread) pair — concurrent spans on the same logical track from
  different cylinder threads get sibling rows ("host-sync", "host-sync/2")
  instead of interleaving their B/E pairs;
- spans emit matched ``B``/``E`` pairs (the ring stores one event per
  completed span, so pairs are matched by construction);
- instants emit thread-scoped ``i`` events;
- counters emit ``C`` events (Perfetto renders a numeric series).

Timestamps are microseconds relative to the first event, sorted
monotonically.  Payloads ride in ``args`` (values stringified only if
not JSON-serializable).
"""

from __future__ import annotations

import json
import math


def _json_safe(v):
    if isinstance(v, float):
        # strict-JSON guard: json.dump would emit bare Infinity/NaN
        # tokens (valid Python, INVALID JSON) and ui.perfetto.dev's
        # JSON.parse would reject the whole file — the hub's first bound
        # update carries old=±inf by construction
        return v if math.isfinite(v) else repr(v)
    if isinstance(v, (bool, int, str)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    try:
        return _json_safe(float(v))           # numpy scalars
    except (TypeError, ValueError):
        return repr(v)


def to_trace_events(events) -> list:
    """Flatten ring events into a ts-sorted trace-event list (dicts)."""
    if not events:
        return []
    t0 = min(ev.t for ev in events)
    # stable tid per (track, os-thread): first-seen order, named rows
    tids: dict = {}
    names: dict = {}

    def tid_of(track, os_tid):
        key = (track, os_tid)
        if key not in tids:
            tids[key] = len(tids) + 1
            n = sum(1 for (tr, _) in tids if tr == track)
            names[tids[key]] = track if n == 1 else f"{track}/{n}"
        return tids[key]

    out = []
    for ev in events:
        ts = (ev.t - t0) * 1e6
        tid = tid_of(ev.track, ev.tid)
        args = _json_safe(ev.payload) if ev.payload else {}
        if ev.kind == "span":
            dur = max(0.0, (ev.dur or 0.0) * 1e6)
            out.append({"name": ev.name, "ph": "B", "pid": 1, "tid": tid,
                        "ts": ts, "args": args})
            out.append({"name": ev.name, "ph": "E", "pid": 1, "tid": tid,
                        "ts": ts + dur})
        elif ev.kind == "counter":
            val = (ev.payload or {}).get("value", 0.0)
            out.append({"name": ev.name, "ph": "C", "pid": 1, "tid": tid,
                        "ts": ts, "args": {"value": _json_safe(val)}})
        else:
            out.append({"name": ev.name, "ph": "i", "pid": 1, "tid": tid,
                        "ts": ts, "s": "t", "args": args})
    out.sort(key=lambda e: (e["ts"], 0 if e["ph"] != "E" else 1))
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": name}} for tid, name in sorted(names.items())]
    return meta + out


def export(events, path: str | None = None) -> dict:
    """Build (and optionally write) the Perfetto JSON document."""
    doc = {"traceEvents": to_trace_events(events),
           "displayTimeUnit": "ms"}
    if path:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
