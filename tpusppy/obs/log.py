"""Logger factory with track-attributable output.

One ``get_logger(name)`` for the whole stack: every record renders as
``[name] message`` so multi-process wheel output (hub, spokes, dist-APH
listeners) is attributable to its cylinder/rank, and the level is one
env knob: ``TPUSPPY_LOG_LEVEL`` (DEBUG/INFO/WARNING/ERROR, default
INFO).  Folds the old :mod:`tpusppy.log` (which re-exports from here):
the root ``tpusppy`` logger still writes to stdout, and
:func:`setup_logger` keeps the reference's custom stream/file factory
(mpisppy/log.py:52-67 semantics).
"""

from __future__ import annotations

import logging
import os
import sys


class _TrackFormatter(logging.Formatter):
    """``[track] message`` — track is the logger name below ``tpusppy``
    (bare root records render untagged, preserving global_toc-era
    output)."""

    def format(self, record):
        msg = record.getMessage()
        track = record.name
        if track.startswith("tpusppy."):
            track = track[len("tpusppy."):]
        out = msg if track in ("tpusppy", "root", "") else f"[{track}] {msg}"
        # keep the logging.Formatter contract: exc_info/stack_info must
        # not be silently dropped (error paths log with exc_info=True)
        if record.exc_info:
            out += "\n" + self.formatException(record.exc_info)
        if record.stack_info:
            out += "\n" + self.formatStack(record.stack_info)
        return out


def _env_level(default=logging.INFO):
    name = os.environ.get("TPUSPPY_LOG_LEVEL", "").strip().upper()
    if not name:
        return default
    return getattr(logging, name, default)


#: Root logger of the stack (stdout, [track]-formatted, env-leveled).
root = logging.getLogger("tpusppy")
root.setLevel(_env_level())
if not root.handlers:
    _h = logging.StreamHandler(sys.stdout)
    _h.setFormatter(_TrackFormatter())
    root.addHandler(_h)


def get_logger(name: str | None = None) -> logging.Logger:
    """Child of the ``tpusppy`` root whose records render as
    ``[name] message``.  ``name`` is the track — a module tag
    ("cylinders.hub"), a cylinder ("spoke1:Lagrangian"), or a rank-tagged
    form ("dist_aph[p3]") for multi-process wheels."""
    if not name:
        return root
    return logging.getLogger(f"tpusppy.{name}")


def set_level(level):
    """Programmatic override of the env knob (accepts names or ints)."""
    if isinstance(level, str):
        level = getattr(logging, level.strip().upper())
    root.setLevel(level)


def setup_logger(name, out, level=logging.DEBUG, mode="w", fmt=None):
    """Set up a custom stream/file logger quickly (mpisppy/log.py:52-67
    semantics, kept for reference parity): ``out`` is a stream
    (stdout/stderr) or a filename."""
    if fmt is None:
        fmt = "(%(asctime)s) %(message)s"
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    formatter = logging.Formatter(fmt)
    if out in (sys.stdout, sys.stderr):
        handler = logging.StreamHandler(out)
    else:
        handler = logging.FileHandler(out, mode=mode)
    handler.setFormatter(formatter)
    lg.addHandler(handler)
    return lg
