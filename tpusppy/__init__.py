"""tpusppy: a TPU-native framework for scenario-based optimization under uncertainty.

Re-implements the capabilities of mpi-sppy (Progressive Hedging and friends with an
asynchronous hub-and-spoke bound architecture) on top of JAX/XLA: scenario subproblems
are an HBM-resident batch solved by a vmapped first-order proximal QP solver,
nonanticipative reductions are ``jax.lax.psum`` over a device mesh, and cross-cylinder
exchange is a write-id-versioned host mailbox.

Reference architecture surveyed in SURVEY.md (mpi-sppy mounted at /root/reference).
This module mirrors ``mpisppy/__init__.py:1-13`` (global_toc timestamped logging).
"""

import time as _time

__version__ = "0.1.0"

_T0 = _time.time()
_toc_enabled = True


def global_toc(msg, cond=True):
    """Timestamped progress message (analogue of mpisppy.global_toc).

    The reference uses Pyomo's TicTocTimer; here a plain monotonic stamp.
    """
    if cond and _toc_enabled:
        print(f"[{_time.time() - _T0:10.2f}] {msg}", flush=True)


def disable_tictoc_output():
    global _toc_enabled
    _toc_enabled = False


def reenable_tictoc_output():
    global _toc_enabled
    _toc_enabled = True
