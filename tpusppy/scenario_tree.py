"""Scenario-tree annotations.

TPU-native analogue of ``mpisppy/scenario_tree.py:44-96`` (``ScenarioNode``) and the
tree-rebuilding logic in ``mpisppy/utils/sputils.py:675-840`` (``_TreeNode`` /
``_ScenTree``).  Node names encode tree structure textually exactly as in the
reference: ``ROOT``, ``ROOT_0``, ``ROOT_0_1``, ...

Instead of annotating a Pyomo model, a :class:`ScenarioNode` here carries the
*indices into the scenario's flat variable vector* that are nonanticipative at that
node, plus the conditional probability.  The tree as a whole is compiled by
:func:`build_tree` into flat integer arrays (scenario -> node-id per stage) that the
batched PH reductions consume on device.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np


@dataclasses.dataclass
class ScenarioNode:
    """Per-scenario annotation of one nonleaf tree node (cf. scenario_tree.py:44-96).

    Args:
      name: textual node name; parent is everything before the final ``_``.
      cond_prob: probability of this node given its parent.
      stage: 1-based stage number (ROOT is stage 1).
      nonant_indices: indices (into the scenario's flat x) of the nonanticipative
        variables attached to this node.
      cost_coeffs: optional per-variable cost vector for "stage cost" reporting
        (the reference attaches a Pyomo cost *expression*; we keep a linear form).
    """

    name: str
    cond_prob: float
    stage: int
    nonant_indices: np.ndarray
    cost_coeffs: np.ndarray | None = None

    def __post_init__(self):
        self.nonant_indices = np.asarray(self.nonant_indices, dtype=np.int32)
        if self.name != "ROOT" and not re.fullmatch(r"ROOT(_\d+)+", self.name):
            raise ValueError(f"Node name {self.name!r} must be ROOT or ROOT_i_j...")
        if self.name == "ROOT" and self.stage != 1:
            raise ValueError("ROOT must be stage 1")

    @property
    def parent_name(self) -> str | None:
        if self.name == "ROOT":
            return None
        return self.name.rsplit("_", 1)[0]


def attach_root_node(problem, nonant_indices, cost_coeffs=None):
    """Two-stage convenience: attach a single ROOT node (cf. sputils.py:844-860)."""
    problem.nodes = [
        ScenarioNode("ROOT", 1.0, 1, np.asarray(nonant_indices), cost_coeffs)
    ]
    return problem


def create_nodenames_from_branching_factors(branching_factors) -> list:
    """All node names of a balanced tree, leaves included — same semantics as
    the reference's ``sputils.create_nodenames_from_BFs`` (sputils.py:934).
    Callers wanting only nonleaf names drop the last level themselves."""
    names = ["ROOT"]
    frontier = ["ROOT"]
    for bf in branching_factors:
        frontier = [f"{p}_{i}" for p in frontier for i in range(bf)]
        names.extend(frontier)
    return names


def extract_num(name: str) -> int:
    """Scrape trailing digits off a scenario name (cf. sputils.extract_num)."""
    m = re.search(r"(\d+)$", name)
    if m is None:
        raise RuntimeError(f"Could not extract number from scenario name {name!r}")
    return int(m.group(1))


@dataclasses.dataclass
class TreeInfo:
    """Compiled tree structure for a scenario batch.

    Produced by :func:`build_tree`; consumed by the batched nonant reductions
    (the analogue of per-tree-node MPI communicators, spbase.py:333-375).

    Attributes:
      node_names: list of all distinct nonleaf node names, ROOT first,
        lexicographic within a stage; node-id = index into this list.
      node_stage: (N,) stage of each node (1-based).
      scen_node_ids: (S, T-1) int array; scen_node_ids[s, t] is the node-id of
        scenario s's stage-(t+1) node.
      nonant_stage: (n_nonant,) 1-based stage of each nonant slot in the packed
        nonant vector.
      nonant_indices: (n_nonant,) indices into the flat x vector (shared across
        scenarios; ragged models must pad first).
      node_prob: (N,) unconditional probability of each node
        (cf. spbase.py:378 _compute_unconditional_node_probabilities).
      scen_prob: (S,) scenario probabilities.
    """

    node_names: list
    node_stage: np.ndarray
    scen_node_ids: np.ndarray
    nonant_stage: np.ndarray
    nonant_indices: np.ndarray
    node_prob: np.ndarray
    scen_prob: np.ndarray

    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    @property
    def num_stages(self) -> int:
        return int(self.scen_node_ids.shape[1]) + 1

    @property
    def num_nonants(self) -> int:
        return int(self.nonant_indices.shape[0])

    def nid_sk(self) -> np.ndarray:
        """(S, K) node-id owning each packed nonant slot, per scenario.

        The single source of truth for the node-grouping index used by host PH
        (Compute_Xbar), the sharded jitted step, and EF column merging."""
        S = self.scen_node_ids.shape[0]
        K = self.num_nonants
        return np.take_along_axis(
            self.scen_node_ids,
            np.broadcast_to(self.nonant_stage[None, :] - 1, (S, K)),
            axis=1,
        ).astype(np.int32)

    def onehot_sk_n(self) -> np.ndarray:
        """(S, K, N) one-hot of :meth:`nid_sk` — the matmul form of per-node
        sub-communicators (replaces one Allreduce per node, phbase.py:75-87)."""
        nid = self.nid_sk()
        S, K = nid.shape
        oh = np.zeros((S, K, self.num_nodes))
        oh[np.arange(S)[:, None], np.arange(K)[None, :], nid] = 1.0
        return oh

    def membership_matrix(self) -> np.ndarray:
        """(N, S) 0/1 node-membership over scenarios, any stage.

        M[n, s] = 1 iff scenario s passes through node n.  Used to build the
        weighted node-averaging matmul that replaces per-node Allreduce
        (phbase.py:75-87).
        """
        S, Tm1 = self.scen_node_ids.shape
        M = np.zeros((self.num_nodes, S), dtype=np.float64)
        for s in range(S):
            for t in range(Tm1):
                M[self.scen_node_ids[s, t], s] = 1.0
        return M


def build_tree(problems) -> TreeInfo:
    """Compile per-scenario node lists into flat arrays.

    ``problems`` is a sequence with ``.nodes`` (list of :class:`ScenarioNode`) and
    ``.prob``.  Validates the same invariants the reference checks at
    spbase.py:150-176 (consistent nonant layouts) and spbase.py:457-502
    (probabilities summing to 1 node-by-node).
    """
    S = len(problems)
    num_stages = len(problems[0].nodes) + 1
    for p in problems:
        if len(p.nodes) != num_stages - 1:
            raise ValueError("All scenarios must have the same number of stages")

    # Collect distinct node names per stage.
    names_by_stage = [dict() for _ in range(num_stages - 1)]  # name -> cond_prob
    for p in problems:
        for t, nd in enumerate(p.nodes):
            if nd.stage != t + 1:
                raise ValueError(
                    f"Node {nd.name} stage {nd.stage} != position {t + 1}"
                )
            prev = names_by_stage[t].setdefault(nd.name, nd.cond_prob)
            if abs(prev - nd.cond_prob) > 1e-12:
                raise ValueError(f"Inconsistent cond_prob for node {nd.name}")

    node_names, node_stage, node_cond = [], [], []
    for t in range(num_stages - 1):
        for name in sorted(names_by_stage[t]):
            node_names.append(name)
            node_stage.append(t + 1)
            node_cond.append(names_by_stage[t][name])
    node_id = {name: i for i, name in enumerate(node_names)}

    # Unconditional node probabilities: product of cond_probs down the path.
    node_prob = np.zeros(len(node_names))
    for i, name in enumerate(node_names):
        p = node_cond[i]
        parent = node_names[i].rsplit("_", 1)[0] if name != "ROOT" else None
        while parent is not None:
            p *= node_cond[node_id[parent]]
            parent = parent.rsplit("_", 1)[0] if parent != "ROOT" else None
        node_prob[i] = p

    scen_node_ids = np.zeros((S, num_stages - 1), dtype=np.int32)
    for s, p in enumerate(problems):
        for t, nd in enumerate(p.nodes):
            scen_node_ids[s, t] = node_id[nd.name]

    # Packed nonant layout: stage-1 slots, then stage-2 slots, ... ; the reference
    # requires identical nonant lengths across scenarios of a node (spbase.py:150).
    ref_nodes = problems[0].nodes
    nonant_indices = np.concatenate(
        [nd.nonant_indices for nd in ref_nodes]
    ).astype(np.int32)
    nonant_stage = np.concatenate(
        [np.full(len(nd.nonant_indices), nd.stage, dtype=np.int32) for nd in ref_nodes]
    )
    for p in problems:
        flat = np.concatenate([nd.nonant_indices for nd in p.nodes])
        if not np.array_equal(flat, nonant_indices):
            raise ValueError(
                "All scenarios must use the same nonant variable slots per stage "
                "(pad ragged models before building the batch)"
            )

    scen_prob = np.array([p.prob for p in problems], dtype=np.float64)
    if np.any(scen_prob < 0):
        raise ValueError("negative scenario probability")
    tot = scen_prob.sum()
    if abs(tot - 1.0) > 1e-9:
        raise ValueError(f"scenario probabilities sum to {tot}, not 1")

    return TreeInfo(
        node_names=node_names,
        node_stage=np.asarray(node_stage, dtype=np.int32),
        scen_node_ids=scen_node_ids,
        nonant_stage=nonant_stage,
        nonant_indices=nonant_indices,
        node_prob=node_prob,
        scen_prob=scen_prob,
    )
