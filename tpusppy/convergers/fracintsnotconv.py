"""FractionalConverger: fraction of non-converged integer nonants.

TPU-native analogue of ``mpisppy/convergers/fracintsnotconv.py:13-77``: an
integer nonant slot is "converged" when its scenarios agree, i.e. when
xbar^2 == xsqbar within tolerance; the metric is the fraction that are not.
"""

from __future__ import annotations

import numpy as np

from .converger import Converger


class FractionalConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        self.name = "fractintsnotconv"
        self.verbose = opt.options.get("verbose", False)

    def _convergence_value(self) -> float:
        opt = self.opt
        ints = opt.batch.is_int[opt.tree.nonant_indices]      # (K,)
        numints = int(ints.sum()) * opt.batch.num_scenarios
        if numints == 0:
            return 0.0
        xb = opt.xbars[:, ints]
        xsq = opt.xsqbars[:, ints]
        conv = np.isclose(xb * xb, xsq, atol=1e-9)
        return 1.0 - float(conv.sum()) / numints

    def is_converged(self) -> bool:
        self.conv = self._convergence_value()
        self.conv_value = self.conv
        if self.verbose:
            print(f"{self.name}: convergence value={self.conv}")
        return self.conv < self.opt.options["convthresh"]
