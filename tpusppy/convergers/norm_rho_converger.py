"""NormRhoConverger: stop when log of the probability-weighted rho norm drops.

TPU-native analogue of ``mpisppy/convergers/norm_rho_converger.py:12-56``.
Only meaningful with :class:`~tpusppy.extensions.norm_rho_updater.NormRhoUpdater`
active (which shrinks rho as residuals converge).
"""

from __future__ import annotations

import math

import numpy as np

from .converger import Converger


class NormRhoConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        nro = opt.options.get("norm_rho_converger_options", {})
        self._verbose = bool(nro.get("verbose", False))

    def _compute_rho_norm(self) -> float:
        opt = self.opt
        return float(opt.probs @ opt.rho.sum(axis=1))

    def is_converged(self) -> bool:
        if not getattr(self.opt, "_norm_rho_update_inuse", False):
            raise RuntimeError(
                "NormRhoConverger can only be used if NormRhoUpdater is"
            )
        log_rho_norm = math.log(max(self._compute_rho_norm(), 1e-300))
        self.conv = log_rho_norm
        self.conv_value = log_rho_norm
        ret = log_rho_norm < self.opt.options["convthresh"]
        if self._verbose:
            print(f"log(|rho|) = {log_rho_norm}")
        return ret
