"""PrimalDualConverger: stop on primal AND dual PH residuals.

TPU-native analogue of ``mpisppy/convergers/primal_dual_converger.py:9-161``:
primal gap = sum_s p_s ||x_s - xbar||_1, dual gap = ||rho*(xbar_t -
xbar_{t-1})||_1; converged when max(primal, dual) <= tol.  Optionally tracks
the per-iteration gaps to CSV.
"""

from __future__ import annotations

import os

import numpy as np

from .converger import Converger


class PrimalDualConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        options = opt.options.get("primal_dual_converger_options", {})
        self._verbose = options.get("verbose", False)
        self.convergence_threshold = options.get("tol", 1)
        self.tracking = options.get("tracking", False)
        self.prev_xbars = np.array(opt.xbars, copy=True)
        self._rows = []
        self._results_folder = options.get("results_folder", "results")

    def _compute_primal_convergence(self) -> float:
        opt = self.opt
        xk = opt.nonants_of(opt.local_x)
        diff = np.abs(xk - opt.xbars).sum(axis=1)
        return float(opt.probs @ diff)

    def _compute_dual_residual(self) -> float:
        opt = self.opt
        # per-node terms: take scenario 0's view per slot scaled by rho; the
        # reference sums rho*|xbar_t - xbar_{t-1}| over local scenarios/nodes
        d = opt.rho * np.abs(opt.xbars - self.prev_xbars)
        return float(opt.probs @ d.sum(axis=1))

    def is_converged(self) -> bool:
        primal_gap = self._compute_primal_convergence()
        dual_gap = self._compute_dual_residual()
        self.prev_xbars = np.array(self.opt.xbars, copy=True)
        self.conv = max(primal_gap, dual_gap)
        self.conv_value = self.conv
        ret = self.conv <= self.convergence_threshold
        if self._verbose:
            print(f"primal gap = {round(primal_gap, 5)}, "
                  f"dual gap = {round(dual_gap, 5)}")
        if self.tracking:
            self._rows.append((self.opt._iter, primal_gap, dual_gap))
        return ret

    def post_everything(self):
        if self.tracking and self._rows:
            os.makedirs(self._results_folder, exist_ok=True)
            path = os.path.join(self._results_folder, "pd.csv")
            with open(path, "w") as f:
                f.write("iteration,primal_gap,dual_gap\n")
                for row in self._rows:
                    f.write(",".join(str(v) for v in row) + "\n")
