"""Converger ABC (mpisppy/convergers/converger.py:18-41).

A converger is a hub-internal stopping rule consulted each PH iteration
(phbase.py:925-934), distinct from the cross-cylinder gap-based termination.
"""


class Converger:
    def __init__(self, opt):
        self.opt = opt
        self.conv_value = None

    def convergence_value(self):
        return self.conv_value

    def is_converged(self) -> bool:
        raise NotImplementedError
