"""WTracker: moving-window statistics of the PH dual weights.

TPU-native analogue of ``mpisppy/utils/wtracker.py:18-203``: records W each
iteration and reports per-slot moving-window mean/stdev — a practical
stall/oscillation diagnostic for PH duals.
"""

from __future__ import annotations

import numpy as np


class WTracker:
    def __init__(self, opt):
        self.opt = opt
        self.iter_Ws = {}          # iteration -> (S, K) W snapshot

    def grab_local_Ws(self):
        """Snapshot current Ws (wtracker.py grab_local_Ws)."""
        self.iter_Ws[self.opt._iter] = np.array(self.opt.W, copy=True)

    def compute_moving_stats(self, wlen: int):
        """((S, K) mean, (S, K) stdev) over the trailing window."""
        if not self.iter_Ws:
            raise RuntimeError("WTracker has no W history")
        iters = sorted(self.iter_Ws)[-wlen:]
        stack = np.stack([self.iter_Ws[i] for i in iters])
        return stack.mean(axis=0), stack.std(axis=0)

    def report_by_moving_stats(self, wlen: int, reportlen=None,
                               stdevthresh=None, file=None):
        """Print slots whose windowed stdev exceeds the threshold
        (wtracker.py report_by_moving_stats)."""
        import sys

        out = file or sys.stdout
        if len(self.iter_Ws) < wlen:
            print(f"WTracker: only {len(self.iter_Ws)} iterations recorded, "
                  f"window is {wlen}; no report", file=out)
            return
        mean, std = self.compute_moving_stats(wlen)
        thresh = 0.0 if stdevthresh is None else stdevthresh
        bad = np.argwhere(std > thresh)
        print(f"WTracker report (window={wlen}): "
              f"{len(bad)} (scenario, slot) pairs above stdev "
              f"threshold {thresh}", file=out)
        for row in bad[: (reportlen or 100)]:
            s, k = row
            print(f"  scen {s} slot {k}: mean {mean[s, k]:.6g} "
                  f"stdev {std[s, k]:.6g}", file=out)

    def write_or_append_to_csv(self, fname: str):
        arrs = sorted(self.iter_Ws)
        with open(fname, "w") as f:
            f.write("iteration," + ",".join(
                f"w_{s}_{k}" for s in range(self.opt.W.shape[0])
                for k in range(self.opt.W.shape[1])) + "\n")
            for it in arrs:
                f.write(f"{it}," + ",".join(
                    repr(v) for v in self.iter_Ws[it].ravel()) + "\n")
