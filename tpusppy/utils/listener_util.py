"""Synchronizer: background-thread reduction engine (the APH listener).

TPU-native analogue of ``mpisppy/utils/listener_util/listener_util.py``
(333 LoC).  The reference runs a listener thread doing MPI Allreduces
concurrently with worker solves, guarding a data cache with a lock
(listener_util.py:80-320).  In the batched runtime global reductions are
cheap host einsums, so :class:`tpusppy.opt.aph.APH` runs them inline; this
class keeps the *architecture* available — a listener thread periodically
reducing worker-published contributions into a lock-guarded global cache —
for workloads where reductions genuinely overlap device solves (e.g.
cross-host DCN reductions).
"""

from __future__ import annotations

import threading
import time

import numpy as np


class Synchronizer:
    """(listener_util.py:53-330 semantics, single-host form).

    Workers publish named local contributions via
    :meth:`compute_global_data`; the listener thread sums the latest
    contribution of every registered worker into the global cache and runs
    the optional ``side_gig`` afterwards.
    """

    def __init__(self, lens: dict, asynch=True, sleep_secs=0.01):
        self.Lens = dict(lens)          # name -> vector length
        self.asynch = asynch
        self.sleep_secs = sleep_secs
        self._lock = threading.Lock()
        self._dirty = False             # new publications since last reduce
        self._locals = {}               # worker id -> {name: vector}
        self._global = {name: np.zeros(ln) for name, ln in self.Lens.items()}
        self.global_quitting = 0
        self.quitting = 0
        self.enable_side_gig = False
        self._listener = None
        self._side_gig = None

    # ---- worker side --------------------------------------------------------
    def compute_global_data(self, local_data: dict, global_out: dict = None,
                            enable_side_gig=False, worker_id=0,
                            rednames=None, keep_up=False):
        """Publish local contributions; read back the global cache."""
        with self._lock:
            slot = self._locals.setdefault(worker_id, {})
            for name, vec in local_data.items():
                if rednames is not None and name not in rednames:
                    continue
                slot[name] = np.array(vec, copy=True)
                self._dirty = True
            if enable_side_gig:
                self.enable_side_gig = True
            if global_out is not None:
                for name in global_out:
                    if name in self._global:
                        global_out[name][...] = self._global[name]
        if not self.asynch:
            self._reduce_once()
            if global_out is not None:
                with self._lock:
                    for name in global_out:
                        if name in self._global:
                            global_out[name][...] = self._global[name]
        return global_out

    def _unsafe_get_global_data(self, name, out: dict):
        out[name] = np.array(self._global[name], copy=True)

    def _unsafe_put_local_data(self, name, data: dict, worker_id=0):
        self._locals.setdefault(worker_id, {})[name] = np.array(
            data[name], copy=True)
        self._dirty = True

    # ---- listener side ------------------------------------------------------
    def _reduce_once(self):
        with self._lock:
            if not self._dirty:
                # nothing new published: reduction output would be
                # unchanged; skip the O(sum Lens) accumulation so an idle
                # listener tick costs nothing (it otherwise competes with
                # worker compute for the GIL)
                return
            self._dirty = False
            for name in self.Lens:
                acc = np.zeros(self.Lens[name])
                for slot in self._locals.values():
                    if name in slot:
                        acc += slot[name]
                self._global[name] = acc
            if self.enable_side_gig and self._side_gig is not None:
                self._side_gig(self)
                self.enable_side_gig = False

    def _listener_daemon(self):
        while self.global_quitting == 0:
            self._reduce_once()
            time.sleep(self.sleep_secs)
        self._reduce_once()

    def run(self, worker_fct, side_gig=None, **worker_kwargs):
        """Start the listener thread, run the worker, join
        (listener_util.py:82-103)."""
        self._side_gig = side_gig
        if self.asynch:
            self._listener = threading.Thread(
                target=self._listener_daemon, name="SynchronizerListener",
                daemon=True)
            self._listener.start()
        try:
            worker_fct(**worker_kwargs)
        finally:
            self.global_quitting = 1
            if self._listener is not None:
                self._listener.join(timeout=30)
