"""Find_Rho / Set_Rho: WW-heuristic rho from costs and nonant spreads.

TPU-native analogue of ``mpisppy/utils/find_rho.py:45-331``: per-variable rho
= |cost| / denominator, where the denominator is either the per-scenario
max(|x - xbar|, 2(x - xbar)^2) or the scenario-independent probability-
weighted spread, then condensed by an order statistic across scenarios.
"""

from __future__ import annotations

import numpy as np

from . import rho_utils


class Find_Rho:
    """(find_rho.py:45-220).  ``self.c``: {(sname, vname): cost} — from
    Find_Grad or a csv (cfg["grad_cost_file"])."""

    def __init__(self, ph_object, cfg):
        self.ph_object = ph_object
        self.cfg = cfg
        self.c = {}
        if cfg.get("grad_cost_file") and cfg.get("load_cost_file", False):
            import csv

            with open(cfg["grad_cost_file"]) as f:
                for row in csv.reader(f):
                    if not row or row[0].startswith("#"):
                        continue
                    self.c[(row[0], row[1])] = float(row[2])

    def _spread(self) -> np.ndarray:
        """(S, K) |x - xbar| at the current iterate."""
        opt = self.ph_object
        xk = opt.nonants_of(opt.local_x)
        return np.abs(xk - opt.xbars)

    def _w_denom(self) -> np.ndarray:
        """(S, K) w denominator (find_rho.py:78-96)."""
        return self._spread()

    def _prox_denom(self) -> np.ndarray:
        """(S, K) prox denominator (find_rho.py:98-116)."""
        return 2.0 * np.square(self._spread())

    def _grad_denom(self) -> np.ndarray:
        """(K,) scenario-independent denominator (find_rho.py:118-148)."""
        opt = self.ph_object
        denom = opt.probs @ self._spread()
        bound = 1.0 / self.cfg.get("rho_relative_bound", 1e3)
        return np.maximum(denom, bound)

    def _order_stat(self, rho_list) -> float:
        """(find_rho.py:150-168)"""
        alpha = self.cfg.get("order_stat", -1.0)
        assert alpha != -1.0, \
            "set the order statistic parameter for rho using --order-stat"
        assert 0 <= alpha <= 1, "0 is the min, 0.5 the average, 1 the max"
        rho_mean = float(np.mean(rho_list))
        rho_min = float(np.min(rho_list))
        rho_max = float(np.max(rho_list))
        if alpha == 0.5:
            return rho_mean
        if alpha < 0.5:
            return rho_min + alpha * 2 * (rho_mean - rho_min)
        return (2 * rho_mean - rho_max) + alpha * 2 * (rho_max - rho_mean)

    def compute_rho(self, indep_denom=False) -> dict:
        """{vname: rho} (find_rho.py:170-206)."""
        opt = self.ph_object
        S = opt.batch.num_scenarios
        K = opt.nonant_length
        vnames = _nonant_var_names(opt)
        if self.c:
            cost = np.zeros((S, K))
            for s, sname in enumerate(opt.all_scenario_names):
                for k, vname in enumerate(vnames):
                    cost[s, k] = self.c.get((sname, vname), 0.0)
        else:
            cost = np.abs(opt.batch.c[:, opt.tree.nonant_indices])
        if indep_denom:
            denom = np.broadcast_to(self._grad_denom()[None, :], (S, K))
        else:
            denom = np.maximum(self._w_denom(), self._prox_denom())
            denom = np.maximum(denom, 1.0 / self.cfg.get(
                "rho_relative_bound", 1e3))
        rho_sk = np.abs(cost / denom)
        return {vname: self._order_stat(rho_sk[:, k])
                for k, vname in enumerate(vnames)}

    def write_rho(self):
        """(find_rho.py:207-219)"""
        if not self.cfg.get("rho_file"):
            return
        rho_utils.rhos_to_csv(self.compute_rho(), self.cfg["rho_file"])


class Set_Rho:
    """rho_setter from a rho csv (find_rho.py:221-262)."""

    def __init__(self, cfg):
        self.cfg = cfg

    def rho_setter(self, batch):
        """(K,) rho over the packed nonant layout from cfg['rho_path']."""
        pairs = rho_utils.rho_list_from_csv(self.cfg["rho_path"])
        name_to_rho = dict(pairs)
        p0_names = batch.names if not hasattr(batch, "var_names") else None
        # map by position in the csv (written in nonant-slot order)
        return np.array([rho for _, rho in pairs])


def _nonant_var_names(opt):
    p0 = opt.scenario_creator(opt.all_scenario_names[0],
                              **opt.scenario_creator_kwargs)
    names = p0.var_names or [f"x[{j}]" for j in range(opt.batch.num_vars)]
    return [names[j] for j in opt.tree.nonant_indices]


def get_rho_from_W(mname, original_cfg):
    """CLI-style driver (find_rho.py:285-331)."""
    import importlib

    from ..opt.ph import PH

    m = importlib.import_module(mname) if isinstance(mname, str) else mname
    cfg = original_cfg
    names = m.scenario_names_creator(cfg["num_scens"])
    ph = PH(
        {"defaultPHrho": cfg.get("default_rho") or 1.0,
         "PHIterLimit": 2, "convthresh": -1.0},
        names, m.scenario_creator,
        scenario_creator_kwargs=m.kw_creator(cfg),
    )
    ph.ph_main(finalize=False)
    fr = Find_Rho(ph, cfg)
    fr.write_rho()
    return fr
