"""rho csv IO (mpisppy/utils/rho_utils.py, 37 LoC)."""

from __future__ import annotations

import csv


def rhos_to_csv(rho_dict, filename):
    """Write {vname: rho} rows as 'vname,rho'."""
    with open(filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["#Rho values"])
        for vname, rho in rho_dict.items():
            w.writerow([vname, repr(float(rho))])


def rho_list_from_csv(filename):
    """[(vname, rho)] from a rho csv."""
    out = []
    with open(filename) as f:
        for row in csv.reader(f):
            if not row or row[0].startswith("#"):
                continue
            out.append((row[0], float(row[1])))
    return out
