"""Amalgamator: fully declarative entry — a model module becomes a run.

TPU-native analogue of ``mpisppy/utils/amalgamator.py:100-451``.  A model
module exporting ``scenario_creator``, ``scenario_names_creator``,
``inparser_adder`` and ``kw_creator`` (checked at amalgamator.py:123-135) is
turned into either a direct EF solve or a full wheel spin, driven by a
:class:`~tpusppy.utils.config.Config`:

* ``cfg["EF_2stage"] / cfg["EF_mstage"]`` -> batched-ADMM EF solve;
* otherwise ``cfg["cylinders"]`` names the hub + spokes, each gated by its
  boolean flag (``cfg["lagrangian"]`` etc.), assembled via
  :mod:`tpusppy.utils.cfg_vanilla`.
"""

from __future__ import annotations

import copy
import importlib
import inspect

from .. import global_toc
from ..ef import solve_ef
from ..ir import ScenarioBatch
from ..scenario_tree import create_nodenames_from_branching_factors
from ..spin_the_wheel import WheelSpinner
from . import cfg_vanilla as vanilla
from .config import Config

# hub / spoke registries (amalgamator.py:60-99); multistage compatibility flags
hubs_and_multi_compatibility = {"ph": True, "aph": True, "lshaped": False}
spokes_and_multi_compatibility = {
    "fwph": False,
    "lagrangian": True,
    "lagranger": True,
    "xhatlooper": False,
    "xhatshuffle": True,
    "xhatspecific": True,
    "xhatxbar": True,
    "xhatlshaped": False,
    "slammax": False,
    "slammin": False,
    "cross_scenario_cuts": False,
}
default_unused_spokes = ["xhatlooper", "xhatspecific"]

extensions_classes = {}  # name -> add_<name> handled via vanilla when present


def _bool_option(cfg, oname):
    return oname in cfg and bool(cfg.get(oname))


def find_hub(cylinders, is_multi=False) -> str:
    hubs = set(cylinders) & set(hubs_and_multi_compatibility)
    if len(hubs) != 1:
        raise RuntimeError("There must be exactly one hub among cylinders")
    hub = hubs.pop()
    if is_multi and not hubs_and_multi_compatibility[hub]:
        raise RuntimeError(f"The hub {hub} does not work with multistage")
    return hub


def find_spokes(cylinders, is_multi=False) -> list:
    spokes = []
    for c in cylinders:
        if c in hubs_and_multi_compatibility:
            continue
        if c not in spokes_and_multi_compatibility:
            raise RuntimeError(f"Unknown cylinder {c}")
        if is_multi and not spokes_and_multi_compatibility[c]:
            raise RuntimeError(f"The spoke {c} does not work with multistage")
        if c in default_unused_spokes:
            print(f"{c} is unused by default; set --{c} to activate it")
        spokes.append(c)
    return spokes


def check_module_ama(module):
    """(amalgamator.py:123-135)"""
    missing = [
        e for e in ("scenario_names_creator", "scenario_creator",
                    "inparser_adder", "kw_creator")
        if not hasattr(module, e)
    ]
    if missing:
        raise RuntimeError(
            f"Module {module} not complete for from_module: missing {missing}"
        )


def Amalgamator_parser(cfg, inparser_adder, extraargs_fct=None,
                       use_command_line=True, args=None):
    """Populate cfg with the right option groups (amalgamator.py:183-250)."""
    if use_command_line:
        if _bool_option(cfg, "EF_2stage"):
            cfg.EF2()
        elif _bool_option(cfg, "EF_mstage"):
            cfg.EF_multistage()
            cfg.add_branching_factors()
        else:
            if _bool_option(cfg, "2stage"):
                cfg.popular_args()
            elif _bool_option(cfg, "mstage"):
                cfg.multistage()
            else:
                raise RuntimeError(
                    "The problem type (2stage or mstage) must be specified"
                )
            cfg.two_sided_args()
            cfg.mip_options()
            if "cylinders" not in cfg:
                raise RuntimeError("A cylinder list must be specified")
            for cylinder in cfg["cylinders"]:
                args_fct = getattr(cfg, cylinder + "_args", None)
                if args_fct is not None:
                    args_fct()
            for extension in cfg.get("extensions") or []:
                args_fct = getattr(cfg, extension + "_args", None)
                if args_fct is not None:
                    args_fct()
        inparser_adder(cfg)
        if extraargs_fct is not None:
            extraargs_fct()
        cfg.parse_command_line(cfg.get("program_name"), args=args)
    else:
        if not (_bool_option(cfg, "EF_2stage")
                or _bool_option(cfg, "EF_mstage")
                or "cylinders" in cfg):
            raise RuntimeError(
                "Bypassing the command line requires EF flags or cylinders"
            )
        if _bool_option(cfg, "EF_mstage") and "branching_factors" not in cfg:
            raise RuntimeError(
                "Multistage problems need cfg['branching_factors']"
            )
    return cfg


def from_module(mname, cfg, extraargs_fct=None, use_command_line=True,
                args=None):
    """(amalgamator.py:139-176).  ``args``: optional argv for testing."""
    if not isinstance(cfg, Config):
        raise RuntimeError(f"from_module bad cfg type={type(cfg)}")
    m = mname if inspect.ismodule(mname) else importlib.import_module(mname)
    check_module_ama(m)
    cfg = Amalgamator_parser(cfg, m.inparser_adder,
                             extraargs_fct=extraargs_fct,
                             use_command_line=use_command_line, args=args)
    if cfg.get("num_scens") is not None:
        cfg.add_and_assign("_mpisppy_probability", "Uniform prob.", float,
                           None, 1.0 / cfg["num_scens"])
    start = cfg.get("start") or 0
    sn = m.scenario_names_creator(cfg["num_scens"], start=start)
    dn = getattr(m, "scenario_denouement", None)
    return Amalgamator(cfg, sn, m.scenario_creator, m.kw_creator,
                       scenario_denouement=dn)


class Amalgamator:
    """(amalgamator.py:253-451)"""

    def __init__(self, cfg, scenario_names, scenario_creator, kw_creator,
                 scenario_denouement=None, verbose=True):
        self.cfg = cfg
        self.scenario_names = list(scenario_names)
        self.scenario_creator = scenario_creator
        self.scenario_denouement = scenario_denouement
        self.kw_creator = kw_creator
        self.kwargs = kw_creator(cfg)
        self.verbose = verbose
        self.is_EF = _bool_option(cfg, "EF_2stage") or _bool_option(
            cfg, "EF_mstage")
        self.is_multi = _bool_option(cfg, "EF_mstage") or _bool_option(
            cfg, "mstage")
        if self.is_multi and "all_nodenames" not in cfg:
            if "branching_factors" in cfg and cfg["branching_factors"]:
                ndnms = create_nodenames_from_branching_factors(
                    cfg["branching_factors"]
                )
                self.cfg.quick_assign("all_nodenames", list, ndnms)
            else:
                raise RuntimeError(
                    "Multistage needs branching_factors or all_nodenames"
                )

    def _build_batch(self) -> ScenarioBatch:
        return ScenarioBatch.from_problems([
            self.scenario_creator(nm, **(self.kwargs or {}))
            for nm in self.scenario_names
        ])

    def run(self):
        """Top-level execution (amalgamator.py:292-411)."""
        if self.is_EF:
            batch = self._build_batch()
            if self.verbose:
                global_toc("Starting EF solve")
            obj, x = solve_ef(batch, solver="admm")
            if self.verbose:
                global_toc("Completed EF solve")
            self.EF_Obj = obj
            self.is_minimizing = True
            self.best_outer_bound = obj
            self.best_inner_bound = obj
            self.ef = (batch, x)
            # nonant cache per node, like sputils.nonant_cache_from_ef
            tree = batch.tree
            root_slots = tree.nonant_indices[tree.nonant_stage == 1]
            self.xhats = {"ROOT": x[0][root_slots]}
            self.local_xhats = self.xhats
            self.first_stage_solution = {"ROOT": self.xhats["ROOT"]}
            return self

        hub_name = find_hub(self.cfg["cylinders"], self.is_multi)
        hub_creator = getattr(vanilla, hub_name + "_hub")
        beans = {
            "cfg": self.cfg,
            "scenario_creator": self.scenario_creator,
            "scenario_denouement": self.scenario_denouement,
            "all_scenario_names": self.scenario_names,
            "scenario_creator_kwargs": self.kwargs,
        }
        if self.is_multi:
            beans["all_nodenames"] = self.cfg["all_nodenames"]
        hub_dict = hub_creator(**beans)

        for extension in self.cfg.get("extensions") or []:
            extension_creator = getattr(vanilla, "add_" + extension, None)
            if extension_creator is not None:
                hub_dict = extension_creator(hub_dict, self.cfg)

        potential = find_spokes(self.cfg["cylinders"], self.is_multi)
        spokes = [s for s in potential if self.cfg.get(s)]
        list_of_spoke_dict = []
        for spoke in spokes:
            spoke_creator = getattr(vanilla, spoke + "_spoke")
            spoke_beans = copy.copy(beans)
            if spoke == "xhatspecific":
                spoke_beans["xhat_scenario_dict"] = self.cfg["scenario_dict"]
            list_of_spoke_dict.append(spoke_creator(**spoke_beans))

        ws = WheelSpinner(hub_dict, list_of_spoke_dict)
        ws.run()
        self.opt = ws.opt
        self.on_hub = True
        self.best_inner_bound = ws.BestInnerBound
        self.best_outer_bound = ws.BestOuterBound
        if "first_stage_solution_csv" in self.cfg:
            ws.write_first_stage_solution(self.cfg["first_stage_solution_csv"])
        if "tree_solution_csv" in self.cfg:
            ws.write_tree_solution(self.cfg["tree_solution_csv"])
        self.local_xhats = ws.local_nonant_cache
        if ws.local_nonant_cache is not None:
            tree = self.opt.tree
            self.first_stage_solution = {
                "ROOT": ws.local_nonant_cache[0][tree.nonant_stage == 1]
            }
        return self
