"""Solver-spec resolution: prefixed names + option-string parsing.

TPU-native analogue of ``mpisppy/utils/solver_spec.py:34-68``: a config may
carry ``solver_name``/``solver_options`` under several prefixes (e.g.
``EF_solver_name``); the first prefix in ``prefixes`` that has a name wins.
Option strings are space-delimited ``key=value`` pairs (config.py solver
options convention); values parse as int/float/bool when they look like one.
"""

from __future__ import annotations


def _coerce(v: str):
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def option_string_to_dict(ostr) -> dict:
    """'mipgap=0.01 threads=2' -> {'mipgap': 0.01, 'threads': 2}
    (sputils option_string_to_dict semantics)."""
    if not ostr:
        return {}
    if isinstance(ostr, dict):
        return dict(ostr)
    out = {}
    for tok in str(ostr).split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = _coerce(v)
        else:
            out[tok] = None
    return out


def solver_specification(cfg, prefixes=("",)) -> tuple:
    """(solver_name, solver_options dict) from the first matching prefix
    (solver_spec.py:34-68)."""
    if isinstance(prefixes, str):
        prefixes = (prefixes,)
    for p in prefixes:
        root = f"{p}_solver" if p else "solver"
        name = cfg.get(f"{root}_name")
        if name is not None:
            return name, option_string_to_dict(cfg.get(f"{root}_options"))
    # fall back to unprefixed
    return cfg.get("solver_name"), option_string_to_dict(
        cfg.get("solver_options")
    )
