"""sputils: reference-namespace compatibility aliases.

``mpisppy.utils.sputils`` is the most-imported helper module in reference
user code (``attach_root_node``, ``extract_num``,
``create_nodenames_from_BFs``, EF construction, solution writers).  The
tpusppy natives live where they architecturally belong (scenario_tree, ir,
ef, spin_the_wheel); this module re-exports them under the names a
migrating user will reach for, so ``from tpusppy.utils import sputils``
works like ``from mpisppy.utils import sputils`` (see
doc/porting_from_mpisppy.md).
"""

from __future__ import annotations

import numpy as np

from ..ef import build_ef, solve_ef
from ..scenario_tree import (ScenarioNode, attach_root_node,
                             create_nodenames_from_branching_factors,
                             extract_num)

__all__ = [
    "ScenarioNode", "attach_root_node", "extract_num",
    "create_nodenames_from_BFs", "create_nodenames_from_branching_factors",
    "create_EF", "build_ef", "solve_ef", "ef_nonants",
    "first_stage_nonant_npy_serializer", "write_ef_first_stage_solution",
    "option_string_to_dict",
]

# the reference's historical name (sputils.py:934)
create_nodenames_from_BFs = create_nodenames_from_branching_factors


def create_EF(scenario_names, scenario_creator, scenario_creator_kwargs=None,
              **ignored):
    """Reference-shaped EF constructor (sputils.py:127-341): returns the
    merged-column EF problem for the named scenarios."""
    from ..ir import ScenarioBatch

    kwargs = scenario_creator_kwargs or {}
    batch = ScenarioBatch.from_problems(
        [scenario_creator(nm, **kwargs) for nm in scenario_names])
    return build_ef(batch)


def ef_nonants(ef_or_batch):
    """Yield (node-ish name, var name, value) triples for a SOLVED EF —
    the reference's ``sputils.ef_nonants`` generator surface."""
    obj, x, batch = _solved(ef_or_batch)
    names = batch.var_names or [f"x[{j}]" for j in range(batch.num_vars)]
    root_slots = np.where(batch.tree.nonant_stage == 1)[0]
    for k in root_slots:
        j = int(batch.tree.nonant_indices[k])
        yield ("ROOT", names[j], float(x[0, j]))


def _solved(ef_or_batch):
    from ..ir import ScenarioBatch

    if isinstance(ef_or_batch, ScenarioBatch):
        obj, x = solve_ef(ef_or_batch, solver="highs")
        return obj, x, ef_or_batch
    raise TypeError(
        "pass the ScenarioBatch (tpusppy EFs are solved via ef.solve_ef)")


def first_stage_nonant_npy_serializer(batch, x, solution_file_name):
    """Write the root-stage nonant values as .npy (sputils.py:37-68)."""
    root_slots = np.where(batch.tree.nonant_stage == 1)[0]
    idx = batch.tree.nonant_indices[root_slots]
    np.save(solution_file_name, np.asarray(x)[0, idx])


write_ef_first_stage_solution = first_stage_nonant_npy_serializer


def option_string_to_dict(option_string):
    """Parse 'key=val key2=val2' solver-option strings (sputils surface)."""
    if not option_string:
        return None
    out = {}
    for tok in option_string.split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            try:
                out[k] = int(v)
            except ValueError:
                try:
                    out[k] = float(v)
                except ValueError:
                    out[k] = v
        else:
            out[tok] = True
    return out
