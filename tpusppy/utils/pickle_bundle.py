"""Proper-bundle serialization: save/load pre-built bundles to skip model
construction.

TPU-native analogue of ``mpisppy/utils/pickle_bundle.py`` (66 LoC): the
reference dill-pickles Pyomo bundle models; here a bundle is a tensor record,
so serialization is a plain ``.npz`` (faster and portable).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioProblem
from ..scenario_tree import ScenarioNode


def dill_pickle(problem: ScenarioProblem, fname: str):
    """Write a ScenarioProblem (bundle or scenario) to .npz
    (pickle_bundle.py:11-33 semantics)."""
    nd = problem.nodes[0]
    np.savez_compressed(
        fname,
        name=np.array(problem.name),
        c=problem.c, q2=problem.q2, A=problem.A, cl=problem.cl,
        cu=problem.cu, lb=problem.lb, ub=problem.ub, is_int=problem.is_int,
        prob=np.array(-1.0 if problem.prob is None else problem.prob),
        const=np.array(problem.const),
        nonant_indices=nd.nonant_indices,
    )


def dill_unpickle(fname: str) -> ScenarioProblem:
    """(pickle_bundle.py:35-46)"""
    if not fname.endswith(".npz"):
        fname = fname + ".npz"
    z = np.load(fname, allow_pickle=False)
    prob = float(z["prob"])
    return ScenarioProblem(
        name=str(z["name"]),
        c=z["c"], q2=z["q2"], A=z["A"], cl=z["cl"], cu=z["cu"],
        lb=z["lb"], ub=z["ub"], is_int=z["is_int"],
        prob=None if prob < 0 else prob,
        nodes=[ScenarioNode("ROOT", 1.0, 1, z["nonant_indices"])],
        const=float(z["const"]),
    )


def check_args(cfg):
    """Option sanity for pickled-bundle CLIs (pickle_bundle.py:48-66)."""
    if cfg.get("pickle_bundles_dir") and cfg.get("unpickle_bundles_dir"):
        raise RuntimeError(
            "Arguments pickle_bundles_dir and unpickle_bundles_dir are "
            "mutually exclusive"
        )
    if cfg.get("bundles_per_rank") and (cfg.get("pickle_bundles_dir")
                                        or cfg.get("unpickle_bundles_dir")):
        raise RuntimeError(
            "Proper bundles (pickle/unpickle dirs) cannot be combined with "
            "loose bundles_per_rank"
        )


def pickle_bundle_config(cfg):
    """Config group (pickle_bundle.py parser args)."""
    cfg.add_to_config("pickle_bundles_dir",
                      "write bundles here (default None)", str, None)
    cfg.add_to_config("unpickle_bundles_dir",
                      "read bundles from here (default None)", str, None)
    cfg.add_to_config("scenarios_per_bundle",
                      "used for pickle/unpickle (default None)", int, None)
