"""ScenarioStructure.dat semantics (reference: pysp_model/tree_structure.py).

Builds the scenario tree the PySP way: explicit Stages (time-ordered), Nodes
with NodeStage + Children + ConditionalProbability, Scenarios mapped to leaf
nodes, per-stage StageVariables (with ``name[*]``-style wildcards) and
StageCost expressions.  Validations mirror the reference's tree checks:
every non-root node has exactly one parent, children probabilities sum to 1,
each scenario's leaf sits in the last stage.

The output is deliberately in tpusppy vocabulary: per-scenario
:class:`~tpusppy.scenario_tree.ScenarioNode` lists use the ROOT/ROOT_i...
naming convention, so a PySP tree drops into the same machinery as
hand-annotated models.
"""

from __future__ import annotations

from .datparser import DatData, parse_dat_file


class ScenarioStructure:
    """Parsed + validated ScenarioStructure.dat."""

    def __init__(self, data: DatData):
        self.stages = [str(s) for s in data["Stages"]]
        self.nodes = [str(n) for n in data["Nodes"]]
        self.node_stage = {str(k): str(v)
                           for k, v in data["NodeStage"].items()}
        self.cond_prob = {str(k): float(v)
                          for k, v in data["ConditionalProbability"].items()}
        self.scenarios = [str(s) for s in data["Scenarios"]]
        self.scenario_leaf = {str(k): str(v)
                              for k, v in data["ScenarioLeafNode"].items()}
        self.children = {}
        for key, val in data.items():
            if key.startswith("Children[") and key.endswith("]"):
                self.children[key[len("Children["):-1]] = [str(c) for c in val]
        self.stage_vars = {}
        for key, val in data.items():
            if key.startswith("StageVariables[") and key.endswith("]"):
                self.stage_vars[key[len("StageVariables["):-1]] = [
                    str(v) for v in val]
        self.stage_cost = {str(k): str(v)
                           for k, v in data.get("StageCost", {}).items()}
        self._validate()
        self._index()

    @classmethod
    def from_file(cls, path: str) -> "ScenarioStructure":
        return cls(parse_dat_file(path))

    @classmethod
    def from_networkx(cls, G) -> "ScenarioStructure":
        """PySP's networkx scenario-tree form (the
        ``pysp_scenario_tree_model_callback`` returning a ``DiGraph`` —
        ref ``instance_factory.py`` / ``tree_structure_model.py``): nodes
        carry ``variables``/``cost`` attributes, edges carry ``weight``
        conditional probabilities, leaves are the scenarios (scenario name
        = leaf name, PySP's default naming).
        """
        roots = [n for n in G.nodes if G.in_degree(n) == 0]
        if len(roots) != 1:
            raise ValueError(f"scenario tree must have one root: {roots}")
        root = roots[0]
        depth = {root: 0}
        order = [root]
        for nd in order:
            for c in G.successors(nd):
                depth[c] = depth[nd] + 1
                order.append(c)
        nstages = max(depth.values()) + 1
        stages = [f"Stage{d + 1}" for d in range(nstages)]
        data = {
            "Stages": stages,
            "Nodes": order,
            "NodeStage": {nd: stages[depth[nd]] for nd in order},
            "ConditionalProbability": {root: 1.0, **{
                c: float(G.edges[p, c].get("weight", 1.0))
                for p, c in G.edges}},
        }
        leaves = [nd for nd in order if G.out_degree(nd) == 0]
        data["Scenarios"] = list(leaves)
        data["ScenarioLeafNode"] = {nd: nd for nd in leaves}
        for nd in order:
            kids = list(G.successors(nd))
            if kids:
                data[f"Children[{nd}]"] = kids
        # node-attached variables/cost roll up to their stage (PySP keeps
        # them per-node but requires stage-consistency; enforce it)
        cost = {}
        for d in range(nstages):
            vs: list = []
            for nd in order:
                if depth[nd] != d:
                    continue
                for v in G.nodes[nd].get("variables", ()):
                    if v not in vs:
                        vs.append(v)
                c = G.nodes[nd].get("cost")
                if c is not None:
                    prev = cost.setdefault(stages[d], str(c))
                    if prev != str(c):
                        raise ValueError(
                            f"nodes of {stages[d]} disagree on cost: "
                            f"{prev} vs {c}")
            if vs:
                data[f"StageVariables[{stages[d]}]"] = vs
        if cost:
            data["StageCost"] = cost
        return cls(data)

    # ---- validation (tree_structure.py checks) --------------------------
    def _validate(self):
        parents = {}
        for p, kids in self.children.items():
            for c in kids:
                if c in parents:
                    raise ValueError(f"node {c} has two parents")
                parents[c] = p
        roots = [nd for nd in self.nodes if nd not in parents]
        if len(roots) != 1:
            raise ValueError(f"expected exactly one root node, got {roots}")
        self.root = roots[0]
        self.parent = parents
        for nd in self.nodes:
            if nd not in self.node_stage:
                raise ValueError(f"node {nd} has no NodeStage entry")
            if nd not in self.cond_prob:
                raise ValueError(f"node {nd} has no ConditionalProbability")
        if abs(self.cond_prob[self.root] - 1.0) > 1e-6:
            raise ValueError(
                f"root node conditional probability must be 1.0, got "
                f"{self.cond_prob[self.root]} (scenario probabilities would "
                "silently fail to sum to 1)")
        for p, kids in self.children.items():
            tot = sum(self.cond_prob[c] for c in kids)
            if abs(tot - 1.0) > 1e-4:
                raise ValueError(
                    f"children probabilities of {p} sum to {tot}, not 1")
        last = self.stages[-1]
        for s in self.scenarios:
            leaf = self.scenario_leaf.get(s)
            if leaf is None:
                raise ValueError(f"scenario {s} has no ScenarioLeafNode")
            if self.node_stage[leaf] != last:
                raise ValueError(
                    f"scenario {s} leaf {leaf} is not in the last stage")

    # ---- indexing -------------------------------------------------------
    def _index(self):
        # canonical ROOT/ROOT_i names: children keep .dat order
        self.canon = {self.root: "ROOT"}

        def walk(nd):
            for i, c in enumerate(self.children.get(nd, [])):
                base = self.canon[nd]
                self.canon[c] = ("ROOT_" + str(i)) if base == "ROOT" \
                    else f"{base}_{i}"
                walk(c)

        walk(self.root)
        self.stage_index = {s: i + 1 for i, s in enumerate(self.stages)}

    def node_path(self, scenario: str):
        """Root->leaf node-name path of a scenario."""
        nd = self.scenario_leaf[scenario]
        path = [nd]
        while nd in self.parent:
            nd = self.parent[nd]
            path.append(nd)
        return list(reversed(path))

    def scenario_probability(self, scenario: str) -> float:
        p = 1.0
        for nd in self.node_path(scenario):
            p *= self.cond_prob[nd]
        return p

    def match_stage_vars(self, stage: str, var_names: list) -> list:
        """Resolve a stage's StageVariables (exact names or ``name[*]``
        wildcards, PySP semantics) against a model's variable names;
        returns indices in var_names order."""
        import re

        pats = self.stage_vars.get(stage, [])
        out = []
        for pat in pats:
            if "*" in pat:
                # literal brackets, '*' as a glob (PySP wildcard semantics;
                # fnmatch would misread '[...]' as a character class)
                rx = re.escape(pat).replace(r"\*", ".*")
                hits = [i for i, nm in enumerate(var_names)
                        if nm is not None and re.fullmatch(rx, nm)]
                if not hits:
                    raise ValueError(
                        f"StageVariables pattern {pat!r} matches nothing")
                out.extend(hits)
            else:
                if pat not in var_names:
                    raise ValueError(
                        f"StageVariables entry {pat!r} not a model variable")
                out.append(var_names.index(pat))
        return out

    def nodes_of_stage(self, stage: str):
        return [nd for nd in self.nodes if self.node_stage[nd] == stage]
