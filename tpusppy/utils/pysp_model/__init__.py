"""PySP-format model ingestion (Pyomo-less).

TPU-native analogue of ``mpisppy/utils/pysp_model/`` (~3.7k LoC in the
reference: ``pysp_model.py``, ``instance_factory.py:1``,
``tree_structure.py:1``).  The reference turns old-PySP inputs — a Pyomo
``ReferenceModel``, a ``ScenarioStructure.dat`` tree file, and per-scenario
or per-node AMPL ``.dat`` data files — into mpi-sppy scenario creators.

This package keeps the PySP DATA side byte-compatible (full parser for the
AMPL .dat subset PySP uses; the ScenarioStructure tree grammar with stages,
nodes, children, conditional probabilities, scenario->leaf maps, wildcard
StageVariables) while replacing the Pyomo side with the builder protocol:
the user's ReferenceModel becomes a callable

    instance_creator(data: dict, scenario_name: str) -> ScenarioProblem

taking the parsed .dat data (sets/params as dicts).  :class:`PySPModel`
then provides ``scenario_creator``/``all_scenario_names``/... exactly like
the reference's wrapper, with nonant annotations derived from
StageVariables instead of hand-written ``attach_root_node`` calls.
"""

from .datparser import parse_dat_file, parse_dat_text
from .tree_structure import ScenarioStructure
from .pysp_model import PySPModel

__all__ = [
    "parse_dat_file", "parse_dat_text", "ScenarioStructure", "PySPModel",
]
