"""Restricted Pyomo-compatible AbstractModel shim: old PySP ReferenceModel.py
files run UNCHANGED (no Pyomo in the image, none needed).

The reference ingests a Pyomo ``ReferenceModel.py`` + ``.dat`` data through
``mpisppy/utils/pysp_model/instance_factory.py`` (888 LoC over the full
Pyomo stack).  Here the LINEAR modeling subset PySP models actually use is
reimplemented directly against the tpusppy IR: ``load_reference_model``
executes the user's model file with ``pyomo.environ`` mapped to this
module, the declared ``AbstractModel`` is instantiated per scenario from
parsed ``.dat`` data (:mod:`.datparser`), and every constraint/objective
rule is evaluated over affine expression objects that lower straight to a
:class:`~tpusppy.ir.ScenarioProblem`.

Supported surface (the PySP test fixtures + typical PySP models):
``AbstractModel``/``ConcreteModel``, ``Set`` (initialize/within/dimen),
``RangeSet``, ``Param`` (initialize/default/mutable/within, any arity),
``Var`` (index sets, bounds tuple or rule, within domains), ``Expression``,
``Objective`` (rule, sense), ``Constraint`` (rule; ``Constraint.Skip``;
tuple ``(lo, body, hi)`` or ``inequality``), ``minimize``/``maximize``,
``value``, ``summation``/``sum_product``.  Nonlinear expressions raise.
"""

from __future__ import annotations

import numbers

import numpy as np

INF = float("inf")


# ---------------------------------------------------------------------------
# affine expressions
# ---------------------------------------------------------------------------

class LinExpr:
    """Affine expression: sum coefs[var] * var + const."""

    __slots__ = ("coefs", "const")

    def __init__(self, coefs=None, const=0.0):
        self.coefs = dict(coefs or {})
        self.const = float(const)

    @staticmethod
    def of(v):
        if isinstance(v, LinExpr):
            return v
        if isinstance(v, numbers.Number):
            return LinExpr({}, float(v))
        raise TypeError(
            f"non-affine or unsupported term in expression: {v!r} "
            "(the PySP shim supports linear models only)")

    def _add(self, other, sign):
        other = LinExpr.of(other)
        coefs = dict(self.coefs)
        for k, c in other.coefs.items():
            coefs[k] = coefs.get(k, 0.0) + sign * c
        return LinExpr(coefs, self.const + sign * other.const)

    def __add__(self, other):
        return self._add(other, 1.0)

    __radd__ = __add__

    def __sub__(self, other):
        return self._add(other, -1.0)

    def __rsub__(self, other):
        return (-self)._add(other, 1.0)

    def __neg__(self):
        return LinExpr({k: -c for k, c in self.coefs.items()}, -self.const)

    def __pos__(self):
        return self

    def __mul__(self, other):
        if not isinstance(other, numbers.Number):
            raise TypeError(
                "product of two expressions is nonlinear; the PySP shim "
                "supports linear models only")
        s = float(other)
        return LinExpr({k: c * s for k, c in self.coefs.items()},
                       self.const * s)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self.__mul__(1.0 / float(other))

    def __le__(self, other):
        d = self._add(other, -1.0)
        return Relation(LinExpr(d.coefs), -INF, -d.const)

    def __ge__(self, other):
        d = self._add(other, -1.0)
        return Relation(LinExpr(d.coefs), -d.const, INF)

    def __eq__(self, other):  # noqa: A003 - Pyomo semantics
        d = self._add(other, -1.0)
        return Relation(LinExpr(d.coefs), -d.const, -d.const)

    __hash__ = None


class Relation:
    """lo <= body <= hi with the constant folded into lo/hi."""

    __slots__ = ("body", "lo", "hi")

    def __init__(self, body, lo, hi):
        self.body = body
        self.lo = float(lo)
        self.hi = float(hi)


def inequality(lower, body, upper):
    body = LinExpr.of(body)
    return Relation(LinExpr(body.coefs), float(lower) - body.const,
                    float(upper) - body.const)


def value(v):
    if isinstance(v, LinExpr):
        if v.coefs:
            raise ValueError("value() of a non-constant expression")
        return v.const
    return float(v)


class _MutableParam:
    """Scalar ``Param(mutable=True)``: an object with a ``.value`` slot, so
    the PySP callback idiom ``instance.p.value = 2.0`` works
    (instance_factory fixtures set mutable params AFTER create_instance and
    before the solve).  Honored because rule lowering re-reads values at
    ``to_problem`` time (:meth:`_Instance._rebuild_rules`)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = float(value)

    def __float__(self):
        return float(self.value)

    def __int__(self):
        return int(self.value)

    def __repr__(self):
        return f"_MutableParam({self.value})"

    def __add__(self, o):
        return float(self) + o if isinstance(o, numbers.Number) \
            else NotImplemented

    __radd__ = __add__

    def __sub__(self, o):
        return float(self) - o if isinstance(o, numbers.Number) \
            else NotImplemented

    def __rsub__(self, o):
        return o - float(self)

    def __mul__(self, o):
        return float(self) * o if isinstance(o, numbers.Number) \
            else NotImplemented

    __rmul__ = __mul__

    def __truediv__(self, o):
        return float(self) / o

    def __rtruediv__(self, o):
        return o / float(self)

    def __neg__(self):
        return -float(self)

    def __le__(self, o):
        return LinExpr({}, float(self)).__le__(o)

    def __ge__(self, o):
        return LinExpr({}, float(self)).__ge__(o)


# LinExpr.of / the linearity checks accept any numbers.Number; a mutable
# param IS a number that happens to be settable
numbers.Number.register(_MutableParam)


# ---------------------------------------------------------------------------
# domains
# ---------------------------------------------------------------------------

class _Domain:
    def __init__(self, lb=-INF, ub=INF, integer=False):
        self.lb, self.ub, self.integer = lb, ub, integer


Reals = _Domain()
NonNegativeReals = _Domain(lb=0.0)
NonPositiveReals = _Domain(ub=0.0)
PositiveReals = _Domain(lb=0.0)
Integers = _Domain(integer=True)
NonNegativeIntegers = _Domain(lb=0.0, integer=True)
PositiveIntegers = _Domain(lb=1.0, integer=True)
Binary = _Domain(lb=0.0, ub=1.0, integer=True)
Boolean = Binary
UnitInterval = _Domain(lb=0.0, ub=1.0)
PercentFraction = UnitInterval
Any = _Domain()

minimize = 1
maximize = -1


# ---------------------------------------------------------------------------
# abstract components
# ---------------------------------------------------------------------------

class _Component:
    def __init__(self, *index_sets, **kw):
        self.index_sets = index_sets
        self.kw = kw
        self.name = None


class Set(_Component):
    pass


class RangeSet(_Component):
    def __init__(self, *bounds, **kw):
        super().__init__(**kw)
        self.bounds = bounds


class Param(_Component):
    pass


class Var(_Component):
    pass


class Expression(_Component):
    pass


class Objective(_Component):
    pass


class _Skip:
    pass


class Constraint(_Component):
    Skip = _Skip()
    Feasible = _Skip()


def summation(*terms):
    """summation(c, x) = sum_i c[i]*x[i]; summation(x) = sum_i x[i]."""
    if len(terms) == 1:
        acc = LinExpr()
        for v in terms[0].values():
            acc = acc + v
        return acc
    if len(terms) == 2:
        c, x = terms
        acc = LinExpr()
        for k in x:
            acc = acc + float(c[k]) * x[k]
        return acc
    raise TypeError("summation supports 1 or 2 args in the PySP shim")


sum_product = summation
dot_product = summation


class AbstractModel:
    """Collects component declarations in order; ``create_instance`` builds
    a concrete, data-resolved instance."""

    def __init__(self, *a, **kw):
        object.__setattr__(self, "_decls", [])

    def __setattr__(self, name, comp):
        if isinstance(comp, _Component):
            comp.name = name
            self._decls.append(comp)
            object.__setattr__(self, name, comp)
        else:
            object.__setattr__(self, name, comp)

    def create_instance(self, data=None, name="instance"):
        return _Instance(self, data or {}, name)


ConcreteModel = AbstractModel


# ---------------------------------------------------------------------------
# instance construction
# ---------------------------------------------------------------------------

class _ParamView(dict):
    def __init__(self, items, default=None):
        super().__init__(items)
        self._default = default

    def __missing__(self, key):
        if self._default is not None:
            return self._default
        raise KeyError(key)

    def values(self):  # iteration order = key order
        return [self[k] for k in self]


class _VarView:
    """Indexed variable accessor: x[i] / x[i, j] -> LinExpr references."""

    def __init__(self, name, keys):
        self._name = name
        self._keys = list(keys)

    def _vname(self, key):
        if isinstance(key, tuple):
            return f"{self._name}[{','.join(str(k) for k in key)}]"
        return f"{self._name}[{key}]"

    def __getitem__(self, key):
        return LinExpr({self._vname(key): 1.0})

    def __iter__(self):
        return iter(self._keys)

    def keys(self):
        return list(self._keys)

    def values(self):
        return [self[k] for k in self._keys]


class _ExprView(dict):
    pass


def _index_product(sets):
    if not sets:
        return [()]
    out = [()]
    for s in sets:
        out = [t + (v,) for t in out for v in s]
    return out


def _resolve_index_sets(inst, comp):
    sets = []
    for s in comp.index_sets:
        if isinstance(s, _Component):
            sets.append(inst._sets[s.name])
        elif isinstance(s, (list, tuple, range)):
            sets.append(list(s))
        else:
            raise TypeError(f"bad index set for {comp.name}: {s!r}")
    return sets


class _Instance:
    def __init__(self, model, data, name):
        self.name = name
        self._sets = {}
        self._vars = {}      # name -> (keys, lb, ub, integer) per flat key
        self._var_order = []
        self._cons = []      # (name, Relation)
        self._rule_decls = []
        self._has_mutable = False
        self._objective = None
        self._obj_sense = minimize
        get = data.get if hasattr(data, "get") else lambda k, d=None: d

        for comp in model._decls:
            kw = comp.kw
            if isinstance(comp, RangeSet):
                if comp.name in data:
                    vals = list(data[comp.name])
                elif len(comp.bounds) == 1:
                    vals = list(range(1, int(_val(self, comp.bounds[0])) + 1))
                else:
                    vals = list(range(int(_val(self, comp.bounds[0])),
                                      int(_val(self, comp.bounds[1])) + 1))
                self._sets[comp.name] = vals
                setattr(self, comp.name, vals)
            elif isinstance(comp, Set):
                if comp.name in data:
                    vals = list(data[comp.name])
                else:
                    init = kw.get("initialize")
                    if callable(init):
                        init = init(self)
                    vals = list(init) if init is not None else []
                self._sets[comp.name] = vals
                setattr(self, comp.name, vals)
            elif isinstance(comp, Param):
                self._build_param(comp, data)
            elif isinstance(comp, (Var, Expression, Constraint, Objective)):
                # value-consuming components are REBUILDABLE: mutable
                # params may be assigned between create_instance and the
                # solve (the PySP callback idiom), so to_problem
                # re-evaluates vars (bounds rules!) and every rule against
                # current values (_rebuild_rules)
                self._rule_decls.append(comp)
            else:
                raise TypeError(f"unsupported component {comp!r}")
        self._rebuild_rules()

    def _rebuild_rules(self):
        """(Re-)evaluate var bounds, expressions, constraints and the
        objective in declaration order against the CURRENT param values —
        Pyomo semantics for ``mutable=True`` params updated after
        ``create_instance`` (bounds included: Pyomo resolves them at
        solve time)."""
        self._cons = []
        self._var_order = []
        self._objective = None
        for comp in self._rule_decls:
            if isinstance(comp, Var):
                self._build_var(comp)
            elif isinstance(comp, Expression):
                self._build_expression(comp)
            elif isinstance(comp, Constraint):
                self._build_constraint(comp)
            else:
                self._build_objective(comp)

    # ---- components -----------------------------------------------------
    def _build_param(self, comp, data):
        kw = comp.kw
        sets = _resolve_index_sets(self, comp)
        default = kw.get("default")
        init = kw.get("initialize")
        src = data[comp.name] if comp.name in data else None
        if not sets:
            if src is not None:
                v = float(src) if isinstance(src, numbers.Number) else src
            elif init is not None:
                v = init(self) if callable(init) else init
            elif default is not None:
                v = default
            else:
                raise ValueError(f"no value for scalar Param {comp.name}")
            if kw.get("mutable"):
                v = _MutableParam(float(v))
                self._has_mutable = True
            setattr(self, comp.name, v)
            return
        keys = _index_product(sets)
        flat = [k[0] if len(k) == 1 else k for k in keys]
        items = {}
        for k in flat:
            if src is not None and hasattr(src, "get") and k in src:
                items[k] = src[k]
            elif src is not None and hasattr(src, "get") and k not in src \
                    and getattr(src, "_default", None) is not None:
                items[k] = src[k]
            elif callable(init):
                items[k] = init(self, *(k if isinstance(k, tuple) else (k,)))
            elif isinstance(init, dict):
                items[k] = init[k]
            elif init is not None:
                items[k] = init
            elif default is not None:
                items[k] = default
            else:
                raise ValueError(f"no value for Param {comp.name}[{k}]")
        if kw.get("mutable"):
            # the _ParamView dict is LIVE — `inst.d[k] = v` updates it in
            # place and rules re-read it — so post-assignment honoring only
            # needs the rebuild flag
            self._has_mutable = True
        setattr(self, comp.name, _ParamView(items, default))

    def _build_var(self, comp):
        kw = comp.kw
        sets = _resolve_index_sets(self, comp)
        dom = kw.get("within", kw.get("domain", Reals))
        bounds = kw.get("bounds")
        if not sets:
            lb, ub = dom.lb, dom.ub
            if bounds is not None:
                b = bounds(self) if callable(bounds) else bounds
                lb = max(lb, _num(b[0], -INF))
                ub = min(ub, _num(b[1], INF))
            self._var_order.append((comp.name, lb, ub, dom.integer))
            setattr(self, comp.name, LinExpr({comp.name: 1.0}))
            return
        keys = _index_product(sets)
        flat = [k[0] if len(k) == 1 else k for k in keys]
        view = _VarView(comp.name, flat)
        for k in flat:
            lb, ub = dom.lb, dom.ub
            if bounds is not None:
                b = (bounds(self, *(k if isinstance(k, tuple) else (k,)))
                     if callable(bounds) else bounds)
                lb = max(lb, _num(b[0], -INF))
                ub = min(ub, _num(b[1], INF))
            self._var_order.append((view._vname(k), lb, ub, dom.integer))
        setattr(self, comp.name, view)

    def _build_expression(self, comp):
        rule = comp.kw.get("rule", comp.kw.get("initialize"))
        sets = _resolve_index_sets(self, comp)
        if not sets:
            setattr(self, comp.name, LinExpr.of(rule(self)))
            return
        keys = _index_product(sets)
        view = _ExprView()
        for k in keys:
            kk = k[0] if len(k) == 1 else k
            view[kk] = LinExpr.of(rule(self, *k))
        setattr(self, comp.name, view)

    def _build_constraint(self, comp):
        rule = comp.kw.get("rule", comp.kw.get("expr"))
        sets = _resolve_index_sets(self, comp)
        for k in _index_product(sets):
            rel = rule(self, *k) if callable(rule) else rule
            if isinstance(rel, _Skip):
                continue
            if isinstance(rel, tuple):
                rel = inequality(_num(rel[0], -INF), rel[1],
                                 _num(rel[2], INF))
            if not isinstance(rel, Relation):
                raise TypeError(
                    f"constraint {comp.name}[{k}] rule returned {rel!r}")
            self._cons.append((comp.name, rel))

    def _build_objective(self, comp):
        if self._objective is not None:
            raise ValueError("multiple objectives are not supported")
        rule = comp.kw.get("rule", comp.kw.get("expr"))
        self._obj_sense = comp.kw.get("sense", minimize)
        self._objective = LinExpr.of(rule(self) if callable(rule) else rule)

    # ---- lowering -------------------------------------------------------
    def to_problem(self, name=None):
        """Lower to a :class:`tpusppy.ir.ScenarioProblem`.

        Rules are re-evaluated first so mutable-param assignments made
        after ``create_instance`` (``instance.p.value = ...``, the PySP
        callback idiom) are reflected — matching Pyomo, where expressions
        hold the param OBJECT and see its current value at solve time.
        Models without mutable params skip the rebuild (rule evaluation
        over index products dominates build time at family scale).
        """
        if self._has_mutable:
            self._rebuild_rules()
        from ...ir import LinearModelBuilder

        b = LinearModelBuilder(name or self.name)
        index = {}
        for (vn, lb, ub, is_int) in self._var_order:
            index[vn] = b.add_var(vn, lb=lb, ub=ub, integer=is_int)
        sense = 1.0 if self._obj_sense == minimize else -1.0
        for vn, ccoef in self._objective.coefs.items():
            b.set_cost(index[vn], sense * ccoef)
        b.const = sense * self._objective.const
        for (cn, rel) in self._cons:
            coeffs = {index[vn]: c for vn, c in rel.body.coefs.items()
                      if c != 0.0}
            b.add_row(coeffs, rel.lo, rel.hi)
        return b.build()


def _num(v, default):
    return default if v is None else float(v)


def _val(inst, v):
    if isinstance(v, _Component):
        return getattr(inst, v.name)
    return v


# ---------------------------------------------------------------------------
# model-file loading (the instance_factory entry)
# ---------------------------------------------------------------------------

def load_reference_module(path):
    """Execute a PySP ``ReferenceModel.py`` with ``pyomo.environ`` mapped to
    this shim; returns the module NAMESPACE (model + any PySP callbacks:
    ``pysp_instance_creation_callback``,
    ``pysp_scenario_tree_model_callback`` — instance_factory.py:200-360
    discovers the same names).
    """
    import sys
    import types

    fake_env = types.ModuleType("pyomo.environ")
    for k, v in globals().items():
        if not k.startswith("_"):
            fake_env.__dict__[k] = v
    fake_pyomo = types.ModuleType("pyomo")
    fake_pyomo.environ = fake_env
    fake_core = types.ModuleType("pyomo.core")
    fake_core.__dict__.update(fake_env.__dict__)
    fake_pyomo.core = fake_core

    saved = {k: sys.modules.get(k)
             for k in ("pyomo", "pyomo.environ", "pyomo.core")}
    sys.modules["pyomo"] = fake_pyomo
    sys.modules["pyomo.environ"] = fake_env
    sys.modules["pyomo.core"] = fake_core
    try:
        ns = {"__file__": path, "__name__": "_pysp_reference_model"}
        with open(path) as f:
            code = compile(f.read(), path, "exec")
        exec(code, ns)
    finally:
        for k, v in saved.items():
            if v is None:
                sys.modules.pop(k, None)
            else:
                sys.modules[k] = v
    return ns


def load_reference_model(path):
    """The declared AbstractModel of a ReferenceModel.py (conventionally
    named ``model``, else the unique AbstractModel global)."""
    return _model_from_ns(load_reference_module(path), path)


def _model_from_ns(ns, where):
    mdl = ns.get("model")
    if not isinstance(mdl, AbstractModel):
        cands = [v for v in ns.values() if isinstance(v, AbstractModel)]
        if len(cands) != 1:
            raise ValueError(
                f"{where} must declare exactly one AbstractModel "
                "(conventionally named 'model')")
        mdl = cands[0]
    return mdl


def reference_model_creator(path_or_model):
    """``instance_creator(data, scenario_name)`` for a ReferenceModel.py —
    plugs straight into :class:`~tpusppy.utils.pysp_model.PySPModel`.
    Accepts a path OR an already-loaded AbstractModel (so callers that ran
    ``load_reference_module`` for callback discovery don't execute the
    user's module — and its side effects — twice)."""
    if isinstance(path_or_model, AbstractModel):
        mdl = path_or_model
    else:
        mdl = load_reference_model(path_or_model)

    def creator(data, scenario_name):
        return mdl.create_instance(data, scenario_name).to_problem(
            scenario_name)

    return creator
