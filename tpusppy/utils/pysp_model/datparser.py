"""AMPL ``.dat`` parser for the subset PySP inputs use.

Covers what appears across the reference's PySP examples and test fixtures
(sslp/hydro data dirs, pysp_model/tests/testdata): comments, simple and
indexed sets, scalar params, keyed params (one or more key columns), and
tabular ``param NAME : c1 c2 ... :=`` matrices.  Everything lands in plain
python dicts — the data surface the Pyomo-less instance creators consume.

Grammar subset::

    # comment to end of line
    set NAME := tok tok ... ;
    set NAME[idx] := tok ... ;
    param NAME := value ;                      # scalar
    param NAME := key value key value ... ;    # 1-key
    param NAME := k1 k2 value ... ;            # n-key (arity passed by caller
                                               #        or inferred per name)
    param NAME default V := ... ;
    param NAME : col col ... := row v v ... ;  # tabular -> {(row, col): v}
"""

from __future__ import annotations

import re


def _tokens(text: str):
    text = re.sub(r"#[^\n]*", " ", text)
    # ':=' and ';' and ':' are their own tokens; brackets stay attached to
    # names (PySP set names like Children[root] and values like x[*])
    text = text.replace(":=", " := ").replace(";", " ; ")
    text = re.sub(r"(?<![:\[]):(?!=)", " : ", text)
    return text.split()


def _coerce(tok: str):
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            return tok


class DefaultedDict(dict):
    """Keyed param with an AMPL ``default`` clause: missing keys return the
    default (PySP/AMPL sparse-param semantics)."""

    def __init__(self, default, items=()):
        super().__init__(items)
        self.default = default

    def __missing__(self, key):
        return self.default

    def get(self, key, fallback=None):  # dict.get bypasses __missing__
        return super().get(key, self.default if fallback is None else fallback)


class DatData(dict):
    """Parsed .dat contents: name -> value.

    Sets are lists; scalar params are numbers/strings; keyed params are
    dicts (tuple keys for arity > 1); tabular params are dicts keyed by
    (row, col).  ``merge`` implements PySP's node-data layering (later files
    override/extend earlier ones, as Pyomo's per-node instance construction
    does).
    """

    def merge(self, other: "DatData"):
        for k, v in other.items():
            if k in self and isinstance(self[k], dict) and isinstance(v, dict):
                merged = {**self[k], **v}
                # a default clause survives layering (later file's wins)
                if isinstance(v, DefaultedDict):
                    merged = DefaultedDict(v.default, merged)
                elif isinstance(self[k], DefaultedDict):
                    merged = DefaultedDict(self[k].default, merged)
                self[k] = merged
            elif k in self and isinstance(self[k], list) and isinstance(v, list):
                self[k] = self[k] + [e for e in v if e not in self[k]]
            else:
                self[k] = v
        return self


def parse_dat_text(text: str, param_arity=None) -> DatData:
    """Parse .dat text; ``param_arity`` maps param name -> number of key
    columns for n-key params (default inferred: scalar if one token, else
    1-key pairs)."""
    param_arity = dict(param_arity or {})
    toks = _tokens(text)
    out = DatData()
    i = 0
    n = len(toks)

    def until_semicolon(j):
        k = j
        while k < n and toks[k] != ";":
            k += 1
        return toks[j:k], k + 1

    while i < n:
        t = toks[i]
        if t == "set":
            name = toks[i + 1]
            if toks[i + 2] != ":=":
                raise ValueError(f"set {name}: expected ':='")
            body, i = until_semicolon(i + 3)
            out[name] = [_coerce(b) for b in body]
        elif t == "param":
            if toks[i + 1] == ":":
                # unnamed AMPL table ``param: A B C := key v v v ... ;`` —
                # each column is its own param keyed by the row key(s) (the
                # reference UC datasets' fleet/Demand/ReserveRequirement
                # form).  Key arity from param_arity via the FIRST column.
                j = i + 2
                cols = []
                while toks[j] != ":=":
                    cols.append(str(toks[j]))
                    j += 1
                body, i = until_semicolon(j + 1)
                arity = int(param_arity.get(cols[0], 1))
                w = arity + len(cols)
                if len(body) % w != 0:
                    raise ValueError(
                        f"param: {cols}: ragged table ({len(body)} toks)")
                store = {c: out.setdefault(c, {}) for c in cols}
                for r in range(0, len(body), w):
                    key = tuple(_coerce(b) for b in body[r:r + arity])
                    if arity == 1:
                        key = key[0]
                    for c, colname in enumerate(cols):
                        store[colname][key] = _coerce(body[r + arity + c])
                continue
            name = toks[i + 1]
            j = i + 2
            default = None
            if toks[j] == "default":
                default = _coerce(toks[j + 1])
                j += 2
            if toks[j] == ":":
                # tabular: columns up to ':=', then rows of key(s) + values.
                # Key arity defaults to 1; multi-key rows (the UC datasets'
                # ``param: Demand :=`` is (bus, hour) -> value) pass their
                # arity through param_arity exactly like keyed params.
                j += 1
                cols = []
                while toks[j] != ":=":
                    cols.append(_coerce(toks[j]))
                    j += 1
                body, i = until_semicolon(j + 1)
                d = {}
                arity = int(param_arity.get(name, 1))
                w = len(cols) + arity
                if len(body) % w != 0:
                    raise ValueError(f"param {name}: ragged table")
                single = len(cols) == 1 and cols[0] == name
                for r in range(0, len(body), w):
                    key = tuple(_coerce(b) for b in body[r:r + arity])
                    if arity == 1:
                        key = key[0]
                    for c, col in enumerate(cols):
                        val = _coerce(body[r + arity + c])
                        if single:
                            d[key] = val
                        elif arity == 1:
                            d[(key, col)] = val
                        else:
                            d[key + (col,)] = val
                out[name] = d if default is None else DefaultedDict(default, d)
            else:
                if toks[j] != ":=":
                    raise ValueError(f"param {name}: expected ':='")
                body, i = until_semicolon(j + 1)
                if len(body) == 1 and name not in param_arity \
                        and default is None:
                    out[name] = _coerce(body[0])
                else:
                    arity = int(param_arity.get(name, 1))
                    w = arity + 1
                    if len(body) % w != 0:
                        raise ValueError(
                            f"param {name}: {len(body)} tokens not "
                            f"divisible by key arity {arity} + 1")
                    d = {}
                    for r in range(0, len(body), w):
                        key = tuple(_coerce(b) for b in body[r:r + arity])
                        if arity == 1:
                            key = key[0]
                        d[key] = _coerce(body[r + arity])
                    out[name] = (d if default is None
                                 else DefaultedDict(default, d))
        elif t == ";":
            i += 1
        else:
            raise ValueError(f"unexpected token {t!r} in .dat input")
    return out


def parse_dat_file(path: str, param_arity=None) -> DatData:
    with open(path) as f:
        return parse_dat_text(f.read(), param_arity)
