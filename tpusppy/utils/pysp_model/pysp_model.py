"""PySPModel: PySP inputs -> tpusppy scenario-creator protocol.

Analogue of ``mpisppy/utils/pysp_model/pysp_model.py`` (which wraps the
reference's instance_factory + tree_structure to expose
``scenario_creator``/``all_scenario_names``/...).  Data layout support, as
in PySP:

- scenario-based: one ``<ScenarioName>.dat`` per scenario, optionally
  layered over a shared ``ReferenceModel.dat``/``RootNode.dat``;
- node-based: one ``<NodeName>.dat`` per tree node; a scenario's data is
  the root->leaf merge of its node files (later stages override).

The Pyomo ReferenceModel becomes ``instance_creator(data, name) ->
ScenarioProblem``: a python callable building the model from the parsed
.dat data dicts.  Nonanticipativity comes from ScenarioStructure's
StageVariables (wildcards supported), turned into per-scenario
:class:`~tpusppy.scenario_tree.ScenarioNode` lists with canonical
ROOT/ROOT_i names — so Amalgamator, WheelSpinner, EF, and the confidence
machinery all work unchanged on PySP-sourced models.
"""

from __future__ import annotations

import os

import numpy as np

from ...scenario_tree import ScenarioNode
from .datparser import DatData, parse_dat_file
from .tree_structure import ScenarioStructure


class PySPModel:
    """``PySPModel(instance_creator, scenario_structure, data_dir)``.

    - ``instance_creator``: callable ``(data: DatData, scenario_name) ->
      ScenarioProblem`` (a module exposing ``pysp_instance_creator`` also
      works) — the Pyomo-less ReferenceModel; OR a path to an actual Pyomo
      ``ReferenceModel.py``, ingested unchanged through the restricted
      AbstractModel shim (:mod:`.abstract_model`);
    - ``scenario_structure``: path to ScenarioStructure.dat (or a parsed
      :class:`ScenarioStructure`);
    - ``data_dir``: directory of the .dat files (defaults to the structure
      file's directory).
    """

    def __init__(self, instance_creator, scenario_structure=None,
                 data_dir=None, param_arity=None):
        self._callback = None
        if isinstance(instance_creator, (str, os.PathLike)):
            instance_creator = os.fspath(instance_creator)
            # a path to an actual Pyomo ReferenceModel.py: ingest it through
            # the restricted AbstractModel shim (abstract_model.py) — old
            # PySP models run unchanged, like the reference's
            # instance_factory.py does with real Pyomo.  The module's PySP
            # callbacks are discovered by name, exactly like
            # instance_factory.py:200-360:
            #   pysp_instance_creation_callback(tree, name, node_names)
            #     builds instances (mutable-param updates honored);
            #   pysp_scenario_tree_model_callback() may supply the tree
            #     itself (networkx DiGraph form), replacing
            #     ScenarioStructure.dat entirely.
            from .abstract_model import (load_reference_module,
                                         reference_model_creator)

            model_path = instance_creator
            ns = load_reference_module(model_path)
            self._callback = ns.get("pysp_instance_creation_callback")
            if self._callback is None:
                # hand the ALREADY-loaded model over: re-executing the
                # user's module would double its side effects + build time
                from .abstract_model import _model_from_ns

                instance_creator = reference_model_creator(
                    _model_from_ns(ns, model_path))
            if scenario_structure is None:
                tree_cb = ns.get("pysp_scenario_tree_model_callback")
                if tree_cb is None:
                    raise ValueError(
                        "no scenario_structure given and the model module "
                        "has no pysp_scenario_tree_model_callback")
                scenario_structure = ScenarioStructure.from_networkx(
                    tree_cb())
                data_dir = data_dir or os.path.dirname(
                    os.path.abspath(model_path))
        elif hasattr(instance_creator, "pysp_instance_creator"):
            instance_creator = instance_creator.pysp_instance_creator
        if scenario_structure is None:
            # only path-based modules can supply the tree via callback;
            # fail HERE rather than deep inside the .dat parser
            raise ValueError(
                "scenario_structure is required for callable instance "
                "creators (tree callbacks come from ReferenceModel.py "
                "paths)")
        self._creator = instance_creator
        if isinstance(scenario_structure, ScenarioStructure):
            self.structure = scenario_structure
            self._dir = data_dir
        else:
            self.structure = ScenarioStructure.from_file(scenario_structure)
            self._dir = data_dir or os.path.dirname(
                os.path.abspath(scenario_structure))
        if self._dir is None and self._callback is None:
            raise ValueError("data_dir required with a parsed structure")
        self._arity = param_arity

    # ---- data loading ---------------------------------------------------
    def _read(self, fname) -> DatData | None:
        """Parse (and memoize) one data file; shared files would otherwise
        be re-parsed once per scenario at batch construction."""
        cache = getattr(self, "_file_cache", None)
        if cache is None:
            cache = self._file_cache = {}
        if fname not in cache:
            path = os.path.join(self._dir, fname)
            cache[fname] = (parse_dat_file(path, self._arity)
                            if os.path.exists(path) else None)
        # parsed data is read-only by contract (merge copies on collision,
        # so cached entries are never mutated by layering)
        return cache[fname]

    def scenario_data(self, scenario_name: str) -> DatData:
        """Parsed data for one scenario (scenario-based preferred, else
        node-based merge along the root->leaf path)."""
        data = DatData()
        for shared in ("ReferenceModel.dat", "RootNode.dat"):
            d = self._read(shared)
            if d:
                data.merge(d)
        own = self._read(f"{scenario_name}.dat")
        if own is not None:
            return data.merge(own)
        merged_any = False
        for nd in self.structure.node_path(scenario_name):
            d = self._read(f"{nd}.dat")
            if d is not None:
                data.merge(d)
                merged_any = True
        if not merged_any:
            # shared data alone would make every scenario identical — the
            # stochastic program silently degenerating to its mean problem
            # is exactly the failure this must catch (e.g. node filenames
            # not matching the structure's node names)
            raise FileNotFoundError(
                f"no scenario-specific data for {scenario_name}: neither "
                f"{scenario_name}.dat nor node files found in {self._dir}")
        return data

    # ---- the tpusppy protocol (pysp_model.py surface) -------------------
    @property
    def all_scenario_names(self):
        return list(self.structure.scenarios)

    def scenario_names_creator(self, num_scens=None, start=0):
        names = self.all_scenario_names
        if num_scens is None:
            return names[start:]
        return names[start:start + num_scens]

    def kw_creator(self, cfg=None, **kwargs):
        return {}

    @staticmethod
    def scenario_denouement(rank, scenario_name, scenario):
        pass

    def scenario_creator(self, scenario_name, **kwargs):
        st = self.structure
        prob = st.scenario_probability(scenario_name)
        if self._callback is not None:
            # instance_factory.py:200-360: the callback builds the instance
            # itself (its own data, typically mutable-param assignments);
            # .dat scenario data is not consulted
            inst = self._callback(st, scenario_name,
                                  st.node_path(scenario_name))
            mdl = inst.to_problem(scenario_name)
        else:
            mdl = self._creator(self.scenario_data(scenario_name),
                                scenario_name)
        if mdl.var_names is None:
            raise ValueError(
                "pysp instance creators must build via LinearModelBuilder "
                "(variable names are needed to resolve StageVariables)")
        nodes = []
        path = st.node_path(scenario_name)
        for nd in path[:-1]:               # nonleaf nodes carry nonants
            stage_name = st.node_stage[nd]
            idx = st.match_stage_vars(stage_name, mdl.var_names)
            # dedup: an explicit entry may overlap a wildcard (legal PySP);
            # duplicates would inflate K and double-count xbar averages
            nodes.append(ScenarioNode(
                st.canon[nd], st.cond_prob[nd], st.stage_index[stage_name],
                np.asarray(sorted(set(idx)), dtype=np.int32)))
        mdl.nodes = nodes
        mdl.prob = prob
        return mdl
