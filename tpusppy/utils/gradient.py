"""Find_Grad: gradient-based cost extraction at a candidate xhat.

TPU-native analogue of ``mpisppy/utils/gradient.py:44-253``.  The reference
computes objective gradients through pynumero's C++ ASL interface
(gradient.py:30,65-82); here the objective is a traced JAX function of x, so
the gradient is ``jax.grad`` — free on TPU and exact for the quadratic IR.
"""

from __future__ import annotations

import csv

import jax
import jax.numpy as jnp
import numpy as np

from ..confidence_intervals import ciutils
from . import rho_utils


class Find_Grad:
    """(gradient.py:44-180)"""

    def __init__(self, ph_object, cfg):
        self.ph_object = ph_object
        self.cfg = cfg
        self.c = {}          # {(sname, vname): gradient cost}

    def compute_grad(self, xhat_cache=None) -> np.ndarray:
        """(S, K) objective gradients w.r.t. nonant slots at the candidate
        (gradient.py:65-82): fix, solve, differentiate."""
        opt = self.ph_object
        if xhat_cache is not None:
            saved = (opt._warm, opt.local_x, opt.pri_res, opt.dua_res)
            opt.fix_nonants(xhat_cache)
            try:
                x = opt.solve_loop(warm=False)
            finally:
                opt.restore_nonants()
                opt._warm, opt.local_x, opt.pri_res, opt.dua_res = saved
        else:
            x = opt.local_x
        b = opt.batch

        def scen_obj(xs, c, q2):
            return jnp.dot(c, xs) + 0.5 * jnp.dot(q2, xs * xs)

        grads = jax.vmap(jax.grad(scen_obj))(
            jnp.asarray(x), jnp.asarray(b.c), jnp.asarray(b.q2))
        return np.asarray(grads)[:, opt.tree.nonant_indices]

    def find_grad_cost(self):
        """(gradient.py:84-123)"""
        if not self.cfg.get("grad_cost_file"):
            return
        if not self.cfg.get("xhatpath"):
            raise RuntimeError(
                "to compute gradient cost, give an xhat path via --xhatpath")
        xhat = ciutils.read_xhat(self.cfg["xhatpath"])
        opt = self.ph_object
        cache = ciutils._root_cache_to_full(opt, xhat)
        grads = self.compute_grad(cache)
        vnames = self._var_names()
        self.c = {
            (sname, vnames[k]): float(grads[s, k])
            for s, sname in enumerate(opt.all_scenario_names)
            for k in range(grads.shape[1])
        }

    def _var_names(self):
        opt = self.ph_object
        p0 = opt.scenario_creator(opt.all_scenario_names[0],
                                  **opt.scenario_creator_kwargs)
        names = p0.var_names or [f"x[{j}]" for j in range(opt.batch.num_vars)]
        return [names[j] for j in opt.tree.nonant_indices]

    def write_grad_cost(self):
        """(gradient.py:125-145)"""
        self.find_grad_cost()
        fname = self.cfg["grad_cost_file"]
        with open(fname, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["#grad cost values"])
            for (sname, vname), val in self.c.items():
                w.writerow([sname, vname, repr(val)])

    def find_grad_rho(self):
        """(gradient.py:146-158): rho from gradient costs via Find_Rho."""
        from .find_rho import Find_Rho

        fr = Find_Rho(self.ph_object, self.cfg)
        fr.c = self.c
        return fr.compute_rho()

    def write_grad_rho(self):
        """(gradient.py:159-180)"""
        rho = self.find_grad_rho()
        rho_utils.rhos_to_csv(rho, self.cfg["grad_rho_file"])


def grad_cost_and_rho(mname, original_cfg):
    """CLI-style driver (gradient.py:204-253): build PH, write both files."""
    import importlib

    from ..opt.ph import PH

    m = importlib.import_module(mname) if isinstance(mname, str) else mname
    cfg = original_cfg
    names = m.scenario_names_creator(cfg["num_scens"])
    ph = PH(
        {"defaultPHrho": cfg.get("default_rho") or 1.0,
         "PHIterLimit": 0, "convthresh": -1.0},
        names, m.scenario_creator,
        scenario_creator_kwargs=m.kw_creator(cfg),
    )
    ph.Iter0()
    fg = Find_Grad(ph, cfg)
    fg.write_grad_cost()
    if cfg.get("grad_rho_file"):
        fg.write_grad_rho()
    return fg
