"""cfg_vanilla: Config -> hub/spoke dict factories.

TPU-native analogue of ``mpisppy/utils/cfg_vanilla.py:41-637``: every factory
returns the dict a :class:`~tpusppy.spin_the_wheel.WheelSpinner` consumes.
Names and structure mirror the reference so driver scripts port mechanically:
``ph_hub``, ``lagrangian_spoke``, ``lagranger_spoke``, ``xhatlooper_spoke``,
``xhatshuffle_spoke``, ``xhatxbar_spoke``, ``xhatspecific_spoke``,
``slammax_spoke``, ``slammin_spoke``, plus ``extension_adder``.
"""

from __future__ import annotations

import copy

from ..cylinders import (
    FrankWolfeOuterBound,
    LagrangerOuterBound,
    LagrangianOuterBound,
    PHHub,
    SlamMaxHeuristic,
    SlamMinHeuristic,
    XhatLooperInnerBound,
    XhatRestrictedEF,
    XhatShuffleInnerBound,
    XhatSpecificInnerBound,
    XhatXbarInnerBound,
)
from ..extensions.extension import MultiExtension
from ..opt.ph import PH
from ..phbase import PHBase
from ..xhat_eval import Xhat_Eval
from .solver_spec import option_string_to_dict


def _hasit(cfg, name):
    return name in cfg and cfg.get(name) is not None


def _admm_solver_options(cfg) -> dict:
    """Translate Config solver knobs into ADMMSettings-shaped options.

    ``solver_options`` strings may carry ADMMSettings field names directly
    (e.g. 'max_iter=500 dtype=float32'); the admm_* fields map onto them.
    """
    so = option_string_to_dict(cfg.get("solver_options"))
    if _hasit(cfg, "admm_dtype"):
        so.setdefault("dtype", cfg.admm_dtype)
    if _hasit(cfg, "admm_max_iter"):
        so.setdefault("max_iter", cfg.admm_max_iter)
    if _hasit(cfg, "admm_restarts"):
        so.setdefault("restarts", cfg.admm_restarts)
    if _hasit(cfg, "admm_eps"):
        so.setdefault("eps_abs", cfg.admm_eps)
        so.setdefault("eps_rel", cfg.admm_eps)
    if _hasit(cfg, "admm_sweep_precision"):
        so.setdefault("sweep_precision", cfg.admm_sweep_precision)
    if _hasit(cfg, "admm_pipeline"):
        so.setdefault("pipeline", bool(cfg.admm_pipeline))
    if _hasit(cfg, "admm_megastep"):
        so.setdefault("megastep", int(cfg.admm_megastep))
    return so


def resilience_hub_options(cfg) -> dict:
    """Hub-side resilience options from a Config (the ``resilience_args``
    group): checkpoint cadence + resume + degradation knobs, threaded
    into ``hub_kwargs["options"]`` by the hub builders so any
    Config-driven CLI gets preemption-safe wheels with two flags
    (doc/resilience.md)."""
    out = {}
    for k in ("checkpoint_dir", "checkpoint_every_secs",
              "checkpoint_every_iters", "checkpoint_keep", "resume",
              "spoke_timeout_secs", "strict_spokes"):
        if _hasit(cfg, k):
            out[k] = cfg.get(k)
    return out


def shared_options(cfg) -> dict:
    """The option dict every cylinder starts from (cfg_vanilla.py:41-63).

    Also the observability entry point for Config-driven CLIs: a truthy
    ``cfg.tracing`` (see :meth:`Config.tracing_args`) arms the flight
    recorder exactly like ``TPUSPPY_TRACE=<path>``, and ``cfg.log_level``
    sets the ``tpusppy`` logger level.  A ``tune_cache`` field arms the
    persistent autotuner verdict store (TPUSPPY_TUNE_CACHE semantics)."""
    from ..obs import log as _obs_log
    from ..obs import trace as _trace

    _trace.maybe_enable_from_config(cfg)
    if cfg.get("log_level"):
        _obs_log.set_level(cfg.get("log_level"))
    if cfg.get("tune_cache"):
        from .. import tune as _tune

        _tune.set_cache_path(cfg.get("tune_cache"))
    shoptions = {
        "solver_name": cfg.get("solver_name"),
        "solver_options": _admm_solver_options(cfg),
        "defaultPHrho": cfg.get("default_rho"),
        "convthresh": 0,
        "PHIterLimit": cfg.get("max_iterations", 1),
        "verbose": cfg.get("verbose", False),
        "display_progress": cfg.get("display_progress", False),
        "display_convergence_detail": cfg.get(
            "display_convergence_detail", False),
        "tee-rank0-solves": cfg.get("tee_rank0_solves", False),
        "trace_prefix": cfg.get("trace_prefix"),
    }
    if _hasit(cfg, "ph_device_state"):
        # the O(1)-host wheel posture (doc/scaling.md)
        shoptions["ph_device_state"] = bool(cfg.ph_device_state)
    return shoptions


def add_multistage_options(cylinder_dict, all_nodenames, branching_factors):
    """(cfg_vanilla.py:64-75)"""
    cylinder_dict = copy.deepcopy(cylinder_dict)
    if branching_factors is not None:
        cylinder_dict["opt_kwargs"].setdefault("options", {})[
            "branching_factors"] = branching_factors
        if all_nodenames is None:
            from ..scenario_tree import create_nodenames_from_branching_factors

            all_nodenames = create_nodenames_from_branching_factors(
                branching_factors[:-1]
            )
    if all_nodenames is not None:
        cylinder_dict["opt_kwargs"]["all_nodenames"] = all_nodenames
    return cylinder_dict


def ph_hub(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    ph_extensions=None,
    extension_kwargs=None,
    ph_converger=None,
    rho_setter=None,
    variable_probability=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:77-127)"""
    options = shared_options(cfg)
    options["convthresh"] = cfg.get("intra_hub_conv_thresh", 1e-10)
    options["bundles_per_rank"] = cfg.get("bundles_per_rank", 0)
    if _hasit(cfg, "cross_scenario_cuts") and cfg.cross_scenario_cuts:
        from ..cylinders import CrossScenarioHub

        hub_class = CrossScenarioHub
    else:
        hub_class = PHHub
    hub_dict = {
        "hub_class": hub_class,
        "hub_kwargs": {"options": {
            "rel_gap": cfg.get("rel_gap"),
            "abs_gap": cfg.get("abs_gap"),
            "max_stalled_iters": cfg.get("max_stalled_iters"),
            **resilience_hub_options(cfg),
        }},
        "opt_class": PH,
        "opt_kwargs": {
            "options": options,
            "all_scenario_names": all_scenario_names,
            "scenario_creator": scenario_creator,
            "scenario_creator_kwargs": scenario_creator_kwargs,
            "scenario_denouement": scenario_denouement,
            "rho_setter": rho_setter,
            "variable_probability": variable_probability,
            "extensions": ph_extensions,
            "extension_kwargs": extension_kwargs,
            "ph_converger": ph_converger,
            "all_nodenames": all_nodenames,
        },
    }
    # drop gap options the cfg does not carry (hub ignores missing keys)
    hub_dict["hub_kwargs"]["options"] = {
        k: v for k, v in hub_dict["hub_kwargs"]["options"].items()
        if v is not None
    }
    # adaptive-rho posture (cfg.ph_args): per-slot rho adaptation from
    # primal/dual residual balance, so families certify without a
    # hand-tuned --default-rho (sslp needed rho=100 before this).
    # Posture defaults (vs the reference's conservative updater defaults):
    # pd_factor 10 — at 100 the update rarely fires and rho never leaves a
    # bad start (sslp probe: gap 14% at pd=100 vs 4.4% at pd=10, robust
    # across default_rho 1..5); drivers can override via norm_rho_options.
    if _hasit(cfg, "adaptive_rho") and cfg.adaptive_rho and not (
            _hasit(cfg, "no_adaptive_rho") and cfg.no_adaptive_rho):
        from ..extensions.norm_rho_updater import NormRhoUpdater

        extension_adder(hub_dict, NormRhoUpdater)
        hub_dict["opt_kwargs"]["options"].setdefault(
            "norm_rho_options", {"primal_dual_difference_factor": 10.0})
    return hub_dict


def aph_hub(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    ph_extensions=None,
    extension_kwargs=None,
    rho_setter=None,
    variable_probability=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:128-163): ph_hub with the APH classes and options."""
    from ..cylinders import APHHub
    from ..opt.aph import APH

    hub_dict = ph_hub(
        cfg, scenario_creator, scenario_denouement, all_scenario_names,
        scenario_creator_kwargs=scenario_creator_kwargs,
        ph_extensions=ph_extensions, extension_kwargs=extension_kwargs,
        rho_setter=rho_setter, variable_probability=variable_probability,
        all_nodenames=all_nodenames,
    )
    hub_dict["hub_class"] = APHHub
    hub_dict["opt_class"] = APH
    opts = hub_dict["opt_kwargs"]["options"]
    opts["APHgamma"] = cfg.get("aph_gamma", 1.0)
    opts["APHnu"] = cfg.get("aph_nu", 1.0)
    opts["async_frac_needed"] = cfg.get("aph_frac_needed", 1.0)
    opts["dispatch_frac"] = cfg.get("aph_dispatch_frac", 1.0)
    opts["async_sleep_secs"] = cfg.get("aph_sleep_seconds", 0.01)
    return hub_dict


def lshaped_hub(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py lshaped_hub semantics): two-stage Benders hub."""
    from ..cylinders import LShapedHub
    from ..opt.lshaped import LShapedMethod

    options = shared_options(cfg)
    options["max_iter"] = cfg.get("max_iterations", 50)
    options["tol"] = cfg.get("intra_hub_conv_thresh", 1e-7)
    return {
        "hub_class": LShapedHub,
        "hub_kwargs": {"options": {
            **{k: v for k, v in {
                "rel_gap": cfg.get("rel_gap"),
                "abs_gap": cfg.get("abs_gap"),
            }.items() if v is not None},
            **resilience_hub_options(cfg),
        }},
        "opt_class": LShapedMethod,
        "opt_kwargs": {
            "options": options,
            "all_scenario_names": all_scenario_names,
            "scenario_creator": scenario_creator,
            "scenario_creator_kwargs": scenario_creator_kwargs,
        },
    }


def xhatlshaped_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:529-553)"""
    from ..cylinders import XhatLShapedInnerBound

    return _xhat_spoke(
        cfg, XhatLShapedInnerBound, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
    )


def extension_adder(hub_dict, ext_class):
    """Attach an extension class, composing with MultiExtension when several
    are requested (cfg_vanilla.py:164-190)."""
    ok = hub_dict["opt_kwargs"]
    cur = ok.get("extensions")
    if cur is None:
        ok["extensions"] = ext_class
    elif cur is MultiExtension:
        kws = ok.setdefault("extension_kwargs", {"ext_classes": []})
        if ext_class not in kws["ext_classes"]:
            kws["ext_classes"].append(ext_class)
    else:
        first = cur
        ok["extensions"] = MultiExtension
        ok["extension_kwargs"] = {"ext_classes": [first, ext_class]}
    return hub_dict


def _spoke_opt_kwargs(cfg, scenario_creator, all_scenario_names,
                      scenario_creator_kwargs, all_nodenames, options):
    return {
        "options": options,
        "all_scenario_names": all_scenario_names,
        "scenario_creator": scenario_creator,
        "scenario_creator_kwargs": scenario_creator_kwargs,
        "all_nodenames": all_nodenames,
    }


def fwph_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:277-319)"""
    from ..fwph import FWPH

    options = shared_options(cfg)
    fw_options = {
        "FW_iter_limit": cfg.get("fwph_iter_limit", 10),
        "FW_weight": cfg.get("fwph_weight", 0.0),
        "FW_conv_thresh": cfg.get("fwph_conv_thresh", 1e-4),
        "stop_check_tol": cfg.get("fwph_stop_check_tol", 1e-4),
        "solver_name": cfg.get("solver_name"),
        "FW_verbose": cfg.get("verbose", False),
    }
    return {
        "spoke_class": FrankWolfeOuterBound,
        "spoke_kwargs": {},
        "opt_class": FWPH,
        "opt_kwargs": {
            "options": options,
            "FW_options": fw_options,
            "all_scenario_names": all_scenario_names,
            "scenario_creator": scenario_creator,
            "scenario_creator_kwargs": scenario_creator_kwargs,
            "all_nodenames": all_nodenames,
        },
    }


def lagrangian_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    rho_setter=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:320-355)"""
    options = shared_options(cfg)
    return {
        "spoke_class": LagrangianOuterBound,
        "spoke_kwargs": {},
        "opt_class": PHBase,
        "opt_kwargs": {
            **_spoke_opt_kwargs(cfg, scenario_creator, all_scenario_names,
                                scenario_creator_kwargs, all_nodenames,
                                options),
            "rho_setter": rho_setter,
        },
    }


def lagranger_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    rho_setter=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:356-392)"""
    options = shared_options(cfg)
    if _hasit(cfg, "lagranger_rho_rescale_factors_json"):
        options["lagranger_rho_rescale_factors_json"] = \
            cfg.lagranger_rho_rescale_factors_json
    return {
        "spoke_class": LagrangerOuterBound,
        "spoke_kwargs": {},
        "opt_class": PHBase,
        "opt_kwargs": {
            **_spoke_opt_kwargs(cfg, scenario_creator, all_scenario_names,
                                scenario_creator_kwargs, all_nodenames,
                                options),
            "rho_setter": rho_setter,
        },
    }


def _xhat_spoke(cfg, spoke_class, scenario_creator, all_scenario_names,
                scenario_creator_kwargs, all_nodenames, extra_options=None):
    options = shared_options(cfg)
    options.update(extra_options or {})
    return {
        "spoke_class": spoke_class,
        "spoke_kwargs": {},
        "opt_class": Xhat_Eval,
        "opt_kwargs": _spoke_opt_kwargs(
            cfg, scenario_creator, all_scenario_names,
            scenario_creator_kwargs, all_nodenames, options),
    }


def xhatlooper_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:393-423)"""
    return _xhat_spoke(
        cfg, XhatLooperInnerBound, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
        {"xhat_looper_options": {
            "xhat_solver_options": {},
            "scen_limit": cfg.get("xhat_scen_limit", 3),
            "dump_prefix": "delme",
            "csvname": "looper.csv",
        }},
    )


def xhatshuffle_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:457-494)"""
    return _xhat_spoke(
        cfg, XhatShuffleInnerBound, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
        {"xhat_looper_options": {
            "xhat_solver_options": {},
            "scen_limit": cfg.get("xhat_scen_limit", 3),
            "reverse": cfg.get("add_reversed_shuffle", False),
            "iter_step": cfg.get("xhatshuffle_iter_step"),
        }},
    )


def xhatrestrictedef_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """tpusppy addition (no reference analogue): relax-and-fix restricted-EF
    incumbents — consensus-confident integers fixed, contested ones MILPed
    over a scenario subsample, result evaluated on the full batch.  The
    incumbent mechanism of choice when naive rounding of the hub consensus
    violates coupling rows (e.g. cardinality constraints)."""
    return _xhat_spoke(
        cfg, XhatRestrictedEF, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
        {"xhat_ef_options": {
            "every": cfg.get("xhat_ef_every", 4),
            "ksub": cfg.get("xhat_ef_ksub", 6),
            "time_limit": cfg.get("xhat_ef_time_limit", 60.0),
        }},
    )


def xhatspecific_spoke(
    cfg,
    scenario_creator,
    xhat_scenario_dict,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:495-528)"""
    return _xhat_spoke(
        cfg, XhatSpecificInnerBound, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
        {"xhat_specific_options": {
            "xhat_solver_options": {},
            "xhat_scenario_dict": xhat_scenario_dict,
            "csvname": "specific.csv",
        }},
    )


def xhatxbar_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:424-456)"""
    return _xhat_spoke(
        cfg, XhatXbarInnerBound, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
        {"xhat_xbar_options": {"xhat_solver_options": {}, "csvname": "xbar.csv"}},
    )


def cross_scenario_cuts_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:602-637)"""
    from ..cylinders import CrossScenarioCutSpoke

    options = shared_options(cfg)
    return {
        "spoke_class": CrossScenarioCutSpoke,
        "spoke_kwargs": {},
        "opt_class": Xhat_Eval,
        "opt_kwargs": _spoke_opt_kwargs(
            cfg, scenario_creator, all_scenario_names,
            scenario_creator_kwargs, all_nodenames, options),
    }


def add_cross_scenario_cuts(hub_dict, cfg):
    """Attach the hub-side cut extension (cfg_vanilla.py:191-214)."""
    from ..extensions.cross_scen_extension import CrossScenarioExtension

    extension_adder(hub_dict, CrossScenarioExtension)
    hub_dict["opt_kwargs"]["options"]["cross_scen_options"] = {
        "check_bound_improve_iterations": cfg.get(
            "cross_scenario_iter_cnt", 4),
    }
    return hub_dict


def slammax_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:554-577)"""
    return _xhat_spoke(
        cfg, SlamMaxHeuristic, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
    )


def slammin_spoke(
    cfg,
    scenario_creator,
    scenario_denouement=None,
    all_scenario_names=None,
    scenario_creator_kwargs=None,
    all_nodenames=None,
):
    """(cfg_vanilla.py:578-601)"""
    return _xhat_spoke(
        cfg, SlamMinHeuristic, scenario_creator, all_scenario_names,
        scenario_creator_kwargs, all_nodenames,
    )
