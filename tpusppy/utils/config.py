"""Config: option store + argparse generator with the reference's group vocabulary.

TPU-native analogue of ``mpisppy/utils/config.py:47-778``.  The reference
subclasses ``pyomo.common.config.ConfigDict``; here a plain dict-backed store
with attribute access, typed fields, and the same ~30 ``*_args()`` feature
groups so reference CLIs map one-to-one (``--solver-name`` etc. — underscores
become dashes on the command line, config.py:51-78).

Options that only parameterize an external MIP solver (mipgaps, threads) are
kept for CLI compatibility and surfaced into solver option dicts where they
have a batched-ADMM meaning, ignored otherwise.
"""

from __future__ import annotations

import argparse


class ConfigValue:
    __slots__ = ("name", "description", "domain", "default", "argparse",
                 "argparse_args")

    def __init__(self, name, description, domain, default, use_argparse=True):
        self.name = name
        self.description = description
        self.domain = domain
        self.default = default
        self.argparse = use_argparse
        self.argparse_args = {}


def _listof(domain):
    def conv(v):
        if v is None:
            return None
        if isinstance(v, str):
            v = v.replace(",", " ").split()
        return [domain(x) for x in v]
    conv.__name__ = f"listof_{getattr(domain, '__name__', 'x')}"
    return conv


class Config:
    """Typed option dict + argparse generation (config.py:47-148)."""

    def __init__(self):
        object.__setattr__(self, "_fields", {})
        object.__setattr__(self, "_values", {})

    # ---- core dict-ish surface ----------------------------------------------
    def add_to_config(self, name, description, domain, default,
                      argparse=True, argparse_args=None):
        """Add one field (config.py:51-78); re-adding is an error like the
        reference's duplicate check."""
        if name in self._fields:
            raise RuntimeError(f"Trying to add duplicate {name} to Config")
        fv = ConfigValue(name, description, domain, default, argparse)
        fv.argparse_args = dict(argparse_args or {})
        self._fields[name] = fv
        self._values[name] = default

    def add_and_assign(self, name, description, domain, default, value,
                       complain=False):
        if name in self._fields:
            if complain:
                print(f"Duplicate {name} will not be added to Config "
                      f"by add_and_assign {value}.")
        else:
            self.add_to_config(name, description, domain, default,
                               argparse=False)
            self._values[name] = value

    def dict_assign(self, name, description, domain, default, value):
        if name not in self._fields:
            self.add_and_assign(name, description, domain, default, value)
        else:
            self._values[name] = value

    def quick_assign(self, name, domain, value):
        self.dict_assign(name, f"field for {name}", domain, None, value)

    def get(self, name, ifmissing=None):
        return self._values.get(name, ifmissing)

    def __contains__(self, name):
        return name in self._fields

    def __getitem__(self, name):
        return self._values[name]

    def __setitem__(self, name, value):
        if name not in self._fields:
            raise KeyError(name)
        self._values[name] = value

    def __getattr__(self, name):
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name in self._values:
            self._values[name] = value
        else:
            object.__setattr__(self, name, value)

    def __iter__(self):
        return iter(self._fields)

    def items(self):
        return self._values.items()

    def keys(self):
        return self._values.keys()

    def display(self):
        for k, v in self._values.items():
            print(f"  {k}: {v}")

    # ---- argparse (config.py:744-778) ---------------------------------------
    def create_parser(self, progname=None):
        if not self._fields:
            raise RuntimeError("create parser called before Config is populated")
        parser = argparse.ArgumentParser(progname,
                                         conflict_handler="resolve")
        for fv in self._fields.values():
            if not fv.argparse:
                continue
            flag = "--" + fv.name.replace("_", "-")
            kwargs = dict(fv.argparse_args)
            if fv.domain is bool:
                parser.add_argument(flag, dest=fv.name,
                                    action="store_true",
                                    default=fv.default,
                                    help=fv.description, **kwargs)
            else:
                parser.add_argument(flag, dest=fv.name, type=fv.domain,
                                    default=fv.default,
                                    help=fv.description, **kwargs)
        return parser

    def parse_command_line(self, progname=None, args=None):
        parser = self.create_parser(progname)
        parsed = parser.parse_args(args)
        return self.import_argparse(parsed)

    def import_argparse(self, parsed):
        for fv in self._fields.values():
            if fv.argparse and hasattr(parsed, fv.name):
                self._values[fv.name] = getattr(parsed, fv.name)
        return parsed

    # ---- shared field helpers ----------------------------------------------
    def add_solver_specs(self, prefix=""):
        sstr = f"{prefix}_solver" if prefix else "solver"
        self.add_to_config(f"{sstr}_name",
                           "solver name (default None)", str, None)
        self.add_to_config(
            f"{sstr}_options",
            "solver options; space delimited with = for values (default None)",
            str, None,
        )

    def num_scens_optional(self):
        self.add_to_config("num_scens", "Number of scenarios (default None)",
                           int, None)

    def num_scens_required(self):
        self.add_to_config("num_scens", "Number of scenarios (default None)",
                           int, None, argparse_args={"required": True})

    def add_branching_factors(self):
        self.add_to_config("branching_factors",
                           "Space/comma delimited branching factors (e.g. 2 2)",
                           _listof(int), None)

    # ---- feature groups (config.py:151-743) ---------------------------------
    def popular_args(self):
        add = self.add_to_config
        add("max_iterations", "hub max iterations (default 1)", int, 1)
        self.add_solver_specs(prefix="")
        add("seed", "Seed for random numbers (default is 1134)", int, 1134)
        add("default_rho", "Global rho for PH (default None)", float, None)
        add("bundles_per_rank", "bundles per rank (default 0 (no bundles))",
            int, 0)
        add("verbose", "verbose output", bool, False)
        add("display_progress", "display progress at each iteration", bool,
            False)
        add("display_convergence_detail",
            "display nonant convergence statistics at each iteration", bool,
            False)
        add("max_solver_threads", "Limit on threads per solver (default None)",
            int, None)
        add("intra_hub_conv_thresh",
            "Within hub convergence threshold (default 1e-10)", float, 1e-10)
        add("trace_prefix",
            "Prefix for bound spoke trace files (None: no traces)", str, None)
        add("tee_rank0_solves", "tee rank-0 solves where supported", bool,
            False)
        add("auxilliary", "Free text for use by hackers (default '')", str, '')

    def tracing_args(self):
        """Observability knobs (tpusppy.obs): ``tracing`` names the
        Perfetto trace path — a truthy value turns the flight recorder on
        (``tpusppy.obs.trace.maybe_enable_from_config``), equivalent to
        the ``TPUSPPY_TRACE=<path>`` env knob; the report JSON lands next
        to it as ``<path>.report.json``."""
        add = self.add_to_config
        add("tracing",
            "Path for a Perfetto trace of the run (None: tracing off)",
            str, None)
        add("log_level",
            "tpusppy log level (TPUSPPY_LOG_LEVEL overrides; default INFO)",
            str, None)

    def resilience_args(self):
        """Checkpoint/restart + degradation knobs (tpusppy.resilience,
        doc/resilience.md).  ``checkpoint_dir`` arms asynchronous wheel
        snapshots; ``resume`` warm-starts from the newest checkpoint
        there (bounds monotone across the restart, PHIterLimit still
        counts TOTAL iterations); ``spoke_timeout_secs`` lets the hub
        declare a progress-less spoke wedged and keep certifying with
        the rest; ``strict_spokes`` restores raise-on-spoke-crash;
        ``tune_cache`` persists autotuner verdicts across runs (the
        TPUSPPY_TUNE_CACHE knob as a Config field)."""
        add = self.add_to_config
        add("checkpoint_dir",
            "directory for async wheel checkpoints (None: off)", str, None)
        add("checkpoint_every_secs",
            "wall-clock checkpoint cadence (default 60)", float, 60.0)
        add("checkpoint_every_iters",
            "iteration checkpoint cadence (None: wall-clock only)", int,
            None)
        add("checkpoint_keep",
            "checkpoints retained before pruning (default 3)", int, 3)
        add("resume",
            "checkpoint dir/file to warm-start the wheel from", str, None)
        add("spoke_timeout_secs",
            "mark a spoke lost after this long with no mailbox/heartbeat "
            "progress (None: only death is loss)", float, None)
        add("strict_spokes",
            "raise on spoke failure instead of degrading gracefully",
            bool, False)
        add("tune_cache",
            "path of the persistent autotuner verdict cache "
            "(TPUSPPY_TUNE_CACHE equivalent; None: off)", str, None)

    def ph_args(self):
        add = self.add_to_config
        # adaptive per-slot rho (NormRhoUpdater, the reference's
        # adaptive_rho_converger lineage): attached by cfg_vanilla.ph_hub
        # when adaptive_rho is on.  Drivers that default the posture ON
        # (examples harness) leave --no-adaptive-rho as the opt-out, since
        # bool flags here are store_true.
        add("adaptive_rho",
            "adapt per-slot rho from primal/dual residual balance "
            "(NormRhoUpdater) instead of relying on a hand-tuned "
            "--default-rho", bool, False)
        add("no_adaptive_rho",
            "force adaptive rho OFF in drivers that default it on",
            bool, False)
        add("linearize_binary_proximal_terms",
            "linearize prox for binary nonants (no-op: the ADMM solver is a "
            "native QP solver)", bool, False)
        add("linearize_proximal_terms",
            "linearize all prox terms (no-op: native QP solver)", bool, False)
        add("proximal_linearization_tolerance",
            "cut tolerance when linearizing prox terms (default 1e-1)", float,
            1e-1)

    def multistage(self):
        self.add_branching_factors()
        self.popular_args()

    def _EF_base(self):
        self.add_solver_specs(prefix="EF")
        self.add_to_config("EF_mipgap",
                           "mip gap option for the solver (default None)",
                           float, None)

    def EF2(self):
        self._EF_base()
        self.num_scens_optional()

    def EF_multistage(self):
        self._EF_base()

    def two_sided_args(self):
        add = self.add_to_config
        add("rel_gap", "relative termination gap (default 0.05)", float, 0.05)
        add("abs_gap", "absolute termination gap (default 0)", float, 0.0)
        add("max_stalled_iters",
            "maximum iterations with no reduction in gap (default 100)", int,
            100)

    def mip_options(self):
        add = self.add_to_config
        add("iter0_mipgap", "mip gap option for iteration 0 (default None)",
            float, None)
        add("iterk_mipgap", "mip gap option non-zero iterations (default None)",
            float, None)

    def aph_args(self):
        add = self.add_to_config
        add("aph_gamma", "APH gamma parameter (default 1.0)", float, 1.0)
        add("aph_nu", "APH nu parameter (default 1.0)", float, 1.0)
        add("aph_frac_needed",
            "fraction of subproblems needed before a projective step "
            "(default 1.0)", float, 1.0)
        add("aph_dispatch_frac",
            "fraction of subproblems to dispatch per APH step (default 1.0)",
            float, 1.0)
        add("aph_sleep_seconds", "APH spin-lock sleep time (default 0.01)",
            float, 0.01)

    def fixer_args(self):
        add = self.add_to_config
        add("fixer", "have an integer fixer extension", bool, False)
        add("fixer_tol", "fixer bounds tolerance (default 1e-2)", float, 1e-2)

    def fwph_args(self):
        add = self.add_to_config
        add("fwph", "have an fwph spoke", bool, False)
        add("fwph_iter_limit", "maximum fwph iterations (default 10)", int, 10)
        add("fwph_weight", "fwph weight (default 0)", float, 0.0)
        add("fwph_conv_thresh", "fwph convergence threshold (default 1e-4)",
            float, 1e-4)
        add("fwph_stop_check_tol", "fwph tolerance for Gamma^t (default 1e-4)",
            float, 1e-4)
        add("fwph_mipgap", "mip gap option FW subproblems (default None)",
            float, None)

    def lagrangian_args(self):
        add = self.add_to_config
        add("lagrangian", "have a lagrangian spoke", bool, False)
        add("lagrangian_iter0_mipgap", "lgr. iter0 mipgap (default None)",
            float, None)
        add("lagrangian_iterk_mipgap", "lgr. iterk mipgap (default None)",
            float, None)

    def lagranger_args(self):
        add = self.add_to_config
        add("lagranger", "have a special lagranger spoke", bool, False)
        add("lagranger_iter0_mipgap", "lagranger iter0 mipgap (default None)",
            float, None)
        add("lagranger_iterk_mipgap", "lagranger iterk mipgap (default None)",
            float, None)
        add("lagranger_rho_rescale_factors_json",
            "json file: rho rescale factors (default None)", str, None)

    def xhatlooper_args(self):
        add = self.add_to_config
        add("xhatlooper", "have an xhatlooper spoke", bool, False)
        add("xhat_scen_limit", "scenario limit xhat looper to try (default 3)",
            int, 3)

    def xhatshuffle_args(self):
        add = self.add_to_config
        add("xhatshuffle", "have an xhatshuffle spoke", bool, False)
        add("add_reversed_shuffle",
            "also use the reversed shuffling (multistage only)", bool, False)
        add("xhatshuffle_iter_step",
            "step in shuffled list between 2 scenarios to try (default None)",
            int, None)

    def xhatrestrictedef_args(self):
        """tpusppy addition (no reference analogue): restricted-EF
        incumbent spoke — relax-and-fix host MILP over a scenario
        subsample at the hub's consensus."""
        add = self.add_to_config
        add("xhatrestrictedef", "have an xhat restricted-EF spoke",
            bool, False)
        add("xhat_ef_every", "hub iterations between restricted-EF tries",
            int, 4)
        add("xhat_ef_ksub", "scenario subsample size for the restricted EF",
            int, 6)
        add("xhat_ef_time_limit", "MILP time limit per restricted EF (sec)",
            float, 60.0)

    def mult_rho_args(self):
        add = self.add_to_config
        add("mult_rho", "have mult_rho extension (default False)", bool, False)
        add("mult_rho_convergence_tolerance",
            "rhomult does nothing with convergence below this (default 1e-4)",
            float, 1e-4)
        add("mult_rho_update_stop_iteration",
            "stop rhomult updates after this iteration (default None)", int,
            None)
        add("mult_rho_update_start_iteration",
            "start rhomult updates on this iteration (default 2)", int, 2)

    def mult_rho_to_dict(self):
        return {
            "mult_rho": self.mult_rho,
            "convergence_tolerance": self.mult_rho_convergence_tolerance,
            "rho_update_stop_iteration": self.mult_rho_update_stop_iteration,
            "rho_update_start_iteration": self.mult_rho_update_start_iteration,
            "verbose": False,
        }

    def xhatspecific_args(self):
        self.add_to_config("xhatspecific", "have an xhatspecific spoke", bool,
                           False)

    def xhatxbar_args(self):
        self.add_to_config("xhatxbar", "have an xhatxbar spoke", bool, False)

    def xhatlshaped_args(self):
        self.add_to_config("xhatlshaped", "have an xhatlshaped spoke", bool,
                           False)

    def wtracker_args(self):
        add = self.add_to_config
        add("wtracker", "use a wtracker extension", bool, False)
        add("wtracker_file_prefix",
            "prefix for rank by rank wtracker files (default '')", str, '')
        add("wtracker_wlen",
            "max length of iteration window for wtracker (default 20)", int,
            20)
        add("wtracker_reportlen",
            "max length of long reports for wtracker (default 100)", int, 100)
        add("wtracker_stdevthresh",
            "ignore moving std dev below this value (default None)", float,
            None)

    def slammax_args(self):
        self.add_to_config("slammax", "have a slammax spoke", bool, False)

    def slammin_args(self):
        self.add_to_config("slammin", "have a slammin spoke", bool, False)

    def cross_scenario_cuts_args(self):
        add = self.add_to_config
        add("cross_scenario_cuts", "have a cross scenario cuts spoke", bool,
            False)
        add("cross_scenario_iter_cnt",
            "cross scen check bound improve iterations (default 4)", int, 4)
        add("eta_bounds_mipgap",
            "mipgap for determining eta bounds for cross scenario cuts "
            "(default 0.01)", float, 0.01)

    def gradient_args(self):
        add = self.add_to_config
        add("xhatpath", "path to npy file with xhat", str, '')
        add("grad_cost_file", "name of the gradient cost file (csv)", str, '')
        add("grad_rho_file", "name of the gradient rho file (csv)", str, '')
        add("order_stat", "order statistic for rho (between 0 and 1)", float,
            -1.0)

    def rho_args(self):
        add = self.add_to_config
        add("whatpath", "path to csv file with what", str, '')
        add("rho_file", "name of the rho file (csv)", str, '')
        add("rho_setter", "use rho setter from a rho file", bool, False)
        add("rho_path", "csv file for the rho setter", str, '')
        if "order_stat" not in self:
            add("order_stat",
                "order statistic for rho: 0 (min) to 1 (max); 0.5 average",
                float, -1.0)
        add("rho_relative_bound", "factor that bounds rho/cost", float, 1e3)

    def converger_args(self):
        add = self.add_to_config
        add("use_norm_rho_converger", "use the norm rho converger", bool,
            False)
        add("primal_dual_converger", "use the primal dual converger", bool,
            False)
        add("primal_dual_converger_tol",
            "tolerance for primal dual converger (default 1e-2)", float, 1e-2)

    def tracking_args(self):
        add = self.add_to_config
        add("tracking_folder", "path of results folder (default results)",
            str, "results")
        add("ph_track_progress",
            "add tracking extension to ph opt cylinders (default False)",
            bool, False)
        add("track_convergence", "track gaps and bounds (default 0)", int, 0)
        add("track_xbars", "track xbars (default 0)", int, 0)
        add("track_duals", "track Ws (default 0)", int, 0)
        add("track_nonants", "track nonants (default 0)", int, 0)
        add("track_scen_gaps", "track scenario gaps (default 0)", int, 0)

    def wxbar_read_write_args(self):
        add = self.add_to_config
        add("init_W_fname", "path of initial W file (default None)", str, None)
        add("init_Xbar_fname", "path of initial Xbar file (default None)",
            str, None)
        add("init_separate_W_files",
            "if True, W is read from separate files (default False)", bool,
            False)
        add("W_fname", "path of final W file (default None)", str, None)
        add("Xbar_fname", "path of final Xbar file (default None)", str, None)
        add("separate_W_files",
            "if True, writes W to separate files (default False)", bool,
            False)

    # ---- tpusppy-specific ---------------------------------------------------
    def admm_args(self):
        """Batched-solver knobs (no reference analogue: Gurobi's role)."""
        add = self.add_to_config
        add("admm_dtype", "solver dtype (float64 on CPU, float32 on TPU)",
            str, None)
        add("admm_max_iter", "ADMM inner iterations per restart", int, 1000)
        add("admm_restarts", "ADMM rho-adaptation restarts", int, 4)
        add("admm_eps", "ADMM absolute/relative tolerance", float, None)
        add("admm_sweep_precision",
            "frozen-sweep matmul precision: default (bf16), high (bf16x3) "
            "or highest (full f32; the default — None follows "
            "matmul_precision).  Lower modes add an f32 refinement phase "
            "and a residual guard (doc/precision.md)", str, None)
        add("admm_pipeline",
            "overlapped dispatch pipeline for segmented continuations "
            "(doc/pipeline.md): speculative segments overlap the per-"
            "segment stop-stats RPC with device compute; identical "
            "results, bounded+billed waste.  False forces the legacy "
            "serial fetch-then-dispatch protocol", bool, True)
        add("admm_megastep",
            "device-resident wheel megakernel (doc/pipeline.md): the PH "
            "hub runs N wheel iterations per dispatch and fetches ONE "
            "packed measurement per megastep.  0 = auto: a banked "
            "autotune verdict when one exists (the hub option "
            "'megastep_autotune' measures and banks one on the first "
            "eligible window; persisted via TPUSPPY_TUNE_CACHE), else "
            "the refresh-cadence window, both under the watchdog cap.  "
            "1 = force the legacy per-iteration dispatch; k > 1 = "
            "request N=k", int, 0)
        add("ph_device_state",
            "device-resident PH state (doc/scaling.md): megastep windows "
            "fetch the LEAN packed measurement only, and the (S, K)/"
            "(S, n) host mirrors refresh by ONE billed fetch at "
            "checkpoint/termination/refresh boundaries — the O(1)-host "
            "posture for S=10^4+ wheels.  Also TPUSPPY_DEVICE_STATE=1",
            bool, False)


def global_config() -> Config:
    """A fresh Config (the reference exposes a module-level global_config)."""
    return Config()
