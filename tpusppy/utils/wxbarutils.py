"""W / xbar checkpoint IO — the reference's only restart mechanism.

TPU-native analogue of ``mpisppy/utils/wxbarutils.py`` (395 LoC): W and xbar
vectors written each iteration and read back to warm-start a later run
(single csv or per-scenario files).  Formats: W rows are
``scenario,slot,value``; xbar rows are ``slot,value``.
"""

from __future__ import annotations

import csv
import os

import numpy as np


def write_W_to_file(opt, fname, sep_files=False):
    """(wxbarutils.py:42-100)"""
    if sep_files:
        os.makedirs(fname, exist_ok=True)
        for s, sname in enumerate(opt.all_scenario_names):
            with open(os.path.join(fname, sname + "_weights.csv"), "w",
                      newline="") as f:
                w = csv.writer(f)
                for k in range(opt.nonant_length):
                    w.writerow([k, repr(float(opt.W[s, k]))])
        return
    with open(fname, "a", newline="") as f:
        w = csv.writer(f)
        for s, sname in enumerate(opt.all_scenario_names):
            for k in range(opt.nonant_length):
                w.writerow([sname, k, repr(float(opt.W[s, k]))])


def set_W_from_file(fname, opt, sep_files=False):
    """(wxbarutils.py:101-180)"""
    W = np.array(opt.W, copy=True)
    name_to_idx = {nm: i for i, nm in enumerate(opt.all_scenario_names)}
    if sep_files:
        for sname, s in name_to_idx.items():
            path = os.path.join(fname, sname + "_weights.csv")
            with open(path) as f:
                for row in csv.reader(f):
                    if not row:
                        continue
                    W[s, int(row[0])] = float(row[1])
    else:
        with open(fname) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                s = name_to_idx.get(row[0])
                if s is not None:
                    W[s, int(row[1])] = float(row[2])
    opt.W = W
    # consistency: probability-weighted W should sum ~0 per slot
    wsum = np.abs(opt.probs @ W).max()
    if wsum > 1e-4 * max(1.0, np.abs(W).max()):
        print(f"WARNING: read Ws are not dual-feasible (max |E W| = {wsum})")


def write_xbar_to_file(opt, fname):
    """(wxbarutils.py:181-220)"""
    with open(fname, "a", newline="") as f:
        w = csv.writer(f)
        for k in range(opt.nonant_length):
            w.writerow([k, repr(float(opt.xbars[0, k]))])


def set_xbar_from_file(fname, opt):
    """(wxbarutils.py:221-260)"""
    xb = np.array(opt.xbars, copy=True)
    with open(fname) as f:
        for row in csv.reader(f):
            if not row or row[0].startswith("#"):
                continue
            xb[:, int(row[0])] = float(row[1])
    opt.xbars = xb
