"""W / xbar checkpoint IO — the reference's only restart mechanism.

TPU-native analogue of ``mpisppy/utils/wxbarutils.py`` (395 LoC): W and xbar
vectors written each iteration and read back to warm-start a later run
(single csv or per-scenario files).  Row formats match the reference so
checkpoints interchange with mpi-sppy runs: W rows are
``scenario,varname,value`` (wxbarutils.py:42-100); xbar rows are
``varname,value``.  Variable names come from the IR's column names
(``SPBase.nonant_var_names``); when a model was built without names the slot
index is written in the name field, and the reader resolves either form.
"""

from __future__ import annotations

import csv
import os

import numpy as np


def _name_resolver(opt):
    """name -> packed nonant slot; accepts var names or literal slot indices."""
    names = opt.nonant_var_names
    table = {nm: k for k, nm in enumerate(names)}

    def resolve(key):
        k = table.get(key)
        if k is None:
            try:
                k = int(key)
            except ValueError:
                k = -1
            if not 0 <= k < len(names):
                raise KeyError(
                    f"unknown nonant variable {key!r} in W/xbar file"
                )
        return k

    return resolve


def write_W_to_file(opt, fname, sep_files=False):
    """(wxbarutils.py:42-100)"""
    names = opt.nonant_var_names
    if sep_files:
        os.makedirs(fname, exist_ok=True)
        for s, sname in enumerate(opt.all_scenario_names):
            with open(os.path.join(fname, sname + "_weights.csv"), "w",
                      newline="") as f:
                w = csv.writer(f)
                for k in range(opt.nonant_length):
                    w.writerow([names[k], repr(float(opt.W[s, k]))])
        return
    with open(fname, "a", newline="") as f:
        w = csv.writer(f)
        for s, sname in enumerate(opt.all_scenario_names):
            for k in range(opt.nonant_length):
                w.writerow([sname, names[k], repr(float(opt.W[s, k]))])


def set_W_from_file(fname, opt, sep_files=False):
    """(wxbarutils.py:101-180)"""
    W = np.array(opt.W, copy=True)
    resolve = _name_resolver(opt)
    name_to_idx = {nm: i for i, nm in enumerate(opt.all_scenario_names)}
    if sep_files:
        for sname, s in name_to_idx.items():
            path = os.path.join(fname, sname + "_weights.csv")
            with open(path) as f:
                for row in csv.reader(f):
                    if not row or row[0].startswith("#"):
                        continue
                    W[s, resolve(row[0])] = float(row[1])
    else:
        with open(fname) as f:
            for row in csv.reader(f):
                if not row or row[0].startswith("#"):
                    continue
                s = name_to_idx.get(row[0])
                if s is not None:
                    W[s, resolve(row[1])] = float(row[2])
    opt.W = W
    # consistency: probability-weighted W should sum ~0 per slot
    wsum = np.abs(opt.probs @ W).max()
    if wsum > 1e-4 * max(1.0, np.abs(W).max()):
        print(f"WARNING: read Ws are not dual-feasible (max |E W| = {wsum})")


def write_xbar_to_file(opt, fname):
    """(wxbarutils.py:181-220)"""
    names = opt.nonant_var_names
    with open(fname, "a", newline="") as f:
        w = csv.writer(f)
        for k in range(opt.nonant_length):
            w.writerow([names[k], repr(float(opt.xbars[0, k]))])


def set_xbar_from_file(fname, opt):
    """(wxbarutils.py:221-260)"""
    xb = np.array(opt.xbars, copy=True)
    resolve = _name_resolver(opt)
    with open(fname) as f:
        for row in csv.reader(f):
            if not row or row[0].startswith("#"):
                continue
            xb[:, resolve(row[0])] = float(row[1])
    opt.xbars = xb
