"""SIZES: two-stage product-sizing MIP (Lokketangen & Woodruff 1996).

Behavioral port of the reference test model
(``mpisppy/tests/examples/sizes/ReferenceModel.py`` +
``sizes.py`` scenario data in ``SIZES3``/``SIZES10``): ten product sizes,
setup + unit production costs, cut-down recycling between sizes, a shared
capacity per stage.  Scenarios differ only in second-stage demands
(0.7/1.0/1.3 times the base demand for the 3-scenario set).

First-stage (nonanticipative) variables: NumProducedFirstStage and
NumUnitsCutFirstStage — matching the reference's ``varlist`` at
``sizes.py:27-29`` (ProduceSizeFirstStage is stage-1 *derived*).
Golden (integer) 3-scenario EF objective: ~224,000 (reference tests round to
220,000 at 2 significant digits); the LP relaxation our batched solver
certifies is a valid lower bound and is cross-checked against HiGHS.
"""

from __future__ import annotations

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

NUM_SIZES = 10
CAPACITY = 200000.0
DEMANDS_FIRST = np.array(
    [2500, 7500, 12500, 10000, 35000, 25000, 15000, 12500, 12500, 5000.0]
)
UNIT_COST = np.array(
    [0.748, 0.7584, 0.7688, 0.7792, 0.7896, 0.8, 0.8104, 0.8208, 0.8312,
     0.8416]
)
SETUP_COST = np.full(10, 453.0)
UNIT_REDUCTION_COST = 0.008
# second-stage demand multipliers per scenario (SIZES3/Scenario{1,2,3}.dat)
DEMAND_FACTORS_3 = [0.7, 1.0, 1.3]


def scenario_names_creator(num_scens, start=0):
    # reference names are Scenario1..ScenarioN (1-based)
    return [f"Scenario{i + 1}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    # num_scens may arrive as a plain kwarg too (the service registry's
    # calling convention) — it must not be shadowed by the cfg default
    out = {"scenario_count": kwargs.get(
        "scenario_count", kwargs.get("num_scens", get("num_scens", 3)))}
    if "relax_integers" in kwargs:
        out["relax_integers"] = bool(kwargs["relax_integers"])
    return out


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()


def _second_stage_demands(scennum: int, scenario_count: int) -> np.ndarray:
    if scenario_count == 3:
        return DEMANDS_FIRST * DEMAND_FACTORS_3[scennum - 1]
    # SIZES10: evenly spread factors around 1.0 (the reference ships ten
    # .dat files; behaviorally a fan of demand levels)
    factors = np.linspace(0.7, 1.3, scenario_count)
    return DEMANDS_FIRST * factors[scennum - 1]


def scenario_creator(scenario_name, scenario_count=3, relax_integers=True):
    scennum = extract_num(scenario_name)
    d1 = DEMANDS_FIRST
    d2 = _second_stage_demands(scennum, scenario_count)
    N = NUM_SIZES

    b = LinearModelBuilder(scenario_name)
    as_int = not relax_integers
    # produce indicators (stage-derived, binary)
    p1 = b.add_vars("ProduceSizeFirstStage", N, lb=0.0, ub=1.0,
                    cost=0.0, integer=as_int)
    p2 = b.add_vars("ProduceSizeSecondStage", N, lb=0.0, ub=1.0,
                    cost=0.0, integer=as_int)
    np1 = b.add_vars("NumProducedFirstStage", N, lb=0.0, ub=CAPACITY,
                     integer=as_int)
    np2 = b.add_vars("NumProducedSecondStage", N, lb=0.0, ub=CAPACITY,
                     integer=as_int)
    # cut variables over (i, j) with i >= j (0-based here)
    cut_pairs = [(i, j) for i in range(N) for j in range(i + 1)]
    c1 = {}
    c2 = {}
    for (i, j) in cut_pairs:
        c1[i, j] = b.add_var(f"NumUnitsCutFirstStage[{i},{j}]", lb=0.0,
                             ub=CAPACITY, integer=as_int)
    for (i, j) in cut_pairs:
        c2[i, j] = b.add_var(f"NumUnitsCutSecondStage[{i},{j}]", lb=0.0,
                             ub=CAPACITY, integer=as_int)

    # costs: setup * produce + unit * produced + reduction * offdiag cuts
    for i in range(N):
        b.set_cost(p1[i], SETUP_COST[i])
        b.set_cost(p2[i], SETUP_COST[i])
        b.set_cost(np1[i], UNIT_COST[i])
        b.set_cost(np2[i], UNIT_COST[i])
    for (i, j) in cut_pairs:
        if i != j:
            b._c[c1[i, j]] = UNIT_REDUCTION_COST
            b._c[c2[i, j]] = UNIT_REDUCTION_COST

    # demand satisfied per size (cuts from larger sizes count)
    for j in range(N):
        b.add_ge({c1[i, j]: 1.0 for i in range(j, N)}, float(d1[j]))
        b.add_ge({c2[i, j]: 1.0 for i in range(j, N)}, float(d2[j]))
    # production forced to zero unless produce flag on
    for i in range(N):
        b.add_le({np1[i]: 1.0, p1[i]: -CAPACITY}, 0.0)
        b.add_le({np2[i]: 1.0, p2[i]: -CAPACITY}, 0.0)
    # stage capacity
    b.add_le({np1[i]: 1.0 for i in range(N)}, CAPACITY)
    b.add_le({np2[i]: 1.0 for i in range(N)}, CAPACITY)
    # inventory: cuts from size i limited by cumulative production of i
    for i in range(N):
        b.add_le({c1[i, j]: 1.0 for j in range(i + 1)} | {np1[i]: -1.0}, 0.0)
        coeffs = {c1[i, j]: 1.0 for j in range(i + 1)}
        for j in range(i + 1):
            coeffs[c2[i, j]] = 1.0
        coeffs[np1[i]] = -1.0
        coeffs[np2[i]] = -1.0
        b.add_le(coeffs, 0.0)

    nonants = np.asarray(np1 + [c1[i, j] for (i, j) in cut_pairs],
                         dtype=np.int32)
    p = b.build()
    p.prob = 1.0 / scenario_count
    p.nodes = [ScenarioNode("ROOT", 1.0, 1, nonants)]
    return p


def scenario_denouement(rank, scenario_name, scenario):
    pass


def _rho_setter(batch, rho_factor=0.001):
    """Per-slot rho from unit costs (sizes.py:38-59): rho for NumProduced is
    RF*unit cost, for cuts RF*reduction cost.  Returns (K,) over the packed
    nonant layout."""
    N = NUM_SIZES
    ncuts = N * (N + 1) // 2
    rho = np.empty(N + ncuts)
    rho[:N] = UNIT_COST * rho_factor
    rho[N:] = UNIT_REDUCTION_COST * rho_factor
    return rho


def id_fix_list_fct(batch):
    """Fixer tuples over nonant slots (sizes.py:62-100)."""
    from ..extensions.fixer import Fixer_tuple

    N = NUM_SIZES
    ncuts = N * (N + 1) // 2
    iter0 = []
    iterk = []
    for k in range(N):
        iter0.append(Fixer_tuple(k, th=0.01, nb=None, lb=0, ub=0))
        iterk.append(Fixer_tuple(k, th=0.2, nb=3, lb=1, ub=2))
    for k in range(N, N + ncuts):
        iter0.append(Fixer_tuple(k, th=0.5, nb=None, lb=0, ub=0))
        iterk.append(Fixer_tuple(k, th=0.2, nb=3, lb=1, ub=2))
    return iter0, iterk
