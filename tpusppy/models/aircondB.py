"""aircondB: aircond with PROPER (whole-subtree) bundles.

Behavioral analogue of ``mpisppy/tests/examples/aircondB.py``: the
scenario_creator accepts either a plain scenario name (``scen7``, delegating
to :mod:`tpusppy.models.aircond`) or a bundle name ``Bundle_first_last``
(e.g. ``Bundle_0_2``), returning the merged EF of those scenarios with all
inner-stage nonanticipativity baked in and only the ROOT nonants exposed —
the "proper bundle" object of pickle_bundle.py.  Bundles must consume
entire second-stage subtrees (aircondB.py:117 rule); pre-built bundles
round-trip through :mod:`tpusppy.utils.pickle_bundle` (.npz) via
``unpickle_bundles_dir`` / ``pickle_bundles_dir`` kwargs.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from . import aircond as base_aircond
from ..bundles import form_bundles

inparser_adder = base_aircond.inparser_adder
kw_creator = base_aircond.kw_creator
scenario_denouement = base_aircond.scenario_denouement


def scenario_names_creator(num_scens, start=None):
    start = start or 0
    return [f"scen{i}" for i in range(start, start + num_scens)]


def bundle_names_creator(num_bundles, num_scens, start=0):
    """Bundle_first_last names covering ``num_scens`` scenarios."""
    if num_scens % num_bundles != 0:
        raise ValueError(f"{num_scens} scenarios do not split into "
                         f"{num_bundles} bundles")
    per = num_scens // num_bundles
    return [f"Bundle_{start + b * per}_{start + (b + 1) * per - 1}"
            for b in range(num_bundles)]


def scenario_creator(sname, **kwargs):
    if "scen" in sname and "Bundle" not in sname:
        return base_aircond.scenario_creator(sname, **kwargs)
    if "Bundle" not in sname:
        raise RuntimeError(
            f"Scenario name does not have scen or Bundle: {sname}")

    firstnum = int(sname.split("_")[1])
    lastnum = int(sname.split("_")[2])
    unpickle_dir = kwargs.pop("unpickle_bundles_dir", None)
    pickle_dir = kwargs.pop("pickle_bundles_dir", None)
    if unpickle_dir is not None:
        from ..utils import pickle_bundle

        return pickle_bundle.dill_unpickle(
            os.path.join(unpickle_dir, sname + ".npz"))

    members = [base_aircond.scenario_creator(f"scen{i}", **kwargs)
               for i in range(firstnum, lastnum + 1)]
    num_scens = kwargs.get("num_scens") or int(
        np.prod(kwargs["branching_factors"]))
    members = [dataclasses.replace(p, prob=1.0 / num_scens)
               for p in members]
    bundle = form_bundles(members, 1)[0]
    bundle = dataclasses.replace(
        bundle, name=sname, prob=len(members) / num_scens)
    if pickle_dir is not None:
        from ..utils import pickle_bundle

        pickle_bundle.dill_pickle(
            bundle, os.path.join(pickle_dir, sname + ".npz"))
    return bundle
