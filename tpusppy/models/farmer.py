"""Scalable farmer model (Birge & Louveaux) in the tpusppy IR.

Mirrors the reference's scalable farmer (`mpisppy/tests/examples/farmer.py`,
`examples/farmer/farmer.py`): three crops (wheat, corn, sugar beets) times
``crops_multiplier``; yields scale by 0.8/1.0/1.2 for Below/Average/Above
scenarios (scennum % 3), with a reproducible random perturbation for scenario
groups beyond the first three.  The classic 3-scenario EF optimum is -108390.

Exports the module protocol the Amalgamator expects (amalgamator.py:123-135):
``scenario_creator``, ``scenario_names_creator``, ``inparser_adder``,
``kw_creator``.
"""

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

# Base data (per crop triple): wheat, corn, sugar beets.
TOTAL_ACREAGE = 500.0
PRICE_QUOTA = np.array([170.0, 150.0, 36.0])
PRICE_SUPER = np.array([0.0, 0.0, 10.0])        # beets above quota
PURCHASE_PRICE = np.array([238.0, 210.0, 1e12])  # beets cannot be purchased
QUOTA = np.array([np.inf, np.inf, 6000.0])
REQUIREMENT = np.array([200.0, 240.0, 0.0])
PLANTING_COST = np.array([150.0, 230.0, 260.0])
MEAN_YIELD = np.array([2.5, 3.0, 20.0])
YIELD_FACTOR = {0: 0.8, 1: 1.0, 2: 1.2}  # Below / Average / Above


def scenario_names_creator(num_scens, start=0):
    return [f"scen{i}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    """Map config to scenario_creator kwargs (cf. farmer.py kw_creator)."""
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {
        "use_integer": kwargs.get("use_integer", get("use_integer", False)),
        "crops_multiplier": kwargs.get(
            "crops_multiplier", get("crops_multiplier", 1)
        ),
        "num_scens": kwargs.get("num_scens", get("num_scens", None)),
        "seedoffset": kwargs.get("seedoffset", get("seedoffset", 0)),
    }


def scenario_denouement(rank, scenario_name, scenario):
    pass


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()
    cfg.add_to_config("crops_multiplier", description="farmer crop multiplier",
                      domain=int, default=1)
    cfg.add_to_config("use_integer", description="integer acreage",
                      domain=bool, default=False)


def scenario_creator(scenario_name, use_integer=False, crops_multiplier=1,
                     num_scens=None, seedoffset=0):
    """Build one farmer scenario as a ScenarioProblem.

    Variable layout per crop group g (crops_multiplier groups of 3 crops):
      x[3g:3g+3]   acres planted          (stage 1, nonanticipative)
      w[..]        tons sold at quota price
      e[..]        tons sold above quota (beets)
      y[..]        tons purchased (wheat/corn only)
    """
    scennum = extract_num(scenario_name)
    basenum = scennum % 3
    groupnum = scennum // 3
    stream = np.random.RandomState(scennum + seedoffset)

    ncrops = 3 * crops_multiplier
    factor = YIELD_FACTOR[basenum]
    # Group 0 is the classic deterministic triple; later groups get a
    # reproducible perturbation, mirroring the reference's use of a seeded
    # stream so scenarios differ beyond the first three.
    yields = np.tile(MEAN_YIELD, crops_multiplier) * factor
    if groupnum > 0:
        yields = yields * (1.0 + 0.1 * stream.uniform(-1.0, 1.0, size=ncrops))

    b = LinearModelBuilder(scenario_name)
    xi, wi, ei, yi = [], [], [], []
    for k in range(ncrops):
        crop = k % 3
        xi.append(
            b.add_var(f"x[{k}]", lb=0.0, ub=TOTAL_ACREAGE * crops_multiplier,
                      cost=PLANTING_COST[crop], integer=use_integer)
        )
    for k in range(ncrops):
        crop = k % 3
        wi.append(b.add_var(f"w[{k}]", lb=0.0, cost=-PRICE_QUOTA[crop]))
        ei.append(b.add_var(f"e[{k}]", lb=0.0, cost=-PRICE_SUPER[crop]))
        if PURCHASE_PRICE[crop] < 1e11:
            yi.append(b.add_var(f"y[{k}]", lb=0.0, cost=PURCHASE_PRICE[crop]))
        else:
            yi.append(None)

    # sum of acreage within each multiplier group <= 500
    for g in range(crops_multiplier):
        b.add_le({xi[3 * g + j]: 1.0 for j in range(3)}, TOTAL_ACREAGE)
    for k in range(ncrops):
        crop = k % 3
        # yield*x + y - w - e >= requirement  (balance)
        coeffs = {xi[k]: yields[k], wi[k]: -1.0, ei[k]: -1.0}
        if yi[k] is not None:
            coeffs[yi[k]] = 1.0
        b.add_ge(coeffs, REQUIREMENT[crop])
        # quota on favorable-price sales
        if np.isfinite(QUOTA[crop]):
            b.add_le({wi[k]: 1.0}, QUOTA[crop])
        else:
            # only beets may be sold above quota
            b.add_eq({ei[k]: 1.0}, 0.0)

    prob = None if num_scens is None else 1.0 / num_scens
    p = b.build()
    p.prob = prob
    p.nodes = [
        ScenarioNode("ROOT", 1.0, 1, np.asarray(xi, dtype=np.int32),
                     cost_coeffs=None)
    ]
    return p
