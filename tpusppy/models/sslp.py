"""SSLP: stochastic server location problem (Ntaimo & Sen).

Behavioral port of ``examples/sslp/model/ReferenceModel.py`` +
``examples/sslp/sslp.py``: first stage opens servers (binary, fixed cost);
second stage assigns present clients to open servers for revenue, with server
capacity and an overflow Dummy at high penalty.  Client presence is the
scenario randomness.

The reference reads SIPLIB ``.dat`` instances (``sslp_15_45_5`` etc.); here
instances are generated from a seeded stream with the same shape — pass
``num_servers``/``num_clients`` mirroring the instance-name convention
(sslp_<servers>_<clients>_<scens>).
"""

from __future__ import annotations

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

PENALTY = 1000.0


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i + 1}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {
        "num_servers": kwargs.get("num_servers", get("sslp_num_servers", 5)),
        "num_clients": kwargs.get("num_clients", get("sslp_num_clients", 15)),
        "seedoffset": kwargs.get("seedoffset", get("seedoffset", 0)),
        "relax_integers": kwargs.get("relax_integers",
                                     get("relax_integers", True)),
    }


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()
    cfg.add_to_config("sslp_num_servers", "number of servers", int, 5)
    cfg.add_to_config("sslp_num_clients", "number of clients", int, 15)


def _instance_data(num_servers, num_clients, seedoffset):
    """Deterministic instance-wide data (demands, costs, revenues) shared by
    all scenarios; SIPLIB-shaped magnitudes."""
    stream = np.random.RandomState(90210 + seedoffset)
    demand = stream.randint(1, 10, size=(num_clients, num_servers)).astype(
        float)
    fixed_cost = stream.randint(40, 80, size=num_servers).astype(float)
    revenue = stream.randint(1, 10, size=(num_clients, num_servers)).astype(
        float)
    capacity = float(demand.mean() * num_clients / max(1, num_servers // 2))
    return demand, fixed_cost, revenue, capacity


def scenario_creator(scenario_name, num_servers=5, num_clients=15,
                     seedoffset=0, relax_integers=True):
    scennum = extract_num(scenario_name)
    demand, fixed_cost, revenue, capacity = _instance_data(
        num_servers, num_clients, seedoffset)
    stream = np.random.RandomState(scennum + seedoffset)
    present = (stream.rand(num_clients) < 0.5).astype(float)

    as_int = not relax_integers
    b = LinearModelBuilder(scenario_name)
    x = b.add_vars("FacilityOpen", num_servers, lb=0.0, ub=1.0,
                   integer=as_int)
    for j in range(num_servers):
        b.set_cost(x[j], fixed_cost[j])
    y = {}
    for i in range(num_clients):
        for j in range(num_servers):
            y[i, j] = b.add_var(f"Allocation[{i},{j}]", lb=0.0, ub=1.0,
                                cost=-revenue[i, j], integer=as_int)
    dummy = b.add_vars("Dummy", num_servers, lb=0.0, cost=PENALTY)

    for j in range(num_servers):
        coeffs = {y[i, j]: demand[i, j] for i in range(num_clients)}
        coeffs[dummy[j]] = -1.0
        coeffs[x[j]] = -capacity
        b.add_le(coeffs, 0.0)
    for i in range(num_clients):
        b.add_eq({y[i, j]: 1.0 for j in range(num_servers)},
                 float(present[i]))

    p = b.build()
    p.nodes = [ScenarioNode("ROOT", 1.0, 1, np.asarray(x, dtype=np.int32))]
    return p


def scenario_denouement(rank, scenario_name, scenario):
    pass


def id_fix_list_fct(batch):
    """Fixer tuples on the server-open slots (sslp.py:41-66)."""
    from ..extensions.fixer import Fixer_tuple

    K = batch.tree.num_nonants
    return None, [Fixer_tuple(k, th=0, nb=None, lb=20, ub=20)
                  for k in range(K)]
