"""NETDES: stochastic network design.

Behavioral port of ``examples/netdes/netdes.py``: first stage opens arcs
(binary, per-arc cost), second stage routes flow on open arcs (variable upper
bound y_e <= u_e x_e) to satisfy per-node net-demand balances that vary by
scenario.

The reference reads ``.dat`` instances from ``examples/netdes/data``; here a
seeded generator builds a random strongly-connected digraph with one source /
one sink whose demand scales per scenario.
"""

from __future__ import annotations

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {
        "num_nodes": kwargs.get("num_nodes", get("netdes_nodes", 10)),
        "num_scens": kwargs.get("num_scens", get("num_scens")),
        "seedoffset": kwargs.get("seedoffset", get("seedoffset", 0)),
        "relax_integers": kwargs.get("relax_integers",
                                     get("relax_integers", True)),
    }


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()
    cfg.add_to_config("netdes_nodes", "number of network nodes", int, 10)


def _instance(num_nodes, seedoffset):
    """Digraph with a ring (connectivity) + random chords; per-edge costs and
    capacities."""
    stream = np.random.RandomState(777 + seedoffset)
    edges = [(i, (i + 1) % num_nodes) for i in range(num_nodes)]
    extra = max(num_nodes, int(1.5 * num_nodes))
    while len(edges) < num_nodes + extra:
        i, j = stream.randint(0, num_nodes, 2)
        if i != j and (i, j) not in edges:
            edges.append((int(i), int(j)))
    c = stream.randint(20, 60, len(edges)).astype(float)    # open cost
    d = stream.randint(1, 10, len(edges)).astype(float)     # flow cost
    u = stream.randint(8, 20, len(edges)).astype(float)     # capacity
    return edges, c, d, u


def scenario_creator(scenario_name, num_nodes=10, num_scens=None,
                     seedoffset=0, relax_integers=True):
    scennum = extract_num(scenario_name)
    edges, c, d, u = _instance(num_nodes, seedoffset)
    stream = np.random.RandomState(1000 + scennum + seedoffset)
    # source node 0 ships to sink node num_nodes//2; demand varies by scenario
    demand = float(stream.randint(5, 15))
    bvec = np.zeros(num_nodes)
    bvec[0] = demand
    bvec[num_nodes // 2] = -demand

    as_int = not relax_integers
    b = LinearModelBuilder(scenario_name)
    x = [b.add_var(f"x[{i},{j}]", lb=0.0, ub=1.0, cost=c[e], integer=as_int)
         for e, (i, j) in enumerate(edges)]
    y = [b.add_var(f"y[{i},{j}]", lb=0.0, cost=d[e])
         for e, (i, j) in enumerate(edges)]

    for e in range(len(edges)):
        b.add_le({y[e]: 1.0, x[e]: -u[e]}, 0.0)       # vub: y <= u x
    for node in range(num_nodes):
        coeffs = {}
        for e, (i, j) in enumerate(edges):
            if i == node:
                coeffs[y[e]] = coeffs.get(y[e], 0.0) + 1.0
            if j == node:
                coeffs[y[e]] = coeffs.get(y[e], 0.0) - 1.0
        b.add_eq(coeffs, float(bvec[node]))           # flow balance

    prob = None if num_scens is None else 1.0 / num_scens
    p = b.build()
    p.prob = prob
    p.nodes = [ScenarioNode("ROOT", 1.0, 1, np.asarray(x, dtype=np.int32))]
    return p


def scenario_denouement(rank, scenario_name, scenario):
    pass
