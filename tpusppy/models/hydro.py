"""Three-stage hydro-thermal scheduling model in the tpusppy IR.

Mirrors the semantics of the reference's multistage test model
(`mpisppy/tests/examples/hydro/hydro.py` + `PySP/scenariodata/*.dat`): three
periods, thermal generation Pgt, hydro generation Pgh, unserved demand PDns,
reservoir volume Vol, and a terminal water-value variable sl.  Scenarios branch
on inflows: stage-2 inflow in {10, 50, 90} and stage-3 inflow in {40, 50, 60}
under branching factors [3, 3] (9 scenarios, named Scen1..Scen9, 1-based).

Golden values (tests/test_ef_ph.py:545-646): EF objective rounds to 190 at two
significant digits; PH trivial bound rounds to 180; Scen7 Pgt[2] rounds to 60.
"""

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

T_PERIODS = 3
DEMAND = np.array([90.0, 160.0, 110.0])
BETA_GT = 1.0
BETA_GH = 0.0
BETA_DNS = 10.0
PGT_MAX = 100.0
PGH_MAX = 100.0
V_MAX = 100.0
U = np.array([0.6048, 0.6048, 1.2096])       # conversion factor per period
DURATION = np.array([168.0, 168.0, 336.0])
V0 = 60.48
T_HORIZON = 8760.0
WATER_VALUE = 4166.67                        # terminal value-of-water slope
INFLOW_STAGE1 = 50.0
INFLOW_STAGE2 = np.array([10.0, 50.0, 90.0])  # branch b -> inflow
INFLOW_STAGE3 = np.array([40.0, 50.0, 60.0])

# discount factor per period: (1/1.1)^(duration/T)
DISCOUNT = (1.0 / 1.1) ** (DURATION / T_HORIZON)


def scenario_names_creator(num_scens, start=0):
    """1-based names, matching the reference's Scen1..ScenN convention."""
    return [f"Scen{i + 1}" for i in range(start, start + num_scens)]


def scenario_denouement(rank, scenario_name, scenario):
    pass


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {
        "branching_factors": kwargs.get(
            "branching_factors", get("branching_factors", [3, 3])
        ),
    }


def inparser_adder(cfg):
    cfg.add_branching_factors()


def scenario_creator(scenario_name, branching_factors=None, data_path=None):
    """Build one hydro scenario as a ScenarioProblem.

    Variable layout: for t in 0..2: Pgt[t], Pgh[t], PDns[t], Vol[t]; then sl.
    Stage-t cost folded onto variables: r[t]*(betaGt*Pgt + betaDns*PDns) with
    the terminal water value sl added at stage 3.
    """
    if branching_factors is None:
        branching_factors = [3, 3]
    b1, b2 = branching_factors
    if b1 > len(INFLOW_STAGE2) or b2 > len(INFLOW_STAGE3):
        raise ValueError(
            f"hydro has {len(INFLOW_STAGE2)}x{len(INFLOW_STAGE3)} inflow "
            f"realizations; branching_factors {branching_factors} unsupported"
        )
    snum = extract_num(scenario_name)             # 1-based
    branch = (snum - 1) // b2                     # stage-2 node index
    leaf = (snum - 1) % b2                        # stage-3 branch index

    inflow = np.array([
        INFLOW_STAGE1,
        INFLOW_STAGE2[branch],
        INFLOW_STAGE3[leaf],
    ])

    b = LinearModelBuilder(scenario_name)
    pgt, pgh, pdns, vol = [], [], [], []
    for t in range(T_PERIODS):
        pgt.append(b.add_var(f"Pgt[{t + 1}]", lb=0.0, ub=PGT_MAX,
                             cost=DISCOUNT[t] * BETA_GT))
        pgh.append(b.add_var(f"Pgh[{t + 1}]", lb=0.0, ub=PGH_MAX,
                             cost=DISCOUNT[t] * BETA_GH))
        pdns.append(b.add_var(f"PDns[{t + 1}]", lb=0.0, ub=DEMAND[t],
                              cost=DISCOUNT[t] * BETA_DNS))
        vol.append(b.add_var(f"Vol[{t + 1}]", lb=0.0, ub=V_MAX))
    sl = b.add_var("sl", lb=0.0, cost=1.0)

    for t in range(T_PERIODS):
        # demand balance: Pgt + Pgh + PDns == D[t]
        b.add_eq({pgt[t]: 1.0, pgh[t]: 1.0, pdns[t]: 1.0}, DEMAND[t])
        # volume conservation: Vol[t] - Vol[t-1] + u[t]*Pgh[t] <= u[t]*A[t]
        coeffs = {vol[t]: 1.0, pgh[t]: U[t]}
        rhs = U[t] * inflow[t]
        if t == 0:
            rhs += V0
        else:
            coeffs[vol[t - 1]] = -1.0
        b.add_le(coeffs, rhs)
    # future cost of empty reservoir: sl >= WATER_VALUE * (V0 - Vol[T])
    b.add_ge({sl: 1.0, vol[-1]: WATER_VALUE}, WATER_VALUE * V0)

    p = b.build()
    p.prob = 1.0 / (b1 * b2)
    stage_vars = lambda t: np.asarray(
        [pgt[t], pgh[t], pdns[t], vol[t]], dtype=np.int32
    )
    p.nodes = [
        ScenarioNode("ROOT", 1.0, 1, stage_vars(0)),
        ScenarioNode(f"ROOT_{branch}", 1.0 / b1, 2, stage_vars(1)),
    ]
    return p
