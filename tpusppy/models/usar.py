"""Urban search and rescue (USAR) in the tpusppy IR.

Mirrors the reference's USAR example (`examples/usar/abstract.py:1-140`,
`examples/usar/generate_data.py`, `examples/usar/scenario_creator.py:1-40`):
a multistage-inspired two-stage MILP after Chen & Miller-Hooks (2012) —
pick which depots to activate (first stage, binary, nonanticipative), then
route rescue teams from depots through household sites to maximize lives
saved under uncertain household sizes and survival times.

The reference builds a Pyomo ``AbstractModel`` and feeds it data dicts from
``generate_data``; here the same binary network-flow/scheduling model is
emitted directly as a :class:`~tpusppy.ir.ScenarioProblem`.  Data generation
reproduces the reference's sampling bit-for-bit (same ``random`` module
draws, same scipy Poisson/Pareto inverse-CDF transforms), so instances are
data-comparable for any (seed, shape) pair.

NOTE the objective sign: the reference MAXIMIZES lives saved; the IR always
minimizes, so the model's objective is the negated lives count.  Drivers
report ``-objective`` as "expected lives saved".
"""

import itertools
import math
import random
from functools import lru_cache

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode

# generate_data.py:19-22 — household sizes ~ Poisson(2), emergency supplies
# stock ~ Pareto(1), minimum survival window of 3 days
_MIN_SURVIVAL_MINUTES = 3 * 24 * 60


def _poisson2_ppf(u):
    """Poisson(2).ppf(u) without scipy: smallest k with CDF(k) >= u."""
    lam = 2.0
    k, cdf, pmf = 0, math.exp(-lam), math.exp(-lam)
    while cdf < u and k < 1000:
        k += 1
        pmf *= lam / k
        cdf += pmf
    return float(k)


def _pareto1_ppf(u):
    """Pareto(b=1).ppf(u) (scipy convention: support [1, inf))."""
    u = min(max(u, 0.0), 1.0 - 1e-15)
    return 1.0 / (1.0 - u)


@lru_cache(maxsize=32)
def _generate_all(num_scens, time_horizon, time_unit_minutes, num_depots,
                  num_active_depots, num_households, constant_rescue_time,
                  travel_speed, constant_depot_inflow, seed):
    """All scenario data for one instance family (generate_data.py:87-169).

    Returns (from_depot_tt, inter_site_tt, per-scenario lives arrays).
    Travel times are scenario-independent (the generator cycles one fixed
    sequence); lives_to_be_saved varies per scenario via fresh Poisson /
    Pareto draws from the shared ``random`` stream.
    """
    random.seed(seed)
    depot_coords = [(random.random(), random.random())
                    for _ in range(num_depots)]
    household_coords = [(random.random(), random.random())
                        for _ in range(num_households)]

    def pairwise_times(coords1, coords2):
        for c1, c2 in itertools.product(coords1, coords2):
            travel_time = math.dist(c1, c2) / travel_speed
            yield max(1, math.ceil(travel_time))

    T, D, N = time_horizon, num_depots, num_households
    fd_seq = itertools.cycle(pairwise_times(depot_coords, household_coords))
    is_seq = itertools.cycle(pairwise_times(household_coords,
                                            household_coords))
    # index order matches the reference's itertools.product(times, depots,
    # sites) fill of a cycled pairwise sequence
    fd_tt = np.fromiter((next(fd_seq) for _ in range(T * D * N)),
                        dtype=np.int64).reshape(T, D, N)
    is_tt = np.fromiter((next(is_seq) for _ in range(T * N * N)),
                        dtype=np.int64).reshape(T, N, N)

    lives = []
    for _ in range(num_scens):
        sizes = [_poisson2_ppf(random.random()) for _ in range(N)]
        stocks = [_pareto1_ppf(random.random()) for _ in range(N)]
        survival_mins = [_MIN_SURVIVAL_MINUTES * st for st in stocks]
        lv = np.zeros((T, N))
        for t in range(T):
            for s in range(N):
                if t * time_unit_minutes <= survival_mins[s]:
                    lv[t, s] = sizes[s]
        lives.append(lv)
    return fd_tt, is_tt, lives


def generate_coords(num_depots, num_households, seed, **kwargs):
    """Depot/household coordinates exactly as the reference samples them
    (generate_data.py:26-52): seeds ``random`` then draws uniforms."""
    random.seed(seed)
    depot_coords = [(random.random(), random.random())
                    for _ in range(num_depots)]
    household_coords = [(random.random(), random.random())
                        for _ in range(num_households)]
    return depot_coords, household_coords


def scenario_names_creator(num_scens, start=0):
    return [f"usar{i}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = (cfg.get if hasattr(cfg, "get")
           else lambda k, d=None: getattr(cfg, k, d))

    def pick(name, default):
        return kwargs.get(name, get(name, default))

    return {
        "num_scens": pick("num_scens", None),
        "time_horizon": pick("time_horizon", 6),
        "time_unit_minutes": pick("time_unit_minutes", 60.0),
        "num_depots": pick("num_depots", 3),
        "num_active_depots": pick("num_active_depots", 2),
        "num_households": pick("num_households", 4),
        "constant_rescue_time": pick("constant_rescue_time", 1),
        "travel_speed": pick("travel_speed", 1.0),
        "constant_depot_inflow": pick("constant_depot_inflow", 2),
        "seed": pick("seed", 0),
        "relax_integers": pick("relax_integers", False),
    }


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()
    for name, domain, default, desc in (
        ("time_horizon", int, 6, "number of time steps"),
        ("time_unit_minutes", float, 60.0, "minutes per time step"),
        ("num_depots", int, 3, "number of depots generated"),
        ("num_active_depots", int, 2, "depots allowed to be active"),
        ("num_households", int, 4, "number of households generated"),
        ("constant_rescue_time", int, 1, "flat time per household rescue"),
        ("travel_speed", float, 1.0, "unit-square distance per time step"),
        ("constant_depot_inflow", int, 2,
         "rescue teams arriving at depots per time step"),
        ("seed", int, 0, "seed for the random module"),
    ):
        if name not in cfg:      # popular_args already declares e.g. seed
            cfg.add_to_config(name, description=desc, domain=domain,
                              default=default)


def scenario_denouement(rank, scenario_name, scenario):
    pass


def scenario_creator(scenario_name, num_scens=None, time_horizon=6,
                     time_unit_minutes=60.0, num_depots=3,
                     num_active_depots=2, num_households=4,
                     constant_rescue_time=1, travel_speed=1.0,
                     constant_depot_inflow=2, seed=0,
                     relax_integers=False):
    """One USAR scenario as a ScenarioProblem (abstract.py:25-140).

    Variables (all binary unless relaxed):
      a[d]            is_active_depot — stage-1 nonanticipative
      dd[t,d,s]       depot_departures
      sd[t,s1,s2]     site_departures (self-loops fixed at 0)
      st[t,s]         stays_at_site
      ita[t,f,s]      is_time_from_arrival (f = time units until arrival)
    """
    scen = int(scenario_name.replace("usar", ""))
    S = num_scens if num_scens is not None else scen + 1
    fd_tt, is_tt, lives_all = _generate_all(
        max(S, scen + 1), time_horizon, time_unit_minutes, num_depots,
        num_active_depots, num_households, constant_rescue_time,
        travel_speed, constant_depot_inflow, seed)
    lives = lives_all[scen]
    T, D, N = time_horizon, num_depots, num_households

    b = LinearModelBuilder(scenario_name)
    intflag = not relax_integers
    a = [b.add_var(f"a[{d}]", lb=0.0, ub=1.0, integer=intflag)
         for d in range(D)]
    dd = np.empty((T, D, N), dtype=np.int64)
    for t in range(T):
        for d in range(D):
            for s in range(N):
                dd[t, d, s] = b.add_var(f"dd[{t},{d},{s}]", lb=0.0, ub=1.0,
                                        integer=intflag)
    sd = np.empty((T, N, N), dtype=np.int64)
    for t in range(T):
        for s1 in range(N):
            for s2 in range(N):
                ub = 0.0 if s1 == s2 else 1.0    # no self-loops
                sd[t, s1, s2] = b.add_var(f"sd[{t},{s1},{s2}]", lb=0.0,
                                          ub=ub, integer=intflag)
    st = np.empty((T, N), dtype=np.int64)
    for t in range(T):
        for s in range(N):
            st[t, s] = b.add_var(f"st[{t},{s}]", lb=0.0, ub=1.0,
                                 integer=intflag)
    ita = np.empty((T, T, N), dtype=np.int64)
    for t in range(T):
        for f in range(T):
            for s in range(N):
                # objective: maximize lives saved => minimize the negation
                cost = -float(lives[t, s]) if f == 0 else 0.0
                ita[t, f, s] = b.add_var(f"ita[{t},{f},{s}]", lb=0.0,
                                         ub=1.0, cost=cost, integer=intflag)

    # limit_num_active_depots (abstract.py:67-72)
    if D:
        b.add_eq({int(a[d]): 1.0 for d in range(D)},
                 float(num_active_depots))
    # depart_only_active_depots (abstract.py:74-80)
    for t in range(T):
        for d in range(D):
            for s in range(N):
                b.add_le({int(dd[t, d, s]): 1.0, int(a[d]): -1.0}, 0.0)
    # limit_depot_outflow (abstract.py:82-86)
    for t in range(T):
        if D and N:
            b.add_le({int(dd[t, d, s]): 1.0
                      for d in range(D) for s in range(N)},
                     float(constant_depot_inflow))
    # set_is_time_from_arrival (abstract.py:88-105)
    for t in range(T):
        for f in range(T):
            for s in range(N):
                coeffs = {int(ita[t, f, s]): 1.0}
                if t > 0 and f + 1 < T:
                    coeffs[int(ita[t - 1, f + 1, s])] = \
                        coeffs.get(int(ita[t - 1, f + 1, s]), 0.0) - 1.0
                for d in range(D):
                    if fd_tt[t, d, s] == f:
                        coeffs[int(dd[t, d, s])] = \
                            coeffs.get(int(dd[t, d, s]), 0.0) - 1.0
                for s2 in range(N):
                    if is_tt[t, s2, s] == f:
                        coeffs[int(sd[t, s2, s])] = \
                            coeffs.get(int(sd[t, s2, s]), 0.0) - 1.0
                b.add_eq(coeffs, 0.0)
    # flow_conservation (abstract.py:107-118)
    for t in range(T):
        for s in range(N):
            coeffs = {int(ita[t, 0, s]): 1.0, int(st[t, s]): -1.0}
            if t > 0:
                coeffs[int(st[t - 1, s])] = 1.0
            for s2 in range(N):
                coeffs[int(sd[t, s, s2])] = \
                    coeffs.get(int(sd[t, s, s2]), 0.0) - 1.0
            b.add_eq(coeffs, 0.0)
    # visit_only_once (abstract.py:120-122)
    for s in range(N):
        b.add_le({int(ita[t, 0, s]): 1.0 for t in range(T)}, 1.0)
    # fully_service_site (abstract.py:124-132)
    for t in range(T):
        for s in range(N):
            coeffs = {int(st[t, s]): 1.0}
            for tp in range(t + 1):
                if tp + constant_rescue_time > t:
                    coeffs[int(ita[tp, 0, s])] = \
                        coeffs.get(int(ita[tp, 0, s]), 0.0) - 1.0 / T
            b.add_ge(coeffs, 0.0)

    p = b.build()
    p.prob = None if num_scens is None else 1.0 / num_scens
    p.nodes = [
        ScenarioNode("ROOT", 1.0, 1, np.asarray(a, dtype=np.int32),
                     cost_coeffs=None)
    ]
    return p
