"""Reference-shape stochastic unit commitment (the headline benchmark family).

This is the scaled counterpart of :mod:`tpusppy.models.uc_lite`, matching the
decision structure of the reference's UC example (egret-built models driven by
``examples/uc/uc_funcs.py`` and the ``paperruns/larger_uc`` wind-scenario
ladders): binary commitment with startup/shutdown variables and min-up/
min-down constraints, dispatch with capacity/ramp/startup-ramp limits,
hourly power balance and spinning-reserve requirements, wind uncertainty.

Wind enters ONLY the balance/reserve right-hand sides, so every scenario
shares one constraint matrix — the batch runs on the shared-A engine
(``ir.ScenarioBatch.A_shared`` -> ``solvers.shared_admm``), which is what
makes 1000-scenario reference-scale instances fit a single chip
(VERDICT r2 missing #1: dense (S, m, n) A at 30 gens x 48 h x S=1000 is
~67 GB; the shared matrix is ~60 MB).

Model (per generator g, hour h; all rows linear):

  vars   u[g,h] in {0,1} commitment (FIRST STAGE, the nonants)
         v[g,h], w[g,h] in [0,1] startup/shutdown indicators
         p[g,h] >= 0 dispatch, shed[h] >= 0 load shed (VOLL),
         rsh[h] >= 0 reserve shortfall (penalized)
  rows   u[g,h] - u[g,h-1] = v[g,h] - w[g,h]            (logic, equality)
         sum_{t in (h-UT,h]} v[g,t] <= u[g,h]           (min-up)
         sum_{t in (h-DT,h]} w[g,t] <= 1 - u[g,h]       (min-down)
         pmin u <= p <= pmax u                          (capacity)
         p[h] - p[h-1] <= RU u[g,h-1] + SU v[g,h]       (ramp up / startup)
         p[h-1] - p[h] <= RD u[g,h] + SD w[g,h]         (ramp down / shutdn)
         sum_g p + shed >= demand[h] - wind_s[h]        (balance; rhs varies)
         sum_g (pmax u - p) + rsh >= resreq_s[h]        (spinning reserve)
  cost   mc p + noload u + startcost v + VOLL shed + rpen rsh

The fleet is a seeded mix of unit classes (base/mid/peaker) with class-scaled
minimum up/down times, ramps and startup costs.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

VOLL = 5000.0      # value of lost load ($/MWh)
RPEN = 1000.0      # reserve-shortfall penalty ($/MWh)
RESERVE_FRAC = 0.1  # spinning reserve requirement as a fraction of demand

_TEMPLATE_CACHE: dict = {}


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {
        "num_gens": kwargs.get("num_gens", get("uc_num_gens", 30)),
        "horizon": kwargs.get("horizon", get("uc_horizon", 24)),
        "num_scens": kwargs.get("num_scens", get("num_scens")),
        "seedoffset": kwargs.get("seedoffset", get("seedoffset", 0)),
        "relax_integers": kwargs.get("relax_integers",
                                     get("relax_integers", False)),
        "wind_frac": kwargs.get("wind_frac", get("uc_wind_frac", 0.25)),
    }


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()
    cfg.add_to_config("uc_num_gens", "number of thermal generators", int, 30)
    cfg.add_to_config("uc_horizon", "scheduling horizon (hours)", int, 24)
    cfg.add_to_config("uc_wind_frac",
                      "mean wind share of peak thermal capacity", float, 0.25)


def _fleet(num_gens, seedoffset):
    """Seeded thermal fleet: base-load / mid-merit / peaker classes with
    class-correlated sizes, costs, ramps and min-up/down times."""
    stream = np.random.RandomState(4242 + seedoffset)
    cls = stream.choice(3, size=num_gens, p=[0.3, 0.4, 0.3])  # 0=base,1=mid,2=peak
    size_lo = np.array([200.0, 80.0, 20.0])[cls]
    size_hi = np.array([400.0, 200.0, 80.0])[cls]
    pmax = size_lo + (size_hi - size_lo) * stream.rand(num_gens)
    pmin = pmax * np.array([0.45, 0.35, 0.2])[cls]
    mc = (np.array([12.0, 25.0, 45.0])[cls]
          * (0.85 + 0.3 * stream.rand(num_gens)))
    noload = pmax * np.array([2.0, 1.2, 0.6])[cls]
    startcost = pmax * np.array([40.0, 15.0, 4.0])[cls]
    ramp = pmax * np.array([0.25, 0.5, 1.0])[cls]          # per-hour ramp
    startramp = np.maximum(pmin, ramp)                     # SU/SD limits
    minup = np.array([8, 4, 1])[cls]
    mindown = np.array([6, 3, 1])[cls]
    return dict(pmax=pmax, pmin=pmin, mc=mc, noload=noload,
                startcost=startcost, ramp=ramp, startramp=startramp,
                minup=minup, mindown=mindown)


def _template(num_gens, horizon, seedoffset, relax_integers):
    key = (num_gens, horizon, seedoffset, relax_integers)
    cached = _TEMPLATE_CACHE.get(key)
    if cached is not None:
        return cached
    fl = _fleet(num_gens, seedoffset)
    as_int = not relax_integers
    G, H = num_gens, horizon
    b = LinearModelBuilder("template")
    u = np.empty((G, H), dtype=np.int64)
    v = np.empty((G, H), dtype=np.int64)
    w = np.empty((G, H), dtype=np.int64)
    p = np.empty((G, H), dtype=np.int64)
    for g in range(G):
        for h in range(H):
            u[g, h] = b.add_var(f"u[{g},{h}]", lb=0.0, ub=1.0,
                                cost=fl["noload"][g], integer=as_int)
    for g in range(G):
        for h in range(H):
            v[g, h] = b.add_var(f"v[{g},{h}]", lb=0.0, ub=1.0,
                                cost=fl["startcost"][g])
    for g in range(G):
        for h in range(H):
            w[g, h] = b.add_var(f"w[{g},{h}]", lb=0.0, ub=1.0)
    for g in range(G):
        for h in range(H):
            p[g, h] = b.add_var(f"p[{g},{h}]", lb=0.0, cost=fl["mc"][g])
    shed = b.add_vars("shed", H, lb=0.0, cost=VOLL)
    rsh = b.add_vars("rsh", H, lb=0.0, cost=RPEN)

    # initial state: units start OFF with p=0 (h=0 logic rows use u[-1]=0)
    for g in range(G):
        pmax_g, pmin_g = float(fl["pmax"][g]), float(fl["pmin"][g])
        RU = float(fl["ramp"][g])
        SU = float(fl["startramp"][g])
        UT = int(fl["minup"][g])
        DT = int(fl["mindown"][g])
        for h in range(H):
            # commitment logic
            if h == 0:
                b.add_eq({u[g, 0]: 1.0, v[g, 0]: -1.0, w[g, 0]: 1.0}, 0.0)
            else:
                b.add_eq({u[g, h]: 1.0, u[g, h - 1]: -1.0,
                          v[g, h]: -1.0, w[g, h]: 1.0}, 0.0)
            # min-up / min-down (Rajan–Takriti turn-on/off inequalities)
            if UT > 1:
                coeffs = {v[g, t]: 1.0 for t in range(max(0, h - UT + 1), h + 1)}
                coeffs[u[g, h]] = coeffs.get(u[g, h], 0.0) - 1.0
                b.add_le(coeffs, 0.0)
            if DT > 1:
                coeffs = {w[g, t]: 1.0 for t in range(max(0, h - DT + 1), h + 1)}
                coeffs[u[g, h]] = coeffs.get(u[g, h], 0.0) + 1.0
                b.add_le(coeffs, 1.0)
            # capacity
            b.add_le({p[g, h]: 1.0, u[g, h]: -pmax_g}, 0.0)
            b.add_ge({p[g, h]: 1.0, u[g, h]: -pmin_g}, 0.0)
            # ramps with startup/shutdown allowances
            if h == 0:
                b.add_le({p[g, 0]: 1.0, v[g, 0]: -SU}, 0.0)
            else:
                b.add_le({p[g, h]: 1.0, p[g, h - 1]: -1.0,
                          u[g, h - 1]: -RU, v[g, h]: -SU}, 0.0)
                b.add_le({p[g, h - 1]: 1.0, p[g, h]: -1.0,
                          u[g, h]: -RU, w[g, h]: -SU}, 0.0)
    # balance + reserve rows LAST (their rhs is the per-scenario part)
    for h in range(H):
        coeffs = {p[g, h]: 1.0 for g in range(G)}
        coeffs[shed[h]] = 1.0
        b.add_ge(coeffs, 0.0)                       # >= demand - wind_s
    for h in range(H):
        coeffs = {u[g, h]: float(fl["pmax"][g]) for g in range(G)}
        for g in range(G):
            coeffs[p[g, h]] = -1.0
        coeffs[rsh[h]] = 1.0
        b.add_ge(coeffs, 0.0)                       # >= reserve requirement

    mdl = b.build()
    m = mdl.num_rows
    balance_rows = np.arange(m - 2 * H, m - H)
    reserve_rows = np.arange(m - H, m)
    nonants = u.reshape(-1).astype(np.int32)
    _TEMPLATE_CACHE[key] = (mdl, balance_rows, reserve_rows, nonants, fl)
    return _TEMPLATE_CACHE[key]


def _wind_demand(scennum, seedoffset, horizon, fl, wind_frac):
    """Deterministic demand sinusoid + per-scenario wind random walk,
    mirroring the reference's wind-scenario ladders
    (paperruns/larger_uc/*scenarios_wind)."""
    cap = fl["pmax"].sum()
    t = np.arange(horizon)
    demand = 0.65 * cap * (1.0 + 0.25 * np.sin(2 * np.pi * (t - 6) / 24.0)
                           + 0.08 * np.sin(4 * np.pi * (t - 2) / 24.0))
    stream = np.random.RandomState(91000 + scennum + seedoffset)
    wind_mean = wind_frac * cap
    walk = np.cumsum(stream.normal(0.0, 0.12 * wind_mean, horizon))
    diurnal = 0.3 * wind_mean * np.sin(2 * np.pi * (t + 6) / 24.0)
    wind = np.clip(wind_mean + diurnal + walk, 0.0, 2.0 * wind_mean)
    return demand, wind


def scenario_creator(scenario_name, num_gens=30, horizon=24, num_scens=None,
                     seedoffset=0, relax_integers=False, wind_frac=0.25):
    scennum = extract_num(scenario_name)
    mdl, balance_rows, reserve_rows, nonants, fl = _template(
        num_gens, horizon, seedoffset, relax_integers)
    demand, wind = _wind_demand(scennum, seedoffset, horizon, fl, wind_frac)
    cl = mdl.cl.copy()
    cl[balance_rows] = demand - wind
    cl[reserve_rows] = RESERVE_FRAC * demand
    return dataclasses.replace(
        mdl,
        name=scenario_name,
        cl=cl,
        prob=None if num_scens is None else 1.0 / num_scens,
        nodes=[ScenarioNode("ROOT", 1.0, 1, nonants)],
    )


def scenario_denouement(rank, scenario_name, scenario):
    pass
