"""Reference UC datasets -> tpusppy scenarios (data-comparable benchmarks).

Ingests the reference's actual stochastic-UC inputs — the WECC-240 system
shipping in ``examples/uc/{3..100}scenarios_r1/`` (demand uncertainty) and
the ``paperruns/larger_uc/{3..1000}scenarios_wind/`` ladders (wind
uncertainty) — so benchmark instances use the reference's DATA, not just
its shape (VERDICT r3 missing #4).  The directory layout is PySP node data:
``RootNode.dat`` (system + fleet + costs), ``Node<k>.dat`` (per-scenario
demand or wind), ``ScenarioStructure.dat`` (names -> leaves,
probabilities); parsing reuses :mod:`tpusppy.utils.pysp_model.datparser`.

Formulation: the Rajan-Takriti commitment core of :mod:`tpusppy.models.uc`
extended with what the data requires —

- **piecewise production costs**: dispatch above minimum is decomposed into
  convex segments from CostPiecewisePoints/Values (slopes increasing, so
  the LP orders them correctly with no extra gating rows: the existing
  ``p <= pmax u`` row zeroes all segments when a unit is off);
- **initial conditions**: UnitOnT0State fixes the commitment a unit's
  remaining min-up/min-down obligation implies, and h=0 logic/ramp rows use
  UnitOnT0/PowerGeneratedT0;
- **dispatchable wind**: one nonnegative wind variable per hour whose
  per-scenario UPPER BOUND is the dataset's MaxNondispatchablePower —
  bounds vary per scenario, the constraint matrix does not, so the family
  stays on the shared-A engine;
- **reserve + shed**: hourly ReserveRequirement with shortfall penalty,
  LoadMismatchPenalty as VOLL on shed.

Deliberate simplifications vs the reference's egret model (documented so
results are compared knowingly): startup cost uses the hottest lag's value
(StartupCosts[0]; the lag ladder would need typed-startup variables), and
reserve is served by committed headroom only (no quick-start credit).

Reference: ``examples/uc/uc_cylinders.py:74-80`` wires these directories
into its scenario creator; ``paperruns/larger_uc/quartz/1000scen_fw:1-16``
is the headline run config.
"""

from __future__ import annotations

import dataclasses
import glob
import os

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode
from ..utils.pysp_model.datparser import parse_dat_file

_ARITY = {"Demand": 2, "MinNondispatchablePower": 2,
          "MaxNondispatchablePower": 2}

_DATA_CACHE: dict = {}


def load_uc_directory(data_dir: str):
    """Parse a reference UC scenario directory into plain arrays.

    Returns a dict with the fleet (per-gen arrays), horizon, demand (root
    or per-scenario), wind bounds (per-scenario, zero when absent),
    reserve requirement, penalties, scenario names and probabilities.
    """
    key = os.path.abspath(data_dir)
    if key in _DATA_CACHE:
        return _DATA_CACHE[key]
    root = parse_dat_file(os.path.join(data_dir, "RootNode.dat"), _ARITY)
    struct = parse_dat_file(
        os.path.join(data_dir, "ScenarioStructure.dat"), _ARITY)
    scen_names = [str(s) for s in struct["Scenarios"]]
    leaf_of = struct["ScenarioLeafNode"]
    condp = struct["ConditionalProbability"]
    probs = np.asarray([float(condp[leaf_of[s]]) for s in scen_names])
    probs = probs / probs.sum()

    H = int(root["NumTimePeriods"])
    gens = [str(g) for g in root["ThermalGenerators"]]

    def col(name, cast=float):
        return np.asarray([cast(root[name][g]) for g in gens])

    fleet = dict(
        names=gens,
        p0=col("PowerGeneratedT0"),
        t0state=col("UnitOnT0State", int),
        pmin=col("MinimumPowerOutput"),
        pmax=col("MaximumPowerOutput"),
        minup=np.maximum(col("MinimumUpTime", int), 1),
        mindown=np.maximum(col("MinimumDownTime", int), 1),
        rampup=col("NominalRampUpLimit"),
        rampdown=col("NominalRampDownLimit"),
        startramp=col("StartupRampLimit"),
        shutramp=col("ShutdownRampLimit"),
    )
    # piecewise production cost: points from pmin..pmax, values $(at point);
    # segment slopes are nondecreasing (convex), checked here
    pw_pts, pw_vals = [], []
    for g in gens:
        pts = [float(x) for x in root[f"CostPiecewisePoints[{g}]"]]
        vals = [float(x) for x in root[f"CostPiecewiseValues[{g}]"]]
        slopes = np.diff(vals) / np.maximum(np.diff(pts), 1e-12)
        if np.any(np.diff(slopes) < -1e-6 * np.abs(slopes[:-1])):
            raise ValueError(f"non-convex cost curve for {g}")
        pw_pts.append(np.asarray(pts))
        pw_vals.append(np.asarray(vals))
    fleet["pw_pts"] = pw_pts
    fleet["pw_vals"] = pw_vals
    # hottest-lag startup cost (see module docstring)
    fleet["startcost"] = np.asarray(
        [float(root[f"StartupCosts[{g}]"][0]) for g in gens])

    resreq = np.zeros(H)
    rr = root.get("ReserveRequirement")
    if rr:
        for h in range(H):
            resreq[h] = float(rr.get(h + 1, 0.0) or 0.0)
    voll = float(root.get("LoadMismatchPenalty", 1e6))

    bus = str(root["Buses"][0])
    demand_root = None
    if "Demand" in root:
        demand_root = np.asarray(
            [float(root["Demand"][(bus, h + 1)]) for h in range(H)])

    node_files = {
        os.path.splitext(os.path.basename(p))[0]: p
        for p in glob.glob(os.path.join(data_dir, "Node*.dat"))}
    demand_s, wind_s = {}, {}
    for s in scen_names:
        leaf = str(leaf_of[s])
        nd = parse_dat_file(node_files[leaf], _ARITY)
        if "Demand" in nd:
            demand_s[s] = np.asarray(
                [float(nd["Demand"][(bus, h + 1)]) for h in range(H)])
        if "MaxNondispatchablePower" in nd:
            # hours beyond the data (wind ladders carry 24 h of wind on a
            # 48-period system) default to 0, AMPL sparse-param semantics
            w = nd["MaxNondispatchablePower"]
            srcs = sorted({k[0] for k in w})
            wind_s[s] = np.asarray(
                [sum(float(w.get((src, h + 1), 0.0)) for src in srcs)
                 for h in range(H)])
    data = dict(H=H, fleet=fleet, probs=probs, scen_names=scen_names,
                demand_root=demand_root, demand_s=demand_s, wind_s=wind_s,
                resreq=resreq, voll=voll)
    _DATA_CACHE[key] = data
    return data


def _template(data, horizon, relax_integers):
    """Scenario-independent model skeleton (per-scenario parts are rhs of
    the trailing balance rows and the wind variable bounds)."""
    fl = data["fleet"]
    G = len(fl["names"])
    H = horizon
    as_int = not relax_integers
    voll = data["voll"]
    b = LinearModelBuilder("uc_data")
    u = np.empty((G, H), dtype=np.int64)
    v = np.empty((G, H), dtype=np.int64)
    w = np.empty((G, H), dtype=np.int64)
    p = np.empty((G, H), dtype=np.int64)
    seg = {}           # (g, h) -> list of segment var ids
    u0 = (fl["t0state"] > 0).astype(float)

    for g in range(G):
        # cost at pmin is the commitment's standing cost (value[0]); the
        # hottest-lag startup cost rides the v variable
        for h in range(H):
            u[g, h] = b.add_var(f"u[{g},{h}]", lb=0.0, ub=1.0,
                                cost=float(fl["pw_vals"][g][0]),
                                integer=as_int)
    for g in range(G):
        for h in range(H):
            v[g, h] = b.add_var(f"v[{g},{h}]", lb=0.0, ub=1.0,
                                cost=float(fl["startcost"][g]))
    for g in range(G):
        for h in range(H):
            w[g, h] = b.add_var(f"w[{g},{h}]", lb=0.0, ub=1.0)
    for g in range(G):
        pts = fl["pw_pts"][g]
        vals = fl["pw_vals"][g]
        widths = np.diff(pts)
        slopes = np.diff(vals) / np.maximum(widths, 1e-12)
        for h in range(H):
            p[g, h] = b.add_var(f"p[{g},{h}]", lb=0.0)
            seg[(g, h)] = [
                b.add_var(f"pseg[{g},{h},{k}]", lb=0.0,
                          ub=float(widths[k]), cost=float(slopes[k]))
                for k in range(len(widths))]
    windp = b.add_vars("wind", H, lb=0.0)      # ub set per scenario
    shed = b.add_vars("shed", H, lb=0.0, cost=voll)
    rsh = b.add_vars("rsh", H, lb=0.0, cost=0.2 * voll)

    # T0 obligations: a unit on (off) for tau hours must stay on (off)
    # until its min-up (min-down) clock expires
    for g in range(G):
        st = int(fl["t0state"][g])
        if st > 0:
            for h in range(min(int(fl["minup"][g]) - st, H)):
                b._lb[u[g, h]] = 1.0
        else:
            for h in range(min(int(fl["mindown"][g]) + st, H)):
                b._ub[u[g, h]] = 0.0

    for g in range(G):
        pmax_g = float(fl["pmax"][g])
        pmin_g = float(fl["pmin"][g])
        RU = float(fl["rampup"][g])
        RD = float(fl["rampdown"][g])
        SU = float(fl["startramp"][g])
        SD = float(fl["shutramp"][g])
        UT = int(fl["minup"][g])
        DT = int(fl["mindown"][g])
        p0 = float(fl["p0"][g])
        for h in range(H):
            # commitment logic (rhs carries u0 at h=0)
            if h == 0:
                b.add_eq({u[g, 0]: 1.0, v[g, 0]: -1.0, w[g, 0]: 1.0},
                         u0[g])
            else:
                b.add_eq({u[g, h]: 1.0, u[g, h - 1]: -1.0,
                          v[g, h]: -1.0, w[g, h]: 1.0}, 0.0)
            if UT > 1:
                coeffs = {v[g, t]: 1.0
                          for t in range(max(0, h - UT + 1), h + 1)}
                coeffs[u[g, h]] = coeffs.get(u[g, h], 0.0) - 1.0
                b.add_le(coeffs, 0.0)
            if DT > 1:
                coeffs = {w[g, t]: 1.0
                          for t in range(max(0, h - DT + 1), h + 1)}
                coeffs[u[g, h]] = coeffs.get(u[g, h], 0.0) + 1.0
                b.add_le(coeffs, 1.0)
            # piecewise decomposition + capacity
            coeffs = {p[g, h]: 1.0, u[g, h]: -pmin_g}
            for sv in seg[(g, h)]:
                coeffs[sv] = -1.0
            b.add_eq(coeffs, 0.0)
            b.add_le({p[g, h]: 1.0, u[g, h]: -pmax_g}, 0.0)
            # ramps (h=0 rhs carries p0/u0)
            if h == 0:
                # p[0] - p0 <= RU u0 + SU v[0];  p0 - p[0] <= RD u[0] + SD w[0]
                b.add_le({p[g, 0]: 1.0, v[g, 0]: -SU},
                         p0 + RU * u0[g])
                b.add_le({p[g, 0]: -1.0, u[g, 0]: -RD, w[g, 0]: -SD},
                         -p0)
            else:
                b.add_le({p[g, h]: 1.0, p[g, h - 1]: -1.0,
                          u[g, h - 1]: -RU, v[g, h]: -SU}, 0.0)
                b.add_le({p[g, h - 1]: 1.0, p[g, h]: -1.0,
                          u[g, h]: -RD, w[g, h]: -SD}, 0.0)

    # balance + reserve rows LAST (their rhs is the per-scenario part)
    for h in range(H):
        coeffs = {p[g, h]: 1.0 for g in range(G)}
        coeffs[windp[h]] = 1.0
        coeffs[shed[h]] = 1.0
        b.add_ge(coeffs, 0.0)                      # >= demand_s[h]
    for h in range(H):
        coeffs = {u[g, h]: float(fl["pmax"][g]) for g in range(G)}
        for g in range(G):
            coeffs[p[g, h]] = -1.0
        coeffs[rsh[h]] = 1.0
        b.add_ge(coeffs, 0.0)                      # >= resreq[h]

    mdl = b.build()
    m = mdl.num_rows
    balance_rows = np.arange(m - 2 * H, m - H)
    reserve_rows = np.arange(m - H, m)
    nonants = u.reshape(-1).astype(np.int32)
    wind_cols = np.asarray(windp, dtype=np.int64)
    repair = _make_repair(
        fl, G, H, u, v, w, p, seg, balance_rows, reserve_rows, wind_cols,
        np.asarray(shed, dtype=np.int64), np.asarray(rsh, dtype=np.int64),
        u0)
    mdl = dataclasses.replace(mdl, repair_fn=repair)
    return mdl, balance_rows, reserve_rows, nonants, wind_cols


def _make_repair(fl, G, H, u_ids, v_ids, w_ids, p_ids, seg_ids,
                 balance_rows, reserve_rows, wind_cols, shed_cols, rsh_cols,
                 u0):
    """Closed-form feasibility repair for the UC family — the scalable
    certified-inner-bound mechanism (``ScenarioProblem.repair_fn``).

    Given any commitment candidate u that satisfies the u-only rows
    (min-up/down, T0 clocks — donor-MILP and restricted-EF candidates do by
    construction; violations are caught by the caller's exact row
    verification), a feasible point ALWAYS exists: the family has full
    dispatch recourse (one-sided balance with VOLL shed, reserve shortfall
    at 0.2 VOLL).  The repair maps the device's near-feasible solution to
    an exactly feasible one in O(S*G*H) vectorized numpy:

      v/w    <- exactly from the u transitions (commitment eq rows);
      p      <- clipped into the per-generator ramp tube: forward/backward
                envelope tightening + a greedy feasible path that stays as
                close to the device dispatch as the tube allows;
      seg    <- convex-order (cheapest-first) fill of p - Pmin*u;
      wind   <- clipped into the scenario bounds;
      shed / rsh <- exact residuals of the balance / reserve rows.

    The repaired objective is a certified upper bound (feasible by
    construction) and tight when the device solve was near-feasible —
    replacing the per-scenario host-LP rescue whose O(S) seconds forbade
    S=1000 evaluation.  Reference context: the reference's incumbents are
    feasible for free because Gurobi/CPLEX solve each scenario exactly
    (xhatbase.py:38-230); this is the batched-LP path's equivalent.
    """
    pmin = np.asarray(fl["pmin"], float)
    pmax = np.asarray(fl["pmax"], float)
    RU = np.asarray(fl["rampup"], float)
    RD = np.asarray(fl["rampdown"], float)
    SU = np.asarray(fl["startramp"], float)
    SD = np.asarray(fl["shutramp"], float)
    p0 = np.asarray(fl["p0"], float)
    u_flat = np.asarray(u_ids).reshape(-1)
    v_flat = np.asarray(v_ids).reshape(-1)
    w_flat = np.asarray(w_ids).reshape(-1)
    p_flat = np.asarray(p_ids).reshape(-1)
    # per-gen segment ids + widths (ragged across gens)
    seg_per_gen = []
    for g in range(G):
        ids = np.asarray([seg_ids[(g, h)] for h in range(H)])  # (H, Kg)
        widths = np.diff(np.asarray(fl["pw_pts"][g], float))
        seg_per_gen.append((ids, widths))

    def repair(x, batch):
        S = x.shape[0]
        x = np.array(np.asarray(x, float), copy=True)
        u = np.clip(np.round(x[:, u_flat]), 0.0, 1.0).reshape(S, G, H)
        u_prev = np.concatenate(
            [np.broadcast_to(u0, (S, G))[:, :, None], u[:, :, :-1]], axis=2)
        v = np.maximum(0.0, u - u_prev)
        w = np.maximum(0.0, u_prev - u)
        cap = pmax[None, :, None] * u
        lo = pmin[None, :, None] * u
        up_h = RU[None, :, None] * u_prev + SU[None, :, None] * v
        dn_h = RD[None, :, None] * u + SD[None, :, None] * w
        # forward/backward envelopes of the ramp-feasible tube
        f = np.empty((S, G, H))
        g_lo = np.empty((S, G, H))
        hi = np.broadcast_to(p0, (S, G)).copy()
        lo_run = hi.copy()
        for h in range(H):
            hi = np.minimum(cap[:, :, h], hi + up_h[:, :, h])
            lo_run = np.maximum(lo[:, :, h], lo_run - dn_h[:, :, h])
            f[:, :, h] = hi
            g_lo[:, :, h] = lo_run
        for h in range(H - 2, -1, -1):
            f[:, :, h] = np.minimum(f[:, :, h],
                                    f[:, :, h + 1] + dn_h[:, :, h + 1])
            g_lo[:, :, h] = np.maximum(g_lo[:, :, h],
                                       g_lo[:, :, h + 1] - up_h[:, :, h + 1])
        # greedy feasible path closest to the device dispatch
        p_dev = x[:, p_flat].reshape(S, G, H)
        p_fix = np.empty((S, G, H))
        prev = np.broadcast_to(p0, (S, G)).copy()
        for h in range(H):
            step_lo = np.maximum(g_lo[:, :, h], prev - dn_h[:, :, h])
            step_hi = np.minimum(f[:, :, h], prev + up_h[:, :, h])
            step_hi = np.maximum(step_hi, step_lo)   # numerical guard
            prev = np.clip(p_dev[:, :, h], step_lo, step_hi)
            p_fix[:, :, h] = prev
        x[:, u_flat] = u.reshape(S, -1)
        x[:, v_flat] = v.reshape(S, -1)
        x[:, w_flat] = w.reshape(S, -1)
        x[:, p_flat] = p_fix.reshape(S, -1)
        for g in range(G):
            ids, widths = seg_per_gen[g]
            q = np.maximum(0.0, p_fix[:, g, :] - pmin[g] * u[:, g, :])
            csum = np.concatenate([[0.0], np.cumsum(widths)[:-1]])
            segs = np.clip(q[:, :, None] - csum[None, None, :],
                           0.0, widths[None, None, :])
            x[:, ids.reshape(-1)] = segs.reshape(S, -1)
        wub = np.asarray(batch.ub)[:, wind_cols]
        wlb = np.asarray(batch.lb)[:, wind_cols]
        wind = np.clip(x[:, wind_cols], wlb, wub)
        x[:, wind_cols] = wind
        demand = np.asarray(batch.cl)[:, balance_rows]
        totp = p_fix.sum(axis=1)
        x[:, shed_cols] = np.maximum(0.0, demand - totp - wind)
        resreq = np.asarray(batch.cl)[:, reserve_rows]
        headroom = (pmax[None, :, None] * u).sum(axis=1) - totp
        x[:, rsh_cols] = np.maximum(0.0, resreq - headroom)
        return x

    return repair


def scenario_names_creator(num_scens=None, start=0, data_dir=None):
    if data_dir is not None:
        names = load_uc_directory(data_dir)["scen_names"]
        return names if num_scens is None else names[start:start + num_scens]
    return [f"Scenario{i + 1}" for i in range(start, start + (num_scens or 0))]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = (cfg.get if hasattr(cfg, "get")
           else lambda k, d=None: getattr(cfg, k, d))
    return {
        "data_dir": kwargs.get("data_dir", get("uc_data")),
        "horizon": kwargs.get("horizon", get("uc_horizon")),
        "num_scens": kwargs.get("num_scens", get("num_scens")),
        "relax_integers": kwargs.get("relax_integers",
                                     get("relax_integers", False)),
    }


def inparser_adder(cfg):
    cfg.add_to_config(
        "uc_data", "reference UC scenario directory "
        "(examples/uc/*scenarios_r1 or paperruns wind ladders)", str, None)


def scenario_creator(scenario_name, data_dir=None, horizon=None,
                     relax_integers=False, num_scens=None):
    """Scenario from a reference UC data directory.

    ``horizon`` truncates NumTimePeriods (the 48 h WECC instances are heavy
    for CI; the paper runs use the full horizon).  ``num_scens`` selects
    the leading scenarios of the directory with renormalized probabilities
    (truncated ladders for degraded benches/tests).
    """
    if data_dir is None:
        raise ValueError("uc_data scenarios need data_dir=<reference dir>")
    data = load_uc_directory(data_dir)
    H = int(horizon or data["H"])
    if H > int(data["H"]):
        raise ValueError(
            f"horizon {H} exceeds the dataset's NumTimePeriods "
            f"{data['H']} ({data_dir})")
    tkey = (os.path.abspath(data_dir), H, bool(relax_integers))
    cached = _DATA_CACHE.get(tkey)
    if cached is None:
        cached = _DATA_CACHE[tkey] = _template(data, H, relax_integers)
    mdl, balance_rows, reserve_rows, nonants, wind_cols = cached

    s = str(scenario_name)
    demand = data["demand_s"].get(s, data["demand_root"])
    if demand is None:
        raise ValueError(f"no demand data for scenario {s}")
    wind_ub = data["wind_s"].get(s, np.zeros(data["H"]))
    cl = mdl.cl.copy()
    cl[balance_rows] = demand[:H]
    cl[reserve_rows] = data["resreq"][:H]
    ub = mdl.ub.copy()
    ub[wind_cols] = wind_ub[:H]
    idx = data["scen_names"].index(s)
    prob = float(data["probs"][idx])
    if num_scens is not None:
        sel = data["probs"][:int(num_scens)]
        if idx >= len(sel):
            raise ValueError(f"{s} outside the first {num_scens} scenarios")
        prob = float(sel[idx] / sel.sum())
    return dataclasses.replace(
        mdl, name=s, cl=cl, ub=ub, prob=prob,
        nodes=[ScenarioNode("ROOT", 1.0, 1, nonants)],
    )


def scenario_denouement(rank, scenario_name, scenario):
    pass
