"""APL1P: two-stage stochastic capacity expansion (Infanger 1992).

Behavioral port of ``mpisppy/tests/examples/apl1p.py``: two generators with
random availability, three demand levels with random demand; first stage
chooses generator capacities (the nonants), second stage dispatches
operation and unserved demand.  Randomness comes from a per-scenario seeded
RandomState drawing the same outcome tables as the reference (costs from
Bailey/Jensen/Morton, 10x Infanger).
"""

from __future__ import annotations

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

AVAIL_OUTCOME = ([1.0, 0.9, 0.5, 0.1], [1.0, 0.9, 0.7, 0.1, 0.0])
AVAIL_PROB = ([0.2, 0.3, 0.4, 0.1], [0.1, 0.2, 0.5, 0.1, 0.1])
CMIN = 1000.0
INVEST = np.array([4.0, 2.5])
OP_COST = np.array([[4.3, 2.0, 0.5], [8.7, 4.0, 1.0]])
DEMAND_OUTCOME = [900.0, 1000.0, 1100.0, 1200.0]
DEMAND_PROB = [0.15, 0.45, 0.25, 0.15]
UNSERVED_COST = 10.0


def scenario_names_creator(num_scens, start=None):
    start = start or 0
    return [f"scen{i}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {"num_scens": kwargs.get("num_scens", get("num_scens"))}


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()


def scenario_creator(sname, num_scens=None):
    scennum = extract_num(sname)
    stream = np.random.RandomState(scennum)
    rand = stream.rand(6)

    # index discipline from the reference: availability for generator g in
    # {1,2} draws random_array[g]; demand level dl in {1,2,3} draws
    # random_array[2+dl]
    avail = np.empty(2)
    avail[0] = AVAIL_OUTCOME[0][int(np.searchsorted(np.cumsum(AVAIL_PROB[0]),
                                                    rand[1]))]
    avail[1] = AVAIL_OUTCOME[1][int(np.searchsorted(np.cumsum(AVAIL_PROB[1]),
                                                    rand[2]))]
    dcum = np.cumsum(DEMAND_PROB)
    demand = np.array([
        DEMAND_OUTCOME[int(np.searchsorted(dcum, rand[2 + dl]))]
        for dl in (1, 2, 3)
    ])

    b = LinearModelBuilder(sname)
    cap = b.add_vars("CapacityGenerators", 2, lb=0.0)
    for g in range(2):
        b.set_cost(cap[g], INVEST[g])
    op = {}
    for g in range(2):
        for dl in range(3):
            op[g, dl] = b.add_var(f"OperationLevel[{g},{dl}]", lb=0.0,
                                  cost=OP_COST[g, dl])
    unserved = b.add_vars("UnservedDemand", 3, lb=0.0, cost=UNSERVED_COST)

    for g in range(2):
        b.add_ge({cap[g]: 1.0}, CMIN)                       # min capacity
        coeffs = {op[g, dl]: 1.0 for dl in range(3)}
        coeffs[cap[g]] = -avail[g]
        b.add_le(coeffs, 0.0)                               # max operating
    for dl in range(3):
        coeffs = {op[g, dl]: 1.0 for g in range(2)}
        coeffs[unserved[dl]] = 1.0
        b.add_ge(coeffs, float(demand[dl]))                 # satisfy demand

    p = b.build()
    p.prob = None if num_scens is None else 1.0 / num_scens
    p.nodes = [ScenarioNode("ROOT", 1.0, 1, np.asarray(cap, dtype=np.int32))]
    return p


def scenario_denouement(rank, scenario_name, scenario):
    pass
