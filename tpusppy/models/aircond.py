"""Aircond: scalable multistage production/inventory model.

Behavioral port of ``mpisppy/tests/examples/aircond.py`` (602 LoC): per stage,
regular and overtime production with capacity, inventory carried between
stages split into positive/negative parts with asymmetric costs (negative =
backorders; the LAST stage rewards positive inventory with a negative cost),
and per-node demand following a clipped random walk whose per-node seeds come
from ``start_seed + node_idx(path, branching_factors)`` — so demands are
node-consistent across the scenarios through a node, exactly as the
reference's ``_demands_creator`` (aircond.py:37-68).

Nonanticipative variables per nonleaf stage t: (RegularProd_t,
OvertimeProd_t) (MakeNodesforScen, aircond.py:251-302).  ``start_ups`` adds a
per-stage binary with a big-M linking constraint (MIP mode).
"""

from __future__ import annotations

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

parms = {
    "mu_dev": (float, 0.0),
    "sigma_dev": (float, 40.0),
    "start_ups": (bool, False),
    "StartUpCost": (float, 300.0),
    "start_seed": (int, 1134),
    "min_d": (float, 0.0),
    "max_d": (float, 400.0),
    "starting_d": (float, 200.0),
    "BeginInventory": (float, 200.0),
    "InventoryCost": (float, 0.5),
    "LastInventoryCost": (float, -0.8),
    "Capacity": (float, 200.0),
    "RegularProdCost": (float, 1.0),
    "OvertimeProdCost": (float, 3.0),
    "NegInventoryCost": (float, 5.0),
    "QuadShortCoeff": (float, 0.0),
}

MAX_T = 25


def _nodenum_before_stage(t, branching_factors):
    total = 0
    prod = 1
    for i in range(t - 1):
        prod *= branching_factors[i]
        total += prod
    return 1 + total - prod if t > 0 else 0


def node_idx(node_path, branching_factors):
    """Unique id of a tree node from its path (sputils.py:492-520)."""
    if not node_path:
        return 0
    stage_id = 0
    for t in range(len(node_path)):
        stage_id = node_path[t] + branching_factors[t] * stage_id
    before = 1
    prod = 1
    for i in range(len(node_path) - 1):
        prod *= branching_factors[i]
        before += prod
    return before + stage_id


def _demands_creator(sname, sample_branching_factors, root_name="ROOT",
                     **kwargs):
    """(aircond.py:37-68): clipped random walk with node-indexed seeds."""
    branching_factors = sample_branching_factors
    kwargs.pop("branching_factors", None)
    start_seed = kwargs["start_seed"]
    max_d = kwargs.get("max_d", 400)
    min_d = kwargs.get("min_d", 0)
    mu_dev = kwargs.get("mu_dev", 0.0)
    sigma_dev = kwargs.get("sigma_dev", 40.0)

    scennum = extract_num(sname)
    prod = int(np.prod(branching_factors))
    s = int(scennum % prod)
    d = kwargs.get("starting_d", 200)
    demands = [d]
    nodenames = [root_name]
    for bf in branching_factors:
        prod = prod // bf
        nodenames.append(str(s // prod))
        s = s % prod
    stagelist = [int(x) for x in nodenames[1:]]
    stream = np.random.RandomState()
    for t in range(1, len(nodenames)):
        stream.seed(start_seed + node_idx(stagelist[:t], branching_factors))
        d = min(max_d, max(min_d, d + stream.normal(mu_dev, sigma_dev)))
        demands.append(d)
    return demands, nodenames


def scenario_names_creator(num_scens, start=None):
    start = start or 0
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()
    if "branching_factors" not in cfg:
        cfg.add_branching_factors()
    for name, (dom, dflt) in parms.items():
        if name not in cfg:
            cfg.add_to_config(name, f"aircond {name} (default {dflt})",
                              dom, dflt)


def kw_creator(cfg=None, optionsin=None, **kwonly):
    options = optionsin or {}
    if "kwargs" in options:
        return options["kwargs"]
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    kwargs = {"branching_factors": options.get(
        "branching_factors", kwonly.get("branching_factors",
                                        get("branching_factors")))}
    for name, (dom, dflt) in parms.items():
        v = options.get(name, kwonly.get(name, get(name)))
        kwargs[name] = dflt if v is None else v
    return kwargs


def aircond_model_creator(demands, sname="scen0", **kwargs):
    """Build the per-scenario LP/MIP over all stages (aircond.py:88-249).

    Returns (builder, per-stage var index lists)."""
    g = lambda k: kwargs.get(k, parms[k][1])
    start_ups = g("start_ups")
    T = len(demands)
    if T > MAX_T:
        raise RuntimeError(f"The number of stages exceeds {MAX_T}")
    bigM = g("Capacity") * MAX_T

    b = LinearModelBuilder(sname)
    reg, ot, posI, negI, su = [], [], [], [], []
    for t in range(T):
        last = t == T - 1
        reg.append(b.add_var(f"RegularProd[{t}]", lb=0.0, ub=bigM,
                             cost=g("RegularProdCost")))
        ot.append(b.add_var(f"OvertimeProd[{t}]", lb=0.0, ub=bigM,
                            cost=g("OvertimeProdCost")))
        inv_cost = g("LastInventoryCost") if last else g("InventoryCost")
        posI.append(b.add_var(f"posInventory[{t}]", lb=0.0, ub=bigM,
                              cost=inv_cost))
        quad = 2.0 * g("QuadShortCoeff") if (g("QuadShortCoeff") > 0
                                             and not last) else 0.0
        negI.append(b.add_var(f"negInventory[{t}]", lb=0.0, ub=bigM,
                              cost=g("NegInventoryCost"), quad=quad))
        if start_ups:
            su.append(b.add_var(f"StartUp[{t}]", lb=0.0, ub=1.0,
                                cost=g("StartUpCost"), integer=True))
        # capacity on regular production
        b.add_le({reg[t]: 1.0}, g("Capacity"))
        if start_ups:
            b.add_le({reg[t]: 1.0, ot[t]: 1.0, su[t]: -bigM}, 0.0)
        # material balance: I_{t-1} + reg + ot - I_t = demand_t
        coeffs = {reg[t]: 1.0, ot[t]: 1.0,
                  posI[t]: -1.0, negI[t]: 1.0}
        rhs = float(demands[t])
        if t == 0:
            rhs -= g("BeginInventory")
        else:
            coeffs[posI[t - 1]] = 1.0
            coeffs[negI[t - 1]] = -1.0
        b.add_eq(coeffs, rhs)
    return b, reg, ot


def scenario_creator(sname, **kwargs):
    if "branching_factors" not in kwargs or \
            kwargs["branching_factors"] is None:
        raise RuntimeError(
            "scenario_creator for aircond needs branching_factors in kwargs"
        )
    branching_factors = list(kwargs["branching_factors"])
    kwargs.setdefault("start_seed", parms["start_seed"][1])
    demands, nodenames = _demands_creator(sname, branching_factors, **kwargs)

    b, reg, ot = aircond_model_creator(demands, sname=sname, **kwargs)
    T = len(demands)
    # nonleaf nodes: stages 1..T-1 (MakeNodesforScen skips the leaf)
    nodes = []
    ndn = "ROOT"
    for stage in range(1, T):
        if stage == 1:
            cond = 1.0
        else:
            ndn = ndn + "_" + nodenames[stage - 1]
            cond = 1.0 / branching_factors[stage - 2]
        nodes.append(ScenarioNode(
            ndn, cond, stage,
            np.asarray([reg[stage - 1], ot[stage - 1]], dtype=np.int32),
        ))
    p = b.build()
    p.prob = 1.0 / float(np.prod(branching_factors))
    p.nodes = nodes
    return p


def sample_tree_scen_creator(sname, stage, sample_branching_factors, seed,
                             given_scenario=None, **scenario_creator_kwargs):
    """Sample-tree scenario for the CI machinery (aircond.py:332-377):
    demands before ``stage`` come from ``given_scenario`` (a ScenarioProblem
    carrying ``_demands``), later stages are redrawn with the dynamic seed."""
    kwargs = dict(scenario_creator_kwargs)
    kwargs["start_seed"] = seed
    starting_d = kwargs.get("starting_d", parms["starting_d"][1])
    if given_scenario is None:
        if stage != 1:
            raise RuntimeError(
                "sample_tree_scen_creator needs given_scenario for stage > 1"
            )
        past_demands = [starting_d]
    else:
        past_demands = list(given_scenario._demands[:stage])
    future_demands, nodenames = _demands_creator(
        sname, sample_branching_factors,
        root_name="ROOT" + "_0" * (stage - 1), **kwargs)
    demands = past_demands + future_demands[1:]

    b, reg, ot = aircond_model_creator(demands, sname=sname,
                                       **scenario_creator_kwargs)
    T = len(demands)
    nodes = []
    ndn = "ROOT"
    bf_offset = stage  # stages 2..stage ride fixed '_0' nodes
    for st in range(1, T):
        if st == 1:
            cond = 1.0
        elif st <= stage:
            ndn = ndn + "_0"
            cond = 1.0
        else:
            ndn = ndn + "_" + nodenames[st - stage]
            cond = 1.0 / sample_branching_factors[st - stage - 1]
        nodes.append(ScenarioNode(
            ndn, cond, st,
            np.asarray([reg[st - 1], ot[st - 1]], dtype=np.int32),
        ))
    p = b.build()
    p.prob = 1.0 / float(np.prod(sample_branching_factors))
    p.nodes = nodes
    p._demands = demands
    return p


def scenario_denouement(rank, scenario_name, scenario):
    pass
