"""Contingency-constrained OPF (CCOPF) in the tpusppy IR — DC approximation.

Mirrors the reference's acopf3 example family (`examples/acopf3/ACtree.py`,
`examples/acopf3/ccopf_multistage.py:67-241`): a multistage stochastic OPF
where transmission lines randomly fail and get repaired along a scenario
tree, each stage solves an OPF with load-mismatch slack, stages couple
through generator ramping, and per-stage generation is nonanticipative at
each tree node.

Honest scope note: the reference builds egret's rectangular-IV ACOPF (or
its SOC relaxation) per stage.  egret is unavailable here and nonconvex AC
physics is outside the LP/convex-QP IR, so this family implements the
classic **DC (B-theta) linearization**: real-power flow f = b*(theta_i -
theta_j) on in-service lines, f = 0 on failed lines, bus balance with
load-mismatch slack at ``load_mismatch_cost`` (the reference's
include_feasibility_slack), and **L1 ramping** r >= |pg[t+1] - pg[t]| at
``ramp_coeff`` (the reference penalizes the squared difference in the
objective, ccopf_multistage.py:190-201; the IR's quadratic term is
diagonal, so the cross-stage square is linearized).  The failure/repair
tree reproduces ACTree's semantics: per-line failure probability per
stage, minutes-out bookkeeping, and a pluggable repair rule (FixFast /
FixNever / probabilistic).

Default grid: the 5-bus PJM test system (gens/loads/lines as in the
public case5 data) — small enough for EF goldens, structured enough for
line outages to matter.
"""

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

# --- repair rules (ccopf_multistage.py:32-49) -----------------------------


def FixFast(minutes):
    return True


def FixNever(minutes):
    return False


# --- default grid: PJM 5-bus ----------------------------------------------
# buses 0..4; loads (MW); generators (bus, pmax, cost $/MWh); lines
# (from, to, susceptance b [p.u. scaled], capacity MW)
CASE5_LOADS = {1: 300.0, 2: 300.0, 3: 400.0}
CASE5_GENS = [
    (0, 110.0, 14.0),     # Alta
    (0, 100.0, 15.0),     # Park City
    (2, 520.0, 30.0),     # Solitude
    (3, 200.0, 40.0),     # Sundance
    (4, 600.0, 10.0),     # Brighton
]
CASE5_LINES = [
    (0, 1, 1.0 / 0.0281, 400.0),
    (0, 3, 1.0 / 0.0304, 1000.0),
    (0, 4, 1.0 / 0.0064, 1000.0),
    (1, 2, 1.0 / 0.0108, 1000.0),
    (2, 3, 1.0 / 0.0297, 1000.0),
    (3, 4, 1.0 / 0.0297, 240.0),
]
NUM_BUSES = 5


class _TreeNode:
    """ACtree.py:89-162 semantics: failed lines carry minutes-out, repairs
    happen first, then fresh failures are drawn per in-service line."""

    def __init__(self, parent, tree, scen_list, name, cond_prob, stream):
        self.name = name
        self.cond_prob = cond_prob
        self.scen_list = scen_list
        self.parent = parent
        if parent is None:
            self.stage = 1
            self.failed = []                     # [(line, minutes_out)]
            self.up = list(tree.line_list)
        else:
            self.stage = parent.stage + 1
            self.failed = list(parent.failed)
            self.up = list(parent.up)
            dur = tree.stage_durations[self.stage - 1]
            still_failed = []
            for line, mo in self.failed:
                if tree.repairer(mo):
                    self.up.append(line)
                else:
                    still_failed.append((line, mo + dur))
            self.failed = still_failed
            # fresh failures (reference iterates while mutating LinesUp,
            # which skips the element after each removal; we draw once per
            # in-service line — same distribution, no iteration quirk)
            survivors = []
            for line in self.up:
                if stream.rand() < tree.fail_prob:
                    self.failed.append((line, dur))
                else:
                    survivors.append(line)
            self.up = survivors
        self.kids = []
        if self.stage < tree.num_stages:
            bf = tree.bfs[self.stage - 1]
            for k in range(bf):
                first = k * len(scen_list) // bf
                last = (k + 1) * len(scen_list) // bf
                self.kids.append(_TreeNode(
                    self, tree, scen_list[first:last],
                    f"{name}_{k}", 1.0 / bf, stream))


class ContingencyTree:
    """ACTree analogue: failure/repair scenario tree over the line set."""

    def __init__(self, num_stages, bfs, seed, fail_prob, stage_durations,
                 repairer, line_list):
        self.num_stages = num_stages
        self.bfs = list(bfs)
        self.fail_prob = fail_prob
        self.stage_durations = list(stage_durations)
        self.repairer = repairer
        self.line_list = list(line_list)
        self.num_scens = int(np.prod(bfs))
        stream = np.random.RandomState(seed)
        self.root = _TreeNode(None, self,
                              list(range(1, self.num_scens + 1)),
                              "ROOT", 1.0, stream)

    def nodes_for_scenario(self, snum):
        """Stage-ordered node path for 1-based scenario ``snum``
        (ACtree.py:60-72)."""
        if not 1 <= snum <= self.num_scens:
            raise ValueError(
                f"scenario {snum} outside 1..{self.num_scens} (the tree has "
                f"prod(branching_factors) = {self.num_scens} scenarios)")
        path = [self.root]
        while path[-1].kids:
            for kid in path[-1].kids:
                if snum in kid.scen_list:
                    path.append(kid)
                    break
            else:
                raise RuntimeError(
                    f"scenario {snum} missing from every child of "
                    f"{path[-1].name}")
        return path

    def all_nodenames(self):
        out = []

        def walk(node):
            out.append(node.name)
            for kid in node.kids:
                walk(kid)

        walk(self.root)
        return out


_TREE_CACHE = {}


def _tree(branching_factors, seed, fail_prob, repair):
    key = (tuple(branching_factors), seed, fail_prob, repair)
    if key not in _TREE_CACHE:
        repairer = {"fast": FixFast, "never": FixNever}[repair]
        num_stages = len(branching_factors) + 1
        durations = [5 * 3 ** t for t in range(num_stages)]
        _TREE_CACHE[key] = ContingencyTree(
            num_stages, branching_factors, seed, fail_prob, durations,
            repairer, list(range(len(CASE5_LINES))))
    return _TREE_CACHE[key]


def scenario_names_creator(num_scens, start=0):
    return [f"Scen{i + 1}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = (cfg.get if hasattr(cfg, "get")
           else lambda k, d=None: getattr(cfg, k, d))

    def pick(name, default):
        v = kwargs.get(name, get(name, default))
        return default if v is None else v

    return {
        "branching_factors": pick("branching_factors", [2, 2]),
        "seed": pick("seed", 1134),
        "fail_prob": pick("fail_prob", 0.2),
        "repair": pick("repair", "fast"),
        "ramp_coeff": pick("ramp_coeff", 100.0),
        "load_mismatch_cost": pick("load_mismatch_cost", 1000.0),
    }


def inparser_adder(cfg):
    if "branching_factors" not in cfg:
        cfg.add_branching_factors()
    if "num_scens" not in cfg:
        cfg.num_scens_optional() if hasattr(cfg, "num_scens_optional") \
            else None
    for name, domain, default, desc in (
        ("fail_prob", float, 0.2, "per-line failure probability per stage"),
        ("repair", str, "fast", "repair rule: fast | never"),
        ("ramp_coeff", float, 100.0, "L1 ramping cost coefficient"),
        ("load_mismatch_cost", float, 1000.0,
         "cost per MW of unserved/spilled load"),
    ):
        if name not in cfg:
            cfg.add_to_config(name, description=desc, domain=domain,
                              default=default)
    if "seed" not in cfg:
        cfg.add_to_config("seed", description="tree seed", domain=int,
                          default=1134)


def scenario_denouement(rank, scenario_name, scenario):
    pass


def all_nodenames(branching_factors=None, seed=1134, fail_prob=0.2,
                  repair="fast", **_):
    return _tree(branching_factors or [2, 2], seed, fail_prob,
                 repair).all_nodenames()


def scenario_creator(scenario_name, branching_factors=None, seed=1134,
                     fail_prob=0.2, repair="fast", ramp_coeff=100.0,
                     load_mismatch_cost=1000.0):
    """One CCOPF scenario: a DC-OPF block per stage along the line-outage
    tree path, ramp-coupled, pg nonanticipative per nonleaf node
    (ccopf_multistage.py:211-226 attaches [pg, qg]; DC has no qg)."""
    branching_factors = branching_factors or [2, 2]
    tree = _tree(branching_factors, seed, fail_prob, repair)
    snum = extract_num(scenario_name)
    path = tree.nodes_for_scenario(snum)
    T = tree.num_stages
    G = len(CASE5_GENS)
    B = NUM_BUSES
    L = len(CASE5_LINES)

    b = LinearModelBuilder(scenario_name)
    pg = np.empty((T, G), dtype=np.int64)
    th = np.empty((T, B), dtype=np.int64)
    fl = np.empty((T, L), dtype=np.int64)
    sp = np.empty((T, B), dtype=np.int64)
    sn = np.empty((T, B), dtype=np.int64)
    for t in range(T):
        up = set(path[t].up)
        for g, (bus, pmax, cost) in enumerate(CASE5_GENS):
            pg[t, g] = b.add_var(f"pg[{t},{g}]", lb=0.0, ub=pmax, cost=cost)
        for i in range(B):
            # reference bus 0 pinned; others free
            lim = 0.0 if i == 0 else np.pi
            th[t, i] = b.add_var(f"th[{t},{i}]", lb=-lim, ub=lim)
        for l, (fi, ti, susc, cap) in enumerate(CASE5_LINES):
            c = cap if l in up else 0.0
            fl[t, l] = b.add_var(f"f[{t},{l}]", lb=-c, ub=c)
        for i in range(B):
            sp[t, i] = b.add_var(f"s+[{t},{i}]", lb=0.0,
                                 cost=load_mismatch_cost)
            sn[t, i] = b.add_var(f"s-[{t},{i}]", lb=0.0,
                                 cost=load_mismatch_cost)
        # flow definition on in-service lines: f - b*(th_i - th_j) = 0;
        # failed lines keep f = 0 (same row count in every scenario)
        for l, (fi, ti, susc, cap) in enumerate(CASE5_LINES):
            if l in up:
                b.add_eq({int(fl[t, l]): 1.0, int(th[t, fi]): -susc,
                          int(th[t, ti]): susc}, 0.0)
            else:
                b.add_eq({int(fl[t, l]): 1.0}, 0.0)
        # bus balance: gen - outflow + inflow + s+ - s- = load
        for i in range(B):
            coeffs = {int(sp[t, i]): 1.0, int(sn[t, i]): -1.0}
            for g, (bus, _, _) in enumerate(CASE5_GENS):
                if bus == i:
                    coeffs[int(pg[t, g])] = 1.0
            for l, (fi, ti, _, _) in enumerate(CASE5_LINES):
                if fi == i:
                    coeffs[int(fl[t, l])] = \
                        coeffs.get(int(fl[t, l]), 0.0) - 1.0
                if ti == i:
                    coeffs[int(fl[t, l])] = \
                        coeffs.get(int(fl[t, l]), 0.0) + 1.0
            b.add_eq(coeffs, CASE5_LOADS.get(i, 0.0))
    # L1 ramping between consecutive stages (linearized analogue of the
    # reference's squared ramping expression)
    for t in range(T - 1):
        for g in range(G):
            r = b.add_var(f"ramp[{t},{g}]", lb=0.0, cost=ramp_coeff)
            b.add_ge({r: 1.0, int(pg[t + 1, g]): -1.0, int(pg[t, g]): 1.0},
                     0.0)
            b.add_ge({r: 1.0, int(pg[t + 1, g]): 1.0, int(pg[t, g]): -1.0},
                     0.0)

    p = b.build()
    p.prob = 1.0 / tree.num_scens
    p.nodes = [
        ScenarioNode(path[t].name, path[t].cond_prob, t + 1,
                     pg[t].astype(np.int32))
        for t in range(T - 1)       # nonleaf stages only
    ]
    return p
