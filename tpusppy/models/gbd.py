"""GBD: aircraft allocation under uncertain route demand (Dantzig 1956).

Behavioral port of ``mpisppy/tests/examples/gbd/gbd.py``: allocate four
aircraft types to five routes before demands realize; slack passengers are
lost revenue.  First-stage nonants are the 4x5 allocation matrix (minus the
three forbidden pairs, which are fixed at 0).  Demand outcomes/probabilities
are the 1956 paper's tables (the reference's json carries an extended fan;
the original tables are used here), drawn with the same seeded flipped-cumsum
scheme.
"""

from __future__ import annotations

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

NUM_AIRCRAFT = [10.0, 19.0, 25.0, 15.0]
FORBIDDEN = {(1, 0), (2, 0), (2, 2)}
# p[i][j]: hundreds of passengers/month for aircraft i route j; row 4 = slack
P = np.array([
    [16, 15, 28, 23, 81],
    [0, 10, 14, 15, 57],
    [0, 5, 0, 7, 29],
    [9, 11, 22, 17, 55],
    [1, 1, 1, 1, 1],
], dtype=float)
# c[i][j]: cost (thousands)/month; row 4 = lost revenue per slack unit
C = np.array([
    [18, 21, 18, 16, 10],
    [0, 15, 16, 14, 9],
    [0, 10, 0, 9, 6],
    [17, 16, 17, 15, 10],
    [13, 13, 7, 7, 1],
], dtype=float)
POSSIBLE_DEMANDS = ([20, 22, 25, 27, 30], [5, 15], [14, 16, 18, 20, 22],
                    [1, 5, 8, 10, 34], [58, 60, 62])
DEMAND_PROBS = ([.2, .05, .35, .2, .2], [.3, .7], [.1, .2, .4, .2, .1],
                [.2, .2, .3, .2, .1], [.1, .8, .1])


def scenario_names_creator(num_scens, start=None):
    start = start or 0
    return [f"scen{i}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {"num_scens": kwargs.get("num_scens", get("num_scens"))}


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()


def scenario_creator(sname, num_scens=None):
    seed = extract_num(sname)
    stream = np.random.RandomState(seed)
    rand = stream.rand(5)
    demand = np.empty(5)
    for r in range(5):
        cum = np.flip(np.cumsum(np.flip(DEMAND_PROBS[r])))
        j = int(np.searchsorted(np.flip(cum), rand[r]))
        demand[r] = POSSIBLE_DEMANDS[r][len(cum) - 1 - j]

    b = LinearModelBuilder(sname)
    x = {}
    for i in range(4):
        for j in range(5):
            ubij = 0.0 if (i, j) in FORBIDDEN else np.inf
            x[i, j] = b.add_var(f"x[{i},{j}]", lb=0.0, ub=ubij,
                                cost=C[i, j])
    slack_a = b.add_vars("aircraftSlack", 4, lb=0.0)
    pos = b.add_vars("passengerSlack_pos", 5, lb=0.0)
    neg = b.add_vars("passengerSlack_neg", 5, lb=0.0)
    for j in range(5):
        b.set_cost(pos[j], C[4, j])      # lost revenue

    for i in range(4):
        coeffs = {x[i, j]: 1.0 for j in range(5)}
        coeffs[slack_a[i]] = 1.0
        b.add_eq(coeffs, NUM_AIRCRAFT[i])
    for j in range(5):
        coeffs = {x[i, j]: P[i, j] for i in range(4)}
        coeffs[pos[j]] = P[4, j]
        coeffs[neg[j]] = -P[4, j]
        b.add_eq(coeffs, float(demand[j]))

    p = b.build()
    p.prob = None if num_scens is None else 1.0 / num_scens
    nonants = np.asarray([x[i, j] for i in range(4) for j in range(5)],
                         dtype=np.int32)
    p.nodes = [ScenarioNode("ROOT", 1.0, 1, nonants)]
    return p


def scenario_denouement(rank, scenario_name, scenario):
    pass
