"""UC-lite: stochastic unit commitment (the headline family, self-contained).

The reference's UC example rides Egret + Prescient wind-scenario data files
(``examples/uc/uc_funcs.py``, ``paperruns/larger_uc``).  This self-contained
analogue keeps the decision structure that makes stochastic UC the paper's
headline benchmark: first-stage per-generator per-hour commitment (the
nonants), second-stage economic dispatch against a stochastic net-load
profile, with min/max output linked to commitment, ramping limits, and load
shedding at VOLL.

Instances are seeded generators: ``num_gens`` thermal units with jittered
cost/capacity blocks, ``horizon`` hours, scenario demand = base sinusoid *
lognormal wind error walk.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..ir import LinearModelBuilder
from ..scenario_tree import ScenarioNode, extract_num

VOLL = 1000.0  # value of lost load ($/MWh)

# Template cache: uncertainty enters ONLY the power-balance rhs, so every
# scenario shares one constraint matrix.  Reusing the same numpy A object
# across ScenarioProblems opts the batch into the shared-A engine
# (ir.ScenarioBatch.A_shared / solvers.shared_admm) — the (S, m, n) tensor is
# never materialized, which is what makes reference-scale UC (SURVEY §6,
# paperruns/larger_uc) fit one chip.
_TEMPLATE_CACHE: dict = {}


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


def kw_creator(cfg=None, **kwargs):
    cfg = cfg or {}
    get = cfg.get if hasattr(cfg, "get") else lambda k, d=None: getattr(cfg, k, d)
    return {
        "num_gens": kwargs.get("num_gens", get("uc_num_gens", 5)),
        "horizon": kwargs.get("horizon", get("uc_horizon", 12)),
        "num_scens": kwargs.get("num_scens", get("num_scens")),
        "seedoffset": kwargs.get("seedoffset", get("seedoffset", 0)),
        # integer commitment by DEFAULT: this is the headline family's whole
        # point (1000-scenario stochastic UC with integer u); pass
        # relax_integers=True explicitly for the easy LP mode
        "relax_integers": kwargs.get("relax_integers",
                                     get("relax_integers", False)),
    }


def inparser_adder(cfg):
    if "num_scens" not in cfg:
        cfg.num_scens_required()
    cfg.add_to_config("uc_num_gens", "number of generators", int, 5)
    cfg.add_to_config("uc_horizon", "scheduling horizon (hours)", int, 12)


def _fleet(num_gens, seedoffset):
    stream = np.random.RandomState(4242 + seedoffset)
    pmax = 50.0 + 100.0 * stream.rand(num_gens)
    pmin = 0.25 * pmax
    mc = 15.0 + 30.0 * stream.rand(num_gens)        # marginal cost
    noload = 100.0 + 300.0 * stream.rand(num_gens)  # no-load (commitment) cost
    ramp = 0.4 * pmax
    return pmax, pmin, mc, noload, ramp


def _template(num_gens, horizon, seedoffset, relax_integers):
    """Build the scenario-independent model ONCE per configuration; scenarios
    only rewrite the balance-row rhs (see module docstring)."""
    key = (num_gens, horizon, seedoffset, relax_integers)
    cached = _TEMPLATE_CACHE.get(key)
    if cached is not None:
        return cached
    pmax, pmin, mc, noload, ramp = _fleet(num_gens, seedoffset)
    as_int = not relax_integers
    b = LinearModelBuilder("template")
    u, p = {}, {}
    for g in range(num_gens):
        for h in range(horizon):
            u[g, h] = b.add_var(f"u[{g},{h}]", lb=0.0, ub=1.0,
                                cost=noload[g], integer=as_int)
    for g in range(num_gens):
        for h in range(horizon):
            p[g, h] = b.add_var(f"p[{g},{h}]", lb=0.0, cost=mc[g])
    shed = b.add_vars("shed", horizon, lb=0.0, cost=VOLL)

    for g in range(num_gens):
        for h in range(horizon):
            b.add_le({p[g, h]: 1.0, u[g, h]: -pmax[g]}, 0.0)   # p <= pmax u
            b.add_ge({p[g, h]: 1.0, u[g, h]: -pmin[g]}, 0.0)   # p >= pmin u
            if h > 0:                                          # ramping
                b.add_le({p[g, h]: 1.0, p[g, h - 1]: -1.0}, float(ramp[g]))
                b.add_ge({p[g, h]: 1.0, p[g, h - 1]: -1.0}, -float(ramp[g]))
    for h in range(horizon):
        coeffs = {p[g, h]: 1.0 for g in range(num_gens)}
        coeffs[shed[h]] = 1.0
        b.add_ge(coeffs, 0.0)                # balance rhs set per scenario

    mdl = b.build()
    balance_rows = np.arange(mdl.num_rows - horizon, mdl.num_rows)
    nonants = np.asarray([u[g, h] for g in range(num_gens)
                          for h in range(horizon)], dtype=np.int32)
    _TEMPLATE_CACHE[key] = (mdl, balance_rows, nonants, pmax)
    return _TEMPLATE_CACHE[key]


def scenario_creator(scenario_name, num_gens=5, horizon=12, num_scens=None,
                     seedoffset=0, relax_integers=False):
    scennum = extract_num(scenario_name)
    mdl, balance_rows, nonants, pmax = _template(
        num_gens, horizon, seedoffset, relax_integers)
    stream = np.random.RandomState(31400 + scennum + seedoffset)
    base = 0.55 * pmax.sum()
    t = np.arange(horizon)
    profile = base * (1.0 + 0.3 * np.sin(2 * np.pi * (t - 3) / 24.0))
    noise = np.cumsum(stream.normal(0.0, 0.03 * base, horizon))
    demand = np.clip(profile + noise, 0.2 * base, 0.95 * pmax.sum())

    cl = mdl.cl.copy()
    cl[balance_rows] = demand
    return dataclasses.replace(
        mdl,
        name=scenario_name,
        cl=cl,
        prob=None if num_scens is None else 1.0 / num_scens,
        nodes=[ScenarioNode("ROOT", 1.0, 1, nonants)],
    )


def scenario_denouement(rank, scenario_name, scenario):
    pass
