"""MMW CLI: confidence interval on the gap of a stored xhat.

TPU-native analogue of ``mpisppy/confidence_intervals/mmw_conf.py`` (113
LoC)::

    python -m tpusppy.confidence_intervals.mmw_conf tpusppy.models.farmer \
        --xhatpath xhat.npy --num-scens 3 --MMW-num-batches 5 \
        --MMW-batch-size 10 --confidence-level 0.95
"""

from __future__ import annotations

import importlib
import sys

from ..utils.config import Config
from . import ciutils
from .confidence_config import confidence_config
from .mmw_ci import MMWConfidenceIntervals


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0].startswith("-"):
        raise SystemExit(
            "usage: mmw_conf <model module> [--xhatpath ...] ...")
    mname = argv.pop(0)
    m = importlib.import_module(mname)

    cfg = Config()
    cfg.add_and_assign("EF_2stage", "2stage EF", bool, None, True)
    cfg.EF2()
    confidence_config(cfg)
    cfg.add_to_config("xhatpath", "path to .npy xhat", str, "xhat.npy")
    cfg.add_to_config("MMW_num_batches", "number of MMW batches", int, 2)
    cfg.add_to_config("MMW_batch_size", "MMW batch size", int, None)
    cfg.add_to_config("start_scen",
                      "first scenario index for sampling (default "
                      "num_scens)", int, None)
    m.inparser_adder(cfg)
    cfg.parse_command_line("mmw_conf", args=argv)

    if cfg.num_scens is None and (cfg.MMW_batch_size is None
                                  or cfg.start_scen is None):
        raise SystemExit(
            "mmw_conf: give --num-scens, or both --MMW-batch-size and "
            "--start-scen")
    xhat = ciutils.read_xhat(cfg.xhatpath)
    start = cfg.start_scen if cfg.start_scen is not None else cfg.num_scens
    # batch_size=None lets MMWConfidenceIntervals resolve it (single source)
    mmw = MMWConfidenceIntervals(mname, cfg, xhat, cfg.MMW_num_batches,
                                 batch_size=cfg.MMW_batch_size, start=start)
    result = mmw.run(confidence_level=cfg.confidence_level)
    print(result)
    return result


if __name__ == "__main__":
    main()
