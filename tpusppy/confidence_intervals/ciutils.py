"""ciutils: seed/branching-factor arithmetic, xhat (de)serialization, gap
estimators.

TPU-native analogue of ``mpisppy/confidence_intervals/ciutils.py`` (427 LoC).
The workhorse is :func:`gap_estimators` — the Bayraksan-Morton G and s
estimators at a candidate xhat over a fresh sample, built on the batched
Amalgamator EF solve + Xhat_Eval (one device program each, replacing the
per-scenario Pyomo solves).
"""

from __future__ import annotations

import importlib

import numpy as np

from .. import global_toc
from ..utils import amalgamator as ama
from ..xhat_eval import Xhat_Eval


def _prime_factors(n: int) -> dict:
    """{prime: exponent} factorization (ciutils.py:21-52)."""
    factors = {}
    d = 2
    while n > 1:
        while n % d == 0:
            factors[d] = factors.get(d, 0) + 1
            n //= d
        d += 1
        if d * d > n and n > 1:
            factors[n] = factors.get(n, 0) + 1
            break
    return factors


def branching_factors_from_numscens(numscens, num_stages):
    """Branching factors for a balanced tree with ~numscens leaves
    (ciutils.py:54-84)."""
    if num_stages == 2:
        return None
    spread = num_stages - 1
    factors = _prime_factors(numscens)
    primes = sorted(
        [p for p, e in factors.items() for _ in range(e)], reverse=True)
    if len(primes) < spread:
        # grow numscens until it factors into enough pieces
        return branching_factors_from_numscens(numscens + 1, num_stages)
    bfs = [1] * spread
    for i, p in enumerate(primes):
        bfs[i % spread] *= p
    return bfs


def number_of_nodes(branching_factors) -> int:
    """Number of nonleaf nodes of a balanced tree (sputils analogue)."""
    total = 1
    prod = 1
    for bf in branching_factors[:-1]:
        prod *= bf
        total += prod
    return total


def writetxt_xhat(xhat, path="xhat.txt", num_stages=2):
    np.savetxt(path, np.asarray(xhat["ROOT"]))


def readtxt_xhat(path="xhat.txt", num_stages=2, delete_file=False):
    xhat = {"ROOT": np.loadtxt(path)}
    if delete_file:
        import os

        os.remove(path)
    return xhat


def write_xhat(xhat, path="xhat.npy", num_stages=2):
    np.save(path, np.asarray(xhat["ROOT"]))


def read_xhat(path="xhat.npy", num_stages=2, delete_file=False):
    xhat = {"ROOT": np.load(path)}
    if delete_file:
        import os

        os.remove(path)
    return xhat


def correcting_numeric(G, cfg=None, relative_error=True, threshold=1e-4,
                       objfct=None):
    """Clamp small negative gap estimates caused by solver noise
    (ciutils.py:185-206)."""
    if relative_error:
        if objfct is None:
            raise RuntimeError(
                "objfct must be specified for relative error correction")
        if objfct == 0:
            return G
        if G / abs(objfct) < -threshold:
            global_toc(f"WARNING: negative gap estimate {G}", True)
        return max(G, 0.0)
    if G < -threshold:
        global_toc(f"WARNING: negative gap estimate {G}", True)
    return max(G, 0.0)


def gap_estimators(xhat_one, mname, solving_type="EF_2stage",
                   scenario_names=None, sample_options=None, ArRP=1,
                   cfg=None, scenario_denouement=None, solver_name=None,
                   solver_options=None, verbose=False):
    """Bayraksan-Morton G and s at xhat over a fresh sample
    (ciutils.py:208-450).

    Two-stage: solve the sampled EF (zn*), then evaluate xhat and x* per
    scenario with one batched fix-and-solve each; G = E[f(xhat) - f(x*)],
    s = unbiased sample stdev of the per-scenario gaps.
    Multistage: the sampled problem is a sample subtree and xhat policies come
    from :func:`tpusppy.confidence_intervals.sample_tree.walking_tree_xhats`.
    """
    from ..utils.config import Config

    is_multi = solving_type == "EF_mstage"
    m = importlib.import_module(mname) if isinstance(mname, str) else mname
    ama.check_module_ama(m)

    if is_multi:
        branching_factors = sample_options["branching_factors"]
        start = sample_options["seed"]
    else:
        from ..scenario_tree import extract_num

        start = extract_num(scenario_names[0])

    if ArRP > 1:
        if is_multi:
            raise RuntimeError("Pooled estimators require two-stage")
        n = len(scenario_names)
        if n % ArRP != 0:
            n = n - n % ArRP
        Gs, ss = [], []
        for k in range(ArRP):
            part = scenario_names[k * (n // ArRP):(k + 1) * (n // ArRP)]
            tmp = gap_estimators(
                xhat_one, mname, solving_type=solving_type,
                scenario_names=part, ArRP=1, cfg=cfg,
                scenario_denouement=scenario_denouement,
                solver_name=solver_name, solver_options=solver_options)
            Gs.append(tmp["G"])
            ss.append(tmp["s"])
        return {"G": float(np.mean(Gs)),
                "s": float(np.linalg.norm(ss) / np.sqrt(n // ArRP)),
                "seed": start}

    if is_multi:
        from . import sample_tree

        samp_tree = sample_tree.SampleSubtree(
            mname, xhats=[], root_scen=None, starting_stage=1,
            branching_factors=branching_factors, seed=start, cfg=cfg,
            solver_name=solver_name, solver_options=solver_options)
        samp_tree.run()
        start += number_of_nodes(branching_factors)
        scenario_names = samp_tree.scenario_names
        scenario_creator = samp_tree.scenario_creator
        scenario_creator_kwargs = samp_tree.scenario_creator_kwargs
        xstars = {"ROOT": samp_tree.root_xstar}
        zn_star = samp_tree.ef_obj
        xhats, start = sample_tree.walking_tree_xhats(
            mname, samp_tree, xhat_one["ROOT"], branching_factors, start,
            cfg, solver_name=solver_name, solver_options=solver_options)
        ev = Xhat_Eval(
            {"solver_options": solver_options or {}},
            scenario_names, scenario_creator,
            scenario_creator_kwargs=scenario_creator_kwargs)
        objs_at_xhat = ev.objective_values(xhats)
        objs_at_xstar = ev.objective_values(samp_tree.xstar_cache)
    else:
        ama_cfg = Config()
        ama_cfg.add_and_assign(solving_type, "solving type", bool, None, True)
        ama_cfg.quick_assign("EF_solver_name", str, solver_name or "admm")
        ama_cfg.quick_assign("num_scens", int, len(scenario_names))
        ama_cfg.quick_assign("start", int, start)
        if cfg is not None:
            for k, v in cfg.items():
                if k not in ama_cfg:
                    ama_cfg.add_and_assign(k, f"copied {k}", object, None, v)
        ama_object = ama.from_module(m, ama_cfg, use_command_line=False)
        ama_object.scenario_names = scenario_names
        ama_object.verbose = False
        ama_object.run()
        start += len(scenario_names)
        zn_star = ama_object.best_outer_bound
        xstars = {"ROOT": ama_object.xhats["ROOT"]}

        scenario_creator_kwargs = ama_object.kwargs
        ev = Xhat_Eval(
            {"solver_options": (solver_options or {})},
            scenario_names, ama_object.scenario_creator,
            scenario_creator_kwargs=scenario_creator_kwargs)
        xhats = _root_cache_to_full(ev, xhat_one)
        objs_at_xhat = ev.objective_values(xhats)
        objs_at_xstar = ev.objective_values(_root_cache_to_full(ev, xstars))

    probs = ev.probs
    scen_gaps = np.asarray(objs_at_xhat) - np.asarray(objs_at_xstar)
    G = float(scen_gaps @ probs)
    ssq = float((scen_gaps ** 2) @ probs)
    prob_sqnorm = float(np.linalg.norm(probs) ** 2)
    obj_at_xhat = float(np.asarray(objs_at_xhat) @ probs)
    sample_var = max((ssq - G ** 2) / max(1.0 - prob_sqnorm, 1e-12), 0.0)
    s = float(np.sqrt(sample_var))
    G = correcting_numeric(G, cfg, objfct=obj_at_xhat,
                           relative_error=(abs(zn_star) > 1))
    if verbose:
        global_toc(f"G = {G}, s = {s}")
    return {"G": G, "s": s, "seed": start}


def _root_cache_to_full(ev, xhat_dict) -> np.ndarray:
    """(K,) candidate over the packed nonant layout from a ROOT-only cache
    (two-stage: the root IS the whole nonant vector)."""
    root = np.asarray(xhat_dict["ROOT"], dtype=float)
    K = ev.nonant_length
    if root.shape[0] == K:
        return root
    out = np.zeros(K)
    out[: root.shape[0]] = root
    return out
