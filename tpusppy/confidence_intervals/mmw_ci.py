"""MMW: Mak-Morton-Wood confidence interval on the optimality gap of an xhat.

TPU-native analogue of ``mpisppy/confidence_intervals/mmw_ci.py:31-189``: over
``num_batches`` fresh sample batches, compute the gap estimator G_n at the
candidate, then a one-sided CI ``Gbar + t * s / sqrt(n)``.
"""

from __future__ import annotations

import importlib

import numpy as np
import scipy.stats

from .. import global_toc
from ..utils import amalgamator as ama
from . import ciutils


class MMWConfidenceIntervals:
    def __init__(self, refmodel, cfg, xhat_one, num_batches, batch_size=None,
                 start=None, verbose=True, mpicomm=None):
        self.refmodel = (importlib.import_module(refmodel)
                         if isinstance(refmodel, str) else refmodel)
        self.refmodelname = refmodel
        self.cfg = cfg
        self.xhat_one = xhat_one
        self.num_batches = num_batches
        self.batch_size = batch_size
        self.verbose = verbose
        if start is None:
            raise RuntimeError("Start must be specified")
        self.start = start
        if ama._bool_option(cfg, "EF_2stage"):
            self.type = "EF_2stage"
            self.multistage = False
            self.numstages = 2
        elif ama._bool_option(cfg, "EF_mstage"):
            self.type = "EF_mstage"
            self.multistage = True
            self.numstages = len(cfg["branching_factors"]) + 1
        else:
            raise RuntimeError(
                "cfg should set 'EF_2stage' or 'EF_mstage' to True")
        needed = ["scenario_names_creator", "scenario_creator", "kw_creator"]
        if self.multistage:
            needed[0] = "sample_tree_scen_creator"
        missing = [e for e in needed if not hasattr(self.refmodel, e)]
        if missing:
            raise RuntimeError(
                f"Module {refmodel} not complete for MMW: missing {missing}")

    def run(self, confidence_level=0.95):
        start = self.start
        batch_size = self.batch_size or self.cfg["num_scens"]
        if self.multistage:
            bfs = ciutils.branching_factors_from_numscens(
                batch_size, self.numstages)
            batch_size = int(np.prod(bfs))
        G = np.zeros(self.num_batches)
        for i in range(self.num_batches):
            scenstart = None if self.multistage else start
            gap_options = ({"seed": start, "branching_factors": bfs}
                           if self.multistage else None)
            scenario_names = self.refmodel.scenario_names_creator(
                batch_size, start=scenstart)
            estim = ciutils.gap_estimators(
                self.xhat_one, self.refmodelname, solving_type=self.type,
                scenario_names=scenario_names, sample_options=gap_options,
                ArRP=1, cfg=self.cfg,
                scenario_denouement=getattr(self.refmodel,
                                            "scenario_denouement", None),
                solver_name=self.cfg.get("EF_solver_name", "admm"),
            )
            G[i] = estim["G"]
            start = estim["seed"]
            if self.verbose:
                global_toc(f"Gn={G[i]} for the batch {i}")

        s_g = float(np.std(G))
        Gbar = float(np.mean(G))
        t_g = scipy.stats.t.ppf(confidence_level, self.num_batches - 1)
        epsilon_g = t_g * s_g / np.sqrt(self.num_batches)
        self.result = {
            "gap_inner_bound": Gbar + epsilon_g,
            "gap_outer_bound": 0.0,
            "Gbar": Gbar,
            "std": s_g,
            "Glist": list(G),
        }
        return self.result
