"""zhat4xhat: confidence interval on z(xhat) for a stored candidate.

TPU-native analogue of ``mpisppy/confidence_intervals/zhat4xhat.py`` (200
LoC): evaluate a fixed first-stage candidate over ``num_samples`` independent
batches and report a t-based CI on its expected objective.
"""

from __future__ import annotations

import importlib

import numpy as np
import scipy.stats

from .. import global_toc
from ..xhat_eval import Xhat_Eval
from . import ciutils


def evaluate_sample_trees(xhat_one, num_samples, cfg, InitSeed=0,
                          model_module=None):
    """Mean/std of z(xhat) over independent sample batches
    (zhat4xhat.py core)."""
    mname = cfg["model_module_name"] if model_module is None else None
    m = model_module or importlib.import_module(mname)
    num_scens = cfg["num_scens"]
    zhats = []
    seed = InitSeed
    kwargs = m.kw_creator(cfg)
    for _ in range(num_samples):
        names = m.scenario_names_creator(num_scens, start=seed)
        seed += num_scens
        ev = Xhat_Eval({"solver_options": {}}, names, m.scenario_creator,
                       scenario_creator_kwargs=kwargs)
        cache = ciutils._root_cache_to_full(ev, xhat_one)
        zhats.append(ev.evaluate(cache))
    return np.array(zhats), seed


def run_samples(cfg, args_module=None, model_module=None):
    """CI on z(xhat): zhatbar +/- t * s / sqrt(n)."""
    m = model_module or importlib.import_module(cfg["model_module_name"])
    xhat_one = ciutils.read_xhat(cfg["xhatpath"])
    num_samples = cfg.get("num_samples", 10)
    confidence_level = cfg.get("confidence_level", 0.95)

    zhats, seed = evaluate_sample_trees(xhat_one, num_samples, cfg,
                                        model_module=m)
    zhatbar = float(np.mean(zhats))
    s_zhat = float(np.std(zhats, ddof=1)) if len(zhats) > 1 else 0.0
    t_zhat = scipy.stats.t.ppf(confidence_level, max(num_samples - 1, 1))
    eps_z = t_zhat * s_zhat / np.sqrt(num_samples)
    global_toc(f"zhatbar = {zhatbar:.6f} +/- {eps_z:.6f} "
               f"({confidence_level:.0%} CI)", True)
    return zhatbar, eps_z
