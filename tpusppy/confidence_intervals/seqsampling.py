"""Sequential sampling to a fixed-width optimality-gap CI.

TPU-native analogue of ``mpisppy/confidence_intervals/seqsampling.py:110-560``:
Bayraksan-Morton ("BM") and Bayraksan-Pierre-Louis ("BPL", optionally
stochastic/FSP) procedures — grow the sample until the gap estimate at a
freshly computed xhat passes the stopping rule, then report the CI.
"""

from __future__ import annotations

import importlib

import numpy as np
import scipy.stats

from .. import global_toc
from ..utils.config import Config
from ..utils import amalgamator
from . import ciutils


def xhat_generator_farmer(scenario_names, solver_name=None,
                          solver_options=None, crops_multiplier=1):
    """Sample-average xhat for farmer (seqsampling.py:64-108)."""
    cfg = Config()
    cfg.add_and_assign("EF_2stage", "2stage EF", bool, None, True)
    cfg.quick_assign("EF_solver_name", str, solver_name or "admm")
    cfg.quick_assign("num_scens", int, len(scenario_names))
    cfg.quick_assign("crops_multiplier", int, crops_multiplier)
    ama = amalgamator.from_module("tpusppy.models.farmer", cfg,
                                  use_command_line=False)
    ama.scenario_names = scenario_names
    ama.verbose = False
    ama.run()
    return {"ROOT": ama.xhats["ROOT"]}


class SeqSampling:
    """(seqsampling.py:110-560)"""

    def __init__(self, refmodel, xhat_generator, cfg,
                 stochastic_sampling=False, stopping_criterion="BM",
                 solving_type="EF_2stage"):
        if not isinstance(cfg, Config):
            raise RuntimeError(f"SeqSampling bad cfg type={type(cfg)}")
        self.refmodel = (importlib.import_module(refmodel)
                         if isinstance(refmodel, str) else refmodel)
        self.refmodelname = refmodel
        self.xhat_generator = xhat_generator
        self.cfg = cfg
        self.stochastic_sampling = stochastic_sampling
        self.stopping_criterion = stopping_criterion
        self.solving_type = solving_type
        self.multistage = solving_type == "EF_mstage"
        self.sample_size_ratio = cfg.get("sample_size_ratio", 1)
        self.xhat_gen_kwargs = cfg.get("xhat_gen_kwargs") or {}
        self.ArRP = cfg.get("ArRP", 1)
        self.kf_Gs = cfg.get("kf_Gs", 1)
        self.kf_xhat = cfg.get("kf_xhat", 1)
        self.confidence_level = cfg.get("confidence_level", 0.95)
        self.solver_name = cfg.get("solver_name") or "admm"
        self.solver_options = {}
        for name in ("BM_eps_prime", "BM_hprime", "BM_eps", "BM_h", "BM_p",
                     "BM_q", "BPL_eps", "BPL_c0", "BPL_c1", "BPL_n0min"):
            setattr(self, name, cfg.get(name))
        if self.stopping_criterion == "BM":
            needed = ["BM_eps_prime", "BM_hprime", "BM_eps", "BM_h", "BM_p"]
        elif self.stopping_criterion == "BPL":
            needed = ["BPL_eps"]
        else:
            raise RuntimeError("Only BM and BPL criteria are supported")
        missing = [n for n in needed if getattr(self, n) is None]
        if missing:
            raise RuntimeError(f"SeqSampling needs options {missing}")
        if self.BPL_c1 is None:
            self.BPL_c1 = 2
        self.ScenCount = 0
        self.SeedCount = 0

        if self.stopping_criterion == "BM":
            self.stop_criterion = self._bm_stopping_criterion
            self.sample_size = self._bm_sampsize
        else:
            self.stop_criterion = self._bpl_stopping_criterion
            self.sample_size = (self._stochastic_sampsize
                                if stochastic_sampling
                                else self._bpl_fsp_sampsize)

    # ---- stopping rules (seqsampling.py:265-330) ----------------------------
    def _bm_stopping_criterion(self, G, s, nk):
        return G > self.BM_hprime * s + self.BM_eps_prime

    def _bpl_stopping_criterion(self, G, s, nk):
        t = scipy.stats.t.ppf(self.confidence_level, nk - 1)
        return G + t * s / np.sqrt(nk) + 1 / np.sqrt(nk) > self.BPL_eps

    def _bm_sampsize(self, k, G, s, nk_m1, r=2):
        p, q = self.BM_p, self.BM_q
        h, hprime = self.BM_h, self.BM_hprime
        j = np.arange(1, 1000)
        if q is None:
            ssum = np.sum(np.power(j.astype(float), -p * np.log(j)))
            c = max(1, 2 * np.log(
                ssum / (np.sqrt(2 * np.pi) * (1 - self.confidence_level))))
            lower_bound = (c + 2 * p * np.log(k) ** 2) / ((h - hprime) ** 2)
        else:
            ssum = np.sum(np.exp(-p * np.power(j, 2 * q / r)))
            c = max(1, 2 * np.log(
                ssum / (np.sqrt(2 * np.pi) * (1 - self.confidence_level))))
            lower_bound = (c + 2 * p * np.power(k, 2 * q / r)) / (
                (h - hprime) ** 2)
        return int(np.ceil(lower_bound))

    def _bpl_fsp_sampsize(self, k, G, s, nk_m1):
        growth = (self.cfg.get("functions_dict") or
                  {"growth_function": lambda x: x - 1})["growth_function"]
        c0 = self.BPL_c0 if self.BPL_c0 is not None else 50
        return int(np.ceil(c0 + self.BPL_c1 * growth(k)))

    def _stochastic_sampsize(self, k, G, s, nk_m1):
        if k == 1:
            n0min = self.BPL_n0min if self.BPL_n0min is not None else 50
            return int(np.ceil(max(n0min, np.log(1 / self.BPL_eps))))
        t = scipy.stats.t.ppf(self.confidence_level, nk_m1 - 1)
        a = -self.BPL_eps
        bq = 1 + t * s
        cq = nk_m1 * G
        maxroot = -(np.sqrt(bq ** 2 - 4 * a * cq) + bq) / (2 * a)
        return int(np.ceil(maxroot ** 2))

    # ---- the sequential loop (seqsampling.py:331-523) -----------------------
    def run(self, maxit=200):
        refmodel = self.refmodel
        mult = self.sample_size_ratio
        k = 1
        lower_bound_k = self.sample_size(k, None, None, None)

        mk = int(np.floor(mult * lower_bound_k))
        xhat_scenario_names = refmodel.scenario_names_creator(
            mk, start=self.ScenCount)
        self.ScenCount += mk
        xgo = dict(self.xhat_gen_kwargs)
        for drop in ("solver_name", "solver_options", "scenario_names"):
            xgo.pop(drop, None)
        xhat_k = self.xhat_generator(
            xhat_scenario_names, solver_name=self.solver_name,
            solver_options=self.solver_options, **xgo)

        Gk, sk, nk = self._estimate(xhat_k, lower_bound_k)

        while self.stop_criterion(Gk, sk, nk) and k < maxit:
            k += 1
            nk_m1, mk_m1 = nk, mk
            lower_bound_k = self.sample_size(k, Gk, sk, nk_m1)
            mk = max(int(np.floor(mult * lower_bound_k)), mk_m1)
            if k % self.kf_xhat == 0:
                xhat_scenario_names = refmodel.scenario_names_creator(
                    mk, start=self.ScenCount)
                self.ScenCount += mk
            else:
                xhat_scenario_names += refmodel.scenario_names_creator(
                    mk - mk_m1, start=self.ScenCount)
                self.ScenCount += mk - mk_m1
            xhat_k = self.xhat_generator(
                xhat_scenario_names, solver_name=self.solver_name,
                solver_options=self.solver_options, **xgo)

            Gk, sk, nk = self._estimate(xhat_k, lower_bound_k, nk_min=nk_m1)

        if k == maxit:
            raise RuntimeError(
                f"The loop terminated after {maxit} iteration with no "
                "acceptable solution")
        T = k
        if self.stopping_criterion == "BM":
            upper_bound = self.BM_h * sk + self.BM_eps
        else:
            upper_bound = self.BPL_eps
        CI = [0, upper_bound]
        global_toc(
            f"G={Gk} sk={sk}; xhat has been computed with {nk * mult} "
            "observations.", True)
        return {"T": T, "Candidate_solution": xhat_k, "CI": CI}

    def _estimate(self, xhat_k, lower_bound_k, nk_min=0):
        """Compute (G, s, nk) at xhat_k — two-stage via fresh scenario
        blocks, multistage via an independent sample tree."""
        refmodel = self.refmodel
        if self.multistage:
            num_stages = len(self.cfg["branching_factors"]) + 1
            bfs = ciutils.branching_factors_from_numscens(
                max(int(lower_bound_k), 2), num_stages)
            nk = int(np.prod(bfs))
            names = refmodel.scenario_names_creator(nk)
            sample_options = {"branching_factors": bfs,
                              "seed": self.SeedCount}
            lcfg = self._local_cfg(nk)
            estim = ciutils.gap_estimators(
                xhat_k, self.refmodelname, solving_type=self.solving_type,
                scenario_names=names, sample_options=sample_options,
                ArRP=1, cfg=lcfg, solver_name=self.solver_name)
            self.SeedCount = estim["seed"]
        else:
            nk = max(self.ArRP * int(np.ceil(lower_bound_k / self.ArRP)),
                     nk_min)
            names = refmodel.scenario_names_creator(nk, start=self.ScenCount)
            self.ScenCount += nk
            lcfg = self._local_cfg(nk)
            estim = ciutils.gap_estimators(
                xhat_k, self.refmodelname, solving_type=self.solving_type,
                scenario_names=names, ArRP=self.ArRP, cfg=lcfg,
                solver_name=self.solver_name)
        return estim["G"], estim["s"], nk

    def _local_cfg(self, nk):
        lcfg = Config()
        for kname, v in self.cfg.items():
            lcfg.add_and_assign(kname, f"copied {kname}", object, None, v)
        lcfg.quick_assign("num_scens", int, nk)
        return lcfg


class IndepScens_SeqSampling(SeqSampling):
    """Multistage variant with independent sample trees
    (multi_seqsampling.py:29-339).  Uses fresh branching-factor samples per
    iteration; otherwise the BM/BPL loop is shared."""

    def __init__(self, refmodel, xhat_generator, cfg,
                 stochastic_sampling=False, stopping_criterion="BM"):
        super().__init__(refmodel, xhat_generator, cfg,
                         stochastic_sampling=stochastic_sampling,
                         stopping_criterion=stopping_criterion,
                         solving_type="EF_mstage")
