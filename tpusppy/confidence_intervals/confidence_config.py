"""Confidence-interval Config groups.

TPU-native analogue of ``mpisppy/confidence_intervals/confidence_config.py``
(85 LoC): the option groups consumed by MMW / sequential sampling CLIs.
"""

from __future__ import annotations


def confidence_config(cfg):
    cfg.add_to_config("confidence_level",
                      "1 minus alpha (default 0.95)", float, 0.95)


def sequential_config(cfg):
    cfg.add_to_config("sample_size_ratio",
                      "xhat sample size / gap estimator sample size "
                      "(default 1)", float, 1.0)
    cfg.add_to_config("ArRP", "how many estimators to pool (default 1)",
                      int, 1)
    cfg.add_to_config("kf_Gs",
                      "resampling frequency for gap estimators (default 1)",
                      int, 1)
    cfg.add_to_config("kf_xhat",
                      "resampling frequency for xhat (default 1)", int, 1)


def BM_config(cfg):
    """Bayraksan-Morton relative-width options (seqsampling defaults)."""
    cfg.add_to_config("BM_h", "BM h parameter (default 0.2)", float, 0.2)
    cfg.add_to_config("BM_hprime", "BM h' parameter (default 0.015)", float,
                      0.015)
    cfg.add_to_config("BM_eps", "BM epsilon (default 0.5)", float, 0.5)
    cfg.add_to_config("BM_eps_prime", "BM epsilon' (default 0.4)", float,
                      0.4)
    cfg.add_to_config("BM_p", "BM p parameter (default 0.2)", float, 0.2)
    cfg.add_to_config("BM_q", "BM q parameter (default 1.2)", float, 1.2)


def BPL_config(cfg):
    """Bayraksan-Pierre-Louis fixed-width options."""
    cfg.add_to_config("BPL_eps", "BPL epsilon (CI width)", float, 50.0)
    cfg.add_to_config("BPL_c0", "BPL starting sample size (default 50)",
                      int, 50)
    cfg.add_to_config("BPL_c1", "BPL growth coefficient (default 2)", int, 2)
    cfg.add_to_config("BPL_n0min",
                      "stochastic-sampling minimum n0 (default 50)", int, 50)
