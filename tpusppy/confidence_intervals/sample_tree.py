"""Sample trees for multistage confidence intervals.

TPU-native analogue of ``mpisppy/confidence_intervals/sample_tree.py:18-313``:
``SampleSubtree`` samples a subtree via the model's
``sample_tree_scen_creator``, solves its EF as one batched problem, and
exposes the stage-``starting_stage`` policy; ``walking_tree_xhats`` produces a
feasible nonanticipative policy for every nonleaf node given a root xhat (the
reference walks the tree resolving stage by stage; here one EF solve with the
root clamped yields the same node-consistent policy because the EF couples
all nodes).
"""

from __future__ import annotations

import importlib

import numpy as np

from ..ef import solve_ef
from ..ir import ScenarioBatch
from ..xhat_eval import Xhat_Eval


class SampleSubtree:
    """(sample_tree.py:18-150)"""

    def __init__(self, mname, xhats, root_scen, starting_stage,
                 branching_factors, seed, cfg, solver_name=None,
                 solver_options=None):
        self.mname = mname
        self.model = (importlib.import_module(mname)
                      if isinstance(mname, str) else mname)
        self.xhats = xhats          # fixed nonants for stages < starting_stage
        self.root_scen = root_scen
        self.stage = starting_stage
        self.branching_factors = list(branching_factors)
        self.seed = seed
        self.cfg = cfg
        self.solver_name = solver_name or "admm"
        self.solver_options = solver_options or {}
        self.scenario_creator_kwargs = self.model.kw_creator(cfg)
        self.scenario_creator_kwargs["branching_factors"] = \
            self.branching_factors

    def _create_scenarios(self):
        prod = int(np.prod(self.branching_factors))
        self.scenario_names = self.model.scenario_names_creator(prod)
        self.problems = [
            self.model.sample_tree_scen_creator(
                nm, self.stage, self.branching_factors, self.seed,
                given_scenario=self.root_scen,
                **self.scenario_creator_kwargs)
            for nm in self.scenario_names
        ]

    def scenario_creator(self, sname, **kwargs):
        """Re-create one of the sampled scenarios (for Xhat_Eval reuse)."""
        return self.model.sample_tree_scen_creator(
            sname, self.stage, self.branching_factors, self.seed,
            given_scenario=self.root_scen, **self.scenario_creator_kwargs)

    def run(self):
        self._create_scenarios()
        batch = ScenarioBatch.from_problems(self.problems)
        self.batch = batch
        if self.xhats:
            # clamp earlier-stage nonants to the provided xhats
            flat = np.concatenate([np.asarray(x) for x in self.xhats])
            idx = batch.tree.nonant_indices[: flat.shape[0]]
            batch.lb[:, idx] = flat[None, :]
            batch.ub[:, idx] = flat[None, :]
        self.ef_obj, x = solve_ef(batch, solver="admm")
        self.ef_x = x
        # policy at the starting stage: nonant slots of that stage
        stage_slots = np.where(batch.tree.nonant_stage == self.stage)[0]
        self.xhat_at_stage = x[0][batch.tree.nonant_indices[stage_slots]]
        root_slots = np.where(batch.tree.nonant_stage == 1)[0]
        self.root_xstar = x[0][batch.tree.nonant_indices[root_slots]]
        # full (S, K) caches for evaluation
        self.xstar_cache = x[:, batch.tree.nonant_indices]
        return self.ef_obj


def walking_tree_xhats(mname, samp_tree, xhat_one, branching_factors, start,
                       cfg, solver_name=None, solver_options=None):
    """Feasible per-node policy given the root xhat (sample_tree.py:151-313).

    One EF solve with the root clamped: the EF's nonanticipativity structure
    makes every node's solution a valid policy for that node.
    Returns ((S, K) cache, updated seed).
    """
    batch = samp_tree.batch
    tree = batch.tree
    root_slots = np.where(tree.nonant_stage == 1)[0]
    root = np.asarray(xhat_one, dtype=float)
    lb = np.array(batch.lb, copy=True)
    ub = np.array(batch.ub, copy=True)
    idx = tree.nonant_indices[root_slots]
    lb[:, idx] = root[None, :]
    ub[:, idx] = root[None, :]
    import dataclasses

    clamped = dataclasses.replace(batch, lb=lb, ub=ub)
    _, x = solve_ef(clamped, solver="admm")
    xhats = x[:, tree.nonant_indices]
    xhats[:, root_slots] = root[None, :]
    start += int(np.prod(branching_factors))
    return xhats, start
