"""FWPH: Frank-Wolfe Progressive Hedging (Boland et al.) — batched.

TPU-native analogue of ``mpisppy/fwph/fwph.py:53-1045``.  The reference keeps,
per scenario, a Pyomo QP over the convex hull of previously-found MIP vertices
and alternates MIP solve / QP column add (``SDM``, fwph.py:210-311).  Here the
column sets live as ONE tensor ``V`` of shape (S, J, n) and both halves of the
alternation are single batched device programs:

* the "MIP" step is the scenario batch solved with the FW-linearized dual
  objective (c + W_mip on nonants, no prox) — one :func:`admm.solve_batch`;
* the QP step is a batch of simplex-constrained QPs over the column weights
  ``a`` (dense quadratic P = V_K diag(rho) V_K'), solved by the same ADMM
  kernel through its dense-P path — replacing per-scenario persistent QP
  solvers and incremental ``add_column`` calls (fwph.py:305-372).

Column capacity is fixed at trace time (ring buffer with an active-column
mask), so the whole algorithm uses exactly two compiled programs.
At inner iteration 0 the linearized solve yields the Lagrangian dual bound
(fwph.py:254-260): FWPH's raison d'etre as an outer-bound spoke.
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from ..phbase import PHBase
from ..solvers import admm


class FWPH(PHBase):
    """Batched FWPH (fwph.py:53-142 constructor semantics)."""

    def __init__(self, options, FW_options, all_scenario_names,
                 scenario_creator, scenario_denouement=None, **kwargs):
        super().__init__(options, all_scenario_names, scenario_creator,
                         scenario_denouement=scenario_denouement, **kwargs)
        self.FW_options = dict(FW_options or {})
        self._options_check(["FW_iter_limit", "FW_weight", "FW_conv_thresh"],
                            self.FW_options)
        self.vb = self.FW_options.get("FW_verbose", False)

    # ---- column machinery ---------------------------------------------------
    def _init_columns(self):
        S, n = self.batch.num_scenarios, self.batch.num_vars
        iters = int(self.options["PHIterLimit"])
        fw_iters = int(self.FW_options["FW_iter_limit"])
        self.Jmax = min(int(self.FW_options.get("max_columns", 50)),
                        iters * fw_iters + 1)
        self.V = np.zeros((S, self.Jmax, n))
        self.V[:, 0, :] = self.local_x          # Iter0 vertices
        self.active = np.zeros((S, self.Jmax), dtype=bool)
        self.active[:, 0] = True
        self.a = np.zeros((S, self.Jmax))
        self.a[:, 0] = 1.0
        self._ring = 1                            # next write slot

    def _add_columns(self, x: np.ndarray):
        """Ring-append one vertex per scenario (fwph.py:305-372)."""
        j = self._ring % self.Jmax
        if j == 0:
            j = 1 % self.Jmax  # never evict slot 0 mid-ring on tiny Jmax
        self.V[:, j, :] = x
        self.active[:, j] = True
        self._ring = self._ring + 1 if (self._ring + 1) % self.Jmax != 0 \
            else 1

    def _solve_qp(self):
        """Batch of simplex QPs over column weights: min 0.5 a'Pa + g'a,
        sum a = 1, 0 <= a <= active (fwph.py:210-311 QP side)."""
        idx = self.tree.nonant_indices
        Vk = self.V[:, :, idx]                       # (S, J, K)
        P = np.einsum("sjk,sk,slk->sjl", Vk, self.rho, Vk)
        g = np.einsum("sjn,sn->sj", self.V, self.batch.c) \
            + np.einsum("sjk,sk->sj", Vk, self.W - self.rho * self.xbars)
        S, J = g.shape
        A = np.ones((S, 1, J))
        one = np.ones((S, 1))
        lbz = np.zeros((S, J))
        ubz = self.active.astype(float)
        sol = admm.solve_batch(g, np.zeros((S, J)), A, one, one, lbz, ubz,
                               settings=self.admm_settings, P=P)
        self.a = np.asarray(sol.x)
        # clean tiny negatives / renormalize on the active set
        self.a = np.clip(self.a, 0.0, None) * self.active
        tot = np.maximum(self.a.sum(axis=1, keepdims=True), 1e-12)
        self.a = self.a / tot
        return np.einsum("sjn,sj->sn", self.V, self.a)   # x_qp

    # ---- the SDM (batched over all scenarios) -------------------------------
    def SDM_batch(self):
        """One major iteration of Algorithm 2 across the whole batch.

        Returns the probability-weighted dual bound from inner iteration 0.
        """
        idx = self.tree.nonant_indices
        alpha = float(self.FW_options["FW_weight"])
        x_qp = np.einsum("sjn,sj->sn", self.V, self.a)
        xt_K = (1.0 - alpha) * self.xbars + alpha * x_qp[:, idx]
        W_qp = self.W
        dual_bound = None
        gamma = np.inf
        for fw in range(int(self.FW_options["FW_iter_limit"])):
            x_source_K = xt_K if fw == 0 else x_qp[:, idx]
            W_mip = W_qp + self.rho * (x_source_K - self.xbars)
            q = np.array(self.batch.c, copy=True)
            q[:, idx] += W_mip
            xstar = self.solve_loop(q=q)
            if fw == 0:
                # CERTIFIED Lagrangian bound: dual objective of the
                # W_mip-augmented solve (weak duality absorbs solver
                # tolerance; the primal objective of an inexact solve can
                # overshoot — cf. lagrangian_bounder)
                dual_bound = self.Edualbound(q=q)
            # Gamma^t stop check (fwph.py:264-283): linearized objective at
            # the QP point minus at the new vertex, normalized
            val0 = np.einsum("sn,sn->s", q, xstar) \
                + 0.5 * np.einsum("sn,sn->s", self.batch.q2, xstar * xstar)
            val1 = np.einsum("sn,sn->s", q, x_qp) \
                + 0.5 * np.einsum("sn,sn->s", self.batch.q2, x_qp * x_qp)
            denom = np.where(np.abs(val0) > 1e-9, np.abs(val0), 1.0)
            gammas = (val1 - val0) / denom
            gamma = float(self.probs @ gammas)
            self._add_columns(xstar)
            x_qp = self._solve_qp()
            if gamma < self.FW_options["FW_conv_thresh"]:
                break
        self.local_x = x_qp      # PH state updates run on the QP point
        return dual_bound, gamma

    # ---- main ---------------------------------------------------------------
    def fwph_main(self, finalize=True):
        """(fwph.py:142-208)"""
        self.trivial_bound = self.Iter0()
        best_bound = self.trivial_bound
        self._local_bound = self.trivial_bound
        self._init_columns()

        if self.spcomm and self.spcomm.is_converged():
            return None, None, None

        itr = 0
        for itr in range(1, int(self.options["PHIterLimit"]) + 1):
            self._iter = itr
            dual_bound, gamma = self.SDM_batch()
            self._local_bound = dual_bound
            best_bound = max(best_bound, dual_bound)

            if self.spcomm:
                if self.spcomm.is_converged():
                    global_toc("FWPH converged to hub criteria", self.vb)
                    break
                self.spcomm.sync()

            self.Compute_Xbar()
            diff = self._conv_diff()
            self.Update_W()
            global_toc(
                f"FWPH iter {itr} bound {dual_bound:.6f} "
                f"best {best_bound:.6f} gamma {gamma:.3e} conv {diff:.3e}",
                self.vb,
            )
            if diff < self.options.get("convthresh", 0.0):
                global_toc("FWPH converged on Boland criteria", self.vb)
                break

        self.best_bound = best_bound
        weight_dict = {"W": np.array(self.W)}
        xbars_dict = {"xbars": np.array(self.xbars)}
        return itr, weight_dict, xbars_dict

    def _conv_diff(self) -> float:
        """Boland Algorithm 3 convergence (fwph.py:528-548): prob-weighted
        squared distance between the QP point and xbar."""
        idx = self.tree.nonant_indices
        d = np.power(self.local_x[:, idx] - self.xbars, 2).sum(axis=1)
        return float(self.probs @ d)
