from .fwph import FWPH

__all__ = ["FWPH"]
