"""SolveServer: a long-lived, multi-tenant, warm-path wheel service.

ROADMAP item 2 ("wheel-as-a-service"), doc/serving.md.  The production
shape for "millions of users" is a PROCESS THAT NEVER GOES COLD: compiled
executables (:mod:`tpusppy.solvers.aot`), autotuner verdicts
(:mod:`tpusppy.tune`) and the content-keyed device constants
(:mod:`tpusppy.spopt`) stay resident while solve requests come and go.

Request lifecycle (each stage observable in the per-request SLO record):

1. **ingest** — :meth:`SolveServer.submit` resolves the request's model
   (farmer/uc_lite/sslp-class, or a custom creator) and runs
   :func:`tpusppy.service.canonical.ingest` ONCE: canonical batched
   arrays + the shape-family key.
2. **warm-bind** — the family key is looked up in the server's registry:
   a previously-seen (isomorphic) family means every program the wheel
   will dispatch is already compiled in-process — the request runs with
   ``aot.misses`` delta == 0 and reaches iter-1 without touching XLA.
3. **schedule** — requests queue FIFO; the executor runs ONE wheel at a
   time (the mesh is a single shared resource) and TIME-SLICES when
   others wait: a running wheel is asked to park via the hub's
   ``preempt_check`` at a window boundary, its state is banked through
   the PR-5 checkpoint seam (capture is pinned zero-extra-fetch), and the
   tenant re-queues; the resumed slice continues with bounds monotone.
4. **SLO record** — queue wait, time-to-iter-1, compile seconds, aot
   hit/miss deltas, iters/s, certified gap, wall; latency percentiles
   ride the ``service.*`` histograms (p50/p95/p99 via
   :mod:`tpusppy.obs.metrics`).

What is shared across tenants: compiled executables, tune verdicts,
device-resident constant caches (content-keyed — identical A shares one
device copy).  What is NOT shared: batch coefficient arrays (each
request's own numbers), wheel state (W/xbars/rho), bounds, checkpoints.
"""

from __future__ import annotations

import collections
import os
import tempfile
import threading
import time
import uuid
from math import inf

import numpy as np

from ..obs import metrics as _metrics
from ..obs import telemetry as _telemetry
from ..obs import trace as _trace
from ..obs.log import get_logger
from ..resilience import faults as _faults
from . import canonical as _canonical
from .journal import RequestJournal

_log = get_logger("service")

_CTR_REQUESTS = _metrics.counter("service.requests")
_CTR_COMPLETED = _metrics.counter("service.completed")
_CTR_FAILED = _metrics.counter("service.failed")
_CTR_WARM_HITS = _metrics.counter("service.warm_hits")
_CTR_COLD_FAMILIES = _metrics.counter("service.cold_families")
_CTR_SLICES = _metrics.counter("service.slices")
_CTR_RECOVERED = _metrics.counter("service.recovered")
_CTR_RECOVERED_COLD = _metrics.counter("service.recovered_cold")
_CTR_REJECTED = _metrics.counter("service.rejected_overload")
_CTR_DEADLINE = _metrics.counter("service.deadline_failed")
_CTR_DUPLICATES = _metrics.counter("service.duplicate_submits")
_HIST_QUEUE_WAIT = _metrics.histogram("service.queue_wait_s")
_HIST_WALL = _metrics.histogram("service.wall_s")
_HIST_TTFI = _metrics.histogram("service.ttfi_s")


class ServerOverloaded(RuntimeError):
    """Typed fast-fail admission rejection: the bounded queue is full.
    Over the TCP transport this surfaces as a structured
    ``{"status": "rejected", "error_code": "overload"}`` payload —
    clients back off instead of timing out."""

    code = "overload"


class ServerClosed(RuntimeError):
    """Submit refused because the server is shutting down.  Typed (and
    surfaced over TCP as ``error_code="unavailable"``) so a client can
    tell "retry against the restarted server" apart from "my request is
    malformed"."""

    code = "unavailable"


def _model_registry():
    """Name -> (module, default opt options).  Lazily imported so the
    server module stays importable without touching every model."""
    from ..models import farmer, netdes, sizes, sslp, uc_lite

    return {
        "farmer": (farmer, {"defaultPHrho": 1.0,
                            "xhat_looper_options": {"scen_limit": 3}}),
        # UC runs the bench wheel's rho (bench_uc.py: LP-relaxation-tight
        # family, rho=500 matches the cost scale)
        "uc_lite": (uc_lite, {"defaultPHrho": 500.0,
                              "xhat_looper_options": {"scen_limit": 3}}),
        "sslp": (sslp, {"defaultPHrho": 5.0,
                        "xhat_looper_options": {"scen_limit": 3}}),
        # integer families (doc/integer.md): one-line requests for the
        # batched integer wheel — rho from the example drivers; requests
        # add {"relax_integers": False} in creator_kwargs for the true
        # integer posture (the sweep arms itself from the int pattern)
        "sizes": (sizes, {"defaultPHrho": 0.01,
                          "xhat_looper_options": {"scen_limit": 3}}),
        "netdes": (netdes, {"defaultPHrho": 1.0,
                            "xhat_looper_options": {"scen_limit": 3}}),
    }


class SolveRequest:
    """One solve request.

    Args:
      model: registry name ("farmer", "uc_lite", "sslp") — or pass
        ``scenario_creator`` + ``names`` for a custom family (in-process
        submits only; the TCP transport is name-based).
      num_scens: scenario count.
      creator_kwargs: extra scenario-creator kwargs (seedoffset,
        crops_multiplier, num_gens, ... — routed through the model's
        ``kw_creator``).
      options: opt/hub option overrides (PHIterLimit, rel_gap,
        solver_options, ...).  ``rel_gap`` defaults to the server's.
      request_id: optional stable id (generated when empty).  A STABLE
        id is the idempotency key: re-submitting a journaled id — a
        client retry after a reconnect or a server restart — resolves to
        the original record instead of starting a second run.
      deadline_secs: optional wall-clock budget from ACCEPTANCE: a
        request still unfinished past it parks at the next checkpoint
        seam and completes ``failed`` (``error_code="deadline"``,
        checkpoint banked) instead of burning scheduler quantum forever.
        The deadline is absolute — it keeps ticking across server
        restarts.
      qos: QoS class ("interactive" < "standard" < "batch") — decides
        SLOT ASSIGNMENT when several same-family tenants compete for a
        continuous-batching slot (doc/serving.md "Continuous batching");
        ties keep submission order, so same-class requests retain FIFO
        semantics.  Scheduler-side only (popped from the canonical
        settings key like rel_gap).
      trace_id: request-scoped trace id (doc/observability.md "The
        request telemetry plane").  Minted at the OUTERMOST edge —
        ``SolveClient.submit`` — and carried here through the wire;
        minted fresh only for requests that arrive without one
        (in-process submits).  Persisted in the journal, so a
        SIGKILL-recovered request keeps its trace.
    """

    def __init__(self, model="farmer", num_scens=3, creator_kwargs=None,
                 options=None, request_id=None, scenario_creator=None,
                 names=None, deadline_secs=None, qos=None,
                 trace_id=None):
        self.model = str(model)
        self.num_scens = int(num_scens)
        self.creator_kwargs = dict(creator_kwargs or {})
        self.options = dict(options or {})
        self.request_id = request_id or f"req-{uuid.uuid4().hex[:10]}"
        self.scenario_creator = scenario_creator
        self.names = names
        if deadline_secs is None:
            # options spelling works too, like rel_gap/linger_secs (it
            # is a hub-side knob — _resolve pops it from the canonical
            # settings key either way)
            deadline_secs = self.options.get("deadline_secs")
        self.deadline_secs = (None if deadline_secs is None
                              else float(deadline_secs))
        if qos is None:
            qos = self.options.get("qos")
        self.qos = str(qos or "standard")
        self.trace_id = str(trace_id or _telemetry.mint_trace_id())

    @classmethod
    def from_dict(cls, d: dict) -> "SolveRequest":
        return cls(model=d.get("model", "farmer"),
                   num_scens=d.get("num_scens", 3),
                   creator_kwargs=d.get("creator_kwargs"),
                   options=d.get("options"),
                   request_id=d.get("request_id"),
                   deadline_secs=d.get("deadline_secs"),
                   qos=d.get("qos"),
                   trace_id=d.get("trace_id"))

    def to_dict(self) -> dict:
        """The journal/wire form.  Custom in-process creators are NOT
        representable (callables don't journal) — such requests are
        accepted but flagged unrecoverable in the WAL."""
        return {"model": self.model, "num_scens": self.num_scens,
                "creator_kwargs": dict(self.creator_kwargs),
                "options": dict(self.options),
                "request_id": self.request_id,
                "deadline_secs": self.deadline_secs,
                "qos": self.qos,
                "trace_id": self.trace_id}


def _blank_record(rid, model, family, fingerprint) -> dict:
    """THE SLO-record template — the single source of the field set
    (both tenant constructors build from it; a recovered tenant's
    journaled snapshot overlays it, so a field added here can never be
    silently absent after a restart)."""
    return {
        "request_id": rid, "model": model,
        "family": family, "fingerprint": fingerprint,
        "status": "queued", "warm_hit": None,
        "queue_wait_s": None, "exec_s": 0.0, "wall_s": None,
        "ttfi_s": None, "compile_s": 0.0,
        "aot_hits": 0.0, "aot_misses": 0.0,
        "slices": 0, "preemptions": 0, "iters": 0,
        "iters_per_sec": None, "rel_gap": None,
        "inner": None, "outer": None, "certified": False,
        "bounds_monotone": True, "error": None, "error_code": None,
        "recovered": None,
        # continuous batching (doc/serving.md): QoS class, whether any
        # execution ran inside a fused tenant batch, and the tenant's
        # live-row share of the shared dispatches' model FLOPs
        "qos": "standard", "batched": False, "attributed_flops": 0.0,
        # request-scoped trace id (the telemetry plane's merge key —
        # riding the record means journal replay restores it for free)
        "trace_id": None,
    }


class _Tenant:
    """Scheduler-side state of one request.

    ``family`` is the canonical model's FAMILY DIGEST (the stable short
    hash of the family-key tuple) rather than the tuple itself: equal
    tuples <=> equal digests, and a digest survives the journal, so
    affinity/warm bookkeeping keys stay comparable across server
    restarts."""

    def __init__(self, req, canon, opt_options, creator, names, workdir):
        self.req = req
        self.canonical = canon             # dropped on completion (the
        self.family = canon.family_digest  # batched arrays are the bulk
        self.opt_options = opt_options     # of a tenant's footprint)
        self.creator = creator
        self.names = names
        self.id = req.request_id
        self.dir = os.path.join(workdir, "tenants", self.id)
        self.seq = 0                       # submission order (server sets)
        self.status = "queued"
        self.slices = 0
        self.submitted = time.monotonic()
        self.deadline_at = (time.time() + req.deadline_secs
                            if req.deadline_secs else None)
        self.first_exec = None
        self.done = threading.Event()
        self.last_outer = -inf
        self.last_inner = inf
        self.record = _blank_record(self.id, req.model,
                                    canon.family_digest,
                                    canon.fingerprint[:12])
        self.record["qos"] = req.qos
        self.trace = req.trace_id
        self.record["trace_id"] = req.trace_id

    def past_deadline(self) -> bool:
        return self.deadline_at is not None and time.time() > self.deadline_at

    @classmethod
    def from_journal(cls, jr, workdir):
        """Rebuild scheduler bookkeeping from a journal record — the
        restart-recovery constructor.  The canonical model is NOT
        rebuilt here (finished stubs never need it; unfinished tenants
        re-ingest in ``SolveServer._recover``)."""
        t = object.__new__(cls)
        t.req = (SolveRequest.from_dict(jr.request) if jr.request
                 else SolveRequest(request_id=jr.rid))
        t.req.request_id = jr.rid
        t.canonical = None
        t.opt_options = None
        t.creator = None
        t.names = None
        t.family = jr.family
        t.id = jr.rid
        t.dir = jr.checkpoint_dir or os.path.join(workdir, "tenants",
                                                  jr.rid)
        t.seq = int(jr.seq)
        t.status = jr.status
        t.slices = int(jr.record.get("slices") or 0)
        t.submitted = time.monotonic()
        t.deadline_at = jr.deadline_at
        t.first_exec = None
        t.done = threading.Event()
        rec = dict(jr.record)
        if not rec and jr.undelivered:
            # no status snapshot ever landed (an undelivered-rejection
            # stub, or a terminal transition whose append failed): the
            # banked response payload is the best record we have
            rec = dict(jr.undelivered)
        ob, ib = rec.get("outer"), rec.get("inner")
        t.last_outer = float(ob) if ob is not None and np.isfinite(ob) \
            else -inf
        t.last_inner = float(ib) if ib is not None and np.isfinite(ib) \
            else inf
        base = _blank_record(t.id, t.req.model, jr.family, "")
        base.update(rec)
        base["status"] = jr.status
        # the trace survives the restart: the journal carries the id
        # first-class (accepted line), with the request payload / record
        # snapshot as legacy fallbacks — a recovered request's spans
        # continue the SAME trace minted at the client
        t.trace = (getattr(jr, "trace_id", "")
                   or base.get("trace_id") or t.req.trace_id)
        base["trace_id"] = t.trace
        t.req.trace_id = t.trace
        t.record = base
        return t


class SolveServer:
    """The long-lived solve server (in-process API; TCP transport in
    :mod:`tpusppy.service.net`).

    Args:
      work_dir: root for per-tenant checkpoints + the AOT/tune caches
        (a temp dir when omitted).  Pointing several server LIFETIMES at
        one ``work_dir`` is the restart-warm path: executables persist.
      quantum_secs: minimum uninterrupted run time a wheel gets before a
        waiting tenant may preempt it.
      rel_gap: default certification target per request.
      arm_caches: arm the AOT executable cache + persistent tune-verdict
        store under ``work_dir`` (kept as-is when the process already
        armed them).
      max_queue: admission bound — a submit that would push the run
        queue past this depth fast-fails with the typed
        :class:`ServerOverloaded` (``service.rejected_overload``).
        None (default) = unbounded.
      checkpoint_every_secs: mid-slice checkpoint cadence for every
        tenant wheel (on top of the terminal park capture) — bounds how
        much work a server crash can cost a RUNNING tenant.
      recover: replay the work dir's request journal on startup
        (doc/serving.md "Durability"): parked tenants re-ingest and
        resume from their banked checkpoints (warm — the AOT disk cache
        under the same work dir re-arms first), queued-never-started
        tenants re-enter the queue in submission order, mid-slice
        tenants without a complete checkpoint restart from scratch
        loudly (``service.recovered_cold``), and finished tenants'
        records stay fetchable by id.  :meth:`recover_from` is the
        explicit spelling.
      batch_slots: continuous batching (doc/serving.md): K > 1 fuses up
        to K concurrent SAME-FAMILY self-certifying tenants into one
        tenant-batched megastep (``service/batching.py``) — joins and
        evictions at window boundaries, per-tenant trajectories exactly
        the solo wheel's.  None/1 keeps pure time-slicing.  A banked
        "batched" tune verdict (``tune.batched_verdict``) CLAMPS K per
        family when one exists.
    """

    def __init__(self, work_dir=None, quantum_secs=5.0, rel_gap=1e-3,
                 linger_secs=30.0, arm_caches=True, max_queue=None,
                 checkpoint_every_secs=20.0, recover=False,
                 in_wheel_bounds=False, batch_slots=None,
                 _start_executor=True):
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="tpusppy_srv_")
        os.makedirs(os.path.join(self.work_dir, "tenants"), exist_ok=True)
        self.quantum_secs = float(quantum_secs)
        self.rel_gap = float(rel_gap)
        self.linger_secs = float(linger_secs)
        # self-certifying tenant wheels (doc/pipeline.md "In-wheel
        # certification"): the megastep's fused bound pass certifies the
        # gap, so a slice runs ZERO spoke threads/device programs —
        # shrinking each request's device footprint to one cylinder.
        # Server default; a request option "in_wheel_bounds" overrides
        # per tenant.
        self.in_wheel_bounds = bool(in_wheel_bounds)
        self.batch_slots = (None if not batch_slots or int(batch_slots) < 2
                            else int(batch_slots))
        self.max_queue = None if max_queue is None else int(max_queue)
        self.checkpoint_every_secs = float(checkpoint_every_secs)
        self._cv = threading.Condition()
        self._runq: collections.deque = collections.deque()
        self._tenants: dict = {}
        self._families: dict = {}          # family digest -> request count
        self._families_done: set = set()   # families with a COMPLETED run
        self._family_open: dict = {}       # family -> set of UNFINISHED seqs
                                           # (affinity checks stay O(open),
                                           # never O(historical requests))
        self._force_preempt: set = set()
        self._stop = False
        self._drain = True                 # shutdown(wait=True) semantics
        self._seq = 0
        # the live telemetry plane (doc/observability.md): bounded
        # per-request progress queues the TCP frontend streams from
        # (SolveClient.watch), plus batch-occupancy bookkeeping for the
        # scrape endpoint's status snapshot
        self.progress = _telemetry.ProgressBus()
        self._batch_live: dict = {}
        _telemetry.record_clock_sync("scheduler", work_dir=self.work_dir)
        # the write-ahead request journal (service/journal.py): accepted
        # requests + status transitions persist under the work dir, so a
        # crashed server's obligations survive it
        self.journal = RequestJournal(
            os.path.join(self.work_dir, "journal.jsonl"))
        if arm_caches:
            self._arm_caches()
        if recover:
            self._recover()
        self._executor = None
        if _start_executor:
            self._executor = threading.Thread(
                target=self._executor_loop, name="solve-server",
                daemon=True)
            self._executor.start()

    @classmethod
    def recover_from(cls, work_dir, **kwargs):
        """A restarted server over an existing ``work_dir``: replay the
        journal, re-admit every unfinished tenant, serve finished
        records by id.  Equivalent to ``SolveServer(work_dir=...,
        recover=True, ...)``."""
        kwargs.setdefault("recover", True)
        return cls(work_dir=work_dir, **kwargs)

    # ---- lifecycle ----------------------------------------------------------
    def _arm_caches(self):
        """Warm-start infrastructure: the AOT executable cache and the
        persistent autotuner verdict store live under the work dir (so a
        RESTARTED server re-binds warm from disk), and whatever is
        already on disk is prewarmed NOW — before any request compiles
        (the loader must not race in-flight compiles; see aot.py)."""
        from .. import tune as _tune
        from ..solvers import aot as _aot

        if not _aot.cache_path():
            _aot.set_cache_path(os.path.join(self.work_dir, "aot"))
        if _aot.enabled():
            _aot.prewarm()
        try:
            if _tune.cache_path() is None:
                _tune.set_cache_path(
                    os.path.join(self.work_dir, "tune_cache.json"))
        except Exception:      # tune persistence is an optimization only
            pass

    # ---- restart recovery ---------------------------------------------------
    def _recover(self):
        """Replay the journal into live scheduler state.  Runs on the
        constructing thread BEFORE the executor starts, so no locking is
        needed against ourselves — and any prewarm the cache arm did has
        already finished (the loader must never race a compile)."""
        from ..resilience import checkpoint as _ckpt

        replayed = self.journal.replay()
        if not replayed:
            return
        # journal writes during recovery go through the degrade-not-die
        # guard like everywhere else: an unwritable journal (disk full)
        # must not abort the restart and strand every journaled
        # obligation — it costs durability of the NEXT crash only
        self._journal_append_safe(lambda: self.journal.recovery_marker(
            {"pid": os.getpid(), "journaled": len(replayed)}))
        self._seq = max(r.seq for r in replayed.values()) + 1
        for jr in sorted(replayed.values(), key=lambda r: r.seq):
            t = _Tenant.from_journal(jr, self.work_dir)
            self._tenants[t.id] = t
            if jr.finished:
                # finished in a previous lifetime: the record stays
                # fetchable by id (result()/the TCP fetch op), and a
                # completed family is warm capital for followers
                # (undelivered-rejection stubs carry no family)
                if t.family:
                    self._families[t.family] = \
                        self._families.get(t.family, 0) + 1
                    if jr.status == "done":
                        self._families_done.add(t.family)
                t.done.set()
                continue
            if not jr.recoverable:
                # custom in-process creators don't journal (callables):
                # fail the obligation loudly rather than strand waiters
                t.status = "failed"
                t.record.update(
                    status="failed", error_code="unrecoverable",
                    error="request used a custom scenario_creator — not "
                          "recoverable across a server restart")
                self._families[t.family] = \
                    self._families.get(t.family, 0) + 1
                self._journal_safe(t.id, "failed", t.record)
                _CTR_FAILED.inc(1)
                t.done.set()
                continue
            try:
                creator, names, kwargs, opt_options = self._resolve(t.req)
                canon = _canonical.ingest(names, creator, kwargs,
                                          options=opt_options)
                t.req.creator_kwargs = kwargs
                t.canonical, t.opt_options = canon, opt_options
                t.creator, t.names = creator, names
                t.record["fingerprint"] = canon.fingerprint[:12]
                drifted = bool(jr.family
                               and canon.family_digest != jr.family)
                if drifted:
                    # the model code changed between lifetimes: the
                    # banked checkpoint/executables belong to a
                    # DIFFERENT program family — it must never be
                    # resumed (shape/settings mismatch), so the warm
                    # branch below is off the table and the stale
                    # checkpoints are wiped by the cold slice's
                    # fresh_start
                    _log.warning(
                        "request %s: family drifted across restart "
                        "(%s -> %s) — cold restart", t.id, jr.family,
                        canon.family_digest)
                    t.family = canon.family_digest
                    t.record["family"] = canon.family_digest
                    t.slices = 0
                    # PERSIST the new family: replay folds `family` from
                    # the accepted event, so without re-journaling it a
                    # SECOND restart would re-detect "drift" against the
                    # stale digest and wipe the legitimately-banked
                    # new-family checkpoints all over again
                    self._journal_append_safe(
                        lambda t=t, jr=jr, canon=canon:
                        self.journal.accepted(
                            rid=t.id, seq=t.seq,
                            request=t.req.to_dict(),
                            family=canon.family_digest,
                            checkpoint_dir=t.dir,
                            recoverable=jr.recoverable,
                            deadline_at=t.deadline_at,
                            record=t.record,
                            trace_id=t.trace))
            except Exception as e:
                t.status = "failed"
                t.record.update(status="failed", error_code="exception",
                                error=repr(e))
                self._families[t.family] = \
                    self._families.get(t.family, 0) + 1
                self._journal_safe(t.id, "failed", t.record)
                _CTR_FAILED.inc(1)
                t.done.set()
                continue
            banked = None if drifted else _ckpt.latest_iteration(t.dir)
            started = jr.status in ("running", "parked") or t.slices > 0
            if started and banked is not None:
                # warm resume: the park (or mid-slice cadence) checkpoint
                # carries W/xbars/rho + bounds; the next slice continues
                # with PHIterLimit total-iteration semantics and bounds
                # monotone vs the snapshot (seeded above from the
                # journaled record)
                t.slices = max(t.slices, 1)
                t.record["recovered"] = "warm"
                _log.info("request %s recovered PARKED at checkpoint "
                          "iteration %d", t.id, banked)
            elif started:
                # mid-slice with no complete checkpoint: the slice's
                # work is LOST — restart from scratch, loudly.  The
                # record's execution state resets WITH the scheduler's
                # (a journaled slices>0 would read as "started" at the
                # next recovery and re-trigger the cold path forever)
                _CTR_RECOVERED_COLD.inc(1)
                t.slices = 0
                t.record["recovered"] = "cold"
                t.last_outer, t.last_inner = -inf, inf
                t.record.update(slices=0, iters=0, ttfi_s=None,
                                exec_s=0.0)
                _log.warning(
                    "request %s was mid-slice with no complete "
                    "checkpoint — restarting from scratch", t.id)
            else:
                t.record["recovered"] = "requeued"
            t.status = "queued"
            t.record["status"] = "queued"
            # family bookkeeping keyed on the FINAL digest (drift above
            # may have rewritten t.family — counting earlier would bank
            # the stale digest and double-count the family forever)
            self._families[t.family] = self._families.get(t.family, 0) + 1
            self._family_open.setdefault(t.family, set()).add(t.seq)
            self._runq.append(t)           # seq-sorted iteration above
            _CTR_RECOVERED.inc(1)          # => original admission order
            self._journal_safe(t.id, "queued", t.record)
            # same trace_id across the kill: the recovered lifetime's
            # spans continue the trace the client minted
            _telemetry.tenant_instant(
                t.id, t.trace, "recovered",
                mode=t.record["recovered"], seq=t.seq)
            self.progress.emit(t.id, "recovered", status="queued",
                               mode=t.record["recovered"],
                               trace_id=t.trace)
        _log.info("recovery: %d journaled request(s) — %d re-admitted, "
                  "%d already finished", len(replayed), len(self._runq),
                  sum(1 for r in replayed.values() if r.finished))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    def shutdown(self, wait: bool = True, timeout: float = 600.0,
                 drain: bool | None = None, park_queued: bool = False):
        """Stop the server.  ``wait=True`` / ``drain=True`` (default)
        is the GRACEFUL DRAIN: admissions stop immediately (submit
        raises), every already-admitted request finishes (or parks on
        its deadline), and each final state is journaled by the normal
        transition path.  ``wait=False`` preempts the running wheel at
        its next window boundary and leaves unfinished tenants PARKED
        on disk — ``SolveServer.recover_from(work_dir)`` resumes them;
        ``park_queued=True`` additionally keeps queued-never-started
        tenants journaled as queued (recoverable) instead of cancelling
        them."""
        if drain is not None:
            wait = bool(drain)
        with self._cv:
            self._stop = True
            self._drain = bool(wait)
            if not wait:
                self._force_preempt.update(t.id for t in self._tenants.values()
                                           if t.status == "running")
                # queued-but-never-started tenants have no state to park:
                # CANCEL them loudly so result() waiters unblock instead
                # of timing out against a dead queue (park_queued=True
                # keeps them journaled-queued for a recovering
                # successor).  Tenants already PARKED in the queue DO
                # have banked checkpoints — they stay parked
                # (resumable), exactly like the running one
                for t in self._runq:
                    if t.slices > 0:
                        t.status = "parked"
                        t.record["status"] = "parked"
                    elif park_queued:
                        t.record["status"] = "queued"
                    else:
                        t.status = "cancelled"
                        t.record.update(
                            status="cancelled", error_code="cancelled",
                            error="server shut down before start")
                        t.canonical = None
                    self._journal_safe(t.id, t.record["status"], t.record)
                    self._close_tenant_locked(t)
                    self.progress.emit(t.id, t.record["status"],
                                       status=t.record["status"])
                    self.progress.mark_done(t.id)
                    t.done.set()
                self._runq.clear()
            self._cv.notify_all()
        if self._executor is not None:
            self._executor.join(timeout=timeout)
        # release shared device memory the serving process held (content-
        # keyed A caches): a clean shutdown parks no orphan device state
        from ..spopt import clear_device_caches

        clear_device_caches()

    def _close_tenant_locked(self, t):
        """Retire a tenant from the affinity index (caller holds _cv)."""
        open_ = self._family_open.get(t.family)
        if open_ is not None:
            open_.discard(t.seq)
            if not open_:
                del self._family_open[t.family]

    def _journal_append_safe(self, fn):
        """Run one journal append; an IO failure (disk full, work dir
        yanked) costs DURABILITY of that entry, never the serving path
        itself — warned once per server."""
        try:
            fn()
        except Exception as e:
            if not getattr(self, "_journal_err_warned", False):
                self._journal_err_warned = True
                _log.warning("journal append failed (durability "
                             "degraded): %r", e)

    def _journal_safe(self, rid, status, record=None):
        self._journal_append_safe(
            lambda: self.journal.transition(rid, status, record))

    # ---- submission ---------------------------------------------------------
    def _resolve(self, req: SolveRequest):
        """(creator, names, creator_kwargs, opt_options) for one request
        — opt_options is the FINAL option dict the wheel opts run with,
        and therefore exactly what the canonicalizer must key on."""
        if req.scenario_creator is not None:
            creator = req.scenario_creator
            names = list(req.names or
                         [f"scen{i}" for i in range(req.num_scens)])
            kwargs = dict(req.creator_kwargs)
            defaults = {"defaultPHrho": 1.0,
                        "xhat_looper_options": {"scen_limit": 3}}
        else:
            registry = _model_registry()
            if req.model not in registry:
                raise ValueError(f"unknown model {req.model!r} "
                                 f"(have {sorted(registry)})")
            module, defaults = registry[req.model]
            names = module.scenario_names_creator(req.num_scens)
            kwargs = module.kw_creator(
                **dict(req.creator_kwargs, num_scens=req.num_scens))
            creator = module.scenario_creator
        opt_options = dict(defaults)
        opt_options.update({
            "PHIterLimit": 200, "convthresh": -1.0,
        })
        opt_options.update(req.options)
        # hub-side knobs must not leak into the canonical settings key
        for k in ("rel_gap", "abs_gap", "linger_secs", "deadline_secs",
                  "qos"):
            opt_options.pop(k, None)
        # the server-level self-certifying default resolves HERE so the
        # family key sees the effective value (a request that rode a
        # different server default must never warm-bind the other
        # variant's programs)
        if opt_options.get("in_wheel_bounds") is None:
            opt_options["in_wheel_bounds"] = self.in_wheel_bounds
        return creator, names, kwargs, opt_options

    def submit(self, req) -> str:
        """Ingest + canonicalize + enqueue; returns the request id.
        Ingestion runs on the CALLER's thread (pure numpy — it cannot
        disturb the executor's device work).

        IDEMPOTENT on request id: re-submitting an already-journaled id
        (a client retry after a reconnect, or after a server restart)
        returns the existing request's id instead of starting a second
        run — ``result(rid)`` then serves the original record.  The
        bounded queue fast-fails with :class:`ServerOverloaded` before
        paying for ingest."""
        if isinstance(req, dict):
            req = SolveRequest.from_dict(req)
        req_payload = req.to_dict()        # journal the ORIGINAL request
        with self._cv:
            if self._stop:
                raise ServerClosed("server is shut down")
            if req.request_id in self._tenants:
                _CTR_DUPLICATES.inc(1)
                _log.info("request %s re-submitted — resolving to the "
                          "existing record (idempotent)", req.request_id)
                return req.request_id
            if (self.max_queue is not None
                    and len(self._runq) >= self.max_queue):
                _CTR_REJECTED.inc(1)
                raise ServerOverloaded(
                    f"queue full ({len(self._runq)}/{self.max_queue}): "
                    f"request {req.request_id!r} rejected")
        if _faults.active():               # deterministic slow-ingest
            _faults.on_ingest()            # injection (stall_ingest)
        creator, names, kwargs, opt_options = self._resolve(req)
        canon = _canonical.ingest(names, creator, kwargs,
                                  options=opt_options)
        t = _Tenant(req, canon, opt_options, creator, names, self.work_dir)
        t.req.creator_kwargs = kwargs
        with self._cv:
            if self._stop:
                # re-check under a lock hold BEFORE any visible state: a
                # shutdown racing the (slow, unlocked) ingest above must
                # not slip a tenant into a queue nobody will ever drain
                raise ServerClosed("server is shut down")
            if t.id in self._tenants:
                # two concurrent submits of the same id raced the
                # ingest: the loser resolves to the winner's record —
                # same idempotency contract as the pre-ingest check
                _CTR_DUPLICATES.inc(1)
                return t.id
            if (self.max_queue is not None
                    and len(self._runq) >= self.max_queue):
                # authoritative admission check at the enqueue (the
                # pre-ingest one is the cheap fast path; concurrent
                # ingests may both have passed it)
                _CTR_REJECTED.inc(1)
                raise ServerOverloaded(
                    f"queue full ({len(self._runq)}/{self.max_queue}): "
                    f"request {t.id!r} rejected")
            self._families[t.family] = \
                self._families.get(t.family, 0) + 1
            t.seq = self._seq
            self._seq += 1
            self._family_open.setdefault(t.family, set()).add(t.seq)
            self._tenants[t.id] = t
            # counted only once ACCEPTED (rejected duplicates/shutdown
            # races must not leave phantom requests on the dashboards)
            _CTR_REQUESTS.inc(1)
        # WRITE-AHEAD: the acceptance is journaled BEFORE the tenant
        # becomes runnable (enqueue + notify below) — otherwise a fast
        # executor could journal this tenant's 'running' (even 'done')
        # transition ahead of its 'accepted' line, and replay drops
        # status events for unknown rids (the crash would then recover
        # a mid-slice tenant as never-started).  The tenant is already
        # in _tenants, so duplicate submits in this window resolve
        # idempotently.
        self._journal_append_safe(lambda: self.journal.accepted(
            rid=t.id, seq=t.seq, request=req_payload,
            family=canon.family_digest, checkpoint_dir=t.dir,
            recoverable=req.scenario_creator is None,
            deadline_at=t.deadline_at, record=t.record,
            trace_id=t.trace))
        with self._cv:
            if self._stop:
                # a shutdown landed while we journaled: the executor may
                # already have drained and exited, so enqueueing now
                # would strand the waiters.  Un-admit loudly — and
                # journal the cancellation so a recovering successor
                # does not resurrect a request its submitter saw fail.
                del self._tenants[t.id]
                self._close_tenant_locked(t)
                self._families[t.family] -= 1
                t.status = "cancelled"
                t.record.update(status="cancelled",
                                error_code="cancelled",
                                error="server shut down during submit")
                self._journal_safe(t.id, "cancelled", t.record)
                # a racing result() waiter that already grabbed the
                # tenant object must unblock, not hang
                self.progress.emit(t.id, "cancelled", status="cancelled")
                self.progress.mark_done(t.id)
                t.done.set()
                raise ServerClosed("server is shut down")
            self._runq.append(t)
            self._cv.notify_all()
        # admission on the request's trace + progress stream: the first
        # event a watcher sees, and the span boundary trace_merge joins
        # to the client's submit instant
        _telemetry.tenant_instant(t.id, t.trace, "admitted",
                                  model=req.model, qos=req.qos,
                                  family=canon.family_digest, seq=t.seq)
        self.progress.emit(t.id, "queued", status="queued",
                           model=req.model, qos=req.qos,
                           trace_id=t.trace)
        # warm_hit is decided at FIRST EXECUTION, not here: only a family
        # whose compile leader actually COMPLETED has executables to bind
        # (family affinity guarantees the leader finishes first; a failed
        # leader must not mark its followers warm)
        _log.info("request %s (%s, family %s) queued", t.id, req.model,
                  canon.family_digest)
        return t.id

    def preempt(self, request_id: str):
        """Ask a running request to park at its next window boundary
        (deterministic preemption for tests/operators; the scheduler's
        own quantum preemption needs no call)."""
        with self._cv:
            self._force_preempt.add(request_id)

    # ---- results ------------------------------------------------------------
    def result(self, request_id: str, timeout: float | None = None) -> dict:
        """Block until the request finishes; returns its SLO record.
        A finished request that was retired from memory (or finished in
        a PREVIOUS server lifetime) still answers from the journal."""
        t = self._tenants.get(request_id)
        if t is None:
            rec = self._journal_record(request_id)
            if rec is not None:
                return rec
            raise KeyError(f"unknown (or retired) request id "
                           f"{request_id!r}")
        if not t.done.wait(timeout):
            raise TimeoutError(f"request {request_id} still "
                               f"{t.status} after {timeout}s")
        return dict(t.record)

    def _journal_record(self, request_id: str) -> dict | None:
        """Finished record for ``request_id`` from the journal (None
        when the journal never saw it, or it never finished).  Uses the
        stat-memoized replay — a polling fetch-by-id client must not
        re-parse the whole journal per call.  An UNDELIVERED banked
        response serves as the fallback: if the terminal transition
        append itself failed (durability degraded) but the frontend's
        failed-put payload was journaled, that payload is still the
        best record we have for the id."""
        try:
            jr = self.journal.replay_cached().get(request_id)
        except Exception:
            return None
        if jr is None:
            return None
        if jr.finished and jr.record:
            return dict(jr.record)
        if jr.undelivered:
            return dict(jr.undelivered)
        return None

    def lookup(self, request_id: str):
        """The live tenant for ``request_id`` (None when unknown) — the
        TCP frontend's non-blocking hook for fetch-by-id."""
        return self._tenants.get(request_id)

    def status_snapshot(self, request_id: str | None = None) -> dict:
        """The live status surface (the ``status`` RPC and the scrape
        endpoint's per-tenant gauges both render this).

        Whole-server form (``request_id=None``)::

            {"queue_depth", "requests_live", "batch_slots",
             "batch_slots_occupied", "requests": {rid: {status, model,
             qos, batched, trace_id, rel_gap, outer, inner, iters,
             certified, attributed_flops, mfu_pct,
             deadline_headroom_s, queue_wait_s, exec_s}}}

        Per-request form: ``{"request_id", "done", "status",
        "record"}`` — the record snapshot is served from memory (live
        tenants) or the journal (previous lifetimes), WITHOUT blocking
        for completion: the answer a poll-free client wakes on."""
        if request_id is not None:
            t = self._tenants.get(str(request_id))
            if t is not None:
                return {"request_id": str(request_id),
                        "done": t.done.is_set(), "status": t.status,
                        "record": dict(t.record)}
            rec = self._journal_record(str(request_id))
            return {"request_id": str(request_id),
                    "done": rec is not None,
                    "status": (rec or {}).get("status"),
                    "record": rec}
        from ..solvers import flops as _flops

        now = time.time()
        with self._cv:
            tenants = list(self._tenants.values())
            qdepth = len(self._runq)
            batch = dict(self._batch_live)
        peak, _note = _flops.device_peak_flops()
        reqs = {}
        live = 0
        for t in tenants:
            r = t.record
            if t.status in ("queued", "running", "parked"):
                live += 1
            mfu = None
            if peak and r.get("attributed_flops") and r.get("exec_s"):
                mfu = (100.0 * r["attributed_flops"]
                       / (r["exec_s"] * peak))
            reqs[t.id] = {
                "status": t.status, "model": r.get("model"),
                "qos": r.get("qos"), "batched": r.get("batched"),
                "trace_id": r.get("trace_id"),
                "rel_gap": r.get("rel_gap"),
                "outer": r.get("outer"), "inner": r.get("inner"),
                "iters": r.get("iters"),
                "certified": r.get("certified"),
                "attributed_flops": r.get("attributed_flops"),
                "mfu_pct": mfu,
                "queue_wait_s": r.get("queue_wait_s"),
                "exec_s": r.get("exec_s"),
                "deadline_headroom_s": (
                    t.deadline_at - now
                    if t.deadline_at is not None else None),
            }
        return {"queue_depth": qdepth, "requests_live": live,
                "batch_slots": batch.get("k", self.batch_slots),
                "batch_slots_occupied": batch.get("occupied"),
                "requests": reqs}

    def retire_finished(self, keep: int = 0) -> int:
        """Drop finished tenants' bookkeeping (all but the newest
        ``keep``), returning how many were retired.  Completed tenants
        already released their batched arrays; this sheds the residual
        _Tenant + SLO-record dicts so a genuinely long-lived server's
        memory and ``slo_records`` cost stay bounded — call it (or wire
        it on a cadence) after harvesting the records you need.  The
        journal COMPACTS in the same sweep: retired records leave the
        file, retained ones fold to two lines each — so the journal's
        replay cost tracks the retained window, not server lifetime."""
        with self._cv:
            finished = [t for t in self._tenants.values()
                        if t.status in ("done", "failed", "cancelled")]
            finished.sort(key=lambda t: t.seq)
            drop = finished[:max(0, len(finished) - int(keep))]
            for t in drop:
                del self._tenants[t.id]
            retained = set(self._tenants)
        for t in drop:
            # progress-bus memory tracks the retained-record window
            self.progress.drop(t.id)
        try:
            # compact_keep folds + rewrites ATOMICALLY under the append
            # lock — a submit/transition racing this sweep serializes
            # against the rewrite instead of landing between read and
            # os.replace and being erased.  UNFINISHED records always
            # survive, retained or not: a submit journaled after the
            # retained-set snapshot must not be un-written.
            self.journal.compact_keep(
                lambda r: r.rid in retained or not r.finished)
        except Exception as e:
            _log.warning("journal compaction failed (file keeps "
                         "growing): %r", e)
        return len(drop)

    def slo_records(self) -> list:
        with self._cv:              # submit() inserts under this lock
            tenants = list(self._tenants.values())
        return [dict(t.record) for t in tenants]

    @staticmethod
    def _pct(values, q):
        """Nearest-rank percentile over this SERVER's own samples."""
        vals = sorted(v for v in values if v is not None)
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]

    def slo_summary(self) -> dict:
        """Aggregate serving SLOs over this instance's RETAINED records
        (``retire_finished`` narrows the window).  Percentiles are
        computed from the records themselves — the ``service.*``
        registry histograms carry the same samples for obs/report
        consumers, but they are process-global and would conflate
        several server lifetimes in one process."""
        with self._cv:
            tenants = list(self._tenants.values())
        recs = [t.record for t in tenants]
        done = [r for r in recs if r["status"] == "done"]
        n_warm = sum(1 for r in done if r["warm_hit"])
        walls = [r["wall_s"] for r in done]
        return {
            "requests": len(tenants),
            "completed": len(done),
            "failed": sum(1 for r in recs if r["status"] == "failed"),
            "warm_hit_rate": (n_warm / len(done)) if done else None,
            "preemptions": sum(r["preemptions"] for r in recs),
            "p50_latency_s": self._pct(walls, 0.50),
            "p95_latency_s": self._pct(walls, 0.95),
            "p99_latency_s": self._pct(walls, 0.99),
            "p50_queue_wait_s": self._pct(
                [r["queue_wait_s"] for r in recs], 0.50),
            "p95_queue_wait_s": self._pct(
                [r["queue_wait_s"] for r in recs], 0.95),
            "p50_ttfi_s": self._pct([r["ttfi_s"] for r in recs], 0.50),
            "families": len(self._families),
        }

    # ---- the executor -------------------------------------------------------
    def _pick_next(self):
        """Next runnable tenant under FAMILY AFFINITY: a tenant never
        starts while an EARLIER-submitted tenant of the same shape
        family is still unfinished.  The first request of a family is
        its compile leader — letting a warm follower race a parked
        leader would hand the follower whatever program variants the
        leader had not reached yet (park/resume truncates execution
        paths), breaking the warm zero-compile contract the follower
        was promised.  Cross-family requests still time-slice freely.
        Blocking is answered from the ``_family_open`` index (seq sets
        of UNFINISHED tenants only — O(open), never O(every request
        ever served)).  Caller holds the lock; returns None when every
        queued tenant is blocked (the blocking leader is queued or
        running, and its park/finish re-notifies)."""
        for i, t in enumerate(self._runq):
            open_ = self._family_open.get(t.family)
            if open_ is None or min(open_) >= t.seq:
                del self._runq[i]
                # mark running UNDER THE LOCK: a shutdown(wait=False)
                # racing the gap between pick and slice start must see
                # this tenant as preemptable, not miss it entirely
                t.status = "running"
                return t
        return None

    def _executor_loop(self):
        while True:
            with self._cv:
                while True:
                    if not self._runq and self._stop:
                        return             # stopped and drained
                    tenant = self._pick_next() if self._runq else None
                    if tenant is not None:
                        break
                    self._cv.wait()
            try:
                if self._batch_viable(tenant):
                    self._run_batch(tenant)
                else:
                    self._run_slice(tenant)
            except Exception as e:         # a tenant failure never kills
                _CTR_FAILED.inc(1)         # the server
                _log.warning("request %s failed: %r", tenant.id, e)
                tenant.status = "failed"
                tenant.record.update(status="failed",
                                     error_code="exception",
                                     error=repr(e))
                tenant.canonical = None    # release the batched arrays
                self._journal_safe(tenant.id, "failed", tenant.record)
                with self._cv:
                    self._close_tenant_locked(tenant)
                self.progress.emit(tenant.id, "failed", status="failed",
                                   error=repr(e))
                self.progress.mark_done(tenant.id)
                tenant.done.set()

    def _want_preempt(self, tenant, slice_start) -> bool:
        # an expired deadline parks UNCONDITIONALLY — the checkpoint
        # seam is where a doomed request exits cleanly (state banked,
        # bounds harvested) instead of burning quantum forever
        if tenant.past_deadline():
            return True
        with self._cv:
            if tenant.id in self._force_preempt:
                self._force_preempt.discard(tenant.id)
                return True
            # preempt only for a tenant that could actually RUN: a
            # queued same-family follower is blocked behind this very
            # tenant (family affinity), and parking for it would churn
            if not any(o.family != tenant.family or o.seq < tenant.seq
                       for o in self._runq):
                return False
        return time.monotonic() - slice_start >= self.quantum_secs

    def _finish_deadline(self, t: _Tenant):
        """Fail a request whose ``deadline_secs`` expired: UNCERTIFIED
        by construction, checkpoint (if any) left banked on disk, the
        record says exactly what happened.  The park already harvested
        bounds, so the record still carries the best-known gap."""
        _CTR_DEADLINE.inc(1)
        _CTR_FAILED.inc(1)
        t.status = "failed"
        t.record.update(
            status="failed", error_code="deadline",
            error=f"deadline_secs={t.req.deadline_secs} exceeded "
                  f"(parked at iter {t.record['iters']})",
            certified=False,
            wall_s=time.monotonic() - t.submitted)
        t.canonical = None
        t.opt_options = None
        t.creator = None
        self._journal_safe(t.id, "failed", t.record)
        with self._cv:
            self._close_tenant_locked(t)
        _log.warning("request %s failed its deadline (gap %s after %d "
                     "iter(s), %d slice(s))", t.id, t.record["rel_gap"],
                     t.record["iters"], t.slices)
        _telemetry.tenant_instant(t.id, t.trace, "deadline_failed",
                                  iters=t.record["iters"],
                                  rel_gap=t.record["rel_gap"])
        self.progress.emit(t.id, "deadline", status="failed",
                           iters=t.record["iters"],
                           rel_gap=t.record["rel_gap"])
        self.progress.mark_done(t.id)
        t.done.set()

    def _tenant_in_wheel(self, t: _Tenant) -> bool:
        """Whether this tenant's slices run the SELF-CERTIFYING wheel —
        resolved into ``opt_options`` at ingest (request option wins over
        the server default) so the family key keyed the same value."""
        return bool((t.opt_options or {}).get("in_wheel_bounds"))

    def _in_wheel_viable(self, t: _Tenant) -> bool:
        """Whether a spoke-LESS slice can actually certify: the fused
        bound pass exists only on the MEGASTEP path, so a family in the
        segmentation regime (the shape can't fit one frozen dispatch
        under the worker watchdog) or with too small a refresh window
        must keep its bound spokes — dropping them would leave the hub
        with zero bound sources and the slice would burn its whole
        budget uncertified.  Mirrors the ``PHBase`` megastep gate on the
        ingest-time canonical model; sparse shapes are modeled at dense
        sweep cost here, which errs toward KEEPING spokes, never toward
        an uncertifiable spoke-less slice."""
        from ..ir import BucketedBatch
        from ..solvers import segmented
        from ..spbase import make_admm_settings
        from ..spopt import bucket_shared

        if int(t.opt_options.get("solver_refresh_every", 16) or 0) <= 2:
            return False
        b = t.canonical.batch
        # second-stage integer columns make the in-scan frozen
        # evaluation an uncertified relaxation (PHBase._inwheel_inner_ok
        # refuses it) — but since the batched-integer-wheel PR
        # (doc/integer.md) such a family STILL certifies spoke-less:
        # the escalation tier's MIP leg (_maybe_integer_inner_mip /
        # escalate_inner) supplies the inner bound, provided the
        # escalation + rescue knobs are armed and the batch is
        # homogeneous (the MILP tier iterates batch.A[s]).  Only when
        # that inner source is UNAVAILABLE must the bound spokes stay.
        subs = ([sub for _, sub in b.buckets]
                if hasattr(b, "buckets") else [b])
        second_stage_int = False
        for sub in subs:
            free = np.ones(sub.num_vars, dtype=bool)
            free[sub.tree.nonant_indices] = False
            if np.asarray(sub.is_int, bool)[free].any():
                second_stage_int = True
                break
        if second_stage_int:
            mip_leg_ok = (
                not hasattr(b, "buckets")
                and t.opt_options.get("integer_escalation", True)
                and t.opt_options.get("in_wheel_host_rescue", True)
                and t.opt_options.get("in_wheel_int_sweep", True))
            if not mip_leg_ok:
                return False
        st = make_admm_settings(dict(t.opt_options), t.canonical.bundling)

        def fits(S, n, m, fb):
            _, seg_f = segmented.dispatch_segments(S, n, m, st,
                                                   factor_batch=fb)
            return seg_f >= st.max_iter

        if isinstance(b, BucketedBatch):
            shapes = []
            for idx, sub in b.buckets:
                fb = 1 if bucket_shared(sub) else idx.size
                if not fits(idx.size, sub.num_vars, sub.num_rows, fb):
                    return False
                shapes.append((idx.size, sub.num_vars, sub.num_rows, fb))
            # the bound-pass reservation must leave the megastep alive:
            # a barely-fitting family (reserved cap < 2) never runs the
            # fused pass (PHBase._megastep_cap_with_bounds declines it)
            return segmented.megastep_cap_multi(
                shapes, st, bound_pass=True) >= 2
        S, n, m = b.num_scenarios, b.num_vars, b.num_rows
        fb = 1 if getattr(b, "A_shared", None) is not None else S
        return (fits(S, n, m, fb)
                and segmented.megastep_cap(S, n, m, st, factor_batch=fb,
                                           bound_pass=True) >= 2)

    def _batch_viable(self, t: _Tenant) -> bool:
        """Whether this tenant may run inside a fused tenant batch
        (doc/serving.md "Continuous batching").  The batched runner is
        the SELF-CERTIFYING wheel generalized over a tenant axis, so the
        gate is the in-wheel gate plus the batch-specific exclusions:
        homogeneous batches only (the tenant kernel carries one shape
        per slot, not a bucket tuple), and no integer nonants (the
        batched integer sweep's global-argmin semantics have no
        per-tenant masked form — integer families keep time-slicing).
        """
        from ..ir import BucketedBatch

        if self.batch_slots is None or t.canonical is None:
            return False
        b = t.canonical.batch
        if isinstance(b, BucketedBatch):
            return False
        if np.asarray(b.is_int, bool).any():
            return False
        return self._tenant_in_wheel(t) and self._in_wheel_viable(t)

    def _build_wheel(self, t: _Tenant, preempt_check, on_iter0_done):
        """Hub/spoke dicts for one slice of one tenant — the standard
        certified-wheel topology (PH hub + Lagrangian outer + XhatShuffle
        inner), every cylinder binding the SAME canonical model.

        In-wheel mode (:meth:`_tenant_in_wheel`): the hub's megastep
        windows certify via the fused bound pass and the slice spawns NO
        spoke threads — per-request device footprint shrinks to one
        cylinder's programs (doc/pipeline.md "In-wheel certification").
        """
        from ..cylinders import (LagrangianOuterBound, PHHub,
                                 XhatShuffleInnerBound)
        from ..opt.ph import PH
        from ..phbase import PHBase
        from ..xhat_eval import Xhat_Eval

        in_wheel = self._tenant_in_wheel(t)
        if in_wheel and not self._in_wheel_viable(t):
            # keep the bound spokes: a spoke-less slice of this family
            # could never certify (no megastep -> no fused bound pass)
            if not getattr(t, "_in_wheel_declined", False):
                t._in_wheel_declined = True
                _log.warning(
                    "request %s: in_wheel_bounds requested but the "
                    "family cannot megastep (segmentation regime / "
                    "refresh window) — keeping bound spokes", t.id)
            in_wheel = False

        def opt_kwargs(extra=None):
            options = dict(t.opt_options, canonical_model=t.canonical)
            options.update(extra or {})
            return {
                "options": options,
                "all_scenario_names": list(t.names),
                "scenario_creator": t.creator,
                "scenario_creator_kwargs": dict(t.req.creator_kwargs),
            }

        hub_options = {
            "rel_gap": float(t.req.options.get("rel_gap", self.rel_gap)),
            "linger_secs": float(t.req.options.get("linger_secs",
                                                   self.linger_secs)),
            "preempt_check": preempt_check,
            # live per-window progress (doc/observability.md): the hub
            # calls this on every gap computation; the server dedupes
            # and feeds the request's progress stream + trace series
            "progress_cb": self._progress_cb(t),
            "checkpoint_dir": t.dir,
            # mid-slice cadence on top of the terminal park capture: a
            # server CRASH (not just a park) loses at most this much of
            # a running tenant's work (doc/serving.md "Durability")
            "checkpoint_every_secs": self.checkpoint_every_secs,
            "resume": t.dir if t.slices else None,
        }
        if "abs_gap" in t.req.options:
            hub_options["abs_gap"] = float(t.req.options["abs_gap"])
        hub_dict = {
            "hub_class": PHHub,
            "hub_kwargs": {"options": hub_options},
            "opt_class": PH,
            "opt_kwargs": opt_kwargs({"on_iter0_done": on_iter0_done}),
        }
        if in_wheel:
            return hub_dict, []
        spokes = [
            {"spoke_class": LagrangianOuterBound, "spoke_kwargs": {},
             "opt_class": PHBase, "opt_kwargs": opt_kwargs()},
            {"spoke_class": XhatShuffleInnerBound, "spoke_kwargs": {},
             "opt_class": Xhat_Eval, "opt_kwargs": opt_kwargs()},
        ]
        return hub_dict, spokes

    def _progress_cb(self, t: _Tenant):
        """Per-window progress hook for a SOLO slice's hub: dedupe the
        compute_gaps call stream (the hub computes gaps more than once
        per iteration) into the request's bounded progress queue — one
        ``gap`` point per new iteration, one ``bound_update`` per actual
        bound improvement — and mirror the same samples onto the
        request's trace track (source char '*': the hub's own typed
        updates)."""
        state = {"iter": -1, "outer": None, "inner": None}
        bus = self.progress

        def cb(abs_gap, rel_gap, outer, inner, iteration):
            improved = (outer, inner) != (state["outer"],
                                          state["inner"])
            fresh = iteration != state["iter"]
            if not (improved or fresh):
                return
            state.update(iter=iteration, outer=outer, inner=inner)
            if improved:
                bus.emit(t.id, "bound_update", source="*",
                         outer=float(outer), inner=float(inner),
                         iteration=int(iteration))
                if np.isfinite(outer):
                    _telemetry.tenant_counter(t.id, t.trace,
                                              "best_outer", outer)
                if np.isfinite(inner):
                    _telemetry.tenant_counter(t.id, t.trace,
                                              "best_inner", inner)
            if np.isfinite(rel_gap):
                bus.emit(t.id, "gap", iteration=int(iteration),
                         rel_gap=float(rel_gap),
                         abs_gap=float(abs_gap), source="*")
                _telemetry.tenant_counter(t.id, t.trace, "rel_gap",
                                          rel_gap)
                _telemetry.tenant_counter(t.id, t.trace, "abs_gap",
                                          abs_gap)
        return cb

    def _run_slice(self, t: _Tenant):
        from ..spin_the_wheel import WheelSpinner

        if t.past_deadline():
            # expired while queued/parked: fail WITHOUT burning a slice
            self._finish_deadline(t)
            return
        t.status = "running"
        t.record["status"] = "running"
        self._journal_safe(t.id, "running", t.record)
        self.progress.emit(t.id, "running", status="running",
                           slice=t.slices + 1)
        if t.first_exec is None:
            t.first_exec = time.monotonic()
            if t.record["queue_wait_s"] is None:
                # recovered tenants that already executed in a previous
                # lifetime keep their journaled queue wait — the restart
                # gap is recovery latency, not queueing, and summing the
                # two would double-count the metric across a recovery
                t.record["queue_wait_s"] = t.first_exec - t.submitted
                _HIST_QUEUE_WAIT.add(t.record["queue_wait_s"])
            # warm verdict at first execution: true only when a member
            # of this family actually COMPLETED (its executables exist);
            # family affinity made any earlier leader finish (or fail)
            # before this point.  None = never evaluated (a recovered
            # tenant keeps its first lifetime's verdict)
            if t.record["warm_hit"] is None:
                with self._cv:
                    warm = t.family in self._families_done
                t.record["warm_hit"] = warm
                (_CTR_WARM_HITS if warm else _CTR_COLD_FAMILIES).inc(1)
                _log.info("request %s starts %s", t.id,
                          "WARM" if warm else "cold")
        slice_start = time.monotonic()

        def on_iter0_done():
            if t.record["ttfi_s"] is None:
                t.record["ttfi_s"] = time.monotonic() - slice_start
                _HIST_TTFI.add(t.record["ttfi_s"])

        if t.slices == 0 and not t.record["warm_hit"]:
            # prewarm-on-ingest for a family THIS lifetime hasn't seen:
            # a restarted server over a persistent work_dir deserializes
            # the family's executables from the AOT disk cache instead
            # of recompiling.  Runs HERE (executor thread, before the
            # wheel's cylinder threads exist) because the executable
            # loader must never race an in-flight compile (aot.py).
            from ..solvers import aot as _aot

            if _aot.enabled():
                _aot.prewarm()
        hub_dict, spokes = self._build_wheel(
            t, lambda: self._want_preempt(t, slice_start), on_iter0_done)
        _CTR_SLICES.inc(1)
        # the executor is the ONLY thread doing device work, so registry
        # window deltas here are this slice's traffic (the wheel's own
        # cylinder threads are part of the slice)
        with _metrics.window() as w, \
                _telemetry.request_scope(t.trace, t.id), \
                _telemetry.tenant_span(t.id, t.trace, "slice",
                                       slice=t.slices + 1):
            ws = WheelSpinner(hub_dict, spokes).run()
        t.slices += 1
        if _faults.active():
            # deterministic serving chaos: the wheel tore down (terminal
            # checkpoint banked) but the transition below has NOT been
            # journaled — the kill lands in exactly the window restart
            # recovery must close (kill_server_after_slices)
            _faults.on_server_slice(t.slices)
        wall = time.monotonic() - slice_start
        hub = ws.spcomm
        rec = t.record
        rec["slices"] = t.slices
        rec["exec_s"] += wall
        rec["compile_s"] += w.delta("aot.compile_s")
        rec["aot_hits"] += w.delta("aot.hits")
        rec["aot_misses"] += w.delta("aot.misses")
        # bounds must be monotone across every park/resume cycle (the
        # seed_resume contract) — a violation is a correctness bug the
        # SLO record surfaces loudly
        ob, ib = float(hub.BestOuterBound), float(hub.BestInnerBound)
        tol = 1e-9 * max(1.0, abs(t.last_outer) if
                         np.isfinite(t.last_outer) else 1.0)
        if ob < t.last_outer - tol or ib > t.last_inner + tol:
            rec["bounds_monotone"] = False
            _log.warning("request %s: bounds regressed across resume "
                         "(outer %s -> %s, inner %s -> %s)", t.id,
                         t.last_outer, ob, t.last_inner, ib)
        t.last_outer = max(t.last_outer, ob)
        t.last_inner = min(t.last_inner, ib)
        rec["outer"], rec["inner"] = ob, ib
        rec["iters"] = int(hub.current_iteration())
        if rec["exec_s"] > 0:
            rec["iters_per_sec"] = rec["iters"] / rec["exec_s"]
        abs_gap, rel_gap = hub.compute_gaps()
        rec["rel_gap"] = float(rel_gap)

        iter_limit = int(t.opt_options.get("PHIterLimit", 200))
        if getattr(hub, "preempted", False) and rec["iters"] < iter_limit:
            if t.past_deadline():
                # the park banked the checkpoint + harvested bounds;
                # the request exits FAILED-UNCERTIFIED instead of
                # re-queueing for quantum it can never certify within
                rec["preemptions"] += 1
                self._finish_deadline(t)
                return
            t.status = "parked"
            rec["status"] = "parked"
            rec["preemptions"] += 1
            self._journal_safe(t.id, "parked", rec)
            _telemetry.tenant_instant(t.id, t.trace, "parked",
                                      iters=rec["iters"])
            self.progress.emit(t.id, "parked", status="parked",
                               iters=rec["iters"],
                               rel_gap=rec["rel_gap"])
            with self._cv:
                if self._stop and not self._drain:
                    # shutdown(wait=False): the park WAS the drain — the
                    # tenant stays parked on disk (resumable by a later
                    # server over this work_dir), and waiters unblock on
                    # the parked record instead of timing out
                    self._close_tenant_locked(t)
                    self.progress.mark_done(t.id)
                    t.done.set()
                    _log.info("request %s left PARKED by shutdown "
                              "(checkpoint banked at iter %d)", t.id,
                              rec["iters"])
                    return
                self._runq.append(t)       # round-robin: back of the line
                self._cv.notify_all()
            _log.info("request %s parked at iter %d (slice %d, %.2fs)",
                      t.id, rec["iters"], t.slices, wall)
            return
        # completion — including a preempt that found the ITERATION
        # BUDGET already spent: a budget-exhausted wheel can only linger,
        # and re-parking it would let two never-certifying tenants of
        # different families alternate {Iter0, quantum of linger, park}
        # forever (each resume restarting the linger clock) — it
        # completes UNCERTIFIED instead, and the record says so
        t.status = "done"
        rec["status"] = "done"
        rec["wall_s"] = time.monotonic() - t.submitted
        rec["certified"] = bool(np.isfinite(rel_gap) and rel_gap <= float(
            t.req.options.get("rel_gap", self.rel_gap)) + 1e-12)
        _HIST_WALL.add(rec["wall_s"])
        _CTR_COMPLETED.inc(1)
        self._journal_safe(t.id, "done", rec)
        with self._cv:
            self._families_done.add(t.family)
            self._close_tenant_locked(t)
        t.canonical = None      # release the batched arrays: a long-lived
        t.opt_options = None    # server must not retain every request's
        t.creator = None        # coefficient tensors (records stay)
        _log.info("request %s done: gap %.3e in %.2fs (%d slice(s), "
                  "%d compiles)", t.id, rel_gap, rec["wall_s"], t.slices,
                  int(rec["aot_misses"]))
        _telemetry.tenant_instant(t.id, t.trace, "complete",
                                  certified=rec["certified"],
                                  iters=rec["iters"])
        if rec["rel_gap"] is not None and np.isfinite(rec["rel_gap"]):
            # the live gap series ends AT the certified gap: the final
            # certification can tighten past the last in-iteration point
            self.progress.emit(t.id, "gap", source="C",
                               rel_gap=rec["rel_gap"],
                               outer=rec["outer"], inner=rec["inner"],
                               iteration=rec["iters"])
        self.progress.emit(t.id, "done", status="done",
                           certified=rec["certified"],
                           rel_gap=rec["rel_gap"], outer=rec["outer"],
                           inner=rec["inner"], iters=rec["iters"])
        self.progress.mark_done(t.id)
        t.done.set()

    # ---- continuous batching ------------------------------------------------
    def _run_batch(self, leader):
        """One BATCHED slice: fuse up to ``batch_slots`` same-family
        tenants into one tenant-batched megastep wheel (doc/serving.md
        "Continuous batching").

        The leader constructs the
        :class:`~tpusppy.service.batching.BatchedFamilyRunner`; queued
        same-family tenants JOIN free slots at window boundaries in QoS
        order, a finishing/expiring tenant EVICTS only its own slot
        (banked through the checkpoint seam), and the freed slot
        backfills from the queue.  Each window report carries the
        tenant's live-row-fraction share of the shared dispatch, so SLO
        records stay comparable with the time-sliced path.  The batch
        as a whole is ONE device occupant: a waiting DIFFERENT-family
        tenant preempts it at the quantum exactly like a solo slice,
        parking every member.
        """
        from ..solvers import aot as _aot
        from ..spbase import make_admm_settings
        from .. import tune as _tune
        from .batching import BatchedFamilyRunner, qos_rank

        if leader.past_deadline():
            self._finish_deadline(leader)
            return

        def mark_running(t, joiner):
            t.status = "running"
            t.record["status"] = "running"
            self._journal_safe(t.id, "running", t.record)
            if t.first_exec is None:
                t.first_exec = time.monotonic()
                if t.record["queue_wait_s"] is None:
                    t.record["queue_wait_s"] = t.first_exec - t.submitted
                    _HIST_QUEUE_WAIT.add(t.record["queue_wait_s"])
                if t.record["warm_hit"] is None:
                    if joiner:
                        # a joiner binds the batch's ALREADY-BUILT fused
                        # program — warm by construction, so the
                        # follower contract (zero compiles) holds even
                        # before any family member COMPLETES
                        warm = True
                    else:
                        with self._cv:
                            warm = t.family in self._families_done
                    t.record["warm_hit"] = warm
                    (_CTR_WARM_HITS if warm else _CTR_COLD_FAMILIES).inc(1)
                    _log.info("request %s starts %s (batched)", t.id,
                              "WARM" if warm else "cold")

        mark_running(leader, joiner=False)
        if leader.slices == 0 and not leader.record["warm_hit"]:
            # same prewarm-before-compile window as _run_slice
            if _aot.enabled():
                _aot.prewarm()

        # K: the server's slot count, clamped by a banked "batched" tune
        # verdict for this family when one exists (the verdict is the
        # largest K whose measured window cost fits the dispatch budget)
        b = leader.canonical.batch
        k = int(self.batch_slots)
        try:
            st = make_admm_settings(dict(leader.opt_options),
                                    leader.canonical.bundling)
            kv = _tune.batched_verdict(b.num_scenarios, b.num_vars,
                                       b.num_rows, settings=st)
        except Exception:
            kv = None
        if kv:
            k = max(2, min(k, int(kv)))

        members: dict = {}
        slice_start = time.monotonic()

        def fail(t, e):
            _CTR_FAILED.inc(1)
            _log.warning("request %s failed: %r", t.id, e)
            t.status = "failed"
            t.record.update(status="failed", error_code="exception",
                            error=repr(e))
            t.canonical = None
            self._journal_safe(t.id, "failed", t.record)
            with self._cv:
                self._close_tenant_locked(t)
            self.progress.emit(t.id, "failed", status="failed",
                               error=repr(e))
            self.progress.mark_done(t.id)
            t.done.set()

        def admit(t, joiner):
            if t.past_deadline():
                # expired while queued/parked: fail WITHOUT a slot
                self._finish_deadline(t)
                return False
            if joiner:
                mark_running(t, joiner=True)
            try:
                info = runner.admit(
                    t.id, t.canonical, t.dir,
                    int(t.opt_options.get("PHIterLimit", 200)),
                    resume=t.slices > 0,
                    best_inner=t.last_inner, best_outer=t.last_outer,
                    trace_id=t.trace)
            except Exception as e:
                fail(t, e)
                return False
            self.progress.emit(t.id, "running", status="running",
                               batched=True, joiner=bool(joiner),
                               resumed=bool(info["resumed"]),
                               slice=t.slices + 1)
            t.slices += 1
            t.record["slices"] = t.slices
            t.record["batched"] = True
            _CTR_SLICES.inc(1)
            if info["resumed"]:
                t.record["iters"] = int(info["iteration"])
            if t.record["ttfi_s"] is None:
                # admit ran Iter0 (or the resume seed) synchronously
                t.record["ttfi_s"] = time.monotonic() - t.first_exec
                _HIST_TTFI.add(t.record["ttfi_s"])
            members[t.id] = t
            return True

        def pull_joiners():
            free = runner.free_slots()
            if free <= 0:
                return []
            with self._cv:
                cand = [t2 for t2 in self._runq
                        if t2.family == leader.family
                        and self._batch_viable(t2)]
                # QoS decides who takes a free slot (the PR-12 debt);
                # ties break on submission order so same-class requests
                # keep FIFO semantics
                cand.sort(key=lambda t2: (qos_rank(t2.req.qos), t2.seq))
                take = cand[:free]
                for t2 in take:
                    self._runq.remove(t2)
                    t2.status = "running"
            return take

        def park(t, stopping):
            t.record["iters"] = int(runner.evict(t.id, bank=True))
            t.record["preemptions"] += 1
            members.pop(t.id, None)
            t.status = "parked"
            t.record["status"] = "parked"
            self._journal_safe(t.id, "parked", t.record)
            self.progress.emit(t.id, "parked", status="parked",
                               batched=True, iters=t.record["iters"],
                               rel_gap=t.record["rel_gap"])
            if stopping:
                # shutdown(wait=False): the evict WAS the drain — the
                # tenant stays parked on disk, waiters unblock now
                with self._cv:
                    self._close_tenant_locked(t)
                self.progress.mark_done(t.id)
                t.done.set()
                _log.info("request %s left PARKED by shutdown "
                          "(checkpoint banked at iter %d)", t.id,
                          t.record["iters"])
            else:
                with self._cv:
                    self._runq.append(t)
                    self._cv.notify_all()
                _log.info("request %s parked at iter %d (batched, "
                          "slice %d)", t.id, t.record["iters"], t.slices)

        def finish_deadline_slot(t):
            # a deadline crossing evicts ONLY this tenant's slot at the
            # window boundary (state banked, bounds harvested) — it
            # never parks the rest of the batch
            t.record["iters"] = int(runner.evict(t.id, bank=True))
            t.record["preemptions"] += 1
            members.pop(t.id, None)
            self._finish_deadline(t)

        def complete(t, certified):
            runner.complete(t.id)
            members.pop(t.id, None)
            rec = t.record
            t.status = "done"
            rec["status"] = "done"
            rec["wall_s"] = time.monotonic() - t.submitted
            rec["certified"] = bool(certified)
            _HIST_WALL.add(rec["wall_s"])
            _CTR_COMPLETED.inc(1)
            self._journal_safe(t.id, "done", rec)
            with self._cv:
                self._families_done.add(t.family)
                self._close_tenant_locked(t)
                self._cv.notify_all()
            t.canonical = None
            t.opt_options = None
            t.creator = None
            _log.info("request %s done (batched): gap %s in %.2fs "
                      "(%d slice(s))", t.id, rec["rel_gap"],
                      rec["wall_s"], t.slices)
            _telemetry.tenant_instant(t.id, t.trace, "complete",
                                      certified=rec["certified"],
                                      iters=rec["iters"], batched=True)
            if (rec["rel_gap"] is not None
                    and np.isfinite(rec["rel_gap"])):
                self.progress.emit(t.id, "gap", source="C",
                                   rel_gap=rec["rel_gap"],
                                   outer=rec["outer"],
                                   inner=rec["inner"],
                                   iteration=rec["iters"])
            self.progress.emit(t.id, "done", status="done",
                               certified=rec["certified"],
                               rel_gap=rec["rel_gap"],
                               outer=rec["outer"], inner=rec["inner"],
                               iters=rec["iters"], batched=True)
            self.progress.mark_done(t.id)
            t.done.set()

        with _metrics.window() as w:
            try:
                runner = BatchedFamilyRunner(leader.canonical,
                                             leader.opt_options, k)
            except Exception as e:
                _log.warning("request %s: batched runner unavailable "
                             "(%r) — time-slicing instead", leader.id, e)
                self._run_slice(leader)
                return

            # compile/AOT deltas attribute to the LEADER: it is the
            # tenant whose admission triggered every program build the
            # batch binds (joiners are warm by construction).
            # Incremental against the window snapshot so repeated
            # flushes never double-count.
            attr = {"aot.compile_s": 0.0, "aot.hits": 0.0,
                    "aot.misses": 0.0}

            def flush_compile(rec):
                for name, key in (("aot.compile_s", "compile_s"),
                                  ("aot.hits", "aot_hits"),
                                  ("aot.misses", "aot_misses")):
                    d = w.delta(name) - attr[name]
                    if d:
                        rec[key] += d
                        attr[name] += d

            if not admit(leader, joiner=False):
                return
            for t2 in pull_joiners():
                admit(t2, joiner=True)

            last_bank = time.monotonic()
            while members:
                # live batch occupancy for the scrape endpoint / status
                # RPC (read under self._cv by status_snapshot)
                with self._cv:
                    self._batch_live = {"k": k,
                                        "occupied": len(members)}
                # (a) deadline crossings — per-slot evictions only
                for t in [t for t in members.values()
                          if t.past_deadline()]:
                    finish_deadline_slot(t)
                # (b) forced preemption / shutdown
                with self._cv:
                    stopping = self._stop and not self._drain
                    forced = set(members) & self._force_preempt
                    self._force_preempt -= forced
                if stopping:
                    for t in list(members.values()):
                        park(t, stopping=True)
                    break
                for rid in forced:
                    park(members[rid], stopping=False)
                # (c) cross-family quantum preemption: the batch is one
                # device occupant — same-family waiters JOIN instead
                if (members
                        and time.monotonic() - slice_start
                        >= self.quantum_secs):
                    with self._cv:
                        other = any(o.family != leader.family
                                    for o in self._runq)
                    if other:
                        for t in list(members.values()):
                            park(t, stopping=False)
                        break
                # (d) backfill freed slots from the queue
                for t2 in pull_joiners():
                    admit(t2, joiner=True)
                if not members:
                    break
                # (e) ONE fused window over every live slot
                reports = runner.window()
                flush_compile(leader.record)
                # (f) mid-run durability cadence (solo parity: a server
                # crash costs each member at most this much work)
                now = time.monotonic()
                if now - last_bank >= self.checkpoint_every_secs:
                    last_bank = now
                    for rid in list(members):
                        try:
                            runner.bank(rid)
                        except Exception as e:
                            _log.warning("mid-run bank failed for %s: "
                                         "%r", rid, e)
                for rid, rep in reports.items():
                    t = members.get(rid)
                    if t is None:
                        continue
                    rec = t.record
                    rec["iters"] = int(rep["iters"])
                    rec["exec_s"] += rep["wall_s"]
                    rec["attributed_flops"] += rep["flops"]
                    if rec["exec_s"] > 0:
                        rec["iters_per_sec"] = (rec["iters"]
                                                / rec["exec_s"])
                    ob, ib = float(rep["outer"]), float(rep["inner"])
                    prev_outer, prev_inner = t.last_outer, t.last_inner
                    tol = 1e-9 * max(1.0, abs(t.last_outer) if
                                     np.isfinite(t.last_outer) else 1.0)
                    if ob < t.last_outer - tol or ib > t.last_inner + tol:
                        rec["bounds_monotone"] = False
                        _log.warning(
                            "request %s: bounds regressed across resume "
                            "(outer %s -> %s, inner %s -> %s)", t.id,
                            t.last_outer, ob, t.last_inner, ib)
                    t.last_outer = max(t.last_outer, ob)
                    t.last_inner = min(t.last_inner, ib)
                    rec["outer"], rec["inner"] = ob, ib
                    rec["rel_gap"] = float(rep["rel_gap"])
                    # per-window progress stream: one gap point per
                    # window, one bound_update per actual improvement
                    # (source 'B': the fused batched dispatch)
                    if t.last_outer > prev_outer or \
                            t.last_inner < prev_inner:
                        self.progress.emit(
                            rid, "bound_update", source="B",
                            outer=ob, inner=ib, iteration=rec["iters"])
                    if np.isfinite(rep["rel_gap"]):
                        self.progress.emit(
                            rid, "gap", source="B",
                            iteration=rec["iters"],
                            rel_gap=float(rep["rel_gap"]),
                            abs_gap=float(rep["abs_gap"]))
                    target = float(t.req.options.get("rel_gap",
                                                     self.rel_gap))
                    hit = (np.isfinite(rep["rel_gap"])
                           and rep["rel_gap"] <= target + 1e-12)
                    if not hit and "abs_gap" in t.req.options:
                        hit = (np.isfinite(rep["abs_gap"])
                               and rep["abs_gap"] <= float(
                                   t.req.options["abs_gap"]) + 1e-12)
                    if hit or rep["exhausted"]:
                        # budget exhaustion completes UNCERTIFIED, like
                        # the solo path — re-parking a spent wheel
                        # would churn forever
                        complete(t, certified=hit)
            flush_compile(leader.record)
        with self._cv:
            self._batch_live = {}
